#!/bin/bash
# Probe the tunneled TPU every ~4 min; when it answers, run the full bench
# (which persists BENCH_last_good.json) and exit so the session is notified.
# -k 5: the wedge being probed ignores SIGTERM; escalate to SIGKILL.
cd /root/repo
for i in $(seq 1 120); do
  if PILOSA_BENCH_PROBE=1 timeout -k 5 70 python bench.py >/dev/null 2>&1; then
    echo "TPU alive on attempt $i at $(date -u +%H:%M:%S)"
    PILOSA_BENCH_ATTEMPTS=2 timeout -k 5 700 python bench.py > /root/repo/.tpu_bench_out.json 2>/root/repo/.tpu_bench_err.log
    rc=$?
    echo "bench rc=$rc"
    cat /root/repo/.tpu_bench_out.json
    # A stale replay or a zero result means the tunnel wedged again
    # between probe and bench — keep watching instead of declaring done.
    if [ $rc -eq 0 ] && ! grep -q '"stale": true' /root/repo/.tpu_bench_out.json \
       && ! grep -q '"value": 0.0' /root/repo/.tpu_bench_out.json; then
      exit 0
    fi
    echo "bench not fresh; continuing watch"
  fi
  sleep 240
done
echo "TPU never answered in ~8h"
exit 1
