"""A/B at 1B scale: TopN via stacked coalescing scorer (shipped) vs
per-query direct dispatch, c32/c64 closed-loop, thorough warm."""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from pilosa_tpu.utils.jaxplatform import bootstrap

bootstrap()

import numpy as np

import bench_tall
from pilosa_tpu.executor import Executor

h, open_s = bench_tall._open_warm(bench_tall.ROWS_PER_SHARD)
print(f"open {open_s}s", flush=True)
topn, _ = bench_tall._queries()

def bench_exec(dev, label):
    for q in topn:
        dev.execute("tall", q)
    for conc in (8, 32, 64):
        bench_tall._measure_closed_loop(dev, topn, conc, 3.0)
    out = {"label": label}
    for conc in (32, 64):
        out[f"c{conc}"] = bench_tall._measure_closed_loop(dev, topn, conc, 12.0)
    print("AB " + json.dumps(out), flush=True)

dev = Executor(h, device_policy="always")
bench_exec(dev, "stacked-coalesced (shipped)")

dev2 = Executor(h, device_policy="always")
orig = dev2.stacked_scorer
class _Direct:
    dispatches = 0
    batched_queries = 0
    max_batch = orig.max_batch
    def score(self, key, mat, src):
        return np.asarray(orig._single_fn(src, mat))
dev2.stacked_scorer = _Direct()
bench_exec(dev2, "per-query-direct")
