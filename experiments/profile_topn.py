"""Profile host-side cost of the tall TopN serving path (chip attached).

Q1: where does the ~11-24 ms of per-query host work go? (cProfile, sequential)
Q2: how much host CPU is serialized per query at c32? (process_time accounting)
"""
import cProfile
import io
import json
import os
import pstats
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from pilosa_tpu.utils.jaxplatform import bootstrap

bootstrap()

import bench_tall
from pilosa_tpu.executor import Executor

t0 = time.monotonic()
h, open_s = bench_tall._open_warm(bench_tall.ROWS_PER_SHARD)
print(f"open_warm_s={open_s}", flush=True)
dev = Executor(h, device_policy="always")
topn, chains = bench_tall._queries()

for rep in range(2):
    for q in topn:
        dev.execute("tall", q)
print(f"warm done at {time.monotonic()-t0:.0f}s", flush=True)

# ---- Q1: sequential profile
pr = cProfile.Profile()
pr.enable()
tq = time.perf_counter()
n = 0
while time.perf_counter() - tq < 12:
    dev.execute("tall", topn[n % len(topn)])
    n += 1
pr.disable()
el = time.perf_counter() - tq
print(f"\n=== sequential: {n} queries in {el:.1f}s = {n/el:.1f} qps ===", flush=True)
s = io.StringIO()
ps = pstats.Stats(pr, stream=s).sort_stats("tottime")
ps.print_stats(28)
print(s.getvalue()[:5500], flush=True)

# ---- Q2: c32 with process_time accounting
def run_c(conc, seconds):
    stop = time.perf_counter() + seconds
    counts = [0] * conc
    def worker(i):
        k = i
        while time.perf_counter() < stop:
            dev.execute("tall", topn[k % len(topn)])
            k += conc
            counts[i] += 1
    cpu0 = time.process_time()
    t0 = time.perf_counter()
    ths = [threading.Thread(target=worker, args=(i,)) for i in range(conc)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    wall = time.perf_counter() - t0
    cpu = time.process_time() - cpu0
    ntot = sum(counts)
    return {
        "conc": conc,
        "qps": round(ntot / wall, 2),
        "queries": ntot,
        "wall_s": round(wall, 1),
        "proc_cpu_s": round(cpu, 1),
        "host_cpu_ms_per_query": round(1000 * cpu / max(ntot, 1), 2),
        "cpu_utilization": round(cpu / wall, 2),
    }

for conc in (8, 32, 64):
    r = run_c(conc, 15)
    print("C-RESULT " + json.dumps(r), flush=True)

# batcher telemetry: how deep the stacked scorer coalesced during the
# concurrency runs above
sc = dev.stacked_scorer
print(f"scorer dispatches={sc.dispatches} batched_queries={sc.batched_queries}")
