"""A/B: chain serving throughput, batched scorer vs per-query pipelining.

Small dataset (2 shards x 200k rows) on the real chip; thorough warm
(two passes per concurrency) so XLA compiles never land in a window.
Sweep PILOSA_CHAIN_MAX_BATCH via fresh Executors.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("PILOSA_BENCH_TALL_SHARDS", "2")
os.environ.setdefault("PILOSA_BENCH_TALL_ROWS_PER_SHARD", "200000")
from pilosa_tpu.utils.jaxplatform import bootstrap

bootstrap()

import bench_tall
from pilosa_tpu.executor import Executor

shards, rps = bench_tall._scale_from_env()
bench_tall.build_data(shards, rps, budget_s=600)
h, _ = bench_tall._open_warm(rps)
_, chains = bench_tall._queries()

def bench_exec(dev, label):
    # warm: sequential once, then two passes at each width
    for q in chains[:6]:
        dev.execute("tall", q)
    for conc in (8, 32, 64):
        bench_tall._measure_closed_loop(dev, chains, conc, 3.0)
    out = {"label": label}
    for conc in (32, 64):
        out[f"c{conc}"] = bench_tall._measure_closed_loop(dev, chains, conc, 10.0)
    d = getattr(dev.chain_scorer, "dispatches", None)
    bq = getattr(dev.chain_scorer, "batched_queries", None)
    out["dispatches"] = d
    out["batched_queries"] = bq
    print("AB " + json.dumps(out), flush=True)

for mb in (1, 32, 64, 128):
    os.environ["PILOSA_CHAIN_MAX_BATCH"] = str(mb)
    if mb == 1:
        # true per-query pipelining (the old path): chain batching off,
        # plus a direct-score shim so not even the scorer leader runs
        os.environ["PILOSA_CHAIN_BATCH"] = "0"
        dev = Executor(h, device_policy="always")
        orig = dev.chain_scorer
        class _Direct:
            dispatches = None
            batched_queries = None
            def score(self, key, tree, leaves):
                import numpy as np
                return np.asarray(orig._single_fn(leaves, tree))
        dev.chain_scorer = _Direct()
        bench_exec(dev, "unbatched-pipelined")
    else:
        # the coalescing gate is read from PILOSA_CHAIN_BATCH at
        # Executor construction — set it BEFORE building the batched
        # arm, or the arm silently measures the unbatched path
        os.environ["PILOSA_CHAIN_BATCH"] = "1"
        dev = Executor(h, device_policy="always")
        bench_exec(dev, f"batched-mb{mb}")
        assert dev.chain_scorer.dispatches > 0, (
            "batched arm never exercised the chain scorer — the "
            "coalescing gate is not open (PILOSA_CHAIN_BATCH)"
        )
