"""Benchmark gauntlet — the five BASELINE.json configs through the FULL
PQL → executor path (not the bare kernel), with bit-identity checks
between the CPU roaring path (device_policy=never) and the device path
(device_policy=always) on every query.

Configs (scaled to single-chip wall-clock; scale with
PILOSA_GAUNTLET_SCALE, default 1):
  1. star_trace — Row/Intersect/Union/Difference/Count over a small
     stargazer-style index (~1k cols).
  2. taxi      — TopN + BSI Sum/Range/Min/Max over ride fields.
  3. ssb       — star-schema-style filtered aggregates
     (Count(Intersect(...)) + Sum with filters).
  4. synthetic — deep Intersect/Union chains over multi-shard fragments.
  5. cluster   — 3-node in-process HTTP cluster, cross-shard
     TopN/Count through the coordinator.

Emits one JSON line per config:
  {"config", "queries", "device_qps", "cpu_qps", "speedup",
   "p50_ms", "bit_identical", "device_qps_c8", "device_qps_c32"}
(the cN columns are closed-loop throughput at that concurrency —
sequential device qps through a tunnel measures the tunnel RTT, the
closed-loop columns measure delivered serving throughput) and a final
summary line. bench.py remains the driver headline metric;
this is the judge-facing full-path gauntlet (SURVEY.md §7 step 10).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time


def _run_queries(execute, queries, warm: bool = False):
    """Run queries, return (results, qps, p50_ms).

    warm=True runs one untimed warmup pass first so staging (the
    stager's HBM cache fill — dense expansion + upload) and jit
    compiles are paid before the clock starts: the serving-steady-state
    number. Cold numbers are the warm=False first pass."""
    if warm:
        for q in queries:
            execute(q)
    lat = []
    results = []
    t_all = time.perf_counter()
    for q in queries:
        t0 = time.perf_counter()
        results.append(execute(q))
        lat.append(time.perf_counter() - t0)
    total = time.perf_counter() - t_all
    lat.sort()
    return results, len(queries) / total, lat[len(lat) // 2] * 1000


def _closed_loop(execute, queries, concurrency: int, min_total: int = 0):
    """Closed-loop throughput at fixed concurrency: ``concurrency``
    workers each issue queries back-to-back (round-robin over the
    list, staggered starts) until every query has run at least twice
    per worker. Returns qps. The sequential column measures per-query
    latency; this measures what the serving path DELIVERS under
    pipelined load — on tunneled devices the two differ by the RTT
    pipelining depth (VERDICT r5 weak #4)."""
    import threading

    total = max(min_total, 2 * concurrency * len(queries))
    per_worker = (total + concurrency - 1) // concurrency
    errs = []

    def work(wid):
        n = len(queries)
        for i in range(per_worker):
            try:
                execute(queries[(wid + i) % n])
            except Exception as e:  # pragma: no cover - surfaced in report
                errs.append(repr(e))
                return

    threads = [
        threading.Thread(target=work, args=(w,)) for w in range(concurrency)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if errs:
        raise RuntimeError(f"closed-loop worker failed: {errs[0]}")
    return (per_worker * concurrency) / dt


def _canon(r):
    """Canonical JSON-able form for bit-identity comparison."""
    from pilosa_tpu.core import Row
    from pilosa_tpu.executor import ValCount

    if isinstance(r, list):
        return [_canon(x) for x in r]
    if isinstance(r, Row):
        return ("row", tuple(int(c) for c in r.columns()))
    if isinstance(r, ValCount):
        return ("valcount", r.val, r.count)
    if isinstance(r, dict):
        return tuple(sorted((k, _canon(v)) for k, v in r.items()))
    return r


def _report(config, queries, dev, cpu, p50, identical, c8=None, c32=None):
    row = {
        "config": config,
        "queries": queries,
        "device_qps": round(dev, 2),
        "cpu_qps": round(cpu, 2),
        "speedup": round(dev / cpu, 2) if cpu else None,
        "p50_ms": round(p50, 3),
        "bit_identical": identical,
    }
    # closed-loop concurrency columns next to sequential (VERDICT §8):
    # the sequential device column through a tunnel measures the
    # tunnel; these measure delivered serving throughput per config
    if c8 is not None:
        row["device_qps_c8"] = round(c8, 2)
    if c32 is not None:
        row["device_qps_c32"] = round(c32, 2)
    print(json.dumps(row))
    return identical


def _device_closed_loop(execute, queries):
    """(c8, c32) closed-loop columns for a device row."""
    return (
        _closed_loop(execute, queries, 8),
        _closed_loop(execute, queries, 32),
    )


def _holder_pair(tmp, name):
    """One data dir, two executors over it: CPU oracle + device."""
    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import Executor

    h = Holder(os.path.join(tmp, name))
    h.open()
    cpu = Executor(h, device_policy="never")
    dev = Executor(h, device_policy="always")
    return h, cpu, dev


def bench_star_trace(tmp, scale):
    import numpy as np

    h, cpu, dev = _holder_pair(tmp, "star")
    idx = h.create_index("repository")
    f = idx.create_field("stargazer")
    lang = idx.create_field("language")
    rng = np.random.default_rng(1)
    n_cols = 1000 * scale
    for row in range(16):
        cols = rng.choice(n_cols, size=max(n_cols // 8, 1), replace=False)
        f.import_bits([row] * len(cols), cols.tolist())
    for row in range(8):
        cols = rng.choice(n_cols, size=max(n_cols // 4, 1), replace=False)
        lang.import_bits([row] * len(cols), cols.tolist())

    queries = []
    for r in range(16):
        queries += [
            f"Row(stargazer={r})",
            f"Count(Row(stargazer={r}))",
            f"Count(Intersect(Row(stargazer={r}), Row(language={r % 8})))",
            f"Count(Union(Row(stargazer={r}), Row(stargazer={(r + 1) % 16})))",
            f"Count(Difference(Row(stargazer={r}), Row(language={r % 8})))",
            f"Count(Xor(Row(stargazer={r}), Row(language={r % 8})))",
        ]
    want, cpu_qps, _ = _run_queries(lambda q: cpu.execute("repository", q), queries)
    got, dev_qps, p50 = _run_queries(lambda q: dev.execute("repository", q), queries, warm=True)
    c8, c32 = _device_closed_loop(lambda q: dev.execute("repository", q), queries)
    ok = _canon(want) == _canon(got)
    h.close()
    return _report("star_trace", len(queries), dev_qps, cpu_qps, p50, ok, c8, c32)


def bench_taxi(tmp, scale):
    import numpy as np

    from pilosa_tpu.core import FieldOptions

    h, cpu, dev = _holder_pair(tmp, "taxi")
    idx = h.create_index("taxi")
    cab = idx.create_field("cab_type")
    dist = idx.create_field(
        "dist", FieldOptions(type="int", min=0, max=500)
    )
    rng = np.random.default_rng(2)
    n = 50_000 * scale
    cols = np.arange(n)
    cab.import_bits(rng.integers(0, 4, size=n).tolist(), cols.tolist())
    dist.import_values(cols.tolist(), rng.integers(0, 500, size=n).tolist())

    queries = []
    for i in range(12):
        queries += [
            "TopN(cab_type, n=4)",
            f"Count(Range(dist > {i * 40}))",
            f"Sum(Row(cab_type={i % 4}), field=dist)",
            "Min(field=dist)",
            "Max(field=dist)",
            f"Count(Range({i * 30} < dist < {i * 30 + 100}))",
        ]
    want, cpu_qps, _ = _run_queries(lambda q: cpu.execute("taxi", q), queries)
    got, dev_qps, p50 = _run_queries(lambda q: dev.execute("taxi", q), queries, warm=True)
    c8, c32 = _device_closed_loop(lambda q: dev.execute("taxi", q), queries)
    ok = _canon(want) == _canon(got)
    h.close()
    return _report("taxi", len(queries), dev_qps, cpu_qps, p50, ok, c8, c32)


def bench_ssb(tmp, scale):
    import numpy as np

    from pilosa_tpu.core import FieldOptions

    h, cpu, dev = _holder_pair(tmp, "ssb")
    idx = h.create_index("lineorder")
    year = idx.create_field("order_year")  # rows 0..6
    region = idx.create_field("cust_region")  # rows 0..4
    discount = idx.create_field("lo_discount")  # rows 0..10
    revenue = idx.create_field(
        "lo_revenue", FieldOptions(type="int", min=0, max=10_000)
    )
    rng = np.random.default_rng(3)
    n = 60_000 * scale
    cols = np.arange(n)
    year.import_bits(rng.integers(0, 7, size=n).tolist(), cols.tolist())
    region.import_bits(rng.integers(0, 5, size=n).tolist(), cols.tolist())
    discount.import_bits(rng.integers(0, 11, size=n).tolist(), cols.tolist())
    revenue.import_values(cols.tolist(), rng.integers(0, 10_000, size=n).tolist())

    queries = []
    for y in range(7):
        for g in range(5):
            queries += [
                f"Count(Intersect(Row(order_year={y}), Row(cust_region={g})))",
                f"Sum(Intersect(Row(order_year={y}), Row(cust_region={g})), field=lo_revenue)",
                f"Count(Intersect(Row(order_year={y}), Row(lo_discount={g * 2})))",
            ]
    want, cpu_qps, _ = _run_queries(lambda q: cpu.execute("lineorder", q), queries)
    got, dev_qps, p50 = _run_queries(lambda q: dev.execute("lineorder", q), queries, warm=True)
    c8, c32 = _device_closed_loop(lambda q: dev.execute("lineorder", q), queries)
    ok = _canon(want) == _canon(got)
    h.close()
    return _report("ssb", len(queries), dev_qps, cpu_qps, p50, ok, c8, c32)


def bench_synthetic(tmp, scale):
    import numpy as np

    from pilosa_tpu import SHARD_WIDTH

    h, cpu, dev = _holder_pair(tmp, "synth")
    idx = h.create_index("synth")
    f = idx.create_field("f")
    rng = np.random.default_rng(4)
    shards = 4
    per_shard = 20_000 * scale
    rows_l, cols_l = [], []
    for s in range(shards):
        base = s * SHARD_WIDTH
        rows_l += rng.integers(0, 32, size=per_shard).tolist()
        cols_l += (base + rng.integers(0, SHARD_WIDTH, size=per_shard)).tolist()
    f.import_bits(rows_l, cols_l)

    queries = []
    for r in range(16):
        a, b, c, d = r, (r + 1) % 32, (r + 2) % 32, (r + 3) % 32
        queries += [
            f"Count(Intersect(Union(Row(f={a}), Row(f={b})), Union(Row(f={c}), Row(f={d}))))",
            f"Count(Union(Intersect(Row(f={a}), Row(f={b})), Intersect(Row(f={c}), Row(f={d})), Row(f={a})))",
            f"Count(Difference(Union(Row(f={a}), Row(f={b}), Row(f={c})), Row(f={d})))",
        ]
    want, cpu_qps, _ = _run_queries(lambda q: cpu.execute("synth", q), queries)
    got, dev_qps, p50 = _run_queries(lambda q: dev.execute("synth", q), queries, warm=True)
    c8, c32 = _device_closed_loop(lambda q: dev.execute("synth", q), queries)
    ok = _canon(want) == _canon(got)
    h.close()
    return _report("synthetic_chains", len(queries), dev_qps, cpu_qps, p50, ok, c8, c32)


def bench_cluster(tmp, scale):
    """3-node in-process cluster, cross-shard TopN/Count via HTTP."""
    import http.client
    import socket

    import numpy as np

    from pilosa_tpu import SHARD_WIDTH
    from pilosa_tpu.server.config import ClusterConfig, Config
    from pilosa_tpu.server.server import Server

    ports = []
    socks = []
    for _ in range(3):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    hosts = [f"127.0.0.1:{p}" for p in ports]

    def boot(policy):
        servers = []
        for i, p in enumerate(ports):
            cfg = Config(
                data_dir=os.path.join(tmp, f"cnode{i}"),
                bind=hosts[i],
                device_policy=policy,
                metric="none",
                cluster=ClusterConfig(
                    disabled=False, coordinator=(i == 0), replicas=1, hosts=hosts
                ),
            )
            sv = Server(cfg)
            sv.open()
            servers.append(sv)
        return servers

    def req(path, body):
        conn = http.client.HTTPConnection("127.0.0.1", ports[0], timeout=60)
        conn.request("POST", path, body)
        resp = conn.getresponse()
        out = resp.read()
        conn.close()
        return json.loads(out)

    queries = []
    for r in range(8):
        queries += [
            f"Count(Row(f={r}))",
            "TopN(f, n=4)",
            f"Count(Intersect(Row(f={r}), Row(f={(r + 1) % 8})))",
        ]

    # pass 1: CPU-path cluster — build the data, measure the oracle
    servers = boot("never")
    try:
        req("/index/c", b"")
        req("/index/c/field/f", b"")
        rng = np.random.default_rng(5)
        sets = []
        for shard in range(6):
            base = shard * SHARD_WIDTH
            for _ in range(400 * scale):
                sets.append(
                    f"Set({base + int(rng.integers(0, SHARD_WIDTH))},"
                    f" f={int(rng.integers(0, 8))})"
                )
        for i in range(0, len(sets), 500):
            req("/index/c/query", " ".join(sets[i : i + 500]).encode())
        # freshen the rank caches before measuring: TopN right after a
        # bulk write serves the debounced (stale-ordered) cache — the
        # reference behaves the same, and ships this endpoint for
        # exactly this (handler.go /recalculate-caches). Pass 2 reopens
        # the dirs (restore = recount), so without this the two passes
        # would diverge on cache freshness, not on compute path.
        req("/recalculate-caches", b"")
        cpu_results, cpu_qps, cpu_p50 = _run_queries(
            lambda q: req("/index/c/query", q.encode()), queries, warm=True
        )
    finally:
        for sv in servers:
            sv.close()

    # pass 2: SAME data dirs rebooted with the device path forced —
    # the round-3 gauntlet reported one number for both columns
    # (speedup: 1.0, a tautology); this measures the question it
    # dodged: does the device help on the cluster HTTP path?
    servers = boot("always")
    try:
        dev_results, dev_qps, dev_p50 = _run_queries(
            lambda q: req("/index/c/query", q.encode()), queries, warm=True
        )
        c8, c32 = _device_closed_loop(
            lambda q: req("/index/c/query", q.encode()), queries
        )
    finally:
        for sv in servers:
            sv.close()
    ok = (
        all("error" not in r for r in cpu_results)
        and all("error" not in r for r in dev_results)
        and [_canon(r) for r in cpu_results] == [_canon(r) for r in dev_results]
    )
    return _report("cluster_3node", len(queries), dev_qps, cpu_qps, dev_p50, ok, c8, c32)


def bench_spmd(tmp, scale):
    """Mesh-server HTTP path: queries against a server with
    mesh_devices=all (multi-shard Count/Sum/TopN lowered through the
    shard_map collectives in parallel/spmd.py) must answer bit-identically
    to a meshless CPU server over the same data."""
    import http.client

    import jax
    import numpy as np

    from pilosa_tpu import SHARD_WIDTH
    from pilosa_tpu.server.config import Config
    from pilosa_tpu.server.server import Server

    if len(jax.devices()) < 2:
        print(
            json.dumps(
                {
                    "config": "spmd_mesh_http",
                    "skipped": f"only {len(jax.devices())} device(s) visible",
                }
            )
        )
        return True

    rng = np.random.default_rng(9)
    sets = []
    for shard in range(6):
        base = shard * SHARD_WIDTH
        for _ in range(400 * scale):
            sets.append(
                f"Set({base + int(rng.integers(0, SHARD_WIDTH))},"
                f" f={int(rng.integers(0, 8))})"
            )
    queries = []
    for r in range(8):
        queries += [
            f"Count(Row(f={r}))",
            "TopN(f, n=4)",
            f"TopN(f, Row(f={r}), n=4)",
            f"Count(Intersect(Row(f={r}), Row(f={(r + 1) % 8})))",
        ]

    def run(name, mesh_devices, policy, closed_loop=False):
        cfg = Config(
            data_dir=os.path.join(tmp, name),
            bind="127.0.0.1:0",
            mesh_devices=mesh_devices,
            device_policy=policy,
            metric="none",
            anti_entropy_interval=0,
        )
        sv = Server(cfg)
        sv.open()
        host, port = sv.address()

        def req(body):
            conn = http.client.HTTPConnection(host, port, timeout=60)
            conn.request("POST", "/index/s/query", body)
            resp = conn.getresponse()
            out = resp.read()
            conn.close()
            return json.loads(out)

        try:
            conn = http.client.HTTPConnection(host, port, timeout=60)
            conn.request("POST", "/index/s", b"")
            conn.getresponse().read()
            conn.request("POST", "/index/s/field/f", b"")
            conn.getresponse().read()
            conn.close()
            for i in range(0, len(sets), 500):
                req(" ".join(sets[i : i + 500]).encode())
            results, qps, p50 = _run_queries(
                lambda q: req(q.encode()), queries, warm=True
            )
            if closed_loop:
                c8, c32 = _device_closed_loop(lambda q: req(q.encode()), queries)
            else:
                c8 = c32 = None
            return results, qps, p50, c8, c32
        finally:
            sv.close()

    want, cpu_qps, _, _, _ = run("spmd_cpu", 0, "never", closed_loop=False)
    got, dev_qps, p50, c8, c32 = run("spmd_mesh", "all", "always", closed_loop=True)
    ok = want == got
    return _report("spmd_mesh_http", len(queries), dev_qps, cpu_qps, p50, ok, c8, c32)


def bench_keyed(tmp, scale):
    """Keyed-index path: string column/row keys through the FULL stack
    (translate store mint/lookup around every query), exercising the
    binary-WAL + numpy-hash-table TranslateStore at gauntlet scale —
    the round-4 memory-scalable store must not slow the serving path.
    Bit-identity compares device vs CPU policies over the same holder."""
    import numpy as np

    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.utils.translate import TranslateStore

    h = Holder(os.path.join(tmp, "keyed"))
    from pilosa_tpu.core.field import FieldOptions

    idx = h.create_index("k", keys=True)
    idx.create_field("likes", FieldOptions(keys=True))
    ts = TranslateStore(os.path.join(tmp, "keyed", ".keys"))
    cpu = Executor(h, device_policy="never", translate_store=ts)
    dev = Executor(h, device_policy="always", translate_store=ts)
    rng = np.random.default_rng(13)
    users = [f"user-{i:06d}" for i in range(2000 * scale)]
    topics = [f"topic-{i}" for i in range(16)]
    writes = []
    for u in users:
        t = topics[int(rng.integers(0, len(topics)))]
        writes.append(f'Set("{u}", likes="{t}")')
    for i in range(0, len(writes), 500):
        cpu.execute("k", " ".join(writes[i : i + 500]))
    queries = [f'Count(Row(likes="{t}"))' for t in topics]
    queries += [f'Row(likes="{t}")' for t in topics[:4]]
    queries += ["TopN(likes, n=5)"]
    cpu_results, cpu_qps, _ = _run_queries(
        lambda q: cpu.execute("k", q), queries, warm=True
    )
    dev_results, dev_qps, p50 = _run_queries(
        lambda q: dev.execute("k", q), queries, warm=True
    )
    c8, c32 = _device_closed_loop(lambda q: dev.execute("k", q), queries)
    ok = [_canon(r) for r in cpu_results] == [_canon(r) for r in dev_results]
    # every written key must resolve — the whole universe, not a token
    resolved = ts.translate_columns_to_ids("k", users, create=False)
    ok = ok and None not in resolved and len(set(resolved)) == len(users)
    ts.close()
    h.close()
    return _report("keyed_translate", len(queries), dev_qps, cpu_qps, p50, ok, c8, c32)


def bench_import(tmp, scale):
    """Bulk import throughput END TO END — CSV file -> CLI parse (native
    fast path) -> HTTP -> field import -> fragment bulk merge +
    snapshot — with integrity as the pass condition: the export must
    round-trip the imported bit set exactly. The reference ships this
    as a run-to-measure micro-benchmark (BenchmarkFragment_Import,
    fragment_internal_test.go:1208); here it is the full-server path."""
    import numpy as np

    from pilosa_tpu import SHARD_WIDTH, native_bridge
    from pilosa_tpu.cli.main import main as cli_main
    from pilosa_tpu.server.config import Config
    from pilosa_tpu.server.server import Server

    N = 2_000_000 * scale
    rng = np.random.default_rng(8)
    rows = rng.integers(0, 5000, N).astype(np.uint64)
    cols = rng.integers(0, 8 << 20, N).astype(np.uint64)
    path = os.path.join(tmp, "imp.csv")
    blob = native_bridge.format_csv_pairs(rows, cols)
    if blob is None:
        blob = "".join(
            f"{r},{c}\n" for r, c in zip(rows.tolist(), cols.tolist())
        ).encode()
    with open(path, "wb") as f:
        f.write(blob)

    cfg = Config(
        data_dir=os.path.join(tmp, "impdata"),
        bind="127.0.0.1:0",
        device_policy="never",
        metric="none",
        anti_entropy_interval=0,
    )
    srv = Server(cfg)
    srv.open()
    try:
        t0 = time.perf_counter()
        rc = cli_main(
            [
                "import",
                "-i", "imp", "-f", "f", "--create",
                "--host", srv.uri,
                path,
            ]
        )
        dt = time.perf_counter() - t0
        bits_per_s = N / dt
        ok = rc == 0
        # integrity: export every shard and compare the bit SET exactly
        # (shard count derived from the generated column range)
        n_shards = ((8 << 20) - 1) // SHARD_WIDTH + 1
        got = set()
        for shard in range(n_shards):
            for line in srv.api.export_csv("imp", "f", shard).splitlines():
                r, c = line.split(b",")
                got.add((int(r), int(c)))
        want = set(zip(rows.tolist(), cols.tolist()))
        ok = ok and got == want
    finally:
        srv.close()
    return _report(
        "bulk_import", N, bits_per_s, 0.0, dt * 1000, ok
    )


def bench_auto_policy(tmp, scale):
    """The SHIPPED policy end-to-end (VERDICT r4 weak #5): device_policy
    "auto" with a MEASURED crossover (autotune, blocking — the same
    measurement the server runs at open) must keep a tiny query on the
    CPU roaring path, agree with its own estimate-vs-crossover rule on
    every Count, and stay bit-identical to the CPU oracle either way."""
    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.executor.autotune import autotune_executor
    from pilosa_tpu.pql import parse

    h = Holder(os.path.join(tmp, "autopol"))
    h.open()
    idx = h.create_index("a")
    f = idx.create_field("f")
    # tiny: row 0 touches 2 containers in shard 0
    f.import_bits([0, 0], [5, 70_000])
    # large: rows 1..8 populate every 2^16 container block of 8 shards
    rows, cols = [], []
    for r in range(1, 9):
        for s in range(8):
            for k in range(16):
                rows.append(r)
                cols.append((s << 20) + (k << 16) + r)
    f.import_bits(rows, cols)

    cpu = Executor(h, device_policy="never")
    auto = Executor(h, device_policy="auto")
    autotune_executor(auto, blocking=True)

    # ≥50 queries SPANNING the routing crossover (VERDICT §8: the old
    # 4-query row was too few to mean anything): tiny single-row reads
    # (estimate ~2 containers, always CPU), mid-size pairs, and wide
    # unions/intersections over the fully-populated rows (8 shards ×
    # 16 containers each — device side of any sane crossover), plus
    # TopN rows exercising the batched scorer path
    tiny_q = "Count(Row(f=0))"
    count_qs = [tiny_q]
    for r in range(1, 9):
        count_qs.append(f"Count(Row(f={r}))")
    for r in range(1, 9):
        count_qs.append(f"Count(Intersect(Row(f={r}), Row(f={r % 8 + 1})))")
    for r in range(1, 9):
        count_qs.append(
            f"Count(Union(Row(f={r}), Row(f={r % 8 + 1}), "
            f"Row(f={(r + 1) % 8 + 1}), Row(f={(r + 2) % 8 + 1})))"
        )
    for r in range(1, 9):
        count_qs.append(f"Count(Difference(Row(f={r}), Row(f=0)))")
    for r in range(1, 9):
        count_qs.append(
            f"Count(Intersect(Union(Row(f={r}), Row(f={r % 8 + 1})), "
            f"Union(Row(f={(r + 1) % 8 + 1}), Row(f={(r + 2) % 8 + 1}))))"
        )
    for r in range(1, 9):
        count_qs.append(f"Count(Xor(Row(f={r}), Row(f={r % 8 + 1})))")
    queries = count_qs + [f"TopN(f, Row(f={r}), n=4)" for r in range(1, 9)]
    assert len(queries) >= 50, len(queries)
    ok = True
    routed = []
    for q in queries:
        before = auto.stager.hits + auto.stager.misses
        want = cpu.execute("a", q)
        got = auto.execute("a", q)
        ok = ok and _canon([want]) == _canon([got])
        routed.append(auto.stager.hits + auto.stager.misses > before)
    # the tiny query must stay on the CPU path under ANY measured
    # crossover (its estimate ~2 is below autotune's floor of 16)
    ok = ok and routed[0] is False
    # each Count's observed routing must agree with the policy's own
    # per-shard estimate-vs-crossover decision — the shipped behavior,
    # not a hardcoded expectation (on a co-located backend the large
    # queries cross; behind a slow tunnel the crossover is higher)
    all_shards = list(range(8))
    routing_table = []
    for q, used in zip(count_qs, routed[: len(count_qs)]):
        call = parse(q).calls[0]
        expect = any(
            auto._use_device("a", call.children[0], s) for s in all_shards
        )
        routing_table.append(
            {"query": q, "device": bool(used), "policy_expects": bool(expect)}
        )
        ok = ok and used == expect
    _, qps, p50 = _run_queries(lambda q: auto.execute("a", q), queries, warm=True)
    _, cpu_qps, _ = _run_queries(lambda q: cpu.execute("a", q), queries)
    c8, c32 = _device_closed_loop(lambda q: auto.execute("a", q), queries)
    h.close()
    n_dev = sum(1 for r in routing_table if r["device"])
    print(
        json.dumps(
            {
                "config": "auto_policy_note",
                "measured_crossover": auto.auto_min_containers,
                "count_queries": len(count_qs),
                "routed_device": n_dev,
                "routed_cpu": len(count_qs) - n_dev,
                "routing_table": routing_table,
            }
        )
    )
    return _report("auto_policy", len(queries), qps, cpu_qps, p50, ok, c8, c32)


def bench_timerange(tmp, scale):
    """Time-quantum config (VERDICT §6): Range(field=row, start, end)
    over YMD quantum views, device path vs CPU roaring bit-identical.
    The device lowering unions the staged per-view rows through the
    shard-stacked path (executor._device_range_stack); the auto-policy
    arm additionally proves the touched-container estimate now COUNTS
    quantum views (it was 0 before, so auto never routed time ranges
    to the device)."""
    from datetime import datetime

    import numpy as np

    from pilosa_tpu import SHARD_WIDTH
    from pilosa_tpu.core import FieldOptions

    h, cpu, dev = _holder_pair(tmp, "timerange")
    idx = h.create_index("events")
    f = idx.create_field(
        "event", FieldOptions(type="time", time_quantum="YMD")
    )
    rng = np.random.default_rng(11)
    shards = 3
    n = 4000 * scale
    for _ in range(n):
        row = int(rng.integers(0, 6))
        col = int(rng.integers(0, shards * SHARD_WIDTH))
        ts = datetime(2020, 1 + int(rng.integers(0, 6)), 1 + int(rng.integers(0, 27)))
        f.set_bit(row, col, ts)

    queries = []
    for row in range(6):
        queries += [
            f"Range(event={row}, 2020-01-01T00:00, 2020-03-15T00:00)",
            f"Count(Range(event={row}, 2020-02-01T00:00, 2020-06-30T00:00))",
            f"Count(Union(Range(event={row}, 2020-01-01T00:00, 2020-02-15T00:00),"
            f" Row(event={(row + 1) % 6})))",
        ]
    want, cpu_qps, _ = _run_queries(lambda q: cpu.execute("events", q), queries)
    got, dev_qps, p50 = _run_queries(lambda q: dev.execute("events", q), queries, warm=True)
    c8, c32 = _device_closed_loop(lambda q: dev.execute("events", q), queries)
    ok = _canon(want) == _canon(got)
    # auto policy must ESTIMATE time ranges (touched containers summed
    # across quantum views > 0), so a populated span can clear the
    # crossover instead of being invisibly pinned to CPU
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.pql import parse

    auto = Executor(h, device_policy="auto")
    call = parse("Range(event=0, 2020-01-01T00:00, 2020-06-30T00:00)").calls[0]
    est = sum(auto._touched_containers("events", call, s) for s in range(shards))
    ok = ok and est > 0
    auto_results = [auto.execute("events", q) for q in queries]
    ok = ok and _canon(want) == _canon(auto_results)
    h.close()
    return _report("timerange_ymd", len(queries), dev_qps, cpu_qps, p50, ok, c8, c32)


def bench_tall_scaled(tmp, scale):
    """Config 4's true shape (tall singleton rows + hot rows, mmap
    store, block-sparse staging) at gauntlet scale: 4 shards x 200k
    rows through the full bench_tall path, incl. its bit-identity
    check. The full 1B-row run is bench.py's headline (.bench_cache)."""
    import bench_tall

    old_cache = bench_tall.CACHE_DIR
    bench_tall.CACHE_DIR = os.path.join(tmp, "tallcfg")
    old_env = {
        k: os.environ.get(k)
        for k in ("PILOSA_BENCH_TALL_SHARDS", "PILOSA_BENCH_TALL_ROWS_PER_SHARD")
    }
    os.environ["PILOSA_BENCH_TALL_SHARDS"] = "4"
    os.environ["PILOSA_BENCH_TALL_ROWS_PER_SHARD"] = str(200_000 * scale)
    try:
        tall = bench_tall.run(deadline_s=180)
    finally:
        bench_tall.CACHE_DIR = old_cache
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    ok = tall.get("bit_identical") is True and not tall.get("error")
    return _report(
        "tall_scaled",
        tall.get("topn_queries_timed") or 0,
        tall.get("topn_qps") or 0.0,
        tall.get("cpu_topn_qps") or 0.0,
        tall.get("topn_p50_ms") or 0.0,
        ok,
    )


def main():
    from pilosa_tpu.utils.jaxplatform import bootstrap

    bootstrap()
    scale = int(os.environ.get("PILOSA_GAUNTLET_SCALE", 1))
    all_ok = True
    t0 = time.time()
    with tempfile.TemporaryDirectory() as tmp:
        for fn in (
            bench_star_trace,
            bench_taxi,
            bench_ssb,
            bench_synthetic,
            bench_cluster,
            bench_spmd,
            bench_keyed,
            bench_import,
            bench_auto_policy,
            bench_timerange,
            bench_tall_scaled,
        ):
            try:
                all_ok &= bool(fn(tmp, scale))
            except Exception as e:
                print(f"{fn.__name__} failed: {type(e).__name__}: {e}", file=sys.stderr)
                all_ok = False
    # same names as the server's /metrics surface (one shared registry,
    # pilosa_tpu/utils/metrics.py): the whole gauntlet ran in-process,
    # so routing/batcher/stager/cache counters cover every config above
    try:
        from pilosa_tpu.utils import metrics as _metrics

        gauntlet_metrics = _metrics.snapshot()
    except Exception:
        gauntlet_metrics = {}
    # heat + placement-skew snapshot riding the artifact (ISSUE 16)
    try:
        from pilosa_tpu.utils import heat as _heat

        _hs = _heat.snapshot(dim="reads")
        gauntlet_heat = {"cells": len(_hs["cells"]), "skew": _hs["skew"]}
    except Exception:
        gauntlet_heat = {}
    print(
        json.dumps(
            {
                "config": "gauntlet_summary",
                "all_bit_identical": all_ok,
                "wall_s": round(time.time() - t0, 1),
                "metrics": gauntlet_metrics,
                "heat": gauntlet_heat,
            }
        )
    )
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
