"""Two-process jax.distributed mesh dryrun — the cross-HOST collective
plane (VERDICT r4 missing #2).

The reference's cluster spans machines as first-class (reference
cluster.go:788-857, memberlist gossip across hosts); the rebuild's SPMD
mesh equivalents (parallel/spmd.py) had only ever run in a single
process. This dryrun initializes a REAL multi-process JAX runtime —
``jax.distributed.initialize`` with a coordinator, N processes, each
owning a slice of the global device set — and runs every serving
collective (psum for Count/Sum, all_gather for TopN) over a mesh whose
shard axis SPANS the process boundary, exactly how a multi-host TPU
deployment lays pods over DCN.

Parent mode spawns the workers and aggregates their per-op verdicts:

    python dryrun_multiprocess.py            # 2 processes x 4 devices
    python dryrun_multiprocess.py --procs 2 --devices-per-proc 4

Worker mode (spawned): PILOSA_MP_RANK set.
"""

from __future__ import annotations

import json
import os
import sys

COORD_PORT_ENV = "PILOSA_MP_COORD"
RANK_ENV = "PILOSA_MP_RANK"
NPROCS_ENV = "PILOSA_MP_NPROCS"
DEVS_ENV = "PILOSA_MP_DEVS"


def worker() -> None:
    rank = int(os.environ[RANK_ENV])
    nprocs = int(os.environ[NPROCS_ENV])
    devs = int(os.environ[DEVS_ENV])

    import jax

    # the deployment image's sitecustomize force-selects the TPU tunnel
    # backend via jax.config, overriding the env var the parent set —
    # re-assert CPU before the distributed runtime initializes
    jax.config.update("jax_platforms", "cpu")
    # cross-process collectives on the CPU backend need an explicit
    # implementation: without gloo selected, XLA raises "Multiprocess
    # computations aren't implemented on the CPU backend" at dispatch.
    # Guarded: the flag name is version-dependent and irrelevant on
    # real multi-host TPU (ICI/DCN collectives need no selection).
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{os.environ[COORD_PORT_ENV]}",
        num_processes=nprocs,
        process_id=rank,
    )
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pilosa_tpu.parallel.spmd import (
        SHARD_AXIS,
        bsi_sum_spmd,
        count_fold_spmd,
        make_mesh,
        topn_spmd,
    )

    assert jax.process_count() == nprocs, jax.process_count()
    devices = jax.devices()  # GLOBAL: nprocs * devs
    assert len(devices) == nprocs * devs, len(devices)
    mesh = make_mesh(devices)
    sharding = NamedSharding(mesh, P(SHARD_AXIS))

    S, K, R, D, W = len(devices), 3, 8, 4, 64
    rng = np.random.default_rng(0)  # same seed every process: shared oracle
    rows = rng.integers(0, 2**32, size=(S, K, W), dtype=np.uint32)
    src = rng.integers(0, 2**32, size=(S, W), dtype=np.uint32)
    mat = rng.integers(0, 2**32, size=(S, R, W), dtype=np.uint32)
    planes = rng.integers(0, 2**32, size=(S, D + 1, W), dtype=np.uint32)
    filt = rng.integers(0, 2**32, size=(S, W), dtype=np.uint32)

    def put(arr):
        # each process contributes its LOCAL slice of the global array
        # (multi-host device_put requires addressable data only): with
        # the 1-D shard axis over jax.devices() (process-major order),
        # rank r owns rows [r*devs, (r+1)*devs)
        local = arr[rank * devs : (rank + 1) * devs]
        return jax.make_array_from_process_local_data(
            sharding, local, global_shape=arr.shape
        )

    ok: dict[str, bool] = {}

    # Count — psum over a shard axis that crosses the process boundary
    count = int(count_fold_spmd(mesh)(put(rows)))
    want = sum(
        int(np.bitwise_count(np.bitwise_and.reduce(rows[s], axis=0)).sum())
        for s in range(S)
    )
    ok["count_psum"] = count == want

    # TopN — local top-k + all_gather across processes
    ids, counts = topn_spmd(mesh, 4)(put(src), put(mat))
    # replicated output: every process holds all S*k candidates locally
    local_ids = np.asarray(ids.addressable_shards[0].data)
    ok["topn_all_gather"] = local_ids.shape[-1] == S * 4

    # BSI Sum — per-plane popcounts psum'd across processes
    plane_counts = np.asarray(
        bsi_sum_spmd(mesh, D)(put(planes), put(filt)).addressable_shards[0].data
    )
    want_planes = np.array(
        [
            sum(
                int(
                    np.bitwise_count(
                        np.bitwise_and(planes[s, d], filt[s])
                    ).sum()
                )
                for s in range(S)
            )
            for d in range(D + 1)
        ]
    )
    ok["bsi_sum_psum"] = bool((plane_counts == want_planes).all())

    print(
        json.dumps(
            {
                "rank": rank,
                "process_count": jax.process_count(),
                "global_devices": len(devices),
                "local_devices": jax.local_device_count(),
                "ok": ok,
            }
        ),
        flush=True,
    )
    sys.exit(0 if all(ok.values()) else 1)


def parent(nprocs: int, devs: int) -> int:
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=f"--xla_force_host_platform_device_count={devs}",
        **{COORD_PORT_ENV: str(port), NPROCS_ENV: str(nprocs), DEVS_ENV: str(devs)},
    )
    procs = []
    for rank in range(nprocs):
        procs.append(
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)],
                env={**env, RANK_ENV: str(rank)},
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    results, rc = [], 0
    for rank, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            rc = 1
        for line in out.splitlines():
            if line.startswith("{"):
                results.append(json.loads(line))
        if p.returncode != 0:
            rc = 1
            print(f"rank {rank} exited {p.returncode}\n{err[-2000:]}", file=sys.stderr)
    summary = {
        "what": (
            "2-process jax.distributed CPU mesh dryrun: every serving "
            "collective (count psum, TopN all_gather, BSI Sum psum) over "
            "a shard axis spanning the process boundary — the cross-host "
            "plane of a multi-host TPU deployment (reference "
            "cluster.go:788-857 spans machines via gossip+HTTP)"
        ),
        "processes": nprocs,
        "devices_per_process": devs,
        "ok": rc == 0 and len(results) == nprocs,
        "per_rank": results,
    }
    print(json.dumps(summary, indent=2))
    with open(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "MULTIPROCESS_r5.json"),
        "w",
    ) as f:
        json.dump(summary, f, indent=2)
    return rc


if __name__ == "__main__":
    if os.environ.get(RANK_ENV) is not None:
        worker()
    else:
        import argparse

        ap = argparse.ArgumentParser()
        ap.add_argument("--procs", type=int, default=2)
        ap.add_argument("--devices-per-proc", type=int, default=4)
        a = ap.parse_args()
        sys.exit(parent(a.procs, a.devices_per_proc))
