"""Ingest crash-recovery dryrun (ISSUE 11) — SIGKILL a server mid-ingest
under injected storage faults, restart it on the same data dir, and
prove the durability contract end to end:

  * ZERO acknowledged writes lost: every batch a client saw ack (HTTP
    200 — its write wave group-committed + fsynced) is present after
    the restart, bit-identical to a CPU oracle replaying only acked
    batches,
  * clean truncation: a record torn by the kill (or by the injected
    ``torn_at`` fault) truncates at reopen instead of failing the open
    or corrupting the replay,
  * batches in flight at the kill (no ack observed) are allowed either
    state — the contract is one-way.

Fault schedule while loading: ``fsync_fail_every=23,torn_at=9000`` —
periodic fsync EIO (waves nack, clients retry) plus one torn append
(the writer repairs the tail in-place). Clients retry nacked batches
until acked, so the oracle stays exact; only the kill itself creates
unknown-outcome batches.

    python dryrun_ingest_crash.py            # full run + artifact
    python dryrun_ingest_crash.py --quick    # smaller load (CI smoke)

Artifact: INGEST_CRASH_r11.json. Worker mode (spawned server):
PILOSA_INGEST_DRYRUN_MODE set.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

MODE_ENV = "PILOSA_INGEST_DRYRUN_MODE"
PORT_ENV = "PILOSA_INGEST_DRYRUN_PORT"
DATA_ENV = "PILOSA_INGEST_DRYRUN_DATA"
FAULTS_ENV = "PILOSA_INGEST_DRYRUN_FAULTS"

ARTIFACT = "INGEST_CRASH_r11.json"
FAULTS = "fsync_fail_every=23,torn_at=9000"


# -- worker (the server process) ---------------------------------------------


def worker() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from pilosa_tpu.server.config import Config
    from pilosa_tpu.server.server import Server

    cfg = Config(
        data_dir=os.environ[DATA_ENV],
        bind=f"127.0.0.1:{os.environ[PORT_ENV]}",
        device_policy="never",
        storage_faults=os.environ.get(FAULTS_ENV, ""),
    )
    s = Server(cfg)
    s.open()
    print(f"ingest dryrun server up on {cfg.bind}", flush=True)
    while True:  # parent SIGKILLs / SIGTERMs us
        time.sleep(1.0)


# -- parent helpers ----------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(port: int, method: str, path: str, body: bytes = b"", timeout: float = 60):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _wait_ready(port: int, deadline_s: float = 120) -> None:
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        try:
            status, _ = _http(port, "GET", "/status", timeout=2)
            if status == 200:
                return
        except OSError:
            pass
        time.sleep(0.25)
    raise TimeoutError("server HTTP never came up")


def _spawn(port: int, data_dir: str, faults: str, tmp: str, tag: str):
    env = dict(os.environ)
    env[MODE_ENV] = "server"
    env[PORT_ENV] = str(port)
    env[DATA_ENV] = data_dir
    env[FAULTS_ENV] = faults
    env["JAX_PLATFORMS"] = "cpu"
    outf = open(os.path.join(tmp, f"server-{tag}.log"), "w+")
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=env,
        stdout=outf,
        stderr=subprocess.STDOUT,
    )
    p._outf = outf  # type: ignore[attr-defined]
    return p


# -- load generation ---------------------------------------------------------


class Writer:
    """One client thread owning a disjoint row range. Retries 429/5xx
    nacks until ack, so its oracle is exact; the batch in flight when
    the server dies is recorded as unknown-outcome."""

    def __init__(self, wid: int, port: int, batch: int, rows_per_writer: int):
        self.wid = wid
        self.port = port
        self.batch = batch
        self.row_base = wid * rows_per_writer
        self.rows_n = rows_per_writer
        self.acked_batches: list[list] = []
        self.unknown: list = []  # mutations with no observed outcome
        self.acked = 0
        self.retries = 0
        self.stop = threading.Event()
        self.thread = threading.Thread(target=self.run, daemon=True)

    def _mutations(self, seq: int) -> list:
        # deterministic per (writer, seq): mostly sets, some clears of
        # previously set cells — exercises OP_REMOVE replay too
        out = []
        for i in range(self.batch):
            r = self.row_base + (seq * 7 + i) % self.rows_n
            c = (seq * self.batch + i) * 13 % 4096
            s = not (seq > 2 and i % 5 == 0)
            out.append((r, c, s))
        return out

    def run(self) -> None:
        seq = 0
        while not self.stop.is_set():
            muts = self._mutations(seq)
            body = json.dumps(
                {
                    "rowIDs": [m[0] for m in muts],
                    "columnIDs": [m[1] for m in muts],
                    "sets": [m[2] for m in muts],
                }
            ).encode()
            while not self.stop.is_set():
                try:
                    status, _ = _http(
                        self.port, "POST", "/index/i/field/f/ingest", body, timeout=10
                    )
                except OSError:
                    # connection died mid-request: outcome unknown (the
                    # kill); stop — every later batch would be unknown too
                    self.unknown.extend(muts)
                    self.stop.set()
                    break
                if status == 200:
                    self.acked_batches.append(muts)
                    self.acked += len(muts)
                    break
                self.retries += 1  # 429 shed or 5xx nacked wave: retry
                time.sleep(0.01)
            seq += 1


def _oracle_rows(writers) -> dict:
    """Replay acked batches in per-writer order → {row: set(cols)}.
    Rows are writer-disjoint, so cross-writer order can't matter."""
    rows: dict[int, set] = {}
    for w in writers:
        for batch in w.acked_batches:
            for r, c, s in batch:
                cells = rows.setdefault(r, set())
                (cells.add if s else cells.discard)(c)
    return rows


def main() -> int:
    quick = "--quick" in sys.argv
    n_writers = 4 if quick else 6
    batch = 24
    rows_per_writer = 32
    load_seconds = 2.5 if quick else 6.0

    tmp = tempfile.mkdtemp(prefix="ingest-crash-")
    data = os.path.join(tmp, "data")
    port = _free_port()
    result: dict = {"quick": quick, "faults": FAULTS, "writers": n_writers}

    print(f"== phase 1: server up (faults: {FAULTS}), concurrent ingest load")
    p = _spawn(port, data, FAULTS, tmp, "a")
    try:
        _wait_ready(port)
        assert _http(port, "POST", "/index/i", b"")[0] == 200
        assert _http(port, "POST", "/index/i/field/f", b"")[0] == 200

        writers = [Writer(w, port, batch, rows_per_writer) for w in range(n_writers)]
        for w in writers:
            w.thread.start()
        time.sleep(load_seconds)

        print("== phase 2: SIGKILL mid-ingest")
        p.send_signal(signal.SIGKILL)
        p.wait()
        for w in writers:
            w.stop.set()
        for w in writers:
            w.thread.join(timeout=15)

        acked_total = sum(w.acked for w in writers)
        retries_total = sum(w.retries for w in writers)
        unknown_total = sum(len(w.unknown) for w in writers)
        result["acked_mutations"] = acked_total
        result["nack_retries"] = retries_total
        result["unknown_mutations"] = unknown_total
        print(
            f"   acked={acked_total} retries={retries_total} "
            f"unknown-at-kill={unknown_total}"
        )
        if acked_total == 0:
            print("FAIL: no batch acked before the kill — nothing proven")
            return 1

        print("== phase 3: restart on the same data dir (no faults), verify")
        p2 = _spawn(port, data, "", tmp, "b")
        try:
            _wait_ready(port)
            # recovery telemetry: did the reopen truncate a torn tail?
            _, ev = _http(port, "GET", "/debug/events?kind=ingest.recovery")
            recov = json.loads(ev).get("events", [])
            result["recovery_events"] = recov
            result["truncated_bytes"] = sum(
                e.get("truncated_bytes", 0) for e in recov
            )

            oracle = _oracle_rows(writers)
            unknown_cells = {
                (r, c) for w in writers for (r, c, _s) in w.unknown
            }
            lost = []
            checked_rows = 0
            for w in writers:
                for r in range(w.row_base, w.row_base + w.rows_n):
                    st, body = _http(
                        port, "POST", "/index/i/query",
                        f"Row(f={r})".encode(),
                    )
                    assert st == 200, (st, body)
                    got = set(json.loads(body)["results"][0].get("columns", []))
                    want = oracle.get(r, set())
                    checked_rows += 1
                    for c in want - got:
                        if (r, c) not in unknown_cells:
                            lost.append((r, c, "acked set missing"))
                    for c in got - want:
                        if (r, c) not in unknown_cells:
                            lost.append((r, c, "acked clear resurfaced"))
            result["checked_rows"] = checked_rows
            result["lost"] = lost[:50]
            result["bit_identical"] = not lost
            print(
                f"   rows checked={checked_rows} "
                f"truncated_bytes={result['truncated_bytes']} lost={len(lost)}"
            )

            # the recovered server still serves durable writes
            st, body = _http(
                port, "POST", "/index/i/field/f/ingest",
                json.dumps({"rowIDs": [9999], "columnIDs": [1]}).encode(),
            )
            assert st == 200 and json.loads(body)["acked"] == 1
            result["post_recovery_ingest"] = True
        finally:
            p2.terminate()
            p2.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()

    with open(ARTIFACT, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
    print(f"artifact: {ARTIFACT}")
    if result.get("lost"):
        print(f"FAIL: {len(result['lost'])} acked writes lost/corrupted")
        return 1
    print("PASS: zero acked writes lost; bit-identical to the acked oracle")
    return 0


if __name__ == "__main__":
    if os.environ.get(MODE_ENV):
        worker()
    else:
        sys.exit(main())
