"""Mesh-vs-stacked TopN decision measurement at NON-TOY candidate scale.

VERDICT r4 weak #3: the round-4 meshed-default decision rested on a
200k-bit / 64-row executor measurement that contradicted the HTTP-level
gauntlet row (0.87x), and the eager mesh staging made the comparison
unrepeatable at 50k candidates. This script measures all three executor
paths AND the server (HTTP) level on the SAME dataset: 8 shards whose
ranked caches hold ~50k candidates each (the reference's default cache
size, field.go:41) — with the round-5 lazy chunked mesh staging.

Run on the 8-virtual-device CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python bench_spmd_measure.py

Writes SPMD_MEASURE_r5.json.
"""

from __future__ import annotations

import json
import os
import shutil
import time

# This experiment is DEFINED on the 8-virtual-device CPU mesh — force
# the platform regardless of the deployment env (which pins the TPU
# tunnel via JAX_PLATFORMS=axon + sitecustomize).
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from pilosa_tpu.utils.jaxplatform import force_cpu_mesh

force_cpu_mesh(8)

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(REPO, ".bench_cache", "spmd_measure_v1")
SHARD_WIDTH = 1 << 20
N_SHARDS = 8
HOT_ROWS = 32
HOT_BITS = 5_000
TAIL_ROWS = 50_000  # fills the reference-default ranked cache


def build() -> None:
    from pilosa_tpu.roaring import build_fragment_file

    vdir = os.path.join(DATA_DIR, "m", "f", "views", "standard", "fragments")
    if os.path.isdir(vdir) and len(os.listdir(vdir)) >= 2 * N_SHARDS:
        return
    shutil.rmtree(DATA_DIR, ignore_errors=True)
    os.makedirs(vdir, exist_ok=True)

    def chunks(shard):
        for h in range(HOT_ROWS):
            rng = np.random.default_rng(h * 7919 + shard)
            cols = np.unique(
                rng.integers(0, SHARD_WIDTH, size=HOT_BITS, dtype=np.uint64)
            )
            yield np.uint64(h * SHARD_WIDTH) + cols
        rows = np.arange(TAIL_ROWS, dtype=np.uint64) + np.uint64(HOT_ROWS)
        cols = (rows * np.uint64(2654435761)) % np.uint64(SHARD_WIDTH)
        yield rows * np.uint64(SHARD_WIDTH) + cols

    for s in range(N_SHARDS):
        build_fragment_file(os.path.join(vdir, str(s)), chunks(s))


def _log(msg: str) -> None:
    import sys

    print(f"[{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def _measure(execute, queries, reps=20, budget_s=30.0):
    lat = []
    t_all = time.perf_counter()
    for _ in range(reps):
        for q in queries:
            t0 = time.perf_counter()
            execute(q)
            lat.append(time.perf_counter() - t0)
        if time.perf_counter() - t_all > budget_s:
            break
    lat.sort()
    return round(lat[len(lat) // 2] * 1000, 2)


def executor_level(out: dict) -> None:
    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.parallel.spmd import make_mesh

    h = Holder(DATA_DIR)
    h.open()
    try:
        cpu = Executor(h, device_policy="never")
        stacked = Executor(h, device_policy="always")
        mesh = Executor(h, device_policy="always", mesh=make_mesh())
        # pruned walk (the serving-realistic case: skewed counts, the
        # ranked walk resolves inside the hot head) and a full scan
        # (n >= cache size: every candidate scored — the worst case the
        # eager mesh staging could not repeat at this scale)
        q_pruned = "TopN(f, Row(f=0), n=10)"
        q_full = f"TopN(f, Row(f=0), n={TAIL_ROWS + HOT_ROWS})"
        res = {}
        for name, ex in [("cpu", cpu), ("stacked", stacked), ("mesh", mesh)]:
            ident = None
            t_cold = {}
            for tag, q in [("pruned", q_pruned), ("full", q_full)]:
                t0 = time.perf_counter()
                got = ex.execute("m", q)
                t_cold[tag] = round((time.perf_counter() - t0) * 1000, 1)
                _log(f"{name} cold {tag}: {t_cold[tag]} ms")
                if name == "cpu":
                    res.setdefault("oracle", {})[tag] = json.dumps(got)
                else:
                    ident = (ident is not False) and (
                        json.dumps(got) == res["oracle"][tag]
                    )
            res[name] = {
                "cold_ms": t_cold,
                "pruned_ms": _measure(
                    lambda q, ex=ex: ex.execute("m", q), [q_pruned], budget_s=15
                ),
                "full_ms": _measure(
                    lambda q, ex=ex: ex.execute("m", q), [q_full], reps=5, budget_s=25
                ),
            }
            if name != "cpu":
                res[name]["bit_identical"] = ident
            _log(f"{name}: {res[name]}")
        res.pop("oracle", None)
        out["executor_level"] = res
    finally:
        h.close()


def server_level(out: dict) -> None:
    """Same dataset through the FULL HTTP stack (parse + handler +
    executor), one server meshless/CPU vs one meshed — the layer where
    the round-3/4 gauntlet saw the mesh lose."""
    import http.client
    import json as _json

    from pilosa_tpu.server.config import Config
    from pilosa_tpu.server.server import Server

    # keep-alive client (what the reference's Go client and every
    # production HTTP client use); the server speaks HTTP/1.1 with
    # TCP_NODELAY, so this measures the serving path without
    # per-request TCP setup
    conns: dict = {}

    def post(uri, path, body: str):
        host = uri.replace("http://", "")
        conn = conns.get(host)
        if conn is None:
            conn = conns[host] = http.client.HTTPConnection(host, timeout=60)
        conn.request("POST", path, body=body.encode())
        resp = conn.getresponse()
        return _json.loads(resp.read())

    q_pruned = "TopN(f, Row(f=0), n=10)"
    q_full = f"TopN(f, Row(f=0), n={TAIL_ROWS + HOT_ROWS})"
    res = {}
    for name, mesh_devices, policy in [
        ("cpu_http", 0, "never"),
        ("mesh_http", "all", "always"),
        ("stacked_http", 0, "always"),
    ]:
        # servers share the prebuilt data dir read-only (no writes here)
        cfg = Config(
            data_dir=DATA_DIR,
            bind="127.0.0.1:0",
            mesh_devices=mesh_devices,
            device_policy=policy,
            metric="none",
            anti_entropy_interval=0,
        )
        srv = Server(cfg)
        srv.open()
        try:
            uri = srv.uri
            post(uri, "/index/m/query", q_pruned)  # warm staging+compile
            post(uri, "/index/m/query", q_full)
            _log(f"{name}: warmed")
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < 6:
                post(uri, "/index/m/query", q_pruned)
                n += 1
            pruned_qps = round(n / (time.perf_counter() - t0), 1)
            t0 = time.perf_counter()
            n = 0
            while time.perf_counter() - t0 < 6:
                post(uri, "/index/m/query", q_full)
                n += 1
            res[name] = {
                "pruned_qps": pruned_qps,
                "full_qps": round(n / (time.perf_counter() - t0), 2),
            }
            _log(f"{name}: {res[name]}")
        finally:
            srv.close()
    out["server_level"] = res


def main():
    from pilosa_tpu.utils.jaxplatform import bootstrap

    bootstrap()
    t0 = time.monotonic()
    build()
    out = {
        "what": (
            "Round-5 mesh-vs-batched decision at NON-TOY scale "
            f"(VERDICT r4 weak #3): {N_SHARDS} shards, ~{TAIL_ROWS + HOT_ROWS} "
            "ranked-cache candidates per shard (reference default cache "
            "size), lazy chunked mesh staging (executor._SpmdLazyScores). "
            "8-virtual-device CPU mesh; pruned = TopN n=10 on skewed "
            "counts (walk resolves in the hot head), full = n >= cache "
            "size (every candidate scored)."
        ),
        "build_s": round(time.monotonic() - t0, 1),
    }
    executor_level(out)
    server_level(out)
    # decision synthesis
    ex = out.get("executor_level", {})
    sv = out.get("server_level", {})
    try:
        out["decision"] = {
            "executor_pruned_mesh_vs_stacked": round(
                ex["stacked"]["pruned_ms"] / ex["mesh"]["pruned_ms"], 2
            ),
            "executor_full_mesh_vs_stacked": round(
                ex["stacked"]["full_ms"] / ex["mesh"]["full_ms"], 2
            ),
            "http_pruned_mesh_vs_stacked": round(
                sv["mesh_http"]["pruned_qps"] / sv["stacked_http"]["pruned_qps"], 2
            ),
            "http_full_mesh_vs_stacked": round(
                sv["mesh_http"]["full_qps"] / sv["stacked_http"]["full_qps"], 2
            ),
        }
    except (KeyError, ZeroDivisionError):
        pass
    with open(os.path.join(REPO, "SPMD_MEASURE_r5.json"), "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
