"""Sharded gang FEDERATION dryrun — 2 gangs x 2 processes on CPU, one
index sharded across both gangs via the cluster plane (ISSUE 7; the
federation-level successor to dryrun_multihost.py's single gang).

Topology: gangs A and B each form their own 2-process jax.distributed
collective (2 virtual CPU devices per process). The two gang LEADERS
are the cluster nodes (``cluster.hosts``, replicas=2), so every query
splits across gangs — local legs replay on this gang's mesh, remote
legs fan out over InternalClient — and every shard has a replica on
the other gang. The parent then walks the whole lifecycle:

  1. serving: load over HTTP via A's leader, answer Count / two-pass
     TopN / BSI Sum / a 3-op chain on BOTH leaders, bit-identical to a
     single-process CPU roaring oracle,
  2. follower kill: SIGKILL A's follower mid-serving — bounded fence
     (503 no longer than the dispatch timeout), gang A DEGRADED into
     replicated-solo, reads correct on both leaders throughout (zero
     wrong answers),
  3. re-form: boot a fresh follower with ``federation-rejoin`` — the
     leader re-stages it (schema + fragments), bumps the epoch, and
     the gang returns to ACTIVE; new writes replicate to the rejoined
     follower,
  4. leader kill: SIGKILL B's leader — reads fail over to gang A's
     replica copies; restart the leader with ``federation-leader``
     (replicated-solo DEGRADED, heals its data from peers at the next
     rejoin) and a fresh follower; gang B back to ACTIVE,
  5. record per-gang unavailability windows + everything else in
     FEDERATION_r7.json.

    python dryrun_federation.py            # full run + artifact
    python dryrun_federation.py --quick    # smaller load (CI smoke)

Worker mode (spawned): PILOSA_FED_DRYRUN_MODE set.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

from dryrun_multihost import (
    READ_QUERIES,
    _dataset,
    _finish,
    _free_port,
    _http,
    _oracle,
    _wait_ready,
)

MODE_ENV = "PILOSA_FED_DRYRUN_MODE"  # gang | rejoin | leader
GANG_ENV = "PILOSA_FED_DRYRUN_GANG"
RANK_ENV = "PILOSA_FED_DRYRUN_RANK"
COORD_ENV = "PILOSA_FED_DRYRUN_COORD"
HTTP_A_ENV = "PILOSA_FED_DRYRUN_HTTP_A"
HTTP_B_ENV = "PILOSA_FED_DRYRUN_HTTP_B"
SELF_HTTP_ENV = "PILOSA_FED_DRYRUN_SELF_HTTP"
NAME_ENV = "PILOSA_FED_DRYRUN_NAME"
DATA_ENV = "PILOSA_FED_DRYRUN_DATA"
TIMEOUT_ENV = "PILOSA_FED_DRYRUN_DISPATCH_TIMEOUT"
REJOIN_ENV = "PILOSA_FED_DRYRUN_REJOIN"

REFORM_BUDGET = 30.0  # federation_reform_budget default; windows must fit


# -- worker ------------------------------------------------------------------


def worker() -> None:
    mode = os.environ[MODE_ENV]

    import jax

    jax.config.update("jax_platforms", "cpu")

    from pilosa_tpu.server.config import ClusterConfig, Config
    from pilosa_tpu.server.server import Server

    hosts = [
        f"127.0.0.1:{os.environ[HTTP_A_ENV]}",
        f"127.0.0.1:{os.environ[HTTP_B_ENV]}",
    ]
    name = os.environ[NAME_ENV]
    common = dict(
        data_dir=os.path.join(os.environ[DATA_ENV], name),
        bind=f"127.0.0.1:{os.environ.get(SELF_HTTP_ENV, '0')}",
        device_policy="always",
        metric="none",
        anti_entropy_interval=0,
    )
    rank = 0
    if mode == "rejoin":
        # re-staged follower: no cluster plane, no jax.distributed —
        # it announces itself to its gang leader and gets re-formed in
        cfg = Config(**common, federation_rejoin=os.environ[REJOIN_ENV])
    elif mode == "leader":
        # restarted gang leader: replicated-solo DEGRADED, keeps its
        # cluster seat; data heals from peers at the next rejoin
        cfg = Config(
            **common,
            federation_leader=True,
            client_retries=2,
            cluster=ClusterConfig(
                disabled=False,
                coordinator=False,
                replicas=2,
                hosts=hosts,
                status_interval=30.0,
            ),
        )
    else:
        gang, rank = os.environ[GANG_ENV], int(os.environ[RANK_ENV])
        cfg = Config(
            **common,
            distributed_enabled=True,
            distributed_coordinator=f"127.0.0.1:{os.environ[COORD_ENV]}",
            distributed_process_id=rank,
            distributed_num_processes=2,
            distributed_idle_interval=1.0,
            distributed_dispatch_timeout=float(os.environ.get(TIMEOUT_ENV, "20")),
            distributed_leader_timeout=15.0,
            client_retries=2,
            cluster=ClusterConfig(
                disabled=False,
                coordinator=(gang == "A"),
                replicas=2,
                hosts=hosts,
                status_interval=30.0,
            ),
        )
    srv = Server(cfg)
    srv.open()

    if mode == "gang" and rank != 0:
        reason = srv.serve_follower()
        stats = srv.multihost.stats() if srv.multihost else None
        # dump BEFORE closing (see dryrun_multihost.py: the dead
        # coordination service can fatally terminate mid-close)
        print(
            json.dumps(
                {"event": "exit", "name": name, "stop_reason": reason, "stats": stats}
            ),
            flush=True,
        )
        try:
            srv.close()
        except Exception:
            pass
        return

    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    print(json.dumps({"event": "ready", "name": name}), flush=True)
    while not stop:
        time.sleep(0.1)
    stats = srv.multihost.stats() if srv.multihost else None
    try:
        srv.close()
    except Exception:
        pass
    print(json.dumps({"event": "exit", "name": name, "stats": stats}), flush=True)
    # gang leaders host their gang's jax.distributed coordination
    # service — linger so a follower poisoned on close can exit clean
    time.sleep(2.0)


# -- parent ------------------------------------------------------------------


def _spawn(env: dict, tmp: str, name: str, **overrides):
    """Worker with stdout/stderr to FILES, never pipes (64 KB pipe
    deadlock — see dryrun_multihost._spawn)."""
    import subprocess

    out = open(os.path.join(tmp, f"{name}.out"), "w+")
    err = open(os.path.join(tmp, f"{name}.err"), "w+")
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env={**env, NAME_ENV: name, **overrides},
        stdout=out,
        stderr=err,
        text=True,
    )
    p._outf, p._errf = out, err  # type: ignore[attr-defined]
    return p


def _http_h(
    port: int,
    method: str,
    path: str,
    body: bytes = b"",
    headers: dict | None = None,
    timeout: float = 60,
):
    """Like dryrun_multihost._http, plus request headers (the
    observability phase sends a traceparent)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body, headers or {})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _collect_pids(d: dict, out: set) -> set:
    meta = d.get("meta") or {}
    if "pid" in meta:
        out.add(meta["pid"])
    for c in d.get("children") or []:
        _collect_pids(c, out)
    return out


def _gang_status(port: int) -> dict:
    status, body = _http(port, "GET", "/status", timeout=10)
    if status != 200:
        return {}
    return json.loads(body).get("gang") or {}


def _poll_gang_state(port: int, want: str, deadline_s: float) -> float:
    """Seconds until the leader on ``port`` reports gang state
    ``want``; raises on timeout."""
    t0 = time.monotonic()
    t_end = t0 + deadline_s
    while time.monotonic() < t_end:
        try:
            if _gang_status(port).get("state") == want:
                return time.monotonic() - t0
        except OSError:
            pass
        time.sleep(0.25)
    raise TimeoutError(f"gang on :{port} never reached {want}")


def _query(port: int, q: str, timeout: float = 120):
    status, body = _http(port, "POST", "/index/i/query", q.encode(), timeout=timeout)
    return status, (json.loads(body).get("results") if status == 200 else body[:300])


def _serve_and_check(port: int, oracle: dict) -> tuple[dict, dict, bool]:
    results, lat = {}, {}
    for q in READ_QUERIES:  # warm (compiles), then timed/recorded
        _http(port, "POST", "/index/i/query", q.encode(), timeout=180)
    for q in READ_QUERIES:
        t0 = time.monotonic()
        status, body = _http(port, "POST", "/index/i/query", q.encode(), timeout=180)
        lat[q] = round((time.monotonic() - t0) * 1000, 2)
        assert status == 200, (q, status, body[:300])
        results[q] = json.loads(body)["results"]
    return results, lat, all(results[q] == oracle[q] for q in READ_QUERIES)


def _load(port: int, recalc_ports: list[int], bits, values) -> None:
    status, _ = _http(port, "POST", "/index/i", b"")
    assert status in (200, 409), status
    status, _ = _http(port, "POST", "/index/i/field/f", b"")
    assert status in (200, 409), status
    status, _ = _http(
        port,
        "POST",
        "/index/i/field/val",
        json.dumps({"options": {"type": "int", "min": 0, "max": 1000}}).encode(),
    )
    assert status in (200, 409), status
    sets = [f"Set({col}, f={row})" for row, col in bits]
    for i in range(0, len(sets), 200):
        status, body = _http(
            port, "POST", "/index/i/query", " ".join(sets[i : i + 200]).encode()
        )
        assert status == 200, (status, body[:300])
    status, body = _http(
        port,
        "POST",
        "/index/i/field/val/import-value",
        json.dumps(
            {"columnIDs": [c for c, _ in values], "values": [v for _, v in values]}
        ).encode(),
    )
    assert status == 200, (status, body[:300])
    for p in recalc_ports:
        status, _ = _http(p, "POST", "/recalculate-caches", b"")
        assert status == 200, status


def parent(quick: bool) -> int:
    import tempfile

    dispatch_timeout = 8.0
    bits, values = _dataset(quick)
    oracle = _oracle(bits, values)
    summary: dict = {
        "what": (
            "2-gang x 2-process federation on CPU: each gang is its own "
            "jax.distributed collective, the gang leaders form the cluster "
            "plane (replicas=2), queries split across gangs and merge "
            "through the Row/TopN/BSI reducers (parallel/federation.py). "
            "Walks follower SIGKILL -> bounded fence -> DEGRADED "
            "replicated-solo -> rejoin re-form -> ACTIVE, then leader "
            "SIGKILL -> replica failover -> federation-leader restart -> "
            "rejoin -> ACTIVE. Zero wrong answers at every step."
        ),
        "gangs": 2,
        "processes_per_gang": 2,
        "devices_per_process": 2,
        "quick": quick,
        "dispatch_timeout_s": dispatch_timeout,
        "reform_budget_s": REFORM_BUDGET,
        "queries": READ_QUERIES,
    }
    ok = True

    with tempfile.TemporaryDirectory() as tmp:
        coord_a, coord_b = _free_port(), _free_port()
        http_a, http_b = _free_port(), _free_port()
        http_a1r, http_b1r = _free_port(), _free_port()
        env = {
            k: v
            for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
        }
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            **{
                MODE_ENV: "gang",
                HTTP_A_ENV: str(http_a),
                HTTP_B_ENV: str(http_b),
                DATA_ENV: tmp,
                TIMEOUT_ENV: str(dispatch_timeout),
            },
        )

        def gang_worker(gang: str, rank: int):
            return _spawn(
                env,
                tmp,
                f"{gang}{rank}",
                **{
                    GANG_ENV: gang,
                    RANK_ENV: str(rank),
                    COORD_ENV: str(coord_a if gang == "A" else coord_b),
                    SELF_HTTP_ENV: str(
                        (http_a if gang == "A" else http_b) if rank == 0 else 0
                    ),
                },
            )

        procs = {f"{g}{r}": gang_worker(g, r) for g in "AB" for r in (0, 1)}
        harvested: dict = {}

        def harvest(name: str, timeout: float = 60):
            out, err, rc = _finish(procs.pop(name), timeout=timeout)
            dump = None
            for line in out.splitlines():
                if line.startswith("{"):
                    d = json.loads(line)
                    if d.get("event") == "exit":
                        dump = d
            harvested[name] = {"rc": rc, "dump": dump, "err_tail": err[-2000:]}
            return harvested[name]

        try:
            # -- phase 1: cross-gang serving bit-identity -----------------
            _wait_ready(http_a)
            _wait_ready(http_b)
            _load(http_a, [http_a, http_b], bits, values)
            res_a, lat_a, ok_a = _serve_and_check(http_a, oracle)
            res_b, lat_b, ok_b = _serve_and_check(http_b, oracle)
            ok &= ok_a and ok_b
            summary["serving"] = {
                "leader_a_bit_identical": ok_a,
                "leader_b_bit_identical": ok_b,
                "latency_ms": {"A": lat_a, "B": lat_b},
                "results": {"A": res_a, "B": res_b},
                "oracle": oracle,
                "gang_health": {
                    "A": _gang_status(http_a),
                    "B": _gang_status(http_b),
                },
            }

            # -- phase 1.5: fleet observability (ISSUE 10) ----------------
            # one traceparent-tagged cross-gang query must come back as
            # ONE stitched trace with spans from >=3 distinct processes
            # (A leader root+replay, A follower's pushed replay, B
            # leader's envelope), and the A leader's fleet scrape must
            # carry every rank's build_info, instance-labeled
            tid = os.urandom(16).hex()
            tp = f"00-{tid}-{os.urandom(8).hex()}-01"
            # a write + TopN chain NOT in READ_QUERIES (and cache=false
            # at ingress): a plan-cache hit on either leader would
            # short-circuit the dispatch and emit no gang replay spans.
            # Row 88 / column 9001 stay outside the oracle's rows so
            # later bit-identity checks are unaffected
            st_t, _ = _http_h(
                http_a,
                "POST",
                "/index/i/query?cache=false",
                b"Set(9001, f=88) TopN(f, n=3)",
                headers={"traceparent": tp},
                timeout=120,
            )
            pids: set = set()
            n_entries = 0
            t_end = time.monotonic() + 30
            while time.monotonic() < t_end:
                st, body = _http(http_a, "GET", f"/debug/traces?trace_id={tid}")
                if st == 200:
                    entries = json.loads(body).get("traces") or []
                    n_entries = len(entries)
                    pids = set()
                    for d in entries:
                        _collect_pids(d, pids)
                    if len(pids) >= 3:
                        break
                time.sleep(0.5)
            trace_ok = st_t == 200 and n_entries >= 1 and len(pids) >= 3
            fleet_instances: set = set()
            t_end = time.monotonic() + 30
            while time.monotonic() < t_end:
                st, body = _http(http_a, "GET", "/metrics?fleet=true")
                if st == 200:
                    fleet_instances = {
                        line.split('instance="', 1)[1].split('"', 1)[0]
                        for line in body.decode().splitlines()
                        if line.startswith("pilosa_build_info{")
                        and 'instance="' in line
                    }
                    if len(fleet_instances) >= 4:
                        break
                time.sleep(0.5)
            fleet_ok = len(fleet_instances) >= 4
            obs_ok = trace_ok and fleet_ok
            ok &= obs_ok
            summary["observability"] = {
                "ok": obs_ok,
                "trace_id": tid,
                "stitched_trace_found": n_entries >= 1,
                "distinct_pids_in_trace": sorted(pids),
                "trace_spans_from_3plus_processes": trace_ok,
                "fleet_build_info_instances": sorted(fleet_instances),
                "fleet_scrape_all_ranks": fleet_ok,
            }

            # -- phase 1.6: federated workload heat (ISSUE 16) ------------
            # a deliberately skewed index: 40 bits land in shard 0, 10
            # in shard 1 (SHARD_WIDTH apart). Write heat is recorded
            # once per applying rank, and the replication x gang-replay
            # multiplier is IDENTICAL for both shards (replicas=2 over
            # both nodes), so the fleet-merged ``writes`` dimension must
            # reproduce the 4:1 ratio and imbalance_ratio
            # max/mean = 40/25 = 1.6 exactly — a hand-computed
            # placement-skew oracle on raw integer counters.
            SW = 1 << 20  # pilosa_tpu.SHARD_WIDTH
            n0, n1 = 40, 10
            st, _ = _http(http_a, "POST", "/index/hx", b"")
            assert st in (200, 409), st
            st, _ = _http(http_a, "POST", "/index/hx/field/hf", b"")
            assert st in (200, 409), st
            hsets = [f"Set({c}, hf=1)" for c in range(n0)]
            hsets += [f"Set({SW + c}, hf=1)" for c in range(n1)]
            st, body = _http(
                http_a, "POST", "/index/hx/query", " ".join(hsets).encode(), timeout=120
            )
            assert st == 200, (st, body[:300])
            # read heat on both shards; cache=false so the plan cache
            # can't short-circuit the executor's per-shard map legs
            for _ in range(3):
                _http(
                    http_a,
                    "POST",
                    "/index/hx/query?cache=false",
                    b"Count(Row(hf=1))",
                    timeout=120,
                )
            heat_ok = False
            hx: dict = {}
            w0 = w1 = 0
            t_end = time.monotonic() + 30
            while time.monotonic() < t_end:
                st, body = _http(
                    http_a, "GET", "/debug/heat?fleet=true&dim=writes&index=hx"
                )
                if st == 200:
                    hx = json.loads(body)
                    by_shard: dict = {}
                    reads_by_shard: dict = {}
                    for c in hx.get("cells") or []:
                        by_shard[c["shard"]] = by_shard.get(c["shard"], 0) + c["writes"]
                        reads_by_shard[c["shard"]] = (
                            reads_by_shard.get(c["shard"], 0) + c["reads"]
                        )
                    w0, w1 = by_shard.get(0, 0), by_shard.get(1, 0)
                    skew = hx.get("skew") or {}
                    top = skew.get("top") or [{}]
                    if (
                        w1 > 0
                        and w0 == 4 * w1  # replication multiplier cancels
                        and skew.get("imbalance_ratio") == 1.6
                        and (top[0].get("index"), top[0].get("shard")) == ("hx", 0)
                        and len(hx.get("instances") or []) >= 4
                        and reads_by_shard.get(0, 0) > 0
                        and reads_by_shard.get(1, 0) > 0
                    ):
                        heat_ok = True
                        break
                time.sleep(0.5)
            ok &= heat_ok
            summary["heat"] = {
                "ok": heat_ok,
                "oracle": {"writes_ratio": 4.0, "imbalance_ratio": 1.6},
                "merged_writes": {"shard0": w0, "shard1": w1},
                "instances": hx.get("instances"),
                "skew": hx.get("skew"),
            }

            # -- phase 2: follower SIGKILL -> bounded fence + DEGRADED ----
            t_kill = time.monotonic()
            procs["A1"].kill()
            t0 = time.monotonic()
            status, _ = _query(
                http_a, "Set(701, f=90)", timeout=dispatch_timeout * 3 + 30
            )
            first_s = time.monotonic() - t0
            _poll_gang_state(http_a, "DEGRADED", dispatch_timeout * 3)
            # first write after the kill either ate the bounded fence
            # (503) or landed after the degrade (200) — never a hang
            bounded = first_s < dispatch_timeout * 3
            w_status, w_res = _query(http_a, "Set(701, f=90)")
            unavail_a = time.monotonic() - t_kill
            # a fenced 503 write may still have applied before the fence
            # (at-least-once), so the retry can see changed=False; the
            # contract is the retry SUCCEEDS and the bit is then visible
            rb_status, rb_res = _query(http_a, "Count(Row(f=90))")
            r_status, r_res = _query(http_a, "Count(Row(f=1))")
            # the other gang keeps answering correctly throughout
            res_b2, _, ok_b2 = _serve_and_check(http_b, oracle)
            follower_exit = harvest("A1", timeout=10)
            kill_ok = (
                bounded
                and status in (200, 503)
                and w_status == 200
                and w_res in ([True], [False])
                and rb_status == 200
                and rb_res == [1]
                and r_status == 200
                and r_res == oracle["Count(Row(f=1))"]
                and ok_b2
            )
            ok &= kill_ok
            summary["follower_kill"] = {
                "ok": kill_ok,
                "first_write_status": status,
                "first_write_seconds": round(first_s, 2),
                "first_write_bounded": bounded,
                "write_after_degrade": [w_status, w_res],
                "write_readback": [rb_status, rb_res],
                "read_after_degrade": [r_status, r_res],
                "write_unavailability_seconds": round(unavail_a, 2),
                "gang_a": _gang_status(http_a),
                "leader_b_bit_identical_during_degrade": ok_b2,
                "follower_rc": follower_exit["rc"],
            }

            # -- phase 3: rejoin -> re-form -> ACTIVE + replication -------
            t0 = time.monotonic()
            procs["A1r"] = _spawn(
                env,
                tmp,
                "A1r",
                **{
                    MODE_ENV: "rejoin",
                    REJOIN_ENV: f"http://127.0.0.1:{http_a}",
                    SELF_HTTP_ENV: str(http_a1r),
                },
            )
            # budget covers worker boot (jax import) + push + reform
            reform_a = _poll_gang_state(http_a, "ACTIVE", REFORM_BUDGET + 30)
            gang_a = _gang_status(http_a)
            _query(http_a, "Set(123, f=97)")
            t_end = time.monotonic() + 15
            repl = None
            while time.monotonic() < t_end:
                st, repl = _query(http_a1r, "Count(Row(f=97))")
                if st == 200 and repl == [1]:
                    break
                time.sleep(0.25)
            res_a3, _, ok_a3 = _serve_and_check(http_a, oracle)
            reform_ok = (
                reform_a < REFORM_BUDGET + 30
                and gang_a.get("epoch", 0) >= 1
                and f"http://127.0.0.1:{http_a1r}" in (gang_a.get("replicas") or [])
                and repl == [1]
                and ok_a3
            )
            ok &= reform_ok
            summary["reform"] = {
                "ok": reform_ok,
                "reform_seconds": round(reform_a, 2),
                "gang_a": gang_a,
                "write_replicated_to_rejoined_follower": repl == [1],
                "leader_a_bit_identical_after_reform": ok_a3,
            }

            # -- phase 3.5: the kill/rejoin cycle in the event journal ----
            # A's leader must journal ACTIVE->DEGRADED, then
            # DEGRADED->REFORMING, then REFORMING->ACTIVE, in seq order,
            # with the epoch bumped across the cycle
            st, body = _http(http_a, "GET", "/debug/events?kind=gang.transition")
            edges = [
                (e.get("frm"), e.get("to"), e.get("epoch", 0))
                for e in (json.loads(body).get("events") or [])
            ] if st == 200 else []

            def _edge_idx(frm: str, to: str) -> int:
                for i, (f, t, _) in enumerate(edges):
                    if f == frm and t == to:
                        return i
                return -1

            i_deg = _edge_idx("ACTIVE", "DEGRADED")
            i_ref = _edge_idx("DEGRADED", "REFORMING")
            i_act = _edge_idx("REFORMING", "ACTIVE")
            events_ok = (
                0 <= i_deg < i_ref < i_act
                and edges[i_act][2] > edges[i_deg][2]
            )
            ok &= events_ok
            summary["observability"]["events_ok"] = events_ok
            summary["observability"]["gang_a_transitions"] = edges

            # -- phase 4: leader SIGKILL -> failover -> solo restart ------
            t_kill = time.monotonic()
            procs["B0"].kill()
            t0 = time.monotonic()
            res_a4, _, ok_a4 = _serve_and_check(http_a, oracle)
            failover_s = time.monotonic() - t0
            b1_exit = harvest("B1", timeout=40)  # leader_timeout=15 + slack
            procs["B0r"] = _spawn(
                env,
                tmp,
                "B0",  # SAME data dir + port: a restarted leader
                **{MODE_ENV: "leader", SELF_HTTP_ENV: str(http_b)},
            )
            _wait_ready(http_b)
            solo = _gang_status(http_b)
            procs["B1r"] = _spawn(
                env,
                tmp,
                "B1r",
                **{
                    MODE_ENV: "rejoin",
                    REJOIN_ENV: f"http://127.0.0.1:{http_b}",
                    SELF_HTTP_ENV: str(http_b1r),
                },
            )
            reform_b = _poll_gang_state(http_b, "ACTIVE", REFORM_BUDGET + 30)
            unavail_b = time.monotonic() - t_kill
            # post-recovery: rank caches on the healed leader
            _http(http_b, "POST", "/recalculate-caches", b"")
            res_b5, _, ok_b5 = _serve_and_check(http_b, oracle)
            res_a5, _, ok_a5 = _serve_and_check(http_a, oracle)
            st97, r97 = _query(http_b, "Count(Row(f=97))")
            leader_ok = (
                ok_a4  # zero wrong answers while B's leader was dead
                and solo.get("state") == "DEGRADED"
                and solo.get("mode") == "replicated"
                and ok_b5
                and ok_a5
                and st97 == 200
                and r97 == [1]  # pre-kill write healed into the restarted B
            )
            ok &= leader_ok
            summary["leader_kill"] = {
                "ok": leader_ok,
                "leader_a_bit_identical_during_outage": ok_a4,
                "failover_first_pass_seconds": round(failover_s, 2),
                "b1_stop_reason": (b1_exit["dump"] or {}).get("stop_reason"),
                "solo_restart_gang": solo,
                "gang_b_reform_seconds": round(reform_b, 2),
                "gang_b_unavailability_seconds": round(unavail_b, 2),
                "gang_b": _gang_status(http_b),
                "leader_b_bit_identical_after_recovery": ok_b5,
                "healed_write_on_restarted_leader": r97 == [1],
            }
            summary["unavailability_windows_s"] = {
                "gang_a_follower_death": summary["follower_kill"][
                    "write_unavailability_seconds"
                ],
                "gang_a_reform": summary["reform"]["reform_seconds"],
                "gang_b_leader_death_to_active": round(unavail_b, 2),
            }
        except Exception as e:
            summary["error"] = f"{type(e).__name__}: {e}"
            ok = False
        finally:
            for name, p in list(procs.items()):
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
            for name in list(procs):
                harvest(name, timeout=60)
            summary["worker_rc"] = {n: h["rc"] for n, h in harvested.items()}
            if not ok:
                for n, h in harvested.items():
                    print(f"-- {n} rc={h['rc']}\n{h['err_tail']}", file=sys.stderr)

    summary["ok"] = bool(ok)
    print(json.dumps(summary, indent=2))
    if not quick:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "FEDERATION_r7.json"
        )
        with open(path, "w") as f:
            json.dump(summary, f, indent=2)
    return 0 if ok else 1


if __name__ == "__main__":
    if os.environ.get(MODE_ENV) is not None:
        worker()
    else:
        import argparse

        ap = argparse.ArgumentParser()
        ap.add_argument("--quick", action="store_true", help="smaller load (CI smoke)")
        a = ap.parse_args()
        sys.exit(parent(a.quick))
