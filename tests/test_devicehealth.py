"""Device health gate: a wedged accelerator degrades reads to the CPU
path (bit-identically) instead of hanging them, and a succeeding probe
restores the device path. The wedge is simulated by patching a device
kernel to block longer than the gate timeout."""

import time

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.devicehealth import DeviceDown, DeviceHealth


def _failing_probe():
    raise RuntimeError("device wedged")


def _holder(tmp_path):
    h = Holder(str(tmp_path / "d"))
    h.open()
    fld = h.create_index("i").create_field("f")
    rng = np.random.default_rng(5)
    rows, cols = [], []
    for shard in range(2):
        base = shard * SHARD_WIDTH
        for r in range(10):
            k = 200 + 30 * r
            rows += [r] * k
            cols += (base + rng.integers(0, SHARD_WIDTH, size=k)).tolist()
    fld.import_bits(rows, cols)
    return h


class TestDeviceHealthUnit:
    def test_guard_runs_and_times_out(self):
        # wedged device: the deadline passes AND the probe fails
        hlth = DeviceHealth(
            timeout_s=0.2,
            probe_interval_s=3600,
            probe_timeout_s=0.1,
            probe_fn=_failing_probe,
        )
        assert hlth.guard(lambda: 41 + 1) == 42
        with pytest.raises(DeviceDown):
            hlth.guard(lambda: time.sleep(2))
        assert not hlth.healthy
        assert hlth.trips == 1
        # gate closed: further guarded calls refuse immediately
        t0 = time.monotonic()
        with pytest.raises(DeviceDown):
            hlth.guard(lambda: 1)
        assert time.monotonic() - t0 < 0.1
        hlth.close()

    def test_slow_call_with_live_device_does_not_trip(self):
        # deadline passes mid-call but the probe answers: the gate must
        # extend the deadline and return the result, not condemn the
        # device (a long pure-CPU stretch can never fake a dead device)
        hlth = DeviceHealth(
            timeout_s=0.15,
            probe_interval_s=3600,
            probe_timeout_s=1.0,
            probe_fn=lambda: None,
        )
        assert hlth.guard(lambda: (time.sleep(0.5), 99)[1]) == 99
        assert hlth.healthy
        assert hlth.trips == 0
        assert hlth.slow_calls >= 1
        hlth.close()

    def test_saturated_pool_with_live_device_degrades_without_trip(self):
        # every worker busy with long (CPU-side) work: a new call must
        # fall back for ITSELF but not condemn the healthy device
        hlth = DeviceHealth(
            timeout_s=0.2,
            probe_interval_s=3600,
            probe_timeout_s=1.0,
            probe_fn=lambda: None,
            max_workers=1,
        )
        import threading

        release = threading.Event()
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=1) as tp:
            occupier = tp.submit(lambda: hlth.guard(release.wait))
            time.sleep(0.05)  # occupier now holds the only guard worker
            with pytest.raises(DeviceDown):
                hlth.guard(lambda: 1)
            assert hlth.healthy  # gate stays open
            assert hlth.trips == 0
            release.set()
            occupier.result(timeout=5)
        hlth.close()

    def test_stager_epoch_blocks_zombie_reinsert(self):
        from pilosa_tpu.executor.stager import DeviceStager

        st = DeviceStager()
        import threading

        entered = threading.Event()
        proceed = threading.Event()

        def slow_builder():
            entered.set()
            proceed.wait(timeout=10)
            return ("stale-handle", 8, 0)

        out = {}
        t = threading.Thread(
            target=lambda: out.update(v=st._get_or_build(("k",), 0, slow_builder))
        )
        t.start()
        entered.wait(timeout=5)
        st.reset_after_wedge()  # wedge + restore while builder is live
        proceed.set()
        t.join(timeout=5)
        # the zombie's value reached its own caller...
        assert out["v"] == "stale-handle"
        # ...but never entered the post-reset cache
        assert st._get_or_build(("k",), 0, lambda: ("fresh", 8, 0)) == "fresh"

    def test_probe_restores(self):
        hlth = DeviceHealth(
            timeout_s=0.2,
            probe_interval_s=0.05,
            probe_timeout_s=1.0,
            probe_fn=lambda: None,  # device recovers: probe succeeds
        )
        hlth._trip("test wedge")
        assert not hlth.healthy
        deadline = time.monotonic() + 5
        while not hlth.healthy and time.monotonic() < deadline:
            time.sleep(0.02)
        assert hlth.healthy
        assert hlth.restores == 1
        assert hlth.guard(lambda: 7) == 7
        hlth.close()


class TestExecutorDegradation:
    def test_wedged_kernel_falls_back_to_cpu(self, tmp_path, monkeypatch):
        h = _holder(tmp_path)
        cpu = Executor(h, device_policy="never")
        hlth = DeviceHealth(
            timeout_s=0.5,
            probe_interval_s=3600,
            probe_timeout_s=0.1,
            probe_fn=_failing_probe,
        )
        dev = Executor(h, device_policy="always", health=hlth)
        q = "TopN(f, Row(f=3), n=5)"
        want = cpu.execute("i", q)
        assert dev.execute("i", q) == want  # healthy path first

        # wedge the stacked scoring kernel (blocks past the deadline)
        import pilosa_tpu.executor.executor as exmod

        def hang(*a, **kw):
            time.sleep(30)

        monkeypatch.setattr(
            exmod.ops, "sparse_intersection_counts_stacked", hang
        )
        monkeypatch.setattr(
            exmod.ops, "sparse_intersection_counts", hang
        )
        t0 = time.monotonic()
        got = dev.execute("i", q)
        elapsed = time.monotonic() - t0
        assert got == want  # served by the CPU fallback, bit-identical
        assert elapsed < 10  # did not wait out the 30 s hang
        assert hlth.trips == 1 and not hlth.healthy
        # gate closed: subsequent reads go straight to CPU, fast
        t0 = time.monotonic()
        assert dev.execute("i", "Count(Row(f=3))") == cpu.execute(
            "i", "Count(Row(f=3))"
        )
        assert time.monotonic() - t0 < 2
        # writes never touch the gate
        assert dev.execute("i", "Set(999999, f=3)") == [True]
        dev.close()
        h.close()

    def test_recovery_restores_device_path(self, tmp_path):
        h = _holder(tmp_path)
        hlth = DeviceHealth(
            timeout_s=0.5,
            probe_interval_s=0.05,
            probe_timeout_s=1.0,
            probe_fn=lambda: None,
        )
        dev = Executor(h, device_policy="always", health=hlth)
        cpu = Executor(h, device_policy="never")
        q = "Count(Intersect(Row(f=1), Row(f=2)))"
        want = cpu.execute("i", q)
        old_scorer = dev.scorer
        old_stacked = dev.stacked_scorer
        # trip the gate directly (simulates a timed-out call)
        hlth._trip("test wedge")
        assert dev.execute("i", q) == want  # CPU while gated
        deadline = time.monotonic() + 5
        while not hlth.healthy and time.monotonic() < deadline:
            time.sleep(0.02)
        assert hlth.healthy
        # restore replaced the machinery whose locks zombies may hold
        assert dev.scorer is not old_scorer
        assert dev.stacked_scorer is not old_stacked
        assert dev.execute("i", q) == want  # device path again
        dev.close()
        h.close()
