"""Sharded gang federation tests (ISSUE 7): fault-injection hooks on
the gang channel (abort-under-loss, fence-under-delay), the gang
lifecycle state machine (degrade → replicated-solo → reform → ACTIVE,
never degrade-forever), cross-gang RPC retries, gang-state gossip on
the cluster plane, and an in-process federated leader + follower
rejoin cycle over real HTTP — plus a slow 2-gang × 2-process kill /
recover run (the dryrun driver in quick mode)."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from pilosa_tpu.parallel import federation, multihost
from pilosa_tpu.parallel.client import ClientError, InternalClient, _retryable
from pilosa_tpu.parallel.multihost import (
    Descriptor,
    FaultSpec,
    FaultyChannel,
    GangFollower,
    GangUnavailable,
    KIND_QUERY,
    LoopbackChannel,
    MODE_COLLECTIVE,
    MODE_REPLICATED,
    MultiHostRuntime,
    STATE_ACTIVE,
    STATE_DEGRADED,
    STATE_REFORMING,
    encode_message,
    maybe_faulty,
)
from pilosa_tpu.parallel.node import Node
from pilosa_tpu.utils import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- fault injection (satellite: env/config-gated channel faults) -------------


class TestFaultSpec:
    def test_parse_all_knobs(self):
        s = FaultSpec.parse("drop_every=3, delay=0.25, dup_every=5, after=10")
        assert s.drop_every == 3
        assert s.dup_every == 5
        assert s.delay == 0.25
        assert s.after == 10
        assert bool(s)

    def test_parse_empty_is_falsy(self):
        assert not FaultSpec.parse("")
        assert not FaultSpec.parse("after=5")  # an offset alone faults nothing

    def test_parse_unknown_knob_rejected(self):
        with pytest.raises(ValueError):
            # check: disable=fault-spec (deliberately invalid knob — the ValueError is the assertion)
            FaultSpec.parse("explode_every=2")

    def test_maybe_faulty(self):
        ch = LoopbackChannel(1024)
        assert maybe_faulty(ch, "") is ch
        wrapped = maybe_faulty(ch, "drop_every=2")
        assert isinstance(wrapped, FaultyChannel)
        assert wrapped.frame_bytes == 1024

    def test_runtime_faults_param_wraps_channel(self):
        rt = MultiHostRuntime(
            rank=0, world=1, channel=LoopbackChannel(1024),
            apply_fn=lambda k, p: None, faults="drop_every=3",
        )
        assert isinstance(rt.channel, FaultyChannel)


class TestChannelFaults:
    def test_drop_aborts_follower_as_desync(self):
        """A dropped (zeroed) frame reads as bad magic — the follower
        must abort the loop cleanly ('desync'), never apply garbage."""
        ch = FaultyChannel(LoopbackChannel(2048), FaultSpec(drop_every=1))
        ch.send(encode_message(KIND_QUERY, json.dumps({"n": 1}).encode(), 2048))
        f = GangFollower(ch, lambda k, p: None, leader_timeout=5.0)
        assert f.run() == "desync"
        assert f.works == 0

    def test_duplicate_frame_detected_as_desync(self):
        """Duplicate delivery inside a multi-frame message breaks seq
        continuity — detected, not silently double-applied."""
        ch = FaultyChannel(LoopbackChannel(512), FaultSpec(dup_every=1))
        blob = json.dumps({"pad": "x" * 2000}).encode()  # several frames
        ch.send(encode_message(KIND_QUERY, blob, 512))
        f = GangFollower(ch, lambda k, p: None, leader_timeout=5.0)
        assert f.run() == "desync"

    def test_after_offset_lets_bringup_pass(self):
        """after=K: the first K frames fly clean (bring-up traffic),
        then the schedule starts."""
        inner = LoopbackChannel(2048)
        ch = FaultyChannel(inner, FaultSpec(drop_every=1, after=2))
        applied = []
        for i in range(3):
            ch.send(encode_message(KIND_QUERY, json.dumps({"n": i}).encode(), 2048))
        f = GangFollower(ch, lambda k, p: applied.append(p["n"]), leader_timeout=0.3)
        assert f.run() == "desync"  # third frame was zeroed
        assert applied == [0, 1]

    def test_delay_trips_dispatch_fence(self):
        """fence-under-delay: a send slower than dispatch_timeout turns
        into the designed degrade + GangUnavailable, never a hang."""
        ch = FaultyChannel(LoopbackChannel(2048), FaultSpec(delay=5.0))
        rt = MultiHostRuntime(
            rank=0, world=2, channel=ch,
            apply_fn=lambda k, p: "never", idle_interval=0,
            dispatch_timeout=0.3,
        )
        t0 = time.monotonic()
        with pytest.raises(GangUnavailable):
            rt.dispatch(Descriptor(KIND_QUERY, {}))
        assert time.monotonic() - t0 < 3.0
        assert rt.degraded


# -- lifecycle state machine --------------------------------------------------


def _runtime(federated=True, **kw):
    kw.setdefault("channel", LoopbackChannel(4096))
    kw.setdefault("apply_fn", lambda k, p: p.get("n"))
    kw.setdefault("idle_interval", 0)
    kw.setdefault("dispatch_timeout", 5.0)
    rt = MultiHostRuntime(rank=0, world=2, **kw)
    rt.federated = federated
    return rt


class TestLifecycle:
    def test_federated_degrade_enters_replicated_solo(self):
        """Follower death on a FEDERATED gang is not the end: the
        leader re-enters service replicated-solo — DEGRADED (peers
        route around it) but still dispatching."""
        hooks = []
        rt = _runtime()
        rt.on_degrade = lambda: hooks.append("degrade")
        rt.degrade("follower died")
        assert rt.state == STATE_DEGRADED and rt.degraded
        assert rt.mode == MODE_REPLICATED
        assert hooks == ["degrade"]
        assert rt.should_dispatch()
        assert rt.dispatch(Descriptor(KIND_QUERY, {"n": 7})) == 7
        rt.close()

    def test_nonfederated_degrade_stays_dead(self):
        """PR 5 single-plane semantics preserved: without a federation,
        DEGRADED-collective refuses dispatch until process restart."""
        rt = _runtime(federated=False)
        rt.degrade("follower died")
        assert rt.mode == MODE_COLLECTIVE
        assert not rt.should_dispatch()
        with pytest.raises(GangUnavailable):
            rt.dispatch(Descriptor(KIND_QUERY, {}))

    def test_reform_bumps_epoch_and_returns_active(self):
        events = []
        rt = _runtime()
        rt.on_reform = lambda: events.append("reform")
        rt.on_state_change = lambda st, ep: events.append((st, ep))
        rt.degrade("follower died")
        out = rt.reform(["http://f:1"], reason="follower rejoined")
        assert out == {"epoch": 1, "state": STATE_ACTIVE, "mode": MODE_REPLICATED}
        assert rt.epoch == 1 and rt.state == STATE_ACTIVE
        assert "reform" in events
        # DEGRADED -> REFORMING -> ACTIVE announced in order
        states = [e[0] for e in events if isinstance(e, tuple)]
        assert states == [STATE_DEGRADED, STATE_REFORMING, STATE_ACTIVE]
        h = rt.health()
        assert h["state"] == STATE_ACTIVE and h["epoch"] == 1
        assert h["replicas"] == ["http://f:1"]
        assert h["lastTransition"]["to"] == STATE_ACTIVE
        # dispatch works again, and the transition log kept the history
        assert rt.dispatch(Descriptor(KIND_QUERY, {"n": 3})) == 3
        arcs = [(t["from"], t["to"]) for t in rt.transitions]
        assert (STATE_ACTIVE, STATE_DEGRADED) in arcs
        assert (STATE_REFORMING, STATE_ACTIVE) in arcs
        rt.close()

    def test_reform_fences_inflight_dispatch(self):
        """Work queued behind an in-flight dispatch gets the bounded
        GangUnavailable when reform fences the queue; the new epoch's
        loop serves fresh work."""
        gate = threading.Event()
        started = threading.Event()

        def apply(kind, payload):
            if payload.get("block"):
                started.set()
                gate.wait(timeout=10)
            return payload.get("n")

        rt = _runtime(apply_fn=apply)
        rt.federated = True
        errs, out = [], []

        def d(payload):
            try:
                out.append(rt.dispatch(Descriptor(KIND_QUERY, payload)))
            except GangUnavailable as e:
                errs.append(e)

        t1 = threading.Thread(target=d, args=({"block": True, "n": 1},))
        t1.start()
        assert started.wait(timeout=5)
        t2 = threading.Thread(target=d, args=({"n": 2},))  # queued behind
        t2.start()
        time.sleep(0.1)
        rt.reform(["http://f:1"], reason="operator")
        t2.join(timeout=5)
        assert len(errs) == 1 and "re-forming" in str(errs[0])
        gate.set()
        t1.join(timeout=5)
        assert out == [1]  # in-flight work completed under the old loop
        assert rt.dispatch(Descriptor(KIND_QUERY, {"n": 9})) == 9
        rt.close()

    def test_replica_loss_degrades_and_recovers_again(self):
        """Double failure: the re-formed replica dies too — the gang
        returns to DEGRADED (solo), keeps serving, and a second reform
        recovers it. No degrade-forever path."""
        rt = _runtime()
        rt.degrade("follower died")
        rt.reform(["http://f:1"])
        calls = []

        def replicate(uri, kind, payload, epoch):
            calls.append((uri, epoch))
            raise ClientError("connection refused", transport=True)

        rt.replicate_fn = replicate
        assert rt.dispatch(Descriptor(KIND_QUERY, {"n": 1})) == 1
        assert calls == [("http://f:1", 1)]
        assert rt.state == STATE_DEGRADED
        assert rt.health()["replicas"] == []
        # still serving solo, and a second reform returns ACTIVE
        assert rt.dispatch(Descriptor(KIND_QUERY, {"n": 2})) == 2
        out = rt.reform(["http://f:2"])
        assert out["epoch"] == 2 and rt.state == STATE_ACTIVE
        rt.close()

    def test_replicated_classmethod_boot(self):
        """A restarted leader boots replicated-solo: active without
        jax.distributed, DEGRADED until a follower rejoins."""
        rt = MultiHostRuntime.replicated(apply_fn=lambda k, p: p["n"] * 2)
        assert rt.active and rt.rank == 0 and rt.world == 1
        assert rt.state == STATE_DEGRADED
        assert rt.mode == MODE_REPLICATED and rt.federated
        assert rt.dispatch(Descriptor(KIND_QUERY, {"n": 4})) == 8
        out = rt.reform(["http://f:1"])
        assert out["state"] == STATE_ACTIVE and out["epoch"] == 1
        rt.close()


# -- dispatch decision tables -------------------------------------------------


class TestDispatchTables:
    def test_query_table_single_plane(self):
        rt = _runtime(federated=False)
        assert rt.should_dispatch_query(remote=False)
        assert not rt.should_dispatch_query(remote=True)
        rt.degrade("dead")
        assert not rt.should_dispatch_query(remote=False)

    def test_query_table_federated_collective(self):
        rt = _runtime()
        # cluster plane splits first; only the routed legs replay
        assert rt.should_dispatch_query(remote=True, query_text="Count(Row(f=1))")
        assert not rt.should_dispatch_query(remote=False)

    def test_query_table_federated_replicated(self):
        rt = _runtime()
        rt.degrade("dead")  # -> replicated-solo
        # reads run straight on the local mesh; writes order + replicate
        assert not rt.should_dispatch_query(remote=True, query_text="Count(Row(f=1))")
        assert rt.should_dispatch_query(remote=True, query_text="Set(10, f=1)")
        assert rt.should_dispatch_query(remote=True, query_text="SetValue(f=10, 7)")
        assert rt.should_dispatch_query(remote=True, query_text="Clear(10, f=1)")
        assert rt.should_dispatch_query(
            remote=True, query_text='SetRowAttrs(f, 1, x="y")'
        )
        assert not rt.should_dispatch_query(remote=True, query_text="TopN(f, n=5)")
        rt.close()

    def test_import_table(self):
        single = _runtime(federated=False)
        assert single.should_dispatch_import(local=False)
        assert not single.should_dispatch_import(local=True)
        fed = _runtime()
        assert fed.should_dispatch_import(local=True)
        assert not fed.should_dispatch_import(local=False)
        fed.degrade("dead")  # replicated-solo still applies local legs
        assert fed.should_dispatch_import(local=True)
        fed.close()

    def test_reforming_refuses_and_degraded_collective_refuses(self):
        rt = _runtime()
        rt.state = STATE_REFORMING
        # control messages apply locally-only during the re-form fence;
        # data paths still route to dispatch(), which raises the
        # bounded GangUnavailable (the 503 the fence is made of)
        assert not rt.should_dispatch()
        assert rt.should_dispatch_import(local=True)
        with pytest.raises(GangUnavailable):
            rt.dispatch(Descriptor(KIND_QUERY, {}))
        rt.state = STATE_ACTIVE
        rt2 = _runtime()
        rt2.federated = True
        rt2.mode = MODE_COLLECTIVE
        rt2.state = STATE_DEGRADED
        assert not rt2.should_dispatch_query(remote=True, query_text="Count(Row(f=1))")
        assert not rt2.should_dispatch_import(local=True)


# -- cross-gang RPC retries (satellite: backoff + jitter + deadline) ----------


class TestClientRetry:
    def _fail_then_ok(self, failures, exc):
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            if calls["n"] <= failures:
                raise exc
            return "ok"

        return fn, calls

    def test_retryable_classification(self):
        assert _retryable(ClientError("x", transport=True))
        assert _retryable(ClientError("x", status=503))
        assert not _retryable(ClientError("x", status=400))
        assert not _retryable(ClientError("x", status=409))

    def test_transient_503_retried_then_succeeds(self):
        c = InternalClient(retries=2, retry_backoff=0.001)
        fn, calls = self._fail_then_ok(2, ClientError("fencing", status=503))
        before = metrics.snapshot().get("client.retries;op:t1", 0)
        assert c._with_retry("t1", fn) == "ok"
        assert calls["n"] == 3
        assert metrics.snapshot().get("client.retries;op:t1", 0) == before + 2

    def test_exhausted_raises_and_counts(self):
        c = InternalClient(retries=2, retry_backoff=0.001)
        fn, calls = self._fail_then_ok(99, ClientError("down", transport=True))
        before = metrics.snapshot().get("client.retry_exhausted;op:t2", 0)
        with pytest.raises(ClientError):
            c._with_retry("t2", fn)
        assert calls["n"] == 3  # initial + 2 retries
        assert metrics.snapshot().get("client.retry_exhausted;op:t2", 0) == before + 1

    def test_deterministic_errors_not_retried(self):
        c = InternalClient(retries=3, retry_backoff=0.001)
        fn, calls = self._fail_then_ok(99, ClientError("bad query", status=400))
        with pytest.raises(ClientError):
            c._with_retry("t3", fn)
        assert calls["n"] == 1

    def test_zero_retries_is_one_shot(self):
        c = InternalClient(retries=0)
        fn, calls = self._fail_then_ok(99, ClientError("down", transport=True))
        with pytest.raises(ClientError):
            c._with_retry("t4", fn)
        assert calls["n"] == 1

    def test_deadline_fences_backoff(self):
        """A retry whose backoff cannot fit the remaining request
        budget is not attempted — fail over instead of a doomed wait."""
        from pilosa_tpu.server import deadline

        c = InternalClient(retries=5, retry_backoff=5.0)
        fn, calls = self._fail_then_ok(99, ClientError("down", transport=True))
        t0 = time.monotonic()
        with deadline.activate(deadline.Deadline.after(0.2)):
            with pytest.raises(ClientError):
                c._with_retry("t5", fn)
        assert calls["n"] == 1
        assert time.monotonic() - t0 < 1.0


# -- gang-state on the cluster plane ------------------------------------------


class TestGangStateGossip:
    def test_node_serialization_round_trip(self):
        n = Node(id="a", uri="http://a:1", gang_state="DEGRADED", gang_epoch=3)
        d = n.to_dict()
        assert d["gangState"] == "DEGRADED" and d["gangEpoch"] == 3
        back = Node.from_dict(d)
        assert back.gang_state == "DEGRADED" and back.gang_epoch == 3

    def test_plain_node_payload_unchanged(self):
        d = Node(id="a", uri="http://a:1").to_dict()
        assert "gangState" not in d and "gangEpoch" not in d
        back = Node.from_dict(d)
        assert back.gang_state == "" and back.gang_epoch == 0


# -- in-process federated rejoin cycle over real HTTP -------------------------


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def _req(uri, method, path, body=None):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(uri + path, data=data, method=method)
    try:
        with urllib.request.urlopen(r, timeout=30) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _wait(pred, timeout=20.0, every=0.1, what="condition"):
    t_end = time.monotonic() + timeout
    while time.monotonic() < t_end:
        if pred():
            return
        time.sleep(every)
    raise AssertionError(f"timed out waiting for {what}")


class TestFederatedRejoinCycle:
    def test_leader_boot_rejoin_replicate_and_double_failure(self, tmp_path):
        """The full lifecycle in one process, over real HTTP: a
        replicated-solo federated leader (DEGRADED) serving next to a
        plain peer, a follower rejoin that re-stages state and flips
        the gang ACTIVE at a bumped epoch, write replication to the
        re-formed follower, epoch fencing of stale descriptors, and a
        second failure returning to DEGRADED — never degrade-forever."""
        from pilosa_tpu.server import ClusterConfig, Config, Server

        pa, pb, pf = _free_ports(3)
        hosts = [f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"]

        def cfg(port, i, **kw):
            return Config(
                data_dir=str(tmp_path / f"n{port}"),
                bind=f"127.0.0.1:{port}",
                device_policy="never",
                metric="none",
                anti_entropy_interval=0,
                client_retries=0,  # fail fast in-process
                cluster=ClusterConfig(
                    disabled=False,
                    coordinator=(i == 0),
                    replicas=2,
                    hosts=hosts,
                    probe_interval=0,
                    # >0 so the boot-time NodeStatus pull runs: B boots
                    # after A's DEGRADED broadcast and must adopt A's
                    # current gang state at join
                    status_interval=30.0,
                ),
                **kw,
            )

        a = Server(cfg(pa, 0, federation_leader=True))
        a.open()
        b = Server(cfg(pb, 1))
        b.open()
        ua, ub = f"http://127.0.0.1:{pa}", f"http://127.0.0.1:{pb}"
        servers = [a, b]
        try:
            # federation wired: replicated-solo leader, DEGRADED
            assert a.multihost is not None and a.multihost.federated
            st, body = _req(ua, "GET", "/status")
            assert st == 200
            assert body["gang"]["state"] == "DEGRADED"
            assert body["gang"]["mode"] == "replicated"
            assert b.multihost is None  # plain peer: no gang block

            # load through the DEGRADED leader: writes order through the
            # gang leader thread, reads route around the fencing gang
            _req(ua, "POST", "/index/i", {})
            _req(ua, "POST", "/index/i/field/f", {})
            for col in range(20):
                st, r = _req(
                    ua, "POST", "/index/i/query", f"Set({col}, f=1)".encode()
                )
                assert st == 200, r
            st, r = _req(ua, "POST", "/index/i/query", b"Count(Row(f=1))")
            assert st == 200 and r["results"] == [20]
            # B's knowledge of A's gang state rides the coordinator's
            # status gossip (async); once it lands, B's reads route
            # around the fencing gang's (write-skipped, stale) replica
            _wait(
                lambda: next(
                    (n.gang_state for n in b.cluster.nodes if n.uri == ua), ""
                )
                == "DEGRADED",
                what="gang-state gossip to peer B",
            )
            st, r = _req(ub, "POST", "/index/i/query", b"Count(Row(f=1))")
            assert st == 200 and r["results"] == [20]

            # follower rejoin: fresh data dir, re-staged over HTTP
            f = Server(
                Config(
                    data_dir=str(tmp_path / "fol"),
                    bind=f"127.0.0.1:{pf}",
                    device_policy="never",
                    metric="none",
                    federation_rejoin=ua,
                )
            )
            f.open()
            servers.append(f)
            uf = f"http://127.0.0.1:{pf}"
            _wait(
                lambda: a.multihost.state == "ACTIVE",
                what="gang re-formation",
            )
            st, body = _req(ua, "GET", "/status")
            assert body["gang"]["state"] == "ACTIVE"
            assert body["gang"]["epoch"] >= 1
            assert uf in body["gang"]["replicas"]
            assert f.gang_epoch == body["gang"]["epoch"]
            # the cluster plane heard the transitions
            node_a = next(n for n in b.cluster.nodes if n.uri == ua)
            assert node_a.gang_state == "ACTIVE"

            # re-staged state: the follower answers like the leader
            st, r = _req(uf, "POST", "/index/i/query", b"Count(Row(f=1))")
            assert st == 200 and r["results"] == [20]

            # a write on the leader replicates to the re-formed follower
            st, r = _req(ua, "POST", "/index/i/query", b"Set(500, f=2)")
            assert st == 200 and r["results"] == [True]
            _wait(
                lambda: _req(uf, "POST", "/index/i/query", b"Count(Row(f=2))")[1].get(
                    "results"
                )
                == [1],
                what="write replication to follower",
            )

            # epoch fence: a stale (pre-re-form) descriptor is refused
            st, r = _req(
                uf,
                "POST",
                "/internal/gang/apply",
                {"kind": multihost.KIND_MESSAGE, "payload": {}, "epoch": 0},
            )
            assert st == 409, r

            # double failure: kill the follower; the next replicated
            # write drops it and the gang returns to DEGRADED — serving
            f.close()
            st, r = _req(ua, "POST", "/index/i/query", b"Set(501, f=2)")
            assert st == 200 and r["results"] == [True]
            _wait(
                lambda: a.multihost.state == "DEGRADED",
                what="degrade on replica loss",
            )
            st, r = _req(ua, "POST", "/index/i/query", b"Count(Row(f=2))")
            assert st == 200 and r["results"] == [2]
            st, body = _req(ua, "GET", "/debug/multihost")
            assert body["state"] == "DEGRADED" and body["replicas"] == []
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass


# -- 2-gang x 2-process kill/recover smoke ------------------------------------


@pytest.mark.slow
def test_two_gang_federation_smoke():
    """The federation dryrun in quick mode: 2 gangs × 2 processes on
    CPU, serving bit-identical to the oracle across gangs, surviving a
    follower SIGKILL (bounded unavailability, re-form to ACTIVE) and a
    leader SIGKILL (replica failover, replicated-solo restart)."""
    import jax

    if not hasattr(jax, "distributed"):
        pytest.skip("jax.distributed unavailable")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "dryrun_federation.py"), "--quick"],
        capture_output=True,
        text=True,
        timeout=560,
        env={
            k: v
            for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
        },
    )
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    summary = json.loads(proc.stdout[proc.stdout.index('{\n  "what"') :])
    assert summary["ok"] is True
