"""Coalesced Count(chain) dispatches (opt-in via PILOSA_CHAIN_BATCH):
concurrent same-shape chains batch into one tree-count kernel launch,
bit-identical to the CPU roaring path (reference executor.go:704-1000
semantics; the batching itself has no reference analog). The default
serving path dispatches per query — measured faster on tunneled chips
(rationale in executor._execute_count) — and must stay bit-identical
under concurrency too."""

import threading
import time

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import Holder
from pilosa_tpu.executor import Executor


@pytest.fixture()
def executors(tmp_path):
    h = Holder(str(tmp_path / "data"))
    h.open()
    fld = h.create_index("i").create_field("f")
    rng = np.random.default_rng(17)
    rows, cols = [], []
    for shard in range(3):
        base = shard * SHARD_WIDTH
        # draw each row's columns from a small shared pool so chains of
        # Intersect/Union/Difference produce nonzero counts (a bug that
        # zeroes everything must not pass vacuously)
        pool = rng.integers(0, SHARD_WIDTH, size=500)
        for r in range(12):
            k = int(rng.integers(120, 260))
            rows += [r] * k
            cols += (base + rng.choice(pool, size=k)).tolist()
    fld.import_bits(rows, cols)
    cpu = Executor(h, device_policy="never")
    # dispatch engine off: these tests pin the legacy thread-coalescing
    # path, where each caller thread enqueues behind the chain scorer's
    # dispatcher flag. With the engine on, cross-request combining
    # happens at the wave layer instead (covered by tests/test_dispatch.py).
    dev = Executor(h, device_policy="always", dispatch_enabled=False)
    dev._chain_batch = True  # coalescing is opt-in (see _make_chain_scorer)
    yield cpu, dev
    h.close()


def _chain(a, b, c, d):
    return (
        f"Count(Intersect(Union(Row(f={a}), Row(f={b})),"
        f" Union(Row(f={c}), Row(f={d}))))"
    )


def test_sequential_chains_bit_identical(executors):
    cpu, dev = executors
    for r in range(4):
        q = _chain(r, r + 1, r + 2, r + 3)
        assert cpu.execute("i", q) == dev.execute("i", q), q
    # different tree shapes take different jits and stay correct
    q2 = "Count(Difference(Union(Row(f=0), Row(f=1), Row(f=2)), Row(f=3)))"
    assert cpu.execute("i", q2) == dev.execute("i", q2)


def test_concurrent_same_shape_chains_coalesce(executors):
    """Deterministic coalescing (same technique as the TopN scorer
    test): hold the dispatcher flag so every caller enqueues, then run
    one drain round — all queries must land in ONE batched launch and
    every result must equal the CPU oracle."""
    cpu, dev = executors
    queries = [_chain(r, (r + 3) % 12, (r + 5) % 12, (r + 7) % 12) for r in range(6)]
    want = [cpu.execute("i", q) for q in queries]

    s = dev.chain_scorer
    with s._lock:
        s._dispatching = True  # this thread plays the leader
    results = [None] * len(queries)

    def run(i):
        results[i] = dev.execute("i", queries[i])

    threads = [threading.Thread(target=run, args=(i,)) for i in range(len(queries))]
    for t in threads:
        t.start()
    deadline = time.time() + 10
    enqueued = False
    while time.time() < deadline:
        with s._lock:
            if sum(len(e[1]) for e in s._pending.values()) == len(queries):
                enqueued = True
                break
        time.sleep(0.001)
    s._dispatch_loop()
    for t in threads:
        t.join()
    assert enqueued, "callers never enqueued behind the held dispatcher"
    assert results == want
    # same tree shape + same leaf shapes = one key = one coalesced launch
    assert s.dispatches == 1
    assert s.batched_queries == len(queries)


def test_chain_batch_pads_with_repeat(executors):
    """3 coalesced chains pad to pow2 4 by repeating a real source
    (leaves tuples have no zeros_like); pad lane results are never
    assigned, so counts stay exact."""
    from pilosa_tpu.executor.batcher import _Slot
    from pilosa_tpu.pql import parse

    cpu, dev = executors
    queries = [_chain(r, r + 2, r + 4, r + 6) for r in range(3)]
    want = [cpu.execute("i", q) for q in queries]

    slots, tree_ref = [], None
    for q in queries:
        call = parse(q).calls[0].children[0]
        leaves, tree = dev._tree_leaves("i", call, [0, 1, 2])
        tree_ref = tree
        slots.append(_Slot(tuple(leaves)))
    dev.chain_scorer._fill(slots, tree_ref)
    got = [[int(np.asarray(s.result).reshape(-1)[0])] for s in slots]
    assert got == want
    assert any(w[0] > 0 for w in want)  # not vacuously zero


def test_default_direct_path_concurrent_identical(executors):
    """With the gate OFF (shipped default), concurrent chains dispatch
    per-query and stay bit-identical to the CPU oracle."""
    cpu, dev = executors
    dev._chain_batch = False
    queries = [_chain(r, r + 1, r + 4, r + 6) for r in range(6)]
    want = [cpu.execute("i", q) for q in queries]
    results = [None] * len(queries)

    def run(i):
        results[i] = dev.execute("i", queries[i])

    threads = [threading.Thread(target=run, args=(i,)) for i in range(len(queries))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == want
    assert dev.chain_scorer.dispatches == 0  # scorer never engaged


def test_distinct_shapes_do_not_mix(executors):
    """Two different tree structures queried concurrently resolve under
    different keys — each gets its own launch and the right answer."""
    cpu, dev = executors
    qa = _chain(0, 1, 2, 3)
    qb = "Count(Union(Intersect(Row(f=0), Row(f=1)), Row(f=4)))"
    want = {qa: cpu.execute("i", qa), qb: cpu.execute("i", qb)}
    results = {}

    def run(q):
        results[q] = dev.execute("i", q)

    threads = [threading.Thread(target=run, args=(q,)) for q in (qa, qb) * 3]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == want
