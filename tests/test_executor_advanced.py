"""Advanced executor coverage: Tanimoto TopN, attribute filters, bulk
attrs, multi-call queries, key translation edge cases (mirrors the long
tail of reference executor_test.go)."""

import numpy as np
import pytest

from pilosa_tpu.core import FieldOptions, Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.utils.attrstore import AttrStore
from pilosa_tpu.utils.translate import TranslateStore


@pytest.fixture()
def holder():
    h = Holder(new_attr_store=lambda path: AttrStore(None))
    h.open()
    return h


def execu(holder, policy="never", translate=False):
    return Executor(
        holder,
        device_policy=policy,
        translate_store=TranslateStore() if translate else None,
    )


class TestTanimoto:
    def setup_fp(self, h):
        """Chemical-similarity style fingerprints (reference
        docs/examples.md Tanimoto workload)."""
        idx = h.create_index("mol")
        f = idx.create_field("fp")
        # molecule rows with fingerprint bits
        fps = {
            1: {1, 2, 3, 4, 5, 6},
            2: {1, 2, 3, 4},
            3: {1, 2, 9, 10},
            4: {20, 21},
        }
        rows, cols = [], []
        for row, bits in fps.items():
            for b in bits:
                rows.append(row)
                cols.append(b)
        f.import_bits(rows, cols)
        return fps

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_tanimoto_threshold(self, holder, policy):
        fps = self.setup_fp(holder)
        e = execu(holder, policy)
        # src = molecule 2's fingerprint {1,2,3,4}
        (res,) = e.execute("mol", "TopN(fp, Row(fp=2), tanimotoThreshold=50)")
        # tanimoto(row1) = ceil(4*100/(6+4-4)) = 67 > 50 ✓
        # tanimoto(row2) = 100 > 50 ✓
        # tanimoto(row3) = ceil(2*100/(4+4-2)) = 34 ≤ 50 ✗
        ids = {p["id"] for p in res}
        assert ids == {1, 2}

    def test_tanimoto_invalid(self, holder):
        self.setup_fp(holder)
        e = execu(holder)
        with pytest.raises(ValueError):
            e.execute("mol", "TopN(fp, Row(fp=2), tanimotoThreshold=150)")


class TestAttrFilters:
    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_topn_attr_filter(self, holder, policy):
        idx = holder.create_index("i")
        f = idx.create_field("f")
        for col in range(5):
            f.set_bit(1, col)
        for col in range(3):
            f.set_bit(2, col)
        for col in range(2):
            f.set_bit(3, col)
        f.row_attr_store.set_attrs(1, {"category": "a"})
        f.row_attr_store.set_attrs(2, {"category": "b"})
        f.row_attr_store.set_attrs(3, {"category": "a"})
        f.view("standard").fragments[0].cache.recalculate()
        e = execu(holder, policy)
        (res,) = e.execute("i", 'TopN(f, n=5, attrName="category", attrValues=["a"])')
        assert res == [{"id": 1, "count": 5}, {"id": 3, "count": 2}]

    def test_row_attrs_on_row_query(self, holder):
        idx = holder.create_index("i")
        f = idx.create_field("f")
        e = execu(holder)
        e.execute("i", 'Set(1, f=10)SetRowAttrs(f, 10, foo="bar", n=5)')
        (row,) = e.execute("i", "Row(f=10)")
        assert row.attrs == {"foo": "bar", "n": 5}
        # attr deletion via null
        e.execute("i", "SetRowAttrs(f, 10, foo=null)")
        (row,) = e.execute("i", "Row(f=10)")
        assert row.attrs == {"n": 5}

    def test_column_attrs(self, holder):
        idx = holder.create_index("i")
        idx.create_field("f")
        e = execu(holder)
        e.execute("i", 'SetColumnAttrs(7, name="acme", active=true)')
        assert idx.column_attrs.attrs(7) == {"name": "acme", "active": True}


class TestKeyTranslation:
    def test_string_col_requires_keys(self, holder):
        holder.create_index("i").create_field("f")
        e = execu(holder, translate=True)
        with pytest.raises(ValueError):
            e.execute("i", 'Set("alice", f=1)')

    def test_keys_workflow(self, holder):
        idx = holder.create_index("u", keys=True)
        idx.create_field("l", FieldOptions(keys=True))
        e = execu(holder, translate=True)
        e.execute("u", 'Set("alice", l="pizza")')
        e.execute("u", 'Set("bob", l="pizza")')
        e.execute("u", 'Set("alice", l="sushi")')
        (row,) = e.execute("u", 'Row(l="pizza")')
        assert row.keys == ["alice", "bob"]
        (cnt,) = e.execute("u", 'Count(Row(l="sushi"))')
        assert cnt == 1


class TestMiscCalls:
    def test_multi_call_query(self, holder):
        holder.create_index("i").create_field("f")
        e = execu(holder)
        results = e.execute("i", "Set(1, f=1)Set(2, f=1)Count(Row(f=1))Clear(1, f=1)Count(Row(f=1))")
        assert results == [True, True, 2, True, 1]

    def test_max_writes_per_request(self, holder):
        holder.create_index("i").create_field("f")
        e = execu(holder)
        e.max_writes_per_request = 2
        with pytest.raises(ValueError):
            e.execute("i", "Set(1, f=1)Set(2, f=1)Set(3, f=1)")

    def test_count_requires_single_child(self, holder):
        holder.create_index("i").create_field("f")
        e = execu(holder)
        with pytest.raises(ValueError):
            e.execute("i", "Count()")
        with pytest.raises(ValueError):
            e.execute("i", "Count(Row(f=1), Row(f=2))")

    def test_setvalue_multiple_fields(self, holder):
        idx = holder.create_index("i")
        idx.create_field("a", FieldOptions(type="int", min=0, max=100))
        idx.create_field("b", FieldOptions(type="int", min=0, max=100))
        e = execu(holder)
        e.execute("i", "SetValue(col=1, a=10, b=20)")
        assert idx.field("a").value(1) == (10, True)
        assert idx.field("b").value(1) == (20, True)

    def test_unknown_call(self, holder):
        holder.create_index("i").create_field("f")
        e = execu(holder)
        with pytest.raises(ValueError):
            e.execute("i", "Frobnicate(f=1)")


class TestBSIFuzz:
    """Randomized BSI property sweep (mirrors the reference's exhaustive
    fragment BSI tests): negative mins, every comparison operator,
    Between, Sum/Min/Max with and without filters — CPU path is the
    oracle, device path must be bit-identical."""

    def _setup(self, h, seed=31):
        rng = np.random.default_rng(seed)
        idx = h.create_index("bz")
        f = idx.create_field(
            "v", FieldOptions(type="int", min=-1000, max=1000)
        )
        g = idx.create_field("grp")
        n = 3000
        cols = np.arange(n)
        vals = rng.integers(-1000, 1001, size=n)
        f.import_values(cols.tolist(), vals.tolist())
        g.import_bits(rng.integers(0, 4, size=n).tolist(), cols.tolist())
        return cols, vals, rng

    def test_bsi_fuzz_cpu_device_identity(self, holder):
        cols, vals, rng = self._setup(holder)
        cpu = execu(holder, "never")
        dev = execu(holder, "always")
        queries = []
        for _ in range(20):
            t = int(rng.integers(-1100, 1100))
            lo = int(rng.integers(-1100, 0))
            hi = int(rng.integers(0, 1100))
            queries += [
                f"Count(Range(v > {t}))",
                f"Count(Range(v >= {t}))",
                f"Count(Range(v < {t}))",
                f"Count(Range(v <= {t}))",
                f"Count(Range(v == {t}))",
                f"Count(Range(v != {t}))",
                f"Count(Range({lo} < v < {hi}))",
            ]
        queries += [
            "Sum(field=v)",
            "Min(field=v)",
            "Max(field=v)",
            "Sum(Row(grp=1), field=v)",
            "Min(Row(grp=2), field=v)",
            "Max(Row(grp=3), field=v)",
        ]
        for q in queries:
            want = cpu.execute("bz", q)
            got = dev.execute("bz", q)
            if hasattr(want[0], "val"):
                assert (want[0].val, want[0].count) == (got[0].val, got[0].count), q
            else:
                assert want == got, q

    def test_bsi_oracle_against_numpy(self, holder):
        """The CPU path itself against a straight numpy oracle."""
        _, vals, rng = self._setup(holder, seed=32)
        cpu = execu(holder, "never")
        thresholds = [-1000, -1, 0, 1, 137, 999, 1000] + [
            int(t) for t in rng.integers(-1000, 1001, size=5)
        ]
        for t in thresholds:
            assert cpu.execute("bz", f"Count(Range(v > {t}))")[0] == int(
                (vals > t).sum()
            ), t
            assert cpu.execute("bz", f"Count(Range(v == {t}))")[0] == int(
                (vals == t).sum()
            ), t
        s = cpu.execute("bz", "Sum(field=v)")[0]
        assert s.val == int(vals.sum()) and s.count == len(vals)
        assert cpu.execute("bz", "Min(field=v)")[0].val == int(vals.min())
        assert cpu.execute("bz", "Max(field=v)")[0].val == int(vals.max())


class TestReferenceParityTail:
    """Long-tail reference executor_test.go behaviors pinned exactly."""

    def test_old_pql_calls_rejected(self, holder):
        """v0-era call names are unknown calls with the reference's
        exact message (reference TestExecutor_Execute_OldPQL,
        executor_test.go:378-391)."""
        idx = holder.create_index("i")
        idx.create_field("f")
        e = execu(holder)
        e.execute("i", "Set(0, f=1)")
        with pytest.raises(ValueError, match="unknown call: SetBit"):
            e.execute("i", "SetBit(frame=f, row=11, col=1)")

    def test_set_column_attrs_excludes_field(self, holder):
        """SetColumnAttrs stores exactly the given attrs — no stray
        field/column key leaks into the attr map (reference
        TestExecutor_SetColumnAttrs_ExcludeField,
        executor_test.go:1264-1312)."""
        idx = holder.create_index("i")
        idx.column_attrs = AttrStore()
        idx.create_field("f")
        e = execu(holder)
        e.execute("i", "Set(10, f=1)")
        e.execute("i", "SetColumnAttrs(10, foo='bar')")
        assert idx.column_attrs.attrs(10) == {"foo": "bar"}
        e.execute("i", "Set(20, f=10)")
        e.execute("i", "SetColumnAttrs(20, foo='bar')")
        assert idx.column_attrs.attrs(20) == {"foo": "bar"}
