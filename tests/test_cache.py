"""RankCache invalidation semantics (reference cache.go:136-286).

The reference's rankCache.Invalidate() re-sorts whenever its 10 s
debounce window has passed — including on the read-only TopN path
(topBitmapPairs, fragment.go:1004-1044). On an unmodified cache that
re-sort is a semantic no-op; at the 1B/64-shard scale it was measured
as the dominant GIL serialization under concurrent TopN (34 ms per 50k
entry fragment). The dirty flag skips it without changing any output.
"""

from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core.cache import RankCache


def _filled(n=1000):
    c = RankCache(50)
    for i in range(n):
        c.bulk_add(i, n - i)
    c.recalculate()
    return c


class TestInvalidateDirtyFlag:
    def test_clean_invalidate_is_free(self, monkeypatch):
        c = _filled()
        # expired debounce window: the old code would re-sort here
        c._update_time = -1e9
        before = c.rankings
        calls = []
        monkeypatch.setattr(
            cache_mod, "sort_pairs", lambda p: calls.append(1) or sorted(
                p, key=lambda x: (-x[1], x[0])
            )
        )
        c.invalidate()
        assert calls == []  # no re-sort
        assert c.rankings is before  # rankings snapshot untouched

    def test_write_then_invalidate_recalculates(self):
        c = _filled()
        c._update_time = -1e9
        c.add(5000, 99999)
        assert c.rankings[0] == (5000, 99999)

    def test_debounce_still_applies_to_dirty(self):
        c = _filled()
        # recent recalc: a write within the window must NOT re-sort
        # (reference debounce, cache.go:233-241)
        before = c.rankings
        c.bulk_add(6000, 88888)
        c.invalidate()
        assert c.rankings is before
        # ...but the dirtiness persists: after the window the next
        # invalidate picks it up
        c._update_time = -1e9
        c.invalidate()
        assert c.rankings[0] == (6000, 88888)

    def test_remove_marks_dirty(self):
        c = _filled()
        top_id = c.rankings[0][0]
        c.remove(top_id)
        assert all(p[0] != top_id for p in c.rankings)
        c._update_time = -1e9
        c.invalidate()  # rebuild from entries must also exclude it
        assert all(p[0] != top_id for p in c.rankings)

    def test_trim_and_threshold_unchanged(self):
        # reference trim behavior: maxEntries cut + thresholdValue from
        # the first trimmed entry (cache.go:250-270)
        c = RankCache(10)
        for i in range(30):
            c.bulk_add(i, 100 - i)
        c.recalculate()
        assert len(c.rankings) == 10
        assert c.threshold_value == 100 - 10
