"""Fleet-wide distributed tracing + telemetry federation (ISSUE 10):
W3C traceparent propagation, coalesce/dedup span links, gang replay
under the originating trace id, remote-leg span envelopes, the stitch
buffer, the lifecycle event journal, fleet metric aggregation, log
correlation, and the zero-allocation contract for unsampled contexts.

Server-level pieces run against a real in-process server on :0 under
JAX_PLATFORMS=cpu (the tier-1 environment)."""

import io
import json
import os
import threading
import time
import urllib.request
from types import SimpleNamespace

import pytest

from pilosa_tpu.server import Config, Server
from pilosa_tpu.utils import events, logger as logger_mod, metrics, trace


@pytest.fixture()
def server(tmp_path):
    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="expvar",
        device_policy="always",
        device_timeout=0,
    )
    s = Server(cfg)
    s.open()
    yield s
    s.close()


@pytest.fixture(autouse=True)
def _clean_globals():
    """The tracer/journal are process-global; every test starts and
    ends with empty rings and no fleet identity."""
    trace.TRACER.clear()
    events.JOURNAL.clear()
    saved_tags = (dict(trace.TRACER.tags), dict(events.JOURNAL.tags))
    yield
    trace.TRACER.clear()
    events.JOURNAL.clear()
    trace.TRACER.tags, events.JOURNAL.tags = saved_tags
    logger_mod.set_context_provider(None)


def req(server, method, path, body=None, raw=False, headers=None):
    url = server.uri + path
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(url, data=data, method=method)
    for k, v in (headers or {}).items():
        r.add_header(k, v)
    try:
        with urllib.request.urlopen(r) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload or b"{}")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, payload if raw else json.loads(payload or b"{}")


def _ctx(sampled=True):
    return (trace.new_trace_id(), trace.new_span_id(), sampled)


# -- traceparent parse/format -------------------------------------------------


def test_traceparent_roundtrip():
    ctx = _ctx()
    assert trace.parse_traceparent(trace.format_traceparent(ctx)) == ctx
    ctx0 = _ctx(sampled=False)
    hdr = trace.format_traceparent(ctx0)
    assert hdr.endswith("-00")
    assert trace.parse_traceparent(hdr) == ctx0
    # uppercase + whitespace normalize; unknown flag bits keep bit 0
    tid, sid, _ = ctx
    assert trace.parse_traceparent(f"  00-{tid.upper()}-{sid}-03 ") == (
        tid,
        sid,
        True,
    )


@pytest.mark.parametrize(
    "header",
    [
        None,
        "",
        "garbage",
        "00-abc-def-01",  # short ids
        "00-" + "g" * 32 + "-" + "1" * 16 + "-01",  # non-hex
        "ff-" + "1" * 32 + "-" + "2" * 16 + "-01",  # forbidden version
        "00-" + "0" * 32 + "-" + "2" * 16 + "-01",  # zero trace id
        "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # zero span id
        "00-" + "1" * 32 + "-" + "2" * 16 + "-01-extra",
        "00-" + "1" * 32 + "-" + "2" * 16 + "-0x",
    ],
)
def test_traceparent_malformed_is_none(header):
    assert trace.parse_traceparent(header) is None


def test_record_link_point_entry():
    tr = trace.Tracer()
    ctx, target = _ctx(), _ctx()
    trace.record_link("pipeline.coalesce", ctx, target, tracer=tr, cls="interactive")
    (d,) = tr.recent()
    assert d["trace_id"] == ctx[0] and d["parent_id"] == ctx[1]
    assert d["links"] == [{"trace_id": target[0], "span_id": target[1]}]
    assert d["meta"]["cls"] == "interactive"


# -- stitch buffer ------------------------------------------------------------


def test_graft_remote_stitches_and_bounds():
    tr = trace.Tracer()
    with tr.trace("query", force=True, ctx=(_tid := trace.new_trace_id(), "", True)):
        pass
    tr.graft_remote(_tid, [{"name": "multihost.replay", "span_id": "a" * 16}])
    (d,) = tr.recent(trace_id=_tid)
    assert [c["name"] for c in d["children"]] == ["multihost.replay"]
    # the ring entry itself is never mutated
    with tr._mu:
        raw = [e for e in tr._ring if e.get("trace_id") == _tid]
    assert "children" not in raw[0]
    # per-trace span bound
    tr.graft_remote(_tid, [{"name": f"s{i}"} for i in range(200)])
    assert len(tr._stitch[_tid]) <= tr.STITCH_SPANS
    # trace-id bound evicts oldest
    for i in range(tr.STITCH_TRACES + 5):
        tr.graft_remote(trace.new_trace_id(), [{"name": "x"}])
    assert len(tr._stitch) <= tr.STITCH_TRACES
    # empty pushes are no-ops
    tr.graft_remote("", [{"name": "x"}])
    tr.graft_remote(_tid, [])


def test_stitched_never_attaches_entry_to_itself():
    tr = trace.Tracer()
    tid = trace.new_trace_id()
    with tr.trace("multihost.replay", force=True, ctx=(tid, "", True)) as sp:
        pass
    # the leader-rank replay grafts its OWN dict into the local buffer
    tr.graft_remote(tid, [sp.to_dict()])
    (d,) = tr.recent(trace_id=tid)
    assert "children" not in d  # not its own child


def test_recent_filters():
    tr = trace.Tracer()
    tr.tags = {"gang": "g1", "rank": 0}
    tid = trace.new_trace_id()
    with tr.trace("query", force=True, ctx=(tid, "", True)):
        pass
    tr.tags = {}
    with tr.trace("query", force=True):
        time.sleep(0.002)
    assert [d["trace_id"] for d in tr.recent(trace_id=tid)] == [tid]
    assert all(
        (d.get("meta") or {}).get("gang") == "g1" for d in tr.recent(gang="g1")
    )
    assert len(tr.recent(gang="g1")) == 1
    slow = tr.recent(min_ms=1.5)
    assert len(slow) == 1 and slow[0]["trace_id"] != tid
    assert len(tr.recent()) == 2


# -- event journal ------------------------------------------------------------


def test_event_journal_record_snapshot_bounds():
    j = events.EventJournal(ring_size=4)
    j.tags = {"gang": "g1", "rank": 2}
    for i in range(6):
        j.record(events.GANG_TRANSITION, frm="ACTIVE", to="DEGRADED", epoch=i)
    j.record(events.GANG_REFORM, epoch=9)
    snap = j.snapshot()
    assert len(snap) == 4  # ring bounded
    assert [e["seq"] for e in snap] == sorted(e["seq"] for e in snap)
    assert all(e["gang"] == "g1" and e["rank"] == 2 for e in snap)
    reforms = j.snapshot(kind=events.GANG_REFORM)
    assert len(reforms) == 1 and reforms[0]["epoch"] == 9
    last = snap[-1]["seq"]
    assert j.snapshot(since_seq=last) == []
    assert j.snapshot(since_seq=last - 1) == [snap[-1]]


def test_event_journal_stamps_active_trace():
    j = events.EventJournal()
    ctx = _ctx(sampled=False)
    with trace.push_ctx(ctx):
        j.record(events.CLIENT_RETRY_EXHAUSTED, op="query")
    (e,) = j.snapshot()
    assert e["trace_id"] == ctx[0]
    j.clear()
    j.record(events.GANG_DEGRADE, reason="x")
    assert "trace_id" not in j.snapshot()[0]


def test_gang_lifecycle_records_events():
    """degrade() and reform() on a replicated runtime journal the
    DEGRADED -> REFORMING -> ACTIVE story with epochs."""
    from pilosa_tpu.parallel.multihost import MultiHostRuntime

    mh = MultiHostRuntime.replicated(apply_fn=lambda kind, payload: None)
    # replicated boot starts DEGRADED; join a follower to reach ACTIVE
    mh.reform(["http://f:1"], reason="boot join")
    base = mh.epoch
    mh.degrade("follower died")
    mh.reform(["http://f:1"], reason="follower rejoined")
    kinds = [e["kind"] for e in events.snapshot()]
    assert events.GANG_DEGRADE in kinds and events.GANG_REFORM in kinds
    transitions = [
        (e["frm"], e["to"])
        for e in events.snapshot(kind=events.GANG_TRANSITION)
    ]
    assert ("DEGRADED", "REFORMING") in transitions
    assert ("REFORMING", "ACTIVE") in transitions
    reform = events.snapshot(kind=events.GANG_REFORM)[-1]
    assert reform["epoch"] > base


# -- coalesce / dedup span links ---------------------------------------------


def test_pipeline_coalesced_follower_links_leader_trace():
    from pilosa_tpu.server.pipeline import QueryPipeline

    pl = QueryPipeline(workers={"interactive": 1})
    lead_ctx, fol_ctx = _ctx(), _ctx()
    started, release = threading.Event(), threading.Event()

    def leader_thunk():
        started.set()
        release.wait(5)
        return "L"

    out = {}
    t1 = threading.Thread(
        target=lambda: out.setdefault(
            "lead",
            pl.submit("interactive", leader_thunk, signature="sig", trace_ctx=lead_ctx),
        )
    )
    t1.start()
    assert started.wait(5)
    t2 = threading.Thread(
        target=lambda: out.setdefault(
            "fol",
            pl.submit("interactive", lambda: "F", signature="sig", trace_ctx=fol_ctx),
        )
    )
    t2.start()
    try:
        # the follower records its link synchronously at admission
        deadline = time.monotonic() + 5
        while not trace.TRACER.recent(trace_id=fol_ctx[0]):
            assert time.monotonic() < deadline, "coalesce link never recorded"
            time.sleep(0.005)
    finally:
        release.set()
        t1.join(5)
        t2.join(5)
    assert out["fol"] == "L"  # served by the leader's execution
    (d,) = trace.TRACER.recent(trace_id=fol_ctx[0])
    assert d["name"] == metrics.STAGE_PIPELINE_COALESCE
    assert d["links"][0]["trace_id"] == lead_ctx[0]
    assert d["meta"]["leader_traced"] is True
    pl.close()


def test_dispatch_deduped_item_links_executed_item():
    from pilosa_tpu.executor.dispatch import DispatchEngine, _Item
    from pilosa_tpu.pql import parse

    ex = SimpleNamespace(
        _execute=lambda index, q, shards, opt: [42] * len(q.calls),
        stager=SimpleNamespace(),
    )
    eng = DispatchEngine(ex)
    q = parse("Count(Row(f=1))")
    lead_ctx, dup_ctx = _ctx(), _ctx()
    opt = SimpleNamespace(
        remote=False, exclude_row_attrs=False, exclude_columns=False, cache=True
    )
    a = _Item("i", q, None, opt, None, "sig", trace_ctx=lead_ctx)
    b = _Item("i", q, None, opt, None, "sig", trace_ctx=dup_ctx)
    eng._run_group([a, b], wave_no=7)
    assert a.value == [42] and b.value == [42]
    assert eng.dedup_hits == 1
    (d,) = trace.TRACER.recent(trace_id=dup_ctx[0])
    assert d["name"] == metrics.STAGE_DISPATCH_DEDUP
    assert d["links"][0]["trace_id"] == lead_ctx[0]
    assert d["meta"]["wave"] == 7 and d["meta"]["signature"] == "sig"
    # the executed item records no link entry
    assert trace.TRACER.recent(trace_id=lead_ctx[0]) == []


# -- gang replay --------------------------------------------------------------


def _stub_gang_server(rank=1, seen=None):
    def execute(index, query, shards, opt):
        if seen is not None:
            seen.append((trace.current_ctx(), trace.current()))
        return [7]

    return SimpleNamespace(
        executor=SimpleNamespace(execute=execute),
        multihost=None,
        _mh_rank=rank,
        gang_epoch=3,
        config=SimpleNamespace(federation_rejoin=""),
        client_ssl_context=lambda: None,
    )


def test_gang_replay_runs_under_originating_trace_id():
    from pilosa_tpu.parallel.multihost import KIND_QUERY, make_apply_fn

    seen = []
    apply = make_apply_fn(_stub_gang_server(rank=1, seen=seen))
    ctx = _ctx()
    out = apply(
        KIND_QUERY,
        {
            "index": "i",
            "query": "Count(Row(f=1))",
            "shards": None,
            "plan": "p",
            "opt": {},
            "trace": trace.format_traceparent(ctx),
        },
    )
    assert out == [7]
    # the replay executed inside a span of the ORIGINATING trace
    (exec_ctx, exec_span) = seen[0]
    assert exec_ctx[0] == ctx[0] and exec_span is not None
    (d,) = trace.TRACER.recent(trace_id=ctx[0])
    assert d["name"] == metrics.STAGE_MH_REPLAY
    assert d["parent_id"] == ctx[1]
    assert d["meta"]["rank"] == 1 and d["meta"]["epoch"] == 3
    assert d["meta"]["pid"] == os.getpid()
    # rank != leader with no leader URI known: shipped into the local
    # stitch buffer as the best-effort fallback target is empty
    assert ctx[0] in trace.TRACER._stitch


def test_gang_replay_unsampled_allocates_no_spans():
    from pilosa_tpu.parallel.multihost import KIND_QUERY, make_apply_fn

    seen = []
    apply = make_apply_fn(_stub_gang_server(seen=seen))
    ctx = _ctx(sampled=False)
    before = trace.span_count()
    apply(
        KIND_QUERY,
        {
            "index": "i",
            "query": "Count(Row(f=1))",
            "shards": None,
            "opt": {},
            "trace": trace.format_traceparent(ctx),
        },
    )
    assert trace.span_count() == before
    # ...but the bare context still propagated to the execution
    exec_ctx, exec_span = seen[0]
    assert exec_ctx == ctx and exec_span is None
    assert trace.TRACER.recent() == []


# -- fleet collector ----------------------------------------------------------


def test_fleet_collector_local_and_render():
    metrics.count("executor.calls", call="Count")
    srv = SimpleNamespace(
        uri="http://a:1", _expvar=None, cluster=None, client_ssl_context=lambda: None
    )
    from pilosa_tpu.server.fleet import FleetCollector

    fleet = FleetCollector(srv)
    pairs = fleet.collect()
    assert [label for label, _ in pairs] == ["http://a:1"]
    assert any(k.startswith("executor.calls") for k in pairs[0][1])
    text = metrics.render_prometheus(
        registry=metrics.Registry(), instances=pairs
    )
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        assert 'instance="http://a:1"' in line, line
    dbg = fleet.debug()
    assert dbg["self"] == "http://a:1" and dbg["members"] == []


def test_fleet_collector_member_pull_failure_is_isolated():
    srv = SimpleNamespace(
        uri="http://a:1", _expvar=None, cluster=None, client_ssl_context=lambda: None
    )
    from pilosa_tpu.server.fleet import FleetCollector

    fleet = FleetCollector(srv)
    # unreachable member: nothing listens on this port
    fleet.register("http://127.0.0.1:1", rank=1, gang="g")
    fleet._client = SimpleNamespace(
        fleet_snapshots=lambda uri: (_ for _ in ()).throw(OSError("down"))
    )
    pairs = fleet.collect()
    assert [label for label, _ in pairs] == ["http://a:1"]  # scrape survived
    assert fleet.debug()["pulls"]["http://127.0.0.1:1"]["ok"] is False
    snap = metrics.snapshot()
    assert any(
        k.startswith(metrics.FLEET_SCRAPES) and "error" in k for k in snap
    )


# -- log correlation ----------------------------------------------------------


def test_logger_emits_trace_and_gang_context():
    from pilosa_tpu.utils.logger import StandardLogger

    buf = io.StringIO()
    lg = StandardLogger(stream=buf)
    lg.printf("plain")
    assert "[" not in buf.getvalue()
    logger_mod.set_context_provider(lambda: {"gang": "g1", "rank": 2, "epoch": 5})
    tr = trace.Tracer()
    with tr.trace("query", force=True):
        lg.printf("inside span")
    out = buf.getvalue().splitlines()[-1]
    assert "trace=" in out and "gang=g1" in out
    assert "rank=2" in out and "epoch=5" in out
    # provider alone (no active span) still correlates gang context
    lg.printf("no span")
    out = buf.getvalue().splitlines()[-1]
    assert "trace=" not in out and "gang=g1" in out
    # a raising provider never breaks logging
    logger_mod.set_context_provider(lambda: 1 / 0)
    lg.printf("still works")
    assert "still works" in buf.getvalue()


# -- server-level: ingress, debug surfaces, fleet scrape ----------------------


def _seed(server, index="fo"):
    req(server, "POST", f"/index/{index}", {})
    req(server, "POST", f"/index/{index}/field/f", {})
    req(server, "POST", f"/index/{index}/query", b"Set(1, f=1)")


def test_ingress_adopts_sampled_traceparent(server):
    _seed(server)
    ctx = _ctx()
    st, body = req(
        server,
        "POST",
        "/index/fo/query",
        b"Count(Row(f=1))",
        headers={"traceparent": trace.format_traceparent(ctx)},
    )
    assert st == 200 and body["results"] == [1]
    st, body = req(server, "GET", f"/debug/traces?trace_id={ctx[0]}")
    assert st == 200 and len(body["traces"]) == 1
    d = body["traces"][0]
    assert d["trace_id"] == ctx[0] and d["parent_id"] == ctx[1]
    assert d["name"] == metrics.STAGE_QUERY
    # other filters reach the same entry
    st, body = req(server, "GET", "/debug/traces?min_ms=0")
    assert st == 200 and body["traces"]
    st, body = req(server, "GET", f"/debug/traces?trace_id={'f' * 32}")
    assert st == 200 and body["traces"] == []
    st, _ = req(server, "GET", "/debug/traces?min_ms=bogus")
    assert st == 400


def test_ingress_unsampled_traceparent_allocates_no_spans(server):
    _seed(server, index="uns")
    # warm so lazy pools/jits don't muddy the probe
    req(server, "POST", "/index/uns/query", b"Count(Row(f=1))")
    ctx = _ctx(sampled=False)
    before = trace.span_count()
    st, body = req(
        server,
        "POST",
        "/index/uns/query",
        b"Count(Row(f=1))",
        headers={"traceparent": trace.format_traceparent(ctx)},
    )
    assert st == 200 and body["results"] == [1]
    assert trace.span_count() == before
    # malformed headers are ignored, never an error
    st, body = req(
        server,
        "POST",
        "/index/uns/query",
        b"Count(Row(f=1))",
        headers={"traceparent": "not-a-traceparent"},
    )
    assert st == 200 and body["results"] == [1]


def test_remote_query_returns_span_envelope(server):
    _seed(server, index="env")
    ctx = _ctx()
    resp = server.api.query(
        "env", "Count(Row(f=1))", remote=True, trace_ctx=ctx
    )
    assert resp["results"] == [1]
    (d,) = resp["spans"]
    assert d["trace_id"] == ctx[0] and d["parent_id"] == ctx[1]
    # unsampled remote legs carry no envelope
    resp = server.api.query(
        "env", "Count(Row(f=1))", remote=True, trace_ctx=_ctx(sampled=False)
    )
    assert "spans" not in resp


def test_trace_push_endpoint_feeds_stitch_buffer(server):
    tid = trace.new_trace_id()
    st, body = req(
        server,
        "POST",
        "/internal/trace/push",
        {"trace_id": tid, "spans": [{"name": "multihost.replay", "meta": {"rank": 1}}]},
    )
    assert st == 200
    assert tid in trace.TRACER._stitch
    snap = metrics.snapshot()
    assert any(
        k.startswith(metrics.TRACE_REMOTE_SPANS) and "push" in k for k in snap
    )
    st, _ = req(server, "POST", "/internal/trace/push", {"spans": []})
    assert st == 400  # trace_id required


def test_debug_events_endpoint_and_cli_filters(server):
    events.record(events.GANG_DEGRADE, reason="test", epoch=1)
    events.record(events.GANG_REFORM, reason="test", epoch=2)
    st, body = req(server, "GET", "/debug/events")
    assert st == 200
    kinds = [e["kind"] for e in body["events"]]
    assert events.GANG_DEGRADE in kinds and events.GANG_REFORM in kinds
    st, body = req(server, "GET", f"/debug/events?kind={events.GANG_REFORM}")
    assert st == 200
    assert all(e["kind"] == events.GANG_REFORM for e in body["events"])
    assert body["events"]
    last = body["events"][-1]["seq"]
    st, body = req(server, "GET", f"/debug/events?since={last}")
    assert st == 200 and body["events"] == []
    st, _ = req(server, "GET", "/debug/events?since=bogus")
    assert st == 400


def test_build_info_and_fleet_scrape(server):
    st, raw = req(server, "GET", "/metrics", raw=True)
    assert st == 200
    text = raw.decode()
    assert "pilosa_build_info{" in text
    (line,) = [
        l for l in text.splitlines() if l.startswith("pilosa_build_info{")
    ]
    assert 'rank="0"' in line and 'leader="true"' in line
    assert f'pid="{os.getpid()}"' in line
    # fleet aggregation on a standalone server: one instance (itself),
    # every sample instance-labeled
    st, raw = req(server, "GET", "/metrics?fleet=true", raw=True)
    assert st == 200
    for l in raw.decode().splitlines():
        if l.startswith("#") or not l:
            continue
        assert f'instance="{server.uri}"' in l, l
    st, body = req(server, "GET", "/debug/fleet")
    assert st == 200 and body["enabled"] is True
    assert body["self"] == server.uri


def test_fleet_register_and_snapshots_endpoints(server):
    st, body = req(
        server,
        "POST",
        "/internal/fleet/register",
        {"uri": "http://127.0.0.1:1", "rank": 1, "gang": "g"},
    )
    assert st == 200 and body["registered"] is True
    members = server.fleet.members()
    assert members and members[0]["uri"] == "http://127.0.0.1:1"
    assert members[0]["rank"] == 1 and members[0]["gang"] == "g"
    st, _ = req(server, "POST", "/internal/fleet/register", {})
    assert st == 400  # uri required
    # drop the dead member so the snapshot pull doesn't wait on it
    server.fleet._members.clear()
    st, body = req(server, "GET", "/internal/fleet/snapshots")
    assert st == 200
    (pair,) = body["snapshots"]
    assert pair[0] == server.uri and isinstance(pair[1], dict)
