"""Invariant checker tests — static rules (good/bad fixture per rule,
including the planted PR 6 ``import_values`` gang-bypass shape), the
dynamic lock-order detector (AB/BA cycle, Condition integration,
self-deadlock), suppression handling, the repo-clean CI gate, and the
OrderedLock overhead bound on the executor-style hot path."""

import threading
import time

import pytest

from pilosa_tpu.analysis import lint
from pilosa_tpu.analysis.lint import check_source
from pilosa_tpu.analysis.locks import (
    LockGraph,
    LockOrderError,
    OrderedLock,
    held_locks,
)


def rules_of(findings):
    return sorted({f.rule for f in findings})


def run_rule(src, rule, relpath="pilosa_tpu/somemod.py", **kw):
    return [
        f
        for f in check_source(src, relpath, **kw)
        if f.rule == rule
    ]


# -- lock-discipline ---------------------------------------------------------


class TestLockDiscipline:
    def test_blocking_result_under_lock_flagged(self):
        src = (
            "class C:\n"
            "    def run(self):\n"
            "        with self._mu:\n"
            "            x = fut.result()\n"
            "        return x\n"
        )
        fs = run_rule(src, "lock-discipline")
        assert len(fs) == 1 and fs[0].line == 4
        assert ".result()" in fs[0].message

    def test_block_until_ready_and_sleep_flagged(self):
        src = (
            "class C:\n"
            "    def run(self):\n"
            "        with self._mu:\n"
            "            arr.block_until_ready()\n"
            "            time.sleep(1)\n"
        )
        fs = run_rule(src, "lock-discipline")
        assert len(fs) == 2

    def test_result_outside_lock_clean(self):
        src = (
            "class C:\n"
            "    def run(self):\n"
            "        with self._mu:\n"
            "            fut = self._q.popleft()\n"
            "        return fut.result()\n"
        )
        assert run_rule(src, "lock-discipline") == []

    def test_condition_wait_not_flagged(self):
        # Condition.wait releases the lock — the one legal block-in-lock
        src = (
            "class C:\n"
            "    def run(self):\n"
            "        with self._mu:\n"
            "            while not self._done:\n"
            "                self._cond.wait(timeout=0.05)\n"
        )
        assert run_rule(src, "lock-discipline") == []

    def test_event_wait_under_lock_flagged(self):
        src = (
            "class C:\n"
            "    def run(self):\n"
            "        with self._mu:\n"
            "            self._ready_event.wait()\n"
        )
        assert len(run_rule(src, "lock-discipline")) == 1

    def test_self_deadlock_shape_flagged(self):
        # the pipeline.close() bug: a method that re-acquires self._mu
        # called from inside `with self._mu:`
        src = (
            "class P:\n"
            "    def _finish(self, e):\n"
            "        with self._mu:\n"
            "            self._inflight.pop(e, None)\n"
            "    def close(self):\n"
            "        with self._mu:\n"
            "            for e in self._q:\n"
            "                self._finish(e)\n"
        )
        fs = run_rule(src, "lock-discipline")
        assert len(fs) == 1 and "self-deadlock" in fs[0].message
        assert fs[0].line == 8

    def test_self_call_on_reentrant_lock_clean(self):
        src = (
            "class P:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.RLock()\n"
            "    def _finish(self, e):\n"
            "        with self._mu:\n"
            "            pass\n"
            "    def close(self):\n"
            "        with self._mu:\n"
            "            self._finish(1)\n"
        )
        assert run_rule(src, "lock-discipline") == []

    def test_nested_function_body_not_scanned(self):
        # a closure defined under the lock runs later, off-lock
        src = (
            "class C:\n"
            "    def run(self):\n"
            "        with self._mu:\n"
            "            def thunk():\n"
            "                return fut.result()\n"
            "            self._q.append(thunk)\n"
        )
        assert run_rule(src, "lock-discipline") == []


# -- lock-wrapper ------------------------------------------------------------


class TestLockWrapper:
    def test_module_level_bare_lock_flagged(self):
        src = "import threading\n_mu = threading.Lock()\n"
        fs = run_rule(src, "lock-wrapper")
        assert len(fs) == 1 and "module-level" in fs[0].message

    def test_instance_lock_in_uninstrumented_module_clean(self):
        src = (
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
        )
        assert run_rule(src, "lock-wrapper", relpath="pilosa_tpu/core/x.py") == []

    def test_instance_lock_in_instrumented_module_flagged(self):
        src = (
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
        )
        fs = run_rule(
            src, "lock-wrapper", relpath="pilosa_tpu/server/pipeline.py"
        )
        assert len(fs) == 1

    def test_orderedlock_clean_everywhere(self):
        src = (
            "from pilosa_tpu.analysis.locks import OrderedLock\n"
            "_mu = OrderedLock('mod.mu')\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = OrderedLock('c.mu')\n"
        )
        assert (
            run_rule(src, "lock-wrapper", relpath="pilosa_tpu/server/pipeline.py")
            == []
        )

    def test_bare_condition_in_instrumented_module_flagged(self):
        src = (
            "class C:\n"
            "    def __init__(self):\n"
            "        self._cond = threading.Condition()\n"
        )
        fs = run_rule(
            src, "lock-wrapper", relpath="pilosa_tpu/executor/dispatch.py"
        )
        assert len(fs) == 1 and "Condition" in fs[0].message


# -- gang-routing (the planted PR 6 bug shape) -------------------------------

# the exact shape PR 6 shipped with: the owner-local write leg inside
# the shard_nodes() routing loop calling the fragment mutator directly
# instead of the *_local gang-replicating entry point — followers
# missed the replay and the next gloo collective diverged
PR6_IMPORT_VALUES_BUG = """
class API:
    def import_values(self, index, field, shard, cols, vals):
        for node in self.cluster.shard_nodes(index, shard):
            if node.id == self.cluster.node_id:
                f = self._field(index, field)
                f.import_values(cols, vals)
            else:
                self.client.import_values(node, index, field, cols, vals)
"""

PR6_FIXED = """
class API:
    def import_values(self, index, field, shard, cols, vals):
        for node in self.cluster.shard_nodes(index, shard):
            if node.id == self.cluster.node_id:
                self.import_values_local(index, field, cols, vals)
            else:
                self.client.import_values(node, index, field, cols, vals)
"""


class TestGangRouting:
    def test_planted_pr6_bug_detected(self):
        fs = run_rule(PR6_IMPORT_VALUES_BUG, "gang-routing")
        assert len(fs) == 1
        assert "gang replay" in fs[0].message
        assert "import_values_local" in fs[0].message

    def test_fixed_routing_clean(self):
        assert run_rule(PR6_FIXED, "gang-routing") == []

    def test_client_leg_not_flagged(self):
        # the remote leg goes through the internal HTTP client — fine
        fs = run_rule(PR6_IMPORT_VALUES_BUG, "gang-routing")
        assert all("client" not in f.message.split("(")[0] for f in fs)
        assert len(fs) == 1  # only the owner leg

    def test_mutator_outside_routing_loop_clean(self):
        src = (
            "def replay(frag, cols, vals):\n"
            "    frag.import_values(cols, vals)\n"
        )
        assert run_rule(src, "gang-routing") == []

    def test_other_mutators_flagged_too(self):
        src = (
            "class API:\n"
            "    def set(self, index, shard, row, col):\n"
            "        for node in self.cluster.shard_nodes(index, shard):\n"
            "            frag = self._frag(index, shard)\n"
            "            frag.set_bit(row, col)\n"
        )
        fs = run_rule(src, "gang-routing")
        assert len(fs) == 1 and "set_bit" in fs[0].message


# -- dispatch-bypass ---------------------------------------------------------


class TestDispatchBypass:
    def test_external_direct_execute_flagged(self):
        src = (
            "def fast_path(executor, index, q):\n"
            "    return executor._execute(index, q, None, None)\n"
        )
        fs = run_rule(src, "dispatch-bypass", relpath="pilosa_tpu/server/x.py")
        assert len(fs) == 1 and "_engine_eligible" in fs[0].message or (
            "eligibility" in fs[0].message
        )

    def test_whitelisted_modules_clean(self):
        src = (
            "def _run(self, item):\n"
            "    return self.executor._execute(item.index, item.q, None, None)\n"
        )
        assert (
            run_rule(
                src, "dispatch-bypass", relpath="pilosa_tpu/executor/dispatch.py"
            )
            == []
        )

    def test_executor_entry_point_without_predicate_flagged(self):
        src = (
            "class Executor:\n"
            "    def execute_fast(self, index, q):\n"
            "        return self._execute(index, q, None, None)\n"
        )
        fs = [
            f
            for f in check_source(
                src, "fixture_exec.py", fixture_role="executor"
            )
            if f.rule == "dispatch-bypass"
        ]
        assert len(fs) == 1 and "execute_fast" in fs[0].message

    def test_executor_entry_point_with_predicate_clean(self):
        src = (
            "class Executor:\n"
            "    def execute_fast(self, index, q, opt):\n"
            "        engine = self.dispatch_engine\n"
            "        if engine is not None and self._engine_eligible(opt):\n"
            "            return engine.submit(index, q, opt).result()\n"
            "        return self._execute(index, q, opt, None)\n"
        )
        fs = [
            f
            for f in check_source(
                src, "fixture_exec.py", fixture_role="executor"
            )
            if f.rule == "dispatch-bypass"
        ]
        assert fs == []


# -- jit-purity --------------------------------------------------------------


class TestJitPurity:
    def test_wall_clock_in_jit_flagged(self):
        src = (
            "import jax, time\n"
            "@jax.jit\n"
            "def k(x):\n"
            "    t = time.time()\n"
            "    return x + t\n"
        )
        fs = run_rule(src, "jit-purity")
        assert len(fs) == 1 and "wall-clock" in fs[0].message

    def test_partial_jit_detected(self):
        src = (
            "import functools, jax\n"
            "@functools.partial(jax.jit, donate_argnums=0)\n"
            "def k(x):\n"
            "    print(x)\n"
            "    return x\n"
        )
        fs = run_rule(src, "jit-purity")
        assert len(fs) == 1

    def test_host_rng_flagged_jax_random_ok(self):
        bad = (
            "@jax.jit\n"
            "def k(x):\n"
            "    return x + np.random.rand()\n"
        )
        good = (
            "@jax.jit\n"
            "def k(x, key):\n"
            "    return x + jax.random.uniform(key)\n"
        )
        assert len(run_rule(bad, "jit-purity")) == 1
        assert run_rule(good, "jit-purity") == []

    def test_metrics_and_locks_flagged(self):
        src = (
            "@jax.jit\n"
            "def k(x):\n"
            "    metrics.count('executor.calls')\n"
            "    with _mu:\n"
            "        pass\n"
            "    return x\n"
        )
        fs = run_rule(src, "jit-purity")
        assert len(fs) == 2

    def test_unjitted_function_clean(self):
        src = "def k(x):\n    return time.time()\n"
        assert run_rule(src, "jit-purity") == []

    def test_expansion_kernels_lint_clean(self):
        """The compressed-upload expansion kernels (ops.packed
        expand_blocks jit scatter, ops.pallas_kernels expand_runs_pallas)
        stay jit-pure — no wall-clock, host RNG, metrics, or locks
        inside the traced bodies."""
        import os

        root = os.path.join(
            os.path.dirname(__file__), "..", "pilosa_tpu", "ops"
        )
        for rel in ("packed.py", "pallas_kernels.py"):
            with open(os.path.join(root, rel)) as fp:
                src = fp.read()
            fs = run_rule(src, "jit-purity", relpath=f"pilosa_tpu/ops/{rel}")
            assert fs == [], "\n".join(f.format() for f in fs)


# -- donation-safety ---------------------------------------------------------


class TestDonationSafety:
    def test_use_after_donation_flagged(self):
        src = (
            "def f(buf):\n"
            "    out = ops.zeros_like_donated(buf)\n"
            "    return buf.sum()\n"
        )
        fs = run_rule(src, "donation-safety")
        assert len(fs) == 1 and fs[0].line == 3

    def test_rebind_after_donation_clean(self):
        src = (
            "def f(buf):\n"
            "    out = ops.zeros_like_donated(buf)\n"
            "    buf = out + 1\n"
            "    return buf.sum()\n"
        )
        assert run_rule(src, "donation-safety") == []

    def test_no_use_after_clean(self):
        src = (
            "def f(buf):\n"
            "    return ops.zeros_like_donated(buf)\n"
        )
        assert run_rule(src, "donation-safety") == []


# -- metrics-sync ------------------------------------------------------------


class TestMetricsSync:
    def test_unregistered_literal_flagged(self):
        src = "metrics.count('no.such.metric', 1)\n"
        fs = run_rule(src, "metrics-sync")
        assert len(fs) == 1 and "no.such.metric" in fs[0].message

    def test_registered_literal_clean(self):
        src = "metrics.count('executor.calls', 1)\n"
        assert run_rule(src, "metrics-sync") == []

    def test_constant_reference_checked(self):
        good = "metrics.gauge(metrics.ANALYSIS_LOCK_CYCLES, 1)\n"
        bad = "metrics.gauge(metrics.NO_SUCH_CONSTANT, 1)\n"
        assert run_rule(good, "metrics-sync") == []
        assert len(run_rule(bad, "metrics-sync")) == 1

    def test_non_metrics_receiver_ignored(self):
        src = "collections.Counter().count('whatever')\nstats.gauge('x', 1)\n"
        assert run_rule(src, "metrics-sync") == []


# -- suppressions ------------------------------------------------------------


class TestSuppressions:
    SRC = (
        "class C:\n"
        "    def run(self):\n"
        "        with self._mu:\n"
        "            x = fut.result()  # check: disable=lock-discipline (bounded: future already done)\n"
    )

    def test_same_line_suppression(self):
        assert run_rule(self.SRC, "lock-discipline") == []

    def test_line_above_suppression(self):
        src = (
            "class C:\n"
            "    def run(self):\n"
            "        with self._mu:\n"
            "            # check: disable=lock-discipline (bounded: future already done)\n"
            "            x = fut.result()\n"
        )
        assert run_rule(src, "lock-discipline") == []

    def test_wrong_rule_does_not_suppress(self):
        src = self.SRC.replace("lock-discipline", "jit-purity")
        assert len(run_rule(src, "lock-discipline")) == 1

    def test_strict_requires_reason(self):
        src = self.SRC.replace(" (bounded: future already done)", "")
        fs = check_source(src, "x.py", strict=True)
        assert any(
            f.rule == "suppression" and "reason" in f.message for f in fs
        )

    def test_strict_flags_unknown_rule(self):
        src = self.SRC.replace("lock-discipline", "no-such-rule")
        fs = check_source(src, "x.py", strict=True)
        assert any(
            f.rule == "suppression" and "unknown rule" in f.message for f in fs
        )
        # and the original finding survives (unknown rule suppresses
        # nothing for lock-discipline)
        assert any(f.rule == "lock-discipline" for f in fs)


# -- the CI gate: checker runs clean on this repo ----------------------------


class TestRepoClean:
    def test_check_exits_zero_on_repo(self):
        findings = lint.check_paths(None, strict=True)
        assert findings == [], "\n".join(f.format() for f in findings)

    def test_cli_check_strict_exits_zero(self, capsys):
        from pilosa_tpu.cli.main import main

        assert main(["check", "--strict"]) == 0
        out = capsys.readouterr().out
        assert "clean" in out

    def test_cli_check_flags_planted_bug(self, tmp_path, capsys):
        bad = tmp_path / "planted.py"
        bad.write_text(PR6_IMPORT_VALUES_BUG)
        from pilosa_tpu.cli.main import main

        assert main(["check", str(bad)]) == 1
        err = capsys.readouterr().err
        assert "gang-routing" in err


# -- dynamic lock-order detection --------------------------------------------


@pytest.fixture()
def fresh_graph():
    """Isolated graph so tests don't pollute the process-global one."""
    g = LockGraph()
    yield g


class TestOrderedLock:
    def test_ab_ba_cycle_raises_under_tests(self, fresh_graph):
        a = OrderedLock("test.A", graph=fresh_graph)
        b = OrderedLock("test.B", graph=fresh_graph)
        with a:
            with b:
                pass
        with b:
            with pytest.raises(LockOrderError) as ei:
                a.acquire()
        assert "test.A" in str(ei.value) and "test.B" in str(ei.value)
        # the cycle is recorded once, canonically
        assert list(fresh_graph.cycles()) == [("test.A", "test.B")]

    def test_consistent_order_never_raises(self, fresh_graph):
        a = OrderedLock("test.A", graph=fresh_graph)
        b = OrderedLock("test.B", graph=fresh_graph)
        for _ in range(100):
            with a:
                with b:
                    pass
        assert fresh_graph.cycles() == {}

    def test_three_lock_cycle_detected(self, fresh_graph):
        a = OrderedLock("t3.A", graph=fresh_graph)
        b = OrderedLock("t3.B", graph=fresh_graph)
        c = OrderedLock("t3.C", graph=fresh_graph)
        with a, b:
            pass
        with b, c:
            pass
        with c:
            with pytest.raises(LockOrderError):
                a.acquire()

    def test_self_deadlock_always_raises(self, fresh_graph):
        a = OrderedLock("test.self", graph=fresh_graph)
        with a:
            with pytest.raises(LockOrderError, match="self-deadlock"):
                a.acquire()
        # and the stack is clean afterwards
        assert held_locks() == ()

    def test_reentrant_lock_reacquire_ok(self, fresh_graph):
        a = OrderedLock("test.re", reentrant=True, graph=fresh_graph)
        with a:
            with a:
                assert a._is_owned()
        assert held_locks() == ()

    def test_nonstrict_counts_instead_of_raising(self, fresh_graph, monkeypatch):
        monkeypatch.setenv("PILOSA_LOCK_STRICT", "0")
        a = OrderedLock("prod.A", graph=fresh_graph)
        b = OrderedLock("prod.B", graph=fresh_graph)
        with a, b:
            pass
        with b:
            with a:  # inversion: recorded, not raised
                pass
        assert list(fresh_graph.cycles()) == [("prod.A", "prod.B")]

    def test_same_name_instances_never_edge(self, fresh_graph):
        # two stagers' locks share a name: nesting across instances is
        # an ownership question, not an ordering one
        a1 = OrderedLock("inst.mu", graph=fresh_graph)
        a2 = OrderedLock("inst.mu", graph=fresh_graph)
        with a1:
            with a2:
                pass
        with a2:
            with a1:
                pass
        assert fresh_graph.edges() == {}

    def test_condition_wait_integration(self, fresh_graph):
        mu = OrderedLock("cond.mu", graph=fresh_graph)
        cond = threading.Condition(mu)
        state = []

        def waiter():
            with cond:
                while not state:
                    cond.wait(timeout=2.0)
                state.append("woke")

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            state.append("go")
            cond.notify_all()
        t.join(timeout=5)
        assert not t.is_alive() and state == ["go", "woke"]
        assert held_locks() == ()

    def test_timeout_and_nonblocking_acquire(self, fresh_graph):
        a = OrderedLock("nb.mu", graph=fresh_graph)
        assert a.acquire(blocking=False)
        # same-thread non-blocking re-acquire: returns False, no raise
        assert a.acquire(blocking=False) is False
        a.release()
        assert held_locks() == ()
        assert not a.locked()

    def test_cross_thread_contention(self, fresh_graph):
        a = OrderedLock("ct.mu", graph=fresh_graph)
        order = []

        def worker(i):
            with a:
                order.append(i)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(8)
        ]
        with a:
            for t in threads:
                t.start()
            time.sleep(0.02)
            order.append("main")
        for t in threads:
            t.join(timeout=5)
        assert order[0] == "main" and len(order) == 9

    def test_gauges_exported_on_cycle(self, fresh_graph, monkeypatch):
        from pilosa_tpu.utils import metrics

        monkeypatch.setenv("PILOSA_LOCK_STRICT", "0")
        a = OrderedLock("g.A", graph=fresh_graph)
        b = OrderedLock("g.B", graph=fresh_graph)
        with a, b:
            pass
        with b, a:
            pass
        snap = metrics.REGISTRY.snapshot()
        assert snap.get(metrics.ANALYSIS_LOCK_CYCLES) == 1
        assert snap.get(metrics.ANALYSIS_LOCK_GRAPH_EDGES, 0) >= 2


class TestMigratedModulesUseOrderedLock:
    def test_five_modules_instrumented(self):
        from pilosa_tpu.executor.dispatch import DispatchEngine  # noqa: F401
        from pilosa_tpu.executor.stager import DeviceStager  # noqa: F401
        from pilosa_tpu.plan.cache import PlanCache
        from pilosa_tpu.server.pipeline import QueryPipeline  # noqa: F401

        pc = PlanCache()
        assert isinstance(pc._mu, OrderedLock)
        # names are lock classes: check each migrated module constructs
        # its locks with the expected class names
        import importlib
        import inspect

        for mod, names in [
            ("pilosa_tpu.executor.dispatch", ["dispatch.mu"]),
            ("pilosa_tpu.server.pipeline", ["pipeline.mu"]),
            ("pilosa_tpu.executor.stager", ["stager.mu", "stager.ahead_mu"]),
            ("pilosa_tpu.plan.cache", ["plancache.mu"]),
            (
                "pilosa_tpu.parallel.multihost",
                ["multihost.gang.mu", "multihost.loopback.mu"],
            ),
        ]:
            src = inspect.getsource(importlib.import_module(mod))
            for n in names:
                assert f'OrderedLock("{n}")' in src, (mod, n)

    def test_pipeline_close_finishes_queued_signatured_entries(self):
        # regression for the close() self-deadlock: a queued entry WITH
        # a coalescing signature must drain without hanging
        from pilosa_tpu.server.pipeline import QueryPipeline, _Entry

        pl = QueryPipeline.__new__(QueryPipeline)
        pl._mu = OrderedLock("pipeline.mu")
        pl._cond = threading.Condition(pl._mu)
        pl._threads = []
        pl._closing = False
        pl._inflight = {}
        pl.drain_timeout = 0.1

        class _Q:
            def __init__(self, entries):
                self.q = __import__("collections").deque(entries)

        e = _Entry.__new__(_Entry)
        e.signature = ("sig", 1)
        e.event = threading.Event()
        e.result = None
        e.error = None
        pl._inflight[e.signature] = e
        pl._classes = {"read": _Q([e])}

        done = []

        def closer():
            pl.close(drain=0.05)
            done.append(True)

        t = threading.Thread(target=closer)
        t.start()
        t.join(timeout=5)
        assert done, "close() hung on a queued signatured entry"
        assert e.event.is_set() and e.error is not None
        assert pl._inflight == {}


class TestOverhead:
    @staticmethod
    def _per_acquire_delta():
        """Best-of-N per-iteration cost of `with lock: pass` for the
        instrumented wrapper vs bare threading.Lock, in seconds."""
        N = 50_000
        bare = threading.Lock()
        inst = OrderedLock("bench.mu", graph=LockGraph())

        def run(lock):
            t0 = time.perf_counter()
            for _ in range(N):
                with lock:
                    pass
            return time.perf_counter() - t0

        run(bare), run(inst)  # warm both paths
        t_bare = min(run(bare) for _ in range(5))
        t_inst = min(run(inst) for _ in range(5))
        return max(0.0, (t_inst - t_bare) / N)

    def test_wrapper_absolute_cost_bounded(self):
        # the wrapper adds one python call frame + a frozenset probe +
        # a thread-local append/pop; keep its absolute per-acquire cost
        # pinned so a regression (e.g. taking the graph mutex on the
        # fast path) shows up here
        delta = self._per_acquire_delta()
        assert delta < 20e-6, f"per-acquire overhead {delta * 1e6:.1f}us"

    def test_executor_microbench_overhead_under_5_percent(self):
        """The acceptance criterion: OrderedLock instrumentation costs
        <5% of the executor micro-bench. Measured as (per-acquire
        wrapper delta x acquisitions per query) against the measured
        per-query wall time — robust against CI noise, unlike
        subtracting two whole-bench timings."""
        from pilosa_tpu.core import Holder
        from pilosa_tpu.executor import Executor

        h = Holder()
        h.open()
        try:
            idx = h.create_index("i")
            f = idx.create_field("general")
            for row in range(16):
                for col in range(0, 4096, 7):
                    f.set_bit(row, col + row)
            ex = Executor(h, device_policy="never")
            q = "Count(Intersect(Row(general=1), Row(general=2)))"
            ex.execute("i", q)  # warm caches/compile

            acquires = [0]
            orig = OrderedLock.acquire

            def counting(self, blocking=True, timeout=-1):
                acquires[0] += 1
                return orig(self, blocking, timeout)

            OrderedLock.acquire = counting
            try:
                reps = 30
                t0 = time.perf_counter()
                for _ in range(reps):
                    ex.execute("i", q)
                elapsed = time.perf_counter() - t0
            finally:
                OrderedLock.acquire = orig
            n_per_query = acquires[0] / reps
            t_per_query = elapsed / reps
        finally:
            h.close()

        delta = self._per_acquire_delta()
        overhead = (n_per_query * delta) / t_per_query
        assert overhead < 0.05, (
            f"instrumentation {overhead:.2%} of query time "
            f"({n_per_query:.0f} acquires x {delta * 1e6:.1f}us over "
            f"{t_per_query * 1e3:.2f}ms)"
        )
