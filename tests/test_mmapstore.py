"""Mmap-backed lazy storage: equivalence with the eager decoder, op-log
replay into the overlay, mutation + snapshot cycles, O(touched) holder
open, and the vectorised bulk helpers the 1B-row path relies on.

Semantics oracle: the eager dict-store decoder (`Bitmap.unmarshal_binary`),
which itself round-trips the reference Go binary's file format
(reference roaring/roaring.go:543-705).
"""

import io
import mmap
import os
import struct

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.roaring import Bitmap
from pilosa_tpu.roaring.mmapstore import MmapContainers


def _random_bitmap(rng, n=5000, spread=1 << 22):
    vals = np.unique(rng.integers(0, spread, size=n, dtype=np.uint64))
    b = Bitmap.from_sorted(vals)
    # mix of forms: force one dense container and one run container
    dense = np.arange(5000, dtype=np.uint64) + (50 << 16)
    run = np.arange(200, dtype=np.uint64) + (60 << 16)
    b.merge_positions(add=np.concatenate([dense, run]))
    b.optimize()
    return b


def _mmap_roundtrip(b: Bitmap) -> Bitmap:
    data = b.to_bytes()
    return Bitmap.unmarshal_mmap(data)


class TestMmapParse:
    def test_equivalence_with_eager(self):
        rng = np.random.default_rng(7)
        b = _random_bitmap(rng)
        lazy = _mmap_roundtrip(b)
        eager = Bitmap.unmarshal_binary(b.to_bytes())
        assert isinstance(lazy.containers, MmapContainers)
        assert lazy.count() == eager.count()
        assert np.array_equal(lazy.slice_all(), eager.slice_all())
        assert lazy.sorted_keys() == eager.sorted_keys()
        for k in eager.sorted_keys():
            assert np.array_equal(
                lazy.containers[k].positions(), eager.containers[k].positions()
            )

    def test_point_lookups(self):
        rng = np.random.default_rng(8)
        b = _random_bitmap(rng)
        lazy = _mmap_roundtrip(b)
        vals = b.slice_all()
        for v in vals[:: max(1, vals.size // 50)]:
            assert lazy.contains(int(v))
        assert not lazy.contains(int(vals.max()) + 12345)

    def test_oplog_replay(self):
        b = Bitmap()
        b.add_no_oplog(5)
        b.add_no_oplog(1 << 20)
        buf = io.BytesIO()
        b.write_to(buf)
        b2 = Bitmap.unmarshal_binary(buf.getvalue())
        b2.op_writer = buf
        b2.add(99, (2 << 20) + 3)
        b2.remove(5)
        lazy = Bitmap.unmarshal_mmap(buf.getvalue())
        assert lazy.op_n == 3
        assert sorted(lazy) == sorted(b2)

    def test_range_ops_match(self):
        rng = np.random.default_rng(9)
        b = _random_bitmap(rng)
        lazy = _mmap_roundtrip(b)
        for s, e in [(0, 1 << 16), (3 << 16, 55 << 16), (123, (1 << 22) - 7)]:
            assert lazy.count_range(s, e) == b.count_range(s, e)
            assert np.array_equal(lazy.slice_range(s, e), b.slice_range(s, e))
        w = lazy.to_words_range(0, 64 << 16)
        assert np.array_equal(w, b.to_words_range(0, 64 << 16))
        orr = lazy.offset_range(0, 48 << 16, 64 << 16)
        assert np.array_equal(
            orr.slice_all(), b.offset_range(0, 48 << 16, 64 << 16).slice_all()
        )

    def test_truncated_header_rejected(self):
        b = _random_bitmap(np.random.default_rng(1))
        data = b.to_bytes()
        with pytest.raises(ValueError):
            Bitmap.unmarshal_mmap(data[:6])
        bad = bytearray(data)
        bad[0] = 0xFF  # corrupt magic
        with pytest.raises(ValueError):
            Bitmap.unmarshal_mmap(bytes(bad))


class TestMmapMutation:
    def test_overlay_add_remove(self):
        b = _random_bitmap(np.random.default_rng(10))
        lazy = _mmap_roundtrip(b)
        oracle = Bitmap.unmarshal_binary(b.to_bytes())
        for v in [0, 7, (50 << 16) + 1, (99 << 16) + 5, 1 << 30]:
            assert lazy.add_no_oplog(v) == oracle.add_no_oplog(v)
        vals = b.slice_all()
        for v in vals[:20]:
            assert lazy.remove_no_oplog(int(v)) == oracle.remove_no_oplog(int(v))
        assert lazy.count() == oracle.count()
        assert np.array_equal(lazy.slice_all(), oracle.slice_all())

    def test_delete_whole_container(self):
        b = Bitmap()
        b.add_no_oplog(5)
        b.add_no_oplog((3 << 16) + 2)
        lazy = _mmap_roundtrip(b)
        assert lazy.remove_no_oplog(5)
        assert 0 not in lazy.containers
        assert len(lazy.containers) == 1
        assert sorted(lazy) == [(3 << 16) + 2]
        # re-add into a tombstoned key
        assert lazy.add_no_oplog(6)
        assert sorted(lazy) == [6, (3 << 16) + 2]

    def test_merge_positions_matches_union_difference(self):
        rng = np.random.default_rng(11)
        b = _random_bitmap(rng)
        lazy = _mmap_roundtrip(b)
        oracle = Bitmap.unmarshal_binary(b.to_bytes())
        add = np.unique(rng.integers(0, 1 << 22, size=3000, dtype=np.uint64))
        rem = np.unique(rng.integers(0, 1 << 22, size=3000, dtype=np.uint64))
        lazy.merge_positions(add=add, remove=rem)
        want = oracle.difference(Bitmap.from_sorted(rem)).union(
            Bitmap.from_sorted(add)
        )
        assert np.array_equal(lazy.slice_all(), want.slice_all())

    def test_serialize_roundtrip_after_mutation(self):
        b = _random_bitmap(np.random.default_rng(12))
        lazy = _mmap_roundtrip(b)
        lazy.add_no_oplog((200 << 16) + 1)
        lazy.remove_no_oplog(int(b.slice_all()[0]))
        out = io.BytesIO()
        lazy.write_to(out)
        back = Bitmap.unmarshal_binary(out.getvalue())
        assert np.array_equal(back.slice_all(), lazy.slice_all())

    def test_keys_and_counts_with_overlay(self):
        b = _random_bitmap(np.random.default_rng(13))
        lazy = _mmap_roundtrip(b)
        lazy.add_no_oplog((300 << 16) + 4)  # new container
        vals = b.slice_all()
        lazy.remove_no_oplog(int(vals[0]))  # mutate an existing one
        keys, ns = lazy.keys_and_counts()
        assert np.all(np.diff(keys.astype(np.int64)) > 0)
        assert int(ns.sum()) == lazy.count()
        # per-key cardinality agrees with ephemeral decode
        for k, n in zip(keys[:10], ns[:10]):
            assert lazy.containers[int(k)].n == int(n)


class TestFragmentMmap:
    def test_fragment_open_is_mmap_backed(self, tmp_path):
        p = str(tmp_path / "frag")
        f = Fragment(p, "i", "f", "standard", 0)
        f.open()
        f.bulk_import([1, 2, 3], [10, 20, 2 << 16])
        f.close()
        f2 = Fragment(p, "i", "f", "standard", 0)
        f2.open()
        assert f2.storage.is_mmap_backed()
        assert f2.row(1).columns() == [10]
        assert f2.row(3).columns() == [2 << 16]
        f2.close()

    def test_set_bits_then_snapshot_remaps(self, tmp_path):
        p = str(tmp_path / "frag")
        f = Fragment(p, "i", "f", "standard", 0)
        f.open()
        f.bulk_import(list(range(8)), list(range(8)))
        f.close()
        f2 = Fragment(p, "i", "f", "standard", 0)
        f2.open()
        f2.set_bit(100, 55)
        assert len(f2.storage.containers.overlay) > 0
        f2.snapshot()
        # overlay drained into the fresh base
        assert f2.storage.is_mmap_backed()
        assert len(f2.storage.containers.overlay) == 0
        assert f2.bit(100, 55)
        f2.close()
        f3 = Fragment(p, "i", "f", "standard", 0)
        f3.open()
        assert f3.bit(100, 55)
        f3.close()

    def test_row_counts_for(self, tmp_path):
        p = str(tmp_path / "frag")
        f = Fragment(p, "i", "f", "standard", 0)
        f.open()
        rng = np.random.default_rng(14)
        rows = rng.integers(0, 50, size=4000).tolist()
        cols = rng.integers(0, SHARD_WIDTH, size=4000).tolist()
        f.bulk_import(rows, cols)
        ids = np.arange(50, dtype=np.uint64)
        counts = f.row_counts_for(ids)
        for r in range(50):
            assert int(counts[r]) == f.row(r).count()
        f.close()


class TestLazyHolderOpen:
    def test_open_touches_only_queried_fragments(self, tmp_path):
        h = Holder(str(tmp_path / "data"))
        h.open()
        idx = h.create_index("i")
        fld = idx.create_field("f")
        for shard in range(6):
            fld.import_bits([1, 2], [shard * SHARD_WIDTH, shard * SHARD_WIDTH + 9])
        h.close()

        h2 = Holder(str(tmp_path / "data"))
        h2.open()
        view = h2.field("i", "f").view("standard")
        assert sorted(view.fragments) == list(range(6))
        assert all(not fr._open for fr in view.fragments.values())
        # touching one shard opens exactly that fragment
        frag = view.fragment(3)
        assert frag._open
        opened = [s for s, fr in view.fragments.items() if fr._open]
        assert opened == [3]
        assert frag.row(1).columns() == [3 * SHARD_WIDTH]
        h2.close()

    def test_available_shards_without_open(self, tmp_path):
        h = Holder(str(tmp_path / "data"))
        h.open()
        idx = h.create_index("i")
        fld = idx.create_field("f")
        fld.set_bit(0, 5 * SHARD_WIDTH + 1)
        fld.set_bit(0, 3)
        h.close()
        h2 = Holder(str(tmp_path / "data"))
        h2.open()
        view = h2.field("i", "f").view("standard")
        assert view.available_shards() == [0, 5]
        assert all(not fr._open for fr in view.fragments.values())
        h2.close()


class TestSerializeClean:
    def test_unmutated_store_streams_base_verbatim(self):
        b = _random_bitmap(np.random.default_rng(60))
        data = b.to_bytes()
        lazy = Bitmap.unmarshal_mmap(data)
        assert lazy.to_bytes() == data  # fast path: verbatim copy

    def test_oplog_tail_not_copied(self):
        import io

        b = Bitmap()
        b.add_no_oplog(5)
        buf = io.BytesIO()
        b.write_to(buf)
        snapshot_len = len(buf.getvalue())
        b2 = Bitmap.unmarshal_binary(buf.getvalue())
        b2.op_writer = buf
        b2.add(99)  # appends an op-log entry after the snapshot
        lazy = Bitmap.unmarshal_mmap(buf.getvalue())
        # ops replayed into the overlay -> fast path must NOT apply
        out = lazy.to_bytes()
        assert len(out) != snapshot_len or out != buf.getvalue()[:snapshot_len]
        back = Bitmap.unmarshal_binary(out)
        assert sorted(back) == [5, 99]

    def test_mutated_store_falls_back(self):
        b = _random_bitmap(np.random.default_rng(61))
        lazy = Bitmap.unmarshal_mmap(b.to_bytes())
        lazy.add_no_oplog((500 << 16) + 1)
        out = lazy.to_bytes()
        back = Bitmap.unmarshal_binary(out)
        assert np.array_equal(back.slice_all(), lazy.slice_all())


class TestConcurrentMmapFragment:
    def test_readers_and_writers_race(self, tmp_path):
        """Concurrent point writes + reads on an mmap-backed fragment:
        no exceptions, and the final state contains every written bit
        (the overlay/occupancy caches must stay coherent under the
        fragment lock)."""
        import threading

        p = str(tmp_path / "frag")
        f = Fragment(p, "i", "f", "standard", 0)
        f.open()
        f.bulk_import(list(range(64)), list(range(64)))
        f.close()
        f2 = Fragment(p, "i", "f", "standard", 0)
        f2.open()
        errors = []
        stop = threading.Event()

        def writer(tid):
            try:
                for i in range(300):
                    f2.set_bit(1000 + tid, i * 7 % SHARD_WIDTH)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def reader():
            try:
                while not stop.is_set():
                    f2.row(3).count()
                    f2.row_counts_for(np.arange(8, dtype=np.uint64))
                    f2.sparse_block_count([1000, 1001, 5])
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        ws = [threading.Thread(target=writer, args=(t,)) for t in range(4)]
        rs = [threading.Thread(target=reader) for _ in range(3)]
        for t in rs + ws:
            t.start()
        for t in ws:
            t.join()
        stop.set()
        for t in rs:
            t.join()
        assert not errors, errors[:3]
        for tid in range(4):
            assert f2.row(1000 + tid).count() == len({i * 7 % SHARD_WIDTH for i in range(300)})
        f2.close()


class TestOccupancySidecar:
    """.occ sidecar: mmapped base occupancy so a 64-fragment 1B index
    opens in O(page-in), not O(copy+cumsum per fragment)."""

    def _write_frag(self, tmp_path, name="0"):
        from pilosa_tpu.roaring.writer import build_fragment_file

        rng = np.random.default_rng(3)
        pos = np.unique(rng.integers(0, 1 << 24, size=20_000, dtype=np.uint64))
        p = str(tmp_path / name)
        build_fragment_file(p, [pos])
        return p, pos

    def test_builder_emits_sidecar_and_open_uses_it(self, tmp_path):
        p, _ = self._write_frag(tmp_path)
        assert os.path.exists(p + ".occ")
        b = Bitmap.open_mmap_file(p)
        # the load path must actually be taken — a silently rejected
        # stamp would fall back to computing and still pass the oracle
        assert b.containers._occ_sidecar_load() is not None
        keys_sc, cs_sc = b.containers.occupancy()
        # oracle: force a from-scratch computation (no sidecar)
        os.unlink(p + ".occ")
        b2 = Bitmap.open_mmap_file(p)
        keys, cs = b2.containers.occupancy()
        assert keys_sc.dtype == keys.dtype and cs_sc.dtype == cs.dtype
        assert np.array_equal(np.asarray(keys_sc), keys)
        assert np.array_equal(np.asarray(cs_sc), cs)
        # ...and the from-scratch pass regenerated the sidecar
        assert os.path.exists(p + ".occ")

    def test_stale_sidecar_rejected_after_snapshot(self, tmp_path):
        p, _ = self._write_frag(tmp_path)
        frag = Fragment(p, "i", "f", "standard", 0)
        frag.ensure_open()
        before = frag.storage.containers.occupancy()
        frag.set_bit(999, 12345)  # overlay mutation
        frag.snapshot()  # rewrites the base; old .occ is now stale
        b = Bitmap.open_mmap_file(p)
        keys, cs = b.containers.occupancy()
        # the new bit's container must be visible in the fresh index
        assert int(cs[-1]) == int(before[1][-1]) + 1
        frag.close()

    def test_corrupt_sidecar_falls_back(self, tmp_path):
        p, _ = self._write_frag(tmp_path)
        with open(p + ".occ", "wb") as f:
            f.write(b"junk")
        b = Bitmap.open_mmap_file(p)
        keys, cs = b.containers.occupancy()
        assert keys.size > 0 and int(cs[-1]) > 0

    def test_mutated_store_does_not_save_or_use_sidecar(self, tmp_path):
        p, _ = self._write_frag(tmp_path)
        os.unlink(p + ".occ")
        b = Bitmap.open_mmap_file(p)
        b.add(77 << 16)  # overlay (new container)
        keys, cs = b.containers.occupancy()
        assert not os.path.exists(p + ".occ")  # impure: no sidecar write
        assert np.uint64(77) in np.asarray(keys).astype(np.uint64)

    def test_balanced_mutation_snapshot_cannot_serve_stale_sidecar(self, tmp_path):
        """Snapshot collision: clear one bit in container A and set one
        in existing container B — container count AND payload bytes are
        unchanged, so (base_n, ops_offset) match the old sidecar. Only
        the mtime/size stamp (plus snapshot's unlink) detects it."""
        from pilosa_tpu.roaring.writer import build_fragment_file

        pos = np.concatenate([
            np.arange(100, dtype=np.uint64),                 # container 0
            np.arange(100, dtype=np.uint64) + (1 << 16),     # container 1
        ])
        p = str(tmp_path / "bal")
        build_fragment_file(p, [np.sort(pos)])
        frag = Fragment(p, "i", "f", "standard", 0)
        frag.ensure_open()
        old_keys, old_cs = frag.storage.containers.occupancy()
        stale = (np.asarray(old_keys).copy(), np.asarray(old_cs).copy())
        frag.clear_bit(0, 99)          # -1 bit in container 0
        frag.set_bit(1, 100)           # +1 bit in container 1
        frag.snapshot()
        b = Bitmap.open_mmap_file(p)
        keys, cs = b.containers.occupancy()
        assert int(cs[-1]) == int(stale[1][-1])  # same total (balanced)
        # but the PER-container sums differ from the stale sidecar
        assert not np.array_equal(np.asarray(cs), stale[1])
        frag.close()


class TestCorruptionRobustness:
    def test_header_region_byte_flip_fuzz(self, tmp_path):
        """Structural corruption (header / metas / offsets region) must
        surface as a Python exception or benign behavior — never a
        native out-of-bounds read. Payload bit flips are undetectable
        without checksums (reference parity: its mmap open has none);
        the STRUCTURAL region is what drives pointer arithmetic, so
        that is what gets fuzzed. Exercises pt_expand_blocks_v2's
        bounds checks through the staging path."""
        from pilosa_tpu.roaring.mmapstore import HEADER_BASE_SIZE

        rng = np.random.default_rng(99)
        b = Bitmap()
        for c in range(4):
            vals = np.unique(rng.integers(0, 1 << 16, size=900, dtype=np.uint64))
            b.merge_positions(add=np.uint64(c << 16) + vals)
        b.merge_positions(
            add=np.uint64(6 << 16)
            + np.unique(rng.integers(0, 1 << 16, size=30000, dtype=np.uint64))
        )
        clean = tmp_path / "frag"
        with open(clean, "wb") as f:
            b.write_to(f)
        data = bytearray(clean.read_bytes())
        # header + metas (12 B/container) + offsets (4 B/container),
        # derived from the file itself so data-generation changes can't
        # silently widen the window into payload bytes
        n_containers = int.from_bytes(bytes(data[4:8]), "little")
        assert n_containers == len(b.containers)
        structural_end = HEADER_BASE_SIZE + 16 * n_containers
        for trial in range(60):
            corrupt = bytearray(data)
            pos = int(rng.integers(0, structural_end))
            corrupt[pos] ^= 1 << int(rng.integers(0, 8))
            p = tmp_path / f"c{trial}"
            p.write_bytes(bytes(corrupt))
            try:
                lazy = Bitmap.open_mmap_file(str(p))
                store = lazy.containers
                # drive the read paths that trust file-provided offsets
                if hasattr(store, "_base_n") and store._base_n:
                    n = min(int(store._base_n), 64)
                    sel = np.arange(n, dtype=np.int64)
                    out = np.zeros((n, 1024), dtype=np.uint64)
                    store.expand_base_blocks(sel, out)  # False or filled; no crash
                lazy.count()
                for k in list(getattr(store, "overlay", {}))[:4]:
                    store.get(k)
            except (ValueError, KeyError, IndexError, OverflowError, struct.error):
                continue  # surfaced as a structured parse error: correct
