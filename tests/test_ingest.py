"""Durable streaming ingest (ISSUE 11): group-commit wire format, torn
op-log tail recovery, storage fault injection, the write-ahead queue's
ack/backpressure contract, and the HTTP ingest surface.

The recovery property under test everywhere: an ACKED write (its wave's
group-commit append fsynced) replays after any crash; a torn trailing
record truncates cleanly instead of failing the open or corrupting the
replay of the intact prefix.
"""

import os
import threading
import time

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import Holder
from pilosa_tpu.core.fragment import (
    Fragment,
    StorageFaultSpec,
    install_storage_faults,
)
from pilosa_tpu.core import fragment as fragment_mod
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.roaring import bitmap as bm
from pilosa_tpu.server.ingest import IngestQueue
from pilosa_tpu.server.pipeline import Overloaded


@pytest.fixture(autouse=True)
def _clean_faults():
    fragment_mod.FAULTS = None
    yield
    fragment_mod.FAULTS = None


def _frag(path) -> Fragment:
    f = Fragment(str(path), "i", "f", VIEW_STANDARD, 0)
    f.open()
    return f


# -- group-commit record wire format ----------------------------------------


def test_op_batch_roundtrip():
    ops = [(bm.OP_ADD, 5), (bm.OP_REMOVE, 9), (bm.OP_ADD, 1 << 40)]
    rec = bm.marshal_op_batch(ops)
    assert len(rec) == bm.OP_BATCH_HEADER_SIZE + 3 * bm.OP_BATCH_ENTRY_SIZE + 4
    got, off = bm.read_op_record(rec, 0)
    assert got == ops and off == len(rec)


def test_op_batch_checksum_detects_flip():
    rec = bytearray(bm.marshal_op_batch([(bm.OP_ADD, 7)]))
    rec[bm.OP_BATCH_HEADER_SIZE + 2] ^= 0x40  # flip a payload bit
    with pytest.raises(ValueError):
        bm.read_op_record(bytes(rec), 0)


def test_single_op_records_still_read():
    rec = bm.marshal_op(bm.OP_ADD, 123)
    got, off = bm.read_op_record(rec, 0)
    assert got == [(bm.OP_ADD, 123)] and off == bm.OP_SIZE


# -- torn-tail recovery, parametrized over record type × cut point ----------

# cut offsets are relative to the start of the torn trailing record;
# None = leave the record intact (control: nothing truncates)
_BATCH_N = 3
_BATCH_SIZE = bm.OP_BATCH_HEADER_SIZE + _BATCH_N * bm.OP_BATCH_ENTRY_SIZE + 4
_CUTS = [
    ("single", "mid-header", 0),  # crash before any byte of the record landed
    ("single", "mid-payload", 5),
    ("single", "mid-checksum", bm.OP_SIZE - 2),
    ("batch", "mid-header", 3),
    ("batch", "mid-payload", bm.OP_BATCH_HEADER_SIZE + bm.OP_BATCH_ENTRY_SIZE + 4),
    ("batch", "mid-checksum", _BATCH_SIZE - 2),
]


@pytest.mark.parametrize(
    "rectype,where,cut", _CUTS, ids=[f"{r}-{w}" for r, w, _ in _CUTS]
)
def test_torn_tail_truncates_and_acked_ops_replay(tmp_path, rectype, where, cut):
    p = tmp_path / "frag"
    f = _frag(p)
    # acked prefix: a single-op record AND a group-commit batch
    f.set_bit(1, 100)
    f.apply_bit_batch([2, 2, 3], [10, 20, 30])
    f.close()
    intact = os.path.getsize(p)
    # the crash: a torn record lands partially at the tail
    if rectype == "single":
        rec = bm.marshal_op(bm.OP_ADD, 777)
    else:
        rec = bm.marshal_op_batch([(bm.OP_ADD, 40 + i) for i in range(_BATCH_N)])
    with open(p, "ab") as fh:
        fh.write(rec[:cut])
    f2 = _frag(p)
    # torn tail truncated to the last intact record
    assert os.path.getsize(p) == intact
    # every acked write replays
    assert f2.bit(1, 100)
    assert f2.bit(2, 10) and f2.bit(2, 20) and f2.bit(3, 30)
    f2.close()


def test_truncated_snapshot_header_resets_to_empty(tmp_path):
    # a file shorter than the roaring header can hold no acked op
    p = tmp_path / "frag"
    f = _frag(p)
    f.close()
    with open(p, "r+b") as fh:
        fh.truncate(bm.HEADER_BASE_SIZE - 3)
    f2 = _frag(p)
    assert f2.row(0).columns().size == 0
    f2.close()


def test_corrupt_snapshot_prefix_quarantines_at_open(tmp_path):
    # the snapshot prefix is written atomically (tmp+fsync+rename), so
    # base corruption is NOT a crash artifact — recovery must not
    # silently wipe it. Since the integrity work the open succeeds but
    # the fragment is QUARANTINED: reads fail clean (503 upstream,
    # never garbage) and the file is kept intact for repair.
    p = tmp_path / "frag"
    f = _frag(p)
    f.set_bit(0, 1)
    f.close()
    size = os.path.getsize(p)
    with open(p, "r+b") as fh:
        fh.seek(0)
        fh.write(b"\xff\xff\xff\xff")
    f2 = _frag(p)
    assert f2.quarantined
    with pytest.raises(fragment_mod.FragmentQuarantinedError):
        f2.row(0)
    assert os.path.getsize(p) == size  # nothing wiped or truncated
    f2.close()


def test_recovery_replays_multiple_waves_bit_identical(tmp_path):
    p = tmp_path / "frag"
    f = _frag(p)
    rng = np.random.default_rng(11)
    oracle = set()
    for _ in range(6):
        rows = rng.integers(0, 16, size=50)
        cols = rng.integers(0, SHARD_WIDTH, size=50)
        sets = rng.integers(0, 2, size=50).astype(bool)
        f.apply_bit_batch(rows, cols, sets)
        for r, c, s in zip(rows, cols, sets):
            (oracle.add if s else oracle.discard)((int(r), int(c)))
    f.close()
    f2 = _frag(p)
    for r in range(16):
        want = sorted(c for (rr, c) in oracle if rr == r)
        assert f2.row(r).columns().tolist() == want, f"row {r}"
    f2.close()


# -- storage fault injection -------------------------------------------------


def test_fault_spec_parse_and_unknown_knob():
    s = StorageFaultSpec.parse("fsync_fail_every=3, torn_at=100")
    assert s.fsync_fail_every == 3 and s.torn_at == 100 and bool(s)
    assert not StorageFaultSpec.parse("")
    with pytest.raises(ValueError):
        # check: disable=fault-spec (deliberately invalid knob — the ValueError is the assertion)
        StorageFaultSpec.parse("rm_rf_every=1")


def test_install_from_env(monkeypatch):
    monkeypatch.setenv(fragment_mod.STORAGE_FAULTS_ENV, "enospc_after=2")
    install_storage_faults()
    assert fragment_mod.FAULTS is not None
    assert fragment_mod.FAULTS.enospc_after == 2
    monkeypatch.setenv(fragment_mod.STORAGE_FAULTS_ENV, "")
    install_storage_faults()
    assert fragment_mod.FAULTS is None


def test_torn_write_fault_nacks_wave_and_repairs_log(tmp_path):
    p = tmp_path / "frag"
    f = _frag(p)
    f.apply_bit_batch([1, 1], [10, 20])  # acked wave
    acked_size = os.path.getsize(p)
    # fault byte counts are relative to install: byte 4 is inside the
    # very next record
    fragment_mod.FAULTS = StorageFaultSpec(torn_at=4)
    with pytest.raises(OSError):
        f.apply_bit_batch([2, 2, 2], [10, 20, 30])
    fragment_mod.FAULTS = None
    # the writer repaired the tail in-place (the partial record would
    # strand later appends behind it), so a LATER wave still acks and
    # survives
    assert os.path.getsize(p) == acked_size
    f.apply_bit_batch([3], [30])
    f.close()
    f2 = _frag(p)
    assert f2.bit(1, 10) and f2.bit(1, 20)
    assert not f2.bit(2, 10)  # nacked wave gone
    assert f2.bit(3, 30)  # acked-after-tear wave survives
    f2.close()


def test_fsync_fault_nacks_and_leaves_fragment_untouched(tmp_path):
    p = tmp_path / "frag"
    f = _frag(p)
    size0 = os.path.getsize(p)
    fragment_mod.FAULTS = StorageFaultSpec(fsync_fail_every=1)
    with pytest.raises(OSError):
        f.apply_bit_batch([5], [50])
    fragment_mod.FAULTS = None
    # write-ahead order: the nacked wave mutated NOTHING in memory and
    # the un-durable record was truncated back out of the tail
    assert not f.bit(5, 50)
    assert os.path.getsize(p) == size0
    f.close()


def test_retry_after_failed_append_relogs_and_survives_crash(tmp_path):
    """The lost-write regression: if a failed append left the bits set
    in memory, the client's retry would see changed=False everywhere,
    log nothing, and get ACKED with nothing in the fsynced log — gone
    on the next crash. The retry must re-log the identical wave."""
    p = tmp_path / "frag"
    f = _frag(p)
    fragment_mod.FAULTS = StorageFaultSpec(fsync_fail_every=1)
    with pytest.raises(OSError):
        f.apply_bit_batch([5, 6], [50, 60])
    fragment_mod.FAULTS = None
    # the retry of the nacked wave: must CHANGE (and therefore log) the
    # same bits again, not no-op its way to a hollow ack
    assert f.apply_bit_batch([5, 6], [50, 60]) == 2
    f.close()
    f2 = _frag(p)
    assert f2.bit(5, 50) and f2.bit(6, 60)
    f2.close()


def test_enospc_fault(tmp_path):
    p = tmp_path / "frag"
    f = _frag(p)
    fragment_mod.FAULTS = StorageFaultSpec(enospc_after=1)
    f.apply_bit_batch([1], [1])  # append #1: allowed
    size = os.path.getsize(p)
    with pytest.raises(OSError) as ei:
        f.apply_bit_batch([2], [2])  # append #2: ENOSPC, writes nothing
    assert ei.value.errno == 28
    assert os.path.getsize(p) == size
    f.close()


# -- 8-writer / 1-crash property test ---------------------------------------


def test_eight_writers_one_crash_acked_survive(tmp_path):
    """8 concurrent writers commit waves against one fragment; a torn
    write injected mid-run crashes one wave. Property: every wave whose
    apply RETURNED (acked) replays after reopen; the torn wave's
    partial record truncates cleanly."""
    p = tmp_path / "frag"
    f = _frag(p)
    # tear roughly mid-run: each wave is 8 ops ≈ 8*9+5+4 = 81 bytes,
    # 8 writers × 6 waves each ≈ 48 appends; tear inside append ~20
    fragment_mod.FAULTS = StorageFaultSpec(torn_at=20 * 81 + 10)
    acked: list[list[tuple[int, int]]] = [[] for _ in range(8)]
    nacked = []
    mu = threading.Lock()

    def writer(w):
        rng = np.random.default_rng(100 + w)
        for wave in range(6):
            rows = rng.integers(0, 8, size=8)
            cols = rng.integers(0, SHARD_WIDTH, size=8)
            pairs = [(int(r), int(c)) for r, c in zip(rows, cols)]
            try:
                f.apply_bit_batch(rows, cols)
            except OSError:
                with mu:
                    nacked.extend(pairs)
            else:
                with mu:
                    acked[w].append(pairs)

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert nacked, "fault schedule never fired"
    f.close()
    fragment_mod.FAULTS = None
    f2 = _frag(p)
    for w in range(8):
        for pairs in acked[w]:
            for r, c in pairs:
                assert f2.bit(r, c), f"acked write ({r},{c}) lost after crash"
    f2.close()


# -- IngestQueue ack / backpressure contract --------------------------------


class _StubAPI:
    """Duck-typed api: records waves; optional failure injection."""

    def __init__(self, fail=False, holder=None):
        self.waves = []
        self.fail = fail

    def apply_write_wave(self, index, field, rows, cols, sets):
        if self.fail:
            raise OSError(5, "injected commit failure")
        self.waves.append((index, field, list(rows), list(cols), list(sets)))
        return len(rows)


def test_queue_acks_after_commit():
    api = _StubAPI()
    q = IngestQueue(api, wave_interval=0.0)
    try:
        n = q.submit("i", "f", [1, 2], [10, 20])
        assert n == 2
        assert sum(len(w[2]) for w in api.waves) == 2
        st = q.stats()
        assert st["acked"] == 2 and st["waves"] >= 1
    finally:
        q.close()


def test_queue_coalesces_concurrent_submits_into_waves():
    api = _StubAPI()
    q = IngestQueue(api, wave_interval=0.02)
    try:
        threads = [
            threading.Thread(
                target=lambda w=w: q.submit("i", "f", [w] * 4, list(range(4)))
            )
            for w in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sum(len(w[2]) for w in api.waves) == 24
        # the coalesce window merged concurrent submitters: fewer
        # waves (= group commits) than submitters
        assert q.stats()["waves"] < 6
    finally:
        q.close()


def test_queue_overflow_sheds_429_with_retry_after():
    api = _StubAPI()
    q = IngestQueue(api, queue_limit=4, wave_interval=0.0, retry_after=0.5)
    try:
        with pytest.raises(Overloaded) as ei:
            q.submit("i", "f", list(range(5)), list(range(5)))
        assert ei.value.status == 429
        assert ei.value.retry_after == 0.5
        assert q.stats()["shed"] == 5
    finally:
        q.close()


def test_queue_commit_failure_nacks_submitter():
    api = _StubAPI(fail=True)
    q = IngestQueue(api, wave_interval=0.0)
    try:
        # a storage-layer wave abort surfaces as a RETRYABLE 503, not
        # the raw OSError (the wave never applied; repair re-opened the
        # log) — the chaos contract: faults cost retries, never a 500
        with pytest.raises(Overloaded) as ei:
            q.submit("i", "f", [1], [1])
        assert ei.value.status == 503
        assert isinstance(ei.value.__cause__, OSError)
        assert q.stats()["nacked"] == 1 and q.stats()["acked"] == 0
    finally:
        q.close()


def test_committer_survives_journal_failure(monkeypatch):
    """An exception OUTSIDE the per-group apply (metrics/journal code)
    must not kill the committer thread: the wave's submitters are
    nacked and woken, and the queue keeps serving later waves."""
    api = _StubAPI()
    q = IngestQueue(api, wave_interval=0.0)
    try:
        from pilosa_tpu.server import ingest as ingest_mod

        def boom(*a, **k):
            raise RuntimeError("journal exploded")

        monkeypatch.setattr(ingest_mod.events, "record", boom)
        with pytest.raises(RuntimeError):
            q.submit("i", "f", [1], [1])
        monkeypatch.undo()
        # the committer thread is still alive and commits the next wave
        assert q.submit("i", "f", [2], [2]) == 1
    finally:
        q.close()


def test_submit_deadline_times_out_504():
    from pilosa_tpu.server import deadline as deadline_mod

    class _SlowAPI:
        def apply_write_wave(self, index, field, rows, cols, sets):
            time.sleep(0.5)
            return len(rows)

    q = IngestQueue(_SlowAPI(), wave_interval=0.0)
    try:
        dl = deadline_mod.Deadline(time.monotonic() + 0.05)
        with pytest.raises(deadline_mod.DeadlineExceeded):
            q.submit("i", "f", [1], [1], deadline=dl)
        # an already-expired deadline is refused at admission
        dl2 = deadline_mod.Deadline(time.monotonic() - 1.0)
        with pytest.raises(deadline_mod.DeadlineExceeded):
            q.submit("i", "f", [2], [2], deadline=dl2)
    finally:
        q.close()


def test_queue_drains_then_503s():
    api = _StubAPI()
    q = IngestQueue(api, wave_interval=0.0)
    q.submit("i", "f", [1], [1])
    q.close()
    with pytest.raises(Overloaded) as ei:
        q.submit("i", "f", [2], [2])
    assert ei.value.status == 503
    assert q.stats()["acked"] == 1


# -- holder-level wave apply + bulk-import cliff -----------------------------


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "d"))
    h.open()
    yield h
    h.close()


def test_small_import_block_pairs_rides_wave_path(holder):
    idx = holder.create_index("i")
    fld = idx.create_field("f")
    fld.import_bits([0] * 4, [1, 2, 3, 4])
    frag = holder.fragment("i", "f", VIEW_STANDARD, 0)
    gen0 = frag.generation
    frag.import_block_pairs(
        np.array([0, 0], dtype=np.uint64),
        np.array([5, 6], dtype=np.uint64),
        clear_rows=np.array([0], dtype=np.uint64),
        clear_cols=np.array([1], dtype=np.uint64),
    )
    # one wave = ONE generation bump, clears applied before sets
    assert frag.generation == gen0 + 1
    assert frag.row(0).columns().tolist() == [2, 3, 4, 5, 6]
    # and the delta log stayed continuous (no reset): provable deltas
    assert frag.deltas_since(gen0) is not None


def test_wave_after_reopen_lands_in_mmapped_fragment(tmp_path):
    """A write wave against a freshly reopened holder must open the
    discovered fragment before mutating it. Holder.open registers
    on-disk fragments lazily (unopened); a wave applied to the
    unopened placeholder would report every re-set bit as changed,
    append nothing to the op log, and lose the whole wave when the
    first read's ensure_open() swapped in the mmapped storage."""
    d = str(tmp_path / "d")
    h = Holder(d)
    h.open()
    idx = h.create_index("i")
    fld = idx.create_field("f")
    v = fld.create_view_if_not_exists(VIEW_STANDARD)
    rows = [r for r in range(6) for _ in range(2)]
    cols = [c for r in range(6) for c in (r * 7 + 1, SHARD_WIDTH + r * 11 + 3)]
    for shard in (0, 1):
        sel = [i for i, c in enumerate(cols) if c // SHARD_WIDTH == shard]
        v.create_fragment_if_not_exists(shard).apply_bit_batch(
            [rows[i] for i in sel], [cols[i] for i in sel]
        )
    h.close()

    h2 = Holder(d)
    h2.open()
    v2 = h2.field("i", "f").view(VIEW_STANDARD)
    rows2 = [r for r in range(12) for _ in range(2)]
    cols2 = [c for r in range(12) for c in (r * 7 + 1, SHARD_WIDTH + r * 11 + 3)]
    changed = 0
    for shard in (0, 1):
        sel = [i for i, c in enumerate(cols2) if c // SHARD_WIDTH == shard]
        changed += v2.create_fragment_if_not_exists(shard).apply_bit_batch(
            [rows2[i] for i in sel], [cols2[i] for i in sel]
        )
    # rows 0-5 are already on disk: only rows 6-11 (2 bits each) change
    assert changed == 12
    for shard in (0, 1):
        frag = v2.fragment(shard)
        for r in range(12):
            assert frag.row(r).count() == 1, f"shard {shard} row {r}"
    h2.close()

    # and the new rows were op-logged: they survive another restart
    h3 = Holder(d)
    h3.open()
    v3 = h3.field("i", "f").view(VIEW_STANDARD)
    for shard in (0, 1):
        frag = v3.fragment(shard)
        for r in range(12):
            assert frag.row(r).count() == 1, f"restart: shard {shard} row {r}"
    h3.close()
