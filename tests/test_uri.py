"""URI abstraction tests (reference uri_test.go semantics for
uri.go:45-264: optional parts, defaults, normalize, equivalence)."""

import pytest

from pilosa_tpu.utils.uri import URI, URIError, same_endpoint


class TestParse:
    def test_full(self):
        u = URI.from_address("https://node1.example.com:3333")
        assert (u.scheme, u.host, u.port) == ("https", "node1.example.com", 3333)

    def test_equivalent_spellings_all_default(self):
        # reference uri.go:38-44: these are all the same address
        expect = URI(scheme="http", host="localhost", port=10101)
        for spelling in (
            "http://localhost:10101",
            "http://localhost",
            "localhost:10101",
            "localhost",
            ":10101",
        ):
            assert URI.from_address(spelling) == expect, spelling

    def test_host_only(self):
        u = URI.from_address("index1.pilosa.com")
        assert (u.scheme, u.host, u.port) == ("http", "index1.pilosa.com", 10101)

    def test_port_only(self):
        assert URI.from_address(":65000").port == 65000

    def test_ipv6(self):
        u = URI.from_address("[::1]:9999")
        assert (u.host, u.port) == ("[::1]", 9999)

    def test_scheme_plus(self):
        u = URI.from_address("http+protobuf://h:1")
        assert u.scheme == "http+protobuf"
        assert u.normalize() == "http://h:1"

    def test_invalid(self):
        for bad in ("foo:bar", "http://host:port", "a b", "HTTP://x:1"):
            with pytest.raises(URIError):
                URI.from_address(bad)

    def test_default_scheme_override(self):
        assert URI.from_address("h:1", default_scheme="https").scheme == "https"


class TestViews:
    def test_host_port_and_str(self):
        u = URI(scheme="http", host="h", port=101)
        assert u.host_port() == "h:101"
        assert str(u) == "http://h:101"
        assert u.path("/schema") == "http://h:101/schema"


class TestEquivalence:
    def test_loopback_spellings(self):
        assert same_endpoint("http://localhost:5001", "http://127.0.0.1:5001")
        assert same_endpoint("127.0.0.1:5001", "localhost:5001")
        assert not same_endpoint("localhost:5001", "localhost:5002")
        assert not same_endpoint("http://a:1", "http://b:1")

    def test_scheme_plus_equivalent(self):
        assert same_endpoint("http+x://h:1", "http://h:1")

    def test_default_port_fill(self):
        assert same_endpoint("http://h:10101", "h")

    def test_unparseable_falls_back_to_string_eq(self):
        assert same_endpoint("!!", "!!")
        assert not same_endpoint("!!", "http://h:1")
