"""SPMD kernel tests on the 8-device virtual CPU mesh — collectives
(psum/all_gather) validated against host oracles."""

import numpy as np
import pytest
import jax

from pilosa_tpu.parallel import (
    ShardBatchPlan,
    bsi_sum_spmd,
    count_fold_spmd,
    make_mesh,
    put_sharded,
    row_algebra_spmd,
    topn_spmd,
)

W = 128  # words per shard-row for tests


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "tests expect 8 virtual devices"
    return make_mesh()


def rand_words(rng, *shape):
    return rng.integers(0, 2**32, size=shape, dtype=np.uint32)


def popcount(a):
    return int(np.bitwise_count(a).sum())


def test_count_fold_spmd(mesh):
    rng = np.random.default_rng(0)
    stacked = rand_words(rng, 8, 3, W)
    fn = count_fold_spmd(mesh)
    got = int(fn(put_sharded(mesh, stacked)))
    want = sum(
        popcount(stacked[s, 0] & stacked[s, 1] & stacked[s, 2]) for s in range(8)
    )
    assert got == want


def test_count_fold_multiple_shards_per_device(mesh):
    rng = np.random.default_rng(1)
    stacked = rand_words(rng, 16, 2, W)  # 2 shards per device
    fn = count_fold_spmd(mesh)
    got = int(fn(put_sharded(mesh, stacked)))
    want = sum(popcount(stacked[s, 0] & stacked[s, 1]) for s in range(16))
    assert got == want


def test_topn_spmd(mesh):
    rng = np.random.default_rng(2)
    S, R, k = 8, 16, 4
    src = rand_words(rng, S, W)
    mat = rand_words(rng, S, R, W)
    fn = topn_spmd(mesh, k)
    ids, counts = fn(put_sharded(mesh, src), put_sharded(mesh, mat))
    ids, counts = np.asarray(ids), np.asarray(counts)
    assert ids.shape == (S * k,)
    # each shard's k entries must be that shard's true top-k scores
    for s in range(S):
        scores = np.bitwise_count(mat[s] & src[s][None, :]).sum(axis=1)
        want = sorted(scores.tolist(), reverse=True)[:k]
        got = sorted(counts[s * k : (s + 1) * k].tolist(), reverse=True)
        assert got == want, s
        # ids match scores
        for i in range(k):
            assert scores[ids[s * k + i]] == counts[s * k + i]


def test_topn_batch_spmd(mesh):
    from pilosa_tpu.parallel import topn_batch_spmd

    rng = np.random.default_rng(7)
    S, R, Q, k = 8, 16, 4, 3
    srcs = rand_words(rng, Q, W)
    mat = rand_words(rng, S, R, W)
    fn = topn_batch_spmd(mesh, k)
    ids, counts = fn(srcs, put_sharded(mesh, mat))
    ids, counts = np.asarray(ids), np.asarray(counts)
    assert ids.shape == (Q, S * k) and counts.shape == (Q, S * k)
    for q in range(Q):
        for s in range(S):
            scores = np.bitwise_count(mat[s] & srcs[q][None, :]).sum(axis=1)
            want = sorted(scores.tolist(), reverse=True)[:k]
            got = sorted(counts[q, s * k : (s + 1) * k].tolist(), reverse=True)
            assert got == want, (q, s)
            for i in range(k):
                assert scores[ids[q, s * k + i]] == counts[q, s * k + i]


def test_bsi_sum_spmd(mesh):
    rng = np.random.default_rng(3)
    S, D = 8, 6
    planes = rand_words(rng, S, D + 1, W)
    filt = rand_words(rng, S, W)
    fn = bsi_sum_spmd(mesh, D)
    counts = np.asarray(fn(put_sharded(mesh, planes), put_sharded(mesh, filt)))
    for i in range(D + 1):
        want = sum(popcount(planes[s, i] & filt[s]) for s in range(S))
        assert int(counts[i]) == want


def test_row_algebra_spmd(mesh):
    rng = np.random.default_rng(4)
    stacked = rand_words(rng, 8, 3, W)
    for op, npfn in [("and", np.bitwise_and), ("or", np.bitwise_or), ("xor", np.bitwise_xor)]:
        fn = row_algebra_spmd(mesh, op)
        got = np.asarray(fn(put_sharded(mesh, stacked)))
        want = npfn.reduce(stacked, axis=1)
        assert np.array_equal(got, want), op


def test_shard_batch_plan_padding(mesh):
    plan = ShardBatchPlan(mesh, [0, 1, 2])  # pads to 8
    assert len(plan.padded) == 8
    rng = np.random.default_rng(5)
    words = {0: rand_words(rng, 2, W), 2: rand_words(rng, 2, W)}
    stacked = plan.stack_rows(words, W)
    assert stacked.shape == (8, 2, W)
    assert np.array_equal(stacked[0], words[0])
    assert not stacked[1].any()
    assert np.array_equal(stacked[2], words[2])
    # padding shards reduce to zero in a count fold
    fn = count_fold_spmd(mesh)
    got = int(fn(put_sharded(mesh, stacked)))
    want = popcount(words[0][0] & words[0][1]) + popcount(words[2][0] & words[2][1])
    assert got == want
