"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip shardings are validated on virtual CPU devices
(xla_force_host_platform_device_count); real-TPU benchmarking happens in
bench.py, not the test suite.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
