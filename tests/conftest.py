"""Test configuration: force an 8-device virtual CPU mesh.

The environment registers a real-TPU backend at interpreter startup
(sitecustomize calls jax.config.update("jax_platforms", "axon,cpu"),
which overrides the JAX_PLATFORMS env var). Tests must hard-override to
CPU *before* any jax backend initialisation so the suite never depends
on TPU-tunnel health. Multi-chip shardings are validated on 8 virtual
CPU devices; real-TPU benchmarking happens in bench.py, not here.
"""

from pilosa_tpu.utils.jaxplatform import force_cpu_mesh

force_cpu_mesh(8)


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`; the soak rides outside it
    config.addinivalue_line(
        "markers", "slow: long multi-process soaks excluded from tier-1"
    )
