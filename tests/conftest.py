"""Test configuration: force an 8-device virtual CPU mesh.

The environment registers a real-TPU backend at interpreter startup
(sitecustomize calls jax.config.update("jax_platforms", "axon,cpu"),
which overrides the JAX_PLATFORMS env var). Tests must hard-override to
CPU *before* any jax backend initialisation so the suite never depends
on TPU-tunnel health. Multi-chip shardings are validated on 8 virtual
CPU devices; real-TPU benchmarking happens in bench.py, not here.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
