"""Cross-process collective plane: the 2-process jax.distributed CPU
mesh dryrun (dryrun_multiprocess.py) must pass — count psum, TopN
all_gather, and BSI Sum psum over a shard axis that SPANS the process
boundary, the in-program analog of the reference's multi-host cluster
(reference cluster.go:788-857)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_mesh_collectives():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "dryrun_multiprocess.py")],
        capture_output=True,
        text=True,
        timeout=280,
        # the parent spawns its own workers with a clean CPU platform;
        # scrub the conftest's single-process XLA flags so the workers
        # get exactly 4 devices each
        env={
            k: v
            for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
        },
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout[proc.stdout.index("{") :])
    assert summary["ok"] is True
    assert summary["processes"] == 2
    assert len(summary["per_rank"]) == 2
    for rank in summary["per_rank"]:
        assert rank["global_devices"] == 8
        assert rank["local_devices"] == 4
        assert all(rank["ok"].values()), rank
