"""TLS on the listener + internal client (reference
server/server.go:166-240, server/config.go TLS block): https serving,
and a 2-node cluster whose node-to-node traffic rides TLS with
skip-verify (self-signed certs)."""

import json
import ssl
import subprocess
import urllib.request

import pytest

from pilosa_tpu.server import ClusterConfig, Config, Server, TLSConfig

from test_cluster import free_ports


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", key, "-out", cert, "-days", "2",
            "-subj", "/CN=127.0.0.1",
            "-addext", "subjectAltName=IP:127.0.0.1",
        ],
        check=True,
        capture_output=True,
    )
    return cert, key


def _req(uri, method, path, body=None):
    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    data = body if (body is None or isinstance(body, bytes)) else json.dumps(body).encode()
    r = urllib.request.Request(uri + path, data=data, method=method)
    with urllib.request.urlopen(r, timeout=30, context=ctx) as resp:
        return resp.status, json.loads(resp.read() or b"{}")


def test_https_serving(tmp_path, certs):
    cert, key = certs
    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="none",
        tls=TLSConfig(certificate_path=cert, certificate_key_path=key, skip_verify=True),
    )
    s = Server(cfg)
    s.open()
    try:
        assert s.uri.startswith("https://")
        st, _ = _req(s.uri, "POST", "/index/t", {})
        assert st == 200
        st, _ = _req(s.uri, "POST", "/index/t/field/f", {})
        assert st == 200
        st, body = _req(s.uri, "POST", "/index/t/query", b"Set(1, f=2)")
        assert st == 200 and body["results"] == [True]
        st, body = _req(s.uri, "POST", "/index/t/query", b"Count(Row(f=2))")
        assert body["results"] == [1]
        # plain http against the TLS listener must fail
        with pytest.raises(Exception):
            urllib.request.urlopen(
                "http://%s:%d/status" % s.address(), timeout=5
            )
    finally:
        s.close()


def test_tls_cluster_node_to_node(tmp_path, certs):
    cert, key = certs
    ports = free_ports(2)
    hosts = [f"127.0.0.1:{p}" for p in ports]
    servers = []
    for i, p in enumerate(ports):
        cfg = Config(
            data_dir=str(tmp_path / f"n{i}"),
            bind=hosts[i],
            device_policy="never",
            metric="none",
            cluster=ClusterConfig(
                disabled=False, coordinator=(i == 0), replicas=1, hosts=hosts
            ),
            tls=TLSConfig(
                certificate_path=cert, certificate_key_path=key, skip_verify=True
            ),
        )
        s = Server(cfg)
        s.open()
        servers.append(s)
    try:
        s0 = servers[0]
        # node URIs derived with the https scheme
        assert all(n.uri.startswith("https://") for n in s0.cluster.nodes)
        _req(s0.uri, "POST", "/index/c", {})
        _req(s0.uri, "POST", "/index/c/field/f", {})
        # writes fan out over TLS to shard owners; reads scatter-gather
        from pilosa_tpu import SHARD_WIDTH

        cols = [sh * SHARD_WIDTH + 5 for sh in range(4)]
        for c in cols:
            st, body = _req(s0.uri, "POST", "/index/c/query", f"Set({c}, f=1)".encode())
            assert st == 200 and body["results"] == [True]
        for s in servers:
            st, body = _req(s.uri, "POST", "/index/c/query", b"Row(f=1)")
            assert body["results"][0]["columns"] == cols, s.uri
    finally:
        for s in servers:
            s.close()
