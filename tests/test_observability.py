"""Observability surface (ISSUE 1): span tracer, profile=true timing
trees, /metrics Prometheus exposition, /debug/traces, the slow-query
trace hook, the tracing-off overhead bound, and docs/name sync.

Everything server-level runs against a real in-process server on :0
under JAX_PLATFORMS=cpu (the tier-1 environment)."""

import json
import os
import re
import time
import urllib.request

import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.server import Config, Server
from pilosa_tpu.utils import metrics, trace
from pilosa_tpu.utils.trace import Tracer


@pytest.fixture()
def server(tmp_path):
    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="expvar",
        device_policy="always",  # CPU jax backend exercises the device path
        device_timeout=0,  # no health gate: keep the test single-purpose
    )
    s = Server(cfg)
    s.open()
    yield s
    s.close()


def req(server, method, path, body=None, raw=False):
    url = server.uri + path
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(r) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload or b"{}")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, payload if raw else json.loads(payload or b"{}")


# -- tracer unit behavior ---------------------------------------------------


def test_tracer_span_nesting_and_ring_bounds():
    tr = Tracer(ring_size=3)
    for i in range(5):
        with tr.trace("query", force=True, i=i) as root:
            with root.child("executor"):
                with trace.current().child("executor.map_shard", shard=7):
                    pass
            root.event("executor.route", path="cpu")
    recent = tr.recent()
    assert len(recent) == 3  # ring bounded
    assert tr.traces_recorded == 5
    d = recent[-1]
    assert d["name"] == "query" and d["meta"] == {"i": 4}
    assert [c["name"] for c in d["children"]] == ["executor", "executor.route"]
    shard_span = d["children"][0]["children"][0]
    assert shard_span["name"] == "executor.map_shard"
    assert shard_span["meta"]["shard"] == 7
    assert d["duration_ms"] >= d["children"][0]["duration_ms"] >= 0
    # events are zero-duration point annotations
    assert d["children"][1]["duration_ms"] == 0


def test_tracer_off_is_nop_and_allocates_nothing():
    tr = Tracer(sample_rate=0.0)
    before = trace.span_count()
    sp = tr.trace("query")
    assert sp is trace.NOP_SPAN
    with sp:
        assert trace.current() is None
        assert trace.child("executor") is trace.NOP_SPAN
        sp.event("x")
        assert sp.child("y") is sp
    assert trace.span_count() == before
    assert tr.recent() == []


def test_tracer_sampling(monkeypatch):
    import random

    tr = Tracer(sample_rate=0.5)
    monkeypatch.setattr(random, "random", lambda: 0.9)
    assert tr.trace("query") is trace.NOP_SPAN  # 0.9 >= 0.5 -> dropped
    monkeypatch.setattr(random, "random", lambda: 0.1)
    with tr.trace("query"):
        pass
    assert len(tr.recent()) == 1


def test_slow_query_hook_fires_with_span_tree():
    tr = Tracer()
    tr.slow_threshold = 1e-9  # everything is slow
    seen = []
    tr.on_slow = seen.append
    with tr.trace("query") as root:  # threshold > 0 => always traced
        with root.child("executor"):
            time.sleep(0.001)
    assert seen and seen[0]["name"] == "query"
    assert seen[0]["children"][0]["name"] == "executor"
    # under-threshold queries record to the ring but don't fire the hook
    tr.slow_threshold = 60.0
    with tr.trace("query"):
        pass
    assert len(seen) == 1
    assert len(tr.recent()) == 2


def test_activate_adopts_span_across_contexts():
    tr = Tracer()
    with tr.trace("query", force=True) as root:
        pass
    assert trace.current() is None
    with trace.activate(root):
        assert trace.current() is root
        trace.child("late")
    assert trace.current() is None
    assert root.children[-1].name == "late"
    # activating None is a no-op
    with trace.activate(None):
        assert trace.current() is None


# -- end-to-end: profile=true, overhead bound -------------------------------


def _seed_two_shards(server, index="obs"):
    req(server, "POST", f"/index/{index}", {})
    req(server, "POST", f"/index/{index}/field/f", {})
    rows, cols = [], []
    for r in range(4):
        for c in range(6):
            rows.append(r)
            cols.append(c * 17 + r)
            rows.append(r)
            cols.append(SHARD_WIDTH + c * 13 + r)
    st, _ = req(
        server,
        "POST",
        f"/index/{index}/field/f/import",
        {"rowIDs": rows, "columnIDs": cols},
    )
    assert st == 200
    req(server, "POST", "/recalculate-caches")


def _span_names(d, out):
    out.add(d["name"])
    for c in d.get("children", []):
        _span_names(c, out)
    return out


def test_profile_query_returns_span_tree(server):
    _seed_two_shards(server)
    st, body = req(
        server, "POST", "/index/obs/query?profile=true", b"Count(Row(f=1))"
    )
    assert st == 200, body
    prof = body["profile"]
    assert prof["name"] == metrics.STAGE_QUERY
    assert prof["duration_ms"] > 0
    names = _span_names(prof, set())
    # acceptance: at least executor, per-shard map, device-routing stages
    assert metrics.STAGE_EXECUTOR in names
    assert metrics.STAGE_MAP_SHARD in names or metrics.STAGE_DEVICE_BATCH in names
    assert metrics.STAGE_ROUTE in names
    # every stage name in the tree is documented (satellite: stage names
    # match the documented set)
    assert names <= set(metrics.STAGES)

    # a TopN over a source bitmap profiles through the scoring stages too
    st, body = req(
        server, "POST", "/index/obs/query?profile=true", b"TopN(f, Row(f=1), n=2)"
    )
    assert st == 200, body
    names = _span_names(body["profile"], set())
    assert names <= set(metrics.STAGES)

    # without profile=true the response carries no profile key
    st, body = req(server, "POST", "/index/obs/query", b"Count(Row(f=1))")
    assert st == 200 and "profile" not in body


def test_untraced_hot_path_creates_no_spans(server):
    """Acceptance overhead bound: sampling off => the instrumented hot
    path allocates zero Span objects (a single branch per shard)."""
    _seed_two_shards(server, index="noov")
    # warm once so lazy pools/jits don't muddy the probe
    req(server, "POST", "/index/noov/query", b"Count(Row(f=1))")
    before = trace.span_count()
    st, body = req(
        server,
        "POST",
        "/index/noov/query",
        b"Count(Row(f=1)) TopN(f, Row(f=2), n=2) Row(f=3)",
    )
    assert st == 200, body
    assert trace.span_count() == before


# -- /metrics ---------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^(?:"
    r"# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*(?: .*)?"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{.*\})? (?:-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|NaN)"
    r")$"
)


def _assert_prometheus_text(text: str) -> None:
    families = []
    for line in text.strip().split("\n"):
        m = _PROM_LINE.match(line)
        assert m, f"invalid exposition line: {line!r}"
        if line.startswith("# TYPE "):
            families.append(line.split()[2])
    # one TYPE declaration per family
    assert len(families) == len(set(families))


def test_metrics_endpoint_prometheus_exposition(server):
    _seed_two_shards(server, index="pm")
    for q in (
        b"Count(Row(f=1))",
        b"TopN(f, Row(f=1), n=2)",
        b"Row(f=0)",
    ):
        st, body = req(server, "POST", "/index/pm/query", q)
        assert st == 200, body
    # exercise the CPU routing leg too, so both route families export
    server.executor.device_policy = "never"
    try:
        st, body = req(server, "POST", "/index/pm/query", b"Count(Row(f=2))")
        assert st == 200, body
    finally:
        server.executor.device_policy = "always"
    st, raw = req(server, "GET", "/metrics", raw=True)
    assert st == 200
    text = raw.decode()
    _assert_prometheus_text(text)
    # acceptance: query counters by call type
    assert 'pilosa_executor_calls{call="Count"}' in text
    assert 'pilosa_executor_calls{call="TopN"}' in text
    # device-vs-CPU routing counters, one family per decision outcome
    assert "pilosa_executor_route_device{" in text
    assert "pilosa_executor_route_cpu{" in text
    # batcher batch-size histogram (the 2-shard TopN coalesces through
    # the stacked scorer)
    assert "pilosa_batcher_batch_size_count" in text
    assert "pilosa_batcher_batch_size{quantile=" in text
    # cache hit/miss (TopN pass 2 consults the rank cache by id)
    assert "pilosa_cache_hits" in text or "pilosa_cache_misses" in text
    # server-level expvar stats merge in with their quantiles
    assert 'pilosa_query_time{index="pm",quantile="0.5"}' in text
    # scrape-time gauges
    assert "pilosa_stager_bytes" in text


def test_render_prometheus_escapes_labels():
    reg = metrics.Registry()
    reg.count("executor.calls", call='we"ird\\na{me}')
    text = metrics.render_prometheus(registry=reg)
    _assert_prometheus_text(text)
    assert '\\"' in text


def test_log_histogram_quantiles_monotonic():
    h = metrics.LogHistogram()
    for v in (0.001, 0.002, 0.004, 0.1, 2.0, 30.0):
        h.observe(v)
    s = h.summary()
    assert s["count"] == 6
    assert s["min"] == 0.001 and s["max"] == 30.0
    assert s["min"] <= s["p50"] <= s["p95"] <= s["p99"] <= s["max"]


# -- /debug/traces + /debug/vars -------------------------------------------


def test_debug_traces_ring_and_sampled_server(tmp_path):
    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        trace_sample_rate=1.0,
        device_timeout=0,
    )
    s = Server(cfg)
    s.open()
    try:
        req(s, "POST", "/index/tr", {})
        req(s, "POST", "/index/tr/field/f", {})
        req(s, "POST", "/index/tr/query", b"Set(1, f=1)")
        st, body = req(s, "POST", "/index/tr/query", b"Count(Row(f=1))")
        assert st == 200 and body["results"] == [1]
        st, body = req(s, "GET", "/debug/traces")
        assert st == 200 and body["traces"]
        assert body["traces"][-1]["name"] == "query"
        names = _span_names(body["traces"][-1], set())
        assert metrics.STAGE_EXECUTOR in names
    finally:
        s.close()


def test_debug_vars_lit_with_statsd_sink(tmp_path):
    """satellite: metric='statsd' must not darken /debug/vars — the
    server always keeps an in-process expvar client and fans out."""
    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="statsd",
        metric_host="127.0.0.1:8125",
        device_timeout=0,
    )
    s = Server(cfg)
    s.open()
    try:
        req(s, "POST", "/index/sv", {})
        req(s, "POST", "/index/sv/field/f", {})
        req(s, "POST", "/index/sv/query", b"Set(1, f=1)")
        req(s, "POST", "/index/sv/query", b"Count(Row(f=1))")
        st, body = req(s, "GET", "/debug/vars")
        assert st == 200
        qt = [k for k in body if k.startswith("query_time")]
        assert qt, f"/debug/vars dark under statsd sink: {sorted(body)[:10]}"
        # percentile summary shape (satellite: actionable timings)
        h = body[qt[0]]
        assert {"count", "sum", "min", "max", "p50", "p95", "p99"} <= set(h)
        # registry snapshot rides along
        assert any(k.startswith("executor.calls") for k in body["metrics"])
    finally:
        s.close()


# -- docs drift guard -------------------------------------------------------


def _doc_table_names(section: str) -> dict:
    path = os.path.join(
        os.path.dirname(__file__), "..", "docs", "administration.md"
    )
    with open(path) as f:
        text = f.read()
    assert section in text, f"docs/administration.md lost section {section!r}"
    chunk = text.split(section, 1)[1]
    # stop at the next heading
    chunk = re.split(r"\n#{2,3} ", chunk)[0]
    rows = re.findall(r"^\| `([^`]+)` \|(?: ([a-z]+) \|)?", chunk, re.M)
    return {name: typ for name, typ in rows}


def test_docs_metric_table_in_sync_with_registry():
    """Every metric name emitted in code is in the docs table, and the
    docs table names only metrics that exist — both directions."""
    doc = _doc_table_names("### Metric reference")
    code = {name: typ for name, (typ, _) in metrics.METRICS.items()}
    assert set(doc) == set(code), (
        f"docs-only: {set(doc) - set(code)}; code-only: {set(code) - set(doc)}"
    )
    for name, typ in code.items():
        assert doc[name] == typ, f"{name}: docs say {doc[name]}, code says {typ}"


def test_docs_stage_table_in_sync_with_registry():
    doc = _doc_table_names("### Trace stages")
    assert set(doc) == set(metrics.STAGES), (
        f"docs-only: {set(doc) - set(metrics.STAGES)}; "
        f"code-only: {set(metrics.STAGES) - set(doc)}"
    )
