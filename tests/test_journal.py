"""Durable event journal (ISSUE 16): segmented on-disk backing behind
the process-global ring — crash-consistent framing (torn tails
truncated at open), monotonic sequence numbers across restart, whole-
segment retention, IO-failure demotion to ring-only, and the
/debug/events paging that rides it.

Server-level pieces run against a real in-process server on :0 under
JAX_PLATFORMS=cpu (the tier-1 environment)."""

import json
import os
import urllib.request

import pytest

from pilosa_tpu.server import Config, Server
from pilosa_tpu.utils import events, metrics


@pytest.fixture(autouse=True)
def _clean_globals():
    events.JOURNAL.clear()
    yield
    events.JOURNAL.close_backing()
    events.JOURNAL.clear()
    events.JOURNAL.on_record = None


def _segments(directory):
    return sorted(
        f for f in os.listdir(directory) if f.startswith("events-")
    )


# -- durable roundtrip --------------------------------------------------------


def _open(tmp_path, **kw):
    j = events.EventJournal()
    j.open_backing(str(tmp_path), kw.pop("max_bytes", 1 << 20), **kw)
    return j


def test_roundtrip_and_monotonic_seq(tmp_path):
    j = _open(tmp_path)
    assert j.durable
    for i in range(5):
        j.record("gang.transition", frm="A", to="B", i=i)
    assert j.record("gang.degrade")["seq"] == 6
    j.close_backing()
    assert not j.durable
    # a NEW journal (fresh process) resumes from the durable tail
    j2 = _open(tmp_path)
    snap = j2.snapshot()
    assert [e["seq"] for e in snap] == [1, 2, 3, 4, 5, 6]
    assert snap[0]["kind"] == "gang.transition" and snap[0]["i"] == 0
    # seq continues monotonically — never reused, never reset
    assert j2.record("gang.reform")["seq"] == 7
    j2.close_backing()


def test_torn_tail_truncated_at_reopen(tmp_path):
    j = _open(tmp_path)
    for i in range(3):
        j.record("ingest.wave", i=i)
    j.close_backing()
    (seg,) = _segments(tmp_path)
    path = os.path.join(str(tmp_path), seg)
    clean = os.path.getsize(path)
    # simulate a SIGKILL mid-append: a frame header promising more
    # bytes than were ever written
    with open(path, "ab") as f:
        f.write(b"\xff\xff\x00\x00garbage")
    j2 = _open(tmp_path)
    assert [e["seq"] for e in j2.snapshot()] == [1, 2, 3]
    assert os.path.getsize(path) == clean  # tail gone from disk
    # appends after recovery are clean and readable
    j2.record("ingest.wave", i=3)
    j2.close_backing()
    j3 = _open(tmp_path)
    assert [e["seq"] for e in j3.snapshot()] == [1, 2, 3, 4]
    j3.close_backing()


def test_corrupt_checksum_stops_the_scan(tmp_path):
    j = _open(tmp_path)
    for i in range(3):
        j.record("ingest.wave", i=i)
    j.close_backing()
    (seg,) = _segments(tmp_path)
    path = os.path.join(str(tmp_path), seg)
    data = bytearray(open(path, "rb").read())
    data[-1] ^= 0xFF  # flip one payload byte of the LAST record
    with open(path, "wb") as f:
        f.write(data)
    j2 = _open(tmp_path)
    assert [e["seq"] for e in j2.snapshot()] == [1, 2]
    j2.close_backing()


def test_retention_drops_whole_oldest_segments(tmp_path):
    # roll at max(64 KiB, max_bytes/8); ~1 KiB records roll segments
    # quickly enough to exercise pruning end-to-end
    j = _open(tmp_path, max_bytes=128 << 10)
    pad = "x" * 1024
    for i in range(300):
        j.record("ingest.wave", i=i, pad=pad)
    segs = _segments(tmp_path)
    assert len(segs) >= 2  # rolled at least once
    total = sum(
        os.path.getsize(os.path.join(str(tmp_path), s)) for s in segs
    )
    assert total <= (128 << 10) + j._roll_bytes()
    j.close_backing()
    # the oldest records are gone from disk, the newest survive
    j2 = _open(tmp_path, max_bytes=128 << 10)
    seqs = [e["seq"] for e in j2.snapshot()]
    assert seqs and seqs[0] > 1 and seqs[-1] == 300
    assert seqs == sorted(seqs)
    j2.close_backing()


def test_append_failure_demotes_to_ring_only(tmp_path):
    j = _open(tmp_path)
    j.record("ingest.wave", i=0)
    before = sum(
        v
        for k, v in metrics.snapshot().items()
        if k.startswith(metrics.JOURNAL_ERRORS)
    )
    j._seg_f.close()  # yank the handle out from under the journal
    d = j.record("ingest.wave", i=1)  # must not raise
    assert d["seq"] == 2
    assert not j.durable  # demoted
    assert [e["i"] for e in j.snapshot()] == [0, 1]  # ring kept both
    after = sum(
        v
        for k, v in metrics.snapshot().items()
        if k.startswith(metrics.JOURNAL_ERRORS)
    )
    assert after == before + 1


def test_ring_entries_predating_the_backing_survive(tmp_path):
    j = events.EventJournal()
    j.record("gang.degrade")  # ring-only era
    j.open_backing(str(tmp_path), 1 << 20)
    j.record("gang.reform")
    snap = j.snapshot()
    assert [e["kind"] for e in snap] == ["gang.degrade", "gang.reform"]
    assert [e["seq"] for e in snap] == [1, 2]
    j.close_backing()


def test_since_seq_pages_past_the_ring(tmp_path):
    j = events.EventJournal(ring_size=8)
    j.open_backing(str(tmp_path), 1 << 20)
    for i in range(40):
        j.record("ingest.wave", i=i)
    # the ring only holds the last 8, but the disk merge pages back
    assert [e["seq"] for e in j.snapshot(since_seq=10)] == list(range(11, 41))
    assert len(j.snapshot(kind="ingest.wave")) == 40
    j.close_backing()


def test_open_backing_disabled_by_zero_budget(tmp_path):
    j = events.EventJournal()
    j.open_backing(str(tmp_path), 0)
    assert not j.durable
    j.record("gang.degrade")
    assert _segments(tmp_path) == []


# -- server wiring ------------------------------------------------------------


def req(server, method, path, body=None):
    url = server.uri + path
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(r) as resp:
        return resp.status, json.loads(resp.read() or b"{}")


def test_server_opens_backing_and_seq_survives_reboot(tmp_path):
    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="expvar",
        device_policy="always",
        device_timeout=0,
    )
    s = Server(cfg)
    s.open()
    try:
        assert events.JOURNAL.durable
        d = events.record("chaos.window", mode="install")
        seq1 = d["seq"]
        st, body = req(s, "GET", f"/debug/events?since={seq1 - 1}")
        assert st == 200
        assert any(e["seq"] == seq1 for e in body["events"])
    finally:
        s.close()
    assert not events.JOURNAL.durable  # close detached the backing
    # same data dir: the journal resumes past every durable record
    s2 = Server(cfg)
    s2.open()
    try:
        assert events.JOURNAL.durable
        d2 = events.record("chaos.window", mode="clear")
        assert d2["seq"] > seq1
        st, body = req(s2, "GET", f"/debug/events?since={seq1}")
        assert any(
            e["seq"] == d2["seq"] and e["mode"] == "clear"
            for e in body["events"]
        )
    finally:
        s2.close()
    # default journal dir rides under the data dir
    assert _segments(str(tmp_path / "data" / ".events"))
