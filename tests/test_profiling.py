"""Always-on performance attribution (ISSUE 12): the waterfall
taxonomy + attribution layer, the continuous stack sampler, compile
tracking with storm detection, HBM telemetry gating, SLO burn-rate
monitoring, and the server surfaces (/debug/latency, /debug/profile,
/debug/slo, profile=waterfall, uptime gauges, fleet scrape).

Server-level pieces run against a real in-process server on :0 under
JAX_PLATFORMS=cpu (the tier-1 environment)."""

import io
import json
import threading
import time
import urllib.request
from types import SimpleNamespace

import pytest

from pilosa_tpu.server import Config, Server
from pilosa_tpu.utils import (
    events,
    logger as logger_mod,
    metrics,
    profiler,
    slo,
    trace,
)
from pilosa_tpu.utils.profiler import (
    CompileTracker,
    DeviceTelemetry,
    StackSampler,
    WaterfallAggregator,
)
from pilosa_tpu.utils.slo import SLOMonitor, parse_objectives


@pytest.fixture()
def server(tmp_path):
    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="expvar",
        device_policy="always",
        device_timeout=0,
    )
    s = Server(cfg)
    s.open()
    yield s
    s.close()


@pytest.fixture(autouse=True)
def _clean_globals():
    """The profiler singletons and journal are process-global; every
    test starts and ends clean."""
    events.JOURNAL.clear()
    profiler.WATERFALL.clear()
    profiler.COMPILES.clear()
    slo.MONITOR.clear()
    yield
    events.JOURNAL.clear()
    profiler.WATERFALL.clear()
    profiler.COMPILES.clear()
    profiler.SAMPLER.stop()
    profiler.SAMPLER.clear()
    slo.MONITOR.configure(parse_objectives(slo.DEFAULT_OBJECTIVES))
    slo.MONITOR.clear()
    logger_mod.set_context_provider(None)


def req(server, method, path, body=None, raw=False):
    url = server.uri + path
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(r) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload or b"{}")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, payload if raw else json.loads(payload or b"{}")


def _seed(server, index="pf"):
    req(server, "POST", f"/index/{index}", {})
    req(server, "POST", f"/index/{index}/field/f", {})
    req(server, "POST", f"/index/{index}/query", b"Set(1, f=1)")


# -- taxonomy completeness ----------------------------------------------------


def test_waterfall_taxonomy_covers_every_span_stage():
    """Every span stage the tracer can record maps into a waterfall
    bucket, and the mapping names only real buckets — a new stage can't
    silently fall outside the attribution taxonomy (and the mapping
    can't rot to stages that no longer exist)."""
    span_stages = set(metrics.STAGES)
    mapped = set(trace.WATERFALL_OF)
    assert span_stages - mapped == set(), "span stages missing a bucket"
    assert mapped - span_stages == set(), "mapping names unknown span stages"
    assert set(trace.WATERFALL_OF.values()) <= set(trace.WATERFALL_STAGES)
    # every bucket is documented for /debug/latency
    assert set(trace.WATERFALL) == set(trace.WATERFALL_STAGES)


# -- attribution layer --------------------------------------------------------


def test_attrib_add_is_noop_without_context():
    assert trace.attrib_current() is None
    trace.attrib_add(trace.WF_REDUCE, 1.0)  # must not raise
    assert trace.attrib_current() is None


def test_attrib_activate_reenters_on_worker_thread():
    """Pool submitters capture the dict once and re-enter it in the
    worker — legs measured on the worker land in the submitter's
    waterfall."""
    wf: dict = {}
    with trace.attrib_activate(wf):
        trace.attrib_add(trace.WF_PLAN_CANON, 0.25)
        captured = trace.attrib_current()

        def worker():
            with trace.attrib_activate(captured):
                trace.attrib_add(trace.WF_DEVICE_COMPUTE, 0.5)

        t = threading.Thread(target=worker)
        t.start()
        t.join(5)
    assert wf == {trace.WF_PLAN_CANON: 0.25, trace.WF_DEVICE_COMPUTE: 0.5}
    # activation nests and restores: no ctx leaks out of the with
    assert trace.attrib_current() is None


def test_waterfall_summarize_sums_to_total():
    """The rendered stages (including the synthetic `other`) partition
    the end-to-end latency exactly, and device+transfer legs set
    rtt_fraction."""
    wf = {
        trace.WF_PLAN_CANON: 0.010,
        trace.WF_DEVICE_COMPUTE: 0.060,
        trace.WF_TRANSFER_DECODE: 0.010,
        "_wave": 7,
    }
    s = WaterfallAggregator.summarize(wf, 0.100)
    assert s["total_ms"] == 100.0
    assert abs(sum(s["stages"].values()) - s["total_ms"]) < 1e-6
    assert s["stages"]["other"] == pytest.approx(20.0, abs=1e-6)
    assert s["rtt_fraction"] == pytest.approx(0.7)
    assert s["wave"] == 7
    # stage order follows the taxonomy, zero stages are skipped
    order = [st for st in trace.WATERFALL_STAGES if st in s["stages"]]
    assert list(s["stages"]) == order
    # degenerate total: no division blow-ups
    z = WaterfallAggregator.summarize({}, 0.0)
    assert z["rtt_fraction"] == 0.0 and z["stages"] == {}


def test_waterfall_aggregator_ring_ema_and_metrics():
    agg = WaterfallAggregator(ring_size=3)
    for i in range(5):
        agg.record("interactive", 0.010, {trace.WF_DEVICE_COMPUTE: 0.005})
    snap = agg.snapshot()
    assert len(snap["recent"]) == 3 and snap["recorded"] == 5
    assert snap["rtt_fraction"] == pytest.approx(0.5)
    assert snap["recent"][-1]["cls"] == "interactive"
    assert agg.snapshot(limit=1)["recent"][-1] == snap["recent"][-1]
    # the per-stage summary landed in the registry, labeled cls+stage
    ms = metrics.snapshot()
    assert any(
        k.startswith(metrics.LATENCY_STAGE_SECONDS)
        and "cls:interactive" in k
        and "stage:device.compute" in k
        for k in ms
    )
    assert agg.record("interactive", 0.01, None) is None  # no attribution ran
    agg.clear()
    assert agg.snapshot()["recorded"] == 0


def test_executor_attributes_device_and_transfer_legs(server):
    """A multi-shard device-path query lands device.compute (fenced
    kernel) and transfer.decode legs in an active attribution ctx —
    the waterfall reflects the live serving path, not a side probe."""
    from pilosa_tpu import SHARD_WIDTH

    _seed(server, index="dev")
    for sh in range(3):
        req(server, "POST", "/index/dev/query", b"Set(%d, f=1)" % (sh * SHARD_WIDTH + 5))
        req(server, "POST", "/index/dev/query", b"Set(%d, f=2)" % (sh * SHARD_WIDTH + 9))
    server.executor.execute("dev", "Count(Row(f=1))")  # warm jits
    wf: dict = {}
    with trace.attrib_activate(wf):
        res = server.executor.execute("dev", "Count(Union(Row(f=1), Row(f=2)))")
    assert res == [7]  # {1, 5, SW+5, 2SW+5} ∪ {9, SW+9, 2SW+9}
    assert wf.get(trace.WF_DEVICE_COMPUTE, 0.0) > 0.0
    assert wf.get(trace.WF_TRANSFER_DECODE, 0.0) > 0.0
    assert set(wf) - {"_wave"} <= set(trace.WATERFALL_STAGES)
    # the compile tracker saw the jit wrap for this plan signature
    comp = profiler.COMPILES.snapshot()
    assert comp["total_compiles"] >= 1
    assert any(r["kind"] == "tree_count" for r in comp["signatures"])


# -- compile tracking ---------------------------------------------------------


def test_compile_tracker_counts_forced_recompile():
    ct = CompileTracker()
    ct.note("tree_count", "sig-a", 0.5)
    # a dropped jit cache forces a recompile of the SAME signature: the
    # tracker must show 2 compiles for one plan shape
    ct.note("tree_count", "sig-a", 0.25)
    ct.note("topn", "sig-b", 0.1)
    snap = ct.snapshot()
    assert snap["total_compiles"] == 3
    assert snap["total_seconds"] == pytest.approx(0.85)
    row = next(r for r in snap["signatures"] if r["signature"] == "tree_count:'sig-a'")
    assert row["compiles"] == 2 and row["seconds"] == pytest.approx(0.75)
    assert any(
        k.startswith(metrics.PROFILER_COMPILES) for k in metrics.snapshot()
    )


def test_compile_tracker_bounded_by_overflow_row():
    ct = CompileTracker(max_sigs=4)
    for i in range(10):
        ct.note("k", f"sig-{i}", 0.01)
    snap = ct.snapshot(top=100)
    assert len(snap["signatures"]) <= 5  # max_sigs + the overflow row
    over = next(r for r in snap["signatures"] if r["signature"] == "(overflow)")
    assert over["compiles"] == 6


def test_compile_storm_edge_triggered():
    ct = CompileTracker(storm_threshold=4, storm_window_s=30.0)
    for i in range(6):
        ct.note("k", f"s{i}", 0.01)
    assert ct.storms == 1  # fires once per episode, not per compile
    evs = events.snapshot(kind=events.PROFILER_RECOMPILE_STORM)
    assert len(evs) == 1 and evs[0]["window_s"] == 30.0


# -- continuous stack sampler -------------------------------------------------


def _fake_frame(name, filename="x.py", lineno=1):
    code = SimpleNamespace(co_name=name, co_filename=filename)
    return SimpleNamespace(f_code=code, f_lineno=lineno, f_back=None)


def test_stack_sampler_aggregates_and_bounds_memory(monkeypatch):
    sam = StackSampler(hz=10.0, max_keys=4, frame_depth=2)
    calls = {"n": 0}

    def frames():
        calls["n"] += 1
        # more distinct stacks than max_keys: overflow must fold
        return {i: _fake_frame(f"fn{calls['n']}_{i}") for i in range(8)}

    monkeypatch.setattr(profiler, "_current_frames", frames)
    sam.sample_once()
    sam.sample_once()
    snap = sam.snapshot()
    assert snap["samples"] == 2
    assert snap["keys"] <= 5  # max_keys + "(other)"
    other = next(r for r in snap["top"] if r["frames"] == "(other)")
    assert other["count"] > 0
    sam.clear()
    assert sam.snapshot()["samples"] == 0


def test_stack_sampler_start_stop_lifecycle():
    sam = StackSampler(hz=200.0)
    assert not sam.running
    sam.start()
    assert sam.running
    deadline = time.monotonic() + 5
    while sam.samples == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    sam.stop()
    assert not sam.running
    assert sam.samples > 0
    # the sampler skips its own thread; real frames aggregate
    assert any(r["count"] > 0 for r in sam.top(5))
    n = sam.samples
    time.sleep(0.03)
    assert sam.samples == n  # stopped means stopped
    # hz<=0 never starts a thread (the config off-switch)
    off = StackSampler(hz=0.0)
    off.start()
    assert not off.running


# -- device telemetry ---------------------------------------------------------


def test_device_telemetry_cpu_backend_degrades_to_no_samples():
    tel = DeviceTelemetry()
    snap = tel.poll_once()  # CPU backend: no memory_stats — no error
    assert snap["devices"] == {}
    assert "stager" not in snap


def test_device_telemetry_gauges_and_watermark_event(monkeypatch):
    tel = DeviceTelemetry(watermark_pct=0.8)
    stats = {"bytes_in_use": 900, "bytes_limit": 1000, "peak_bytes_in_use": 950}
    monkeypatch.setattr(tel, "_device_stats", lambda: [("tpu:0", stats)])
    tel.stager_probe = lambda: (250, 1000)
    snap = tel.poll_once()
    dev = snap["devices"]["tpu:0"]
    assert dev["fraction"] == 0.9 and dev["peak_bytes"] == 950
    assert snap["stager"]["fraction"] == 0.25
    ms = metrics.snapshot()
    for name in (
        metrics.HBM_BYTES_IN_USE,
        metrics.HBM_PEAK_BYTES,
        metrics.HBM_BYTES_LIMIT,
    ):
        assert any(k.startswith(name) and "tpu:0" in k for k in ms)
    assert any(k.startswith(metrics.HBM_STAGER_FRACTION) for k in ms)
    # watermark is edge-triggered: above, above, below, above → 2 events
    tel.poll_once()
    stats["bytes_in_use"] = 100
    tel.poll_once()
    stats["bytes_in_use"] = 950
    tel.poll_once()
    evs = events.snapshot(kind=events.PROFILER_HBM_WATERMARK)
    assert len(evs) == 2
    assert evs[0]["device"] == "tpu:0" and evs[0]["fraction"] == 0.9


# -- SLO burn-rate monitoring -------------------------------------------------


def test_parse_objectives():
    assert parse_objectives("interactive=250@0.999") == {
        "interactive": (0.25, 0.999)
    }
    # malformed entries are skipped, not fatal; target defaults to 0.999
    out = parse_objectives("a=100, garbage, b=oops@0.9, c=50@2.0, d=200@0.99")
    assert out == {"a": (0.1, 0.999), "d": (0.2, 0.99)}
    # a spec that parses to nothing falls back to the defaults
    assert parse_objectives("total-garbage") == parse_objectives(
        slo.DEFAULT_OBJECTIVES
    )
    assert parse_objectives("") == {}


def test_slo_burn_fires_on_both_windows_with_cooldown():
    mon = SLOMonitor(
        objectives={"interactive": (0.1, 0.999)}, burn_threshold=14.4
    )
    t0 = 10_000.0
    # injected latency: every query blows the 100ms objective
    for i in range(20):
        mon.record("interactive", duration_s=1.0, ok=True, now=t0 + i)
    fired = mon.tick(now=t0 + 21)
    assert len(fired) == 1
    ev = fired[0]
    assert ev["kind"] == events.SLO_BURN and ev["cls"] == "interactive"
    assert ev["burn_5m"] >= 14.4 and ev["burn_1h"] >= 14.4
    assert ev["latency_ms"] == 100.0
    # edge-triggered: still burning → no second event
    assert mon.tick(now=t0 + 22) == []
    snap = mon.snapshot(now=t0 + 22)
    st = snap["classes"]["interactive"]
    assert st["firing"] is True and st["budget_remaining"] == 0.0
    assert st["samples"] == {"good": 0, "bad": 20}
    # recovery: enough good traffic drops both windows below threshold
    for i in range(20_000):
        mon.record("interactive", duration_s=0.01, ok=True, now=t0 + 23 + i % 280)
    assert mon.tick(now=t0 + 300) == []
    assert mon.snapshot(now=t0 + 300)["classes"]["interactive"]["firing"] is False
    assert any(k.startswith(metrics.SLO_BURNS) for k in metrics.snapshot())


def test_slo_short_window_alone_does_not_fire():
    """A brief blip trips the 5m window but not the 1h window — no
    alert (the long window proves it matters)."""
    mon = SLOMonitor(objectives={"interactive": (0.1, 0.99)}, burn_threshold=10.0)
    t0 = 50_000.0
    # an hour of good traffic, then a 30-second blip of failures
    for i in range(0, 3500, 10):
        mon.record("interactive", 0.01, ok=True, now=t0 + i)
    for i in range(30):
        mon.record("interactive", 1.0, ok=False, now=t0 + 3500 + i)
    rates = mon.burn_rates(now=t0 + 3531)["interactive"]
    assert rates["5m"] > 10.0 > rates["1h"]
    assert mon.tick(now=t0 + 3531) == []


def test_slo_4xx_is_not_budget_burn(server):
    """Client errors are the client's fault: a 400 parse error must not
    consume availability budget (ok=True accounting path)."""
    _seed(server, index="slo4")
    st, _ = req(server, "POST", "/index/slo4/query", b"NotAFunction(")
    assert st == 400
    snap = slo.MONITOR.snapshot()
    for cls in snap["classes"].values():
        assert cls["samples"]["bad"] == 0


# -- server surfaces ----------------------------------------------------------


def test_query_profile_waterfall_param(server):
    _seed(server, index="wfq")
    req(server, "POST", "/index/wfq/query", b"Count(Row(f=1))")  # warm
    st, body = req(
        server, "POST", "/index/wfq/query?profile=waterfall", b"Count(Row(f=1))"
    )
    assert st == 200 and body["results"] == [1]
    wf = body["profile"]["waterfall"]
    assert wf["total_ms"] > 0.0
    # stages partition the total (each stage rounded to 1µs in the
    # response, so allow one rounding step per stage)
    assert abs(sum(wf["stages"].values()) - wf["total_ms"]) < 0.001 * (
        len(wf["stages"]) + 1
    )
    assert set(wf["stages"]) <= set(trace.WATERFALL_STAGES)
    assert 0.0 <= wf["rtt_fraction"] <= 1.0
    # plain queries don't carry the split (but are still aggregated)
    st, body = req(server, "POST", "/index/wfq/query", b"Count(Row(f=1))")
    assert st == 200 and "profile" not in body and "_waterfall" not in body


def test_debug_latency_endpoint(server):
    _seed(server, index="lat")
    for _ in range(3):
        req(server, "POST", "/index/lat/query", b"Count(Row(f=1))")
    st, body = req(server, "GET", "/debug/latency")
    assert st == 200
    assert body["recorded"] >= 3
    assert set(body["stages"]) == set(trace.WATERFALL_STAGES)
    assert body["recent"] and body["recent"][-1]["total_ms"] > 0
    assert body["rtt_fraction"] is not None
    # per-class/per-stage histograms ride the registry
    assert any(
        k.startswith(metrics.LATENCY_STAGE_SECONDS) and "stage:" in k
        for k in body["summary"]
    )
    st, body2 = req(server, "GET", "/debug/latency?limit=1")
    assert st == 200 and len(body2["recent"]) == 1
    st, _ = req(server, "GET", "/debug/latency?limit=bogus")
    assert st == 400


def test_debug_profile_endpoint(server):
    st, body = req(server, "GET", "/debug/profile")
    assert st == 200
    assert body["sampler"]["running"] is True  # always-on by default
    assert body["sampler"]["hz"] == server.config.profiler_hz
    assert "compiles" in body and "hbm" in body
    assert body["capture"]["running"] is False
    # capture control: stop with nothing running reports, never raises
    st, body = req(server, "GET", "/debug/profile?capture=stop")
    assert st == 200 and body["capture"]["ok"] is False
    st, _ = req(server, "GET", "/debug/profile?capture=bogus")
    assert st == 400
    st, _ = req(server, "GET", "/debug/profile?top=bogus")
    assert st == 400


def test_debug_slo_endpoint_and_burn_event(server):
    _seed(server, index="slos")
    req(server, "POST", "/index/slos/query", b"Count(Row(f=1))")
    st, body = req(server, "GET", "/debug/slo")
    assert st == 200
    assert body["burn_threshold"] == server.config.slo_burn_threshold
    inter = body["classes"]["interactive"]
    assert inter["samples"]["good"] >= 1
    # injected latency: force the interactive class over budget in both
    # windows, then let the scrape-path tick fire the burn event
    now = time.monotonic()
    for i in range(50):
        slo.MONITOR.record("interactive", duration_s=5.0, ok=True, now=now - i)
    st, body = req(server, "GET", "/debug/slo")
    assert st == 200 and body["classes"]["interactive"]["firing"] is True
    evs = events.snapshot(kind=events.SLO_BURN)
    assert evs and evs[-1]["cls"] == "interactive"
    st, body = req(server, "GET", "/debug/events?kind=slo.burn")
    assert st == 200 and body["events"]


def test_debug_events_limit_param(server):
    for i in range(5):
        events.record(events.GANG_DEGRADE, reason=f"r{i}")
    st, body = req(server, "GET", "/debug/events?limit=2")
    assert st == 200 and len(body["events"]) == 2
    # limit keeps the NEWEST entries
    assert [e["reason"] for e in body["events"]] == ["r3", "r4"]
    st, _ = req(server, "GET", "/debug/events?limit=bogus")
    assert st == 400


def test_uptime_and_start_time_gauges(server):
    st, raw = req(server, "GET", "/metrics", raw=True)
    assert st == 200
    text = raw.decode()
    lines = {
        l.split(" ")[0]: float(l.split(" ")[1])
        for l in text.splitlines()
        if l.startswith(("pilosa_uptime_seconds", "pilosa_process_start_time_seconds"))
    }
    assert lines["pilosa_uptime_seconds"] >= 0.0
    assert abs(lines["pilosa_process_start_time_seconds"] - time.time()) < 600


def test_fleet_scrape_carries_profile_and_slo_samples(server):
    """The PR 9 fleet scrape federates the new attribution samples:
    every profile/slo family appears instance-labeled per rank."""
    _seed(server, index="fl")
    req(server, "POST", "/index/fl/query", b"Count(Row(f=1))")
    req(server, "GET", "/metrics", raw=True)  # tick refreshes the slo gauges
    st, raw = req(server, "GET", "/metrics?fleet=true", raw=True)
    assert st == 200
    text = raw.decode()
    for family in (
        "pilosa_latency_stage_seconds",
        "pilosa_slo_burn_rate",
        "pilosa_executor_rtt_fraction",
        "pilosa_uptime_seconds",
    ):
        sample = [
            l
            for l in text.splitlines()
            if l.startswith(family) and not l.startswith("#")
        ]
        assert sample, f"{family} missing from fleet scrape"
        assert all(f'instance="{server.uri}"' in l for l in sample)


def test_logger_correlation_includes_dispatch_wave():
    from pilosa_tpu.utils.logger import StandardLogger

    buf = io.StringIO()
    lg = StandardLogger(stream=buf)
    tok = trace.set_wave(41)
    try:
        tr = trace.Tracer()
        with tr.trace("query", force=True):
            lg.printf("inside wave")
    finally:
        trace.reset_wave(tok)
    out = buf.getvalue().splitlines()[-1]
    assert "wave=41" in out and "trace=" in out
    # wave 0 (no wave) adds nothing
    lg.printf("outside")
    assert "wave=" not in buf.getvalue().splitlines()[-1]


# -- docs drift guard ---------------------------------------------------------


def _doc_table_names(section: str) -> dict:
    import os
    import re

    path = os.path.join(
        os.path.dirname(__file__), "..", "docs", "administration.md"
    )
    with open(path) as f:
        text = f.read()
    assert section in text, f"docs/administration.md lost section {section!r}"
    chunk = re.split(r"\n#{2,3} ", text.split(section, 1)[1])[0]
    rows = re.findall(r"^\| `([^`]+)` \|", chunk, re.M)
    return {name: None for name in rows}


def test_docs_waterfall_stage_table_in_sync():
    doc = set(_doc_table_names("### Waterfall stages"))
    code = set(trace.WATERFALL_STAGES)
    assert doc == code, f"docs-only: {doc - code}; code-only: {code - doc}"


def test_docs_event_kind_catalog_in_sync():
    doc = set(_doc_table_names("### Event kinds"))
    code = set(events.EVENT_KINDS)
    assert doc == code, f"docs-only: {doc - code}; code-only: {code - doc}"


# -- overhead gate ------------------------------------------------------------


@pytest.mark.slow
def test_attribution_overhead_gate(tmp_path):
    """Executor micro with sampler + attribution enabled stays within
    5% of disabled (interleaved rounds, min-of-rounds; the CI profiling
    step runs this explicitly — it is excluded from tier-1 as
    timing-sensitive)."""
    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="expvar",
        device_policy="always",
        device_timeout=0,
    )
    s = Server(cfg)
    s.open()
    try:
        s.api.create_index("ov")
        s.api.create_field("ov", "f", {})
        s.api.query("ov", "Set(1, f=1)")
        for _ in range(20):
            s.api.query("ov", "Count(Row(f=1))")  # warm

        def round_(attrib: bool, iters=60) -> float:
            t0 = time.perf_counter()
            for _ in range(iters):
                if attrib:
                    with trace.attrib_activate({}):
                        s.executor.execute("ov", "Count(Row(f=1))")
                else:
                    s.executor.execute("ov", "Count(Row(f=1))")
            return time.perf_counter() - t0

        # interleave base/instrumented rounds so a transient load spike
        # hits both sides, and take the min of each — scheduling noise
        # is strictly additive, so min is the honest per-iteration cost.
        # CI runners are still noisy, so best of up to 3 attempts.
        profiler.SAMPLER.hz = 10.0
        overhead = float("inf")
        for _ in range(3):
            base = instrumented = float("inf")
            for _ in range(9):
                profiler.SAMPLER.stop()
                base = min(base, round_(attrib=False))
                profiler.SAMPLER.start()
                try:
                    instrumented = min(instrumented, round_(attrib=True))
                finally:
                    profiler.SAMPLER.stop()
            overhead = min(overhead, instrumented / base - 1.0)
            if overhead < 0.05:
                break
        assert overhead < 0.05, f"attribution overhead {overhead:.1%} >= 5%"
    finally:
        s.close()
