"""Device-resident analytics (ISSUE 18): GroupBy / Distinct / Percentile.

Property tests against a pure-Python oracle built from the raw imported
data (never from the executor), bit-identity across the classic CPU
path, the shard-batched device path, and the fused segmented-reduction
path; plus the satellite regressions — exactly-one-fused-launch per
panel, heat-ledger attribution at the batched launch sites, plan-driven
prefetch of explicit GroupBy dims, quarantine's clean 503 through the
degrade ladder, bulk-class routing, and docs drift both directions.

Runs under JAX_PLATFORMS=cpu (the tier-1 environment)."""

import itertools
import json
import os
import time
import types
import urllib.request

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import FieldOptions, Holder
from pilosa_tpu.core.field import FIELD_TYPE_INT, FIELD_TYPE_TIME
from pilosa_tpu.core.fragment import FragmentQuarantinedError
from pilosa_tpu.executor import Executor, ValCount, analytics
from pilosa_tpu.pql import parse
from pilosa_tpu.utils import heat, metrics, slo


@pytest.fixture()
def holder():
    h = Holder()  # in-memory
    h.open()
    return h


def _counter(name: str) -> float:
    """Sum a counter family across labels from the global registry."""
    return sum(
        v
        for k, v in metrics.REGISTRY.snapshot().items()
        if isinstance(v, (int, float)) and str(k).split(";")[0] == name
    )


# -- oracle model -------------------------------------------------------------
#
# seed() imports random data and returns a shadow model maintained
# independently of the index: field -> row -> set(global column ids),
# plus the BSI value map. The oracle functions below compute every
# analytic result from that model alone.


def seed(holder, rng, name="i", nshards=3, ncols=3000, nseg=5, ndev=4,
         vmin=-50, vmax=900, val_frac=0.85):
    idx = holder.create_index(name)
    seg = idx.create_field("seg")
    dev = idx.create_field("dev")
    val = idx.create_field(
        "v", FieldOptions(type=FIELD_TYPE_INT, min=vmin, max=vmax)
    )
    cols = rng.choice(nshards * SHARD_WIDTH, size=ncols, replace=False)
    segrows = rng.integers(0, nseg, size=ncols)
    devrows = rng.integers(0, ndev, size=ncols)
    seg.import_bits(segrows.tolist(), cols.tolist())
    dev.import_bits(devrows.tolist(), cols.tolist())
    mask = rng.random(ncols) < val_frac
    vcols = cols[mask]
    vals = rng.integers(vmin, vmax + 1, size=len(vcols))
    val.import_values(vcols.tolist(), vals.tolist())
    model = {"seg": {}, "dev": {}, "vals": dict(zip(vcols.tolist(), vals.tolist()))}
    for r, c in zip(segrows.tolist(), cols.tolist()):
        model["seg"].setdefault(int(r), set()).add(int(c))
    for r, c in zip(devrows.tolist(), cols.tolist()):
        model["dev"].setdefault(int(r), set()).add(int(c))
    return model


def oracle_groupby(model, dims, filt=None, agg=False, limit=None):
    """dims: [(field, [row ids in final order])]. ``count`` is the size
    of the dim-row intersection (∩ filter); ``sum`` totals only columns
    holding a value (nulls count toward ``count``, never ``sum``)."""
    out = []
    for key in itertools.product(*[ids for _, ids in dims]):
        colsets = [model[f].get(r, set()) for (f, _), r in zip(dims, key)]
        cols = set.intersection(*colsets) if colsets else set()
        if filt is not None:
            cols &= filt
        if not cols:
            continue
        entry = {
            "group": [
                {"field": f, "rowID": int(r)}
                for (f, _), r in zip(dims, key)
            ],
            "count": len(cols),
        }
        if agg:
            entry["sum"] = sum(
                model["vals"][c] for c in cols if c in model["vals"]
            )
        out.append(entry)
    return out[:limit] if limit else out


def oracle_distinct(model, filt=None):
    items = model["vals"].items()
    return sorted(
        {v for c, v in items if filt is None or c in filt}
    )


def oracle_percentile(model, nth_bp, filt=None):
    vals = sorted(
        v for c, v in model["vals"].items() if filt is None or c in filt
    )
    if not vals:
        return None
    k = analytics.nearest_rank(nth_bp, len(vals))
    return ValCount(vals[k - 1], len(vals))


def executors(holder):
    """(classic CPU, shard-batched device, fused device) — the gauntlet."""
    return (
        Executor(holder, device_policy="never"),
        Executor(holder, device_policy="always", fusion_enabled=False),
        Executor(holder, device_policy="always", fusion_enabled=True),
    )


# -- PQL surface --------------------------------------------------------------


class TestParsing:
    @pytest.mark.parametrize("q", [
        "GroupBy(Rows(seg))",
        "GroupBy(Rows(seg), Rows(dev, ids=[0,2]), Sum(field=v), limit=5)",
        "GroupBy(Rows(seg), Row(dev=1), limit=3)",
        "Distinct(field=v)",
        "Distinct(Row(seg=2), field=v)",
        "Percentile(field=v, nth=99.9)",
        "Percentile(Row(seg=2), field=v, nth=50)",
    ])
    def test_roundtrip(self, q):
        query = parse(q)
        assert str(parse(str(query))) == str(query)

    def test_rows_outside_groupby_rejected(self, holder):
        holder.create_index("i").create_field("seg")
        e = Executor(holder, device_policy="never")
        with pytest.raises(ValueError, match="GroupBy"):
            e.execute("i", "Rows(seg)")

    @pytest.mark.parametrize("q,msg", [
        ("GroupBy(Row(seg=1))", "Rows"),
        ("Percentile(field=v)", "nth"),
        ("Percentile(field=v, nth=101)", "0, 100"),
        ("Percentile(field=v, nth=12.345)", "decimal"),
        ("Percentile(field=v, nth=-1)", "0, 100"),
    ])
    def test_validation_errors(self, holder, q, msg):
        idx = holder.create_index("i")
        idx.create_field("seg")
        idx.create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=0, max=10))
        e = Executor(holder, device_policy="never")
        with pytest.raises(ValueError, match=msg):
            e.execute("i", q)


# -- oracle gauntlet: classic == batched == fused == oracle -------------------


class TestOracleGauntlet:
    @pytest.mark.parametrize("seed_n", [7, 19, 42])
    def test_groupby_cross_product(self, holder, seed_n):
        model = seed(holder, np.random.default_rng(seed_n))
        dims = [
            ("seg", sorted(model["seg"])),
            ("dev", sorted(model["dev"])),
        ]
        want = oracle_groupby(model, dims, agg=True)
        q = "GroupBy(Rows(seg), Rows(dev), Sum(field=v))"
        for e in executors(holder):
            (got,) = e.execute("i", q)
            assert got == want

    @pytest.mark.parametrize("seed_n", [7, 42])
    def test_groupby_filter_and_limit(self, holder, seed_n):
        model = seed(holder, np.random.default_rng(seed_n))
        filt = model["seg"].get(2, set())
        want = oracle_groupby(
            model, [("dev", sorted(model["dev"]))], filt=filt, limit=3
        )
        for e in executors(holder):
            (got,) = e.execute("i", "GroupBy(Rows(dev), Row(seg=2), limit=3)")
            assert got == want

    def test_groupby_explicit_ids_keep_given_order(self, holder):
        model = seed(holder, np.random.default_rng(3))
        # out-of-order explicit ids + one id with no row: the absent id
        # yields only zero-count groups, which are dropped everywhere
        want = oracle_groupby(
            model, [("dev", [2, 0, 99]), ("seg", sorted(model["seg"]))],
            agg=True,
        )
        assert all(g["group"][0]["rowID"] != 99 for g in want)
        q = "GroupBy(Rows(dev, ids=[2,0,99]), Rows(seg), Sum(field=v))"
        for e in executors(holder):
            (got,) = e.execute("i", q)
            assert got == want

    @pytest.mark.parametrize("seed_n", [7, 42])
    def test_distinct(self, holder, seed_n):
        model = seed(holder, np.random.default_rng(seed_n))
        for e in executors(holder):
            (got,) = e.execute("i", "Distinct(field=v)")
            assert got == oracle_distinct(model)
            (got,) = e.execute("i", "Distinct(Row(seg=1), field=v)")
            assert got == oracle_distinct(model, filt=model["seg"].get(1, set()))

    @pytest.mark.parametrize("nth", [0, 0.01, 25, 50, 90, 99.99, 100])
    def test_percentile(self, holder, nth):
        model = seed(holder, np.random.default_rng(11))
        nth_bp = int(round(nth * 100))
        want = oracle_percentile(model, nth_bp)
        for e in executors(holder):
            (got,) = e.execute("i", f"Percentile(field=v, nth={nth})")
            assert got == want
        wantf = oracle_percentile(model, nth_bp, filt=model["seg"].get(2, set()))
        for e in executors(holder):
            (got,) = e.execute("i", f"Percentile(Row(seg=2), field=v, nth={nth})")
            assert got == wantf

    def test_empty_index(self, holder):
        idx = holder.create_index("i")
        idx.create_field("seg")
        idx.create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=0, max=100))
        for e in executors(holder):
            (g,) = e.execute("i", "GroupBy(Rows(seg))")
            assert g == []
            (d,) = e.execute("i", "Distinct(field=v)")
            assert d == []
            (p,) = e.execute("i", "Percentile(field=v, nth=50)")
            assert p.count == 0

    def test_time_quantum_filter(self, holder):
        """GroupBy filtered by a time-quantum Range: the filter subtree
        fans out through quantum views identically on every path."""
        idx = holder.create_index("i")
        seg = idx.create_field("seg")
        idx.create_field("t", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMD"))
        e0 = Executor(holder, device_policy="never")
        model = {"seg": {}, "vals": {}}
        rng = np.random.default_rng(5)
        for col in range(0, 2 * SHARD_WIDTH, 9173):
            r = int(rng.integers(0, 3))
            seg.set_bit(r, col)
            model["seg"].setdefault(r, set()).add(col)
            day = 1 + (col % 4)  # days 1-4; filter below spans 1-2
            e0.execute("i", f"Set({col}, t=1, 2010-01-0{day}T00:00)")
        filt_q = "Range(t=1, 2010-01-01T00:00, 2010-01-03T00:00)"
        (frow,) = e0.execute("i", filt_q)
        filt = set(frow.columns().tolist())
        assert filt  # the window is populated
        want = oracle_groupby(
            model, [("seg", sorted(model["seg"]))], filt=filt
        )
        for e in executors(holder):
            (got,) = e.execute("i", f"GroupBy(Rows(seg), {filt_q})")
            assert got == want

    def test_freshness_under_mid_run_ingest(self, holder):
        """An ingest wave between two panel executions must be visible:
        generation bumps invalidate staged blocks and cached plans."""
        rng = np.random.default_rng(23)
        model = seed(holder, rng, ncols=1500)
        e_cpu, e_dev, e_fused = executors(holder)
        q = "GroupBy(Rows(seg), Rows(dev), Sum(field=v))"
        for e in (e_dev, e_fused):
            (warm,) = e.execute("i", q)  # stage + compile
            assert warm == oracle_groupby(
                model,
                [("seg", sorted(model["seg"])), ("dev", sorted(model["dev"]))],
                agg=True,
            )
        # mid-run wave: new columns land in both dims and the BSI field
        idx = holder.index("i")
        newcols = [SHARD_WIDTH + 77, 2 * SHARD_WIDTH + 991, 1234567]
        idx.field("seg").import_bits([0, 1, 2], newcols)
        idx.field("dev").import_bits([1, 1, 3], newcols)
        idx.field("v").import_values(newcols, [500, -50, 900])
        for r, c in zip([0, 1, 2], newcols):
            model["seg"].setdefault(r, set()).add(c)
        for r, c in zip([1, 1, 3], newcols):
            model["dev"].setdefault(r, set()).add(c)
        model["vals"].update(dict(zip(newcols, [500, -50, 900])))
        want = oracle_groupby(
            model,
            [("seg", sorted(model["seg"])), ("dev", sorted(model["dev"]))],
            agg=True,
        )
        for e in (e_cpu, e_dev, e_fused):
            (got,) = e.execute("i", q)
            assert got == want

    def test_max_groups_cap(self, holder):
        seed(holder, np.random.default_rng(1))
        e = Executor(holder, device_policy="never", analytics_max_groups=4)
        with pytest.raises(ValueError, match="analytics-max-groups"):
            e.execute("i", "GroupBy(Rows(seg), Rows(dev))")


# -- fused launch accounting --------------------------------------------------


class TestFusedLaunch:
    def test_panel_is_exactly_one_fused_launch(self, holder):
        """A K-combination GroupBy panel must execute as ONE fused
        segmented-reduction launch — counter-proven on the fuser and on
        the fusion.groupby_launches metric family."""
        model = seed(holder, np.random.default_rng(13))
        e = Executor(holder, device_policy="always", fusion_enabled=True)
        before_launch = e.fuser.fused_launches
        before_metric = _counter(metrics.FUSION_GROUPBY_LAUNCHES)
        (got,) = e.execute("i", "GroupBy(Rows(seg), Rows(dev), Sum(field=v))")
        assert e.fuser.fused_launches - before_launch == 1
        assert _counter(metrics.FUSION_GROUPBY_LAUNCHES) - before_metric == 1
        k = len(model["seg"]) * len(model["dev"])
        assert 0 < len(got) <= k

    def test_mixed_query_single_launch(self, holder):
        """Interactive calls and a panel in one query still fuse into a
        single launch, and every result matches the classic path."""
        seed(holder, np.random.default_rng(17))
        e = Executor(holder, device_policy="always", fusion_enabled=True)
        cpu = Executor(holder, device_policy="never")
        q = (
            "Count(Row(seg=1))"
            "GroupBy(Rows(dev), Sum(field=v))"
            "Distinct(field=v)"
            "Percentile(field=v, nth=95)"
        )
        before = e.fuser.fused_launches
        got = e.execute("i", q)
        assert e.fuser.fused_launches - before == 1
        assert got == cpu.execute("i", q)

    def test_analytics_queries_metric_labels(self, holder):
        seed(holder, np.random.default_rng(2))
        e = Executor(holder, device_policy="never")
        snap0 = metrics.REGISTRY.snapshot()
        e.execute("i", "GroupBy(Rows(seg))")
        e.execute("i", "Distinct(field=v)")
        e.execute("i", "Percentile(field=v, nth=50)")
        snap1 = metrics.REGISTRY.snapshot()
        for call in ("GroupBy", "Distinct", "Percentile"):
            key = f"{metrics.ANALYTICS_QUERIES};call:{call}"
            assert snap1.get(key, 0) - snap0.get(key, 0) == 1, call


# -- satellite: heat-ledger attribution at the batched launch sites -----------


class TestHeatAttribution:
    @pytest.fixture(autouse=True)
    def _clean_ledger(self):
        heat.LEDGER.clear()
        heat.LEDGER.configure(True, 300.0)
        yield
        heat.LEDGER.clear()
        heat.LEDGER.configure(True, 300.0)

    def _reads(self):
        cells = heat.LEDGER.snapshot()["cells"]
        return {
            (c["field"], c["shard"]): c["reads"]
            for c in cells
            if c["reads"] > 0
        }

    @pytest.mark.parametrize("fusion", [False, True])
    def test_multi_shard_groupby_records_reads(self, holder, fusion):
        """Regression (satellite 1): the segmented-reduction launch
        sites bypass _map_reduce's per-shard loop, so they must record
        their own read legs — every (field, shard) the panel touched."""
        seed(holder, np.random.default_rng(29))
        e = Executor(holder, device_policy="always", fusion_enabled=fusion)
        e.execute("i", "GroupBy(Rows(seg), Rows(dev), Sum(field=v))")
        reads = self._reads()
        assert reads, "multi-shard GroupBy recorded no heat reads"
        for field in ("seg", "dev", "v"):
            for shard in range(3):
                assert reads.get((field, shard), 0) > 0, (field, shard)

    def test_distinct_and_percentile_record_reads(self, holder):
        seed(holder, np.random.default_rng(31))
        e = Executor(holder, device_policy="always", fusion_enabled=False)
        e.execute("i", "Distinct(field=v)")
        e.execute("i", "Percentile(field=v, nth=50)")
        reads = self._reads()
        for shard in range(3):
            assert reads.get(("v", shard), 0) >= 2, shard


# -- satellite: plan-driven prefetch sees analytic operands -------------------


class TestPrefetchWidening:
    def test_extract_row_operands_sees_analytic_calls(self):
        q = parse(
            "GroupBy(Rows(dev, ids=[4,1]), Rows(seg), Row(seg=2), Sum(field=v))"
            "Percentile(Row(seg=7), field=v, nth=50)"
        )
        ops = __import__(
            "pilosa_tpu.plan.planner", fromlist=["extract_row_operands"]
        ).extract_row_operands(q.calls)
        # explicit dim ids + filter Row leaves; discovered dims are
        # unknowable pre-execution and stay out
        assert ops == [("dev", 4), ("dev", 1), ("seg", 2), ("seg", 7)]

    def test_prefetch_accuracy_attributed_on_queued_groupby(self, holder):
        """A queued GroupBy's explicit dim rows stage ahead of the
        launch; executing the panel then reaches the speculative blocks
        and attributes them used (satellite 2)."""
        from pilosa_tpu.executor.tiering import PrefetchScheduler

        seed(holder, np.random.default_rng(37))
        e = Executor(holder, device_policy="always", fusion_enabled=False)
        sched = PrefetchScheduler(e, depth=2, enabled=True)
        q = "GroupBy(Rows(dev, ids=[0,1]), Row(seg=2))"
        item = types.SimpleNamespace(query=parse(q), index="i", shards=None)
        n = sched.schedule([item])
        assert n > 0 and sched.scheduled == n
        deadline = time.monotonic() + 5.0
        while e.stager.prefetch_issued < n and time.monotonic() < deadline:
            time.sleep(0.01)
        assert e.stager.prefetch_issued >= n
        e.execute("i", q)
        assert e.stager.prefetch_used > 0


# -- satellite: quarantine degrades to the clean 503 --------------------------


class TestQuarantineDegrade:
    @pytest.mark.parametrize("fusion", [False, True])
    def test_groupby_clean_503(self, holder, fusion):
        from pilosa_tpu.core import VIEW_STANDARD

        seed(holder, np.random.default_rng(41))
        frag = holder.fragment("i", "seg", VIEW_STANDARD, 1)
        frag.quarantine("test corruption")
        e = Executor(holder, device_policy="always", fusion_enabled=fusion)
        before = _counter(metrics.ANALYTICS_DEGRADED_LEGS)
        with pytest.raises(FragmentQuarantinedError) as ei:
            e.execute("i", "GroupBy(Rows(seg), Rows(dev))")
        assert ei.value.status == 503
        # the device leg degraded to the classic path (which then
        # surfaced the quarantine cleanly) instead of poisoning the
        # fused/batched launch with an opaque device error
        assert _counter(metrics.ANALYTICS_DEGRADED_LEGS) > before

    def test_distinct_clean_503(self, holder):
        from pilosa_tpu.core import VIEW_BSI_GROUP_PREFIX

        seed(holder, np.random.default_rng(43))
        frag = holder.fragment("i", "v", VIEW_BSI_GROUP_PREFIX + "v", 0)
        frag.quarantine("test corruption")
        e = Executor(holder, device_policy="always", fusion_enabled=True)
        with pytest.raises(FragmentQuarantinedError) as ei:
            e.execute("i", "Distinct(field=v)")
        assert ei.value.status == 503

    def test_healthy_shards_unaffected_after_degrade(self, holder):
        """After a quarantine-triggered failure, a query not touching
        the quarantined fragment still runs on the device path."""
        from pilosa_tpu.core import VIEW_STANDARD

        model = seed(holder, np.random.default_rng(47))
        holder.fragment("i", "seg", VIEW_STANDARD, 1).quarantine("test")
        e = Executor(holder, device_policy="always", fusion_enabled=True)
        with pytest.raises(FragmentQuarantinedError):
            e.execute("i", "GroupBy(Rows(seg))")
        (got,) = e.execute("i", "GroupBy(Rows(dev))")
        assert got == oracle_groupby(model, [("dev", sorted(model["dev"]))])


# -- merge / federation units -------------------------------------------------


class TestMergeUnits:
    def test_merge_group_lists_sums_and_copies(self):
        a = [{"group": [{"field": "f", "rowID": 1}], "count": 2, "sum": 10}]
        b = [
            {"group": [{"field": "f", "rowID": 1}], "count": 3, "sum": 5},
            {"group": [{"field": "f", "rowID": 0}], "count": 1},
        ]
        merged = analytics.merge_group_lists(a, b)
        assert [analytics.group_key(e) for e in merged] == [(0,), (1,)]
        assert merged[1]["count"] == 5 and merged[1]["sum"] == 15
        # inputs never mutated (remote decodes can be cached)
        assert a[0]["count"] == 2 and b[0]["count"] == 3

    def test_finalize_ranks_explicit_ids_by_position(self):
        plan = analytics.GroupByPlan([("f", [5, 2, 9])], None, None, 2)
        merged = [
            {"group": [{"field": "f", "rowID": r}], "count": c}
            for r, c in ((2, 4), (9, 1), (5, 7))
        ]
        got = analytics.finalize_groups(plan, merged)
        assert [analytics.group_key(e) for e in got] == [(5,), (2,)]

    def test_finalize_drops_zero_counts(self):
        plan = analytics.GroupByPlan([("f", None)], None, None, None)
        merged = [
            {"group": [{"field": "f", "rowID": 1}], "count": 0},
            {"group": [{"field": "f", "rowID": 2}], "count": 3},
        ]
        assert [
            analytics.group_key(e)
            for e in analytics.finalize_groups(plan, merged)
        ] == [(2,)]

    @pytest.mark.parametrize("nth_bp,count,want", [
        (0, 5, 1), (10000, 5, 5), (5000, 4, 2), (5000, 5, 3),
        (9999, 10000, 9999), (1, 10000, 1), (2500, 7, 2),
    ])
    def test_nearest_rank(self, nth_bp, count, want):
        assert analytics.nearest_rank(nth_bp, count) == want

    def test_nearest_rank_matches_ceil_definition(self):
        import math

        for nth_bp in (0, 1, 37, 5000, 9999, 10000):
            for count in (1, 2, 9, 100, 12345):
                k = analytics.nearest_rank(nth_bp, count)
                want = min(max(math.ceil(nth_bp * count / 10000), 1), count)
                assert k == want, (nth_bp, count)

    def test_decode_presence_words(self):
        words = np.array([0b1010, 0, 1 << 31], dtype=np.uint32)
        assert analytics.decode_presence_words(words, -3) == [-2, 0, 92]

    def test_decode_remote_branches(self):
        from pilosa_tpu.parallel.cluster import Cluster
        from pilosa_tpu.pql.ast import Call

        raw = [{"group": [{"field": "seg", "rowID": 1}], "count": 3}]
        assert Cluster._decode_remote(Call("GroupBy"), raw) == raw
        assert Cluster._decode_remote(Call("Distinct"), [3, 1]) == [3, 1]
        vc = Cluster._decode_remote(Call("Percentile"), {"value": 7, "count": 2})
        assert vc == ValCount(7, 2)

    def test_heat_fields(self):
        q = parse("GroupBy(Rows(seg), Rows(dev), Row(seg=1), Sum(field=v))")
        assert analytics.heat_fields(q.calls[0]) == ["seg", "dev", "v"]
        q2 = parse("Distinct(field=v)")
        assert analytics.heat_fields(q2.calls[0]) == ["v"]


# -- serving surface: bulk class + HTTP + /debug/heat -------------------------


def _req(server, method, path, body=None):
    url = server.uri + path
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(r) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


class TestServing:
    @pytest.fixture()
    def server(self, tmp_path):
        from pilosa_tpu.server import Config, Server

        heat.LEDGER.clear()
        slo.MONITOR.clear()
        cfg = Config(
            data_dir=str(tmp_path / "data"),
            bind="127.0.0.1:0",
            metric="expvar",
            device_policy="always",
            device_timeout=0,
        )
        s = Server(cfg)
        s.open()
        yield s
        s.close()
        heat.LEDGER.clear()

    def test_classify(self):
        from pilosa_tpu.server.pipeline import classify_query

        assert classify_query("GroupBy(Rows(seg))", False) == "bulk"
        assert classify_query("Distinct(field=v)", False) == "bulk"
        assert classify_query("Percentile(field=v, nth=1)", False) == "bulk"
        assert classify_query("Count(Row(seg=1))", False) == "interactive"
        assert classify_query("GroupBy(Rows(seg))", True) == "internal"

    def test_http_groupby_bulk_class_and_heat(self, server):
        """End to end over HTTP: wire shapes, bulk-class SLO accounting,
        and the /debug/heat regression — a multi-shard GroupBy shows
        nonzero reads on every touched (field, shard) cell."""
        seg = server.holder.create_index("an").create_field("seg")
        val = server.holder.index("an").create_field(
            "v", FieldOptions(type=FIELD_TYPE_INT, min=0, max=100)
        )
        cols = list(range(0, 2 * SHARD_WIDTH, 131072))
        seg.import_bits([c % 3 for c in cols], cols)
        val.import_values(cols, [c % 97 for c in cols])
        st, body = _req(
            server, "POST", "/index/an/query",
            b"GroupBy(Rows(seg), Sum(field=v))",
        )
        assert st == 200, body
        groups = body["results"][0]
        assert groups and all(
            g["group"][0]["field"] == "seg" and g["count"] > 0 and "sum" in g
            for g in groups
        )
        st, body = _req(
            server, "POST", "/index/an/query",
            b"Percentile(field=v, nth=50)",
        )
        assert st == 200 and set(body["results"][0]) == {"value", "count"}
        # bulk-class SLO accounting took the analytic requests
        cls = slo.MONITOR.snapshot()["classes"]["bulk"]["samples"]
        assert cls["good"] >= 2
        # /debug/heat regression: nonzero reads on both shards
        st, snap = _req(server, "GET", "/debug/heat?index=an")
        assert st == 200
        reads = {
            (c["field"], c["shard"]): c["reads"]
            for c in snap["cells"]
            if c["reads"] > 0
        }
        for shard in (0, 1):
            assert reads.get(("seg", shard), 0) > 0, shard
            assert reads.get(("v", shard), 0) > 0, shard


# -- docs drift guard ---------------------------------------------------------


def _doc(name: str) -> str:
    root = os.path.join(os.path.dirname(__file__), "..", "docs")
    with open(os.path.join(root, name)) as f:
        return f.read()


def test_docs_document_analytics_knobs_with_current_defaults():
    from pilosa_tpu.server import Config

    cfg = Config(data_dir="x")
    conf = _doc("configuration.md")
    for knob, default in (
        ("analytics-max-groups", str(cfg.analytics_max_groups)),
        ("analytics-timeout", str(cfg.analytics_timeout)),
    ):
        assert f"| `{knob}` | {default} |" in conf, knob
    # the bulk-class SLO objective default the analytic class burns
    assert "bulk=2000@0.99" in conf


def test_docs_query_language_covers_analytic_calls():
    ql = _doc("query-language.md")
    for call in ("GroupBy(", "Distinct(", "Percentile(", "Rows("):
        assert call in ql, call
    for shape in ("`GroupBy`", "`Distinct`", "`Percentile`"):
        assert f"| {shape} |" in ql, shape  # result-shape table rows


def test_docs_administration_names_analytics_metrics():
    admin = _doc("administration.md")
    for m in (
        metrics.FUSION_GROUPBY_LAUNCHES,
        metrics.FUSION_GROUPBY_GROUPS,
        metrics.ANALYTICS_QUERIES,
        metrics.ANALYTICS_DEGRADED_LEGS,
    ):
        assert f"`{m}`" in admin, m
