"""Telemetry export pipeline (ISSUE 16): the batching exporter's
never-block backpressure contract, the JSONL and OTLP/HTTP sinks, the
journal/tracer taps, and the server wiring (config-driven sinks, taps
detached on close, disabled path leaves the taps as plain None).

Server-level pieces run against a real in-process server on :0 under
JAX_PLATFORMS=cpu (the tier-1 environment)."""

import json
import time

import pytest

from pilosa_tpu.server import Config, Server
from pilosa_tpu.utils import events, metrics, telemetry_export, trace
from pilosa_tpu.utils.telemetry_export import (
    BatchingExporter,
    JsonlFileSink,
    OtlpHttpSink,
    build_exporter,
)


@pytest.fixture(autouse=True)
def _clean_taps():
    yield
    events.JOURNAL.on_record = None
    trace.TRACER.on_export = None
    events.JOURNAL.clear()


class ListSink:
    name = "list"

    def __init__(self):
        self.batches = []

    def write_batch(self, batch):
        self.batches.append(batch)

    def close(self):
        pass


class BoomSink:
    name = "boom"

    def write_batch(self, batch):
        raise OSError("sink down")

    def close(self):
        pass


def _metric(prefix: str) -> float:
    return sum(
        v for k, v in metrics.snapshot().items() if k.startswith(prefix)
    )


# -- backpressure -------------------------------------------------------------


def test_full_queue_drops_and_counts_never_blocks():
    ex = BatchingExporter([ListSink()], queue_max=4)
    before = _metric(metrics.EXPORT_DROPPED)
    t0 = time.perf_counter()
    results = [ex.enqueue("events", {"i": i}) for i in range(10)]
    elapsed = time.perf_counter() - t0
    assert results == [True] * 4 + [False] * 6
    assert ex.stats()["enqueued"] == 4 and ex.stats()["dropped"] == 6
    assert _metric(metrics.EXPORT_DROPPED) == before + 6
    # "never blocks" pinned coarsely: 10 enqueues against a full queue
    # finish in interactive time, no waiting on any consumer
    assert elapsed < 1.0
    # a flush drains the queue and new records are accepted again
    assert ex.flush() == 4
    assert ex.enqueue("events", {"i": 10}) is True
    ex.close()


def test_flush_is_per_sink_isolated():
    good = ListSink()
    before = _metric(metrics.EXPORT_ERRORS)
    ex = BatchingExporter([BoomSink(), good], queue_max=16)
    ex.enqueue("events", {"i": 1})
    assert ex.flush() == 1
    # the failing sink dropped its batch and was counted; the good
    # sink still shipped
    assert _metric(metrics.EXPORT_ERRORS) == before + 1
    assert len(good.batches) == 1
    ex.close()


def test_metrics_fn_sampled_per_flush():
    sink = ListSink()
    ex = BatchingExporter([sink], metrics_fn=lambda: {"up": 1.0})
    ex.flush()
    (batch,) = sink.batches
    assert [r["stream"] for r in batch] == ["metrics"]
    assert batch[0]["record"] == {"up": 1.0}
    ex.close()


# -- sinks --------------------------------------------------------------------


def test_jsonl_sink_roundtrip_and_flush_on_close(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    ex = BatchingExporter([JsonlFileSink(path)], queue_max=16)
    ex.enqueue("events", {"kind": "gang.degrade", "seq": 1})
    ex.enqueue("spans", {"name": "query", "duration_ms": 2.5})
    ex.close()  # flush-on-close, no background thread ever started
    lines = [json.loads(l) for l in open(path)]
    assert [l["stream"] for l in lines] == ["events", "spans"]
    assert lines[0]["record"]["kind"] == "gang.degrade"
    assert all("t" in l for l in lines)


def test_otlp_sink_posts_traces_logs_and_metrics(monkeypatch):
    posts = []

    class _Resp:
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    def fake_urlopen(req, timeout=None):
        posts.append((req.full_url, json.loads(req.data)))
        return _Resp()

    monkeypatch.setattr(
        telemetry_export.urllib.request, "urlopen", fake_urlopen
    )
    sink = OtlpHttpSink("http://collector:4318/")
    now = time.time()
    sink.write_batch(
        [
            {
                "stream": "spans",
                "t": now,
                "record": {
                    "name": "query",
                    "trace_id": "ab" * 16,
                    "span_id": "cd" * 8,
                    "duration_ms": 10.0,
                    "meta": {"index": "i", "shards": 2, "ok": True},
                },
            },
            {
                "stream": "events",
                "t": now,
                "record": {"kind": "gang.degrade", "t": now, "seq": 7},
            },
            {
                "stream": "metrics",
                "t": now,
                "record": {"uptime": 12.5, "name": "not-a-number"},
            },
        ]
    )
    by_path = {url.rsplit("/v1/", 1)[1]: body for url, body in posts}
    assert set(by_path) == {"traces", "logs", "metrics"}
    (span,) = by_path["traces"]["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert span["name"] == "query" and span["traceId"] == "ab" * 16
    dur_ns = int(span["endTimeUnixNano"]) - int(span["startTimeUnixNano"])
    assert abs(dur_ns - 10e6) < 1e4  # 10ms span, float-nano slack
    (rec,) = by_path["logs"]["resourceLogs"][0]["scopeLogs"][0]["logRecords"]
    assert rec["body"]["stringValue"] == "gang.degrade"
    (gauge,) = by_path["metrics"]["resourceMetrics"][0]["scopeMetrics"][0][
        "metrics"
    ]
    assert gauge["name"] == "uptime"
    assert gauge["gauge"]["dataPoints"][0]["asDouble"] == 12.5


def test_build_exporter_none_without_sinks(tmp_path):
    assert build_exporter() is None
    ex = build_exporter(path=str(tmp_path / "t.jsonl"))
    assert [s.name for s in ex.sinks] == ["jsonl"]
    ex.close()


# -- taps ---------------------------------------------------------------------


def test_journal_and_tracer_taps_feed_the_queue():
    sink = ListSink()
    ex = BatchingExporter([sink], queue_max=16)
    events.JOURNAL.on_record = ex.tap_event
    tr = trace.Tracer()
    tr.on_export = ex.tap_span
    events.record("gang.degrade", gang="A")
    with tr.trace("query", force=True):
        pass
    ex.flush()
    (batch,) = sink.batches
    streams = [r["stream"] for r in batch]
    assert "events" in streams and "spans" in streams
    ev = next(r for r in batch if r["stream"] == "events")
    assert ev["record"]["kind"] == "gang.degrade"
    sp = next(r for r in batch if r["stream"] == "spans")
    assert sp["record"]["name"] == "query"
    ex.close()


# -- server wiring ------------------------------------------------------------


def _cfg(tmp_path, **kw):
    return Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="expvar",
        device_policy="always",
        device_timeout=0,
        **kw,
    )


def test_server_disabled_path_leaves_taps_none(tmp_path):
    s = Server(_cfg(tmp_path))
    s.open()
    try:
        # no export sink configured: no exporter object, and the hot
        # paths see a plain None attribute — one branch, no allocation
        assert s.exporter is None
        assert events.JOURNAL.on_record is None
        assert trace.TRACER.on_export is None
    finally:
        s.close()


def test_server_exports_events_to_jsonl_and_detaches_on_close(tmp_path):
    path = str(tmp_path / "out.jsonl")
    s = Server(_cfg(tmp_path, export_path=path, export_interval=600.0))
    s.open()
    try:
        assert s.exporter is not None
        assert getattr(events.JOURNAL.on_record, "__self__", None) is s.exporter
        assert getattr(trace.TRACER.on_export, "__self__", None) is s.exporter
        events.record("chaos.window", mode="install")
    finally:
        s.close()
    # close detached the taps, then flushed the queue into the sink
    assert events.JOURNAL.on_record is None
    assert trace.TRACER.on_export is None
    lines = [json.loads(l) for l in open(path)]
    assert any(
        l["stream"] == "events" and l["record"]["kind"] == "chaos.window"
        for l in lines
    )
    # every flush also samples a metrics snapshot
    assert any(l["stream"] == "metrics" for l in lines)
