"""Plan canonicalization (plan/canon.py): flatten/sort/normalize rules,
hash stability and distinctness, and the pipeline signature helper."""

import pytest

from pilosa_tpu.pql import parse
from pilosa_tpu.pql.ast import Call, Condition
from pilosa_tpu.plan.canon import (
    CACHED_CALL,
    call_hash,
    canonicalize,
    query_hash,
    query_signature,
)


def h(text: str) -> str:
    (c,) = parse(text).calls
    return call_hash(c)


# -- equivalences -----------------------------------------------------------


@pytest.mark.parametrize(
    "a,b",
    [
        # commutative operand order
        ("Intersect(Row(f=1), Row(f=2))", "Intersect(Row(f=2), Row(f=1))"),
        ("Union(Row(f=1), Row(f=2))", "Union(Row(f=2), Row(f=1))"),
        ("Xor(Row(f=1), Row(f=2))", "Xor(Row(f=2), Row(f=1))"),
        # associative nesting flattens (Union/Intersect)
        ("Union(Row(f=1), Union(Row(f=2), Row(f=3)))",
         "Union(Row(f=1), Row(f=2), Row(f=3))"),
        ("Union(Union(Row(f=3), Row(f=1)), Row(f=2))",
         "Union(Row(f=2), Row(f=3), Row(f=1))"),
        ("Intersect(Intersect(Row(f=1), Row(f=2)), Row(f=3))",
         "Intersect(Row(f=3), Intersect(Row(f=2), Row(f=1)))"),
        # permutation deep inside a parent op
        ("Count(Intersect(Row(a=1), Row(b=2)))",
         "Count(Intersect(Row(b=2), Row(a=1)))"),
        # option order
        ("TopN(f, Row(f=1), n=5, threshold=2)",
         "TopN(f, Row(f=1), threshold=2, n=5)"),
        # whitespace / text-level differences
        ("Count(Row(f=1))", "Count( Row( f = 1 ) )"),
    ],
)
def test_equivalent_spellings_share_hash(a, b):
    assert h(a) == h(b)


@pytest.mark.parametrize(
    "a,b",
    [
        # Difference is NOT commutative
        ("Difference(Row(f=1), Row(f=2))", "Difference(Row(f=2), Row(f=1))"),
        # Xor is commutative but NOT flattened into Union/Intersect
        ("Union(Row(f=1), Xor(Row(f=2), Row(f=3)))",
         "Union(Row(f=1), Row(f=2), Row(f=3))"),
        # operand multiplicity matters (Xor(a,a) is empty, not a)
        ("Xor(Row(f=1), Row(f=1))", "Row(f=1)"),
        # literal types stay distinct
        ("TopN(f, n=1)", 'TopN(f, n="1")'),
        # different calls / fields / rows
        ("Count(Row(f=1))", "Count(Row(f=2))"),
        ("Count(Row(f=1))", "Count(Row(g=1))"),
        ("Union(Row(f=1), Row(f=2))", "Intersect(Row(f=1), Row(f=2))"),
    ],
)
def test_distinct_queries_get_distinct_hashes(a, b):
    assert h(a) != h(b)


def test_hash_is_stable_across_calls():
    assert h("Count(Intersect(Row(a=1), Row(b=2)))") == h(
        "Count(Intersect(Row(a=1), Row(b=2)))"
    )


def test_condition_args_hash():
    a = h("Range(v > 10)")
    assert a == h("Range(v > 10)")
    assert a != h("Range(v > 11)")
    assert a != h("Range(v >= 10)")


# -- canonicalize (tree form) ----------------------------------------------


def test_canonicalize_flattens_and_sorts_without_mutating_input():
    (c,) = parse("Union(Row(f=3), Union(Row(f=1), Row(f=2)))").calls
    before = str(c)
    canon = canonicalize(c)
    assert str(c) == before  # input untouched
    assert canon.name == "Union"
    assert [k.name for k in canon.children] == ["Row", "Row", "Row"]
    rows = sorted(k.args["f"] for k in canon.children)
    assert rows == [1, 2, 3]
    # canonical form of a canonical tree is itself (idempotent)
    assert call_hash(canon) == call_hash(c)


def test_cached_placeholder_hashes_as_replaced_subtree():
    (c,) = parse("Count(Intersect(Row(a=1), Row(b=2)))").calls
    inner = c.children[0]
    ih = call_hash(inner)
    rewritten = Call(
        "Count", dict(c.args), [Call(CACHED_CALL, args={"_h": ih})]
    )
    assert call_hash(rewritten) == call_hash(c)


def test_write_and_unknown_calls_still_hash():
    # canonicalization never refuses a tree — cacheability is the
    # planner's decision, identity is canon's
    assert h("Set(10, f=1)") != h("Set(10, f=2)")
    c = Call("Weird", {"x": Condition(">", 3)}, [])
    assert call_hash(c) == call_hash(c)


# -- query-level signature --------------------------------------------------


def test_query_hash_is_call_order_sensitive():
    a = query_hash(parse("Count(Row(f=1)) Count(Row(f=2))"))
    b = query_hash(parse("Count(Row(f=2)) Count(Row(f=1))"))
    assert a != b  # results are positional


def test_query_signature_coalesces_permutations_and_survives_garbage():
    s1 = query_signature("Count(Intersect(Row(a=1), Row(b=2)))")
    s2 = query_signature("Count(Intersect(Row(b=2), Row(a=1)))")
    assert s1 is not None and s1 == s2
    assert s1.startswith("pqh:")
    assert query_signature("NotEvenPQL(((") is None
    # memoized answers stay consistent
    assert query_signature("Count(Intersect(Row(a=1), Row(b=2)))") == s1
    assert query_signature("NotEvenPQL(((") is None
