"""Continuous-batching async dispatch engine (ISSUE 8): heterogeneous
waves bit-identical to the CPU oracle, wave-level singleflight dedup,
overlap correctness under concurrent writes, deadline cancellation of
queued-but-unlaunched items, the gang/serial bypass (PR 5/6
determinism contract), engine drain on close (bare and via server),
the read-pool close/submit race regression, and the /debug/dispatch +
metrics surface.

The engine is ON by default for bare executors (PILOSA_DISPATCH), so
the whole tier-1 suite exercises the routed path implicitly; these
tests pin the engine-specific behaviors explicitly."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import FieldOptions, Holder
from pilosa_tpu.core.field import FIELD_TYPE_INT
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.server import deadline as dl_mod
from pilosa_tpu.server.deadline import Deadline, DeadlineExceeded
from pilosa_tpu.utils import metrics


@pytest.fixture
def holder():
    h = Holder()  # in-memory
    h.open()
    return h


def seed_mixed(h, n_shards=3):
    """Multi-shard index with a set field and a BSI field — enough
    surface for TopN / Count / Sum / chain plans in one wave."""
    rng = np.random.default_rng(9)
    idx = h.create_index("i")
    f = idx.create_field("f")
    v = idx.create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=-50, max=5000))
    rows = rng.integers(0, 12, size=3000)
    cols = rng.integers(0, n_shards * SHARD_WIDTH, size=3000)
    f.import_bits(rows.tolist(), cols.tolist())
    vcols = rng.choice(n_shards * SHARD_WIDTH, size=800, replace=False)
    vvals = rng.integers(-50, 5000, size=800)
    v.import_values(vcols.tolist(), vvals.tolist())


# heterogeneous plan mix: bitmap, count, TopN, BSI Sum, fused chains
MIXED_QUERIES = [
    "Row(f=1)",
    "Count(Row(f=2))",
    "TopN(f, n=5)",
    "TopN(f, Row(f=3), n=4)",
    'Sum(field="v")',
    'Sum(Row(f=1), field="v")',
    "Count(Intersect(Row(f=1), Row(f=2)))",
    "Count(Union(Row(f=3), Xor(Row(f=4), Row(f=5)), Difference(Row(f=6), Row(f=7))))",
    "Count(Range(v > 100))",
]


def _gated_executor(h, **kw):
    """Device executor whose FIRST _execute blocks on a gate: wave 1
    occupies the single in-flight slot while everything submitted
    meanwhile piles into the queue, so wave 2 is provably wide."""
    ex = Executor(
        h, device_policy="always", dispatch_enabled=True,
        dispatch_max_inflight=1, dispatch_max_wave=32, **kw
    )
    orig = ex._execute
    gate = threading.Event()
    first = threading.Event()

    def gated(index, query, shards=None, opt=None):
        if not first.is_set():
            first.set()
            assert gate.wait(10), "test gate never released"
        return orig(index, query, shards, opt)

    ex._execute = gated
    return ex, gate, first


def _run_clients(ex, queries, index="i"):
    results = {}
    errors = {}
    lock = threading.Lock()

    def client(i, q):
        try:
            r = ex.execute(index, q)
        except BaseException as e:
            with lock:
                errors[i] = e
            return
        with lock:
            results[i] = r

    ts = [
        threading.Thread(target=client, args=(i, q))
        for i, q in enumerate(queries)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return results, errors


def _wait_queued(engine, n, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if engine.stats()["queued"] >= n:
            return
        time.sleep(0.005)
    raise AssertionError(
        f"queue never reached {n}: {engine.stats()}"
    )


class TestHeterogeneousWave:
    def test_mixed_wave_bit_identical_to_cpu_oracle(self, holder):
        """TopN/Count/BSI Sum/chain plans coexisting in ONE wave return
        exactly what the blocking CPU oracle returns per query."""
        seed_mixed(holder)
        oracle = Executor(holder, device_policy="never", dispatch_enabled=False)
        want = {i: oracle.execute("i", q) for i, q in enumerate(MIXED_QUERIES)}

        ex, gate, first = _gated_executor(holder)
        try:
            # wave 1: a lone query holds the only slot at the gate
            blocker = threading.Thread(
                target=lambda: ex.execute("i", "Count(Row(f=0))")
            )
            blocker.start()
            assert first.wait(10)
            # everything else queues behind it -> one heterogeneous wave
            t_res = {}
            ts = []

            def client(i, q):
                t_res[i] = ex.execute("i", q)

            for i, q in enumerate(MIXED_QUERIES):
                t = threading.Thread(target=client, args=(i, q))
                t.start()
                ts.append(t)
            _wait_queued(ex.dispatch_engine, len(MIXED_QUERIES))
            gate.set()
            for t in ts:
                t.join()
            blocker.join()
            for i, q in enumerate(MIXED_QUERIES):
                assert t_res[i] == want[i], q
            st = ex.dispatch_engine.stats()
            # the drained wave really was wide and really combined
            # heterogeneous members into one execution
            assert st["waves"] >= 2
            assert st["combined_items"] >= len(MIXED_QUERIES) - 1
        finally:
            gate.set()
            ex.close()

    def test_duplicate_queries_dedup_to_one_execution(self, holder):
        """Wave-level singleflight: identical plans queued in the same
        wave execute once; every waiter gets the shared result."""
        seed_mixed(holder)
        oracle = Executor(holder, device_policy="never", dispatch_enabled=False)
        (want,) = oracle.execute("i", "Count(Row(f=1))")

        ex, gate, first = _gated_executor(holder)
        try:
            blocker = threading.Thread(
                target=lambda: ex.execute("i", "Count(Row(f=0))")
            )
            blocker.start()
            assert first.wait(10)
            dup_queries = ["Count(Row(f=1))"] * 6
            ts = []
            res = {}

            def client(i):
                res[i] = ex.execute("i", dup_queries[i])

            for i in range(len(dup_queries)):
                t = threading.Thread(target=client, args=(i,))
                t.start()
                ts.append(t)
            _wait_queued(ex.dispatch_engine, len(dup_queries))
            gate.set()
            for t in ts:
                t.join()
            blocker.join()
            for i in range(len(dup_queries)):
                assert res[i] == [want]
            assert ex.dispatch_engine.stats()["dedup_hits"] >= 5
        finally:
            gate.set()
            ex.close()


class TestOverlapCorrectness:
    def test_read_after_write_never_stale_mid_wave(self, holder):
        """A read submitted AFTER a write completes must observe that
        write even when an earlier wave (started pre-write) is still
        executing — generation bumps mid-wave never serve stale
        blocks."""
        seed_mixed(holder)
        ex, gate, first = _gated_executor(holder)
        oracle = Executor(holder, device_policy="never", dispatch_enabled=False)
        try:
            (before,) = oracle.execute("i", "Count(Row(f=0))")
            blocker = threading.Thread(
                target=lambda: ex.execute("i", "Count(Row(f=0))")
            )
            blocker.start()
            assert first.wait(10)
            # wave 1 is mid-flight; write through the SAME executor
            # (writes bypass the engine and run inline)
            new_cols = [SHARD_WIDTH * 2 + 777 + k for k in range(5)]
            for c in new_cols:
                assert ex.execute("i", f"Set({c}, f=0)") == [True]
            (after,) = oracle.execute("i", "Count(Row(f=0))")
            assert after == before + len(new_cols)
            # read submitted after the write returned: queued behind
            # the stalled wave, must see the post-write generation
            res = {}
            t = threading.Thread(
                target=lambda: res.update(r=ex.execute("i", "Count(Row(f=0))"))
            )
            t.start()
            _wait_queued(ex.dispatch_engine, 1)
            gate.set()
            t.join()
            blocker.join()
            assert res["r"] == [after]
        finally:
            gate.set()
            ex.close()


class TestDeadlines:
    def test_queued_item_deadline_cancels_without_hurting_wave(self, holder):
        """An item whose deadline expires while queued is cancelled at
        wave build (clients see DeadlineExceeded -> 504); wave-mates
        are unaffected."""
        seed_mixed(holder)
        oracle = Executor(holder, device_policy="never", dispatch_enabled=False)
        (want,) = oracle.execute("i", "Count(Row(f=2))")
        ex, gate, first = _gated_executor(holder)
        try:
            base_expired = metrics.snapshot().get(
                "pipeline.deadline_expired;stage:dispatch", 0
            )
            blocker = threading.Thread(
                target=lambda: ex.execute("i", "Count(Row(f=0))")
            )
            blocker.start()
            assert first.wait(10)
            outcome = {}

            def doomed():
                with dl_mod.activate(Deadline.after(0.15)):
                    try:
                        ex.execute("i", "Count(Row(f=1))")
                    except DeadlineExceeded as e:
                        outcome["err"] = e

            def healthy():
                outcome["ok"] = ex.execute("i", "Count(Row(f=2))")

            td = threading.Thread(target=doomed)
            th = threading.Thread(target=healthy)
            td.start()
            th.start()
            _wait_queued(ex.dispatch_engine, 2)
            time.sleep(0.3)  # let the queued deadline lapse
            gate.set()
            td.join()
            th.join()
            blocker.join()
            assert isinstance(outcome.get("err"), DeadlineExceeded)
            assert outcome["ok"] == [want]  # wave unaffected
            st = ex.dispatch_engine.stats()
            assert st["deadline_expired"] >= 1
            assert (
                metrics.snapshot().get(
                    "pipeline.deadline_expired;stage:dispatch", 0
                )
                > base_expired
            )
        finally:
            gate.set()
            ex.close()

    def test_failed_wave_never_reexecutes_lapsed_deadline_item(self, holder):
        """Blast-radius fix (ISSUE 14): when a combined wave attempt
        fails, an item whose deadline lapsed DURING the failed attempt
        gets DeadlineExceeded (-> 504) instead of burning a full solo
        re-execution on a future its waiter already abandoned.
        Wave-mates still re-run solo and answer correctly."""
        seed_mixed(holder)
        oracle = Executor(holder, device_policy="never", dispatch_enabled=False)
        want3 = oracle.execute("i", "Count(Row(f=3))")
        want4 = oracle.execute("i", "Count(Row(f=4))")
        ex, gate, first = _gated_executor(holder)
        inner = ex._execute
        state = {"faulted": False, "solo_calls": []}

        def faulty(index, query, shards=None, opt=None):
            n = len(query.calls)
            if n == 4:  # the combined 3-item group (2 + 1 + 1 calls)
                state["faulted"] = True
                time.sleep(2.0)  # the doomed item's deadline lapses here
                raise RuntimeError("injected wave fault")
            if state["faulted"]:
                state["solo_calls"].append(n)
            return inner(index, query, shards, opt)

        ex._execute = faulty
        try:
            blocker = threading.Thread(
                target=lambda: ex.execute("i", "Count(Row(f=0))")
            )
            blocker.start()
            assert first.wait(10)
            outcome = {}

            def doomed():
                with dl_mod.activate(Deadline.after(1.2)):
                    try:
                        ex.execute("i", "Count(Row(f=1))Count(Row(f=2))")
                    except DeadlineExceeded as e:
                        outcome["err"] = e

            def healthy(name, q):
                outcome[name] = ex.execute("i", q)

            ts = [
                threading.Thread(target=doomed),
                threading.Thread(
                    target=healthy, args=("h3", "Count(Row(f=3))")
                ),
                threading.Thread(
                    target=healthy, args=("h4", "Count(Row(f=4))")
                ),
            ]
            for t in ts:
                t.start()
            _wait_queued(ex.dispatch_engine, 3)
            gate.set()
            for t in ts:
                t.join()
            blocker.join()
            assert state["faulted"], "combined wave attempt never ran"
            assert isinstance(outcome.get("err"), DeadlineExceeded)
            assert outcome["h3"] == want3 and outcome["h4"] == want4
            # the lapsed 2-call item was NEVER re-executed solo — only
            # its two healthy wave-mates were
            assert sorted(state["solo_calls"]) == [1, 1]
            st = ex.dispatch_engine.stats()
            assert st["fallbacks"] >= 1 and st["deadline_expired"] >= 1
        finally:
            gate.set()
            ex.close()
            oracle.close()


class TestBypass:
    """The PR 5/6 determinism contract: gang-dispatched execution keeps
    ExecOptions.serial and never reaches the async engine."""

    def test_serial_opt_bypasses_engine(self, holder):
        seed_mixed(holder)
        ex = Executor(holder, device_policy="always", dispatch_enabled=True)
        try:
            r = ex.execute("i", "Count(Row(f=1))", opt=ExecOptions(serial=True))
            oracle = Executor(
                holder, device_policy="never", dispatch_enabled=False
            )
            assert r == oracle.execute("i", "Count(Row(f=1))")
            # the engine never saw it (loop not even started)
            assert ex.dispatch_engine.stats()["items"] == 0
        finally:
            ex.close()

    def test_gang_and_cluster_modes_ineligible(self, holder):
        ex = Executor(holder, device_policy="always", dispatch_enabled=True)
        try:
            opt = ExecOptions()
            assert ex._engine_eligible(opt)
            ex.gang = object()  # multihost leader: gang dispatch owns it
            assert not ex._engine_eligible(opt)
            ex.gang = None
            ex.cluster = object()  # cluster fan-out owns routing
            assert not ex._engine_eligible(opt)
            ex.cluster = None
            assert not ex._engine_eligible(ExecOptions(remote=True))
            assert not ex._engine_eligible(ExecOptions(serial=True))
        finally:
            ex.gang = None
            ex.cluster = None
            ex.close()

    def test_writes_bypass_engine(self, holder):
        seed_mixed(holder)
        ex = Executor(holder, device_policy="always", dispatch_enabled=True)
        try:
            assert ex.execute("i", f"Set({SHARD_WIDTH + 123456}, f=9)") == [True]
            assert ex.dispatch_engine.stats()["items"] == 0
        finally:
            ex.close()


class TestDrain:
    def test_close_fails_queued_work_and_falls_back_inline(self, holder):
        """close() drains what it can within the budget, fails the
        rest; afterwards execute() runs inline (submit returns None) —
        shutdown can never strand or race a submit."""
        seed_mixed(holder)
        ex, gate, first = _gated_executor(holder)
        try:
            blocker_res = {}
            blocker = threading.Thread(
                target=lambda: blocker_res.update(
                    r=ex.execute("i", "Count(Row(f=0))")
                )
            )
            blocker.start()
            assert first.wait(10)
            errs = {}

            def stuck(i):
                try:
                    ex.execute("i", "Count(Row(f=1))")
                except BaseException as e:
                    errs[i] = e

            ts = [threading.Thread(target=stuck, args=(i,)) for i in range(3)]
            for t in ts:
                t.start()
            _wait_queued(ex.dispatch_engine, 3)
            assert ex.dispatch_engine.close(drain=0.2) is False
            for t in ts:
                t.join()
            assert len(errs) == 3
            for e in errs.values():
                assert "shut down" in str(e)
            gate.set()
            blocker.join()
            # the in-flight wave still completed for its waiter
            assert blocker_res["r"] is not None
            # post-close execution runs inline and stays correct
            oracle = Executor(
                holder, device_policy="never", dispatch_enabled=False
            )
            assert ex.execute("i", "Count(Row(f=2))") == oracle.execute(
                "i", "Count(Row(f=2))"
            )
        finally:
            gate.set()
            ex.close()

    def test_clean_close_after_traffic(self, holder):
        seed_mixed(holder)
        ex = Executor(holder, device_policy="always", dispatch_enabled=True)
        try:
            results, errors = _run_clients(ex, MIXED_QUERIES)
            assert not errors
            assert len(results) == len(MIXED_QUERIES)
            assert ex.dispatch_engine.close(drain=5.0) is True
        finally:
            ex.close()


class TestReadPoolRace:
    def test_close_during_concurrent_execution_is_clean(self, holder):
        """Regression for the _read_pool close/submit race: close()
        used to null the attr while a concurrent execute() held a local
        ref. Now shutdown drains pool users within the budget and late
        acquires run serially inline — every concurrent read completes
        correctly, before and after close."""
        seed_mixed(holder)
        # engine OFF so every execute drives the read pool from its own
        # caller thread — the racy pre-PR shape
        ex = Executor(holder, device_policy="always", dispatch_enabled=False)
        oracle = Executor(holder, device_policy="never", dispatch_enabled=False)
        q = "Count(Union(Row(f=3), Xor(Row(f=4), Row(f=5)), Difference(Row(f=6), Row(f=7))))"
        want = oracle.execute("i", q)
        stop = time.monotonic() + 2.0
        errors = []
        done = []

        def reader():
            try:
                while time.monotonic() < stop:
                    assert ex.execute("i", q) == want
                done.append(True)
            except BaseException as e:  # pragma: no cover - the regression
                errors.append(e)

        ts = [threading.Thread(target=reader) for _ in range(6)]
        for t in ts:
            t.start()
        time.sleep(0.3)
        ex.close()  # mid-traffic: must drain or reject cleanly
        for t in ts:
            t.join()
        assert not errors, errors[0]
        assert len(done) == 6
        assert ex._read_pool is None


class TestServerSurface:
    def _mkserver(self, tmp_path, **cfg_kwargs):
        from pilosa_tpu.server import Config, Server

        cfg = Config(
            data_dir=str(tmp_path / "data"),
            bind="127.0.0.1:0",
            metric="expvar",
            device_policy="never",
            device_timeout=0,
            **cfg_kwargs,
        )
        s = Server(cfg)
        s.open()
        return s

    def _post(self, s, path, body):
        r = urllib.request.Request(s.uri + path, data=body, method="POST")
        with urllib.request.urlopen(r) as resp:
            return json.loads(resp.read() or b"{}")

    def _get(self, s, path):
        with urllib.request.urlopen(s.uri + path) as resp:
            return resp.read()

    def test_debug_dispatch_metrics_and_server_close_drain(self, tmp_path):
        s = self._mkserver(tmp_path)
        try:
            assert s.executor.dispatch_engine is not None
            # engine owns cross-request combining -> pipeline hands off
            assert s.pipeline.stats()["dispatch_handoff"] is True
            self._post(s, "/index/ds", b"{}")
            self._post(s, "/index/ds/field/f", b"{}")
            self._post(
                s, "/index/ds/field/f/import",
                json.dumps(
                    {"rowIDs": [0, 0, 1, 1, 1], "columnIDs": [1, 2, 3, 4, 5]}
                ).encode(),
            )
            for _ in range(3):
                got = self._post(s, "/index/ds/query", b"Count(Row(f=1))")
                assert got == {"results": [3]}
            snap = json.loads(self._get(s, "/debug/dispatch"))
            assert snap["enabled"] is True
            assert snap["items"] >= 3
            assert snap["waves"] >= 1
            assert 0.0 <= snap["device_idle_fraction"] <= 1.0
            for key in ("queued", "inflight_waves", "dedup_hits",
                        "combined_items", "deadline_expired"):
                assert key in snap
            prom = self._get(s, "/metrics").decode()
            assert "pilosa_dispatch_wave_size" in prom
            assert "pilosa_dispatch_queue_wait_seconds" in prom
            assert "pilosa_dispatch_inflight_depth" in prom
            assert "pilosa_dispatch_device_idle_fraction" in prom
            engine = s.executor.dispatch_engine
        finally:
            s.close()
        # server close closed the engine; snapshot says so
        assert engine.stats()["closing"] is True
        assert engine.stats()["queued"] == 0

    def test_cli_metrics_dispatch_flag(self, tmp_path, capsys):
        from pilosa_tpu.cli.main import main

        s = self._mkserver(tmp_path)
        try:
            self._post(s, "/index/dc", b"{}")
            self._post(s, "/index/dc/field/f", b"{}")
            self._post(s, "/index/dc/query", b"Set(1, f=1)")
            self._post(s, "/index/dc/query", b"Count(Row(f=1))")
            rc = main(["metrics", "--host", s.uri, "--dispatch"])
            assert rc == 0
            out = capsys.readouterr().out
            snap = json.loads(out)
            assert snap["enabled"] is True
            assert snap["items"] >= 1
        finally:
            s.close()

    def test_dispatch_disabled_config(self, tmp_path):
        s = self._mkserver(tmp_path, dispatch_enabled=False)
        try:
            assert s.executor.dispatch_engine is None
            assert s.pipeline.stats()["dispatch_handoff"] is False
            snap = json.loads(self._get(s, "/debug/dispatch"))
            assert snap == {"enabled": False}
        finally:
            s.close()


class TestStageAhead:
    def test_stage_ahead_warms_queued_rows(self, holder):
        """The legacy thunk-based stage-ahead hook fires at wave launch
        for items still queued behind the wave; warming is advisory
        (errors swallowed, execution correct regardless). The
        plan-driven prefetcher (the default) is covered by
        test_plan_driven_prefetcher_stages_queued_operands."""
        seed_mixed(holder)
        # max_wave=1 so each launch leaves the rest of the backlog
        # queued — that leftover is what the peek prefetches
        ex = Executor(
            holder, device_policy="always", dispatch_enabled=True,
            dispatch_max_inflight=1, dispatch_max_wave=1,
            prefetch_enabled=False,
        )
        orig = ex._execute
        gate = threading.Event()
        first = threading.Event()

        def gated(index, query, shards=None, opt=None):
            if not first.is_set():
                first.set()
                assert gate.wait(10), "test gate never released"
            return orig(index, query, shards, opt)

        ex._execute = gated
        try:
            warmed = []
            orig_warm = ex._warm_query
            ex._warm_query = lambda *a: warmed.append(a) or orig_warm(*a)
            blocker = threading.Thread(
                target=lambda: ex.execute("i", "Count(Row(f=0))")
            )
            blocker.start()
            assert first.wait(10)
            res = {}

            def client(i):
                res[i] = ex.execute("i", f"Count(Row(f={i + 3}))")

            ts = [threading.Thread(target=client, args=(i,)) for i in range(3)]
            for t in ts:
                t.start()
            _wait_queued(ex.dispatch_engine, 3)
            gate.set()
            for t in ts:
                t.join()
            blocker.join()
            deadline = time.monotonic() + 2.0
            while not warmed and time.monotonic() < deadline:
                time.sleep(0.01)
            assert warmed  # the async stage-ahead hook really ran
            oracle = Executor(
                holder, device_policy="never", dispatch_enabled=False
            )
            for i in range(3):
                assert res[i] == oracle.execute("i", f"Count(Row(f={i + 3}))")
        finally:
            gate.set()
            ex.close()

    def test_plan_driven_prefetcher_stages_queued_operands(self, holder):
        """With the prefetcher enabled (the default), wave launch hands
        queued items' PLANS to the scheduler, which stages exactly the
        operand rows they name — observable as prefetch_issued on the
        stager and scheduled on the prefetcher; results stay
        bit-identical to the CPU oracle."""
        seed_mixed(holder)
        ex = Executor(
            holder, device_policy="always", dispatch_enabled=True,
            dispatch_max_inflight=1, dispatch_max_wave=1,
            prefetch_enabled=True,
        )
        assert ex.prefetcher is not None and ex.prefetcher.enabled
        orig = ex._execute
        gate = threading.Event()
        first = threading.Event()

        def gated(index, query, shards=None, opt=None):
            if not first.is_set():
                first.set()
                assert gate.wait(10), "test gate never released"
            return orig(index, query, shards, opt)

        ex._execute = gated
        try:
            blocker = threading.Thread(
                target=lambda: ex.execute("i", "Count(Row(f=0))")
            )
            blocker.start()
            assert first.wait(10)
            res = {}

            def client(i):
                res[i] = ex.execute("i", f"Count(Row(f={i + 3}))")

            ts = [threading.Thread(target=client, args=(i,)) for i in range(3)]
            for t in ts:
                t.start()
            _wait_queued(ex.dispatch_engine, 3)
            gate.set()
            for t in ts:
                t.join()
            blocker.join()
            deadline = time.monotonic() + 2.0
            while ex.prefetcher.scheduled == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert ex.prefetcher.scheduled > 0
            st = ex.dispatch_engine.stats()
            assert st["prefetch"]["enabled"] is True
            assert st["prefetch"]["scheduled"] == ex.prefetcher.scheduled
            oracle = Executor(
                holder, device_policy="never", dispatch_enabled=False
            )
            for i in range(3):
                assert res[i] == oracle.execute("i", f"Count(Row(f={i + 3}))")
        finally:
            gate.set()
            ex.close()
