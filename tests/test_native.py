"""Native C++ kernel bridge tests — parity with numpy references, and the
build/fallback path."""

import numpy as np
import pytest

from pilosa_tpu import native_bridge


def test_builds_and_loads():
    # g++ is in the image; the library must build and load
    assert native_bridge.available()


def test_popcount_parity():
    rng = np.random.default_rng(1)
    w = rng.integers(0, 2**64, size=4096, dtype=np.uint64)
    assert native_bridge.popcount(w) == int(np.bitwise_count(w).sum())


def test_intersection_count_words():
    rng = np.random.default_rng(2)
    a = rng.integers(0, 2**64, size=2048, dtype=np.uint64)
    b = rng.integers(0, 2**64, size=2048, dtype=np.uint64)
    assert native_bridge.intersection_count_words(a, b) == int(
        np.bitwise_count(a & b).sum()
    )


def test_sorted_u16_ops():
    rng = np.random.default_rng(3)
    a = np.unique(rng.integers(0, 65536, size=3000).astype(np.uint16))
    b = np.unique(rng.integers(0, 65536, size=3000).astype(np.uint16))
    want = np.intersect1d(a, b, assume_unique=True)
    got = native_bridge.intersect_sorted_u16(a, b)
    assert np.array_equal(got, want)
    assert native_bridge.intersection_count_sorted_u16(a, b) == want.size


def test_matrix_counts():
    rng = np.random.default_rng(4)
    src = rng.integers(0, 2**64, size=256, dtype=np.uint64)
    mat = rng.integers(0, 2**64, size=(64, 256), dtype=np.uint64)
    want = np.bitwise_count(mat & src[None, :]).sum(axis=1)
    got = native_bridge.intersection_counts_matrix(src, mat)
    assert np.array_equal(got, want)


def test_popcount_per_block():
    rng = np.random.default_rng(5)
    w = rng.integers(0, 2**64, size=16 * 128, dtype=np.uint64)
    want = np.bitwise_count(w.reshape(16, 128)).sum(axis=1)
    got = native_bridge.popcount_per_block(w, 128)
    assert np.array_equal(got, want)
