"""Native C++ kernel bridge tests — parity with numpy references, and the
build/fallback path."""

import numpy as np
import pytest

from pilosa_tpu import native_bridge


def test_builds_and_loads():
    # g++ is in the image; the library must build and load
    assert native_bridge.available()


def test_popcount_parity():
    rng = np.random.default_rng(1)
    w = rng.integers(0, 2**64, size=4096, dtype=np.uint64)
    assert native_bridge.popcount(w) == int(np.bitwise_count(w).sum())


def test_intersection_count_words():
    rng = np.random.default_rng(2)
    a = rng.integers(0, 2**64, size=2048, dtype=np.uint64)
    b = rng.integers(0, 2**64, size=2048, dtype=np.uint64)
    assert native_bridge.intersection_count_words(a, b) == int(
        np.bitwise_count(a & b).sum()
    )


def test_sorted_u16_ops():
    rng = np.random.default_rng(3)
    a = np.unique(rng.integers(0, 65536, size=3000).astype(np.uint16))
    b = np.unique(rng.integers(0, 65536, size=3000).astype(np.uint16))
    want = np.intersect1d(a, b, assume_unique=True)
    got = native_bridge.intersect_sorted_u16(a, b)
    assert np.array_equal(got, want)
    assert native_bridge.intersection_count_sorted_u16(a, b) == want.size


def test_matrix_counts():
    rng = np.random.default_rng(4)
    src = rng.integers(0, 2**64, size=256, dtype=np.uint64)
    mat = rng.integers(0, 2**64, size=(64, 256), dtype=np.uint64)
    want = np.bitwise_count(mat & src[None, :]).sum(axis=1)
    got = native_bridge.intersection_counts_matrix(src, mat)
    assert np.array_equal(got, want)


def test_popcount_per_block():
    rng = np.random.default_rng(5)
    w = rng.integers(0, 2**64, size=16 * 128, dtype=np.uint64)
    want = np.bitwise_count(w.reshape(16, 128)).sum(axis=1)
    got = native_bridge.popcount_per_block(w, 128)
    assert np.array_equal(got, want)


class TestExpandBlocks:
    """Native mmap-direct container expansion (staging pack hot loop):
    must match the per-container Python decode bit for bit across all
    three container forms."""

    def test_matches_python_decode(self, tmp_path):
        import numpy as np

        from pilosa_tpu.roaring import Bitmap
        from pilosa_tpu import native_bridge

        if not native_bridge.available():
            import pytest

            pytest.skip("native library unavailable")
        rng = np.random.default_rng(21)
        b = Bitmap()
        # array containers
        for c in range(6):
            vals = np.unique(rng.integers(0, 1 << 16, size=500, dtype=np.uint64))
            b.merge_positions(add=np.uint64(c << 16) + vals)
        # a dense bitmap container and run containers
        b.merge_positions(add=np.uint64(10 << 16) + np.arange(40000, dtype=np.uint64))
        b.merge_positions(add=np.uint64(12 << 16) + np.arange(300, dtype=np.uint64))
        b.merge_positions(
            add=np.uint64(12 << 16) + np.arange(1000, 1500, dtype=np.uint64)
        )
        b.optimize()
        p = str(tmp_path / "frag")
        with open(p, "wb") as f:
            b.write_to(f)
        lazy = Bitmap.open_mmap_file(p)
        store = lazy.containers
        n = store._base_n
        assert n >= 8
        sel = np.arange(n, dtype=np.int64)
        out = np.zeros((n, 1024), dtype=np.uint64)
        assert store.expand_base_blocks(sel, out)
        for j in range(n):
            k = int(store.metas["key"][j])
            want = store.get(k).words()
            assert np.array_equal(out[j], want), f"container {k}"

    def test_truncated_file_declines_instead_of_oob(self, tmp_path):
        """File-provided offsets are bounds-checked in the kernel: a
        truncated fragment file must make expand_base_blocks return
        False (Python decode then surfaces the corruption as an error)
        rather than read past the mmap (SIGSEGV on the serving path)."""
        import numpy as np

        from pilosa_tpu.roaring import Bitmap
        from pilosa_tpu import native_bridge

        if not native_bridge.available():
            import pytest

            pytest.skip("native library unavailable")
        b = Bitmap()
        # a dense bitmap container (8 KiB payload, NOT optimize()d —
        # arange would convert to a tiny run container and the payload
        # would fit inside any truncation)
        rng = np.random.default_rng(7)
        b.merge_positions(
            add=np.unique(rng.integers(0, 1 << 16, size=40000, dtype=np.uint64))
        )
        p = str(tmp_path / "frag")
        with open(p, "wb") as f:
            b.write_to(f)
        lazy = Bitmap.open_mmap_file(p)
        store = lazy.containers
        # corrupt the offsets table the way a damaged file would: point
        # the container payload within a page of the buffer end, so the
        # 8 KiB bitmap payload would run past the mmap
        store.offsets = store.offsets.copy()
        store.offsets[:] = max(0, len(store.buf) - 16)
        out = np.zeros((store._base_n, 1024), dtype=np.uint64)
        sel = np.arange(store._base_n, dtype=np.int64)
        assert not store.expand_base_blocks(sel, out)
        assert not out.any()  # partial expansion discarded

    def test_impure_store_declines(self, tmp_path):
        import numpy as np

        from pilosa_tpu.roaring import Bitmap

        b = Bitmap()
        b.merge_positions(add=np.arange(100, dtype=np.uint64))
        p = str(tmp_path / "frag")
        with open(p, "wb") as f:
            b.write_to(f)
        lazy = Bitmap.open_mmap_file(p)
        lazy.add_no_oplog(5 << 16)  # overlay → indices no longer base
        out = np.zeros((1, 1024), dtype=np.uint64)
        assert not lazy.containers.expand_base_blocks(
            np.zeros(1, dtype=np.int64), out
        )


class TestCsvFastPath:
    """Native import CSV parser: strict 2-column u64 lines at C speed;
    ANY deviation defers to the Python csv loop (which owns error
    reporting and timestamp handling — reference ctl/import.go)."""

    def test_parses_and_matches(self):
        from pilosa_tpu import native_bridge

        if not native_bridge.available():
            import pytest

            pytest.skip("native library unavailable")
        got = native_bridge.parse_csv_pairs(
            b"1,2\n3,4\r\n\n18446744073709551615,0\n5,6"
        )
        assert got is not None
        a, b = got
        assert a.tolist() == [1, 3, 18446744073709551615, 5]
        assert b.tolist() == [2, 4, 0, 6]

    def test_deviations_defer_to_python(self):
        from pilosa_tpu import native_bridge

        if not native_bridge.available():
            import pytest

            pytest.skip("native library unavailable")
        for bad in (
            b"1,2,2018-01-02T03:04\n",  # timestamp column
            b"1, 2\n",                   # spaces
            b'"1",2\n',                  # quoting
            b"a,2\n",
            b"1,\n",
            b",2\n",
            b"18446744073709551616,1\n",  # u64 overflow
            b"1,2\x003,4\n",              # junk separator
        ):
            assert native_bridge.parse_csv_pairs(bad) is None, bad

    def test_format_round_trips_with_parse(self):
        from pilosa_tpu import native_bridge

        if not native_bridge.available():
            import pytest

            pytest.skip("native library unavailable")
        a = np.array([0, 1, 18446744073709551615, 42], dtype=np.uint64)
        b = np.array([5, 0, 7, 1 << 20], dtype=np.uint64)
        out = native_bridge.format_csv_pairs(a, b)
        assert out == b"0,5\n1,0\n18446744073709551615,7\n42,1048576\n"
        ra, rb = native_bridge.parse_csv_pairs(out)
        assert ra.tolist() == a.tolist() and rb.tolist() == b.tolist()
