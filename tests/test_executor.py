"""Executor tests — every PQL call, CPU vs device paths bit-identical
(mirrors reference executor_test.go)."""

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import FieldOptions, Holder
from pilosa_tpu.core.field import FIELD_TYPE_INT, FIELD_TYPE_TIME
from pilosa_tpu.executor import Executor, ValCount


@pytest.fixture()
def holder():
    h = Holder()  # in-memory
    h.open()
    return h


def execu(holder, policy="never"):
    return Executor(holder, device_policy=policy)


class TestBitmapCalls:
    def setup_holder(self, h):
        idx = h.create_index("i")
        f = idx.create_field("general")
        f.set_bit(10, 3)
        f.set_bit(10, SHARD_WIDTH + 1)
        f.set_bit(10, SHARD_WIDTH + 2)
        f.set_bit(11, 2)
        f.set_bit(11, SHARD_WIDTH + 2)
        f.set_bit(12, SHARD_WIDTH + 2)
        return idx

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_row(self, holder, policy):
        self.setup_holder(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", "Row(general=10)")
        assert res.columns().tolist() == [3, SHARD_WIDTH + 1, SHARD_WIDTH + 2]

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_intersect(self, holder, policy):
        self.setup_holder(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", "Intersect(Row(general=10), Row(general=11))")
        assert res.columns().tolist() == [SHARD_WIDTH + 2]

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_union(self, holder, policy):
        self.setup_holder(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", "Union(Row(general=10), Row(general=11))")
        assert res.columns().tolist() == [2, 3, SHARD_WIDTH + 1, SHARD_WIDTH + 2]

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_difference(self, holder, policy):
        self.setup_holder(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", "Difference(Row(general=10), Row(general=11))")
        assert res.columns().tolist() == [3, SHARD_WIDTH + 1]

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_xor(self, holder, policy):
        self.setup_holder(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", "Xor(Row(general=10), Row(general=11))")
        assert res.columns().tolist() == [2, 3, SHARD_WIDTH + 1]

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_count(self, holder, policy):
        self.setup_holder(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", "Count(Row(general=10))")
        assert res == 3
        (res,) = e.execute(
            "i", "Count(Intersect(Row(general=10), Row(general=12)))"
        )
        assert res == 1

    def test_empty_union(self, holder):
        self.setup_holder(holder)
        e = execu(holder)
        (res,) = e.execute("i", "Union()")
        assert res.columns().tolist() == []

    def test_empty_intersect_raises(self, holder):
        self.setup_holder(holder)
        e = execu(holder)
        with pytest.raises(ValueError):
            e.execute("i", "Intersect()")

    def test_set_and_clear(self, holder):
        idx = holder.create_index("i")
        idx.create_field("f")
        e = execu(holder)
        assert e.execute("i", "Set(3, f=10)") == [True]
        assert e.execute("i", "Set(3, f=10)") == [False]
        (row,) = e.execute("i", "Row(f=10)")
        assert row.columns().tolist() == [3]
        assert e.execute("i", "Clear(3, f=10)") == [True]
        assert e.execute("i", "Clear(3, f=10)") == [False]


class TestBSICalls:
    def setup_bsi(self, h):
        idx = h.create_index("i")
        idx.create_field("f")  # for filters
        idx.create_field(
            "foo", FieldOptions(type=FIELD_TYPE_INT, min=-100, max=3000)
        )
        e = execu(h)
        vals = {0: 20, 1: -5, 2: -5, 3: 10, SHARD_WIDTH: 30, SHARD_WIDTH + 2: 40}
        for col, v in vals.items():
            e.execute("i", f"SetValue(col={col}, foo={v})")
        # filter rows
        for col in [0, 1, 2, 3, SHARD_WIDTH, SHARD_WIDTH + 2]:
            e.execute("i", f"Set({col}, f=1)")
        for col in [0, 3, SHARD_WIDTH + 2]:
            e.execute("i", f"Set({col}, f=2)")
        return vals

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_sum(self, holder, policy):
        vals = self.setup_bsi(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", 'Sum(field="foo")')
        assert res == ValCount(sum(vals.values()), len(vals))
        (res,) = e.execute("i", 'Sum(Row(f=2), field="foo")')
        assert res == ValCount(20 + 10 + 40, 3)

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_min_max(self, holder, policy):
        self.setup_bsi(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", 'Min(field="foo")')
        assert res == ValCount(-5, 2)
        (res,) = e.execute("i", 'Max(field="foo")')
        assert res == ValCount(40, 1)
        (res,) = e.execute("i", 'Min(Row(f=2), field="foo")')
        assert res == ValCount(10, 1)
        (res,) = e.execute("i", 'Max(Row(f=2), field="foo")')
        assert res == ValCount(40, 1)

    @pytest.mark.parametrize("policy", ["never", "always"])
    @pytest.mark.parametrize(
        "q,want",
        [
            ("Range(foo > 20)", {SHARD_WIDTH, SHARD_WIDTH + 2}),
            ("Range(foo >= 20)", {0, SHARD_WIDTH, SHARD_WIDTH + 2}),
            ("Range(foo < 10)", {1, 2}),
            ("Range(foo <= 10)", {1, 2, 3}),
            ("Range(foo == -5)", {1, 2}),
            ("Range(foo != -5)", {0, 3, SHARD_WIDTH, SHARD_WIDTH + 2}),
            ("Range(foo != null)", {0, 1, 2, 3, SHARD_WIDTH, SHARD_WIDTH + 2}),
            ("Range(foo >< [10, 30])", {0, 3, SHARD_WIDTH}),
            # out-of-range guards
            ("Range(foo > 5000)", set()),
            ("Range(foo < -200)", set()),
            # fully-encompassing → not-null
            ("Range(foo < 99999)", {0, 1, 2, 3, SHARD_WIDTH, SHARD_WIDTH + 2}),
        ],
    )
    def test_range(self, holder, policy, q, want):
        self.setup_bsi(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", q)
        assert set(res.columns().tolist()) == want

    def test_range_as_filter(self, holder):
        self.setup_bsi(holder)
        for policy in ("never", "always"):
            e = execu(holder, policy)
            (res,) = e.execute("i", 'Count(Range(foo > 0))')
            assert res == 4
            (res,) = e.execute("i", 'Sum(Range(foo > 0), field="foo")')
            assert res == ValCount(20 + 10 + 30 + 40, 4)


class TestTopN:
    def setup_topn(self, h):
        idx = h.create_index("i")
        f = idx.create_field("f")
        other = idx.create_field("other")
        e = execu(h)
        # row 0: 5 bits, row 10: 3 bits, row 20: 2 bits, row 30: 1 bit
        bits = []
        for col in range(5):
            bits.append((0, col))
        for col in range(3):
            bits.append((10, col))
        for col in [0, SHARD_WIDTH]:
            bits.append((20, col))
        bits.append((30, SHARD_WIDTH + 5))
        f.import_bits([b[0] for b in bits], [b[1] for b in bits])
        other.import_bits([0] * 3, [0, 1, 2])
        return e

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_topn_plain(self, holder, policy):
        self.setup_topn(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", "TopN(f, n=2)")
        assert res == [{"id": 0, "count": 5}, {"id": 10, "count": 3}]
        (res,) = e.execute("i", "TopN(f)")
        assert res == [
            {"id": 0, "count": 5},
            {"id": 10, "count": 3},
            {"id": 20, "count": 2},
            {"id": 30, "count": 1},
        ]

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_topn_with_src(self, holder, policy):
        self.setup_topn(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", "TopN(f, Row(other=0), n=2)")
        # intersection with cols {0,1,2}: row0 → 3, row10 → 3, row20 → 1
        assert res == [{"id": 0, "count": 3}, {"id": 10, "count": 3}]

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_topn_ids(self, holder, policy):
        self.setup_topn(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", "TopN(f, ids=[10, 30])")
        assert res == [{"id": 10, "count": 3}, {"id": 30, "count": 1}]

    @pytest.mark.parametrize("policy", ["never", "always"])
    def test_topn_threshold(self, holder, policy):
        self.setup_topn(holder)
        e = execu(holder, policy)
        (res,) = e.execute("i", "TopN(f, threshold=2)")
        # row 20 has 2 bits total but 1 per shard: the threshold applies
        # per shard in the reference (fragment.top MinThreshold check), so
        # it is excluded here exactly as the reference excludes it.
        assert res == [
            {"id": 0, "count": 5},
            {"id": 10, "count": 3},
        ]


class TestTimeRange:
    def test_range_quantum_views(self, holder):
        idx = holder.create_index("i")
        idx.create_field(
            "f", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMDH")
        )
        e = execu(holder)
        e.execute("i", "Set(2, f=1, 2010-01-01T00:00)")
        e.execute("i", "Set(3, f=1, 2010-01-02T00:00)")
        e.execute("i", "Set(4, f=1, 2010-01-05T00:00)")
        e.execute("i", "Set(5, f=1, 2010-02-01T00:00)")
        e.execute("i", "Set(6, f=1, 2011-01-01T00:00)")
        for policy in ("never", "always"):
            e2 = execu(holder, policy)
            (res,) = e2.execute(
                "i", "Range(f=1, 2010-01-01T00:00, 2010-01-03T00:00)"
            )
            assert res.columns().tolist() == [2, 3], policy
            (res,) = e2.execute(
                "i", "Range(f=1, 2010-01-01T00:00, 2012-01-01T00:00)"
            )
            assert res.columns().tolist() == [2, 3, 4, 5, 6], policy

    def test_auto_policy_estimates_time_range_views(self, holder):
        """The touched-container estimate must COUNT quantum views for
        a time-range Range (it was 0 before, so the auto policy never
        routed time ranges to the existing device lowering — VERDICT
        §6), and must still estimate 0 for an empty span."""
        from pilosa_tpu.pql import parse

        idx = holder.create_index("tr")
        idx.create_field(
            "f", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMD")
        )
        e = execu(holder)
        for day in (1, 2, 5):
            e.execute("tr", f"Set(2, f=1, 2010-01-0{day}T00:00)")
        call = parse("Range(f=1, 2010-01-01T00:00, 2010-01-06T00:00)").calls[0]
        est = e._touched_containers("tr", call, 0)
        # row 1 occupies one container in each of: 3 day views, 1 month
        # view, 1 year view, plus the standard view union targets — the
        # exact count depends on quantum fan-out; what matters is that
        # the populated span is VISIBLE to the policy
        assert est > 0
        empty = parse("Range(f=1, 2015-01-01T00:00, 2015-01-06T00:00)").calls[0]
        assert e._touched_containers("tr", empty, 0) == 0
        # a batched Count over the populated span routes like the
        # policy's own estimate says (crossover default 64)
        e_auto = execu(holder, "auto")
        cnt_call = parse(
            "Count(Range(f=1, 2010-01-01T00:00, 2010-01-06T00:00))"
        ).calls[0]
        expect = (
            sum(
                e_auto._touched_containers("tr", cnt_call.children[0], s)
                for s in [0]
            )
            >= e_auto.auto_min_containers
        )
        assert e_auto._use_device_batched_decide("tr", cnt_call.children[0], [0]) is (
            False
        )  # single shard: batched path needs >= 2 shards
        assert isinstance(expect, bool)


class TestAutoPolicyEquivalence:
    def test_large_random_workload(self, holder):
        """Property test: CPU vs device identical on a random workload."""
        rng = np.random.default_rng(42)
        idx = holder.create_index("i")
        f = idx.create_field("f")
        rows = rng.integers(0, 50, size=3000)
        cols = rng.integers(0, 2 * SHARD_WIDTH, size=3000)
        f.import_bits(rows.tolist(), cols.tolist())
        queries = [
            "Count(Row(f=1))",
            "Count(Intersect(Row(f=1), Row(f=2), Row(f=3)))",
            "Count(Union(Row(f=1), Row(f=2), Xor(Row(f=4), Row(f=5))))",
            "Count(Difference(Row(f=1), Row(f=2)))",
            "TopN(f, n=10)",
            "TopN(f, Row(f=7), n=5)",
            "Row(f=3)",
            "Union(Row(f=1), Row(f=9))",
        ]
        e_cpu = execu(holder, "never")
        e_dev = execu(holder, "always")
        for q in queries:
            r_cpu = e_cpu.execute("i", q)
            r_dev = e_dev.execute("i", q)
            for a, b in zip(r_cpu, r_dev):
                if hasattr(a, "columns"):
                    assert a.columns().tolist() == b.columns().tolist(), q
                else:
                    assert a == b, q


class TestBatchedShardPath:
    def test_batched_count_and_sum_match_cpu(self, holder):
        """Shard-batched device path (one dispatch over u32[S, W] stacks)
        vs the CPU per-shard path on a many-shard workload."""
        rng = np.random.default_rng(77)
        idx = holder.create_index("i")
        f = idx.create_field("f")
        from pilosa_tpu.core.field import FieldOptions

        v = idx.create_field("v", FieldOptions(type="int", min=-50, max=5000))
        n_shards = 6
        rows = rng.integers(0, 20, size=4000)
        cols = rng.integers(0, n_shards * SHARD_WIDTH, size=4000)
        f.import_bits(rows.tolist(), cols.tolist())
        vcols = rng.choice(n_shards * SHARD_WIDTH, size=1500, replace=False)
        vvals = rng.integers(-50, 5000, size=1500)
        v.import_values(vcols.tolist(), vvals.tolist())

        queries = [
            "Count(Row(f=1))",
            "Count(Intersect(Row(f=1), Row(f=2)))",
            "Count(Union(Row(f=3), Xor(Row(f=4), Row(f=5)), Difference(Row(f=6), Row(f=7))))",
            "Count(Range(v > 100))",
            "Count(Range(v >< [0, 2500]))",
            'Sum(field="v")',
            'Sum(Row(f=1), field="v")',
            'Sum(Range(v != null), field="v")',
        ]
        e_cpu = execu(holder, "never")
        e_dev = execu(holder, "always")
        for q in queries:
            assert e_cpu.execute("i", q) == e_dev.execute("i", q), q
