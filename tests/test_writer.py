"""Streaming roaring file builder: format equivalence with the eager
writer, chunk-boundary healing, dense-container handling, and the
fragment-level .cache sidecar."""

import numpy as np

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.roaring import Bitmap, build_fragment_file, write_roaring_file


def _chunked(vals, k):
    return [vals[i : i + k] for i in range(0, len(vals), k)]


class TestWriteRoaringFile:
    def test_matches_eager_writer(self, tmp_path):
        rng = np.random.default_rng(21)
        vals = np.unique(rng.integers(0, 1 << 24, size=20000, dtype=np.uint64))
        p = str(tmp_path / "r")
        keys, ns = write_roaring_file(p, _chunked(vals, 777))
        with open(p, "rb") as f:
            got = f.read()
        want = Bitmap.from_sorted(vals).to_bytes()
        assert got == want
        assert int(ns.sum()) == vals.size
        b = Bitmap.unmarshal_mmap(got)
        assert np.array_equal(b.slice_all(), vals)

    def test_dense_containers(self, tmp_path):
        # one container over the array/bitmap threshold mid-stream; the
        # builder writes array/bitmap forms only (no run optimization),
        # so compare decoded content rather than bytes
        dense = np.arange(6000, dtype=np.uint64) + (5 << 16)
        sparse_a = np.array([1, 2, 3], dtype=np.uint64)
        sparse_b = np.array([(9 << 16) + 7], dtype=np.uint64)
        vals = np.concatenate([sparse_a, dense, sparse_b])
        p = str(tmp_path / "r")
        write_roaring_file(p, _chunked(vals, 100))
        with open(p, "rb") as f:
            data = f.read()
        b = Bitmap.unmarshal_binary(data)
        assert np.array_equal(b.slice_all(), vals)
        from pilosa_tpu.roaring import CONTAINER_BITMAP

        assert b.containers[5].typ == CONTAINER_BITMAP

    def test_chunk_boundary_inside_container(self, tmp_path):
        vals = np.arange(100, dtype=np.uint64)  # single container
        p = str(tmp_path / "r")
        write_roaring_file(p, _chunked(vals, 7))
        b = Bitmap.unmarshal_mmap(open(p, "rb").read())
        assert b.count() == 100

    def test_empty(self, tmp_path):
        p = str(tmp_path / "r")
        keys, ns = write_roaring_file(p, [])
        b = Bitmap.unmarshal_mmap(open(p, "rb").read())
        assert b.count() == 0
        assert keys.size == 0


class TestBuildFragmentFile:
    def test_fragment_opens_and_queries(self, tmp_path):
        p = str(tmp_path / "frag" / "0")
        rng = np.random.default_rng(22)
        rows = np.sort(rng.choice(100_000, size=5000, replace=False).astype(np.uint64))
        # one bit per row at a random column, plus a hot row 7
        cols = rng.integers(0, SHARD_WIDTH, size=rows.size, dtype=np.uint64)
        pos = np.unique(rows * np.uint64(SHARD_WIDTH) + cols)
        hot = np.uint64(7 * SHARD_WIDTH) + np.arange(500, dtype=np.uint64) * 13
        pos = np.unique(np.concatenate([pos, hot]))
        stats = build_fragment_file(p, _chunked(pos, 1009), cache_size=100)
        assert stats["bits"] == pos.size
        assert stats["cached_rows"] == 100

        f = Fragment(p, "i", "f", "standard", 0)
        f.open()
        assert f.storage.is_mmap_backed()
        assert f.row(7).count() >= 500
        top = f.top(__import__("pilosa_tpu.core.fragment", fromlist=["TopOptions"]).TopOptions(n=5))
        assert top[0][0] == 7  # the hot row ranks first
        f.close()

    def test_cache_holds_top_rows(self, tmp_path):
        p = str(tmp_path / "frag" / "0")
        # rows 0..49, row r has r+1 bits; cache_size 10 keeps rows 40..49
        pos = []
        for r in range(50):
            pos.append(r * SHARD_WIDTH + np.arange(r + 1, dtype=np.uint64))
        pos = np.unique(np.concatenate(pos).astype(np.uint64))
        build_fragment_file(p, [pos], cache_size=10)
        ids = cache_mod.read_cache(p + ".cache")
        assert ids == list(range(40, 50))
