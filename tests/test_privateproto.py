"""Control-plane protobuf envelope (reference broadcast.go:52-158,
internal/private.proto): every cluster message round-trips through the
1-byte-type + protobuf-body wire form, and the HTTP endpoint accepts it."""

import urllib.error
import urllib.request

import pytest

from pilosa_tpu.utils import privateproto as pp


SCHEMA = [
    {
        "name": "idx",
        "keys": True,
        "fields": [
            {
                "name": "f",
                "options": {
                    "type": "int",
                    "cacheType": "ranked",
                    "cacheSize": 50000,
                    "min": -250,
                    "max": 1000,
                    "timeQuantum": "",
                    "keys": False,
                },
                "views": ["bsig_f"],
            },
            {
                "name": "t",
                "options": {
                    "type": "time",
                    "cacheType": "ranked",
                    "cacheSize": 50000,
                    "min": 0,
                    "max": 0,
                    "timeQuantum": "YMD",
                    "keys": True,
                },
                "views": ["standard", "standard_2017"],
            },
        ],
    }
]

NODES = [
    {"id": "n0", "uri": "http://127.0.0.1:10101", "isCoordinator": True, "state": "READY"},
    {"id": "n1", "uri": "https://10.0.0.2:9999", "isCoordinator": False, "state": "DOWN"},
]

MESSAGES = [
    {"type": "create-shard", "index": "idx", "shard": 37},
    {"type": "create-index", "index": "idx", "keys": True},
    {"type": "create-index", "index": "idx", "keys": False},
    {"type": "delete-index", "index": "idx"},
    {
        "type": "create-field",
        "index": "idx",
        "field": "f",
        "options": SCHEMA[0]["fields"][0]["options"],
    },
    {"type": "delete-field", "index": "idx", "field": "f"},
    {"type": "create-view", "index": "idx", "field": "f", "view": "standard_2017"},
    {"type": "delete-view", "index": "idx", "field": "f", "view": "standard_2017"},
    {
        "type": "cluster-status",
        "state": "NORMAL",
        "nodes": NODES,
        "schema": SCHEMA,
        "maxShards": {"idx": 63, "other": 0},
        "replicaN": 2,
        "partitionN": 256,
        "fromCoordinator": True,
    },
    {
        "type": "resize-instruction",
        "job": 3,
        "coordinator": "http://127.0.0.1:10101",
        "schema": SCHEMA,
        "sources": [
            {
                "index": "idx",
                "field": "f",
                "view": "standard",
                "shard": 5,
                "from_uri": "http://127.0.0.1:10102",
                "from_uris": [
                    "http://127.0.0.1:10102",
                    "http://127.0.0.1:10103",
                ],
            }
        ],
        "node": NODES[1],
        "new_nodes": NODES,
    },
    {"type": "resize-complete", "job": 3, "node_id": "n1", "ok": True},
    {"type": "resize-complete", "job": 3, "node_id": "n1", "ok": False, "error": "boom"},
    {"type": "set-coordinator", "node": NODES[0]},
    {"type": "update-coordinator", "node": NODES[0]},
    {"type": "node-state", "node_id": "n1", "state": "READY"},
    {"type": "recalculate-caches"},
    {"type": "node-join", "node": NODES[1]},
    {"type": "node-status", "node_id": "n0", "schema": SCHEMA, "maxShards": {"idx": 12}},
    {"type": "holder-clean"},
    {"type": "schema", "schema": SCHEMA},
]


class TestRoundTrip:
    @pytest.mark.parametrize("msg", MESSAGES, ids=lambda m: m["type"] + (":err" if m.get("error") else ""))
    def test_round_trip(self, msg):
        buf = pp.marshal_message(msg)
        out = pp.unmarshal_message(buf)
        # every key the sender set must survive the wire
        for k, v in msg.items():
            assert out[k] == v, (k, out.get(k), v)

    def test_envelope_bytes_match_reference(self):
        # broadcast.go:52-68 iota numbering
        assert pp.marshal_message({"type": "create-shard", "index": "i", "shard": 0})[0] == 0
        assert pp.marshal_message({"type": "create-index", "index": "i"})[0] == 1
        assert pp.marshal_message({"type": "delete-index", "index": "i"})[0] == 2
        assert pp.marshal_message({"type": "cluster-status", "state": "NORMAL", "nodes": []})[0] == 7
        assert pp.marshal_message({"type": "recalculate-caches"})[0] == 13
        assert pp.marshal_message({"type": "node-join", "node": NODES[0]})[0] == 14

    def test_unknown_type_rejected(self):
        with pytest.raises(KeyError):
            pp.marshal_message({"type": "no-such-message"})
        with pytest.raises(ValueError):
            pp.unmarshal_message(b"")
        with pytest.raises(ValueError):
            pp.unmarshal_message(bytes([250]) + b"\x00")

    def test_truncated_body_rejected(self):
        buf = pp.marshal_message(
            {"type": "cluster-status", "state": "NORMAL", "nodes": NODES, "schema": SCHEMA}
        )
        with pytest.raises(ValueError):
            pp.unmarshal_message(buf[: len(buf) - 4])

    def test_bare_ipv6_addresses_survive(self):
        # a digits-only final colon group must never be split off as a
        # port from a bare IPv6 literal, and the decoded form must be
        # a fixed point: re-encoding it yields the same (host, port)
        for addr, want in [
            ("http://::1", "http://[::1]:10101"),
            ("http://fd00::2", "http://[fd00::2]:10101"),
            ("http://[fd00::2]:9999", "http://[fd00::2]:9999"),
            ("http://[::1]", "http://[::1]:10101"),
        ]:
            msg = {"type": "node-join", "node": {"id": "x", "uri": addr}}
            out = pp.unmarshal_message(pp.marshal_message(msg))
            assert out["node"]["uri"] == want, addr
            # idempotent across relay hops
            msg2 = {"type": "node-join", "node": {"id": "x", "uri": out["node"]["uri"]}}
            out2 = pp.unmarshal_message(pp.marshal_message(msg2))
            assert out2["node"]["uri"] == want, addr

    def test_lenient_node_addresses_encode(self):
        # addresses already in a topology must encode even when they
        # would fail strict URI validation (underscore hosts etc.)
        msg = {
            "type": "node-join",
            "node": {"id": "n9", "uri": "http://pilosa_node_1:10101", "isCoordinator": False},
        }
        out = pp.unmarshal_message(pp.marshal_message(msg))
        assert out["node"]["uri"] == "http://pilosa_node_1:10101"

    def test_wire_type_confusion_raises_value_error_shape(self):
        # field 4 of Index encoded as varint instead of length-delimited:
        # must raise (any exception type), never return a half-decoded dict
        bad_schema = bytes([pp.MSG_SCHEMA]) + bytes([0x0A, 0x02, 0x20, 0x05])
        with pytest.raises(Exception):
            pp.unmarshal_message(bad_schema)

    def test_negative_bsi_bounds_survive(self):
        msg = {
            "type": "create-field",
            "index": "i",
            "field": "f",
            "options": {
                "type": "int",
                "cacheType": "ranked",
                "cacheSize": 50000,
                "min": -(2**40),
                "max": 2**40,
                "timeQuantum": "",
                "keys": False,
            },
        }
        out = pp.unmarshal_message(pp.marshal_message(msg))
        assert out["options"]["min"] == -(2**40)
        assert out["options"]["max"] == 2**40


class TestWireIntegration:
    def test_endpoint_accepts_protobuf(self, tmp_path):
        from tests.test_cluster import boot_static_cluster

        servers = boot_static_cluster(tmp_path, n=1)
        try:
            s = servers[0]
            buf = pp.marshal_message({"type": "create-index", "index": "pbidx", "keys": False})
            r = urllib.request.Request(
                s.uri + "/internal/cluster/message",
                data=buf,
                method="POST",
                headers={"Content-Type": pp.CONTENT_TYPE},
            )
            with urllib.request.urlopen(r, timeout=30) as resp:
                assert resp.status == 200
            assert s.holder.index("pbidx") is not None
            # malformed protobuf must 400, not execute
            bad = urllib.request.Request(
                s.uri + "/internal/cluster/message",
                data=bytes([250, 1, 2]),
                method="POST",
                headers={"Content-Type": pp.CONTENT_TYPE},
            )
            try:
                with urllib.request.urlopen(bad, timeout=30) as resp:
                    raise AssertionError(f"expected 400, got {resp.status}")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            for s in servers:
                s.close()

    def test_cluster_converges_over_protobuf_plane(self, tmp_path, monkeypatch):
        """The schema broadcast between live nodes must actually travel
        as protobuf (assert on the client's chosen encoding), and the
        peer must apply it."""
        from pilosa_tpu.parallel.client import InternalClient
        from tests.test_cluster import boot_static_cluster, req

        sent_types = []
        orig = InternalClient._request

        def spy(self, method, uri, path, body=None, query=None, raw=False, headers=None):
            if path == "/internal/cluster/message":
                sent_types.append((headers or {}).get("Content-Type", "json"))
            return orig(self, method, uri, path, body=body, query=query, raw=raw, headers=headers)

        monkeypatch.setattr(InternalClient, "_request", spy)
        servers = boot_static_cluster(tmp_path, n=2)
        try:
            s0, s1 = servers
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            assert s1.holder.index("i") is not None
            assert s1.holder.field("i", "f") is not None
            assert sent_types and all(t == pp.CONTENT_TYPE for t in sent_types)
        finally:
            for s in servers:
                s.close()
