"""Translate store unit tests: binary WAL round-trip, reopen/replay,
replication streaming, JSONL migration, and the memory-scalability
contract (reference translate.go: LogEntry format 548-723, mmapped
index economics 733-899)."""

import json
import os

import pytest

from pilosa_tpu.utils.translate import (
    LOG_ENTRY_INSERT_COLUMN,
    LOG_ENTRY_INSERT_ROW,
    TranslateStore,
)


class TestBasics:
    def test_mint_lookup_reverse_in_memory(self):
        ts = TranslateStore()
        ids = ts.translate_columns_to_ids("i", ["alice", "bob", "alice"])
        assert ids == [1, 2, 1]
        rids = ts.translate_rows_to_ids("i", "f", ["x", "y"])
        assert rids == [1, 2]  # per-space id sequences
        assert ts.translate_column_to_string("i", 1) == "alice"
        assert ts.translate_column_to_string("i", 2) == "bob"
        assert ts.translate_row_to_string("i", "f", 2) == "y"
        assert ts.translate_column_to_string("i", 99) is None
        # create=False leaves unknown keys unminted
        assert ts.translate_columns_to_ids("i", ["zed"], create=False) == [None]
        assert ts.translate_columns_to_ids("i", ["zed"]) == [3]

    def test_unicode_and_binaryish_keys(self):
        ts = TranslateStore()
        keys = ["héllo", "ключ", "日本語", 'quo"te', "a\tb"]
        ids = ts.translate_columns_to_ids("i", keys)
        assert ids == [1, 2, 3, 4, 5]
        for k, i in zip(keys, ids):
            assert ts.translate_column_to_string("i", i) == k

    def test_reopen_replays_wal(self, tmp_path):
        p = str(tmp_path / ".keys")
        ts = TranslateStore(p)
        ids = ts.translate_columns_to_ids("i", [f"k{j}" for j in range(100)])
        ts.translate_rows_to_ids("i", "f", ["r1", "r2"])
        ts.close()
        ts2 = TranslateStore(p)
        assert ts2.translate_columns_to_ids(
            "i", [f"k{j}" for j in range(100)], create=False
        ) == ids
        assert ts2.translate_row_to_string("i", "f", 1) == "r1"
        # sequence continues, no id reuse
        assert ts2.translate_columns_to_ids("i", ["new"]) == [101]
        ts2.close()

    def test_torn_tail_is_truncated(self, tmp_path):
        p = str(tmp_path / ".keys")
        ts = TranslateStore(p)
        ts.translate_columns_to_ids("i", ["a", "b"])
        ts.close()
        good = os.path.getsize(p)
        with open(p, "ab") as f:
            f.write(b"\x50\x01")  # half an entry
        ts2 = TranslateStore(p)
        assert os.path.getsize(p) == good
        assert ts2.translate_columns_to_ids("i", ["a"], create=False) == [1]
        assert ts2.offset() == good
        ts2.close()


class TestReplication:
    def test_stream_apply_and_idempotence(self, tmp_path):
        primary = TranslateStore(str(tmp_path / "p.keys"))
        replica = TranslateStore(str(tmp_path / "r.keys"))
        primary.translate_columns_to_ids("i", ["a", "b", "c"])
        primary.translate_rows_to_ids("i", "f", ["r"])
        data, _ = primary.read_from(0)
        consumed = replica.apply_log(data)
        assert consumed == len(data) == primary.offset()
        assert replica.translate_columns_to_ids(
            "i", ["a", "b", "c"], create=False
        ) == [1, 2, 3]
        assert replica.translate_row_to_string("i", "f", 1) == "r"
        # re-applying the same stream is harmless (restart re-pull)
        assert replica.apply_log(data) == len(data)
        assert replica.translate_columns_to_ids("i", ["a"], create=False) == [1]
        # a partial trailing entry is left for the next pull
        primary.translate_columns_to_ids("i", ["d"])
        data2, _ = primary.read_from(consumed)
        cut = len(data2) - 3
        assert replica.apply_log(data2[:cut]) == 0
        assert replica.apply_log(data2) == len(data2)
        assert replica.translate_columns_to_ids("i", ["d"], create=False) == [4]
        # replicated mappings survive a replica restart (local WAL)
        replica.close()
        r2 = TranslateStore(str(tmp_path / "r.keys"))
        assert r2.translate_columns_to_ids("i", ["d"], create=False) == [4]
        r2.close()
        primary.close()

    def test_forward_path_minting(self):
        primary = TranslateStore()
        follower = TranslateStore()
        follower.forward = lambda index, field, keys: primary.mint(
            index, field, keys
        )
        ids = follower.translate_columns_to_ids("i", ["x", "y", "x"])
        assert ids == [1, 2, 1]
        assert primary.translate_columns_to_ids("i", ["x"], create=False) == [1]
        # short answer fails loudly
        follower.forward = lambda index, field, keys: []
        with pytest.raises(ValueError):
            follower.translate_columns_to_ids("i", ["zz"])


class TestMigration:
    def test_jsonl_wal_upgrades_in_place(self, tmp_path):
        p = str(tmp_path / ".keys")
        with open(p, "w") as f:
            for rec in (
                {"index": "i", "field": "", "key": "alice", "id": 1},
                {"index": "i", "field": "", "key": "bob", "id": 2},
                {"index": "i", "field": "likes", "key": "pizza", "id": 1},
            ):
                f.write(json.dumps(rec) + "\n")
        ts = TranslateStore(p)
        assert ts.translate_columns_to_ids("i", ["alice", "bob"], create=False) == [1, 2]
        assert ts.translate_row_to_string("i", "likes", 1) == "pizza"
        assert ts.translate_columns_to_ids("i", ["carol"]) == [3]
        ts.close()
        with open(p, "rb") as f:
            assert f.read(1) != b"{"  # now binary


class TestWireFormat:
    def test_entry_round_trip(self):
        blob = TranslateStore.encode_entry(
            LOG_ENTRY_INSERT_ROW, "idx", "frame", [7, 300], [b"k1", b"key-two"]
        )
        end, index, field, pairs = TranslateStore.decode_entry(blob, 0)
        assert end == len(blob)
        assert (index, field) == ("idx", "frame")
        assert [(i, k) for i, k, _ in pairs] == [(7, b"k1"), (300, b"key-two")]
        # column entries ignore the field name (reference applyEntry)
        blob = TranslateStore.encode_entry(
            LOG_ENTRY_INSERT_COLUMN, "idx", "", [1], [b"c"]
        )
        _, _, field, _ = TranslateStore.decode_entry(blob, 0)
        assert field == ""

    def test_incomplete_and_corrupt(self):
        blob = TranslateStore.encode_entry(LOG_ENTRY_INSERT_COLUMN, "i", "", [1], [b"k"])
        assert TranslateStore.decode_entry(blob[:-1], 0) is None
        with pytest.raises(ValueError):
            # declared length covers the bytes, but the key length
            # inside runs past the entry
            bad = bytearray(blob)
            bad[-2] = 0xF0
            TranslateStore.decode_entry(bytes(bad), 0)


class TestScalability:
    N = 200_000

    def test_bounded_memory_per_key(self, tmp_path):
        """The memory contract: tables are numpy open-addressing over
        WAL offsets — tens of bytes per key, NOT Python dicts of
        strings (hundreds of bytes per key). 200k keys must fit in
        < 50 B/key of table residency; correctness spot-checked."""
        p = str(tmp_path / ".keys")
        ts = TranslateStore(p)
        batch = 10_000
        for start in range(0, self.N, batch):
            keys = [f"user:{j:012d}" for j in range(start, start + batch)]
            ids = ts.translate_columns_to_ids("i", keys)
            assert ids[0] == start + 1
        per_key = ts.rss_bytes() / self.N
        assert per_key < 50, f"{per_key:.1f} B/key resident"
        # random membership + reverse lookups
        assert ts.translate_columns_to_ids(
            "i", ["user:%012d" % 123456, "user:%012d" % 7], create=False
        ) == [123457, 8]
        assert ts.translate_column_to_string("i", 199999) == "user:%012d" % 199998
        ts.close()
        # reopen replays the binary WAL into the same tables
        ts2 = TranslateStore(p)
        assert ts2.translate_columns_to_ids(
            "i", ["user:%012d" % 54321], create=False
        ) == [54322]
        assert ts2.translate_columns_to_ids("i", ["fresh"]) == [self.N + 1]
        ts2.close()

    def test_no_python_key_dicts(self):
        """Structural guard: spaces are __slots__ numpy holders — no
        attribute can silently grow a per-key Python dict again."""
        ts = TranslateStore()
        ts.translate_columns_to_ids("i", ["a"])
        sp = ts._spaces[("i", "")]
        assert not hasattr(sp, "__dict__")
        for attr in sp.__slots__:
            v = getattr(sp, attr)
            assert not isinstance(v, dict), attr


class TestCheckpoint:
    def test_open_uses_checkpoint_and_replays_tail(self, tmp_path):
        p = str(tmp_path / ".keys")
        ts = TranslateStore(p)
        ts.translate_columns_to_ids("i", [f"k{j}" for j in range(5000)])
        ts.close()  # writes .ckpt
        assert os.path.exists(p + ".ckpt")
        ts2 = TranslateStore(p)
        assert ts2.translate_columns_to_ids("i", ["k42"], create=False) == [43]
        # mint a tail, then simulate a crash (no checkpoint refresh)
        ts2.translate_columns_to_ids("i", ["tail1", "tail2"])
        ts2._log.close()
        ts2._log = None
        os.close(ts2._read_fd)
        ts2._read_fd = None
        ts3 = TranslateStore(p)
        assert ts3.translate_columns_to_ids("i", ["tail2"], create=False) == [5002]
        assert ts3.translate_columns_to_ids("i", ["k0"], create=False) == [1]
        ts3.close()

    def test_stale_checkpoint_falls_back_to_full_replay(self, tmp_path):
        p = str(tmp_path / ".keys")
        ts = TranslateStore(p)
        for j in range(100):  # one WAL entry per key
            ts.translate_columns_to_ids("i", [f"k{j}"])
        ts.close()
        # WAL shrinks behind the checkpoint (e.g. restored from backup):
        # the checkpoint must be distrusted and the surviving complete
        # entries replayed from scratch
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.truncate(size - 1)  # tears only the LAST entry
        ts2 = TranslateStore(p)
        assert ts2.translate_columns_to_ids("i", ["k0"], create=False) == [1]
        assert ts2.translate_columns_to_ids("i", ["k98"], create=False) == [99]
        assert ts2.translate_columns_to_ids("i", ["k99"], create=False) == [None]
        ts2.close()

    def test_corrupt_checkpoint_is_ignored(self, tmp_path):
        p = str(tmp_path / ".keys")
        ts = TranslateStore(p)
        ts.translate_columns_to_ids("i", ["a", "b"])
        ts.close()
        with open(p + ".ckpt", "wb") as f:
            f.write(b"garbage")
        ts2 = TranslateStore(p)
        assert ts2.translate_columns_to_ids("i", ["b"], create=False) == [2]
        ts2.close()


class TestReviewRegressions:
    """Round-4 review findings: sentinel aliasing, batch-hash memory,
    replica WAL growth, dense-id skip, hash parity."""

    def test_reverse_lookup_of_unassigned_id_is_none(self):
        # follower adopts a sparse primary-minted subset: seq jumps to
        # 500 with ids 1..499 unassigned locally; reverse lookups of
        # those must be None, not bytes read from WAL offset 0
        primary = TranslateStore()
        for j in range(499):
            primary.translate_columns_to_ids("i", [f"k{j}"])
        follower = TranslateStore()
        follower.forward = lambda index, field, keys: primary.mint(index, field, keys)
        assert follower.translate_columns_to_ids("i", ["k499"]) == [500]
        assert follower.translate_column_to_string("i", 500) == "k499"
        for probe in (1, 3, 250, 499):
            assert follower.translate_column_to_string("i", probe) is None

    def test_one_huge_key_in_batch_does_not_blow_memory(self):
        ts = TranslateStore()
        keys = [f"k{j}" for j in range(1000)] + ["X" * 1_000_000]
        ids = ts.translate_columns_to_ids("i", keys)
        assert ids[-1] == 1001
        assert ts.translate_columns_to_ids("i", ["X" * 1_000_000], create=False) == [1001]

    def test_replica_repull_does_not_grow_wal(self, tmp_path):
        primary = TranslateStore(str(tmp_path / "p.keys"))
        replica = TranslateStore(str(tmp_path / "r.keys"))
        primary.translate_columns_to_ids("i", [f"k{j}" for j in range(100)])
        data, _ = primary.read_from(0)
        replica.apply_log(data)
        size1 = replica.offset()
        for _ in range(3):  # restart re-pulls from 0
            replica.apply_log(data)
        assert replica.offset() == size1, "re-pull must not re-append"

    def test_overlapping_mint_does_not_skip_ids(self):
        # the stale-miss-list race: ids are assigned AFTER the
        # under-lock absence re-check, so an overlap cannot burn an id
        ts = TranslateStore()
        assert ts.translate_columns_to_ids("i", ["a", "b"]) == [1, 2]
        with ts.mu:
            resolved = ts._adopt("i", "", ["b", "c"], None)  # stale miss list
        assert resolved == {"b": 2, "c": 3}
        assert ts.translate_columns_to_ids("i", ["d"]) == [4]
        # dense invariant: every id 1..4 reverse-resolves
        assert [ts.translate_column_to_string("i", j) for j in (1, 2, 3, 4)] == [
            "a", "b", "c", "d",
        ]

    def test_hash_parity_scalar_vs_vector(self):
        from pilosa_tpu.utils.translate import _hash_key, _hash_keys

        keys = [b"", b"a", b"user:000000000123", "日本語".encode(), b"Z" * 300,
                b"y" * 257, b"x" * 256]
        assert [_hash_key(k) for k in keys] == [int(v) for v in _hash_keys(keys)]


class TestMigrationSniff:
    def test_binary_wal_starting_with_0x7b_survives_reopen(self, tmp_path):
        # an entry whose length uvarint is 0x7B ('{') must not be
        # mistaken for round-3 JSONL and destroyed
        p = str(tmp_path / ".keys")
        ts = TranslateStore(p)
        key = "K" * 116  # entry body = 123 = 0x7B bytes
        assert ts.translate_columns_to_ids("i", [key]) == [1]
        ts.close()
        os.unlink(p + ".ckpt")  # force a raw-WAL replay path
        with open(p, "rb") as f:
            assert f.read(1) == b"{"
        ts2 = TranslateStore(p)
        assert ts2.translate_columns_to_ids("i", [key], create=False) == [1]
        ts2.close()
