"""Gang-dispatch protocol tests (parallel/multihost.py): descriptor and
frame round-trips, follower deadline abort, idle-tick liveness, leader
dispatch fencing — all in-process against the LoopbackChannel — plus a
2-process jax.distributed serving smoke (the dryrun driver in quick
mode), skipped when jax.distributed is unavailable."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from pilosa_tpu.parallel import multihost
from pilosa_tpu.parallel.multihost import (
    Descriptor,
    GangFollower,
    GangUnavailable,
    KIND_IMPORT,
    KIND_POISON,
    KIND_QUERY,
    KIND_TICK,
    LoopbackChannel,
    MultiHostRuntime,
    STATE_DEGRADED,
    decode_frame,
    decode_message,
    encode_message,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- framing ------------------------------------------------------------------


def test_frame_round_trip_single():
    frames = encode_message(KIND_QUERY, b"Count(Row(f=1))", 4096)
    assert len(frames) == 1
    assert len(frames[0]) == 4096  # fixed-size: one compiled hop program
    kind, payload = decode_message(frames)
    assert kind == KIND_QUERY
    assert payload == b"Count(Row(f=1))"


def test_frame_round_trip_multi_frame():
    blob = bytes(range(256)) * 200  # 51200 bytes across several frames
    frames = encode_message(KIND_IMPORT, blob, 4096)
    assert len(frames) > 1
    assert all(len(f) == 4096 for f in frames)
    kind, payload = decode_message(frames)
    assert kind == KIND_IMPORT and payload == blob


def test_frame_round_trip_empty_payload():
    frames = encode_message(KIND_POISON, b"", 1024)
    assert decode_message(frames) == (KIND_POISON, b"")


def test_frame_bad_magic_rejected():
    frame = b"\x00" * 4096
    with pytest.raises(multihost.FrameError):
        decode_frame(frame)


def test_frame_inconsistent_sequence_rejected():
    a = encode_message(KIND_QUERY, b"x" * 9000, 4096)
    b = encode_message(KIND_TICK, b"y", 4096)
    with pytest.raises(multihost.FrameError):
        decode_message([a[0], b[0]])


def test_descriptor_round_trip():
    desc = multihost.query_descriptor(
        "idx",
        'Count(Intersect(Row(f=1), Row(g="a b")))',
        [0, 3, 5],
        type("O", (), {"exclude_row_attrs": True, "exclude_columns": False})(),
    )
    kind, raw = desc.kind, desc.encode()
    back = Descriptor.decode(kind, raw)
    assert back.payload == desc.payload
    assert back.payload["index"] == "idx"
    assert back.payload["shards"] == [0, 3, 5]
    assert back.payload["opt"]["exclude_row_attrs"] is True
    # canonical plan identity rides along (plan/canon.py)
    assert back.payload["plan"] and back.payload["plan"].startswith("pqh:")


# -- follower loop ------------------------------------------------------------


def _send(ch, kind, payload: bytes = b""):
    ch.send(encode_message(kind, payload, ch.frame_bytes))


def test_follower_applies_work_and_exits_on_poison():
    ch = LoopbackChannel(2048)
    applied = []
    f = GangFollower(ch, lambda k, p: applied.append((k, p)), leader_timeout=5.0)
    _send(ch, KIND_QUERY, json.dumps({"q": 1}).encode())
    _send(ch, KIND_QUERY, json.dumps({"q": 2}).encode())
    _send(ch, KIND_POISON)
    assert f.run() == "poison"
    assert applied == [(KIND_QUERY, {"q": 1}), (KIND_QUERY, {"q": 2})]
    assert f.works == 2


def test_follower_deadline_abort_on_silent_leader():
    """A follower whose leader goes quiet past leader_timeout aborts
    the loop cleanly (deadline-fenced) instead of hanging forever."""
    ch = LoopbackChannel(2048)
    f = GangFollower(ch, lambda k, p: None, leader_timeout=0.2)
    t0 = time.monotonic()
    assert f.run() == "leader_timeout"
    assert time.monotonic() - t0 < 2.0


def test_follower_abort_on_channel_closed():
    """Collective-plane death (the real channel's peer-loss surface)
    exits the loop with channel_closed, not a hang or a raise."""
    ch = LoopbackChannel(2048)
    ch.close()
    f = GangFollower(ch, lambda k, p: None, leader_timeout=5.0)
    assert f.run() == "channel_closed"


def test_follower_idle_tick_liveness():
    """Ticks keep the loop alive across idle gaps longer than any
    single recv, carry the leader clock for lag measurement, and work
    dispatched after a tick run still applies."""
    ch = LoopbackChannel(2048)
    applied = []
    f = GangFollower(ch, lambda k, p: applied.append(p), leader_timeout=0.6)

    def leader():
        for _ in range(4):
            time.sleep(0.25)  # > half the timeout: only ticks keep it alive
            _send(ch, KIND_TICK, json.dumps({"t": time.time()}).encode())
        _send(ch, KIND_QUERY, json.dumps({"late": True}).encode())
        _send(ch, KIND_POISON)

    t = threading.Thread(target=leader)
    t.start()
    assert f.run() == "poison"
    t.join()
    assert f.ticks == 4
    assert f.last_lag < 5.0
    assert applied == [{"late": True}]


def test_follower_expected_apply_error_continues():
    """Validation-class errors (bad args, missing schema) raise before
    any collective on every rank identically — the loop continues."""
    ch = LoopbackChannel(2048)

    def apply(kind, payload):
        if payload.get("boom"):
            raise ValueError("Count() requires an input bitmap")
        return "ok"

    f = GangFollower(ch, apply, leader_timeout=5.0)
    _send(ch, KIND_QUERY, json.dumps({"boom": True}).encode())
    _send(ch, KIND_QUERY, json.dumps({}).encode())
    _send(ch, KIND_POISON)
    assert f.run() == "poison"
    assert f.errors == 1 and f.works == 2


def test_follower_unexpected_apply_error_aborts_loop():
    """An unexpected mid-execution failure may have skipped collectives
    the leader still runs — continuing would pair mismatched
    collectives on the next hop (observed as a gloo size-mismatch abort
    killing BOTH processes). The loop must exit cleanly instead; the
    leader's dispatch fence then degrades the gang."""
    ch = LoopbackChannel(2048)

    def apply(kind, payload):
        raise RuntimeError("device wedged mid-kernel")

    f = GangFollower(ch, apply, leader_timeout=5.0)
    _send(ch, KIND_QUERY, json.dumps({}).encode())
    _send(ch, KIND_QUERY, json.dumps({}).encode())  # never reached
    assert f.run() == "apply_error"
    assert f.errors == 1 and f.works == 1


# -- leader dispatch ----------------------------------------------------------


def test_leader_dispatch_runs_in_lockstep_order():
    """Leader dispatch broadcasts the descriptor and runs it locally;
    an attached follower on the same channel applies the identical
    descriptors in the identical order."""
    ch = LoopbackChannel(4096)
    leader_applied, follower_applied = [], []
    rt = MultiHostRuntime(
        rank=0,
        world=2,
        channel=ch,
        apply_fn=lambda k, p: (leader_applied.append(p), p["n"] * 10)[1],
        idle_interval=0,  # no ticker: the follower loop below is finite
        dispatch_timeout=5.0,
    )
    results = [rt.dispatch(Descriptor(KIND_QUERY, {"n": i})) for i in range(3)]
    assert results == [0, 10, 20]
    rt.close()  # poison pill lands after the three work messages
    f = GangFollower(ch, lambda k, p: follower_applied.append(p), leader_timeout=2.0)
    assert f.run() == "poison"
    assert follower_applied == leader_applied == [{"n": 0}, {"n": 1}, {"n": 2}]


def test_leader_dispatch_timeout_degrades_and_503s():
    """A wedged channel (dead follower) turns a dispatch into a clean
    GangUnavailable within the fence, flips the runtime to degraded,
    and fires the degrade hook — never a hang."""

    class WedgedChannel:
        frame_bytes = 4096

        def send(self, frames):
            time.sleep(30)

    degraded = []
    rt = MultiHostRuntime(
        rank=0,
        world=2,
        channel=WedgedChannel(),
        apply_fn=lambda k, p: None,
        idle_interval=0,
        dispatch_timeout=0.3,
        on_degrade=lambda: degraded.append(1),
    )
    t0 = time.monotonic()
    with pytest.raises(GangUnavailable) as ei:
        rt.dispatch(Descriptor(KIND_QUERY, {}))
    assert time.monotonic() - t0 < 3.0
    assert ei.value.status == 503
    assert rt.degraded and degraded == [1]
    # post-degrade dispatches fail fast without waiting the fence
    t0 = time.monotonic()
    with pytest.raises(GangUnavailable):
        rt.dispatch(Descriptor(KIND_QUERY, {}))
    assert time.monotonic() - t0 < 0.1


def test_degrade_swaps_executor_before_state_flip():
    """Regression: degrade() used to flip state to DEGRADED before the
    on_degrade hook had swapped the executor off the dead collective
    plane — a query routed in that window ran a cross-process
    collective on the poisoned gloo context ('Gloo all-reduce failed:
    Connection reset by peer'). The hook must complete before the
    state flip is visible, and route decisions made mid-swap must
    wait for it."""
    hook_entered = threading.Event()
    release_hook = threading.Event()
    seen = {}

    def hook():
        seen["state_in_hook"] = rt.state
        hook_entered.set()
        assert release_hook.wait(timeout=5)

    rt = MultiHostRuntime(
        rank=0,
        world=2,
        channel=LoopbackChannel(4096),
        apply_fn=lambda k, p: None,
        idle_interval=0,
        dispatch_timeout=5.0,
        on_degrade=hook,
    )
    deg = threading.Thread(target=rt.degrade, args=("test",))
    deg.start()
    assert hook_entered.wait(timeout=5)
    # mid-swap: the verdict is in (new dispatches refuse) but the
    # executor handoff is not done — route decisions must block here
    with pytest.raises(GangUnavailable):
        rt.dispatch(Descriptor(KIND_QUERY, {}))
    decided = []
    router = threading.Thread(
        target=lambda: decided.append(rt.should_dispatch_query(False))
    )
    router.start()
    router.join(timeout=0.3)
    assert router.is_alive(), "route decision did not wait for the swap"
    release_hook.set()
    deg.join(timeout=5)
    router.join(timeout=5)
    assert not router.is_alive() and decided == [False]
    assert seen["state_in_hook"] != STATE_DEGRADED
    assert rt.state == STATE_DEGRADED
    rt.close()


def test_leader_request_deadline_does_not_degrade():
    """A caller deadline shorter than the fence raises
    DeadlineExceeded and leaves the gang HEALTHY — a slow query must
    never tear down a live gang."""
    from pilosa_tpu.server.deadline import Deadline, DeadlineExceeded

    class SlowChannel:
        frame_bytes = 4096

        def send(self, frames):
            time.sleep(1.0)

    rt = MultiHostRuntime(
        rank=0,
        world=2,
        channel=SlowChannel(),
        apply_fn=lambda k, p: "late",
        idle_interval=0,
        dispatch_timeout=30.0,
    )
    with pytest.raises(DeadlineExceeded):
        rt.dispatch(Descriptor(KIND_QUERY, {}), deadline=Deadline.after(0.15))
    assert not rt.degraded


def test_single_process_runtime_is_inactive():
    rt = MultiHostRuntime(rank=0, world=1, channel=LoopbackChannel(1024),
                          apply_fn=lambda k, p: None)
    assert not rt.active
    assert not rt.should_dispatch()


# -- 2-process serving smoke --------------------------------------------------


def test_two_process_multihost_serving_smoke():
    """The full serving path on a real 2-process jax.distributed CPU
    mesh: HTTP on rank 0, gang replay on rank 1, bit-identity against
    the CPU oracle, and a bounded follower-kill failure — the dryrun
    driver in quick mode."""
    import jax

    if not hasattr(jax, "distributed"):
        pytest.skip("jax.distributed unavailable")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "dryrun_multihost.py"), "--quick"],
        capture_output=True,
        text=True,
        timeout=560,
        env={
            k: v
            for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
        },
    )
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    summary = json.loads(proc.stdout[proc.stdout.index('{\n  "what"') :])
    assert summary["ok"] is True
    assert summary["serving"]["rank0_http_bit_identical"] is True
    assert summary["serving"]["rank1_replay_bit_identical"] is True
    assert summary["follower_kill"]["first_query_bounded"] is True
    assert summary["follower_kill"]["degraded"] is True
