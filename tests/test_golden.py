"""Golden fixtures from the reference's executor_test.go, run against
all three execution paths: CPU roaring (device_policy=never), single-
device kernels (always), and SPMD over the 8-virtual-device mesh.

The expected outputs are transcribed verbatim from the reference test
assertions (see tests/golden_fixtures.json `_comment`), so a pass here
is parity with the reference's own oracle, not a self-referential
device-vs-CPU check.
"""

import json
import os

import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import FieldOptions, Holder, Row
from pilosa_tpu.executor import Executor, ValCount
from pilosa_tpu.parallel.spmd import make_mesh

FIXTURES = json.load(
    open(os.path.join(os.path.dirname(__file__), "golden_fixtures.json"))
)["fixtures"]
BY_NAME = {f["name"]: f for f in FIXTURES}

SW = SHARD_WIDTH


def _expand(value):
    """Expand {SW}-style placeholders: '{SW+1}' -> 1048577."""
    if isinstance(value, str) and value.startswith("{") and value.endswith("}"):
        expr = value[1:-1].replace("SW", str(SW))
        return eval(expr, {"__builtins__": {}})  # noqa: S307 — fixture-controlled
    return value


def _expand_query(q: str) -> str:
    import re

    return re.sub(
        r"\{([^}]+)\}", lambda m: str(_expand("{" + m.group(1) + "}")), q
    )


def _base(fx):
    """Follow the `reuse` chain to the fixture owning schema/setup."""
    while "reuse" in fx:
        fx = BY_NAME[fx["reuse"]]
    return fx


def _build_holder(tmp_path, fx):
    from pilosa_tpu.utils.attrstore import new_attr_store

    base = _base(fx)
    h = Holder(str(tmp_path / "data"), new_attr_store=new_attr_store)
    h.open()
    idx = h.create_index("i")
    for fname, opts in base["fields"].items():
        idx.create_field(fname, FieldOptions.from_dict(opts))
    setup = Executor(h, device_policy="never")
    for q in base.get("setup", []):
        setup.execute("i", _expand_query(q))
    if "row_attrs" in base:
        ra = base["row_attrs"]
        fld = h.field("i", ra["field"])
        fld.row_attr_store.set_attrs(ra["row"], ra["attrs"])
    for q in fx.get("extra_setup", []):
        setup.execute("i", _expand_query(q))
    if base.get("recalculate") or fx.get("recalculate"):
        for f in h.index("i").fields.values():
            for v in f.views.values():
                for frag in v.fragments.values():
                    frag.cache.recalculate()
    return h


def _canon(result):
    if isinstance(result, Row):
        return ("columns", tuple(int(c) for c in result.columns()))
    if isinstance(result, ValCount):
        return ("valcount", result.val, result.count)
    if isinstance(result, list):  # TopN pairs
        return ("pairs", tuple((p["id"], p["count"]) for p in result))
    if isinstance(result, (int, bool)):
        return ("count", int(result))
    return ("other", repr(result))


def _want(fx):
    e = fx["expect"]
    if "columns" in e:
        return ("columns", tuple(_expand(c) for c in e["columns"]))
    if "pairs" in e:
        return ("pairs", tuple((p[0], p[1]) for p in e["pairs"]))
    if "valcount" in e:
        return ("valcount", e["valcount"][0], e["valcount"][1])
    if "count" in e:
        return ("count", e["count"])
    raise ValueError(f"bad fixture expect: {e}")


def _run(fx, executor):
    q = _expand_query(fx["query"])
    if fx["expect"].get("error"):
        with pytest.raises(Exception):
            executor.execute("i", q)
        return None
    res = executor.execute("i", q)
    assert len(res) == 1
    got = _canon(res[0])
    want = _want(fx)
    assert got == want, f"{fx['name']} ({fx['ref']}): got {got}, want {want}"


@pytest.mark.parametrize("fx", FIXTURES, ids=[f["name"] for f in FIXTURES])
def test_golden_cpu(fx, tmp_path):
    h = _build_holder(tmp_path, fx)
    try:
        _run(fx, Executor(h, device_policy="never"))
    finally:
        h.close()


@pytest.mark.parametrize("fx", FIXTURES, ids=[f["name"] for f in FIXTURES])
def test_golden_device(fx, tmp_path):
    h = _build_holder(tmp_path, fx)
    try:
        _run(fx, Executor(h, device_policy="always"))
    finally:
        h.close()


@pytest.mark.parametrize("fx", FIXTURES, ids=[f["name"] for f in FIXTURES])
def test_golden_spmd(fx, tmp_path):
    h = _build_holder(tmp_path, fx)
    try:
        mesh = make_mesh()
        _run(fx, Executor(h, device_policy="always", mesh=mesh))
    finally:
        h.close()
