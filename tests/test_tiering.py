"""Tiered block staging (ISSUE 17): the host-RAM compressed tier (T1)
between the stager's device LRU (T0) and the mmapped fragment (T2),
plan-driven prefetch accuracy accounting, and the compressed-upload →
on-device-expansion path.

The load-bearing claims pinned here:

  * T1 admission/eviction byte accounting is exact, the cost-model
    admission really rejects candidates colder than the LRU head, and
    stale generations revalidate through the fragment delta log.
  * The compressed-upload expansion kernels (ops.packed.expand_blocks,
    ops.pallas_kernels.expand_runs_pallas) are bit-identical to the
    host dense build for array, RLE, and bitmap containers.
  * A hot set ~3x the stager budget serves bit-identically to the CPU
    oracle while T1 absorbs the re-entry cost (the oversubscription
    gauntlet).
  * A raising stage-ahead thunk neither kills the prefetch loop nor
    disappears: counted + journaled once per reason (ISSUE 17 s1).
  * docs/configuration.md documents the tiering knobs with the defaults
    the code actually uses (the test_fusion.py knob-sync idiom).
"""

import os
import threading
import time

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH, ops
from pilosa_tpu.core import FieldOptions, Holder, VIEW_BSI_GROUP_PREFIX, VIEW_STANDARD
from pilosa_tpu.core.field import FIELD_TYPE_INT
from pilosa_tpu.executor import DeviceStager, Executor
from pilosa_tpu.executor.hbm import HbmGovernor
from pilosa_tpu.executor.tiering import Tier1Cache
from pilosa_tpu.utils import events, metrics

W32 = SHARD_WIDTH // 32
ROW_BYTES = W32 * 4


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "d"))
    h.open()
    yield h
    h.close()


def _seed_fragment(holder, rows=8, bits_per_row=50, seed=7, name="ti"):
    idx = holder.create_index(name)
    f = idx.create_field("f")
    rng = np.random.default_rng(seed)
    rids, cids = [], []
    for r in range(rows):
        rids += [r] * bits_per_row
        cids += rng.integers(0, SHARD_WIDTH, size=bits_per_row).tolist()
    f.import_bits(rids, cids)
    return idx, f, holder.fragment(name, "f", VIEW_STANDARD, 0)


# -- Tier1Cache unit behavior -------------------------------------------------


class _FakeFrag:
    """Just enough fragment surface for Tier1Cache: identity cell for
    the heat lookup, a generation, and a delta log."""

    def __init__(self):
        self.index, self.field, self.shard = "t1", "f", 0
        self.generation = 1
        # None = log can't prove continuity; else (pos, is_set, gen)
        self.deltas = None

    def deltas_since(self, gen):
        return self.deltas


class TestTier1Cache:
    def test_admission_eviction_byte_accounting(self):
        t1 = Tier1Cache(300)
        frag = _FakeFrag()
        t1.put(frag, (0,), ["A"], nbytes=100, gen=1, cost=1.0)
        t1.put(frag, (1,), ["B"], nbytes=100, gen=1, cost=1.0)
        # C is worth more per byte than the LRU head (A): 2/150 > 1/100
        assert t1.put(frag, (2,), ["C"], nbytes=150, gen=1, cost=2.0)
        st = t1.stats()
        assert st["entries"] == 2 and st["bytes"] == 250
        assert st["admitted"] == 3 and st["evicted"] == 1
        assert t1.get(frag, (0,)) is None  # A evicted LRU
        assert t1.get(frag, (1,)) == ["B"]
        assert t1.get(frag, (2,)) == ["C"]
        st = t1.stats()
        assert st["hits"] == 2 and st["misses"] == 1

    def test_admission_rejects_colder_than_lru_head(self):
        t1 = Tier1Cache(150)
        frag = _FakeFrag()
        assert t1.put(frag, (0,), ["hot"], nbytes=100, gen=1, cost=10.0)
        # zero rebuild cost: evicting the 0.1-value head for it would
        # trade retained seconds-per-byte for nothing
        assert not t1.put(frag, (1,), ["cold"], nbytes=100, gen=1, cost=0.0)
        st = t1.stats()
        assert st["rejected"] == 1 and st["evicted"] == 0
        assert st["entries"] == 1 and st["bytes"] == 100
        assert t1.get(frag, (0,)) == ["hot"]  # undisturbed

    def test_oversized_and_empty_candidates_rejected(self):
        t1 = Tier1Cache(100)
        frag = _FakeFrag()
        assert not t1.put(frag, (0,), ["x"], nbytes=101, gen=1, cost=1.0)
        assert not t1.put(frag, (1,), ["y"], nbytes=0, gen=1, cost=1.0)
        assert t1.stats()["rejected"] == 2 and t1.stats()["bytes"] == 0

    def test_stale_generation_revalidates_through_delta_log(self):
        t1 = Tier1Cache(1000)
        frag = _FakeFrag()
        t1.put(frag, (0, 1), ["payload"], nbytes=100, gen=1, cost=1.0)
        # log truncated → evict
        frag.generation = 2
        frag.deltas = None
        assert t1.get(frag, (0, 1)) is None
        assert t1.stats()["evicted"] == 1 and t1.stats()["bytes"] == 0
        # deltas that miss every cached row leave the payloads exact:
        # generation refreshed, subsequent gets are cheap hits
        t1.put(frag, (0, 1), ["payload"], nbytes=100, gen=2, cost=1.0)
        frag.generation = 3
        frag.deltas = (
            np.array([5 * SHARD_WIDTH + 10], np.uint64),  # row 5: not cached
            np.array([True]),
            3,
        )
        assert t1.get(frag, (0, 1)) == ["payload"]
        frag.deltas = AssertionError  # must not be consulted again
        assert t1.get(frag, (0, 1)) == ["payload"]
        # a delta landing in a cached row evicts
        frag.generation = 4
        frag.deltas = (
            np.array([1 * SHARD_WIDTH + 7], np.uint64),  # row 1: cached
            np.array([True]),
            4,
        )
        assert t1.get(frag, (0, 1)) is None
        assert t1.stats()["evicted"] == 2 and t1.stats()["bytes"] == 0

    def test_governor_mirror_is_host_domain(self):
        gov = HbmGovernor(budget_bytes=1000)
        t1 = Tier1Cache(500)
        t1.set_governor(gov)
        frag = _FakeFrag()
        t1.put(frag, (0,), ["x"], nbytes=200, gen=1, cost=1.0)
        st = gov.stats()
        ten = st["tenants"]["tier1"]
        assert ten["domain"] == "host" and ten["used"] == 200
        # host tenants are ledger-visible but never count against the
        # device budget or its relief sweeps
        assert st["used_bytes"] == 0
        assert gov.headroom() == 1000
        t1.clear()
        assert gov.stats()["tenants"]["tier1"]["used"] == 0


# -- prefetch accuracy accounting --------------------------------------------


class TestPrefetchAccuracy:
    def test_prefetched_then_hit_counts_used(self, holder):
        _, _, frag = _seed_fragment(holder)
        st = DeviceStager()
        st.row(frag, 0, prefetch=True)
        assert st.prefetch_issued == 1 and st.prefetch_used == 0
        st.row(frag, 0)  # a real query reaches the speculative block
        assert st.prefetch_used == 1 and st.prefetch_evicted == 0
        st.row(frag, 0)  # later hits no longer re-attribute
        assert st.prefetch_used == 1

    def test_prefetched_then_evicted_counts_wasted(self, holder):
        _, _, frag = _seed_fragment(holder)
        st = DeviceStager(budget_bytes=ROW_BYTES)  # one-row budget
        st.row(frag, 0, prefetch=True)
        st.row(frag, 1)  # over budget → LRU drops the speculative row
        assert st.prefetch_evicted == 1 and st.prefetch_used == 0
        st.row(frag, 0)  # rebuilt for real: no double attribution
        assert st.prefetch_evicted == 1 and st.prefetch_used == 0

    def test_capacity_reentry_counts_restaged_bytes(self, holder):
        """A cold miss on a key previously dropped under capacity
        pressure is a re-entry: the re-uploaded bytes land in
        stager.restaged_bytes (first stages and plain misses do not)."""

        def restaged():
            snap = metrics.snapshot()
            return sum(
                v
                for k, v in snap.items()
                if not isinstance(v, dict)
                and k.startswith(metrics.STAGER_RESTAGED_BYTES)
            )

        _, _, frag = _seed_fragment(holder)
        st = DeviceStager(budget_bytes=ROW_BYTES)  # one-row budget
        base = restaged()
        st.row(frag, 0)  # first stage: not a re-entry
        st.row(frag, 1)  # evicts row 0; itself a first stage
        assert restaged() == base
        st.row(frag, 0)  # re-entry of the evicted row
        assert restaged() == base + ROW_BYTES
        st.row(frag, 0)  # resident hit: no further accounting
        assert restaged() == base + ROW_BYTES


# -- on-device expansion vs host dense build ---------------------------------


def _ref_set_bits(ref, positions):
    for p in positions:
        ref[p >> 5] |= np.uint32(1) << np.uint32(p & 31)


class TestExpansionKernels:
    def test_expand_blocks_all_container_types(self):
        """Hand-built array/RLE/bitmap payloads with kernel-dropped
        padding expand bit-identically to a numpy reference."""
        rows, num_words = 4, 4 * W32
        ref = np.zeros(num_words, np.uint32)
        rng = np.random.default_rng(3)
        # array containers: row 0 slot 0, row 2 slot 3
        pos = np.concatenate(
            [
                0 * SHARD_WIDTH + rng.choice(65536, 37, replace=False),
                2 * SHARD_WIDTH + 3 * 65536 + rng.choice(65536, 11, replace=False),
            ]
        ).astype(np.uint32)
        _ref_set_bits(ref, pos.tolist())
        # RLE runs: same-word, word-crossing, interior-covering, width-1
        runs = [
            (1 * SHARD_WIDTH + 10, 1 * SHARD_WIDTH + 20),
            (1 * SHARD_WIDTH + 1000, 1 * SHARD_WIDTH + 1100),
            (3 * SHARD_WIDTH + 0, 3 * SHARD_WIDTH + 70000),
            (0 * SHARD_WIDTH + 131071, 0 * SHARD_WIDTH + 131071),
        ]
        for s, e in runs:
            _ref_set_bits(ref, range(s, e + 1))
        starts = np.array([s for s, _ in runs], np.uint32)
        ends = np.array([e for _, e in runs], np.uint32)
        # dense bitmap container: row 2 slot 1
        dense = rng.integers(0, 1 << 32, size=(1, 2048), dtype=np.uint32)
        dword = np.array([2 * W32 + (1 << 11)], np.int32)
        ref[dword[0] : dword[0] + 2048] |= dense[0]
        # padding the kernel must provably drop
        pos = np.concatenate([pos, np.full(3, 0xFFFFFFFF, np.uint32)])
        starts = np.concatenate([starts, np.array([1, 1], np.uint32)])
        ends = np.concatenate([ends, np.array([0, 0], np.uint32)])
        dense = np.concatenate([dense, np.zeros((1, 2048), np.uint32)])
        dword = np.concatenate([dword, np.array([num_words], np.int32)])
        got = np.asarray(
            ops.expand_blocks(pos, starts, ends, dense, dword, num_words=num_words)
        )
        np.testing.assert_array_equal(got, ref)

    def test_expand_runs_pallas_matches_reference(self):
        from pilosa_tpu.ops.pallas_kernels import expand_runs_pallas

        num_words = 2 * W32
        ref = np.zeros(num_words, np.uint32)
        runs = [(5, 9), (31, 33), (40000, 41000), (SHARD_WIDTH + 7, SHARD_WIDTH + 7)]
        for s, e in runs:
            _ref_set_bits(ref, range(s, e + 1))
        starts = np.array([s for s, _ in runs] + [1, 1], np.int32)
        ends = np.array([e for _, e in runs] + [0, 0], np.int32)
        got = np.asarray(
            expand_runs_pallas(starts, ends, num_words=num_words, interpret=True)
        )
        np.testing.assert_array_equal(got, ref)

    def test_stager_compressed_path_bit_identical(self, holder):
        """Tiered stager (T1 + compressed upload forced on) vs the
        untiered host dense build, across row/rows/planes forms and a
        post-write rebuild."""
        idx, f, frag = _seed_fragment(holder, rows=6, bits_per_row=300)
        # a dense run + a bitmap-heavy row alongside the sparse ones
        f.import_bits([6] * 4001, list(range(5000, 9001)))
        rng = np.random.default_rng(11)
        heavy = rng.choice(65536, 5000, replace=False) + 2 * 65536
        f.import_bits([7] * 5000, heavy.tolist())
        tiered = DeviceStager(tier1_max_bytes=32 << 20, compressed_min_ratio=1e-9)
        plain = DeviceStager()
        for r in range(8):
            np.testing.assert_array_equal(
                np.asarray(tiered.row(frag, r)), np.asarray(plain.row(frag, r))
            )
        ids = tuple(range(8))
        np.testing.assert_array_equal(
            np.asarray(tiered.rows(frag, ids, pad_pow2=True)),
            np.asarray(plain.rows(frag, ids, pad_pow2=True)),
        )
        assert tiered.tier1.stats()["admitted"] > 0
        # BSI planes form
        v = idx.create_field(
            "v", FieldOptions(type=FIELD_TYPE_INT, min=0, max=4000)
        )
        v.import_values([5, 9, 700, 9000], [17, 2000, 3999, 1])
        vfrag = holder.fragment("ti", "v", VIEW_BSI_GROUP_PREFIX + "v", 0)
        depth = v.bsi_group("v").bit_depth()
        np.testing.assert_array_equal(
            np.asarray(tiered.planes(vfrag, depth)),
            np.asarray(plain.planes(vfrag, depth)),
        )
        # a write invalidates T1 exactly; the rebuild stays identical
        f.set_bit(3, 424242)
        np.testing.assert_array_equal(
            np.asarray(tiered.row(frag, 3)),
            frag.row_words(3).view("<u4"),
        )


# -- the oversubscription gauntlet -------------------------------------------


class TestOversubscriptionGauntlet:
    def test_hot_set_3x_budget_bit_identical(self, holder):
        """A hot set ~3x the T0 budget, two passes + a mid-gauntlet
        write: every answer bit-identical to the CPU oracle, T0 stays
        inside its budget, and the second pass re-enters through T1."""
        n_rows = 18
        _, f, frag = _seed_fragment(
            holder, rows=n_rows, bits_per_row=60, name="og"
        )
        budget = 6 * ROW_BYTES  # hot set is 3x this
        stager = DeviceStager(
            budget_bytes=budget,
            tier1_max_bytes=64 << 20,
            compressed_min_ratio=1.5,
        )
        ex = Executor(holder, device_policy="always", stager=stager)
        oracle = Executor(holder, device_policy="never", dispatch_enabled=False)
        try:
            queries = [f"Count(Row(f={k}))" for k in range(n_rows)] + [
                "Count(Intersect(Row(f=1), Row(f=2)))",
                "Count(Union(Row(f=3), Row(f=17)))",
            ]
            for q in queries:
                assert ex.execute("og", q) == oracle.execute("og", q)
            f.set_bit(3, 123456)  # invalidates T1/T0 for row 3 exactly
            for q in queries:
                assert ex.execute("og", q) == oracle.execute("og", q)
            assert stager._bytes <= budget
            # cycle the whole hot set through the row form twice: T0
            # holds 6 of 18 rows, so the second lap's re-entries MUST
            # come through T1 — and stay bit-identical to the fragment
            for _ in range(2):
                for r in range(n_rows):
                    np.testing.assert_array_equal(
                        np.asarray(stager.row(frag, r)),
                        frag.row_words(r).view("<u4"),
                    )
            assert stager._bytes <= budget
            st = stager.tier1.stats()
            assert st["admitted"] > 0
            assert st["hits"] > 0, f"hot set never re-entered via T1: {st}"
        finally:
            ex.close()
            oracle.close()


# -- stage-ahead error accounting (ISSUE 17 s1) ------------------------------


class TestStageAheadErrors:
    def test_raising_thunk_counted_journaled_loop_survives(self, holder):
        st = DeviceStager()

        def boom():
            raise ValueError("prefetch thunk exploded")

        before = len(events.snapshot(kind=events.STAGER_AHEAD_ERROR))
        st.stage_ahead(boom)
        deadline = time.monotonic() + 5.0
        while st.ahead_errors < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert st.ahead_errors == 1
        recs = events.snapshot(kind=events.STAGER_AHEAD_ERROR)
        assert len(recs) == before + 1
        assert recs[-1]["reason"] == "ValueError"
        assert "exploded" in recs[-1]["error"]
        # same reason again: counted, NOT re-journaled
        st.stage_ahead(boom)
        deadline = time.monotonic() + 5.0
        while st.ahead_errors < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert st.ahead_errors == 2
        assert len(events.snapshot(kind=events.STAGER_AHEAD_ERROR)) == before + 1
        # the loop survived: a healthy thunk still runs
        done = threading.Event()
        st.stage_ahead(done.set)
        assert done.wait(5.0), "stage-ahead loop died after a raising thunk"


# -- docs drift guard ---------------------------------------------------------


def test_docs_document_tiering_knobs_with_current_defaults():
    """docs/configuration.md names every tiering knob with the default
    the code actually uses (the test_fusion.py knob-sync idiom)."""
    from pilosa_tpu.server import Config

    cfg = Config(data_dir="x")
    root = os.path.join(os.path.dirname(__file__), "..", "docs")
    with open(os.path.join(root, "configuration.md")) as fp:
        conf = fp.read()
    for knob, default in (
        ("tier1-max-bytes", str(cfg.tier1_max_bytes)),
        ("prefetch-enabled", "true" if cfg.prefetch_enabled else "false"),
        ("prefetch-depth", str(cfg.prefetch_depth)),
        (
            "compressed-upload-min-ratio",
            str(cfg.compressed_upload_min_ratio),
        ),
    ):
        assert f"| `{knob}` | {default} |" in conf, (
            f"configuration.md row for {knob} missing or default drifted "
            f"(expected {default})"
        )
    assert "tier1-max-bytes = " in cfg.to_toml()
    for name in (
        metrics.TIER1_HITS,
        metrics.TIERING_COMPRESSED_UPLOADS,
        metrics.PREFETCH_ISSUED,
    ):
        assert name in metrics.METRICS
