"""End-to-end HTTP server tests — boots a real server on :0 and drives
the reference's getting-started 'Star Trace' workflow over REST
(mirrors reference server/handler_test.go TestHandler_Endpoints)."""

import json
import urllib.request

import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.server import Config, Server


@pytest.fixture()
def server(tmp_path):
    cfg = Config(data_dir=str(tmp_path / "data"), bind="127.0.0.1:0", metric="expvar")
    s = Server(cfg)
    s.open()
    yield s
    s.close()


def req(server, method, path, body=None, raw=False):
    url = server.uri + path
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(r) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload or b"{}")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, payload if raw else json.loads(payload or b"{}")


def test_version_info_status(server):
    st, body = req(server, "GET", "/version")
    assert st == 200 and "version" in body
    st, body = req(server, "GET", "/info")
    assert st == 200 and body["shardWidth"] == 1 << 20
    st, body = req(server, "GET", "/status")
    assert st == 200 and body["state"] == "NORMAL"


def test_star_trace_workflow(server):
    # schema
    st, _ = req(server, "POST", "/index/repository", {})
    assert st == 200
    st, _ = req(
        server, "POST", "/index/repository/field/stargazer",
        {"options": {"type": "time", "timeQuantum": "YMD"}},
    )
    assert st == 200
    st, _ = req(
        server, "POST", "/index/repository/field/language", {"options": {}}
    )
    assert st == 200

    # writes
    st, body = req(
        server, "POST", "/index/repository/query", b"Set(10, stargazer=1)"
    )
    assert st == 200 and body == {"results": [True]}
    for q in [
        "Set(20, stargazer=1)",
        "Set(10, stargazer=2)",
        "Set(30, stargazer=2)",
        "Set(10, language=5)",
        "Set(20, language=5)",
        "Set(10, stargazer=3, 2017-05-01T00:00)",
    ]:
        st, body = req(server, "POST", "/index/repository/query", q.encode())
        assert st == 200, body

    # reads
    st, body = req(server, "POST", "/index/repository/query", b"Row(stargazer=1)")
    assert st == 200
    assert body["results"][0]["columns"] == [10, 20]
    st, body = req(
        server,
        "POST",
        "/index/repository/query",
        b"Intersect(Row(stargazer=1), Row(stargazer=2))",
    )
    assert body["results"][0]["columns"] == [10]
    st, body = req(
        server, "POST", "/index/repository/query", b"Count(Row(stargazer=2))"
    )
    assert body["results"][0] == 2
    # the rank cache debounces recalculation (reference cache.go:233-241);
    # force it like the reference's own tests do before TopN assertions
    req(server, "POST", "/recalculate-caches")
    st, body = req(
        server, "POST", "/index/repository/query", b"TopN(stargazer, n=2)"
    )
    assert body["results"][0] == [
        {"id": 1, "count": 2},
        {"id": 2, "count": 2},
    ]
    # time range
    st, body = req(
        server,
        "POST",
        "/index/repository/query",
        b"Range(stargazer=3, 2017-01-01T00:00, 2018-01-01T00:00)",
    )
    assert body["results"][0]["columns"] == [10]

    # schema reflects everything
    st, body = req(server, "GET", "/schema")
    idx = body["indexes"][0]
    assert idx["name"] == "repository"
    assert {f["name"] for f in idx["fields"]} == {"stargazer", "language"}


def test_bsi_over_http(server):
    req(server, "POST", "/index/i", {})
    req(
        server, "POST", "/index/i/field/bytes",
        {"options": {"type": "int", "min": 0, "max": 1000000}},
    )
    for col, v in [(1, 100), (2, 2000), (3, 30000)]:
        st, body = req(
            server, "POST", "/index/i/query",
            f"SetValue(col={col}, bytes={v})".encode(),
        )
        assert st == 200, body
    st, body = req(server, "POST", "/index/i/query", b'Sum(field="bytes")')
    assert body["results"][0] == {"value": 32100, "count": 3}
    st, body = req(server, "POST", "/index/i/query", b"Range(bytes > 1000)")
    assert body["results"][0]["columns"] == [2, 3]


def test_import_and_export(server):
    req(server, "POST", "/index/i", {})
    req(server, "POST", "/index/i/field/f", {})
    st, body = req(
        server,
        "POST",
        "/index/i/field/f/import",
        {"rowIDs": [1, 1, 2], "columnIDs": [100, 200, 100]},
    )
    assert st == 200
    st, body = req(server, "POST", "/index/i/query", b"Row(f=1)")
    assert body["results"][0]["columns"] == [100, 200]
    st, csv_data = req(server, "GET", "/export?index=i&field=f&shard=0", raw=True)
    assert st == 200
    lines = sorted(csv_data.decode().strip().splitlines())
    assert lines == ["1,100", "1,200", "2,100"]


def test_import_values(server):
    req(server, "POST", "/index/i", {})
    req(
        server, "POST", "/index/i/field/v",
        {"options": {"type": "int", "min": -10, "max": 10}},
    )
    st, _ = req(
        server,
        "POST",
        "/index/i/field/v/import-value",
        {"columnIDs": [1, 2, 3], "values": [-5, 0, 7]},
    )
    assert st == 200
    st, body = req(server, "POST", "/index/i/query", b'Sum(field="v")')
    assert body["results"][0] == {"value": 2, "count": 3}
    st, body = req(server, "POST", "/index/i/query", b'Min(field="v")')
    assert body["results"][0] == {"value": -5, "count": 1}


def test_attrs(server):
    req(server, "POST", "/index/i", {})
    req(server, "POST", "/index/i/field/f", {})
    st, body = req(
        server, "POST", "/index/i/query",
        b'Set(1, f=10)SetRowAttrs(f, 10, category="search")SetColumnAttrs(1, name="acme")',
    )
    assert st == 200, body
    st, body = req(server, "POST", "/index/i/query", b"Row(f=10)")
    assert body["results"][0]["attrs"] == {"category": "search"}
    st, body = req(
        server, "POST", "/index/i/query?columnAttrs=true", b"Row(f=10)"
    )
    assert body["columnAttrs"] == [{"id": 1, "attrs": {"name": "acme"}}]


def test_key_translation(server):
    req(server, "POST", "/index/users", {"options": {"keys": True}})
    req(
        server, "POST", "/index/users/field/likes",
        {"options": {"keys": True}},
    )
    st, body = req(
        server, "POST", "/index/users/query", b'Set("alice", likes="pizza")'
    )
    assert st == 200 and body["results"] == [True]
    req(server, "POST", "/index/users/query", b'Set("bob", likes="pizza")')
    st, body = req(server, "POST", "/index/users/query", b'Row(likes="pizza")')
    # keys come back in column-id order; partitioned assignment makes
    # that hash-dependent, not insertion order
    assert sorted(body["results"][0]["keys"]) == ["alice", "bob"]
    st, body = req(server, "POST", "/index/users/query", b'TopN(likes, n=5)')
    assert body["results"][0] == [{"key": "pizza", "count": 2}]


def test_error_handling(server):
    st, body = req(server, "POST", "/index/nope/query", b"Row(f=1)")
    assert st == 404 and "error" in body
    st, body = req(server, "POST", "/index/i", {})
    st, body = req(server, "POST", "/index/i", {})
    assert st == 409
    st, body = req(server, "POST", "/index/i/query", b"BadCall(")
    assert st == 400 and "error" in body
    st, body = req(server, "GET", "/no/such/route")
    assert st == 404


def test_persistence_across_restart(tmp_path):
    cfg = Config(data_dir=str(tmp_path / "data"), bind="127.0.0.1:0")
    s = Server(cfg)
    s.open()
    req(s, "POST", "/index/i", {})
    req(s, "POST", "/index/i/field/f", {})
    req(s, "POST", "/index/i/query", b"Set(7, f=1)")
    node_id = s.node_id
    s.close()

    s2 = Server(Config(data_dir=str(tmp_path / "data"), bind="127.0.0.1:0"))
    s2.open()
    try:
        assert s2.node_id == node_id
        st, body = req(s2, "POST", "/index/i/query", b"Row(f=1)")
        assert body["results"][0]["columns"] == [7]
    finally:
        s2.close()


def test_restart_durability_fuzz(tmp_path):
    """Randomized write mix (sets, clears, int values, timestamps,
    attrs, op-log tails past snapshot boundaries) — a restart must
    answer every query identically to the pre-restart server."""
    import numpy as np

    rng = np.random.default_rng(12345)
    cfg = Config(data_dir=str(tmp_path / "d"), bind="127.0.0.1:0", device_policy="never")
    s = Server(cfg)
    s.open()
    req(s, "POST", "/index/i", {})
    req(s, "POST", "/index/i/field/f", {})
    req(s, "POST", "/index/i/field/t", {"options": {"type": "time", "timeQuantum": "YMD"}})
    req(s, "POST", "/index/i/field/v", {"options": {"type": "int", "min": -100, "max": 900}})
    days = ["2021-03-05T08:00", "2021-03-17T20:00", "2021-06-01T00:00"]
    batch = []
    for _ in range(1200):
        kind = rng.random()
        col = int(rng.integers(0, 3 * SHARD_WIDTH))
        row = int(rng.integers(0, 20))
        if kind < 0.55:
            batch.append(f"Set({col}, f={row})")
        elif kind < 0.65:
            batch.append(f"Clear({col}, f={row})")
        elif kind < 0.80:
            batch.append(f"Set({col}, t={row}, {days[rng.integers(0, 3)]})")
        elif kind < 0.95:
            batch.append(f"SetValue(col={col}, v={int(rng.integers(-100, 901))})")
        else:
            batch.append(f'SetRowAttrs(f, {row}, tag="r{row}", w={int(rng.integers(0, 9))})')
    for i in range(0, len(batch), 300):
        st, _ = req(s, "POST", "/index/i/query", " ".join(batch[i : i + 300]).encode())
        assert st == 200

    queries = []
    for r in range(0, 20, 3):
        queries += [
            f"Count(Row(f={r}))",
            f"Row(f={r})",
            f"TopN(f, Row(f={r}), n=5)",
            f"Count(Range(t={r}, 2021-03-01T00:00, 2021-04-01T00:00))",
        ]
    queries += ["Sum(field=v)", "Min(field=v)", "Max(field=v)",
                "Count(Range(v > 250))", "Count(Range(v >< [-50, 500]))"]
    req(s, "POST", "/recalculate-caches")
    before = {}
    for q in queries:
        st, body = req(s, "POST", "/index/i/query", q.encode())
        assert st == 200, (q, body)
        before[q] = body
    s.close()

    s2 = Server(Config(data_dir=str(tmp_path / "d"), bind="127.0.0.1:0", device_policy="never"))
    s2.open()
    try:
        req(s2, "POST", "/recalculate-caches")
        for q in queries:
            st, body = req(s2, "POST", "/index/i/query", q.encode())
            assert st == 200 and body == before[q], (q, body, before[q])
        # attrs survive too
        st, body = req(s2, "POST", "/index/i/query", b"Row(f=3)")
        assert st == 200
    finally:
        s2.close()


def test_backup_restore_full_index(tmp_path):
    """Disaster recovery drill: tar every fragment off a populated
    server, restore into a FRESH server (new data dir), and answer
    identically — the reference's fragment archive workflow end to end."""
    import numpy as np

    rng = np.random.default_rng(31337)
    src = Server(Config(data_dir=str(tmp_path / "src"), bind="127.0.0.1:0", device_policy="never"))
    src.open()
    req(src, "POST", "/index/b", {})
    req(src, "POST", "/index/b/field/f", {})
    req(src, "POST", "/index/b/field/v", {"options": {"type": "int", "min": 0, "max": 99}})
    rows = rng.integers(0, 10, size=800)
    cols = rng.integers(0, 2 * SHARD_WIDTH, size=800)
    st, _ = req(src, "POST", "/index/b/field/f/import",
                {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()})
    assert st == 200
    vcols = rng.choice(2 * SHARD_WIDTH, size=120, replace=False)
    st, _ = req(src, "POST", "/index/b/field/v/import-value",
                {"columnIDs": vcols.tolist(), "values": rng.integers(0, 100, size=120).tolist()})
    assert st == 200
    req(src, "POST", "/recalculate-caches")
    queries = [f"Count(Row(f={r}))" for r in range(10)] + [
        "TopN(f, n=5)", "Sum(field=v)", "Count(Range(v >= 50))"]
    before = {q: req(src, "POST", "/index/b/query", q.encode())[1] for q in queries}

    # tar every (field, view, shard) off the source
    archives = []
    for field in ("f", "v"):
        st, views = req(src, "GET", f"/index/b/field/{field}/views")
        for view in views["views"]:
            for shard in (0, 1):
                st, data = req(
                    src, "GET",
                    f"/internal/fragment/data?index=b&field={field}&view={view}&shard={shard}",
                    raw=True,
                )
                if st == 200:
                    archives.append((field, view, shard, data))
    src.close()
    assert archives

    dst = Server(Config(data_dir=str(tmp_path / "dst"), bind="127.0.0.1:0", device_policy="never"))
    dst.open()
    try:
        req(dst, "POST", "/index/b", {})
        req(dst, "POST", "/index/b/field/f", {})
        req(dst, "POST", "/index/b/field/v", {"options": {"type": "int", "min": 0, "max": 99}})
        for field, view, shard, data in archives:
            st, _ = req(
                dst, "POST",
                f"/internal/fragment/data?index=b&field={field}&view={view}&shard={shard}",
                data, raw=True,
            )
            assert st == 200, (field, view, shard)
        req(dst, "POST", "/recalculate-caches")
        for q in queries:
            st, body = req(dst, "POST", "/index/b/query", q.encode())
            assert st == 200 and body == before[q], (q, body, before[q])
    finally:
        dst.close()


def test_debug_vars_and_recalculate(server):
    req(server, "POST", "/index/i", {})
    req(server, "POST", "/index/i/field/f", {})
    req(server, "POST", "/index/i/query", b"Set(1, f=1)")
    st, _ = req(server, "POST", "/recalculate-caches")
    assert st == 200
    st, body = req(server, "GET", "/debug/vars")
    assert st == 200


def test_fragment_data_roundtrip(server):
    req(server, "POST", "/index/i", {})
    req(server, "POST", "/index/i/field/f", {})
    req(server, "POST", "/index/i/query", b"Set(1, f=1)Set(2, f=1)")
    st, data = req(
        server, "GET", "/internal/fragment/data?index=i&field=f&shard=0", raw=True
    )
    assert st == 200
    # blocks endpoint
    st, body = req(
        server, "GET", "/internal/fragment/blocks?index=i&field=f&shard=0"
    )
    assert st == 200 and len(body["blocks"]) == 1
    # restore into a second field
    req(server, "POST", "/index/i/field/g", {})
    st, _ = req(
        server,
        "POST",
        "/internal/fragment/data?index=i&field=g&shard=0",
        data,
    )
    assert st == 200
    st, body = req(server, "POST", "/index/i/query", b"Row(g=1)")
    assert body["results"][0]["columns"] == [1, 2]


def test_malformed_protobuf_is_400_not_executed(server):
    """A clipped length-delimited field must 400, not silently execute a
    truncated request (advisor finding: publicproto._decode_multi)."""
    from pilosa_tpu.utils import publicproto

    req(server, "POST", "/index/mp", body=b"")
    req(server, "POST", "/index/mp/field/f", body=b"")
    good = publicproto.encode_import_request(
        "mp", "f", 0, row_ids=[1, 2], column_ids=[10, 20], timestamps=None
    )
    clipped = good[:-3]
    url = server.uri + "/index/mp/field/f/import"
    r = urllib.request.Request(
        url,
        data=clipped,
        method="POST",
        headers={"Content-Type": publicproto.CONTENT_TYPE},
    )
    try:
        with urllib.request.urlopen(r) as resp:
            status, payload, ctype = resp.status, resp.read(), resp.headers.get("Content-Type")
    except urllib.error.HTTPError as e:
        status, payload, ctype = e.code, e.read(), e.headers.get("Content-Type")
    assert status == 400
    # import routes answer errors in plain text (reference http.Error),
    # not a protobuf QueryResponse
    assert ctype.startswith("text/plain")
    assert b"unmarshalling" in payload
    # nothing was imported
    st, body = req(server, "POST", "/index/mp/query", body=b"Count(Row(f=1))")
    assert st == 200 and body["results"] == [0]


def test_query_route_protobuf_error_payload(server):
    """The query route DOES answer protobuf clients with
    QueryResponse{Err} (reference http/error.go)."""
    from pilosa_tpu.utils import publicproto

    req(server, "POST", "/index/qe", body=b"")
    url = server.uri + "/index/qe/query"
    bad = publicproto.encode_query_request("ThisIsNotPQL((", shards=None)
    r = urllib.request.Request(
        url,
        data=bad,
        method="POST",
        headers={"Content-Type": publicproto.CONTENT_TYPE},
    )
    try:
        with urllib.request.urlopen(r) as resp:
            payload, ctype = resp.read(), resp.headers.get("Content-Type")
    except urllib.error.HTTPError as e:
        payload, ctype = e.read(), e.headers.get("Content-Type")
    assert ctype == publicproto.CONTENT_TYPE
    decoded = publicproto.decode_query_response(payload)
    assert decoded["error"]


def test_periodic_cache_flush(tmp_path):
    """reference monitorCacheFlush (holder.go:425): fragment .cache
    files persist on the interval, not only at close."""
    import os
    import time

    from pilosa_tpu.server import Config, Server

    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="none",
        cache_flush_interval=0.2,
        anti_entropy_interval=0,
    )
    s = Server(cfg)
    s.open()
    try:
        req(s, "POST", "/index/cf")
        req(s, "POST", "/index/cf/field/f")
        req(s, "POST", "/index/cf/query", b"Set(1, f=3) Set(2, f=3)")
        frag = s.holder.fragment("cf", "f", "standard", 0)
        cache_path = frag.cache_path()
        deadline = time.time() + 5
        while time.time() < deadline and not os.path.exists(cache_path):
            time.sleep(0.05)
        assert os.path.exists(cache_path)
        from pilosa_tpu.core.cache import read_cache

        assert read_cache(cache_path) == [3]
    finally:
        s.close()
