"""Device kernel tests against semantic oracles.

BSI kernels are validated against a direct per-column evaluation of the
predicate (not against a re-implementation of the reference's recurrence)
so a transcription bug in both places can't hide."""

import numpy as np
import pytest

from pilosa_tpu import ops

W = 256  # words per row for tests (8192 columns) — kernels are width-agnostic


def pack(cols, width_words=W):
    w = np.zeros(width_words, dtype=np.uint32)
    for c in cols:
        w[c >> 5] |= np.uint32(1 << (c & 31))
    return w


def unpack(words):
    bits = np.unpackbits(np.asarray(words).view(np.uint8), bitorder="little")
    return set(np.nonzero(bits)[0].tolist())


@pytest.fixture(scope="module")
def bsi_data():
    rng = np.random.default_rng(11)
    ncols = W * 32
    depth = 10
    # ~60% of columns have a value
    has = rng.random(ncols) < 0.6
    vals = rng.integers(0, 1 << depth, size=ncols)
    planes = np.zeros((depth + 1, W), dtype=np.uint32)
    for c in range(ncols):
        if has[c]:
            planes[depth][c >> 5] |= np.uint32(1 << (c & 31))
            for i in range(depth):
                if (vals[c] >> i) & 1:
                    planes[i][c >> 5] |= np.uint32(1 << (c & 31))
    filt_cols = set(np.nonzero(rng.random(ncols) < 0.5)[0].tolist())
    return depth, has, vals, planes, pack(filt_cols), filt_cols


def test_popcount_and_algebra():
    rng = np.random.default_rng(3)
    a_cols = set(rng.choice(W * 32, 500, replace=False).tolist())
    b_cols = set(rng.choice(W * 32, 700, replace=False).tolist())
    a, b = pack(a_cols), pack(b_cols)
    assert int(ops.count_bits(a)) == len(a_cols)
    assert unpack(np.asarray(ops.and_(a, b))) == (a_cols & b_cols)
    assert unpack(np.asarray(ops.or_(a, b))) == (a_cols | b_cols)
    assert unpack(np.asarray(ops.xor_(a, b))) == (a_cols ^ b_cols)
    assert unpack(np.asarray(ops.andnot(a, b))) == (a_cols - b_cols)
    assert int(ops.intersection_count(a, b)) == len(a_cols & b_cols)


def test_fold_and_matrix_counts():
    rng = np.random.default_rng(5)
    sets = [set(rng.choice(W * 32, 800, replace=False).tolist()) for _ in range(4)]
    mat = np.stack([pack(s) for s in sets])
    inter = sets[0] & sets[1] & sets[2] & sets[3]
    union = sets[0] | sets[1] | sets[2] | sets[3]
    assert unpack(np.asarray(ops.fold_rows(mat, "and"))) == inter
    assert unpack(np.asarray(ops.fold_rows(mat, "or"))) == union
    assert int(ops.count_and_fold(mat)) == len(inter)
    counts = np.asarray(ops.count_bits_rows(mat))
    assert counts.tolist() == [len(s) for s in sets]
    src = pack(sets[0])
    ic = np.asarray(ops.intersection_counts_matrix(src, mat))
    assert ic.tolist() == [len(sets[0] & s) for s in sets]


def test_u64_u32_reinterpret():
    rng = np.random.default_rng(9)
    w64 = rng.integers(0, 2**63, size=64, dtype=np.uint64)
    w32 = ops.u64_to_u32(w64)
    # bit p of the 64-bit stream must land at bit p of the 32-bit stream
    b64 = np.unpackbits(w64.view(np.uint8), bitorder="little")
    b32 = np.unpackbits(w32.view(np.uint8), bitorder="little")
    assert np.array_equal(b64, b32)
    assert np.array_equal(ops.u32_to_u64(w32), w64)


def test_bsi_sum(bsi_data):
    depth, has, vals, planes, filt, filt_cols = bsi_data
    counts = np.asarray(
        ops.bsi_plane_counts(planes, filt, bit_depth=depth, has_filter=True)
    )
    total = sum(int(counts[i]) << i for i in range(depth))
    want = sum(int(vals[c]) for c in range(len(has)) if has[c] and c in filt_cols)
    assert total == want
    assert int(counts[depth]) == sum(1 for c in range(len(has)) if has[c] and c in filt_cols)
    # unfiltered
    counts = np.asarray(
        ops.bsi_plane_counts(planes, planes[0], bit_depth=depth, has_filter=False)
    )
    assert sum(int(counts[i]) << i for i in range(depth)) == sum(
        int(vals[c]) for c in range(len(has)) if has[c]
    )


def test_bsi_min_max(bsi_data):
    depth, has, vals, planes, filt, filt_cols = bsi_data
    present = [int(vals[c]) for c in range(len(has)) if has[c] and c in filt_cols]
    bits, count = ops.bsi_min(planes, filt, bit_depth=depth, has_filter=True)
    got_min = sum(1 << i for i, b in enumerate(np.asarray(bits)) if b)
    assert got_min == min(present)
    assert int(count) == present.count(min(present))
    bits, count = ops.bsi_max(planes, filt, bit_depth=depth, has_filter=True)
    got_max = sum(1 << i for i, b in enumerate(np.asarray(bits)) if b)
    assert got_max == max(present)
    assert int(count) == present.count(max(present))


@pytest.mark.parametrize("pred", [0, 1, 7, 300, 511, 512, 1023])
def test_bsi_range_ops(bsi_data, pred):
    depth, has, vals, planes, _, _ = bsi_data
    ncols = len(has)
    exists = {c for c in range(ncols) if has[c]}

    def got(kernel, **kw):
        return unpack(np.asarray(kernel(planes, np.uint32(pred), bit_depth=depth, **kw)))

    assert got(ops.bsi_range_eq) == {c for c in exists if vals[c] == pred}
    assert got(ops.bsi_range_neq) == {c for c in exists if vals[c] != pred}
    if pred == 0:
        # Reference quirk: rangeLT(0, strict) yields value==0 columns
        # (reference fragment.go:712-760 leading-zeros path; the executor
        # normally guards this via bsiGroup.baseValue out-of-range checks).
        assert got(ops.bsi_range_lt, allow_equality=False) == {
            c for c in exists if vals[c] == 0
        }
    else:
        assert got(ops.bsi_range_lt, allow_equality=False) == {
            c for c in exists if vals[c] < pred
        }
    assert got(ops.bsi_range_lt, allow_equality=True) == {
        c for c in exists if vals[c] <= pred
    }
    assert got(ops.bsi_range_gt, allow_equality=False) == {
        c for c in exists if vals[c] > pred
    }
    assert got(ops.bsi_range_gt, allow_equality=True) == {
        c for c in exists if vals[c] >= pred
    }


@pytest.mark.parametrize("lo,hi", [(0, 1023), (5, 5), (100, 700), (900, 1023), (0, 0)])
def test_bsi_between(bsi_data, lo, hi):
    depth, has, vals, planes, _, _ = bsi_data
    exists = {c for c in range(len(has)) if has[c]}
    out = unpack(
        np.asarray(
            ops.bsi_range_between(
                planes, np.uint32(lo), np.uint32(hi), bit_depth=depth
            )
        )
    )
    assert out == {c for c in exists if lo <= vals[c] <= hi}


def test_pallas_scores_matches_xla():
    """Pallas TopN scoring kernel (interpret mode on CPU) vs the XLA path."""
    from pilosa_tpu.ops.pallas_kernels import (
        TILE_W,
        intersection_counts_matrix_pallas,
        pad_for_pallas,
    )

    rng = np.random.default_rng(21)
    R, Wp = 16, TILE_W
    mat = rng.integers(0, 2**32, size=(R, Wp), dtype=np.uint32)
    src = rng.integers(0, 2**32, size=(Wp,), dtype=np.uint32)
    padded, r = pad_for_pallas(mat)
    psrc = np.pad(src, (0, padded.shape[1] - Wp))
    got = np.asarray(intersection_counts_matrix_pallas(psrc, padded, interpret=True))[:r]
    want = np.bitwise_count(mat & src[None, :]).sum(axis=1)
    assert np.array_equal(got, want)
    # non-tile-aligned words exercise the padding path on both axes
    mat2 = rng.integers(0, 2**32, size=(13, Wp + 7), dtype=np.uint32)
    src2 = rng.integers(0, 2**32, size=(Wp + 7,), dtype=np.uint32)
    padded, r = pad_for_pallas(mat2)
    psrc = np.pad(src2, (0, padded.shape[1] - src2.shape[0]))
    got = np.asarray(intersection_counts_matrix_pallas(psrc, padded, interpret=True))[:r]
    want = np.bitwise_count(mat2 & src2[None, :]).sum(axis=1)
    assert np.array_equal(got, want)
