"""Incremental HBM delta staging (snapshot + delta model).

Every staged form must stay bit-identical across three paths after any
interleaving of writes and staged reads:

  * the delta path — a shared stager patching resident arrays forward
  * a forced full re-stage — a fresh stager rebuilding from host
  * the CPU source of truth — the fragment's packed-word exports

plus byte-accounting invariants under eviction and epoch reset (no
leaked ``_bytes``, no stale delta replay after ``reset_after_wedge``).
"""

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import (
    FieldOptions,
    Holder,
    VIEW_BSI_GROUP_PREFIX,
    VIEW_STANDARD,
)
from pilosa_tpu.core.field import FIELD_TYPE_INT
from pilosa_tpu.executor import DeviceStager, Executor
from pilosa_tpu.utils import metrics

W32 = SHARD_WIDTH // 32


def _delta_counters(snap=None):
    snap = snap if snap is not None else metrics.snapshot()
    out = {"applied": 0.0, "fallback": 0.0, "cold": 0.0, "invalidation": 0.0}
    for k, v in snap.items():
        if isinstance(v, dict):
            continue
        if k.startswith(metrics.STAGER_DELTA_APPLIED):
            out["applied"] += v
        elif k.startswith(metrics.STAGER_DELTA_FALLBACK):
            out["fallback"] += v
        elif k.startswith(metrics.STAGER_MISSES_COLD):
            out["cold"] += v
        elif k.startswith(metrics.STAGER_MISSES_INVALIDATION):
            out["invalidation"] += v
    return out


@pytest.fixture
def holder(tmp_path):
    h = Holder(str(tmp_path / "d"))
    h.open()
    yield h
    h.close()


def _seed_fragment(holder, rows=24, bits_per_row=40, seed=7):
    idx = holder.create_index("dl")
    f = idx.create_field("f")
    rng = np.random.default_rng(seed)
    rids, cids = [], []
    for r in range(rows):
        rids += [r] * bits_per_row
        cids += rng.integers(0, SHARD_WIDTH, size=bits_per_row).tolist()
    f.import_bits(rids, cids)
    return idx, f, holder.fragment("dl", "f", VIEW_STANDARD, 0)


def _assert_row_identical(stager, frag, row_id):
    got = np.asarray(stager.row(frag, row_id))
    want = frag.row_words(row_id).view("<u4")
    np.testing.assert_array_equal(got, want)


class TestFormsBitIdentical:
    """Fuzz: random write/read interleavings on one fragment; every
    staged form answers bit-identically to the CPU full path AND to a
    forced full re-stage."""

    def test_random_interleaving_all_forms(self, holder):
        idx, f, frag = _seed_fragment(holder)
        v = idx.create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=0, max=4000))
        v.import_values([5, 9, 700], [17, 2000, 3999])
        vfrag = holder.fragment("dl", "v", VIEW_BSI_GROUP_PREFIX + "v", 0)
        depth = v.bsi_group("v").bit_depth()

        shared = DeviceStager()  # delta path: entries live across writes
        rng = np.random.default_rng(1234)
        staged_rows = (0, 3, 5, 11)

        for step in range(30):
            op = rng.choice(["set", "clear", "setvalue"])
            if op == "set":
                f.set_bit(int(rng.integers(0, 24)), int(rng.integers(0, SHARD_WIDTH)))
            elif op == "clear":
                f.clear_bit(int(rng.integers(0, 24)), int(rng.integers(0, SHARD_WIDTH)))
            else:
                v.set_value(int(rng.integers(0, 1000)), int(rng.integers(0, 4000)))

            fresh = DeviceStager()  # forced full re-stage oracle
            # -- row
            rid = int(rng.integers(0, 24))
            want_row = frag.row_words(rid).view("<u4")
            np.testing.assert_array_equal(np.asarray(shared.row(frag, rid)), want_row)
            np.testing.assert_array_equal(np.asarray(fresh.row(frag, rid)), want_row)
            # -- rows (padded + unpadded)
            for pad in (False, True):
                got = np.asarray(shared.rows(frag, staged_rows, pad_pow2=pad))
                full = np.asarray(fresh.rows(frag, staged_rows, pad_pow2=pad))
                np.testing.assert_array_equal(got, full)
                for k, r in enumerate(staged_rows):
                    np.testing.assert_array_equal(
                        got[k], frag.row_words(r).view("<u4")
                    )
            # -- matrix
            ids_s, dev_s = shared.matrix(frag)
            ids_f, dev_f = fresh.matrix(frag)
            assert ids_s == ids_f == frag.row_ids()
            np.testing.assert_array_equal(np.asarray(dev_s), np.asarray(dev_f))
            # -- planes
            got_p = np.asarray(shared.planes(vfrag, depth))
            want_p = vfrag.bsi_planes(depth).view("<u4").reshape(depth + 1, -1)
            np.testing.assert_array_equal(got_p, want_p)
            # -- sparse_rows (documented fallback form — still correct)
            blocks, brow, bslot, _ = shared.sparse_rows(frag, staged_rows)
            fb, fr, fs, _ = fresh.sparse_rows(frag, staged_rows)
            np.testing.assert_array_equal(np.asarray(blocks), np.asarray(fb))

        # the shared stager must have actually exercised the delta path
        assert shared.delta_applies > 0

    def test_stack_forms_bit_identical(self, holder):
        idx = holder.create_index("st")
        f = idx.create_field("f")
        rng = np.random.default_rng(99)
        rids, cids = [], []
        for shard in range(2):
            for r in range(8):
                rids += [r] * 30
                cids += (
                    shard * SHARD_WIDTH
                    + rng.integers(0, SHARD_WIDTH, size=30)
                ).tolist()
        f.import_bits(rids, cids)
        v = idx.create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=0, max=500))
        v.import_values([3, SHARD_WIDTH + 8], [77, 431])
        frags = [
            holder.fragment("st", "f", VIEW_STANDARD, s) for s in range(2)
        ]
        vfrags = [
            holder.fragment("st", "v", VIEW_BSI_GROUP_PREFIX + "v", s)
            for s in range(2)
        ]
        depth = v.bsi_group("v").bit_depth()
        shared = DeviceStager()

        for step in range(12):
            shard = int(rng.integers(0, 2))
            if rng.random() < 0.5:
                f.set_bit(
                    int(rng.integers(0, 8)),
                    shard * SHARD_WIDTH + int(rng.integers(0, SHARD_WIDTH)),
                )
            else:
                v.set_value(
                    shard * SHARD_WIDTH + int(rng.integers(0, SHARD_WIDTH)),
                    int(rng.integers(0, 500)),
                )
            fresh = DeviceStager()
            rid = int(rng.integers(0, 8))
            got = np.asarray(shared.row_stack(frags, rid))
            np.testing.assert_array_equal(
                got, np.asarray(fresh.row_stack(frags, rid))
            )
            for s in range(2):
                np.testing.assert_array_equal(
                    got[s], frags[s].row_words(rid).view("<u4")
                )
            got_p = np.asarray(shared.planes_stack(vfrags, depth))
            np.testing.assert_array_equal(
                got_p, np.asarray(fresh.planes_stack(vfrags, depth))
            )
        assert shared.delta_applies > 0


class TestExecutorReadWriteMix:
    def test_device_results_match_cpu_under_writes(self, holder):
        idx, f, frag = _seed_fragment(holder, rows=40, bits_per_row=60)
        cpu = Executor(holder, device_policy="never")
        dev = Executor(holder, device_policy="always")
        rng = np.random.default_rng(5)
        queries = [
            "TopN(f, n=6)",
            "TopN(f, Row(f=3), n=4)",
            "Count(Intersect(Row(f=1), Row(f=2)))",
            "Count(Union(Row(f=0), Row(f=5), Row(f=7)))",
        ]
        for step in range(15):
            f.set_bit(int(rng.integers(0, 40)), int(rng.integers(0, SHARD_WIDTH)))
            for q in queries:
                assert cpu.execute("dl", q) == dev.execute("dl", q), (step, q)
        # the executor's stager absorbed writes as deltas, not rebuilds
        assert dev.stager.delta_applies > 0


class TestFallbacks:
    def test_small_bulk_import_rides_delta_path(self, holder):
        # the bulk-import cliff fix: batches at or under
        # ``delta_max_batch`` apply as one write wave (delta-extend +
        # single generation bump), not a delta reset + full re-stage
        idx, f, frag = _seed_fragment(holder)
        st = DeviceStager()
        st.row(frag, 0)
        before = _delta_counters()
        f.import_bits([0, 0], [17, 18])
        _assert_row_identical(st, frag, 0)
        after = _delta_counters()
        assert after["applied"] > before["applied"]
        assert after["fallback"] == before["fallback"]

    def test_large_bulk_import_forces_full_restage(self, holder):
        idx, f, frag = _seed_fragment(holder)
        frag.delta_max_batch = 4
        st = DeviceStager()
        st.row(frag, 0)
        before = _delta_counters()
        # over the wave threshold: bulk path resets the delta log
        f.import_bits([0] * 8, list(range(17, 25)))
        _assert_row_identical(st, frag, 0)
        after = _delta_counters()
        assert after["invalidation"] == before["invalidation"] + 1
        assert after["fallback"] > before["fallback"]

    def test_log_truncation_falls_back(self, holder):
        idx, f, frag = _seed_fragment(holder)
        frag.delta_log_max = 8
        st = DeviceStager()
        st.row(frag, 0)
        for i in range(20):  # > log capacity: snapshot gen falls below floor
            f.set_bit(0, 1000 + i)
        before = _delta_counters()
        _assert_row_identical(st, frag, 0)
        after = _delta_counters()
        assert after["invalidation"] == before["invalidation"] + 1

    def test_external_generation_bump_is_not_misread_as_empty_delta(self, holder):
        """A raw ``generation += 1`` (the fragment-restore path) must
        fault the log — replaying "no deltas" over replaced content
        would serve stale bits."""
        idx, f, frag = _seed_fragment(holder)
        st = DeviceStager()
        st.row(frag, 0)
        with frag.mu:
            frag.storage.add(17)  # bypasses the log, like a restore
            frag.generation += 1
        _assert_row_identical(st, frag, 0)  # full rebuild, fresh bits
        # and the log re-anchors: the next tracked write delta-applies
        applied0 = st.delta_applies
        f.set_bit(0, 99)
        _assert_row_identical(st, frag, 0)
        assert st.delta_applies == applied0 + 1

    def test_ratio_zero_always_restages(self, holder):
        idx, f, frag = _seed_fragment(holder)
        st = DeviceStager(delta_max_ratio=0.0)
        st.row(frag, 5)
        f.set_bit(5, 4242)
        before = _delta_counters()
        _assert_row_identical(st, frag, 5)
        after = _delta_counters()
        assert after["invalidation"] == before["invalidation"] + 1
        assert st.delta_applies == 0

    def test_delta_disabled_restages(self, holder):
        idx, f, frag = _seed_fragment(holder)
        st = DeviceStager(delta_enabled=False)
        st.row(frag, 5)
        f.set_bit(5, 4242)
        _assert_row_identical(st, frag, 5)
        assert st.delta_applies == 0

    def test_matrix_shape_change_restages(self, holder):
        idx, f, frag = _seed_fragment(holder, rows=6)
        st = DeviceStager()
        ids0, _ = st.matrix(frag)
        f.set_bit(500, 1)  # brand-new row: matrix shape changes
        ids1, dev1 = st.matrix(frag)
        assert 500 in ids1 and ids1 == frag.row_ids()
        np.testing.assert_array_equal(
            np.asarray(dev1)[ids1.index(500)], frag.row_words(500).view("<u4")
        )


class TestByteAccounting:
    def test_no_leaked_bytes_under_eviction_with_deltas(self, holder):
        idx, f, frag = _seed_fragment(holder)
        # budget fits ~2 row blocks (128 KB each): staging several rows
        # forces continuous eviction while deltas patch survivors
        st = DeviceStager(budget_bytes=300 * 1024)
        rng = np.random.default_rng(3)
        for step in range(40):
            if rng.random() < 0.3:
                f.set_bit(int(rng.integers(0, 24)), int(rng.integers(0, SHARD_WIDTH)))
            rid = int(rng.integers(0, 8))
            _assert_row_identical(st, frag, rid)
            with st._mu:
                ent_bytes = sum(e.nbytes for e in st._cache.values())
                assert st._bytes == ent_bytes
                assert st._bytes <= max(
                    st.budget_bytes, max((e.nbytes for e in st._cache.values()), default=0)
                )

    def test_refresh_replaces_bytes_not_accumulates(self, holder):
        idx, f, frag = _seed_fragment(holder)
        st = DeviceStager()
        st.row(frag, 0)
        b0 = st._bytes
        for i in range(5):
            f.set_bit(0, 2000 + i)
            st.row(frag, 0)
        assert st._bytes == b0  # same block, same footprint, 5 refreshes

    def test_reset_after_wedge_drops_deltas_and_bytes(self, holder):
        idx, f, frag = _seed_fragment(holder)
        st = DeviceStager()
        st.row(frag, 0)
        f.set_bit(0, 123)
        st.reset_after_wedge()
        assert st._bytes == 0 and not st._cache
        before = _delta_counters()
        _assert_row_identical(st, frag, 0)  # rebuilt, not delta-replayed
        after = _delta_counters()
        assert after["cold"] == before["cold"] + 1
        assert after["applied"] == before["applied"]
        ent_bytes = sum(e.nbytes for e in st._cache.values())
        assert st._bytes == ent_bytes


class TestDeltaLogUnit:
    def test_deltas_since_tracks_and_truncates(self, holder):
        idx, f, frag = _seed_fragment(holder, rows=2, bits_per_row=4)
        g0 = frag.generation
        f.set_bit(0, 10)
        f.clear_bit(0, 10)
        pos, is_set, gen = frag.deltas_since(g0)
        assert pos.tolist() == [10, 10]
        assert is_set.tolist() == [True, False]
        assert gen == frag.generation
        # empty tail
        pos2, is_set2, _ = frag.deltas_since(frag.generation)
        assert pos2.size == 0 and is_set2.size == 0
        # truncation floor
        frag.delta_log_max = 4
        for i in range(10):
            f.set_bit(1, 20 + i)
        assert frag.deltas_since(g0) is None

    def test_snapshot_preserves_log_continuity(self, holder):
        idx, f, frag = _seed_fragment(holder, rows=2, bits_per_row=4)
        g0 = frag.generation
        f.set_bit(0, 33)
        frag.snapshot()  # content-preserving generation bump
        d = frag.deltas_since(g0)
        assert d is not None
        pos, is_set, gen = d
        assert pos.tolist() == [33] and gen == frag.generation
