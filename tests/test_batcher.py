"""Batched TopN scoring: batch kernels (XLA + Pallas interpret) and the
continuous micro-batching scorer."""

import threading

import numpy as np
import pytest

from pilosa_tpu import ops
from pilosa_tpu.executor import BatchedScorer
from pilosa_tpu.ops.pallas_kernels import (
    TILE_R,
    TILE_W,
    intersection_counts_matrix_batch_pallas,
)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    mat = rng.integers(0, 2**32, size=(TILE_R, TILE_W), dtype=np.uint32)
    srcs = rng.integers(0, 2**32, size=(4, TILE_W), dtype=np.uint32)
    return srcs, mat


def test_batch_op_matches_single(data):
    srcs, mat = data
    batched = np.asarray(ops.intersection_counts_matrix_batch(srcs, mat))
    for q in range(srcs.shape[0]):
        single = np.asarray(ops.intersection_counts_matrix(srcs[q], mat))
        np.testing.assert_array_equal(batched[q], single)


def test_batch_pallas_matches_xla(data):
    srcs, mat = data
    got = np.asarray(
        intersection_counts_matrix_batch_pallas(srcs, mat, interpret=True)
    )
    want = np.asarray(ops.intersection_counts_matrix_batch(srcs, mat))
    np.testing.assert_array_equal(got, want)


def test_scorer_single_caller(data):
    srcs, mat = data
    s = BatchedScorer()
    got = s.score(("k",), mat, srcs[0])
    np.testing.assert_array_equal(
        got, np.asarray(ops.intersection_counts_matrix(srcs[0], mat))
    )
    assert s.dispatches == 1 and s.batched_queries == 0  # no batching alone


def test_scorer_concurrent_same_key(data):
    """Deterministic coalescing: mark the scorer as having an active
    dispatcher so every caller enqueues as a waiter; then run one
    dispatch round — it must drain the whole queue into ONE batched
    launch."""
    import time

    srcs, mat = data
    q = srcs.shape[0]
    s = BatchedScorer()
    key = ("frag0", 0, (1, 2))
    with s._lock:
        s._dispatching = True  # play the leader from this thread

    results = [None] * q

    def run(i):
        results[i] = s.score(key, mat, srcs[i])

    threads = [threading.Thread(target=run, args=(i,)) for i in range(q)]
    for t in threads:
        t.start()
    # wait until every caller is enqueued behind the active dispatcher
    deadline = time.time() + 5
    while time.time() < deadline:
        with s._lock:
            ent = s._pending.get(key)
            if ent is not None and len(ent[1]) == q:
                break
        time.sleep(0.001)
    else:
        s._dispatch_loop()
        pytest.fail("callers never enqueued")
    s._dispatch_loop()  # drains everything, then clears _dispatching
    for t in threads:
        t.join()
    for i in range(q):
        np.testing.assert_array_equal(
            results[i], np.asarray(ops.intersection_counts_matrix(srcs[i], mat))
        )
    assert s.dispatches == 1  # one coalesced launch for all callers
    assert s.batched_queries == q


def test_scorer_distinct_keys_not_mixed(data):
    srcs, mat = data
    mat2 = np.roll(mat, 1, axis=0)
    s = BatchedScorer()
    a = s.score(("a",), mat, srcs[0])
    b = s.score(("b",), mat2, srcs[0])
    np.testing.assert_array_equal(
        a, np.asarray(ops.intersection_counts_matrix(srcs[0], mat))
    )
    np.testing.assert_array_equal(
        b, np.asarray(ops.intersection_counts_matrix(srcs[0], mat2))
    )


def test_scorer_pads_to_pow2(data):
    srcs, mat = data
    s = BatchedScorer(max_batch=8)
    # force the batched path with 3 sources via the internal fill
    from pilosa_tpu.executor.batcher import _Slot

    slots = [_Slot(srcs[i]) for i in range(3)]
    s._fill(slots, mat)
    for i in range(3):
        np.testing.assert_array_equal(
            slots[i].result,
            np.asarray(ops.intersection_counts_matrix(srcs[i], mat)),
        )


def test_scorer_error_propagates_to_peers(data, monkeypatch):
    """A failed batched launch must surface the real error to every
    coalesced caller, not hand peers a None result."""
    from pilosa_tpu.executor import batcher as batcher_mod
    from pilosa_tpu.executor.batcher import _Slot

    srcs, mat = data
    s = BatchedScorer()
    boom = RuntimeError("device exploded")

    def raise_fn(*a, **k):
        raise boom

    monkeypatch.setattr(
        batcher_mod.ops, "intersection_counts_matrix_batch_list", raise_fn
    )
    slots = [_Slot(srcs[0]), _Slot(srcs[1])]
    with pytest.raises(RuntimeError, match="device exploded"):
        s._fill(slots, mat)
    for slot in slots:
        assert slot.event.is_set()
        with pytest.raises(RuntimeError, match="device exploded"):
            slot.finish()


def test_multicall_request_runs_parallel_and_batches():
    """A single PQL request with several read-only TopN calls executes
    them concurrently, coalescing their scoring into batched launches;
    results match per-call sequential execution, order preserved."""
    import tempfile

    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import Executor

    with tempfile.TemporaryDirectory() as d:
        h = Holder(d)
        h.open()
        idx = h.create_index("mc")
        f = idx.create_field("f")
        rng = np.random.default_rng(13)
        for row in range(6):
            cols = rng.choice(4000, size=600, replace=False)
            f.import_bits([row] * len(cols), cols.tolist())
        ex = Executor(h, device_policy="always")
        multi = " ".join(f"TopN(f, Row(f={r}), n=3)" for r in range(6))
        sequential = [
            ex.execute("mc", f"TopN(f, Row(f={r}), n=3)")[0] for r in range(6)
        ]
        got = ex.execute("mc", multi)
        assert got == sequential
        # writes force the sequential path and still work
        mixed = ex.execute("mc", "Set(9999, f=0) Row(f=0)")
        assert mixed[0] is True
        assert 9999 in [int(c) for c in mixed[1].columns()]
        h.close()


def test_executor_concurrent_topn_batches():
    """Concurrent TopN queries through the executor produce identical
    results to sequential execution and coalesce kernel launches."""
    import tempfile

    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import Executor

    with tempfile.TemporaryDirectory() as d:
        h = Holder(d)
        h.open()
        idx = h.create_index("bt")
        f = idx.create_field("f")
        rng = np.random.default_rng(9)
        for row in range(8):
            cols = rng.choice(5000, size=800, replace=False)
            f.import_bits([row] * len(cols), cols.tolist())
        ex = Executor(h, device_policy="always")
        sequential = [
            ex.execute("bt", f"TopN(f, Row(f={r}), n=4)") for r in range(4)
        ]
        results = [None] * 4
        barrier = threading.Barrier(4)

        def run(i):
            barrier.wait()
            results[i] = ex.execute("bt", f"TopN(f, Row(f={i}), n=4)")

        threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == sequential
        h.close()


def test_stager_concurrent_cold_miss_stages_once(tmp_path):
    """Concurrent misses on one cold key build once: every caller gets
    the SAME device array (so scorer keys coalesce) and the byte budget
    is charged exactly once."""
    import threading

    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import DeviceStager

    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("cc")
    f = idx.create_field("f")
    f.import_bits([0, 1, 2], [1, 2, 3])
    frag = h.fragment("cc", "f", "standard", 0)
    st = DeviceStager()
    n = 8
    out = [None] * n
    barrier = threading.Barrier(n)

    def run(i):
        barrier.wait()
        out[i] = st.rows(frag, (0, 1, 2), pad_pow2=True)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(o is out[0] for o in out)  # one staged array shared
    assert st.misses == 1
    ent_bytes = sum(e.nbytes for e in st._cache.values())
    assert st._bytes == ent_bytes  # budget charged exactly once
    h.close()


class TestRankingsMemo:
    def test_chunk_ids_consistent_across_recalculate(self):
        """A provider holding a rankings snapshot must get ids for THAT
        snapshot even if the cache recalculates concurrently."""
        from pilosa_tpu.core.cache import RankCache

        c = RankCache(100)
        for i in range(20):
            c.bulk_add(i, 100 - i)
        c.recalculate()
        snap = c.top()
        want = tuple(p[0] for p in snap[0:8])
        assert snap.chunk_ids(0, 8) == want
        # cache swaps rankings; the old snapshot's memo still matches it
        c.bulk_add(55, 999)
        c.recalculate()
        assert c.top() is not snap
        assert snap.chunk_ids(0, 8) == want  # memo hit, same object data
        new = c.top()
        assert new.chunk_ids(0, 1) == (55,)

    def test_memoization_returns_same_tuple(self):
        from pilosa_tpu.core.cache import Rankings

        r = Rankings([(5, 9), (3, 7), (1, 2)])
        a = r.chunk_ids(0, 2)
        assert a is r.chunk_ids(0, 2)
        assert r.chunk_ids(2, 10) == (1,)


def test_chunk_schedule_pow2_and_deterministic():
    """The lazy-walk chunk schedule: small head, geometric growth to
    MAX_CHUNK, every size pow2 (bounded XLA compile cache), and a pure
    function of position — chunk boundaries (and therefore staging
    keys) must be identical across queries for the HBM cache to hit."""
    from pilosa_tpu.executor.executor import (
        FIRST_CHUNK,
        MAX_CHUNK,
        SCORE_CHUNK,
        _chunk_size,
    )

    pos, sizes = 0, []
    while pos < 100_000:
        s = _chunk_size(pos)
        sizes.append(s)
        pos += s
    assert sizes[0] == FIRST_CHUNK
    assert sizes[1] == SCORE_CHUNK
    assert all(s & (s - 1) == 0 for s in sizes)
    assert max(sizes) == MAX_CHUNK
    assert sizes == sorted(sizes)  # monotone growth
    # replaying the boundary positions yields the same schedule
    pos = 0
    for s in sizes:
        assert _chunk_size(pos) == s
        pos += s
