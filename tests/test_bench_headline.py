"""Bench artifact headline conventions (bench.py helpers): the
published value is the best measured closed-loop serving number, never
lowered by a degraded window below the sequential number the run
achieved, and the vs_baseline note always states which convention the
ratio uses. These lock the semantics the BENCH_r05 artifacts and
docs/perf_analysis.md rely on."""

import importlib.util
import os

import pytest


@pytest.fixture(scope="module")
def bench():
    path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    spec = importlib.util.spec_from_file_location("bench_module", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_headline_prefers_best_closed_loop(bench):
    t = {"topn_qps": 12.5, "topn_qps_c8": 39.0, "topn_qps_c32": 101.6,
         "topn_qps_c64": 132.9}
    assert bench.headline_mode(t) == ("64 closed-loop clients", 132.9)


def test_headline_never_below_sequential(bench):
    # a degraded concurrency window must not lower the published number
    t = {"topn_qps": 0.41, "topn_qps_c8": 0.39}
    assert bench.headline_mode(t) == ("sequential", 0.41)


def test_headline_sequential_only_run(bench):
    assert bench.headline_mode({"topn_qps": 12.5}) == ("sequential", 12.5)


def test_best_closed_loop_ignores_non_numeric_and_other_keys(bench):
    t = {"topn_qps": 5.0, "topn_qps_c8": 7.0, "topn_qps_c32": "err",
         "topn_queries_timed": 99, "chain_qps_c8": 1000.0}
    assert bench.best_closed_loop(t, "topn_qps_c") == ("topn_qps_c8", 7.0)
    assert bench.best_closed_loop({}, "topn_qps_c") == (None, None)


def test_vs_baseline_note_matches_mode(bench):
    serving = bench.vs_baseline_fields("32 closed-loop clients", 112.4, 0.4)
    assert serving["vs_baseline"] == round(112.4 / 0.4, 2)
    assert "serving" in serving["vs_baseline_note"]
    seq = bench.vs_baseline_fields("sequential", 12.5, 0.4)
    assert "sequential qps both sides" in seq["vs_baseline_note"]
    assert bench.vs_baseline_fields("sequential", 12.5, None) == {}


def test_vs_baseline_uses_measured_cpu_closed_loop_denominator(bench):
    # when a CPU closed-loop window was measured, the serving ratio
    # divides by the BEST measured CPU throughput, not the asserted
    # sequential ceiling — the denominator is backed by data
    out = bench.vs_baseline_fields(
        "32 closed-loop clients", 112.4, 0.4, cpu_closed_qps=0.5
    )
    assert out["vs_baseline"] == round(112.4 / 0.5, 2)
    assert out["baseline_cpu_closed_qps"] == 0.5
    assert "measured" in out["vs_baseline_note"]
    # a degraded closed-loop window never RAISES the ratio
    out = bench.vs_baseline_fields(
        "32 closed-loop clients", 112.4, 0.4, cpu_closed_qps=0.3
    )
    assert out["vs_baseline"] == round(112.4 / 0.4, 2)


def test_window_quality_derives_rtt_and_depth(bench):
    t = {
        "topn_qps": 12.5,
        "topn_qps_c64": 100.0,
        "profile": {"device_rtt_ms": 20.0},
    }
    wq = bench.window_quality(t)
    assert wq["sustained_rtt_ms"] == 20.0
    # 100 qps x 20 ms RTT = 2 concurrent round-trips in flight
    assert wq["pipelining_depth"] == 2.0
    assert wq["headline_qps"] == 100.0
    # no RTT profile measured -> no quality record
    assert bench.window_quality({"topn_qps": 12.5}) is None
    assert bench.window_quality({}) is None
    assert bench.window_quality(
        {"topn_qps": 1.0, "profile": {"error": "x"}}
    ) is None


def test_degraded_rtt_refuses_last_good_overwrite(bench):
    good = {"sustained_rtt_ms": 20.0, "pipelining_depth": 2.0}
    # mildly worse RTT: fine
    ok = {"sustained_rtt_ms": 30.0, "pipelining_depth": 2.0}
    assert bench.window_degraded(ok, good) == (False, None)
    # RTT past the degradation factor: refused, with the reason
    bad = {"sustained_rtt_ms": 20.0 * bench.DEGRADED_RTT_FACTOR + 1,
           "pipelining_depth": 2.0}
    degraded, why = bench.window_degraded(bad, good)
    assert degraded and "RTT" in why


def test_collapsed_pipelining_depth_refuses_overwrite(bench):
    good = {"sustained_rtt_ms": 20.0, "pipelining_depth": 10.0}
    bad = {"sustained_rtt_ms": 20.0,
           "pipelining_depth": 10.0 * bench.DEGRADED_DEPTH_FACTOR - 0.5}
    degraded, why = bench.window_degraded(bad, good)
    assert degraded and "depth" in why


def test_window_gating_bootstrap_and_unprovable_runs(bench):
    wq = {"sustained_rtt_ms": 20.0, "pipelining_depth": 2.0}
    # no prior quality record (pre-gating artifact): anything may seed
    assert bench.window_degraded(wq, None) == (False, None)
    assert bench.window_degraded(None, None) == (False, None)
    # a run that measured no quality must not displace one that did
    degraded, why = bench.window_degraded(None, wq)
    assert degraded and "window_quality" in why


def test_window_quality_carries_fused_rtt_fields(bench):
    t = {
        "topn_qps": 12.5,
        "profile": {
            "device_rtt_ms": 20.0,
            "fused_rtt": {
                "rtt_multiple": 1.3,
                "fused_launches_per_query": 1.0,
            },
        },
    }
    wq = bench.window_quality(t)
    assert wq["fused_rtt_multiple"] == 1.3
    assert wq["fused_launches_per_query"] == 1.0
    # no fused probe (or a bad value) -> fields simply absent
    wq = bench.window_quality({"topn_qps": 12.5, "profile": {"device_rtt_ms": 20.0}})
    assert "fused_rtt_multiple" not in wq
    t["profile"]["fused_rtt"] = {"rtt_multiple": 0}
    assert "fused_rtt_multiple" not in bench.window_quality(t)


def test_fused_window_regression_refuses_overwrite(bench):
    good = {"sustained_rtt_ms": 20.0, "pipelining_depth": 2.0,
            "fused_rtt_multiple": 1.3}
    # comparable fused window: fine
    ok = dict(good, fused_rtt_multiple=1.5)
    assert bench.window_degraded(ok, good) == (False, None)
    # fusion regressed to per-call round trips: refused, with the reason
    bad = dict(good, fused_rtt_multiple=1.3 * bench.DEGRADED_RTT_FACTOR + 0.1)
    degraded, why = bench.window_degraded(bad, good)
    assert degraded and "fused" in why
    # fused window not measured while last-good has one: refused
    degraded, why = bench.window_degraded(
        {"sustained_rtt_ms": 20.0, "pipelining_depth": 2.0}, good
    )
    assert degraded and "fused" in why
    # last-good PRE-fusion (no fused fields): new fused fields accepted
    old = {"sustained_rtt_ms": 20.0, "pipelining_depth": 2.0}
    assert bench.window_degraded(good, old) == (False, None)


def test_vs_baseline_seq_ratio_rides_alongside(bench):
    out = bench.vs_baseline_fields(
        "64 closed-loop clients", 132.9, 0.4, seq_qps=12.5
    )
    assert out["vs_baseline_seq"] == round(12.5 / 0.4, 2)
    # sequential mode: the headline IS the sequential ratio already
    out = bench.vs_baseline_fields("sequential", 12.5, 0.4, seq_qps=12.5)
    assert "vs_baseline_seq" not in out
