"""Roaring bitmap engine tests — property tests against a Python-set oracle
plus serialization round-trips (mirrors the reference's
roaring/roaring_internal_test.go strategy)."""


import numpy as np
import pytest

from pilosa_tpu.roaring import (
    ARRAY_MAX_SIZE,
    Bitmap,
    Container,
    marshal_op,
    unmarshal_op,
)


def make_cases(seed=7):
    rng = np.random.default_rng(seed)
    cases = []
    # array-form: sparse small values
    cases.append(sorted(rng.choice(1 << 18, size=500, replace=False).tolist()))
    # bitmap-form: dense in one container
    cases.append(sorted(rng.choice(1 << 16, size=8000, replace=False).tolist()))
    # run-form: contiguous ranges
    runs = []
    for start in (0, 70000, 1 << 20):
        runs.extend(range(start, start + 3000))
    cases.append(runs)
    # spanning many containers + large positions
    cases.append(
        sorted(
            set(
                rng.choice(1 << 30, size=2000, replace=False).tolist()
                + [2**40, 2**40 + 1, 2**50]
            )
        )
    )
    cases.append([])
    return cases


CASES = make_cases()


def mk(vals):
    b = Bitmap.from_sorted(np.array(sorted(vals), dtype=np.uint64))
    return b


@pytest.mark.parametrize("i", range(len(CASES)))
@pytest.mark.parametrize("j", range(len(CASES)))
def test_set_algebra_matches_python_sets(i, j):
    a_vals, b_vals = set(CASES[i]), set(CASES[j])
    a, b = mk(a_vals), mk(b_vals)
    assert sorted(a_vals & b_vals) == a.intersect(b).slice_all().tolist()
    assert sorted(a_vals | b_vals) == a.union(b).slice_all().tolist()
    assert sorted(a_vals - b_vals) == a.difference(b).slice_all().tolist()
    assert sorted(a_vals ^ b_vals) == a.xor(b).slice_all().tolist()
    assert len(a_vals & b_vals) == a.intersection_count(b)
    assert len(a_vals) == a.count()


@pytest.mark.parametrize("i", range(len(CASES)))
def test_add_remove_contains(i):
    vals = set(CASES[i])
    b = Bitmap()
    for v in CASES[i]:
        assert b.add(v)
        assert not b.add(v)
    assert b.count() == len(vals)
    for v in list(vals)[:100]:
        assert b.contains(v)
        assert b.remove(v)
        assert not b.remove(v)
        assert not b.contains(v)


def test_count_range():
    vals = CASES[3]
    b = mk(vals)
    arr = np.array(sorted(vals), dtype=np.uint64)
    for start, end in [(0, 1 << 30), (100, 2**40 + 1), (2**40, 2**50 + 1), (5, 5)]:
        want = int(((arr >= start) & (arr < end)).sum())
        assert b.count_range(start, end) == want, (start, end)


def test_slice_range():
    b = mk(CASES[0])
    arr = np.array(sorted(CASES[0]), dtype=np.uint64)
    got = b.slice_range(1000, 100000)
    want = arr[(arr >= 1000) & (arr < 100000)]
    assert got.tolist() == want.tolist()


@pytest.mark.parametrize("i", range(len(CASES)))
def test_serialization_roundtrip(i):
    b = mk(CASES[i])
    data = b.to_bytes()
    b2 = Bitmap.unmarshal_binary(data)
    assert b.slice_all().tolist() == b2.slice_all().tolist()
    # serialize again: stable
    assert b2.to_bytes() == data


def test_serialization_with_oplog():
    b = mk(CASES[0])
    data = b.to_bytes()
    # Simulate an op log appended after the snapshot.
    extra = marshal_op(0, 12345678) + marshal_op(1, CASES[0][0]) + marshal_op(0, 7)
    b2 = Bitmap.unmarshal_binary(data + extra)
    want = set(CASES[0]) | {12345678, 7}
    want.discard(CASES[0][0])
    assert b2.slice_all().tolist() == sorted(want)
    assert b2.op_n == 3


def test_op_marshal_roundtrip():
    for typ, val in [(0, 0), (1, 2**63 + 11), (0, 42)]:
        assert unmarshal_op(marshal_op(typ, val)) == (typ, val)
    with pytest.raises(ValueError):
        unmarshal_op(b"\x00" * 13)


def test_offset_range():
    # bits in shard-1 positions, offset to absolute column space
    vals = [2**20 + 5, 2**20 + 99, 2**20 + 65536]
    b = mk(vals)
    out = b.offset_range(3 * 2**20, 2**20, 2 * 2**20)
    assert out.slice_all().tolist() == [3 * 2**20 + 5, 3 * 2**20 + 99, 3 * 2**20 + 65536]


def test_words_range_roundtrip():
    vals = CASES[1]
    b = mk(vals)
    words = b.to_words_range(0, 1 << 20)
    assert int(np.bitwise_count(words).sum()) == len(set(vals))
    b2 = Bitmap.from_words_range(words)
    assert b2.slice_all().tolist() == sorted(set(vals))


def test_container_form_transitions():
    c = Container()
    # array -> bitmap when exceeding ARRAY_MAX_SIZE
    for v in range(ARRAY_MAX_SIZE + 1):
        c.add(v)
    assert c.typ == 2  # bitmap
    assert c.n == ARRAY_MAX_SIZE + 1
    # optimize to run form (fully contiguous)
    c.optimize()
    assert c.typ == 3  # run
    assert c.n == ARRAY_MAX_SIZE + 1
    assert c.contains(17)
    assert not c.contains(ARRAY_MAX_SIZE + 1)


def test_flip():
    b = mk([1, 3, 5])
    f = b.flip(0, 6)
    assert f.slice_all().tolist() == [0, 2, 4, 6]


def test_bulk_from_sorted_dense():
    vals = np.arange(0, 300000, 2, dtype=np.uint64)
    b = Bitmap.from_sorted(vals)
    assert b.count() == vals.size
    assert b.slice_all().tolist() == vals.tolist()


REFERENCE_FIXTURE = "/root/reference/testdata/sample_view/0"


def test_parse_reference_fixture():
    """Ingest a roaring file produced by the reference Go implementation."""
    import os

    if not os.path.exists(REFERENCE_FIXTURE):
        pytest.skip("reference fixture unavailable")
    with open(REFERENCE_FIXTURE, "rb") as f:
        b = Bitmap.unmarshal_binary(f.read())
    assert b.count() == 35001
    assert len(b.containers) == 14207
    a = b.slice_all()
    assert int(a[0]) == 32966 and int(a[-1]) == 1048560182
    b2 = Bitmap.unmarshal_binary(b.to_bytes())
    assert a.tolist() == b2.slice_all().tolist()


# -- exhaustive container-form pair matrix ----------------------------------
# The reference exercises every {array,bitmap,run}x{array,bitmap,run}
# operation pair (roaring_internal_test.go); here each form pair runs
# through the full algebra against a Python-set oracle, in both operand
# orders, plus count-only variants and serialization of each form.

import itertools

from pilosa_tpu.roaring import (
    CONTAINER_ARRAY,
    CONTAINER_BITMAP,
    CONTAINER_RUN,
    positions_to_words,
)

FORM_VALUES = {
    "array": sorted(np.random.default_rng(21).choice(60000, 300, replace=False).tolist()),
    "bitmap": sorted(np.random.default_rng(22).choice(65536, 9000, replace=False).tolist()),
    "run": [v for s in (10, 30000, 61000) for v in range(s, s + 1500)],
}


def _bitmap_in_form(form, values):
    """One-container bitmap whose container is forced into `form`."""
    b = Bitmap()
    low = np.array(values, dtype=np.uint16)
    if form == "array":
        # keep under ARRAY_MAX_SIZE so it stays array-form
        low = low[:ARRAY_MAX_SIZE]
        c = Container.from_array(low)
        want_type = CONTAINER_ARRAY
    elif form == "bitmap":
        c = Container.from_words(positions_to_words(low), n=len(low))
        want_type = CONTAINER_BITMAP
    else:
        c = Container.from_array(low)
        c.optimize()
        want_type = CONTAINER_RUN
    b.containers[0] = c
    return b, want_type


@pytest.mark.parametrize(
    "fa,fb", list(itertools.product(FORM_VALUES, FORM_VALUES))
)
def test_container_form_pair_algebra(fa, fb):
    ba, ta = _bitmap_in_form(fa, FORM_VALUES[fa])
    bb, tb = _bitmap_in_form(fb, FORM_VALUES[fb])
    # the matrix only covers all 9 pairs if each side really holds its form
    assert ba.containers[0].typ == ta, fa
    assert bb.containers[0].typ == tb, fb
    sa = set(int(v) for v in ba.slice_all())
    sb = set(int(v) for v in bb.slice_all())
    ops_oracle = {
        "intersect": sa & sb,
        "union": sa | sb,
        "difference": sa - sb,
        "xor": sa ^ sb,
    }
    for op, want in ops_oracle.items():
        got = set(int(v) for v in getattr(ba, op)(bb).slice_all())
        assert got == want, (fa, fb, op)
    assert ba.intersection_count(bb) == len(sa & sb)
    assert ba.count() == len(sa) and bb.count() == len(sb)


@pytest.mark.parametrize("form", list(FORM_VALUES))
def test_container_form_serialization(form):
    b, _ = _bitmap_in_form(form, FORM_VALUES[form])
    rt = Bitmap.unmarshal_binary(b.to_bytes())
    np.testing.assert_array_equal(rt.slice_all(), b.slice_all())


@pytest.mark.parametrize("form", list(FORM_VALUES))
def test_container_form_point_ops(form):
    b, _ = _bitmap_in_form(form, FORM_VALUES[form])
    before = set(int(v) for v in b.slice_all())
    probe = 40001
    had = probe in before
    assert b.contains(probe) == had
    b.add(probe)
    assert b.contains(probe)
    b.remove(probe)
    assert not b.contains(probe)
    assert b.count() == len(before - {probe})


class TestFlipVectorized:
    def test_flip_matches_per_bit_semantics(self):
        import numpy as np

        from pilosa_tpu.roaring import Bitmap

        rng = np.random.default_rng(51)
        vals = np.unique(rng.integers(0, 1 << 18, size=4000, dtype=np.uint64))
        b = Bitmap.from_sorted(vals)
        for start, end in [
            (0, 6),
            (5, 5),
            (100, (1 << 16) - 1),
            ((1 << 16) - 3, (1 << 16) + 3),  # crosses a container edge
            (70000, 200000),                 # spans whole containers
            (0, (1 << 18) + 100),            # past the last set bit
        ]:
            got = set(int(v) for v in b.flip(start, end))
            have = set(int(v) for v in vals)
            want = (have - set(range(start, end + 1))) | (
                set(range(start, end + 1)) - have
            )
            assert got == want, (start, end)

    def test_flip_empty_and_reverse_range(self):
        from pilosa_tpu.roaring import Bitmap

        b = Bitmap(3, 70000)
        assert sorted(b.flip(10, 5)) == [3, 70000]  # end < start = no-op clone
        e = Bitmap()
        assert sorted(e.flip(2, 4)) == [2, 3, 4]
