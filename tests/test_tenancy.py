"""Multi-tenant QoS (ISSUE 19, server/tenancy.py): per-index admission
token buckets (429 + Retry-After, distinct from the 503 overload shed),
virtual-time weighted-fair scheduling in the pipeline class queues,
HbmGovernor per-index quotas with over-quota-first relief, and
per-tenant SLO/waterfall attribution.

The fairness tests are property-style: over a backlogged window the WFQ
dequeue mix must track the configured weights within a bound, and a
single 100x-flooding tenant must not push another tenant's queue wait
past its deadline budget."""

import os
import random
import threading
import time

import pytest

from pilosa_tpu.executor.hbm import HbmGovernor
from pilosa_tpu.server.pipeline import (
    Overloaded,
    QueryPipeline,
    _Entry,
    _TenantFairQueue,
)
from pilosa_tpu.server.tenancy import (
    TenancyManager,
    TenantThrottled,
    parse_tenant_map,
)


def entry(index):
    return _Entry(cls="interactive", thunk=lambda: None, index=index)


# -- config parsing ----------------------------------------------------------


def test_parse_tenant_map_basics_and_default():
    m, default = parse_tenant_map("a=4, b=1.5, *=2")
    assert m == {"a": 4.0, "b": 1.5}
    assert default == 2.0
    m, default = parse_tenant_map("")
    assert m == {} and default is None
    # malformed / negative entries are skipped, never fatal
    m, default = parse_tenant_map("a=oops,=3,b=-1,c=7")
    assert m == {"c": 7.0}
    assert default is None


def test_manager_disabled_by_default_is_passthrough():
    tn = TenancyManager()
    assert not tn.enabled
    # no lock taken, no bucket created, nothing raised
    tn.admit("anything", "interactive", nbytes=1 << 20)
    tn.release("anything", "interactive", nbytes=1 << 20)
    assert tn.snapshot()["tenants"] == {}


# -- admission ---------------------------------------------------------------


def test_token_bucket_throttles_429_with_retry_after():
    tn = TenancyManager(qps="a=5")
    assert tn.enabled
    codes = []
    for _ in range(50):
        try:
            tn.admit("a", "interactive")
            codes.append(200)
        except TenantThrottled as e:
            codes.append(e.status)
            assert e.status == 429
            assert e.retry_after > 0
    # burst = 2s * 5qps = 10 tokens admitted, the rest throttled
    assert codes.count(200) == 10
    assert codes.count(429) == 40
    # an unrelated tenant is untouched (no explicit qps, no default)
    tn.admit("b", "interactive")


def test_throttle_is_per_tenant_not_global():
    tn = TenancyManager(qps="noisy=1")
    with pytest.raises(TenantThrottled):
        for _ in range(10):
            tn.admit("noisy", "interactive")
    # the quiet tenant admits freely while the noisy one is throttled
    for _ in range(100):
        tn.admit("quiet", "interactive")


def test_internal_class_exempt_from_admission():
    tn = TenancyManager(qps="a=1")
    for _ in range(50):
        tn.admit("a", "internal")  # never throttled


def test_inflight_byte_cap():
    tn = TenancyManager(inflight_bytes="a=1000")
    tn.admit("a", "interactive", nbytes=900)
    with pytest.raises(TenantThrottled) as ei:
        tn.admit("a", "interactive", nbytes=900)
    assert ei.value.status == 429
    tn.release("a", "interactive", nbytes=900)
    tn.admit("a", "interactive", nbytes=900)


def test_throttled_is_429_overload_is_503():
    # the two failure modes clients must distinguish: per-tenant flow
    # control (back off, your own bucket) vs whole-server overload
    # (retry elsewhere / later)
    assert TenantThrottled("x").status == 429
    assert Overloaded("x").status == 503


# -- weighted-fair queue -----------------------------------------------------


def test_wfq_without_weights_is_exactly_fifo():
    q = _TenantFairQueue(None)
    es = [entry(f"t{i % 3}") for i in range(64)]
    for e in es:
        q.append(e)
    assert [q.popleft() for _ in range(len(es))] == es


def test_wfq_dequeue_tracks_weights_within_bound():
    """Property: over any backlogged window, each tenant's dequeue
    share tracks weight/total within a small absolute bound."""
    weights = {"a": 4.0, "b": 2.0, "c": 1.0}
    q = _TenantFairQueue(lambda t: weights[t])
    rng = random.Random(19)
    per_tenant = 400
    backlog = [entry(t) for t in weights for _ in range(per_tenant)]
    rng.shuffle(backlog)
    for e in backlog:
        q.append(e)
    window = 350  # every tenant stays backlogged throughout
    got = {t: 0 for t in weights}
    for _ in range(window):
        got[q.popleft().index] += 1
    total_w = sum(weights.values())
    for t, w in weights.items():
        expect = window * w / total_w
        # unit-cost WFQ is within one quantum per tenant per round;
        # 5% absolute slack is generous and version-stable
        assert abs(got[t] - expect) <= window * 0.05 + 2.0, (t, got)


def test_wfq_flooder_cannot_starve_light_tenant():
    """One tenant enqueues 100x the other's load; the light tenant's
    entries still dequeue near the front (bounded queue positions), so
    its queue wait stays inside any sane deadline budget."""
    weights = {"noisy": 1.0, "quiet": 1.0}
    q = _TenantFairQueue(lambda t: weights[t])
    for _ in range(200):
        q.append(entry("noisy"))
    quiet = entry("quiet")
    q.append(quiet)  # arrives dead last
    pos = 0
    while True:
        pos += 1
        if q.popleft() is quiet:
            break
    # FIFO would put it at position 201; WFQ interleaves it immediately
    assert pos <= 3, pos


def test_wfq_idle_tenant_gets_no_banked_credit():
    weights = {"a": 1.0, "b": 1.0}
    q = _TenantFairQueue(lambda t: weights[t])
    # a drains 100 entries alone, advancing virtual time
    for _ in range(100):
        q.append(entry("a"))
    for _ in range(100):
        q.popleft()
    # b was idle the whole time: it may NOT monopolize the next window
    for _ in range(20):
        q.append(entry("a"))
        q.append(entry("b"))
    first10 = [q.popleft().index for _ in range(10)]
    assert 3 <= first10.count("b") <= 7, first10


def test_wfq_remove_is_respected():
    q = _TenantFairQueue(lambda t: 1.0)
    es = [entry("a") for _ in range(5)]
    for e in es:
        q.append(e)
    q.remove(es[1])
    assert len(q) == 4
    assert es[1] not in list(q)
    out = [q.popleft() for _ in range(4)]
    assert es[1] not in out


def test_starved_tenant_queue_wait_stays_inside_deadline_budget():
    """End-to-end pipeline regression: a 100x flooder on one tenant
    must not push the other tenant's queue wait past its deadline
    budget (here 250ms — the interactive default objective)."""
    tn = TenancyManager(weights="noisy=1,quiet=1")
    pl = QueryPipeline(
        workers={"interactive": 1, "bulk": 1, "internal": 1},
        queue_limits={"interactive": 512, "bulk": 1, "internal": 1},
        tenancy=tn,
    )
    stop = time.monotonic() + 1.2
    budget_s = 0.25

    def flood():
        while time.monotonic() < stop:
            try:
                pl.submit(
                    "interactive",
                    lambda: time.sleep(0.002),
                    index="noisy",
                )
            except Overloaded:
                time.sleep(0.001)

    flooders = [threading.Thread(target=flood) for _ in range(4)]
    for t in flooders:
        t.start()
    time.sleep(0.1)  # let the backlog build
    waits = []
    while time.monotonic() < stop - 0.2:
        t0 = time.monotonic()
        pl.submit("interactive", lambda: None, index="quiet")
        waits.append(time.monotonic() - t0)
        time.sleep(0.01)
    for t in flooders:
        t.join(10)
    pl.close(drain=5.0)
    assert waits, "no quiet-tenant samples collected"
    assert max(waits) < budget_s, (max(waits), len(waits))
    stats = pl.stats()
    assert stats["weighted_fair"]
    assert stats["tenants"]["quiet"]["admitted"] == len(waits)
    assert stats["tenants"]["noisy"]["admitted"] > 0


def test_pipeline_tenant_counters_shed_and_throttle():
    tn = TenancyManager(qps="limited=1")
    pl = QueryPipeline(
        workers={"interactive": 1, "bulk": 1, "internal": 1},
        queue_limits={"interactive": 1, "bulk": 1, "internal": 1},
        tenancy=tn,
    )
    try:
        with pytest.raises(TenantThrottled) as ei:
            for _ in range(10):
                pl.submit("interactive", lambda: None, index="limited")
        assert ei.value.status == 429
        row = pl.stats()["tenants"]["limited"]
        assert row["throttled"] >= 1
        assert row["admitted"] >= 1
    finally:
        pl.close(drain=1.0)


# -- HBM governor sub-tenant accounting --------------------------------------


def test_governor_by_index_charges_and_releases_balance():
    gov = HbmGovernor(budget_bytes=1 << 30)
    gov.register("stager", share_bytes=1 << 30, evict_fn=lambda need: 0)
    gov.reserve("stager", 100, index="a")
    gov.reserve("stager", 50, index="b")
    gov.reserve("stager", 25, index="a")
    assert gov.index_used("a") == 125
    assert gov.index_used("b") == 50
    gov.release("stager", 125, index="a")
    gov.release("stager", 50, index="b")
    assert gov.index_used("a") == 0
    assert gov.index_used("b") == 0
    st = gov.stats()
    # fully-released indexes are pruned from the attribution map
    assert st["tenants"]["stager"].get("by_index", {}) == {}


def test_governor_by_index_balances_under_concurrency():
    """Satellite 4: concurrent per-index reserve/release (staging) with
    interleaved relief sweeps — the per-index ledger must balance to
    exactly the net outstanding bytes per index."""
    gov = HbmGovernor(budget_bytes=1 << 30)
    evicted = threading.Event()

    def evict_fn(need, prefer=None):
        evicted.set()
        return 0  # nothing actually freed: pure accounting pressure

    gov.register("stager", share_bytes=1 << 30, evict_fn=evict_fn)
    indexes = ["a", "b", "c", "d"]
    outstanding = {i: 0 for i in indexes}
    mu = threading.Lock()
    stop = threading.Event()

    def churn(seed):
        rng = random.Random(seed)
        held = []  # (index, nbytes) this thread still owes a release
        for _ in range(400):
            idx = rng.choice(indexes)
            n = rng.randrange(1, 4096)
            gov.reserve("stager", n, index=idx)
            held.append((idx, n))
            with mu:
                outstanding[idx] += n
            if len(held) > 3:
                ridx, rn = held.pop(rng.randrange(len(held)))
                gov.release("stager", rn, index=ridx)
                with mu:
                    outstanding[ridx] -= rn
        for ridx, rn in held[: len(held) // 2]:
            gov.release("stager", rn, index=ridx)
            with mu:
                outstanding[ridx] -= rn

    def sweeper():
        while not stop.is_set():
            gov.relieve(4096)
            time.sleep(0.001)

    threads = [threading.Thread(target=churn, args=(s,)) for s in range(6)]
    sw = threading.Thread(target=sweeper)
    sw.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    stop.set()
    sw.join(10)
    for idx in indexes:
        assert gov.index_used(idx) == outstanding[idx], idx
    # total tenant usage equals the sum of per-index attributions
    st = gov.stats()["tenants"]["stager"]
    assert st["used"] == sum(outstanding.values())
    assert st["by_index"] == {
        i: n for i, n in outstanding.items() if n > 0
    }


def test_reserve_past_quota_sweeps_only_that_index():
    gov = HbmGovernor(budget_bytes=1 << 30)
    calls = []

    def evict_fn(need, prefer=None):
        calls.append((need, tuple(prefer) if prefer is not None else None))
        return 0

    gov.register("stager", share_bytes=1 << 30, evict_fn=evict_fn)
    gov.set_index_quotas({"capped": 1000})
    gov.reserve("stager", 800, index="capped")
    assert calls == []  # under quota: no sweep
    gov.reserve("stager", 800, index="capped")
    # over quota by 600: a targeted sweep of ONLY this index's blocks
    assert calls and calls[-1][1] == ("capped",)
    assert calls[-1][0] >= 600
    # an uncapped index never triggers a quota sweep
    calls.clear()
    gov.reserve("stager", 1 << 20, index="free")
    assert calls == []


def test_relief_prefers_over_quota_index_first():
    """Satellite 4: under global pressure, the over-quota tenant's
    blocks go first; an under-quota tenant loses nothing until the
    preferred pass came up short."""
    gov = HbmGovernor(budget_bytes=10_000)
    sweep_log = []
    # an over-quota-preferring tier that can free everything asked
    freed_pool = {"n": 100_000}

    def evict_fn(need, prefer=None):
        sweep_log.append(tuple(prefer) if prefer is not None else None)
        take = min(need, freed_pool["n"])
        freed_pool["n"] -= take
        # relief accounting: evictions release from the over-quota index
        if take:
            gov.release("stager", take, index="hog")
        return take

    gov.register("stager", share_bytes=10_000, evict_fn=evict_fn)
    gov.set_index_quotas({"hog": 2_000})
    gov.reserve("stager", 6_000, index="innocent")
    # hog blows past its quota AND pushes the ledger over budget
    gov.reserve("stager", 6_000, index="hog")
    # the first sweep pass targeted the over-quota index, not global LRU
    assert sweep_log[0] == ("hog",)
    # the innocent tenant kept every byte
    assert gov.index_used("innocent") == 6_000


def test_quota_stats_surface():
    gov = HbmGovernor(budget_bytes=1 << 20)
    gov.register("stager", share_bytes=1 << 20, evict_fn=lambda need: 0)
    gov.set_index_quotas({"a": 4096}, default=8192)
    gov.reserve("stager", 5000, index="b")
    st = gov.stats()
    assert st["index_quotas"] == {"a": 4096, "default": 8192}
    assert st["index_used"]["b"] == 5000
    assert gov.index_over_quota("b") == 0  # 5000 < 8192 default
    gov.reserve("stager", 5000, index="b")
    assert gov.index_over_quota("b") == 10_000 - 8192
    assert gov.over_quota_indexes() == ["b"]


# -- SLO + snapshot -----------------------------------------------------------


def test_tenant_objectives_register_and_burn():
    from pilosa_tpu.utils import slo

    tn = TenancyManager(objectives="gold=100@0.999,*=500@0.99")
    objs = tn.slo_objectives()
    assert objs == {"tenant:gold": (0.1, 0.999)}
    mon = slo.SLOMonitor(objectives={})
    old = slo.MONITOR
    slo.MONITOR = mon
    try:
        tn.observe("gold", 0.05, ok=True)  # explicit objective
        tn.observe("lazy", 0.05, ok=True)  # registered from the * default
        assert mon.has_class("tenant:gold")
        assert mon.has_class("tenant:lazy")
        rates = mon.burn_rates()
        assert "tenant:lazy" in rates
    finally:
        slo.MONITOR = old


def test_snapshot_lists_every_known_tenant():
    tn = TenancyManager(weights="a=4", qps="b=2")
    tn.admit("c", "interactive")  # touched at runtime only
    snap = tn.snapshot()
    assert set(snap["tenants"]) >= {"a", "b"}
    assert snap["tenants"]["a"]["weight"] == 4.0
    assert snap["tenants"]["b"]["qps"] == 2.0


# -- config + docs ------------------------------------------------------------

TENANT_KNOBS = {
    "tenant-weights": '""',
    "tenant-qps": '""',
    "tenant-hbm-quota": '""',
    "tenant-inflight-bytes": '""',
    "tenant-objectives": '""',
}


def test_config_tenant_knobs_roundtrip():
    from pilosa_tpu.server.config import Config

    cfg = Config.from_dict(
        {
            "tenant-weights": "a=4,*=1",
            "tenant-qps": "a=100",
            "tenant-hbm-quota": "a=1048576",
            "tenant-inflight-bytes": "a=65536",
            "tenant-objectives": "a=250@0.999",
        }
    )
    assert cfg.tenant_weights == "a=4,*=1"
    toml = cfg.to_toml()
    for key in TENANT_KNOBS:
        assert key in toml, key
    from pilosa_tpu.server import config as config_mod

    cfg2 = Config.from_dict(config_mod.tomllib.loads(toml))
    assert cfg2.tenant_qps == "a=100"
    assert cfg2.tenant_objectives == "a=250@0.999"


def test_docs_configuration_names_tenant_knobs():
    root = os.path.join(os.path.dirname(__file__), "..", "docs")
    with open(os.path.join(root, "configuration.md")) as f:
        doc = f.read()
    for knob, default in TENANT_KNOBS.items():
        assert f"`{knob}`" in doc, f"configuration.md missing {knob}"
    # the 429-vs-503 contract is operator-facing administration doc
    with open(os.path.join(root, "administration.md")) as f:
        admin = f.read()
    assert "429" in admin and "tenant" in admin
    assert "/debug/tenancy" in admin
