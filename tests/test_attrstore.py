"""Attr store unit tests: SQLite B-tree residency (reference
boltdb/attrstore.go:82), merge semantics, block-checksum diff
(attr.go:90-120), LRU bounding, and round-3 JSONL migration."""

import json

from pilosa_tpu.utils.attrstore import ATTR_BLOCK_SIZE, AttrStore


class TestBasics:
    def test_merge_and_delete_semantics(self, tmp_path):
        s = AttrStore(str(tmp_path / "a.db"))
        s.set_attrs(1, {"name": "alice", "age": 30})
        s.set_attrs(1, {"age": 31, "city": "nyc"})
        assert s.attrs(1) == {"name": "alice", "age": 31, "city": "nyc"}
        s.set_attrs(1, {"city": None})  # None deletes the key
        assert s.attrs(1) == {"name": "alice", "age": 31}
        assert s.attrs(999) == {}
        s.set_attrs(2, {"x": 1})
        s.set_attrs(2, {"x": None})  # emptied id disappears entirely
        assert s.ids() == [1]
        s.close()

    def test_durability_across_reopen(self, tmp_path):
        p = str(tmp_path / "a.db")
        s = AttrStore(p)
        s.set_bulk_attrs({i: {"v": i * 2} for i in range(500)})
        s.close()
        s2 = AttrStore(p)
        assert s2.attrs(250) == {"v": 500}
        assert len(s2.ids()) == 500
        s2.close()

    def test_block_checksums_and_diff(self, tmp_path):
        a = AttrStore(str(tmp_path / "a.db"))
        b = AttrStore(str(tmp_path / "b.db"))
        for s in (a, b):
            s.set_bulk_attrs({i: {"v": i} for i in range(250)})
        assert AttrStore.diff_blocks(a.blocks(), b.blocks()) == []
        b.set_attrs(150, {"v": -1})  # diverge block 1
        diff = AttrStore.diff_blocks(a.blocks(), b.blocks())
        assert diff == [150 // ATTR_BLOCK_SIZE]
        assert b.block_data(1)[150] == {"v": -1}
        a.close()
        b.close()


class TestBoundedMemory:
    def test_attrs_exceed_cache_stay_on_disk(self, tmp_path):
        """attrs >> cache: residency is the LRU cap, correctness is the
        B-tree (the boltdb contract the round-3 dict store broke)."""
        s = AttrStore(str(tmp_path / "a.db"), cache_size=64)
        n = 5000
        s.set_bulk_attrs({i: {"p": f"payload-{i}"} for i in range(n)})
        assert s.cache_len() <= 64
        # random access far beyond the cache still answers from disk
        for probe in (0, 63, 64, 1234, 4999):
            assert s.attrs(probe) == {"p": f"payload-{probe}"}
        assert s.cache_len() <= 64
        # block checksums stream without inflating the cache
        blocks = s.blocks()
        assert len(blocks) == n // ATTR_BLOCK_SIZE
        assert s.cache_len() <= 64
        s.close()

    def test_memory_contract_and_identity_under_eviction(self, tmp_path):
        """The enforcement version of 'bounded memory' (VERDICT r4 #6,
        mirroring the translate store's <50 B/key contract): attrs >>
        cache must keep Python-heap residency at the LRU cap — an
        explicit bytes assertion, independent of N — while attr-filtered
        TopN and the anti-entropy attr diff stay bit-identical to an
        eviction-free store."""
        from pilosa_tpu import SHARD_WIDTH
        from pilosa_tpu.core import Holder
        from pilosa_tpu.executor import Executor

        n = 30_000
        payload = {i: {"cat": "hot" if i % 7 == 0 else f"c{i % 50}"} for i in range(n)}

        small = AttrStore(str(tmp_path / "small.db"), cache_size=128)
        big = AttrStore(str(tmp_path / "big.db"), cache_size=n * 2)
        small.set_bulk_attrs(payload)
        big.set_bulk_attrs(payload)

        # explicit bytes-resident assertion: the LRU holds <= 128
        # entries of ~tens of bytes each — far below 128 KiB — no
        # matter that 30k attrs live on disk
        assert small.cache_len() <= 128
        assert small.resident_bytes() < (1 << 17), small.resident_bytes()

        # random reads far beyond the cache answer from the B-tree and
        # never grow residency
        for probe in (0, 127, 128, 12345, n - 1):
            assert small.attrs(probe) == payload[probe]
        assert small.resident_bytes() < (1 << 17)

        # anti-entropy attr diff: block checksums computed under
        # eviction pressure must equal the eviction-free store's
        assert small.blocks() == big.blocks()
        assert small.resident_bytes() < (1 << 17)

        # attr-filtered TopN (reference fragment.go:922-934) must be
        # bit-identical whether or not the filter walk evicts
        h = Holder()
        h.open()
        f = h.create_index("i").create_field("f", None)
        rng_rows = range(0, 4000)
        for r in rng_rows:
            f.set_bit(r, (r * 131) % SHARD_WIDTH)
            f.set_bit(r, (r * 131 + 1) % SHARD_WIDTH)
        for frag in f.view("standard").fragments.values():
            # the rank cache debounces invalidation for 10 s (reference
            # cache.go:233-241); force the post-write recalculate
            frag.cache.recalculate()
        ex = Executor(h, device_policy="never")
        q = 'TopN(f, n=20, attrName="cat", attrValues=["hot"])'
        results = {}
        for name, store in (("small", small), ("big", big)):
            f.row_attr_store = store
            for frag in f.view("standard").fragments.values():
                frag.row_attr_store = store
            results[name] = ex.execute("i", q)
        assert results["small"] == results["big"]
        assert len(results["small"][0]) == 20  # the filter actually selected
        assert small.resident_bytes() < (1 << 17)
        small.close()
        big.close()


class TestMigration:
    def test_jsonl_log_upgrades_in_place(self, tmp_path):
        p = str(tmp_path / "a.attrs")
        with open(p, "w") as f:
            f.write(json.dumps({"id": 1, "attrs": {"name": "alice"}}) + "\n")
            f.write(json.dumps({"id": 1, "attrs": {"age": 30}}) + "\n")
            f.write(json.dumps({"id": 2, "attrs": {"x": 1}}) + "\n")
            f.write(json.dumps({"id": 2, "attrs": {"x": None}}) + "\n")
        s = AttrStore(p)
        assert s.attrs(1) == {"name": "alice", "age": 30}
        assert s.ids() == [1]  # id 2 was emptied by the None delete
        s.close()
        with open(p, "rb") as f:
            assert f.read(16) == b"SQLite format 3\x00"

    def test_digest_stability_across_store_generations(self, tmp_path):
        """The block digest hashes sorted-keys JSON: a migrated store
        and a fresh store with the same attrs must agree, or the first
        anti-entropy sweep after an upgrade would re-ship every block."""
        p = str(tmp_path / "old.attrs")
        with open(p, "w") as f:
            f.write(json.dumps({"id": 7, "attrs": {"b": 2, "a": 1}}) + "\n")
        migrated = AttrStore(p)
        fresh = AttrStore(str(tmp_path / "new.db"))
        fresh.set_attrs(7, {"a": 1, "b": 2})
        assert migrated.blocks() == fresh.blocks()
        migrated.close()
        fresh.close()
