"""Storage-tree tests (fragment/view/field/index/holder) — mirrors the
scenarios of the reference's fragment_internal_test.go / field_internal_test.go."""

import os
from datetime import datetime

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import (
    Field,
    FieldOptions,
    Fragment,
    Holder,
    Row,
    TopOptions,
    VIEW_STANDARD,
)
from pilosa_tpu.core.field import FIELD_TYPE_INT, FIELD_TYPE_TIME


def mem_fragment(shard=0, **kw):
    f = Fragment(None, "i", "f", VIEW_STANDARD, shard, **kw)
    f.open()
    return f


class TestFragment:
    def test_set_clear_bit(self):
        f = mem_fragment()
        assert f.set_bit(120, 1)
        assert f.set_bit(120, 6)
        assert not f.set_bit(120, 1)
        assert f.row(120).columns().tolist() == [1, 6]
        assert f.clear_bit(120, 1)
        assert not f.clear_bit(120, 1)
        assert f.row(120).columns().tolist() == [6]
        assert f.bit(120, 6) and not f.bit(120, 1)

    def test_shard_offset_rows(self):
        f = mem_fragment(shard=2)
        col = 2 * SHARD_WIDTH + 7
        assert f.set_bit(5, col)
        assert f.row(5).columns().tolist() == [col]
        with pytest.raises(ValueError):
            f.set_bit(5, 3)  # column outside shard

    def test_max_row_id(self):
        f = mem_fragment()
        f.set_bit(100, 0)
        f.set_bit(3, 1)
        assert f.max_row_id == 100

    def test_value_roundtrip(self):
        f = mem_fragment()
        assert f.set_value(100, 16, 3829)
        assert f.value(100, 16) == (3829, True)
        assert f.value(101, 16) == (0, False)
        # overwrite
        f.set_value(100, 16, 121)
        assert f.value(100, 16) == (121, True)

    def test_sum_min_max(self):
        f = mem_fragment()
        vals = {10: 7, 20: 3, 30: 9, 40: 9, 50: 0}
        for col, v in vals.items():
            f.set_value(col, 8, v)
        s, c = f.sum(None, 8)
        assert (s, c) == (sum(vals.values()), len(vals))
        mn, cn = f.min(None, 8)
        assert (mn, cn) == (0, 1)
        mx, cx = f.max(None, 8)
        assert (mx, cx) == (9, 2)
        filt = Row(10, 20, 30)
        s, c = f.sum(filt, 8)
        assert (s, c) == (19, 3)
        mn, cn = f.min(filt, 8)
        assert (mn, cn) == (3, 1)
        mx, cx = f.max(filt, 8)
        assert (mx, cx) == (9, 1)

    @pytest.mark.parametrize("op,pred,want", [
        ("==", 7, {10}),
        ("!=", 9, {10, 20, 50}),
        ("<", 9, {10, 20, 50}),
        ("<=", 9, {10, 20, 30, 40, 50}),
        (">", 3, {10, 30, 40}),
        (">=", 7, {10, 30, 40}),
    ])
    def test_range_ops(self, op, pred, want):
        f = mem_fragment()
        for col, v in {10: 7, 20: 3, 30: 9, 40: 9, 50: 0}.items():
            f.set_value(col, 8, v)
        got = set(f.range_op(op, 8, pred).columns().tolist())
        assert got == want

    def test_range_between(self):
        f = mem_fragment()
        for col, v in {10: 7, 20: 3, 30: 9, 40: 9, 50: 0}.items():
            f.set_value(col, 8, v)
        assert set(f.range_between(8, 3, 7).columns().tolist()) == {10, 20}
        assert set(f.range_between(8, 0, 9).columns().tolist()) == {10, 20, 30, 40, 50}

    def test_top_basic(self):
        f = mem_fragment()
        for col in range(10):
            f.set_bit(1, col)
        for col in range(5):
            f.set_bit(2, col)
        for col in range(8):
            f.set_bit(3, col)
        f.cache.recalculate()
        top = f.top(TopOptions(n=2))
        assert top == [(1, 10), (3, 8)]

    def test_top_with_src(self):
        f = mem_fragment()
        for col in range(10):
            f.set_bit(1, col)
        for col in range(0, 10, 2):
            f.set_bit(2, col)
        for col in range(3):
            f.set_bit(3, col)
        f.cache.recalculate()
        src = f.row(2)  # cols 0,2,4,6,8
        top = f.top(TopOptions(n=3, src=src))
        assert top[0] == (1, 5) or top[0] == (2, 5)
        got = dict(top)
        assert got[1] == 5 and got[2] == 5 and got[3] == 2

    def test_top_row_ids(self):
        f = mem_fragment()
        for col in range(10):
            f.set_bit(1, col)
        for col in range(5):
            f.set_bit(2, col)
        f.cache.recalculate()
        top = f.top(TopOptions(n=1, row_ids=[2]))
        assert top == [(2, 5)]

    def test_bulk_import(self, tmp_path):
        f = Fragment(str(tmp_path / "frag"), "i", "f", VIEW_STANDARD, 0)
        f.open()
        rows = [0, 0, 1, 2, 2, 2]
        cols = [1, 5, 1, 0, 1, 2]
        f.bulk_import(rows, cols)
        assert f.row(0).columns().tolist() == [1, 5]
        assert f.row(2).columns().tolist() == [0, 1, 2]
        # snapshot persisted: reopen and verify
        f.close()
        f2 = Fragment(str(tmp_path / "frag"), "i", "f", VIEW_STANDARD, 0)
        f2.open()
        assert f2.row(2).columns().tolist() == [0, 1, 2]

    def test_persistence_oplog_and_snapshot(self, tmp_path):
        p = str(tmp_path / "frag")
        f = Fragment(p, "i", "f", VIEW_STANDARD, 0)
        f.open()
        f.set_bit(1, 10)
        f.set_bit(1, 20)
        f.clear_bit(1, 10)
        f.close()
        # ops are in the file tail; reopen replays them
        f2 = Fragment(p, "i", "f", VIEW_STANDARD, 0)
        f2.open()
        assert f2.row(1).columns().tolist() == [20]
        # force snapshot, then more ops
        f2.snapshot()
        f2.set_bit(2, 30)
        f2.close()
        f3 = Fragment(p, "i", "f", VIEW_STANDARD, 0)
        f3.open()
        assert f3.row(1).columns().tolist() == [20]
        assert f3.row(2).columns().tolist() == [30]

    def test_snapshot_trigger_on_max_opn(self, tmp_path):
        p = str(tmp_path / "frag")
        f = Fragment(p, "i", "f", VIEW_STANDARD, 0)
        f.open()
        f.max_op_n = 10
        for i in range(25):
            f.set_bit(0, i)
        assert f.op_n <= 10
        f.close()
        f2 = Fragment(p, "i", "f", VIEW_STANDARD, 0)
        f2.open()
        assert f2.row(0).count() == 25

    def test_blocks_checksums(self):
        f = mem_fragment()
        f.set_bit(0, 1)
        f.set_bit(100, 1)
        f.set_bit(250, 1)
        blocks = dict(f.blocks())
        assert set(blocks) == {0, 1, 2}
        g = mem_fragment()
        g.set_bit(0, 1)
        g.set_bit(100, 1)
        g.set_bit(250, 2)
        gb = dict(g.blocks())
        assert gb[0] == blocks[0] and gb[1] == blocks[1] and gb[2] != blocks[2]

    def test_block_data(self):
        f = mem_fragment()
        f.set_bit(0, 5)
        f.set_bit(150, 7)
        rows, cols = f.block_data(1)
        assert rows.tolist() == [150] and cols.tolist() == [7]

    def test_packed_export(self):
        f = mem_fragment(shard=1)
        base = SHARD_WIDTH
        f.set_bit(3, base + 0)
        f.set_bit(3, base + 64)
        f.set_bit(7, base + 100)
        ids, mat = f.row_matrix()
        assert ids == [3, 7]
        assert int(np.bitwise_count(mat[0]).sum()) == 2
        assert (int(mat[0][0]) & 1) == 1
        assert (int(mat[0][1]) & 1) == 1
        assert int(np.bitwise_count(mat[1]).sum()) == 1


class TestField:
    def test_set_field_time_views(self, tmp_path):
        f = Field(
            str(tmp_path / "f"),
            "i",
            "f",
            FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMD"),
        )
        f.open()
        t = datetime(2018, 2, 3)
        assert f.set_bit(1, 100, t)
        assert sorted(f.views) == [
            "standard",
            "standard_2018",
            "standard_201802",
            "standard_20180203",
        ]
        assert f.row(1).columns().tolist() == [100]
        # hierarchical clear
        assert f.clear_bit(1, 100)
        for v in f.views.values():
            assert v.row(1).count() == 0

    def test_time_range_walker_survives_month_boundary_days(self):
        """Go AddDate normalization (Jan 29 + 1 month = Mar 1): the
        range walker probes month/year boundaries from mid-walk days,
        so day >= 29 starts used to crash with 'day is out of range'."""
        from pilosa_tpu.core.timequantum import views_by_time_range

        for start, end in [
            (datetime(2019, 1, 29), datetime(2019, 3, 2)),
            (datetime(2019, 1, 31), datetime(2019, 4, 1)),
            (datetime(2020, 2, 29), datetime(2021, 3, 1)),  # leap day
            (datetime(2019, 12, 31, 23), datetime(2020, 1, 1, 2)),
        ]:
            views = views_by_time_range("standard", start, end, "YMDH")
            assert views, (start, end)
            assert len(views) == len(set(views))
        # leap-day start with a years-only quantum exercises the
        # down-walk's year step (Go AddDate(1,0,0) on Feb 29 -> Mar 1)
        views = views_by_time_range(
            "standard", datetime(2020, 2, 29), datetime(2023, 1, 1), "Y"
        )
        assert views == ["standard_2020", "standard_2021", "standard_2022"]

    def test_int_field_value(self, tmp_path):
        f = Field(
            str(tmp_path / "f"),
            "i",
            "f",
            FieldOptions(type=FIELD_TYPE_INT, min=-10, max=1000),
        )
        f.open()
        assert f.set_value(1, 500)
        assert f.value(1) == (500, True)
        assert f.set_value(2, -10)
        assert f.value(2) == (-10, True)
        assert f.value(3) == (0, False)
        with pytest.raises(ValueError):
            f.set_value(4, 2000)

    def test_import_bits_with_time(self, tmp_path):
        f = Field(
            str(tmp_path / "f"),
            "i",
            "f",
            FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YM"),
        )
        f.open()
        f.import_bits(
            [1, 1, 2],
            [10, SHARD_WIDTH + 3, 20],
            [datetime(2018, 1, 1), datetime(2018, 2, 1), None],
        )
        assert f.row(1).columns().tolist() == [10, SHARD_WIDTH + 3]
        assert f.view("standard_201801").row(1).columns().tolist() == [10]
        assert f.view("standard_201802").row(1).columns().tolist() == [SHARD_WIDTH + 3]
        assert f.available_shards() == [0, 1]

    def test_import_values(self, tmp_path):
        f = Field(
            str(tmp_path / "f"), "i", "f",
            FieldOptions(type=FIELD_TYPE_INT, min=0, max=100),
        )
        f.open()
        f.import_values([1, 2, SHARD_WIDTH + 1], [10, 20, 30])
        assert f.value(1) == (10, True)
        assert f.value(2) == (20, True)
        assert f.value(SHARD_WIDTH + 1) == (30, True)


class TestHolder:
    def test_create_and_reopen(self, tmp_path):
        h = Holder(str(tmp_path / "data"))
        h.open()
        idx = h.create_index("myidx")
        fld = idx.create_field("myfield")
        fld.set_bit(1, 100)
        fld.set_bit(1, SHARD_WIDTH * 3 + 5)
        assert idx.max_shard() == 3
        h.close()

        h2 = Holder(str(tmp_path / "data"))
        h2.open()
        idx2 = h2.index("myidx")
        assert idx2 is not None
        f2 = idx2.field("myfield")
        assert f2.row(1).columns().tolist() == [100, SHARD_WIDTH * 3 + 5]
        assert idx2.max_shard() == 3

    def test_schema_apply(self, tmp_path):
        h = Holder(str(tmp_path / "a"))
        h.open()
        idx = h.create_index("i1")
        idx.create_field("f1", FieldOptions(type=FIELD_TYPE_INT, min=0, max=10))
        schema = h.schema()

        h2 = Holder(str(tmp_path / "b"))
        h2.open()
        h2.apply_schema(schema)
        assert h2.index("i1").field("f1").options.type == FIELD_TYPE_INT
        assert h2.schema() == schema

    def test_node_id_persists(self, tmp_path):
        h = Holder(str(tmp_path / "data"))
        h.open()
        nid = h.load_node_id()
        assert h.load_node_id() == nid
        h2 = Holder(str(tmp_path / "data"))
        assert h2.load_node_id() == nid

    def test_delete(self, tmp_path):
        h = Holder(str(tmp_path / "data"))
        h.open()
        idx = h.create_index("i1")
        idx.create_field("f1")
        h.delete_index("i1")
        assert h.index("i1") is None
        assert not os.path.exists(os.path.join(str(tmp_path / "data"), "i1"))
