"""Workload heat ledger (ISSUE 16): per-(index, field, shard) counter
and EWMA accounting, skew statistics, fleet merge, the /debug/heat
surface, and the CI-gated <=5% overhead contract for the executor read
hook.

Server-level pieces run against a real in-process server on :0 under
JAX_PLATFORMS=cpu (the tier-1 environment)."""

import json
import time
import urllib.request

import pytest

from pilosa_tpu.server import Config, Server
from pilosa_tpu.utils import heat


@pytest.fixture(autouse=True)
def _clean_ledger():
    heat.LEDGER.clear()
    heat.LEDGER.configure(True, 300.0)
    yield
    heat.LEDGER.clear()
    heat.LEDGER.configure(True, 300.0)


@pytest.fixture()
def server(tmp_path):
    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="expvar",
        device_policy="always",
        device_timeout=0,
    )
    s = Server(cfg)
    s.open()
    yield s
    s.close()


def req(server, method, path, body=None, raw=False):
    url = server.uri + path
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(r) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload or b"{}")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, payload if raw else json.loads(payload or b"{}")


# -- ledger accounting --------------------------------------------------------


def test_counters_are_exact_integers():
    led = heat.HeatLedger()
    led.record_read("i", "f", 0, n=3)
    led.record_read("i", "f", 0)
    led.record_write("i", "f", 1, 7)
    led.record_stage("i", "f", 0, 4096, hit=False)
    led.record_stage("i", "f", 0, 0, hit=True)
    led.record_wave("i", "", 0, n=2)
    snap = led.snapshot()
    by = {(c["field"], c["shard"]): c for c in snap["cells"]}
    assert by[("f", 0)]["reads"] == 4
    assert by[("f", 1)]["writes"] == 7
    assert by[("f", 0)]["bytes_staged"] == 4096
    assert by[("f", 0)]["stager_misses"] == 1
    assert by[("f", 0)]["stager_hits"] == 1
    assert by[("", 0)]["waves"] == 2


def test_staging_does_not_move_the_ewma_score():
    led = heat.HeatLedger()
    led.record_stage("i", "f", 0, 1 << 20, hit=False)
    led.record_wave("i", "f", 0)
    (cell,) = led.snapshot()["cells"]
    assert cell["heat"] == 0.0
    led.record_read("i", "f", 0)
    (cell,) = led.snapshot()["cells"]
    assert cell["heat"] > 0.0


def test_ewma_half_life_decay():
    led = heat.HeatLedger(halflife=10.0)
    led.record_read("i", "f", 0)
    # rewind the cell's clock one half-life: the snapshot-time decay
    # must halve the score without anyone touching the cell
    cell = led._cells[("i", "f", 0)]
    cell[1] -= 10.0
    (c,) = led.snapshot()["cells"]
    assert 0.45 < c["heat"] < 0.55
    # the next touch decays first, then adds its weight
    led.record_read("i", "f", 0)
    (c,) = led.snapshot()["cells"]
    assert 1.4 < c["heat"] < 1.6


def test_disabled_ledger_records_nothing():
    led = heat.HeatLedger()
    led.configure(False, 300.0)
    led.record_read("i", "f", 0)
    led.record_write("i", "f", 0, 5)
    led.record_stage("i", "f", 0, 100, hit=False)
    led.record_wave("i", "f", 0)
    assert led.snapshot()["cells"] == []
    assert led.snapshot()["enabled"] is False


def test_snapshot_index_filter_and_unknown_dim():
    led = heat.HeatLedger()
    led.record_read("a", "f", 0)
    led.record_read("b", "f", 0)
    snap = led.snapshot(index="a")
    assert [c["index"] for c in snap["cells"]] == ["a"]
    with pytest.raises(ValueError):
        led.snapshot(dim="bogus")


# -- skew statistics ----------------------------------------------------------


def test_skew_oracle_exact_on_raw_counters():
    led = heat.HeatLedger()
    led.record_read("i", "f", 0, n=3)
    led.record_read("i", "f", 1, n=1)
    skew = led.snapshot(dim="reads")["skew"]
    assert skew["shards"] == 2
    assert skew["top"][0] == {"index": "i", "shard": 0, "reads": 3}
    assert skew["top"][1] == {"index": "i", "shard": 1, "reads": 1}
    # max / mean = 3 / 2 exactly
    assert skew["imbalance_ratio"] == 1.5


def test_skew_empty_and_top_k():
    assert heat.compute_skew([], dim="reads") == {
        "shards": 0,
        "top": [],
        "imbalance_ratio": 1.0,
    }
    cells = [
        {"index": "i", "field": "f", "shard": s, "reads": s + 1} for s in range(5)
    ]
    skew = heat.compute_skew(cells, dim="reads", top_k=2)
    assert skew["shards"] == 5 and len(skew["top"]) == 2
    assert skew["top"][0]["shard"] == 4
    with pytest.raises(ValueError):
        heat.compute_skew(cells, dim="bogus")


def test_skew_aggregates_fields_of_one_shard():
    """Cells are per-(index, field, shard); skew is per-(index, shard) —
    two fields of one shard pool their load."""
    led = heat.HeatLedger()
    led.record_read("i", "f", 0, n=2)
    led.record_read("i", "g", 0, n=2)
    led.record_read("i", "f", 1, n=1)
    skew = led.snapshot(dim="reads")["skew"]
    assert skew["top"][0] == {"index": "i", "shard": 0, "reads": 4}


def test_merge_fleet_sums_instances():
    a = heat.HeatLedger()
    a.record_write("i", "f", 0, 4)
    b = heat.HeatLedger()
    b.record_write("i", "f", 0, 4)
    b.record_write("i", "f", 1, 2)
    merged = heat.merge_fleet(
        [("rank0", a.snapshot()), ("rank1", b.snapshot())], dim="writes"
    )
    assert [i["instance"] for i in merged["instances"]] == ["rank0", "rank1"]
    skew = merged["skew"]
    assert skew["top"][0] == {"index": "i", "shard": 0, "writes": 8}
    assert skew["top"][1] == {"index": "i", "shard": 1, "writes": 2}
    assert skew["imbalance_ratio"] == 1.6


# -- server surface -----------------------------------------------------------


def test_debug_heat_records_reads_writes_and_staging(server):
    req(server, "POST", "/index/ht", {})
    req(server, "POST", "/index/ht/field/f", {})
    req(server, "POST", "/index/ht/query", b"Set(1, f=1)")
    # cache=false so the plan cache can't short-circuit the map legs
    for _ in range(2):
        st, body = req(server, "POST", "/index/ht/query?cache=false", b"Count(Row(f=1))")
        assert st == 200 and body["results"] == [1]
    st, snap = req(server, "GET", "/debug/heat?index=ht")
    assert st == 200 and snap["enabled"] is True
    shard0 = [c for c in snap["cells"] if c["shard"] == 0]
    assert sum(c["reads"] for c in shard0) >= 2
    assert sum(c["writes"] for c in shard0) >= 1
    # device_policy=always: the first read staged the fragment (miss +
    # bytes), the second hit the stager
    assert sum(c["stager_misses"] for c in shard0) >= 1
    assert sum(c["stager_hits"] for c in shard0) >= 1
    assert sum(c["bytes_staged"] for c in shard0) > 0
    assert snap["skew"]["shards"] >= 1


def test_debug_heat_validates_dim_and_top(server):
    st, body = req(server, "GET", "/debug/heat?dim=bogus")
    assert st == 400
    st, body = req(server, "GET", "/debug/heat?top=x")
    assert st == 400


def test_debug_heat_fleet_merges_local_instance(server):
    req(server, "POST", "/index/hf", {})
    req(server, "POST", "/index/hf/field/f", {})
    req(server, "POST", "/index/hf/query", b"Set(1, f=1)")
    st, merged = req(server, "GET", "/debug/heat?fleet=true&dim=writes&index=hf")
    assert st == 200 and merged["fleet"] is True
    assert [i["instance"] for i in merged["instances"]] == [server.uri]
    assert all(c["index"] == "hf" for c in merged["cells"])
    assert merged["skew"]["top"][0]["index"] == "hf"


# -- docs drift guard ---------------------------------------------------------


def test_docs_document_observability_knobs_with_current_defaults():
    """docs/configuration.md names every heat/journal/export knob with
    the default the code actually uses, and docs/administration.md
    keeps the §Workload heat & durable journal section — both
    directions of drift (the test_fusion.py knob-sync idiom)."""
    import os

    cfg = Config(data_dir="x")
    root = os.path.join(os.path.dirname(__file__), "..", "docs")
    with open(os.path.join(root, "configuration.md")) as f:
        conf = f.read()
    for knob, default in (
        ("heat-enabled", "true" if cfg.heat_enabled else "false"),
        ("heat-decay-halflife", str(cfg.heat_decay_halflife)),
        ("journal-dir", f"`{cfg.journal_dir or chr(34) * 2}`"),
        ("journal-max-bytes", str(cfg.journal_max_bytes)),
        ("export-path", f"`{cfg.export_path or chr(34) * 2}`"),
        ("export-url", f"`{cfg.export_url or chr(34) * 2}`"),
        ("export-interval", str(cfg.export_interval)),
        ("export-queue", str(cfg.export_queue)),
    ):
        assert f"| `{knob}` | {default} |" in conf, knob
    with open(os.path.join(root, "administration.md")) as f:
        admin = f.read()
    assert "### Workload heat & durable journal" in admin
    assert "/debug/heat" in admin and "/debug/bundle" in admin
    assert "debug-bundle" in admin and "events --follow" in admin


# -- overhead gate ------------------------------------------------------------


@pytest.mark.slow
def test_heat_overhead_gate(tmp_path):
    """Executor micro with the heat ledger enabled stays within 5% of
    disabled (interleaved rounds, min-of-rounds — the ISSUE 12
    attribution-gate harness; the CI observability step runs this
    explicitly, it is excluded from tier-1 as timing-sensitive)."""
    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="expvar",
        device_policy="always",
        device_timeout=0,
    )
    s = Server(cfg)
    s.open()
    try:
        s.api.create_index("ov")
        s.api.create_field("ov", "f", {})
        s.api.query("ov", "Set(1, f=1)")
        for _ in range(20):
            s.api.query("ov", "Count(Row(f=1))")  # warm

        def round_(hot: bool, iters=60) -> float:
            heat.LEDGER.enabled = hot
            t0 = time.perf_counter()
            for _ in range(iters):
                s.executor.execute("ov", "Count(Row(f=1))")
            return time.perf_counter() - t0

        # interleave disabled/enabled rounds so a transient load spike
        # hits both sides, and take the min of each — scheduling noise
        # is strictly additive, so min is the honest per-iteration cost.
        # CI runners are still noisy, so best of up to 3 attempts.
        overhead = float("inf")
        for _ in range(3):
            base = instrumented = float("inf")
            for _ in range(9):
                base = min(base, round_(hot=False))
                instrumented = min(instrumented, round_(hot=True))
            overhead = min(overhead, instrumented / base - 1.0)
            if overhead < 0.05:
                break
        assert overhead < 0.05, f"heat accounting overhead {overhead:.1%} >= 5%"
    finally:
        heat.LEDGER.enabled = True
        s.close()
