"""End-to-end data integrity (ISSUE 15): the checksummed snapshot
format (blake2b digest trailer), integrity fault injection
(corrupt_at / bitrot / snapshot_kill), open-time and scrub-time
verification with quarantine's clean-503 contract, SIGKILL-mid-snapshot
atomicity, the offline `check` data-file mode, verify-before-apply
fragment transfer, and holder backup/restore.

The property under test everywhere: corruption is DETECTED before it is
SERVED — a rotted fragment answers 503 (never garbage) until repair
replaces it with a verified replica copy, and a tampered archive is
refused before a single byte is applied.
"""

import io
import os
import random
import subprocess
import sys
import tarfile
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.core import fragment as fragment_mod
from pilosa_tpu.core.fragment import (
    Fragment,
    FragmentQuarantinedError,
    StorageFaultSpec,
)
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.roaring import bitmap as bm
from pilosa_tpu.server import ClusterConfig, Config, Server
from pilosa_tpu.utils import events, metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    fragment_mod.FAULTS = None
    yield
    fragment_mod.FAULTS = None


def _frag(path) -> Fragment:
    f = Fragment(str(path), "i", "f", VIEW_STANDARD, 0)
    f.open()
    return f


def _seed(f: Fragment) -> None:
    for r in range(4):
        for c in range(0, 400, 7):
            f.set_bit(r, (r * 31 + c) % 4096)


# -- checksummed snapshot format ---------------------------------------------


def test_snapshot_carries_digest_trailer_and_verifies(tmp_path):
    p = tmp_path / "frag"
    f = _frag(p)
    _seed(f)
    f.snapshot()
    f.close()
    data = open(p, "rb").read()
    end = bm.snapshot_base_end(data)
    assert bm.has_digest_trailer(data, end)
    assert bm.verify_digest_trailer(data, end)
    # any flipped base byte breaks verification
    rotted = bytearray(data)
    rotted[end // 2] ^= 0x01
    assert not bm.verify_digest_trailer(bytes(rotted), end)
    # a legacy file (base only, no trailer) has nothing to verify
    legacy = data[:end]
    assert not bm.has_digest_trailer(legacy, end)


def test_legacy_file_without_trailer_still_opens(tmp_path):
    p = tmp_path / "frag"
    f = _frag(p)
    _seed(f)
    f.snapshot()
    f.close()
    data = open(p, "rb").read()
    end = bm.snapshot_base_end(data)
    with open(p, "wb") as fh:  # strip the trailer: pre-PR-15 file
        fh.write(data[:end])
    f2 = _frag(p)
    assert not f2.quarantined
    assert f2.bit(0, 0)
    assert f2.verify_integrity() is None
    f2.close()


def test_ops_appended_after_trailer_replay(tmp_path):
    p = tmp_path / "frag"
    f = _frag(p)
    _seed(f)
    f.snapshot()
    f.set_bit(9, 4095)  # op-log record lands AFTER the trailer
    f.close()
    f2 = _frag(p)
    assert f2.bit(9, 4095) and f2.bit(0, 0)
    assert f2.verify_integrity(deep=True) is None
    f2.close()


# -- fault spec: integrity knobs ---------------------------------------------


def test_fault_spec_parses_integrity_knobs():
    s = StorageFaultSpec.parse("corrupt_at=12, bitrot=2, snapshot_kill=post")
    assert s.corrupt_at == 12 and s.bitrot == 2 and s.snapshot_kill == "post"
    assert bool(s)
    with pytest.raises(ValueError):
        # check: disable=fault-spec (deliberately invalid phase — the ValueError is the assertion)
        StorageFaultSpec.parse("snapshot_kill=sideways")


def test_bitrot_fires_every_nth_verification():
    s = StorageFaultSpec(bitrot=2)
    assert [s.bitrot_due() for _ in range(4)] == [False, True, False, True]


# -- corruption detection + quarantine ---------------------------------------


def test_corrupt_write_caught_at_open(tmp_path):
    """corrupt_at flips a byte between digest computation and the media
    — exactly what the trailer must catch at the next open."""
    p = tmp_path / "frag"
    f = _frag(p)
    _seed(f)
    fragment_mod.FAULTS = StorageFaultSpec(corrupt_at=10)
    f.snapshot()
    fragment_mod.FAULTS = None
    f.close()
    f2 = _frag(p)
    assert f2.quarantined
    assert f2.quarantine_reason == "snapshot digest mismatch at open"
    with pytest.raises(FragmentQuarantinedError) as ei:
        f2.check_serving()
    assert ei.value.status == 503 and ei.value.retry_after >= 1
    with pytest.raises(FragmentQuarantinedError):
        f2.set_bit(0, 1)  # writes are fenced too
    f2.close()


def test_bitrot_detected_by_scrub_and_sticky(tmp_path):
    p = tmp_path / "frag"
    f = _frag(p)
    _seed(f)
    f.snapshot()
    assert f.verify_integrity(deep=True) is None  # clean baseline
    fragment_mod.FAULTS = StorageFaultSpec(bitrot=1)
    reason = f.verify_integrity()
    assert reason == "snapshot digest mismatch"
    assert f.quarantined
    fragment_mod.FAULTS = None
    # quarantine is sticky: re-verifying reports, never un-quarantines
    assert f.verify_integrity() == reason
    f.close()


def test_deep_verify_catches_consistent_but_wrong_disk(tmp_path):
    """Rot that rewrites the base AND its trailer (valid digest over
    wrong bytes) passes the shallow check; only the deep blocks-vs-disk
    compare sees the live mmap (old inode) diverge from the file."""
    p = tmp_path / "frag"
    f = _frag(p)
    _seed(f)
    f.snapshot()
    f.close()
    f = _frag(p)  # mmap-backed: deep compare applies
    data = open(p, "rb").read()
    end = bm.snapshot_base_end(data)
    base = bytearray(data[:end])
    base[end - 1] ^= 0x01
    with open(str(p) + ".rot", "wb") as fh:
        fh.write(bytes(base) + bm.make_digest_trailer(bytes(base)))
    os.replace(str(p) + ".rot", p)
    assert f.verify_integrity(deep=False) is None  # digest says fine
    reason = f.verify_integrity(deep=True)
    assert reason in (
        "on-disk blocks diverge from memory",
        "snapshot base unparseable",
    )
    assert f.quarantined
    f.close()


def test_op_log_crc_walk_catches_garbage_tail(tmp_path):
    p = tmp_path / "frag"
    f = _frag(p)
    _seed(f)
    f.snapshot()
    f.set_bit(5, 99)
    with open(p, "ab") as fh:
        fh.write(b"\xde\xad\xbe\xef" * 4)
    reason = f.verify_integrity()
    assert reason is not None and reason.startswith("op log CRC mismatch")
    assert f.quarantined
    f.close()


# -- SIGKILL mid-snapshot: atomicity property --------------------------------

_KILL_CHILD = r"""
import os, random, sys
sys.path.insert(0, sys.argv[4])
from pilosa_tpu.core import fragment as fragment_mod
from pilosa_tpu.core.fragment import Fragment, StorageFaultSpec
from pilosa_tpu.core.view import VIEW_STANDARD

path, phase, seed = sys.argv[1], sys.argv[2], int(sys.argv[3])
rng = random.Random(seed)
bits = sorted({(rng.randrange(8), rng.randrange(5000)) for _ in range(300)})
f = Fragment(path, "i", "f", VIEW_STANDARD, 0)
f.open()
for r, c in bits[:150]:
    f.set_bit(r, c)
f.snapshot()  # durable base
for r, c in bits[150:]:
    f.set_bit(r, c)  # durable op-log tail
fragment_mod.FAULTS = StorageFaultSpec(snapshot_kill=phase)
f.snapshot()  # dies at the scheduled point
os._exit(3)  # unreachable: the kill point must fire
"""


@pytest.mark.parametrize("phase", ["pre", "post"])
@pytest.mark.parametrize("seed", [15, 16])
def test_sigkill_mid_snapshot_is_atomic(tmp_path, phase, seed):
    """Kill the process immediately before and immediately after the
    snapshot's atomic rename: either way the reopened fragment must be
    bit-identical to everything written (old base + op log, or the new
    base) — never a half-written file."""
    p = tmp_path / "frag"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_CHILD, str(p), phase, str(seed), REPO],
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
    )
    assert proc.returncode == 137, (proc.returncode, proc.stderr[-500:])
    rng = random.Random(seed)
    bits = sorted({(rng.randrange(8), rng.randrange(5000)) for _ in range(300)})
    f = _frag(p)
    assert not f.quarantined
    assert f.verify_integrity(deep=True) is None
    for r, c in bits:
        assert f.bit(r, c), f"lost bit ({r}, {c}) after {phase}-rename kill"
    f.close()


# -- offline `check` data-file mode ------------------------------------------


def _run_check(*argv):
    return subprocess.run(
        [sys.executable, "-m", "pilosa_tpu", "check", *argv],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
    )


def test_check_cli_clean_torn_repair_and_rot(tmp_path):
    p = tmp_path / "frag"
    f = _frag(p)
    _seed(f)
    f.snapshot()
    f.set_bit(5, 99)
    f.close()
    intact = os.path.getsize(p)

    assert _run_check(str(p)).returncode == 0

    with open(p, "ab") as fh:  # torn tail: non-zero exit, names --repair
        fh.write(b"\x01\x02\x03")
    r = _run_check(str(p))
    assert r.returncode == 1 and "--repair" in r.stdout + r.stderr

    r = _run_check("--repair", str(p))  # truncates the torn bytes
    assert r.returncode == 0, r.stdout + r.stderr
    assert os.path.getsize(p) == intact
    assert _run_check(str(p)).returncode == 0
    f2 = _frag(p)  # acked ops survive the repair
    assert f2.bit(5, 99) and f2.bit(0, 0)
    f2.close()

    data = bytearray(open(p, "rb").read())  # rotted base: fails, loudly
    data[bm.snapshot_base_end(bytes(data)) // 2] ^= 0x01
    open(p, "wb").write(bytes(data))
    r = _run_check(str(p))
    assert r.returncode == 1 and "digest mismatch" in r.stdout + r.stderr


# -- scrub / quarantine / repair over a live cluster -------------------------


def _flip_frag(server, index="i", field="f", shard=0):
    frag = server.holder.fragment(index, field, "standard", shard)
    with frag.mu:
        frag.snapshot()
    frag._flip_disk_byte(10)
    return frag


def test_scrub_quarantine_503_and_repair_from_replica(tmp_path):
    from tests.test_cluster import boot_static_cluster, req

    servers = boot_static_cluster(tmp_path, n=2, replicas=2)
    try:
        uri = servers[0].uri
        assert req(uri, "POST", "/index/i", {})[0] == 200
        assert req(uri, "POST", "/index/i/field/f", {})[0] == 200
        for col in range(0, 120, 3):
            st, _ = req(uri, "POST", "/index/i/query", f"Set({col}, f=7)".encode())
            assert st == 200
        for s in servers:
            frag = s.holder.fragment("i", "f", "standard", 0)
            with frag.mu:
                frag.snapshot()

        _flip_frag(servers[0])
        # detect-only sweep (repair suppressed): quarantines and stays
        st, body = req(uri, "POST", "/debug/scrub", {"repair": False})
        assert st == 200 and body["corrupt"] == 1 and body["repaired"] == 0
        frag = servers[0].holder.fragment("i", "f", "standard", 0)
        assert frag.quarantined

        # with a healthy replica the cluster keeps answering — and the
        # answer must be RIGHT (node 1's copy), never node 0's poison
        st, body = req(uri, "POST", "/index/i/query", b"Row(f=7)")
        if st == 200:
            assert body["results"][0]["columns"] == list(range(0, 120, 3))
        else:
            assert st == 503

        # /status surfaces the quarantine
        st, body = req(uri, "GET", "/status")
        q = body["integrity"]["quarantined"]
        assert q and q[0]["shard"] == 0 and "mismatch" in q[0]["reason"]

        # repairing sweep pulls the healthy replica copy from node 1
        st, body = req(uri, "POST", "/debug/scrub", {})
        assert st == 200 and body["repaired"] == 1, body
        frag = servers[0].holder.fragment("i", "f", "standard", 0)
        assert not frag.quarantined
        assert frag.verify_integrity(deep=True) is None
        st, body = req(uri, "POST", "/index/i/query", b"Row(f=7)")
        assert st == 200
        assert body["results"][0]["columns"] == list(range(0, 120, 3))

        # stats surface
        st, body = req(uri, "GET", "/debug/scrub")
        assert st == 200 and body["sweeps"] >= 2 and body["repairs"] >= 1
        assert body["unrecoverable"] == []
    finally:
        for s in servers:
            s.close()


def test_scrub_unrecoverable_without_healthy_replica(tmp_path):
    from tests.test_cluster import boot_static_cluster, req

    servers = boot_static_cluster(tmp_path, n=1, replicas=1)
    try:
        uri = servers[0].uri
        assert req(uri, "POST", "/index/i", {})[0] == 200
        assert req(uri, "POST", "/index/i/field/f", {})[0] == 200
        assert req(uri, "POST", "/index/i/query", b"Set(3, f=1)")[0] == 200
        _flip_frag(servers[0])
        seq0 = events.JOURNAL._seq
        st, body = req(uri, "POST", "/debug/scrub", {})
        assert st == 200 and body["unrecoverable"] == 1, body
        # no replica to fail over to: reads 503 + Retry-After, never
        # garbage (the quarantine's whole contract)
        r = urllib.request.Request(
            uri + "/index/i/query", data=b"Row(f=1)", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(r, timeout=30)
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        st, body = req(uri, "GET", "/status")
        unrec = body["integrity"]["unrecoverable"]
        assert unrec and unrec[0]["index"] == "i"
        assert events.snapshot(
            kind=events.SCRUB_UNRECOVERABLE, since_seq=seq0
        )
    finally:
        for s in servers:
            s.close()


# -- verify-before-apply fragment transfer -----------------------------------


def _tamper_tar_member(archive: bytes, prefix: str) -> bytes:
    """Flip a byte inside the payload of the first member whose name
    starts with ``prefix`` (a flip at an arbitrary offset can land in
    tar padding and change nothing)."""
    with tarfile.open(fileobj=io.BytesIO(archive)) as tr:
        off = next(
            m.offset_data
            for m in tr.getmembers()
            if m.name.startswith(prefix) and m.size > 0
        )
    bad = bytearray(archive)
    bad[off] ^= 0x01
    return bytes(bad)


def test_unmarshal_fragment_refuses_tampered_archive(tmp_path):
    from tests.test_cluster import boot_static_cluster, req

    servers = boot_static_cluster(tmp_path, n=1, replicas=1)
    try:
        uri = servers[0].uri
        assert req(uri, "POST", "/index/i", {})[0] == 200
        assert req(uri, "POST", "/index/i/field/f", {})[0] == 200
        assert req(uri, "POST", "/index/i/query", b"Set(8, f=2)")[0] == 200
        path = "/internal/fragment/data?index=i&field=f&view=standard&shard=0"
        st, archive = req(uri, "GET", path, raw=True)
        assert st == 200
        st, body = req(uri, "POST", path, _tamper_tar_member(archive, "data"))
        assert st == 400, body
        # the fragment is untouched: still serving the original bits
        st, body = req(uri, "POST", "/index/i/query", b"Row(f=2)")
        assert st == 200 and body["results"][0]["columns"] == [8]
        # the pristine archive still applies
        assert req(uri, "POST", path, archive)[0] == 200
    finally:
        for s in servers:
            s.close()


# -- holder backup / restore -------------------------------------------------


def test_backup_restore_roundtrip_and_tamper_refusal(tmp_path):
    from tests.test_cluster import boot_static_cluster, req

    servers = boot_static_cluster(tmp_path, n=1, replicas=1)
    try:
        uri = servers[0].uri
        assert req(uri, "POST", "/index/i", {})[0] == 200
        assert req(uri, "POST", "/index/i/field/f", {})[0] == 200
        cols = list(range(0, 90, 9))
        for c in cols:
            assert req(uri, "POST", "/index/i/query", f"Set({c}, f=4)".encode())[0] == 200

        st, archive = req(uri, "GET", "/backup", raw=True)
        assert st == 200
        with tarfile.open(fileobj=io.BytesIO(archive)) as tr:
            names = tr.getnames()
        assert names[0] == "MANIFEST.json"  # manifest leads the stream
        assert "schema.json" in names
        assert any(n.startswith("fragments/i/f/") for n in names)

        # tampered: refused with 400 + journal, nothing applied
        seq0 = events.JOURNAL._seq
        st, body = req(uri, "POST", "/restore", _tamper_tar_member(archive, "fragments/"))
        assert st == 400 and "restore refused" in body["error"], body
        assert events.snapshot(kind=events.RESTORE_REFUSED, since_seq=seq0)
        st, body = req(uri, "POST", "/index/i/query", b"Row(f=4)")
        assert body["results"][0]["columns"] == cols

        # wipe → restore: every bit comes back
        assert req(uri, "DELETE", "/index/i")[0] == 200
        st, body = req(uri, "POST", "/restore", archive)
        assert st == 200 and body["fragments"] >= 1, body
        st, body = req(uri, "POST", "/index/i/query", b"Row(f=4)")
        assert st == 200 and body["results"][0]["columns"] == cols
    finally:
        for s in servers:
            s.close()


def test_backup_restore_cli_roundtrip(tmp_path):
    from tests.test_cluster import boot_static_cluster, req

    servers = boot_static_cluster(tmp_path, n=1, replicas=1)
    try:
        uri = servers[0].uri
        host = servers[0].config.bind
        assert req(uri, "POST", "/index/i", {})[0] == 200
        assert req(uri, "POST", "/index/i/field/f", {})[0] == 200
        assert req(uri, "POST", "/index/i/query", b"Set(44, f=6)")[0] == 200

        out = str(tmp_path / "holder.tar")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "pilosa_tpu", "backup", "--host", host, "-o", out],
            capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
        )
        assert r.returncode == 0 and os.path.getsize(out) > 0, r.stderr

        bad = str(tmp_path / "tampered.tar")
        open(bad, "wb").write(
            _tamper_tar_member(open(out, "rb").read(), "fragments/")
        )
        r = subprocess.run(
            [sys.executable, "-m", "pilosa_tpu", "restore", "--host", host, bad],
            capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
        )
        assert r.returncode == 1 and "REFUSED" in r.stderr, (r.stdout, r.stderr)

        r = subprocess.run(
            [sys.executable, "-m", "pilosa_tpu", "restore", "--host", host, out],
            capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
        )
        assert r.returncode == 0, (r.stdout, r.stderr)
        st, body = req(uri, "POST", "/index/i/query", b"Row(f=6)")
        assert st == 200 and body["results"][0]["columns"] == [44]
    finally:
        for s in servers:
            s.close()


# -- anti-entropy failure accounting -----------------------------------------


def test_anti_entropy_error_counted_and_journaled(tmp_path):
    ports_mod = __import__("tests.test_cluster", fromlist=["free_ports"])
    port = ports_mod.free_ports(1)[0]
    host = f"127.0.0.1:{port}"
    cfg = Config(
        data_dir=str(tmp_path / "n0"),
        bind=host,
        device_policy="never",
        metric="expvar",
        anti_entropy_interval=0.05,
        cluster=ClusterConfig(
            disabled=False, coordinator=True, replicas=1, hosts=[host]
        ),
    )
    s = Server(cfg)
    s.open()
    try:
        seq0 = events.JOURNAL._seq

        def boom():
            raise RuntimeError("peer sync exploded")

        s.cluster.sync_holder = boom
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if events.snapshot(kind=events.ANTI_ENTROPY_ERROR, since_seq=seq0):
                break
            time.sleep(0.05)
        evs = events.snapshot(kind=events.ANTI_ENTROPY_ERROR, since_seq=seq0)
        assert evs and "exploded" in evs[-1]["error"]
        assert s._expvar._root.get(metrics.ANTI_ENTROPY_ERRORS, 0) >= 1
    finally:
        s.close()
