"""Randomized tri-path equivalence: every generated PQL query must
return bit-identical results on the CPU roaring path, the single-device
batched path, and the SPMD mesh path (reference executor_test.go pins
per-call cases; this sweeps the composition space those cases can't).

Query shapes are drawn from a bounded template set so XLA compiles a
small number of tree structures; row ids and predicates are traced
values and vary freely without recompiles (docs/architecture.md §7).
"""

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import FieldOptions, Holder
from pilosa_tpu.core.field import FIELD_TYPE_INT
from pilosa_tpu.executor import Executor
from pilosa_tpu.parallel.spmd import make_mesh

N_SHARDS = 3
N_ROWS = 24
VAL_MIN, VAL_MAX = -50, 500


@pytest.fixture(scope="module")
def loaded():
    rng = np.random.default_rng(20260730)
    h = Holder()
    h.open()
    idx = h.create_index("z")
    f = idx.create_field("f")
    g = idx.create_field("g")
    v = idx.create_field(
        "v", FieldOptions(type=FIELD_TYPE_INT, min=VAL_MIN, max=VAL_MAX)
    )
    rows, cols = [], []
    for r in range(N_ROWS):
        k = int(rng.integers(5, 400))
        rows += [r] * k
        cols += rng.integers(0, N_SHARDS * SHARD_WIDTH, size=k).tolist()
    f.import_bits(rows, cols)
    rows, cols = [], []
    for r in range(N_ROWS):
        k = int(rng.integers(1, 200))
        rows += [r] * k
        cols += rng.integers(0, N_SHARDS * SHARD_WIDTH, size=k).tolist()
    g.import_bits(rows, cols)
    vcols = rng.choice(N_SHARDS * SHARD_WIDTH, size=600, replace=False)
    vvals = rng.integers(VAL_MIN, VAL_MAX + 1, size=600)
    v.import_values(vcols.tolist(), vvals.tolist())
    return h


@pytest.fixture(scope="module")
def execs(loaded):
    cpu = Executor(loaded, device_policy="never")
    dev = Executor(loaded, device_policy="always")
    spmd = Executor(loaded, device_policy="always", mesh=make_mesh())
    return cpu, dev, spmd


def _normalize(results):
    out = []
    for r in results:
        out.append(sorted(r.columns()) if hasattr(r, "columns") else r)
    return out


def _gen_bitmap(rng, depth: int) -> str:
    """Random bitmap subtree from a bounded shape set."""
    if depth == 0 or rng.random() < 0.35:
        field = rng.choice(["f", "g"])
        return f"Row({field}={int(rng.integers(0, N_ROWS))})"
    op = rng.choice(["Intersect", "Union", "Difference", "Xor"])
    arity = int(rng.integers(2, 4))  # Difference/Xor are n-ary too
    kids = ", ".join(_gen_bitmap(rng, depth - 1) for _ in range(arity))
    return f"{op}({kids})"


def _gen_query(rng) -> str:
    kind = rng.choice(
        ["count", "bitmap", "topn", "sum", "minmax", "range", "range_count"]
    )
    if kind == "count":
        return f"Count({_gen_bitmap(rng, int(rng.integers(1, 3)))})"
    if kind == "bitmap":
        return _gen_bitmap(rng, int(rng.integers(1, 3)))
    if kind == "topn":
        field = rng.choice(["f", "g"])
        n = int(rng.integers(1, 8))
        src = _gen_bitmap(rng, 1)
        if rng.random() < 0.3:
            thr = int(rng.integers(1, 30))
            return f"TopN({field}, {src}, n={n}, threshold={thr})"
        return f"TopN({field}, {src}, n={n})"
    if kind == "sum":
        if rng.random() < 0.5:
            return f"Sum({_gen_bitmap(rng, 1)}, field=v)"
        return "Sum(field=v)"
    if kind == "minmax":
        call = rng.choice(["Min", "Max"])
        return f"{call}(field=v)"
    pred = int(rng.integers(VAL_MIN - 20, VAL_MAX + 20))
    op = rng.choice(["<", "<=", "==", "!=", ">", ">="])
    rq = f"Range(v {op} {pred})"
    if rng.random() < 0.2:
        lo = int(rng.integers(VAL_MIN, 0))
        hi = int(rng.integers(1, VAL_MAX))
        rq = f"Range(v >< [{lo}, {hi}])"
    return f"Count({rq})" if kind == "range_count" else rq


def test_tri_path_equivalence(execs):
    cpu, dev, spmd = execs
    rng = np.random.default_rng(7)
    mismatches = []
    for i in range(250):
        q = _gen_query(rng)
        want = _normalize(cpu.execute("z", q))
        for name, ex in (("device", dev), ("spmd", spmd)):
            got = _normalize(ex.execute("z", q))
            if got != want:
                mismatches.append((i, name, q, want, got))
    assert not mismatches, mismatches[:3]


def test_time_quantum_tri_path_equivalence():
    """Time-field ranges (per-quantum view unions) must agree across
    all three paths for random timestamps and random range windows."""
    from pilosa_tpu.core.field import FIELD_TYPE_TIME

    rng = np.random.default_rng(23)
    h = Holder()
    h.open()
    idx = h.create_index("t")
    f = idx.create_field(
        "ev", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMD")
    )
    cpu = Executor(h, device_policy="never")
    dev = Executor(h, device_policy="always")
    spmd = Executor(h, device_policy="always", mesh=make_mesh())
    days = [f"2019-{m:02d}-{d:02d}T{hh:02d}:00"
            for m in (1, 2, 3) for d in (1, 5, 14, 28) for hh in (0, 12)]
    for _ in range(300):
        row = int(rng.integers(0, 8))
        col = int(rng.integers(0, 2 * SHARD_WIDTH))
        ts = days[rng.integers(0, len(days))]
        cpu.execute("t", f"Set({col}, ev={row}, {ts})")
    windows = [
        ("2019-01-01T00:00", "2019-02-01T00:00"),
        ("2019-01-05T00:00", "2019-03-28T00:00"),
        ("2019-02-14T00:00", "2019-02-15T00:00"),
        ("2018-12-01T00:00", "2020-01-01T00:00"),
    ]
    for i in range(40):
        row = int(rng.integers(0, 8))
        lo, hi = windows[rng.integers(0, len(windows))]
        q = f"Count(Range(ev={row}, {lo}, {hi}))"
        want = _normalize(cpu.execute("t", q))
        assert _normalize(dev.execute("t", q)) == want, q
        assert _normalize(spmd.execute("t", q)) == want, q


def test_keyed_tri_path_equivalence():
    """String-keyed index: key translation happens once at the query
    boundary, so all three paths must agree through it too."""
    from pilosa_tpu.core import FieldOptions as FO
    from pilosa_tpu.utils.translate import TranslateStore

    rng = np.random.default_rng(17)
    h = Holder()
    h.open()
    idx = h.create_index("k", keys=True)
    idx.create_field("likes", FO(keys=True))
    ts = TranslateStore()
    cpu = Executor(h, device_policy="never", translate_store=ts)
    dev = Executor(h, device_policy="always", translate_store=ts)
    spmd = Executor(h, device_policy="always", mesh=make_mesh(), translate_store=ts)
    users = [f"user-{i}" for i in range(40)]
    things = [f"thing-{i}" for i in range(12)]
    for _ in range(400):
        u = users[rng.integers(0, len(users))]
        t = things[rng.integers(0, len(things))]
        cpu.execute("k", f'Set("{u}", likes="{t}")')
    def norm(results):
        out = []
        for r in results:
            out.append(sorted(r.keys) if hasattr(r, "keys") else r)
        return out
    for i in range(60):
        a = things[rng.integers(0, len(things))]
        b = things[rng.integers(0, len(things))]
        for q in (
            f'Count(Row(likes="{a}"))',
            f'Row(likes="{a}")',
            f'Count(Intersect(Row(likes="{a}"), Row(likes="{b}")))',
            f'Count(Union(Row(likes="{a}"), Row(likes="{b}")))',
            f'TopN(likes, Row(likes="{a}"), n=4)',
        ):
            want = norm(cpu.execute("k", q))
            assert norm(dev.execute("k", q)) == want, q
            assert norm(spmd.execute("k", q)) == want, q


def test_equivalence_after_mutations(execs):
    """Interleave writes with reads: staged state must track mutations
    (generation-keyed staging) on both device paths."""
    cpu, dev, spmd = execs
    rng = np.random.default_rng(11)
    for i in range(12):
        row = int(rng.integers(0, N_ROWS))
        col = int(rng.integers(0, N_SHARDS * SHARD_WIDTH))
        # write through ONE executor (shared holder), read through all
        cpu.execute("z", f"Set({col}, f={row})")
        q = f"Count(Intersect(Row(f={row}), Row(g={int(rng.integers(0, N_ROWS))})))"
        want = _normalize(cpu.execute("z", q))
        assert _normalize(dev.execute("z", q)) == want, q
        assert _normalize(spmd.execute("z", q)) == want, q
        cpu.execute("z", f"Clear({col}, f={row})")
        want2 = _normalize(cpu.execute("z", q))
        assert _normalize(dev.execute("z", q)) == want2, q
        assert _normalize(spmd.execute("z", q)) == want2, q
