"""Serving pipeline (ISSUE 2): bounded admission with 503 + Retry-After
sheds, deadline propagation/cancellation at stage boundaries,
singleflight coalescing, cross-request batching, graceful drain, and
the /debug/pipeline + metrics surface.

Server-level tests run a real in-process server on :0 under
JAX_PLATFORMS=cpu (the tier-1 environment)."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.server import Config, Server
from pilosa_tpu.server import deadline as dl_mod
from pilosa_tpu.server.deadline import Deadline, DeadlineExceeded
from pilosa_tpu.server.pipeline import Overloaded, QueryPipeline
from pilosa_tpu.utils import metrics


def req(server, method, path, body=None, headers=None, raw=False):
    url = server.uri + path
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(url, data=data, method=method, headers=headers or {})
    try:
        with urllib.request.urlopen(r) as resp:
            payload = resp.read()
            return (
                resp.status,
                payload if raw else json.loads(payload or b"{}"),
                dict(resp.headers),
            )
    except urllib.error.HTTPError as e:
        payload = e.read()
        return (
            e.code,
            payload if raw else json.loads(payload or b"{}"),
            dict(e.headers),
        )


def make_server(tmp_path, **cfg_kwargs):
    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        metric="expvar",
        device_policy="never",
        device_timeout=0,
        **cfg_kwargs,
    )
    s = Server(cfg)
    s.open()
    return s


def seed(server, index="pl", n_rows=4):
    st, _, _ = req(server, "POST", f"/index/{index}", {})
    assert st == 200
    st, _, _ = req(server, "POST", f"/index/{index}/field/f", {})
    assert st == 200
    rows, cols = [], []
    for r in range(n_rows):
        # row r gets r+1 bits in shard 0 and r+1 in shard 1 — distinct
        # per-row counts so combined-batch result splitting is provable
        for c in range(r + 1):
            rows.append(r)
            cols.append(c * 13 + r)
            rows.append(r)
            cols.append(SHARD_WIDTH + c * 17 + r)
    st, _, _ = req(
        server, "POST", f"/index/{index}/field/f/import",
        {"rowIDs": rows, "columnIDs": cols},
    )
    assert st == 200


# -- deadline unit behavior -------------------------------------------------


def test_deadline_from_request_parsing():
    assert dl_mod.from_request({}, {}) is None
    d = dl_mod.from_request({}, {"timeout": ["2.5"]})
    assert 2.0 < d.remaining() <= 2.5
    # header: absolute unix epoch seconds
    d = dl_mod.from_request({"x-request-deadline": str(time.time() + 5)}, {})
    assert 4.0 < d.remaining() <= 5.1
    # past header deadline admits but is already expired
    d = dl_mod.from_request({"x-request-deadline": str(time.time() - 5)}, {})
    assert d.expired()
    # configured default applies only when the client sent nothing
    d = dl_mod.from_request({}, {}, default_timeout=1.0)
    assert d is not None and 0.5 < d.remaining() <= 1.0
    # timeout param wins over header and default
    d = dl_mod.from_request(
        {"x-request-deadline": str(time.time() + 99)},
        {"timeout": ["1.0"]},
        default_timeout=50.0,
    )
    assert d.remaining() <= 1.0
    for bad in ({"timeout": ["abc"]}, {"timeout": ["-1"]}, {"timeout": ["inf"]}):
        with pytest.raises(ValueError):
            dl_mod.from_request({}, bad)
    with pytest.raises(ValueError):
        dl_mod.from_request({"x-request-deadline": "tomorrow"}, {})


def test_deadline_check_and_context():
    d = Deadline.after(60)
    d.check("anywhere")  # not expired: no raise
    expired = Deadline.after(-1)
    with pytest.raises(DeadlineExceeded):
        expired.check("stage")
    assert dl_mod.current() is None
    with dl_mod.activate(d):
        assert dl_mod.current() is d
        with dl_mod.activate(None):  # None activation is a no-op
            assert dl_mod.current() is d
    assert dl_mod.current() is None


# -- executor-level cancellation -------------------------------------------


def test_deadline_cancels_before_per_shard_map(tmp_path):
    s = make_server(tmp_path)
    try:
        seed(s, "exq")
        ex = s.executor

        # expired BEFORE the executor: zero call dispatch happens
        before = metrics.snapshot().get("executor.calls;call:Count", 0)
        with dl_mod.activate(Deadline.after(-1)):
            with pytest.raises(DeadlineExceeded):
                ex.execute("exq", "Count(Row(f=1))")
        assert metrics.snapshot().get("executor.calls;call:Count", 0) == before

        # expires MID-map: the second shard's work is cancelled at the
        # shard boundary instead of computed and discarded
        mapped = []
        orig = ex._bitmap_call_shard_cpu

        def slow_shard(index, c, shard):
            mapped.append(shard)
            time.sleep(0.08)
            return orig(index, c, shard)

        ex._bitmap_call_shard_cpu = slow_shard
        try:
            with dl_mod.activate(Deadline.after(0.04)):
                with pytest.raises(DeadlineExceeded):
                    ex.execute("exq", "Count(Row(f=1))")
        finally:
            ex._bitmap_call_shard_cpu = orig
        assert len(mapped) == 1, f"expected cancellation after shard 1, mapped {mapped}"
    finally:
        s.close()


# -- HTTP deadline surface --------------------------------------------------


def test_http_deadline_504_and_bad_values(tmp_path):
    s = make_server(tmp_path)
    try:
        seed(s)
        st, body, _ = req(
            s, "POST", "/index/pl/query?timeout=0.000001", b"Count(Row(f=1))"
        )
        assert st == 504 and "deadline" in body["error"]
        st, body, _ = req(
            s,
            "POST",
            "/index/pl/query",
            b"Count(Row(f=1))",
            headers={"X-Request-Deadline": str(time.time() - 10)},
        )
        assert st == 504
        st, body, _ = req(
            s, "POST", "/index/pl/query?timeout=banana", b"Count(Row(f=1))"
        )
        assert st == 400
        # an ample deadline answers normally
        st, body, _ = req(
            s, "POST", "/index/pl/query?timeout=30", b"Count(Row(f=1))"
        )
        assert st == 200 and body["results"] == [4]
    finally:
        s.close()


# -- overload shedding ------------------------------------------------------


def test_overload_sheds_503_with_retry_after(tmp_path):
    # queue-full is WHOLE-SERVER overload → 503 + Retry-After (the
    # internal client retries 503 against replicas); the per-tenant
    # throttle is the only 429 (tests/test_tenancy.py)
    s = make_server(
        tmp_path,
        pipeline_interactive_workers=2,
        pipeline_interactive_queue=2,
        pipeline_shed_retry_after=3.0,
    )
    try:
        seed(s, "ov")
        gate = threading.Event()
        orig = s.executor.execute

        def gated(index, query, shards=None, opt=None):
            gate.wait(10)
            return orig(index, query, shards, opt)

        s.executor.execute = gated
        results = []
        lock = threading.Lock()

        def client(i):
            # writes: never coalesced or batch-combined, so each one
            # occupies a real worker/queue slot
            st, body, hd = req(s, "POST", "/index/ov/query", f"Set({i}, f=9)".encode())
            with lock:
                results.append((st, hd))

        threads = [threading.Thread(target=client, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        # wait until the pipeline is saturated: 2 executing + 2 queued,
        # everyone else shed
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with lock:
                if len(results) >= 12:
                    break
            time.sleep(0.01)
        gate.set()
        for t in threads:
            t.join()
        s.executor.execute = orig
        codes = sorted(st for st, _ in results)
        assert codes.count(200) == 4, codes
        assert codes.count(503) == 12, codes
        shed_headers = [hd for st, hd in results if st == 503]
        assert all(hd.get("Retry-After") == "3" for hd in shed_headers)
        stats = s.pipeline.stats()
        assert stats["classes"]["interactive"]["sheds"] == 12
        # the registry carries the same counters for /metrics
        snap = metrics.snapshot()
        assert snap.get("pipeline.sheds;cls:interactive", 0) >= 12
    finally:
        s.close()


# -- singleflight coalescing ------------------------------------------------


def test_identical_concurrent_queries_coalesce(tmp_path):
    s = make_server(tmp_path)
    try:
        seed(s, "co")
        calls = []
        orig = s.executor.execute

        def slow(index, query, shards=None, opt=None):
            calls.append(1)
            time.sleep(0.25)
            return orig(index, query, shards, opt)

        s.executor.execute = slow
        results = []
        lock = threading.Lock()

        def client():
            st, body, _ = req(s, "POST", "/index/co/query", b"Count(Row(f=2))")
            with lock:
                results.append((st, body))

        threads = [threading.Thread(target=client) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        s.executor.execute = orig
        assert all(st == 200 and body == {"results": [6]} for st, body in results)
        hits = s.pipeline.stats()["coalesce_hits"]
        assert hits >= 1
        # every coalesced duplicate saved one execution
        assert len(calls) <= 8 - hits
    finally:
        s.close()


def test_permuted_argument_order_queries_coalesce(tmp_path):
    """Regression (ISSUE 4 satellite 1): singleflight used to key on
    raw PQL text, so Intersect(Row(a),Row(b)) vs Intersect(Row(b),
    Row(a)) never coalesced. Keys are now the canonical plan hash —
    permuted spellings of one query attach to one in-flight leader."""
    s = make_server(tmp_path)
    try:
        seed(s, "perm")
        orig = s.executor.execute

        def slow(index, query, shards=None, opt=None):
            time.sleep(0.25)
            return orig(index, query, shards, opt)

        s.executor.execute = slow
        spellings = [
            b"Count(Intersect(Row(f=1), Row(f=2)))",
            b"Count(Intersect(Row(f=2), Row(f=1)))",
            b"Count(Intersect( Row(f=2) , Row(f=1) ))",
        ]
        results = []
        lock = threading.Lock()

        def client(ci):
            st, body, _ = req(
                s, "POST", "/index/perm/query", spellings[ci % len(spellings)]
            )
            with lock:
                results.append((st, body))

        threads = [threading.Thread(target=client, args=(ci,)) for ci in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        s.executor.execute = orig
        # Intersect of rows 1 (2 bits/shard) and 2 (3 bits/shard):
        # identical correct result for every spelling
        first = results[0][1]
        assert all(st == 200 and body == first for st, body in results)
        assert s.pipeline.stats()["coalesce_hits"] >= 1
    finally:
        s.close()


# -- cross-request batching -------------------------------------------------


def test_homogeneous_queued_queries_batch_into_one_execution(tmp_path):
    # dispatch_enabled=False pins the legacy pipeline gang-batching
    # path: with the dispatch engine on, cross-request combining moves
    # into the engine (dispatch_handoff) and is covered by
    # tests/test_dispatch.py instead
    s = make_server(
        tmp_path, pipeline_interactive_workers=1, dispatch_enabled=False
    )
    try:
        seed(s, "ba", n_rows=4)
        gate = threading.Event()
        exec_calls = []
        orig = s.executor.execute

        def gated(index, query, shards=None, opt=None):
            exec_calls.append(query)
            if len(exec_calls) == 1:
                gate.wait(10)  # stall the lone worker on the first query
            return orig(index, query, shards, opt)

        s.executor.execute = gated
        results = {}
        lock = threading.Lock()

        def client(row):
            st, body, _ = req(s, "POST", "/index/ba/query", f"Count(Row(f={row}))".encode())
            with lock:
                results[row] = (st, body)

        # first request occupies the worker; the rest pile into the queue
        t0 = threading.Thread(target=client, args=(0,))
        t0.start()
        deadline = time.monotonic() + 5
        while not exec_calls and time.monotonic() < deadline:
            time.sleep(0.005)
        rest = [threading.Thread(target=client, args=(r,)) for r in (1, 2, 3)]
        for t in rest:
            t.start()
        # wait until they are actually queued before releasing the gate
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if s.pipeline.stats()["classes"]["interactive"]["queue_depth"] >= 3:
                break
            time.sleep(0.005)
        gate.set()
        t0.join()
        for t in rest:
            t.join()
        s.executor.execute = orig
        # every request got ITS OWN correct per-row count
        for row in range(4):
            st, body = results[row]
            assert st == 200, body
            assert body == {"results": [2 * (row + 1)]}, (row, body)
        stats = s.pipeline.stats()
        assert stats["batches"] >= 1
        assert stats["batched_entries"] >= 2
        assert metrics.snapshot().get("pipeline.batches", 0) >= 1
    finally:
        s.close()


# -- graceful drain ---------------------------------------------------------


def test_drain_completes_in_flight_work(tmp_path):
    s = make_server(tmp_path)
    seed(s, "dr")
    started = threading.Event()
    orig = s.executor.execute

    def slow(index, query, shards=None, opt=None):
        started.set()
        time.sleep(0.4)
        return orig(index, query, shards, opt)

    s.executor.execute = slow
    outcome = {}

    def client():
        outcome["resp"] = req(s, "POST", "/index/dr/query", b"Count(Row(f=1))")

    t = threading.Thread(target=client)
    t.start()
    assert started.wait(5)
    s.close()  # drains the pipeline before tearing anything down
    t.join(5)
    st, body, _ = outcome["resp"]
    assert st == 200 and body == {"results": [4]}
    # after the drain, new submissions are refused as shutting down
    with pytest.raises(Overloaded) as ei:
        s.pipeline.submit("interactive", lambda: None)
    assert ei.value.status == 503


def test_bare_pipeline_drain_fails_leftovers_503():
    pl = QueryPipeline(
        workers={"interactive": 1, "bulk": 1, "internal": 1},
        queue_limits={"interactive": 8, "bulk": 1, "internal": 1},
        drain_timeout=0.2,
    )
    gate = threading.Event()
    outcomes = []

    def submit_one(i):
        try:
            outcomes.append(("ok", pl.submit("interactive", lambda: gate.wait(10))))
        except BaseException as e:
            outcomes.append(("err", e))

    threads = [threading.Thread(target=submit_one, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.1)  # the first occupies the worker; two sit queued
    clean = pl.close(drain=0.2)  # worker is stuck: drain times out
    assert not clean
    gate.set()
    for t in threads:
        t.join(5)
    errs = [o for kind, o in outcomes if kind == "err"]
    assert any(isinstance(e, Overloaded) and e.status == 503 for e in errs)


# -- disabled pipeline ------------------------------------------------------


def test_pipeline_disabled_still_serves_with_deadlines(tmp_path):
    s = make_server(tmp_path, pipeline_enabled=False)
    try:
        assert s.pipeline is None
        seed(s, "nd")
        st, body, _ = req(s, "POST", "/index/nd/query", b"Count(Row(f=1))")
        assert st == 200 and body == {"results": [4]}
        # deadlines are honored even without the pipeline
        st, body, _ = req(
            s, "POST", "/index/nd/query?timeout=0.000001", b"Count(Row(f=1))"
        )
        assert st == 504
        st, body, _ = req(s, "GET", "/debug/pipeline")
        assert st == 200 and body == {"enabled": False}
    finally:
        s.close()


# -- closed-loop smoke: the serving surface lights up -----------------------


def test_closed_loop_smoke_populates_queue_wait_metrics(tmp_path):
    """test_bench_headline-style smoke: a short closed-loop window
    through the full HTTP path populates the pipeline's queue-wait and
    admission metrics, /debug/pipeline, and the Prometheus families."""
    s = make_server(tmp_path, pipeline_interactive_workers=2)
    try:
        seed(s, "cl")
        queries = [f"Count(Row(f={r}))".encode() for r in range(4)]
        stop = time.perf_counter() + 0.8
        counts = [0] * 6
        errors = []

        def client(ci):
            i = ci
            try:
                while time.perf_counter() < stop:
                    st, body, _ = req(
                        s, "POST", "/index/cl/query", queries[i % len(queries)]
                    )
                    assert st == 200, body
                    counts[ci] += 1
                    i += 1
            except BaseException as e:
                errors.append(e)

        threads = [threading.Thread(target=client, args=(ci,)) for ci in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[0]
        total = sum(counts)
        assert total > 0
        st, stats, _ = req(s, "GET", "/debug/pipeline")
        assert st == 200
        icl = stats["classes"]["interactive"]
        assert icl["admitted"] > 0
        assert icl["completed"] > 0
        assert icl["queue_depth"] == 0  # drained after the window
        snap = metrics.snapshot()
        wait = snap.get("pipeline.wait_seconds.hist;cls:interactive")
        assert wait and wait["count"] > 0, sorted(snap)[:20]
        st, raw, _ = req(s, "GET", "/metrics", raw=True)
        text = raw.decode()
        assert "pilosa_pipeline_wait_seconds_count" in text
        assert 'pilosa_pipeline_admitted{cls="interactive"}' in text
        assert "pilosa_pipeline_queue_depth" in text
    finally:
        s.close()


# -- /debug/pipeline shape --------------------------------------------------


def test_debug_pipeline_snapshot_shape(tmp_path):
    s = make_server(tmp_path)
    try:
        seed(s, "sh")
        req(s, "POST", "/index/sh/query", b"Count(Row(f=1))")
        st, stats, _ = req(s, "GET", "/debug/pipeline")
        assert st == 200
        assert stats["enabled"] is True and stats["closing"] is False
        assert set(stats["classes"]) == {"interactive", "bulk", "internal"}
        for cls in stats["classes"].values():
            assert {
                "queue_depth",
                "queue_limit",
                "workers",
                "busy",
                "admitted",
                "sheds",
                "completed",
            } <= set(cls)
        for k in ("coalesce_hits", "batches", "batched_entries", "deadline_expired"):
            assert k in stats
    finally:
        s.close()
