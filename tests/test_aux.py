"""Auxiliary components: StatsD client, gcnotify, iterators, B+tree
container store (reference statsd/, gcnotify/, iterator.go,
enterprise/b)."""

import gc
import random
import socket

import numpy as np
import pytest

from pilosa_tpu.core import (
    BufIterator,
    LimitIterator,
    RoaringIterator,
    SliceIterator,
)
from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.roaring import (
    Bitmap,
    BTreeContainers,
    get_default_container_store,
    set_default_container_store,
)
from pilosa_tpu.utils.gcnotify import GCNotifier
from pilosa_tpu.utils.stats import StatsDClient


# -- StatsD ----------------------------------------------------------------


@pytest.fixture
def udp_server():
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.settimeout(2.0)
    yield sock
    sock.close()


def _recv(sock) -> str:
    return sock.recvfrom(4096)[0].decode()


def test_statsd_wire_format(udp_server):
    port = udp_server.getsockname()[1]
    c = StatsDClient(host=f"127.0.0.1:{port}")
    c.count("setBit", 3)
    assert _recv(udp_server) == "pilosa.setBit:3|c"
    c.gauge("goroutines", 12.0)
    assert _recv(udp_server) == "pilosa.goroutines:12.0|g"
    c.timing("query", 1.5)
    assert _recv(udp_server) == "pilosa.query:1.5|ms"
    c.set("user", "a")
    assert _recv(udp_server) == "pilosa.user:a|s"
    c.histogram("h", 2.0)
    assert _recv(udp_server) == "pilosa.h:2.0|h"
    c.close()


def test_statsd_tags_propagate(udp_server):
    port = udp_server.getsockname()[1]
    c = StatsDClient(host=f"127.0.0.1:{port}")
    tagged = c.with_tags("index:i", "field:f")
    assert tagged.tags() == ["field:f", "index:i"]
    tagged.count("importBit", 1)
    assert _recv(udp_server) == "pilosa.importBit:1|c|#field:f,index:i"
    # parent unaffected
    assert c.tags() == []
    c.close()


def test_statsd_sampling(udp_server, monkeypatch):
    port = udp_server.getsockname()[1]
    c = StatsDClient(host=f"127.0.0.1:{port}")
    monkeypatch.setattr(random, "random", lambda: 0.99)
    c.count("dropped", 1, rate=0.5)  # 0.99 >= 0.5 → dropped
    monkeypatch.setattr(random, "random", lambda: 0.01)
    c.count("kept", 1, rate=0.5)
    assert _recv(udp_server) == "pilosa.kept:1|c|@0.5"
    c.close()


def test_statsd_bare_hostname_defaults_port():
    c = StatsDClient(host="localhost")
    assert c._addr == ("localhost", 8125)
    c.close()


def test_statsd_closed_socket_swallows_errors():
    """UDP fire-and-forget: a dead socket must never surface into the
    serving path (uses the _sock injection point)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    c = StatsDClient(host="127.0.0.1:9", _sock=sock)
    sock.close()
    c.count("x", 1)
    c.gauge("g", 1.0)
    c.timing("t", 0.5)
    c.histogram("h", 2.0)
    c.set("s", "v")
    c.close()  # double-close of the injected socket is swallowed too


def test_statsd_tagged_child_shares_socket(udp_server):
    """with_tags returns a view over the SAME socket — closing the
    parent closes the child; tags ride every metric type."""
    port = udp_server.getsockname()[1]
    c = StatsDClient(host=f"127.0.0.1:{port}")
    t = c.with_tags("shard:3")
    assert t._sock is c._sock
    t.timing("q", 2.5)
    assert _recv(udp_server) == "pilosa.q:2.5|ms|#shard:3"
    t.gauge("g", 7)
    assert _recv(udp_server) == "pilosa.g:7|g|#shard:3"
    c.close()


# -- expvar percentile histograms ------------------------------------------


def test_expvar_histogram_percentiles():
    from pilosa_tpu.utils.stats import ExpvarStatsClient

    c = ExpvarStatsClient()
    for v in range(1, 101):
        c.histogram("h", float(v))
    h = c.snapshot()["h.hist"]
    assert h["count"] == 100
    assert h["min"] == 1.0 and h["max"] == 100.0
    assert abs(h["sum"] - 5050.0) < 1e-9
    # log-spaced buckets: estimates carry bounded relative error
    assert 40 <= h["p50"] <= 60
    assert h["p50"] <= h["p95"] <= h["p99"] <= 100.0


def test_expvar_timing_reports_percentiles():
    from pilosa_tpu.utils.stats import ExpvarStatsClient

    c = ExpvarStatsClient(tags=["index:i"])
    for _ in range(10):
        c.timing("query_time", 0.25)
    h = c.snapshot()["query_time.timing.hist;index:i"]
    assert h["count"] == 10
    for k in ("p50", "p95", "p99"):
        assert 0.15 <= h[k] <= 0.35


def test_multi_stats_snapshot_keeps_expvar_lit():
    """satellite: with metric='statsd' the server fans out through a
    MultiStatsClient whose snapshot merges in-process children, so
    /debug/vars never goes dark."""
    from pilosa_tpu.utils.stats import (
        ExpvarStatsClient,
        MultiStatsClient,
        NopStatsClient,
    )

    ev = ExpvarStatsClient()
    m = MultiStatsClient(ev, NopStatsClient())
    m.count("c", 2)
    m.timing("t", 0.5)
    snap = m.snapshot()
    assert snap["c"] == 2
    assert snap["t.timing.hist"]["count"] == 1


# -- gcnotify --------------------------------------------------------------


def test_gcnotifier_counts_cycles():
    n = GCNotifier()
    try:
        gc.collect()
        gc.collect()
        assert n.poll() >= 2
        assert n.poll() == 0  # poll resets
    finally:
        n.close()
    gc.collect()
    assert n.poll() == 0  # closed → no longer counting


# -- iterators (reference iterator.go) -------------------------------------


PAIRS = [(0, 1), (0, 5), (2, 0), (2, 9), (7, 3)]


def _slice_iter():
    return SliceIterator([p[0] for p in PAIRS], [p[1] for p in PAIRS])


def test_slice_iterator():
    assert list(_slice_iter()) == PAIRS
    it = _slice_iter()
    it.seek(2, 1)
    assert it.next_pair() == (2, 9, False)


def test_limit_iterator():
    assert list(LimitIterator(_slice_iter(), 3)) == PAIRS[:3]
    assert list(LimitIterator(_slice_iter(), 99)) == PAIRS


def test_buf_iterator_unread_and_peek():
    it = BufIterator(_slice_iter())
    assert it.peek() == (0, 1, False)
    assert it.next_pair() == (0, 1, False)  # peek did not consume
    it.unread()
    assert it.next_pair() == (0, 1, False)  # unread re-returns
    assert it.next_pair() == (0, 5, False)
    it.unread()
    with pytest.raises(RuntimeError):
        it.unread()  # single-slot buffer


def test_roaring_iterator():
    b = Bitmap()
    for r, c in PAIRS:
        b.add(r * SHARD_WIDTH + c)
    it = RoaringIterator(b)
    assert list(it) == PAIRS
    it.seek(2, 1)
    assert it.next_pair() == (2, 9, False)
    it.seek(99, 0)
    assert it.next_pair() == (0, 0, True)


# -- B+tree container store (reference enterprise/b) -----------------------


def test_btree_containers_basics():
    t = BTreeContainers()
    keys = list(range(0, 1000, 3))
    random.Random(5).shuffle(keys)
    for k in keys:
        t[k] = f"v{k}"
    assert len(t) == len(keys)
    assert list(t) == sorted(keys)  # in-order iteration
    assert t[999 // 3 * 3] == f"v{999 // 3 * 3}"
    assert t.get(1) is None
    assert 6 in t and 7 not in t
    del t[6]
    assert 6 not in t and len(t) == len(keys) - 1
    with pytest.raises(KeyError):
        del t[6]
    assert t.pop(9) == "v9"
    assert t.pop(9, "dflt") == "dflt"
    assert list(t.keys() & {0, 3, 6, 9, 1}) != []
    t.clear()
    assert len(t) == 0 and list(t) == []


def test_btree_containers_overwrite():
    t = BTreeContainers()
    t[5] = "a"
    t[5] = "b"
    assert len(t) == 1 and t[5] == "b"


def test_bitmap_algebra_with_btree_store():
    """Same results dict-store vs btree-store across the full algebra."""
    rng = np.random.default_rng(11)
    vals_a = np.unique(rng.integers(0, 5_000_000, 4000).astype(np.uint64))
    vals_b = np.unique(rng.integers(0, 5_000_000, 4000).astype(np.uint64))

    da, db = Bitmap.from_sorted(vals_a), Bitmap.from_sorted(vals_b)
    set_default_container_store(BTreeContainers)
    try:
        ba, bb = Bitmap.from_sorted(vals_a), Bitmap.from_sorted(vals_b)
        assert isinstance(ba.containers, BTreeContainers)
        for op in ("intersect", "union", "difference", "xor"):
            want = getattr(da, op)(db).slice_all()
            got = getattr(ba, op)(bb).slice_all()
            np.testing.assert_array_equal(want, got)
        assert da.intersection_count(db) == ba.intersection_count(bb)
        assert da.count() == ba.count()
        # point ops + serialization round-trip through the btree store
        ba.add(10_000_000)
        assert ba.contains(10_000_000)
        ba.remove(10_000_000)
        assert not ba.contains(10_000_000)
        data = ba.to_bytes()
    finally:
        set_default_container_store(dict)
    rt = Bitmap.unmarshal_binary(data)
    np.testing.assert_array_equal(rt.slice_all(), ba.slice_all())
    assert get_default_container_store() is dict


def test_btree_store_survives_many_containers():
    set_default_container_store(BTreeContainers)
    try:
        b = Bitmap()
        # >64 containers forces splits (one container per 2^16 block)
        positions = [i << 16 for i in range(300)]
        b.add(*positions)
        assert b.count() == 300
        assert [int(v) for v in b.slice_all()] == positions
    finally:
        set_default_container_store(dict)


# -- stager pow2 padding + pprof route --------------------------------------


def test_stager_rows_pow2_padding(tmp_path):
    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import DeviceStager

    h = Holder(str(tmp_path))
    h.open()
    idx = h.create_index("sp")
    f = idx.create_field("f")
    f.import_bits([0, 1, 2, 3, 4], [1, 2, 3, 4, 5])
    frag = h.fragment("sp", "f", "standard", 0)
    st = DeviceStager()
    mat = st.rows(frag, (0, 1, 2, 3, 4), pad_pow2=True)
    assert mat.shape[0] == 8  # 5 rows → next pow2
    assert np.asarray(mat)[5:].sum() == 0  # padding rows are zero
    unpadded = st.rows(frag, (0, 1, 2, 3, 4))
    assert unpadded.shape[0] == 5  # separate cache entries
    np.testing.assert_array_equal(np.asarray(mat)[:5], np.asarray(unpadded))
    h.close()


def test_debug_pprof_route(tmp_path):
    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.server.api import API
    from pilosa_tpu.server.http_handler import Handler, RawResponse

    h = Holder(str(tmp_path))
    h.open()
    handler = Handler(API(h, Executor(h)))
    out = handler.handle("GET", "/debug/pprof", {}, b"")
    assert isinstance(out, RawResponse)
    assert b"goroutine-analog" in out.data and b"test_debug_pprof_route" in out.data
    h.close()
