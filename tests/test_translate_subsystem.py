"""Key translation subsystem (ISSUE 20): durable sharded key↔id stores,
federated assignment, and the keyed query surface.

Covers the per-space CRC-framed logs (durability across reopen,
torn-tail + corrupt-frame truncation, no id reassignment), the
federated Translator (partition ownership, forward + durable adoption,
pull replication idempotence, restore wipe/replace), the keyed gauntlet
(Set/Row/Count/TopN/GroupBy/Distinct via string keys bit-identical to
the same traffic pre-translated to raw ids — single node, 2-node
federated, and the quarantine/503 path), server round-trips (keyed
ingest, /debug/translate, backup/restore with tamper refusal), and the
docs↔knob sync for `translate-partitions` / `translate-cache-bytes`.

Runs under JAX_PLATFORMS=cpu (the tier-1 environment)."""

import hashlib
import io
import json
import os
import tarfile
import time

import pytest

from pilosa_tpu.core import FieldOptions, Holder
from pilosa_tpu.core.field import FIELD_TYPE_INT
from pilosa_tpu.executor import Executor
from pilosa_tpu.translate import SpaceStore, Translator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- SpaceStore: durable per-space logs ---------------------------------------


class TestSpaceStore:
    def test_stride_lane_ids_are_disjoint_residue_classes(self):
        # column partition p of P mints ids ≡ p+1 (mod P): partitions
        # never collide even though each allocates independently
        stores = [SpaceStore(None, "i", "", 4, p) for p in range(4)]
        ids = []
        for p, st in enumerate(stores):
            got = st.assign([f"k{p}.{j}" for j in range(5)])
            for id_ in got.values():
                assert (id_ - 1) % 4 == p
            ids.extend(got.values())
        assert len(set(ids)) == len(ids) == 20
        assert 0 not in ids  # id 0 is the unknown-read-key sentinel

    def test_durability_and_monotonic_ids_across_reopen(self, tmp_path):
        p = str(tmp_path / "rows.f.log")
        st = SpaceStore(p, "i", "f")
        first = st.assign([f"k{j}" for j in range(50)])
        st.close()
        st2 = SpaceStore(p, "i", "f")
        assert st2.lookup([f"k{j}" for j in range(50)]) == [
            first[f"k{j}"] for j in range(50)
        ]
        for k, id_ in first.items():
            assert st2.read_key(id_) == k
        # the sequence continues above the replayed high-water mark:
        # no id is ever reassigned
        more = st2.assign(["new1", "new2"])
        assert set(more.values()).isdisjoint(first.values())
        assert min(more.values()) > max(first.values())
        st2.close()

    def test_torn_tail_is_truncated(self, tmp_path):
        p = str(tmp_path / "rows.f.log")
        st = SpaceStore(p, "i", "f")
        ids = st.assign(["a", "b", "c"])
        st.close()
        good = os.path.getsize(p)
        with open(p, "ab") as f:
            f.write(b"\x09\x00\x00\x00\x51")  # header + partial body
        st2 = SpaceStore(p, "i", "f")
        assert os.path.getsize(p) == good
        assert st2.truncated_bytes == 5
        assert st2.lookup(["a", "b", "c"]) == [ids["a"], ids["b"], ids["c"]]
        st2.close()

    def test_corrupt_frame_truncates_from_there(self, tmp_path):
        # a bit-flip inside a frame body fails that frame's CRC: the
        # log is truncated AT the corrupt frame (everything before
        # survives, everything after is discarded with it)
        p = str(tmp_path / "rows.f.log")
        st = SpaceStore(p, "i", "f")
        st.assign(["alpha"])
        keep = st.offset()
        st.assign(["beta"])
        st.assign(["gamma"])
        st.close()
        data = bytearray(open(p, "rb").read())
        data[keep + 8 + 2] ^= 0x01  # inside beta's frame body
        open(p, "wb").write(bytes(data))
        st2 = SpaceStore(p, "i", "f")
        assert st2.offset() == keep == os.path.getsize(p)
        assert st2.truncated_bytes > 0
        assert st2.lookup(["alpha", "beta", "gamma"]) == [1, None, None]
        # re-minting after truncation reuses nothing that survived
        again = st2.assign(["beta"])
        assert again["beta"] != 1
        st2.close()

    def test_assign_is_first_write_wins(self, tmp_path):
        p = str(tmp_path / "rows.f.log")
        st = SpaceStore(p, "i", "f")
        a = st.assign(["k"])["k"]
        # an adopt of a conflicting id for an already-assigned key is a
        # no-op: the acked assignment is never changed
        st.assign(["k"], [a + 100])
        assert st.lookup(["k"]) == [a]
        st.close()

    def test_frame_stream_replication_idempotent(self):
        src = SpaceStore(None, "i", "f")
        dst = SpaceStore(None, "i", "f")
        src.assign(["x", "y"])
        data, end = src.read_from(0)
        assert end == src.offset()
        assert dst.apply_frames(data) == len(data)
        assert dst.apply_frames(data) == len(data)  # re-apply: no-op
        assert dst.lookup(["x", "y"]) == src.lookup(["x", "y"])
        assert dst.read_key(src.lookup(["y"])[0]) == "y"


# -- Translator: federation, replication, restore -----------------------------


def _pair(partitions=8, cache_bytes=1 << 20):
    """Two in-memory Translators federated directly (no server): t0
    owns even column partitions and all row spaces, t1 owns odd
    partitions. forward_to bridges them the way InternalClient does."""
    t0 = Translator(None, partitions=partitions, cache_bytes=cache_bytes)
    t1 = Translator(None, partitions=partitions, cache_bytes=cache_bytes)

    def resolver_for(me):
        def resolver(index, field, partition):
            if field or partition < 0:  # row spaces: t0 owns
                return "" if me is t0 else "uri://t0"
            owner = t0 if partition % 2 == 0 else t1
            return "" if owner is me else f"uri://t{0 if owner is t0 else 1}"

        return resolver

    def forward(uri, index, field, keys):
        target = t0 if uri.endswith("t0") else t1
        return target.mint(index, field, keys)

    for t in (t0, t1):
        t.owner_resolver = resolver_for(t)
        t.forward_to = forward
    return t0, t1


class TestTranslatorFederation:
    def test_owner_is_sole_allocator_and_nonowner_adopts(self):
        t0, t1 = _pair()
        keys = [f"user-{j}" for j in range(64)]
        ids0 = t0.translate_columns_to_ids("i", keys)
        assert len(set(ids0)) == 64 and all(i >= 1 for i in ids0)
        # t1 resolves the same keys to the same ids — the misses it
        # owned were minted locally, the rest forwarded to t0; either
        # way both sides now agree durably
        ids1 = t1.translate_columns_to_ids("i", keys)
        assert ids1 == ids0
        assert t0.forwards > 0  # t0 really did forward odd partitions
        # reads never forward: unknown keys stay unminted everywhere
        assert t1.translate_columns_to_ids("i", ["nope"], create=False) == [None]
        # reverse translation agrees on both nodes
        for k, id_ in zip(keys[:8], ids0[:8]):
            assert t0.translate_column_to_string("i", id_) == k
            assert t1.translate_column_to_string("i", id_) == k

    def test_misowned_gates_the_mint_endpoint(self):
        t0, t1 = _pair()
        keys = [f"k{j}" for j in range(32)]
        owned0 = [k for k in keys if not t0.misowned("i", "", [k])]
        owned1 = [k for k in keys if not t1.misowned("i", "", [k])]
        assert owned0 and owned1  # both parities represented
        assert set(owned0).isdisjoint(owned1)  # exactly one owner each
        assert t1.misowned("i", "", [owned0[0]]) == "uri://t0"
        # row spaces: t0 owns them all
        assert t0.misowned("i", "f", ["r"]) == ""
        assert t1.misowned("i", "f", ["r"]) == "uri://t0"

    def test_pull_replication_catches_up_and_is_idempotent(self):
        t0 = Translator(None, partitions=4)
        t1 = Translator(None, partitions=4)
        t0.translate_columns_to_ids("i", [f"c{j}" for j in range(20)])
        t0.translate_rows_to_ids("i", "f", ["r1", "r2"])
        offsets = {}
        for _ in range(2):  # second pass: everything already applied
            for entry in t0.stores():
                name, off = entry["name"], offsets.get(entry["name"], 0)
                if entry["offset"] <= off:
                    continue
                data = t0.read_store(name, off)
                offsets[name] = off + t1.apply_frames(data)
        assert t1.translate_columns_to_ids(
            "i", [f"c{j}" for j in range(20)], create=False
        ) == t0.translate_columns_to_ids("i", [f"c{j}" for j in range(20)], create=False)
        assert t1.translate_row_to_string("i", "f", 1) == t0.translate_row_to_string(
            "i", "f", 1
        )

    def test_read_store_rejects_traversal(self):
        t = Translator(None)
        for bad in ["../etc/passwd", "/abs/path", "noslash", "i/../../x"]:
            with pytest.raises(ValueError):
                t.read_store(bad, 0)

    def test_restore_stores_replaces_the_translate_plane(self, tmp_path):
        src = Translator(str(tmp_path / "src"), partitions=4)
        ids = src.translate_columns_to_ids("i", ["a", "b", "c"])
        blobs = src.store_files()
        dst = Translator(str(tmp_path / "dst"), partitions=4)
        dst.translate_columns_to_ids("i", ["stale1", "stale2"])
        dst.restore_stores(blobs)
        assert dst.translate_columns_to_ids("i", ["a", "b", "c"], create=False) == ids
        # pre-restore assignments are gone — the restored holder
        # resolves exactly the archive's keys
        assert dst.translate_columns_to_ids("i", ["stale1"], create=False) == [None]
        # and the replacement is durable
        dst.close()
        dst2 = Translator(str(tmp_path / "dst"), partitions=4)
        assert dst2.translate_columns_to_ids("i", ["a", "b", "c"], create=False) == ids

    def test_cache_bounded_and_counts(self):
        t = Translator(None, partitions=2, cache_bytes=256)
        keys = [f"key-{j:04d}" for j in range(64)]
        ids = t.translate_columns_to_ids("i", keys)
        for id_ in ids:
            t.translate_column_to_string("i", id_)
        st = t.stats()["cache"]
        assert st["bytes"] <= 256
        assert st["misses"] >= 64
        # a hot id now hits
        t.translate_column_to_string("i", ids[-1])
        assert t.stats()["cache"]["hits"] >= 1


# -- keyed gauntlet: bit-identical to the raw-id twin -------------------------

KEYED_QUERIES = [
    'Row(likes="fiction")',
    'Count(Row(likes="fiction"))',
    'Count(Intersect(Row(likes="fiction"), Row(likes="scifi")))',
    'Count(Union(Row(likes="fiction"), Row(likes="poetry")))',
    "TopN(likes, n=3)",
    'TopN(likes, ids=["fiction", "poetry"])',
    "GroupBy(Rows(segment))",
    'GroupBy(Rows(likes, ids=["fiction", "scifi"]))',
    "Distinct(field=age)",
]

GENRES = ["fiction", "scifi", "poetry"]
SEGMENTS = ["free", "premium"]


def _keyed_workload(n=60):
    """(col_key, genre, segment, age) tuples — the keyed traffic."""
    return [
        (f"user-{j:03d}", GENRES[j % 3], SEGMENTS[j % 2], 20 + j % 7)
        for j in range(n)
    ]


def _build_keyed(translator):
    h = Holder()
    h.open()
    idx = h.create_index("users", keys=True)
    idx.create_field("likes", FieldOptions(keys=True))
    idx.create_field("segment", FieldOptions(keys=True))
    idx.create_field("age", FieldOptions(type=FIELD_TYPE_INT, min=0, max=100))
    e = Executor(h, device_policy="never", translate_store=translator)
    for col, genre, seg, age in _keyed_workload():
        e.execute("users", f'Set("{col}", likes="{genre}")')
        e.execute("users", f'Set("{col}", segment="{seg}")')
        e.execute("users", f'SetValue(col="{col}", age={age})')
    return e


def _build_raw_twin(translator):
    """The SAME traffic pre-translated to raw ids through the keyed
    side's translator — the oracle the keyed surface must match
    bit-for-bit."""
    h = Holder()
    h.open()
    idx = h.create_index("users")
    idx.create_field("likes")
    idx.create_field("segment")
    idx.create_field("age", FieldOptions(type=FIELD_TYPE_INT, min=0, max=100))
    e = Executor(h, device_policy="never")
    for col, genre, seg, age in _keyed_workload():
        (cid,) = translator.translate_columns_to_ids("users", [col], create=False)
        (gid,) = translator.translate_rows_to_ids("users", "likes", [genre], create=False)
        (sid,) = translator.translate_rows_to_ids(
            "users", "segment", [seg], create=False
        )
        assert cid and gid and sid, "keyed run must have minted these"
        e.execute("users", f"Set({cid}, likes={gid})")
        e.execute("users", f"Set({cid}, segment={sid})")
        e.execute("users", f"SetValue(col={cid}, age={age})")
    return e


def _raw_query(translator, q, index="users"):
    """Pre-translate one keyed gauntlet query to its raw-id twin."""
    for genre in GENRES:
        (gid,) = translator.translate_rows_to_ids(index, "likes", [genre], create=False)
        q = q.replace(f'"{genre}"', str(gid))
    return q


def _strip_keys(r):
    """Canonicalize a result down to its raw skeleton: drop translated
    decorations (``key``/``keys``/``rowKey``); TopN pairs — where the
    keyed shape REPLACES ``id`` with ``key`` — compare by count slot
    (the strict key↔id mapping is asserted separately)."""
    if isinstance(r, list):
        return [_strip_keys(x) for x in r]
    if isinstance(r, dict):
        d = {k: _strip_keys(v) for k, v in r.items() if k not in ("key", "keys", "rowKey")}
        if "count" in d and ("id" in d or "key" in r) and "group" not in d:
            return {"count": d["count"]}
        return d
    if hasattr(r, "columns"):
        return ("row", tuple(int(c) for c in r.columns()))
    return r


class TestKeyedGauntletSingleNode:
    def test_bit_identical_to_raw_twin(self):
        t = Translator(None, partitions=8)
        keyed = _build_keyed(t)
        raw = _build_raw_twin(t)
        for q in KEYED_QUERIES:
            (kr,) = keyed.execute("users", q)
            (rr,) = raw.execute("users", _raw_query(t, q))
            if q.startswith("Row("):
                # same column-id bitmap, plus translated column keys
                assert tuple(kr.columns()) == tuple(rr.columns())
                got = sorted(kr.keys)
                want = sorted(
                    t.translate_column_to_string("users", c) for c in rr.columns()
                )
                assert got == want, q
            elif q.startswith("TopN"):
                # counts identical in order; keys are the ids' reverse
                # translations
                assert [p["count"] for p in kr] == [p["count"] for p in rr], q
                assert [p["key"] for p in kr] == [
                    t.translate_row_to_string("users", "likes", p["id"]) for p in rr
                ], q
            elif q.startswith("GroupBy"):
                assert _strip_keys(kr) == _strip_keys(rr), q
                for g in kr:
                    for dim in g["group"]:
                        assert dim["rowKey"] == t.translate_row_to_string(
                            "users", dim["field"], dim["rowID"]
                        )
            else:
                assert _strip_keys(kr) == _strip_keys(rr), q

    def test_unknown_read_key_matches_nothing(self):
        t = Translator(None, partitions=8)
        keyed = _build_keyed(t)
        (r,) = keyed.execute("users", 'Row(likes="never-written")')
        assert list(r.columns()) == []
        (c,) = keyed.execute("users", 'Count(Row(likes="never-written"))')
        assert c == 0
        # ...and the read did NOT mint: still unknown afterwards
        assert t.translate_rows_to_ids(
            "users", "likes", ["never-written"], create=False
        ) == [None]

    def test_type_mixing_is_a_clean_400_class_error(self):
        t = Translator(None, partitions=8)
        keyed = _build_keyed(t)
        with pytest.raises(ValueError):
            keyed.execute("users", "Set(12, likes=3)")  # int col on keyed index
        raw = _build_raw_twin(t)
        with pytest.raises(ValueError):
            raw.execute("users", 'Row(likes="fiction")')  # str on unkeyed

    def test_plan_cache_sees_resolved_ids_only(self):
        # two spellings of the same keyed subtree share one canonical
        # plan: resolution happens BEFORE canonicalization
        from pilosa_tpu.plan import call_hash
        from pilosa_tpu.plan import planner as planner_mod
        from pilosa_tpu.pql.parser import parse

        t = Translator(None, partitions=8)
        keyed = _build_keyed(t)
        idx = keyed.holder.indexes["users"]
        q1 = 'Count(Intersect(Row(likes="fiction"), Row(likes="scifi")))'
        q2 = 'Count(Intersect(Row(likes="scifi"), Row(likes="fiction")))'

        def canon_hash(q):
            calls = parse(q).calls
            planner_mod.resolve_keys(keyed, "users", idx, calls)
            return call_hash(calls[0])

        assert canon_hash(q1) == canon_hash(q2)


# -- server round-trips: keyed ingest, debug, backup/restore ------------------


def _tamper_tar_member(archive: bytes, prefix: str, fix_manifest: bool = False):
    """Flip a byte in the first member under ``prefix``. With
    fix_manifest=True the MANIFEST digest is recomputed for the
    corrupted blob, so the archive passes the digest check and the
    deeper translate-log parse probe must catch it."""
    buf = io.BytesIO(archive)
    members = []
    with tarfile.open(fileobj=buf) as tr:
        for m in tr.getmembers():
            members.append((m.name, tr.extractfile(m).read() if m.size else b""))
    target = next(n for n, b in members if n.startswith(prefix) and b)
    out_members = []
    manifest = None
    for n, b in members:
        if n == target:
            bad = bytearray(b)
            bad[len(bad) // 2] ^= 0x01
            b = bytes(bad)
        if n == "MANIFEST.json":
            manifest = json.loads(b)
            continue
        out_members.append((n, b))
    if fix_manifest:
        manifest["entries"][target] = {
            "blake2b": hashlib.blake2b(
                next(b for n, b in out_members if n == target), digest_size=16
            ).hexdigest(),
            "size": len(next(b for n, b in out_members if n == target)),
        }
    out = io.BytesIO()
    with tarfile.open(fileobj=out, mode="w") as tw:
        for n, b in [("MANIFEST.json", json.dumps(manifest).encode())] + out_members:
            info = tarfile.TarInfo(n)
            info.size = len(b)
            tw.addfile(info, io.BytesIO(b))
    return out.getvalue()


class TestServerKeyed:
    def test_keyed_ingest_debug_backup_restore(self, tmp_path):
        from tests.test_cluster import boot_static_cluster, req

        servers = boot_static_cluster(tmp_path, n=1, replicas=1)
        try:
            uri = servers[0].uri
            assert req(uri, "POST", "/index/u", {"options": {"keys": True}})[0] == 200
            assert (
                req(uri, "POST", "/index/u/field/f", {"options": {"keys": True}})[0]
                == 200
            )
            # keyed bulk ingest: the whole batch resolves before the wave
            st, body = req(
                uri,
                "POST",
                "/index/u/field/f/ingest",
                {
                    "rowKeys": ["r1", "r1", "r2"],
                    "columnKeys": ["alice", "bob", "alice"],
                },
            )
            assert st == 200, body
            st, body = req(uri, "POST", "/index/u/query", b'Row(f="r1")')
            assert st == 200
            assert sorted(body["results"][0]["keys"]) == ["alice", "bob"]

            # /debug/translate: live stats surface
            st, dbg = req(uri, "GET", "/debug/translate")
            assert st == 200 and dbg["enabled"] is True
            # 2 row keys (r1, r2) + 2 column keys (alice, bob)
            assert dbg["keys"] == 4 and dbg["minted"] == 4
            st, stores = req(uri, "GET", "/internal/translate/stores")
            assert st == 200 and any(
                e["name"].startswith("u/columns.") for e in stores
            )

            # backup carries the translate logs in the MANIFEST
            st, archive = req(uri, "GET", "/backup", raw=True)
            assert st == 200
            with tarfile.open(fileobj=io.BytesIO(archive)) as tr:
                names = tr.getnames()
                manifest = json.loads(tr.extractfile("MANIFEST.json").read())
            t_names = [n for n in names if n.startswith("translate/")]
            assert t_names and all(n in manifest["entries"] for n in t_names)

            # tampered translate entry → refused (digest mismatch)
            st, body = req(
                uri, "POST", "/restore", _tamper_tar_member(archive, "translate/")
            )
            assert st == 400 and "restore refused" in body["error"], body
            # tampered AND digest-fixed → the parse probe refuses it
            st, body = req(
                uri,
                "POST",
                "/restore",
                _tamper_tar_member(archive, "translate/", fix_manifest=True),
            )
            assert st == 400 and "restore refused" in body["error"], body
            # nothing was applied either time: keys still resolve
            st, body = req(uri, "POST", "/index/u/query", b'Count(Row(f="r1"))')
            assert st == 200 and body["results"][0] == 2

            # pristine restore into a SECOND fresh server: every acked
            # key resolves to its original id
            fresh = boot_static_cluster(tmp_path / "fresh", n=1, replicas=1)
            try:
                furi = fresh[0].uri
                st, body = req(furi, "POST", "/restore", archive)
                assert st == 200, body
                st, body = req(furi, "POST", "/index/u/query", b'Row(f="r1")')
                assert st == 200
                assert sorted(body["results"][0]["keys"]) == ["alice", "bob"]
                src_ts = servers[0].translate_store
                dst_ts = fresh[0].translate_store
                for key in ("alice", "bob"):
                    assert dst_ts.translate_columns_to_ids(
                        "u", [key], create=False
                    ) == src_ts.translate_columns_to_ids("u", [key], create=False)
            finally:
                for s in fresh:
                    s.close()
        finally:
            for s in servers:
                s.close()


# -- federated 2-node keyed gauntlet + quarantine/503 -------------------------


def _wait_until(pred, timeout=15.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


class TestClusterKeyed:
    def test_two_node_keyed_gauntlet_matches_raw_twin(self, tmp_path):
        from tests.test_cluster import boot_static_cluster, req

        servers = boot_static_cluster(tmp_path, n=2, replicas=2)
        try:
            uris = [s.uri for s in servers]
            for path, opts in [
                ("/index/u", {"options": {"keys": True}}),
                ("/index/u/field/likes", {"options": {"keys": True}}),
                ("/index/raw", {}),
                ("/index/raw/field/likes", {}),
            ]:
                assert req(uris[0], "POST", path, opts)[0] == 200
            # keyed writes land on BOTH nodes round-robin: assignment
            # must federate (owner mints, non-owner forwards + adopts)
            work = _keyed_workload(40)
            for j, (col, genre, _seg, _age) in enumerate(work):
                st, body = req(
                    uris[j % 2],
                    "POST",
                    "/index/u/query",
                    f'Set("{col}", likes="{genre}")'.encode(),
                )
                assert st == 200, body
            ts = servers[0].translate_store
            # raw twin: the same traffic pre-translated through node 0
            for col, genre, _seg, _age in work:
                (cid,) = ts.translate_columns_to_ids("u", [col], create=False)
                (gid,) = ts.translate_rows_to_ids("u", "likes", [genre], create=False)
                assert cid and gid
                st, _ = req(
                    uris[0], "POST", "/index/raw/query", f"Set({cid}, likes={gid})".encode()
                )
                assert st == 200
            queries = [
                'Row(likes="fiction")',
                'Count(Row(likes="scifi"))',
                "TopN(likes, n=3)",
                'GroupBy(Rows(likes, ids=["fiction", "poetry"]))',
            ]
            for q in queries:
                rq = _raw_query(ts, q, index="u")
                for uri in uris:  # both nodes answer, identically
                    st, kb = req(uri, "POST", "/index/u/query", q.encode())
                    assert st == 200, (q, kb)
                    st, rb = req(uri, "POST", "/index/raw/query", rq.encode())
                    assert st == 200, (rq, rb)
                    kres, rres = kb["results"][0], rb["results"][0]
                    if q.startswith("Row("):
                        # keyed rows serialize "keys" IN PLACE OF
                        # "columns": they must be the raw columns'
                        # reverse translations, nothing more or less
                        want = sorted(
                            ts.translate_column_to_string("u", c)
                            for c in rres["columns"]
                        )
                        assert sorted(kres["keys"]) == want, (uri, q)
                    elif q.startswith("TopN"):
                        assert [p["count"] for p in kres] == [
                            p["count"] for p in rres
                        ], (uri, q)
                        assert [p["key"] for p in kres] == [
                            ts.translate_row_to_string("u", "likes", p["id"])
                            for p in rres
                        ], (uri, q)
                    else:
                        assert _strip_keys(kres) == _strip_keys(rres), (uri, q)
            # both nodes converge on identical reverse translation
            (fic,) = ts.translate_rows_to_ids("u", "likes", ["fiction"], create=False)
            _wait_until(
                lambda: servers[1].translate_store.translate_row_to_string(
                    "u", "likes", fic
                )
                == "fiction",
                what="replica adoption of row key",
            )
        finally:
            for s in servers:
                s.close()

    def test_keyed_query_through_quarantine_503(self, tmp_path):
        from tests.test_cluster import boot_static_cluster, req

        # replicas=1: no healthy copy to fail over to, so the keyed
        # read must surface the clean 503 — never a stack trace, never
        # poisoned bits
        servers = boot_static_cluster(tmp_path, n=1, replicas=1)
        try:
            uri = servers[0].uri
            assert req(uri, "POST", "/index/u", {"options": {"keys": True}})[0] == 200
            assert (
                req(uri, "POST", "/index/u/field/f", {"options": {"keys": True}})[0]
                == 200
            )
            for j in range(24):
                st, _ = req(
                    uri, "POST", "/index/u/query", f'Set("u{j}", f="r{j % 3}")'.encode()
                )
                assert st == 200
            frag = servers[0].holder.fragment("u", "f", "standard", 0)
            with frag.mu:
                frag.snapshot()
            frag._flip_disk_byte(10)
            st, body = req(uri, "POST", "/debug/scrub", {"repair": False})
            assert st == 200 and body["corrupt"] == 1
            st, body = req(uri, "POST", "/index/u/query", b'Row(f="r0")')
            assert st == 503, body
            assert "quarantine" in body["error"]
            # translation itself stays healthy: key lookups are not
            # fragment reads
            ts = servers[0].translate_store
            assert ts.translate_columns_to_ids("u", ["u0"], create=False)[0] >= 1
        finally:
            for s in servers:
                s.close()


# -- docs wired to the registry ----------------------------------------------


class TestDocsSync:
    def test_configuration_knobs_documented(self):
        doc = open(os.path.join(REPO, "docs", "configuration.md")).read()
        for knob in ("translate-partitions", "translate-cache-bytes"):
            assert f"`{knob}`" in doc, f"configuration.md missing {knob}"

    def test_query_language_keys_section(self):
        doc = open(os.path.join(REPO, "docs", "query-language.md")).read()
        assert "## Keys" in doc
        for frag in ('Set("user-9"', "rowKeys", "translate-cache-bytes"):
            assert frag in doc

    def test_administration_debug_translate_bullet(self):
        doc = open(os.path.join(REPO, "docs", "administration.md")).read()
        assert "/debug/translate" in doc
        assert "Key translation in a cluster" in doc

    def test_config_defaults_match_docs(self):
        from pilosa_tpu.server import Config

        cfg = Config()
        assert cfg.translate_partitions == 16
        assert cfg.translate_cache_bytes == 1 << 20
