"""Whole-query / wave device fusion (ISSUE 13, executor/fusion.py +
plan/cache.py DevicePlanCache): multi-call reads lowering to ONE jitted
launch bit-identical to both the unfused device path and the CPU
oracle (TopN, Count, BSI Sum, 3-op chains, __cached substitution),
wave fusion through the dispatch engine with read-after-write
freshness, the device-resident plan cache (LRU under a byte budget,
generation invalidation, epoch reset), the bypass matrix
(gang/cluster/mesh/serial/remote/write/cpu — the PR 5/6 determinism
contract), and the fusion.* metrics + /debug/fusion surface."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import FieldOptions, Holder
from pilosa_tpu.core.field import FIELD_TYPE_INT
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.plan.cache import DevicePlanCache, PlanCache
from pilosa_tpu.pql import parse
from pilosa_tpu.utils import chaos, metrics


@pytest.fixture
def holder():
    h = Holder()  # in-memory
    h.open()
    return h


def seed_mixed(h, n_shards=3):
    """Multi-shard index with a set field and a BSI field — enough
    surface for TopN / Count / Sum / chain plans in one fused launch."""
    rng = np.random.default_rng(9)
    idx = h.create_index("i")
    f = idx.create_field("f")
    v = idx.create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=-50, max=5000))
    rows = rng.integers(0, 12, size=3000)
    cols = rng.integers(0, n_shards * SHARD_WIDTH, size=3000)
    f.import_bits(rows.tolist(), cols.tolist())
    vcols = rng.choice(n_shards * SHARD_WIDTH, size=800, replace=False)
    vvals = rng.integers(-50, 5000, size=800)
    v.import_values(vcols.tolist(), vvals.tolist())


# the fusion gauntlet: every fusable unit kind plus 3-op chains, in one
# multi-call query so a single launch covers them all
GAUNTLET = (
    "Count(Row(f=1))"
    "TopN(f, Row(f=3), n=4)"
    'Sum(Row(f=1), field="v")'
    'Sum(field="v")'
    "Count(Intersect(Row(f=1), Row(f=2)))"
    "Count(Union(Row(f=3), Xor(Row(f=4), Row(f=5)), Difference(Row(f=6), Row(f=7))))"
    "Count(Range(v > 100))"
    "TopN(f, Union(Row(f=1), Row(f=2)), n=6)"
)


def oracle_of(h):
    return Executor(h, device_policy="never", dispatch_enabled=False)


# -- whole-query fusion bit-identity ----------------------------------------


class TestBitIdentity:
    def test_gauntlet_fused_vs_unfused_vs_oracle(self, holder):
        """The full gauntlet in ONE query: fused results match both the
        per-call device path (fusion off) and the CPU oracle exactly."""
        seed_mixed(holder)
        oracle = oracle_of(holder)
        want = oracle.execute("i", GAUNTLET)
        unfused = Executor(
            holder, device_policy="always", dispatch_enabled=False,
            fusion_enabled=False,
        )
        assert unfused.fuser is None
        assert unfused.execute("i", GAUNTLET) == want
        ex = Executor(holder, device_policy="always", dispatch_enabled=False)
        try:
            got = ex.execute("i", GAUNTLET)
            assert got == want
            st = ex.fuser.stats()
            # one launch covered the fusable mix (childless Sum/TopN
            # variants may stay residual; chains and filtered TopN fuse)
            assert st["fused_launches"] == 1
            assert st["fused_calls"] >= 5
            assert st["bytes_returned"] > 0
            # repeat reuses the compiled program — no recompile per query
            assert ex.execute("i", GAUNTLET) == want
            st2 = ex.fuser.stats()
            assert st2["fused_launches"] >= 2
            assert st2["programs"] == st["programs"]
        finally:
            ex.close()
            unfused.close()
            oracle.close()

    def test_three_op_chains_fuse_into_one_launch(self, holder):
        """Three 3-op chain Counts — the bench's chain shape — cost one
        fused launch instead of three round trips."""
        seed_mixed(holder)
        q = (
            "Count(Union(Row(f=1), Intersect(Row(f=2), Row(f=3))))"
            "Count(Difference(Union(Row(f=4), Row(f=5)), Row(f=6)))"
            "Count(Xor(Row(f=7), Union(Row(f=8), Row(f=9))))"
        )
        oracle = oracle_of(holder)
        want = oracle.execute("i", q)
        ex = Executor(holder, device_policy="always", dispatch_enabled=False)
        try:
            assert ex.execute("i", q) == want
            st = ex.fuser.stats()
            assert st["fused_launches"] == 1 and st["fused_calls"] == 3
        finally:
            ex.close()
            oracle.close()

    def test_cached_subtree_substitution_stays_fresh_and_identical(self, holder):
        """__cached substitution under fusion: a repeated subtree CSEs
        into a __cached node whose bitmap stack the device cache pins;
        repeats serve from both caches and writes invalidate exactly."""
        seed_mixed(holder)
        q = (
            "Count(Intersect(Row(f=1), Row(f=2)))"
            "TopN(f, Intersect(Row(f=1), Row(f=2)), n=5)"
        )
        oracle = oracle_of(holder)
        ex = Executor(
            holder, device_policy="always", dispatch_enabled=False,
            plan_cache=PlanCache(),
        )
        try:
            assert ex.device_cache is not None
            want = oracle.execute("i", q)
            for rep in range(4):
                assert ex.execute("i", q) == want, rep
            dst = ex.device_cache.stats()
            assert dst["inserts"] >= 1 and dst["hits"] >= 1
            assert ex.fuser.stats()["cache_served"] >= 1
            # write → generation bump → nothing stale anywhere
            assert ex.execute("i", f"Set({SHARD_WIDTH + 55}, f=1)") == [True]
            assert ex.execute("i", f"Set({SHARD_WIDTH + 55}, f=2)") == [True]
            want2 = oracle.execute("i", q)
            assert want2 != want
            assert ex.execute("i", q) == want2
        finally:
            ex.close()
            oracle.close()

    def test_plan_cache_serves_whole_calls_on_fused_path(self, holder):
        """Whole-call plan-cache hits short-circuit lowering: repeats of
        a cacheable multi-call read stop launching entirely."""
        seed_mixed(holder)
        q = "Count(Row(f=1))Count(Row(f=2))"
        oracle = oracle_of(holder)
        want = oracle.execute("i", q)
        ex = Executor(
            holder, device_policy="always", dispatch_enabled=False,
            plan_cache=PlanCache(),
        )
        try:
            for rep in range(4):
                assert ex.execute("i", q) == want, rep
            st = ex.fuser.stats()
            assert st["fused_launches"] == 1  # first execution only
            assert st["cache_served"] >= 4
        finally:
            ex.close()
            oracle.close()


# -- wave fusion through the dispatch engine --------------------------------


def _gated_executor(h, **kw):
    """Device executor whose FIRST _execute blocks on a gate so
    everything submitted meanwhile piles into one provably-wide wave."""
    ex = Executor(
        h, device_policy="always", dispatch_enabled=True,
        dispatch_max_inflight=1, dispatch_max_wave=32, **kw
    )
    orig = ex._execute
    gate = threading.Event()
    first = threading.Event()

    def gated(index, query, shards=None, opt=None):
        if not first.is_set():
            first.set()
            assert gate.wait(10), "test gate never released"
        return orig(index, query, shards, opt)

    ex._execute = gated
    return ex, gate, first


def _wait_queued(engine, n, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if engine.stats()["queued"] >= n:
            return
        time.sleep(0.005)
    raise AssertionError(f"queue never reached {n}: {engine.stats()}")


WAVE_QUERIES = [
    "Count(Row(f=2))",
    "TopN(f, Row(f=3), n=4)",
    'Sum(Row(f=1), field="v")',
    "Count(Intersect(Row(f=1), Row(f=2)))",
    "Count(Range(v > 100))",
]


class TestWaveFusion:
    def test_combined_wave_is_one_fused_launch(self, holder):
        """A heterogeneous wave of 5 queries combines into one Query and
        executes as ONE fused launch, per-item results split exactly."""
        seed_mixed(holder)
        oracle = oracle_of(holder)
        want = {i: oracle.execute("i", q) for i, q in enumerate(WAVE_QUERIES)}
        ex, gate, first = _gated_executor(holder)
        try:
            blocker = threading.Thread(
                target=lambda: ex.execute("i", "Count(Row(f=0))")
            )
            blocker.start()
            assert first.wait(10)
            res = {}
            ts = []

            def client(i, q):
                res[i] = ex.execute("i", q)

            for i, q in enumerate(WAVE_QUERIES):
                t = threading.Thread(target=client, args=(i, q))
                t.start()
                ts.append(t)
            _wait_queued(ex.dispatch_engine, len(WAVE_QUERIES))
            gate.set()
            for t in ts:
                t.join()
            blocker.join()
            for i, q in enumerate(WAVE_QUERIES):
                assert res[i] == want[i], q
            st = ex.fuser.stats()
            assert st["fused_launches"] >= 1
            assert st["fused_calls"] >= len(WAVE_QUERIES) - 1
            assert ex.dispatch_engine.stats()["combined_items"] >= len(
                WAVE_QUERIES
            ) - 1
        finally:
            gate.set()
            ex.close()
            oracle.close()

    def test_read_after_write_fresh_through_fused_wave(self, holder):
        """A read submitted after a write observes that write even when
        the wave it joins executes fused — generation bumps mid-stream
        never serve stale fused results."""
        seed_mixed(holder)
        oracle = oracle_of(holder)
        ex, gate, first = _gated_executor(holder)
        try:
            blocker = threading.Thread(
                target=lambda: ex.execute("i", "Count(Row(f=0))")
            )
            blocker.start()
            assert first.wait(10)
            new_cols = [SHARD_WIDTH * 2 + 777 + k for k in range(5)]
            for c in new_cols:
                assert ex.execute("i", f"Set({c}, f=0)") == [True]
            (after,) = oracle.execute("i", "Count(Row(f=0))")
            # two reads queue into one post-write wave → fused together
            res = {}
            ts = [
                threading.Thread(
                    target=lambda k=k: res.update(
                        {k: ex.execute("i", "Count(Row(f=0))")}
                    )
                )
                for k in range(2)
            ]
            for t in ts:
                t.start()
            _wait_queued(ex.dispatch_engine, 2)
            gate.set()
            for t in ts:
                t.join()
            blocker.join()
            assert res[0] == [after] and res[1] == [after]
        finally:
            gate.set()
            ex.close()
            oracle.close()


# -- device-resident plan cache ---------------------------------------------


class TestDevicePlanCache:
    def test_lru_eviction_under_byte_budget(self):
        gen = ("g", 1)
        dc = DevicePlanCache(max_bytes=1000)
        a = np.zeros(100, dtype=np.uint32)  # 400 bytes
        dc.put("a", gen, a, a.nbytes)
        dc.put("b", gen, a, a.nbytes)
        assert dc.stats()["entries"] == 2 and dc.stats()["bytes"] == 800
        dc.get("a", lambda: gen)  # a is now MRU
        dc.put("c", gen, a, a.nbytes)  # over budget → evict LRU = b
        st = dc.stats()
        assert st["entries"] == 2 and st["bytes"] == 800
        assert st["evictions"] == 1
        assert dc.get("a", lambda: gen) is not None
        assert dc.get("b", lambda: gen) is None
        assert dc.get("c", lambda: gen) is not None

    def test_oversized_value_never_stored(self):
        dc = DevicePlanCache(max_bytes=100)
        dc.put("big", ("g",), np.zeros(1000, dtype=np.uint32), 4000)
        assert dc.stats()["entries"] == 0

    def test_generation_mismatch_invalidates(self):
        dc = DevicePlanCache(max_bytes=1000)
        dc.put("k", ("gen", 1), np.zeros(4, dtype=np.uint32), 16)
        assert dc.get("k", lambda: ("gen", 1)) is not None
        # the stamped generation no longer matches → drop, miss
        assert dc.get("k", lambda: ("gen", 2)) is None
        st = dc.stats()
        assert st["invalidations"] == 1 and st["entries"] == 0

    def test_epoch_fence_rejects_pre_reset_builds(self):
        dc = DevicePlanCache(max_bytes=1000)
        epoch0 = dc.epoch
        dc.epoch_reset()  # device restore while a build was in flight
        dc.put("k", ("g",), np.zeros(4, dtype=np.uint32), 16, epoch0=epoch0)
        assert dc.stats()["entries"] == 0

    def test_executor_epoch_reset_clears_device_cache(self, holder):
        seed_mixed(holder, n_shards=1)
        ex = Executor(
            holder, device_policy="always", dispatch_enabled=False,
            plan_cache=PlanCache(),
        )
        try:
            ex.device_cache.put(
                "k", ("g",), np.zeros(4, dtype=np.uint32), 16
            )
            assert ex.device_cache.stats()["entries"] == 1
            ex._on_device_restore()
            st = ex.device_cache.stats()
            assert st["entries"] == 0 and st["epoch"] >= 1
        finally:
            ex.close()

    def test_disabled_without_plan_cache_or_budget(self, holder):
        assert (
            Executor(holder, device_policy="always").device_cache is None
        )  # no plan cache → no device cache
        assert (
            Executor(
                holder, device_policy="always", plan_cache=PlanCache(),
                plan_cache_device_bytes=0,
            ).device_cache
            is None
        )
        assert (
            Executor(
                holder, device_policy="always", plan_cache=PlanCache()
            ).device_cache
            is not None
        )


# -- bypass matrix (PR 5/6 determinism contract) ----------------------------


class TestBypassMatrix:
    def _calls(self, q="Count(Row(f=1))Count(Row(f=2))"):
        return parse(q).calls

    def test_gang_cluster_mesh_and_opt_bypass(self, holder):
        seed_mixed(holder, n_shards=1)
        ex = Executor(holder, device_policy="always", dispatch_enabled=False)
        try:
            fuser, calls = ex.fuser, self._calls()
            ex.gang = object()
            assert fuser.try_execute("i", calls, [0], ExecOptions()) is None
            ex.gang = None
            ex.cluster = object()
            assert fuser.try_execute("i", calls, [0], ExecOptions()) is None
            ex.cluster = None
            ex.mesh = object()
            assert fuser.try_execute("i", calls, [0], ExecOptions()) is None
            ex.mesh = None
            assert (
                fuser.try_execute("i", calls, [0], ExecOptions(remote=True))
                is None
            )
            assert (
                fuser.try_execute("i", calls, [0], ExecOptions(serial=True))
                is None
            )
            assert fuser.try_execute("i", calls, [], ExecOptions()) is None
            for reason in ("topology", "mesh", "opt", "no_shards"):
                assert fuser.bypasses.get(reason, 0) >= 1, (
                    reason,
                    fuser.bypasses,
                )
            # and after every probe the real path still fuses
            assert fuser.try_execute("i", calls, [0], ExecOptions()) is not None
        finally:
            ex.gang = None
            ex.cluster = None
            ex.mesh = None
            ex.close()

    def test_serial_and_single_call_never_reach_fuser(self, holder):
        seed_mixed(holder)
        oracle = oracle_of(holder)
        ex = Executor(holder, device_policy="always", dispatch_enabled=False)
        try:
            q = "Count(Row(f=1))Count(Row(f=2))"
            assert ex.execute("i", q, opt=ExecOptions(serial=True)) == (
                oracle.execute("i", q)
            )
            assert ex.execute("i", "Count(Row(f=1))") == oracle.execute(
                "i", "Count(Row(f=1))"
            )
            assert ex.fuser.stats()["fused_launches"] == 0
        finally:
            ex.close()
            oracle.close()

    def test_writes_bypass_fusion(self, holder):
        """A query containing any write runs the classic serial path —
        the fuser never sees it (cross-call ordering must hold)."""
        seed_mixed(holder)
        ex = Executor(holder, device_policy="always", dispatch_enabled=False)
        try:
            col = SHARD_WIDTH + 424242
            got = ex.execute("i", f"Set({col}, f=1)Count(Row(f=1))")
            assert got[0] is True
            assert ex.fuser.stats()["fused_launches"] == 0
            # the read in the same query already observes the write
            oracle = oracle_of(holder)
            assert got[1] == oracle.execute("i", "Count(Row(f=1))")[0]
        finally:
            ex.close()

    def test_cpu_policy_and_max_calls_bypass(self, holder):
        seed_mixed(holder, n_shards=1)
        ex = Executor(holder, device_policy="never", dispatch_enabled=False)
        try:
            assert (
                ex.fuser.try_execute("i", self._calls(), [0], ExecOptions())
                is None
            )
            assert ex.fuser.bypasses.get("cpu", 0) >= 1
        finally:
            ex.close()
        ex2 = Executor(
            holder, device_policy="always", dispatch_enabled=False,
            fusion_max_calls=1,
        )
        try:
            q = "Count(Row(f=1))Count(Row(f=2))"
            oracle = oracle_of(holder)
            assert ex2.execute("i", q) == oracle.execute("i", q)
            assert ex2.fuser.bypasses.get("too_many_calls", 0) >= 1
            assert ex2.fuser.stats()["fused_launches"] == 0
        finally:
            ex2.close()

    def test_lowering_failure_degrades_to_classic_path(self, holder):
        """A fuser that blows up mid-flight must not surface: reads are
        pure, so the classic path re-runs and answers correctly."""
        seed_mixed(holder)
        oracle = oracle_of(holder)
        ex = Executor(holder, device_policy="always", dispatch_enabled=False)
        try:
            ex.fuser._lower_and_launch = lambda *a, **k: 1 / 0
            q = "Count(Row(f=1))Count(Row(f=2))"
            assert ex.execute("i", q) == oracle.execute("i", q)
            assert ex.fuser.bypasses.get("error", 0) >= 1
        finally:
            ex.close()
            oracle.close()


# -- injected device faults (ISSUE 14) --------------------------------------


class TestDeviceFaultDegrade:
    """The chaos hooks against the REAL fused path: a poisoned jit
    lowering and an injected launch OOM both land on the classic
    per-call path (or recover in place) bit-identical to the oracle —
    never a wrong answer, never an unhandled 500."""

    def test_poisoned_lowering_degrades_to_classic_path(self, holder):
        seed_mixed(holder)
        oracle = oracle_of(holder)
        want = oracle.execute("i", GAUNTLET)
        ex = Executor(holder, device_policy="always", dispatch_enabled=False)
        try:
            chaos.install_device_faults("poison_every=1")
            assert ex.execute("i", GAUNTLET) == want
            assert ex.fuser.stats()["fused_launches"] == 0
            assert ex.fuser.bypasses.get("error", 0) >= 1
            assert chaos.FAULTS.injected >= 1
            # clearing the schedule restores the fused path untouched
            chaos.install_device_faults("")
            assert ex.execute("i", GAUNTLET) == want
            assert ex.fuser.stats()["fused_launches"] >= 1
        finally:
            chaos.install_device_faults("")
            ex.close()
            oracle.close()

    def test_injected_launch_oom_recovers_via_evict_and_retry(self, holder):
        """oom_every=N>1: the injected RESOURCE_EXHAUSTED fires inside
        the attempted launch, the recovery sweep + single retry
        re-consults the counter and passes — every OOM recovers in
        place, nothing degrades, results stay bit-identical."""
        seed_mixed(holder)
        oracle = oracle_of(holder)
        want = oracle.execute("i", GAUNTLET)
        ex = Executor(holder, device_policy="always", dispatch_enabled=False)
        try:
            base = metrics.snapshot().get("device.oom_recovered;path:retry", 0)
            chaos.install_device_faults("oom_every=2")
            for rep in range(4):
                assert ex.execute("i", GAUNTLET) == want, rep
            assert chaos.FAULTS.injected >= 1
            st = ex._oom.stats()
            assert st["ooms"] >= 1 and st["recovered"] == st["ooms"]
            assert st["degraded"] == 0  # no CPU degrade, no health trip
            assert (
                metrics.snapshot().get("device.oom_recovered;path:retry", 0)
                > base
            )
        finally:
            chaos.install_device_faults("")
            ex.close()
            oracle.close()

    def test_unrecoverable_launch_oom_degrades_to_cpu_leg(self, holder):
        """oom_every=1: the retry OOMs too, so the call degrades to the
        CPU roaring leg (DeviceOom rides the DeviceDown fallback) and
        the post-OOM cooldown forces later calls CPU-side — answers
        still bit-identical."""
        seed_mixed(holder)
        oracle = oracle_of(holder)
        want = oracle.execute("i", GAUNTLET)
        ex = Executor(holder, device_policy="always", dispatch_enabled=False)
        try:
            base = metrics.snapshot().get("device.oom_cpu_degrades", 0)
            chaos.install_device_faults("oom_every=1")
            assert ex.execute("i", GAUNTLET) == want
            st = ex._oom.stats()
            assert st["degraded"] >= 1
            assert metrics.snapshot().get("device.oom_cpu_degrades", 0) > base
            assert ex._cpu_forced()  # the cooldown holds the CPU leg
            # and the NEXT query never touches the device at all
            n0 = chaos.FAULTS._kernels
            assert ex.execute("i", GAUNTLET) == want
            assert chaos.FAULTS._kernels == n0
        finally:
            chaos.install_device_faults("")
            ex.close()
            oracle.close()


# -- observability ----------------------------------------------------------


class TestObservability:
    def test_fusion_metrics_emitted(self, holder):
        seed_mixed(holder)
        base = metrics.snapshot().get(metrics.FUSION_FUSED_LAUNCHES, 0)
        ex = Executor(holder, device_policy="always", dispatch_enabled=False)
        try:
            ex.execute("i", "Count(Row(f=1))Count(Row(f=2))")
        finally:
            ex.close()
        snap = metrics.snapshot()
        assert snap.get(metrics.FUSION_FUSED_LAUNCHES, 0) > base
        assert any(
            k.startswith(metrics.FUSION_BYTES_RETURNED) for k in snap
        )

    def test_stats_shape(self, holder):
        seed_mixed(holder, n_shards=1)
        ex = Executor(
            holder, device_policy="always", dispatch_enabled=False,
            plan_cache=PlanCache(),
        )
        try:
            ex.execute("i", "Count(Row(f=1))Count(Row(f=2))")
            st = ex.fuser.stats()
            for key in (
                "enabled", "max_calls", "fused_launches", "fused_calls",
                "avg_calls_per_launch", "bytes_returned", "cache_served",
                "programs", "bypasses", "device_cache",
            ):
                assert key in st, key
            assert st["device_cache"]["enabled"] is True
            assert st["device_cache"]["max_bytes"] > 0
            # dispatch snapshot carries the fusion block too
            ds = ex.dispatch_engine.stats() if ex.dispatch_engine else None
            assert ds is None or "fusion" in ds
        finally:
            ex.close()


class TestServerSurface:
    def _mkserver(self, tmp_path, **cfg_kwargs):
        from pilosa_tpu.server import Config, Server

        cfg = Config(
            data_dir=str(tmp_path / "data"),
            bind="127.0.0.1:0",
            metric="expvar",
            device_policy="never",
            device_timeout=0,
            **cfg_kwargs,
        )
        s = Server(cfg)
        s.open()
        return s

    def _get(self, s, path):
        with urllib.request.urlopen(s.uri + path) as resp:
            return resp.read()

    def test_debug_fusion_endpoint_and_config_knobs(self, tmp_path):
        s = self._mkserver(tmp_path, fusion_max_calls=32)
        try:
            assert s.executor.fuser is not None
            assert s.executor.fuser.max_calls == 32
            snap = json.loads(self._get(s, "/debug/fusion"))
            assert snap["enabled"] is True
            for key in ("fused_launches", "bypasses", "device_cache"):
                assert key in snap
            # dispatch snapshot embeds the fusion block
            dsnap = json.loads(self._get(s, "/debug/dispatch"))
            assert "fusion" in dsnap
            # knobs round-trip through TOML
            toml = s.config.to_toml()
            assert "fusion-enabled = true" in toml
            assert "fusion-max-calls = 32" in toml
            assert "plan-cache-device-bytes" in toml
        finally:
            s.close()

    def test_fusion_disabled_config(self, tmp_path):
        s = self._mkserver(tmp_path, fusion_enabled=False)
        try:
            assert s.executor.fuser is None
            assert json.loads(self._get(s, "/debug/fusion")) == {
                "enabled": False
            }
        finally:
            s.close()


def test_docs_document_fusion_knobs_with_current_defaults():
    """docs/configuration.md names every fusion knob with the default
    the code actually uses, and docs/administration.md keeps the
    Device-resident execution section — both directions of drift."""
    import os

    from pilosa_tpu.server import Config

    cfg = Config(data_dir="x")
    root = os.path.join(os.path.dirname(__file__), "..", "docs")
    with open(os.path.join(root, "configuration.md")) as f:
        conf = f.read()
    for knob, default in (
        ("fusion-enabled", "true" if cfg.fusion_enabled else "false"),
        ("fusion-max-calls", str(cfg.fusion_max_calls)),
        ("plan-cache-device-bytes", str(cfg.plan_cache_device_bytes)),
    ):
        assert f"| `{knob}` | {default} |" in conf, knob
    with open(os.path.join(root, "administration.md")) as f:
        admin = f.read()
    assert "## Device-resident execution" in admin
    assert "/debug/fusion" in admin
