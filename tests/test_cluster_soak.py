"""2-process cluster liveness soak (slow): the HTTP probe plane across
real OS processes — no spurious DOWN under load, bounded DOWN verdict
after SIGKILL, post-restart convergence (dryrun_cluster_soak.py;
VERDICT r5 weak #5). Loopback in-process tests cover the logic; this
covers the timing."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_two_process_liveness_soak():
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "dryrun_cluster_soak.py"),
            "--soak-seconds",
            "20",
            "--no-artifact",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        env={
            k: v
            for k, v in os.environ.items()
            if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
        },
    )
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:])
    summary = json.loads(proc.stdout[proc.stdout.index("{") :])
    assert summary["ok"] is True
    assert summary["soak"]["spurious_down_verdicts"] == []
    assert summary["soak"]["load_queries_ok"] > 0
    assert summary["kill"]["down_verdict_seconds"] is not None
    assert summary["kill"]["down_verdict_seconds"] <= summary["kill"]["bound_seconds"]
    assert summary["rejoin"]["converged_seconds"] is not None
