"""Device-policy autotune tests (executor/autotune.py): the crossover
comes from measured dispatch RTT vs per-container CPU cost, a high-RTT
rig routes small queries to CPU with NO env var, and a wedged device
never stalls startup."""


from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import Holder
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.autotune import (
    MAX_CROSSOVER,
    MIN_CROSSOVER,
    autotune_executor,
    measure_cpu_container_ms,
    tuned_min_containers,
)


class TestCrossoverMath:
    def test_high_rtt_rig(self):
        # the AUTOTUNE.json measurements: 66 ms dispatch, 0.018 ms/ctr
        got = tuned_min_containers(dispatch_ms=66.0, cpu_ms_per_container=0.018)
        assert 3000 <= got <= 4000, got

    def test_colocated_rig(self):
        got = tuned_min_containers(dispatch_ms=1.5, cpu_ms_per_container=0.018)
        assert 50 <= got <= 120, got

    def test_clamps(self):
        assert tuned_min_containers(0.0001, 10.0) == MIN_CROSSOVER
        assert tuned_min_containers(1e9, 0.001) == MAX_CROSSOVER

    def test_unmeasurable_device_keeps_none(self, monkeypatch):
        from pilosa_tpu.executor import autotune

        monkeypatch.setattr(autotune, "measure_dispatch_ms", lambda **kw: None)
        assert tuned_min_containers(cpu_ms_per_container=0.02) is None

    def test_cpu_measurement_is_sane(self):
        ms = measure_cpu_container_ms(reps=3)
        assert 0.0001 < ms < 10.0, ms


class TestExecutorAdoption:
    def _executor(self):
        h = Holder()
        idx = h.create_index("i")
        idx.create_field("f")
        for r in range(4):
            for c in range(0, SHARD_WIDTH, SHARD_WIDTH // 64):
                h.field("i", "f").set_bit(r, c)
        return Executor(h, device_policy="auto")

    def test_high_rtt_routes_small_queries_to_cpu_without_env(self):
        ex = self._executor()
        # simulated deployment measurement: tunneled chip
        autotune_executor(
            ex, blocking=True,
            measure=lambda: tuned_min_containers(66.0, 0.018),
        )
        assert ex.auto_min_containers > 3000
        from pilosa_tpu.pql import parse

        call = parse("Count(Row(f=1))").calls[0]
        assert not ex._use_device("i", call.children[0], 0)

    def test_colocated_routes_same_query_to_device(self):
        ex = self._executor()
        autotune_executor(
            ex, blocking=True,
            measure=lambda: tuned_min_containers(1.0, 0.018),
        )
        assert ex.auto_min_containers <= 64

    def test_unmeasurable_keeps_default(self):
        ex = self._executor()
        before = ex.auto_min_containers
        autotune_executor(ex, blocking=True, measure=lambda: None)
        assert ex.auto_min_containers == before

    def test_async_thread_lands(self):
        ex = self._executor()
        t = autotune_executor(ex, measure=lambda: 1234)
        t.join(timeout=10)
        assert ex.auto_min_containers == 1234
