"""SPMD serving-path tests: queries through the executor with a device
mesh configured must be bit-identical to the CPU roaring path.

The reference distributes per-shard work over nodes with HTTP
scatter-gather (reference executor.go:1444-1593); here the same shard
set runs as shard_map programs over an 8-virtual-device CPU mesh
(conftest.py) with psum/all_gather collectives. Odd shard counts
exercise the mesh padding in Executor._shard_plan.
"""

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import FieldOptions, Holder
from pilosa_tpu.core.field import FIELD_TYPE_INT
from pilosa_tpu.executor import Executor
from pilosa_tpu.parallel.spmd import make_mesh


N_SHARDS = 5  # deliberately not a multiple of the 8-device mesh


@pytest.fixture(scope="module")
def loaded_holder():
    rng = np.random.default_rng(7)
    h = Holder()
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("general")
    intf = idx.create_field("val", FieldOptions(type=FIELD_TYPE_INT, min=0, max=1000))
    # ~40 rows x 5 shards of set bits; int values on a spread of columns
    for _ in range(900):
        row = int(rng.integers(0, 40))
        col = int(rng.integers(0, N_SHARDS * SHARD_WIDTH))
        f.set_bit(row, col)
    for _ in range(400):
        col = int(rng.integers(0, N_SHARDS * SHARD_WIDTH))
        intf.set_value(col, int(rng.integers(0, 1000)))
    return h


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


@pytest.fixture(scope="module")
def cpu_exec(loaded_holder):
    return Executor(loaded_holder, device_policy="never")


@pytest.fixture(scope="module")
def spmd_exec(loaded_holder, mesh):
    e = Executor(loaded_holder, device_policy="always", mesh=mesh)
    assert e.stager.mesh is mesh
    return e


QUERIES = [
    "Count(Row(general=1))",
    "Count(Intersect(Row(general=1), Row(general=2)))",
    "Count(Union(Row(general=1), Row(general=2), Row(general=3)))",
    "Count(Xor(Row(general=4), Row(general=5)))",
    "Count(Difference(Row(general=6), Row(general=7)))",
    "Sum(field=val)",
    "Sum(Row(general=1), field=val)",
    "Count(Range(val > 250))",
    "Count(Range(val >< [100, 800]))",
    "Sum(Range(val <= 500), field=val)",
    "TopN(general, n=5)",
    "TopN(general, Row(general=1), n=5)",
    "TopN(general, Row(general=2), n=3, threshold=2)",
    "TopN(general, Union(Row(general=1), Row(general=3)), n=7)",
]


@pytest.mark.parametrize("q", QUERIES)
def test_spmd_matches_cpu(cpu_exec, spmd_exec, q):
    want = cpu_exec.execute("i", q)
    got = spmd_exec.execute("i", q)
    assert _normalize(got) == _normalize(want), q


def _normalize(results):
    out = []
    for r in results:
        if hasattr(r, "columns"):
            out.append(list(r.columns()))
        else:
            out.append(r)
    return out


def test_spmd_kernels_reached(spmd_exec):
    """The mesh path must actually lower through the shard_map kernels,
    not silently fall back to per-shard dispatch."""
    spmd_exec.execute("i", "Count(Row(general=1))")
    spmd_exec.execute("i", "Sum(field=val)")
    spmd_exec.execute("i", "TopN(general, Row(general=1), n=5)")
    kinds = {k[0] for k in spmd_exec._spmd_kernels}
    assert {"count", "plane_counts", "topn_scores_sparse"} <= kinds


def test_spmd_pass2_reuses_pass1_scores(cpu_exec, spmd_exec, monkeypatch):
    """TopN pass 2 must be served from the cross-pass score carry on
    the mesh path too — pass 1 scores every cache candidate, so the
    exact-count pass never needs a second shard_map dispatch."""
    q = "TopN(general, Row(general=1), n=5)"
    want = cpu_exec.execute("i", q)
    spmd_exec.execute("i", q)  # warm staging + compile

    calls = []
    orig = spmd_exec._spmd_kernel

    def spy(kind, *statics):
        fn = orig(kind, *statics)
        if kind != "topn_scores_sparse":
            return fn

        def wrapped(*a, **kw):
            calls.append(kind)
            return fn(*a, **kw)

        return wrapped

    monkeypatch.setattr(spmd_exec, "_spmd_kernel", spy)
    got = spmd_exec.execute("i", q)
    assert _normalize(got) == _normalize(want)
    assert calls == ["topn_scores_sparse"]  # pass 1, one chunk, pass 2 carried


def test_spmd_topn_staging_is_lazy_and_bounded(mesh):
    """At a candidate count far beyond the walk's pruning point, the
    mesh path must stage only the chunks the ranked walk reaches —
    NOT every ranked-cache candidate (the eager predecessor staged
    k × S × 128 KB dense; VERDICT r4 missing #1). Skewed counts make
    the walk prune inside the head chunk."""
    from pilosa_tpu.executor.executor import FIRST_CHUNK, SCORE_CHUNK

    h = Holder()
    h.open()
    idx = h.create_index("lazy")
    f = idx.create_field("g")
    # two shards; a skewed head: rows 0/1 heavy, then a long tail of
    # light rows — the ranked walk resolves TopN inside the head
    for shard in range(2):
        for row in range(2):
            for j in range(60):
                f.set_bit(row, shard * SHARD_WIDTH + j)
        for row in range(2, 700):
            f.set_bit(row, shard * SHARD_WIDTH + (row % SHARD_WIDTH))
    cpu = Executor(h, device_policy="never")
    dev = Executor(h, device_policy="always", mesh=mesh)
    q = "TopN(g, Row(g=0), n=2)"
    want = cpu.execute("lazy", q)
    got = dev.execute("lazy", q)
    assert _normalize(got) == _normalize(want)
    # staged sparse stacks must cover at most the head chunk (pass 1)
    staged_chunks = [
        key for key in dev.stager._cache if "sparse_rows_stack" in key
    ]
    assert staged_chunks, "mesh TopN did not stage sparse chunks"
    sizes = {key[-2] for key in staged_chunks}
    assert sizes <= {FIRST_CHUNK, SCORE_CHUNK}
    # the walk pruned early: nothing close to the 700-candidate cache
    # was staged in one piece
    total_staged_rows = sum(key[-2] for key in staged_chunks)
    assert total_staged_rows <= FIRST_CHUNK + SCORE_CHUNK


def test_stack_is_mesh_sharded(spmd_exec, mesh):
    """Staged shard stacks carry a NamedSharding over the mesh axis."""
    spmd_exec.execute("i", "Count(Row(general=1))")
    staged = [
        e.value
        for (key, e) in spmd_exec.stager._cache.items()
        if "row_stack" in key
    ]
    assert staged, "row_stack was not staged"
    sharding = staged[-1].sharding
    assert getattr(sharding, "mesh", None) is not None


def test_http_server_with_mesh(tmp_path):
    """End-to-end: HTTP query against a server configured with
    mesh_devices=all answers identically to a meshless server."""
    import json
    from urllib.request import Request, urlopen

    from pilosa_tpu.server.config import Config
    from pilosa_tpu.server.server import Server

    def post(uri, path, body):
        req = Request(uri + path, data=body.encode(), method="POST")
        with urlopen(req) as resp:
            return json.loads(resp.read())

    results = {}
    for name, mesh_devices, policy in [
        ("cpu", 0, "never"),
        ("mesh", "all", "always"),
    ]:
        cfg = Config(
            data_dir=str(tmp_path / name),
            bind="127.0.0.1:0",
            mesh_devices=mesh_devices,
            device_policy=policy,
            metric="none",
            anti_entropy_interval=0,
        )
        srv = Server(cfg)
        srv.open()
        try:
            uri = srv.uri
            post(uri, "/index/i", "{}")
            post(uri, "/index/i/field/f", "{}")
            sets = "".join(
                f"Set({c}, f={r})"
                for r, c in [
                    (1, 1),
                    (1, SHARD_WIDTH + 5),
                    (1, 3 * SHARD_WIDTH + 7),
                    (2, 1),
                    (2, 2 * SHARD_WIDTH),
                    (3, 3 * SHARD_WIDTH + 7),
                ]
            )
            post(uri, "/index/i/query", sets)
            results[name] = [
                post(uri, "/index/i/query", "Count(Row(f=1))"),
                post(uri, "/index/i/query", "TopN(f, Row(f=1), n=3)"),
                post(uri, "/index/i/query", "Count(Union(Row(f=1), Row(f=2)))"),
            ]
        finally:
            srv.close()
    assert results["mesh"] == results["cpu"]
    assert results["cpu"][0]["results"] == [3]
