"""Reference data-directory compatibility: protobuf .meta decoding and
opening a reference-shaped tree (roaring fragments + proto metadata)."""

import os
import shutil

import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.utils.protometa import (
    decode_field_options,
    decode_index_meta,
    encode_field_options,
    encode_index_meta,
)

REFERENCE_FIXTURE = "/root/reference/testdata/sample_view/0"


def test_field_options_roundtrip():
    opts = {
        "type": "int",
        "cacheType": "ranked",
        "cacheSize": 50000,
        "timeQuantum": "",
        "min": -100,
        "max": 2048,
        "keys": True,
    }
    data = encode_field_options(opts)
    got = decode_field_options(data)
    assert got == opts


def test_index_meta_roundtrip():
    assert decode_index_meta(encode_index_meta(True)) == {"keys": True}
    assert decode_index_meta(encode_index_meta(False)) == {"keys": False}
    assert decode_index_meta(b"") == {"keys": False}


def test_open_reference_style_data_dir(tmp_path):
    """Build a data dir shaped like the reference's (proto .meta files,
    roaring fragment) and open it with our Holder."""
    if not os.path.exists(REFERENCE_FIXTURE):
        pytest.skip("reference fixture unavailable")
    root = tmp_path / "data"
    field_dir = root / "myindex" / "myfield"
    frag_dir = field_dir / "views" / "standard" / "fragments"
    frag_dir.mkdir(parents=True)
    (root / "myindex" / ".meta").write_bytes(encode_index_meta(False))
    (field_dir / ".meta").write_bytes(
        encode_field_options(
            {"type": "set", "cacheType": "ranked", "cacheSize": 50000}
        )
    )
    shutil.copy(REFERENCE_FIXTURE, frag_dir / "0")
    os.chmod(frag_dir / "0", 0o644)

    h = Holder(str(root))
    h.open()
    try:
        idx = h.index("myindex")
        assert idx is not None and not idx.keys
        f = idx.field("myfield")
        assert f is not None and f.options.type == "set"
        frag = h.fragment("myindex", "myfield", "standard", 0)
        assert frag is not None
        assert frag.storage.count() == 35001
        # query a row out of the reference-written fragment
        assert frag.row(0).count() >= 0
    finally:
        h.close()
