"""Reference data-directory compatibility: protobuf .meta decoding and
opening a reference-shaped tree (roaring fragments + proto metadata)."""

import os
import shutil

import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.utils.protometa import (
    decode_field_options,
    decode_index_meta,
    encode_field_options,
    encode_index_meta,
)

REFERENCE_FIXTURE = "/root/reference/testdata/sample_view/0"


def test_field_options_roundtrip():
    opts = {
        "type": "int",
        "cacheType": "ranked",
        "cacheSize": 50000,
        "timeQuantum": "",
        "min": -100,
        "max": 2048,
        "keys": True,
    }
    data = encode_field_options(opts)
    got = decode_field_options(data)
    assert got == opts


def test_index_meta_roundtrip():
    assert decode_index_meta(encode_index_meta(True)) == {"keys": True}
    assert decode_index_meta(encode_index_meta(False)) == {"keys": False}
    assert decode_index_meta(b"") == {"keys": False}


def test_open_reference_style_data_dir(tmp_path):
    """Build a data dir shaped like the reference's (proto .meta files,
    roaring fragment) and open it with our Holder."""
    if not os.path.exists(REFERENCE_FIXTURE):
        pytest.skip("reference fixture unavailable")
    root = tmp_path / "data"
    field_dir = root / "myindex" / "myfield"
    frag_dir = field_dir / "views" / "standard" / "fragments"
    frag_dir.mkdir(parents=True)
    (root / "myindex" / ".meta").write_bytes(encode_index_meta(False))
    (field_dir / ".meta").write_bytes(
        encode_field_options(
            {"type": "set", "cacheType": "ranked", "cacheSize": 50000}
        )
    )
    shutil.copy(REFERENCE_FIXTURE, frag_dir / "0")
    os.chmod(frag_dir / "0", 0o644)

    h = Holder(str(root))
    h.open()
    try:
        idx = h.index("myindex")
        assert idx is not None and not idx.keys
        f = idx.field("myfield")
        assert f is not None and f.options.type == "set"
        frag = h.fragment("myindex", "myfield", "standard", 0)
        assert frag is not None
        assert frag.storage.count() == 35001
        # query a row out of the reference-written fragment
        assert frag.row(0).count() >= 0
    finally:
        h.close()


def test_cache_file_reference_protobuf_roundtrip(tmp_path):
    """.cache files use the reference's protobuf Cache{IDs} format and
    still read this framework's legacy JSON files."""
    from pilosa_tpu.core.cache import decode_cache, read_cache, write_cache

    p = str(tmp_path / "frag.cache")
    write_cache(p, [3, 1, 500000])
    data = open(p, "rb").read()
    assert data[:1] != b"["  # not JSON
    assert read_cache(p) == [3, 1, 500000]
    # packed field 1 decodes identically via protoc's canonical codec shape
    assert decode_cache(data) == [3, 1, 500000]
    # legacy JSON still accepted
    (tmp_path / "old.cache").write_text("[7, 9]")
    assert read_cache(str(tmp_path / "old.cache")) == [7, 9]
    # empty file → empty cache
    (tmp_path / "empty.cache").write_bytes(b"")
    assert read_cache(str(tmp_path / "empty.cache")) == []


def test_fragment_tar_archive_roundtrip(tmp_path):
    """marshal_fragment emits the reference's tar(data,cache) archive;
    unmarshal restores storage AND the TopN cache, and still accepts
    raw roaring bytes."""
    import io
    import tarfile

    from pilosa_tpu.executor import Executor
    from pilosa_tpu.server.api import API

    h = Holder(str(tmp_path / "a"))
    h.open()
    api = API(h, Executor(h))
    api.create_index("t")
    api.create_field("t", "f", {"type": "set"})
    f = h.field("t", "f")
    f.import_bits([1, 1, 2], [10, 11, 12])
    blob = api.marshal_fragment("t", "f", "standard", 0)
    with tarfile.open(fileobj=io.BytesIO(blob)) as tr:
        # "digest" extends the reference format: the receiver verifies
        # the data member against it before replacing anything
        assert {m.name for m in tr.getmembers()} == {"data", "cache", "digest"}
        import hashlib

        data = tr.extractfile("data").read()
        digest = tr.extractfile("digest").read().decode()
        assert digest == hashlib.blake2b(data, digest_size=16).hexdigest()

    h2 = Holder(str(tmp_path / "b"))
    h2.open()
    api2 = API(h2, Executor(h2))
    api2.create_index("t")
    api2.create_field("t", "f", {"type": "set"})
    api2.unmarshal_fragment("t", "f", "standard", 0, blob)
    frag = h2.fragment("t", "f", "standard", 0)
    assert frag.storage.count() == 3
    assert sorted(frag.cache.ids()) == [1, 2]  # cache restored from tar
    # raw roaring bytes (pre-tar wire format) still restore
    api2.unmarshal_fragment(
        "t", "f", "standard", 0, h.fragment("t", "f", "standard", 0).storage.to_bytes()
    )
    assert h2.fragment("t", "f", "standard", 0).storage.count() == 3
    h.close()
    h2.close()
