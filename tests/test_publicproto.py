"""Public protobuf wire codec (reference internal/public.proto) and
HTTP content negotiation."""

import shutil
import subprocess

import pytest

from pilosa_tpu.utils import publicproto as pp

RESULTS = [
    {"columns": [1, 2, 1048577], "attrs": {"a": 1, "b": "x", "c": True, "d": 1.5}},
    [{"id": 5, "count": 9}, {"key": "k", "count": 2}],
    {"value": -3, "count": 4},
    12345,
    True,
    None,
]


def test_query_request_roundtrip():
    data = pp.encode_query_request(
        "TopN(f, n=5)", shards=[0, 3, 99], remote=True, exclude_columns=True
    )
    d = pp.decode_query_request(data)
    assert d["query"] == "TopN(f, n=5)"
    assert d["shards"] == [0, 3, 99]
    assert d["remote"] and d["excludeColumns"]
    assert not d["columnAttrs"] and not d["excludeRowAttrs"]


def test_query_response_roundtrip():
    data = pp.encode_query_response(RESULTS, [{"id": 8, "attrs": {"z": "w"}}])
    d = pp.decode_query_response(data)
    assert d["results"][0]["columns"] == [1, 2, 1048577]
    assert d["results"][0]["attrs"] == {"a": 1, "b": "x", "c": True, "d": 1.5}
    assert d["results"][1] == [{"id": 5, "count": 9}, {"key": "k", "count": 2}]
    assert d["results"][2] == {"value": -3, "count": 4}
    assert d["results"][3] == 12345
    assert d["results"][4] is True
    assert d["results"][5] is None
    assert d["columnAttrs"] == [{"id": 8, "attrs": {"z": "w"}}]


def test_import_request_roundtrip():
    data = pp.encode_import_request(
        "i", "f", 2, [1, 2], [3, 4], timestamps=[-1, 10**18], row_keys=["r"]
    )
    d = pp.decode_import_request(data)
    assert d["index"] == "i" and d["field"] == "f" and d["shard"] == 2
    assert d["rowIDs"] == [1, 2] and d["columnIDs"] == [3, 4]
    assert d["timestamps"] == [-1, 10**18]
    assert d["rowKeys"] == ["r"]


def test_import_value_request_roundtrip():
    data = pp.encode_import_value_request("i", "f", 0, [9], [-42])
    d = pp.decode_import_value_request(data)
    assert d["columnIDs"] == [9] and d["values"] == [-42]


PROTO_SPEC = """
syntax = "proto3";
package check;
message Row { repeated uint64 Columns = 1; repeated string Keys = 3; repeated Attr Attrs = 2; }
message Pair { uint64 ID = 1; string Key = 3; uint64 Count = 2; }
message ValCount { int64 Val = 1; int64 Count = 2; }
message Attr { string Key = 1; uint64 Type = 2; string StringValue = 3; int64 IntValue = 4; bool BoolValue = 5; double FloatValue = 6; }
message ColumnAttrSet { uint64 ID = 1; string Key = 3; repeated Attr Attrs = 2; }
message QueryRequest { string Query = 1; repeated uint64 Shards = 2; bool ColumnAttrs = 3; bool Remote = 5; bool ExcludeRowAttrs = 6; bool ExcludeColumns = 7; }
message QueryResponse { string Err = 1; repeated QueryResult Results = 2; repeated ColumnAttrSet ColumnAttrSets = 3; }
message QueryResult { uint32 Type = 6; Row Row = 1; uint64 N = 2; repeated Pair Pairs = 3; ValCount ValCount = 5; bool Changed = 4; }
message ImportRequest { string Index = 1; string Field = 2; uint64 Shard = 3; repeated uint64 RowIDs = 4; repeated uint64 ColumnIDs = 5; repeated string RowKeys = 7; repeated string ColumnKeys = 8; repeated int64 Timestamps = 6; }
message ImportValueRequest { string Index = 1; string Field = 2; uint64 Shard = 3; repeated uint64 ColumnIDs = 5; repeated string ColumnKeys = 7; repeated int64 Values = 6; }
"""


@pytest.fixture(scope="module")
def canonical_pb(tmp_path_factory):
    """protoc-generated canonical codec for the same message schema
    (field numbers/types per reference internal/public.proto:5-82)."""
    if shutil.which("protoc") is None:
        pytest.skip("protoc unavailable")
    pytest.importorskip("google.protobuf")
    d = tmp_path_factory.mktemp("pb")
    (d / "check.proto").write_text(PROTO_SPEC)
    subprocess.run(
        ["protoc", f"--python_out={d}", "check.proto"], cwd=d, check=True
    )
    import sys

    sys.path.insert(0, str(d))
    try:
        import check_pb2
    finally:
        sys.path.pop(0)
    return check_pb2


def test_wire_compat_with_canonical_protobuf(canonical_pb):
    pb = canonical_pb
    # our encode → canonical decode
    m = pb.QueryRequest()
    m.ParseFromString(pp.encode_query_request("Count(Row(f=1))", shards=[7]))
    assert m.Query == "Count(Row(f=1))" and list(m.Shards) == [7]

    r = pb.QueryResponse()
    r.ParseFromString(pp.encode_query_response(RESULTS))
    assert [x.Type for x in r.Results] == [1, 2, 3, 4, 5, 0]
    assert list(r.Results[0].Row.Columns) == [1, 2, 1048577]
    assert r.Results[1].Pairs[0].ID == 5 and r.Results[1].Pairs[1].Key == "k"
    assert r.Results[2].ValCount.Val == -3
    assert r.Results[3].N == 12345 and r.Results[4].Changed

    # canonical encode → our decode (unpacked or packed both fine)
    m2 = pb.ImportRequest(
        Index="i", Field="f", Shard=3, RowIDs=[1], ColumnIDs=[2], Timestamps=[-5]
    )
    d = pp.decode_import_request(m2.SerializeToString())
    assert d["shard"] == 3 and d["timestamps"] == [-5]

    # ImportValueRequest both directions against the canonical codec
    m3 = pb.ImportValueRequest()
    m3.ParseFromString(pp.encode_import_value_request("i", "f", 2, [9, 10], [-42, 7]))
    assert m3.Index == "i" and m3.Shard == 2
    assert list(m3.ColumnIDs) == [9, 10] and list(m3.Values) == [-42, 7]
    m4 = pb.ImportValueRequest(Index="x", Field="y", ColumnIDs=[1], Values=[5])
    d = pp.decode_import_value_request(m4.SerializeToString())
    assert d["columnIDs"] == [1] and d["values"] == [5]


def test_handler_content_negotiation(tmp_path):
    """POST protobuf QueryRequest + Accept protobuf → protobuf response."""
    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.server.api import API
    from pilosa_tpu.server.http_handler import Handler, RawResponse

    h = Holder(str(tmp_path))
    h.open()
    api = API(h, Executor(h))
    api.create_index("p")
    api.create_field("p", "f", {"type": "set"})
    handler = Handler(api)
    hdrs = {"Content-Type": pp.CONTENT_TYPE, "Accept": pp.CONTENT_TYPE}
    body = pp.encode_query_request("Set(1, f=1) Set(2, f=1) Row(f=1) Count(Row(f=1))")
    out = handler.handle("POST", "/index/p/query", {}, body, headers=hdrs)
    assert isinstance(out, RawResponse) and out.content_type == pp.CONTENT_TYPE
    d = pp.decode_query_response(out.data)
    assert d["results"][0] is True and d["results"][1] is True
    assert d["results"][2]["columns"] == [1, 2]
    assert d["results"][3] == 2

    # protobuf import
    imp = pp.encode_import_request("p", "f", 0, [4, 4], [10, 11])
    handler.handle(
        "POST", "/index/p/field/f/import", {}, imp, headers=hdrs
    )
    out = handler.handle(
        "POST", "/index/p/query", {}, pp.encode_query_request("Row(f=4)"), headers=hdrs
    )
    assert pp.decode_query_response(out.data)["results"][0]["columns"] == [10, 11]
    h.close()
