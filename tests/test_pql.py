"""PQL parser tests (mirrors reference pql/parser_test.go scenarios)."""

import pytest

from pilosa_tpu.pql import BETWEEN, Call, Condition, ParseError, parse


def one(q):
    query = parse(q)
    assert len(query.calls) == 1
    return query.calls[0]


class TestBasicCalls:
    def test_row(self):
        c = one("Row(stargazer=5)")
        assert c.name == "Row"
        assert c.args == {"stargazer": 5}
        assert c.field_arg() == "stargazer"
        assert c.uint_arg("stargazer") == (5, True)

    def test_set(self):
        c = one("Set(33, stargazer=5)")
        assert c.name == "Set"
        assert c.args == {"_col": 33, "stargazer": 5}

    def test_set_with_timestamp(self):
        c = one("Set(10, stargazer=1, 2017-01-02T03:04)")
        assert c.args == {
            "_col": 10,
            "stargazer": 1,
            "_timestamp": "2017-01-02T03:04",
        }

    def test_set_quoted_col(self):
        c = one('Set("foo", stargazer=5)')
        assert c.args["_col"] == "foo"

    def test_clear(self):
        c = one("Clear(10, stargazer=1)")
        assert c.name == "Clear"
        assert c.args == {"_col": 10, "stargazer": 1}

    def test_nested(self):
        c = one("Count(Intersect(Row(a=1), Row(b=2)))")
        assert c.name == "Count"
        assert len(c.children) == 1
        inner = c.children[0]
        assert inner.name == "Intersect"
        assert [ch.name for ch in inner.children] == ["Row", "Row"]
        assert inner.children[0].args == {"a": 1}
        assert inner.children[1].args == {"b": 2}

    def test_multiple_calls(self):
        q = parse("Set(1, f=2)Set(3, f=4)\nCount(Row(f=2))")
        assert [c.name for c in q.calls] == ["Set", "Set", "Count"]
        assert q.write_call_n() == 2

    def test_union_empty(self):
        c = one("Union()")
        assert c.name == "Union" and not c.children and not c.args


class TestTopN:
    def test_plain(self):
        c = one("TopN(stargazer, n=10)")
        assert c.args == {"_field": "stargazer", "n": 10}

    def test_with_child(self):
        c = one("TopN(stargazer, Row(language=5), n=3)")
        assert c.args == {"_field": "stargazer", "n": 3}
        assert c.children[0].name == "Row"

    def test_with_ids_and_filters(self):
        c = one(
            'TopN(f, Row(other=7), n=4, ids=[5,10,15], attrName="category", attrValues=["a","b"])'
        )
        assert c.args["ids"] == [5, 10, 15]
        assert c.args["attrName"] == "category"
        assert c.args["attrValues"] == ["a", "b"]
        assert c.uint_slice_arg("ids") == ([5, 10, 15], True)

    def test_no_args(self):
        c = one("TopN(f)")
        assert c.args == {"_field": "f"}


class TestRange:
    def test_condition_ops(self):
        for op in ("<", "<=", ">", ">=", "==", "!="):
            c = one(f"Range(bytes {op} 1000)")
            assert c.args == {"bytes": Condition(op, 1000)}
            assert c.has_condition_arg()

    def test_between_op(self):
        c = one("Range(bytes >< [10, 20])")
        assert c.args == {"bytes": Condition("><", [10, 20])}

    def test_conditional_form(self):
        # a < field < b  (see reference endConditional quirk)
        c = one("Range(4 < bytes < 1000)")
        assert c.args == {"bytes": Condition(BETWEEN, [5, 1000])}
        c = one("Range(4 <= bytes < 1000)")
        assert c.args == {"bytes": Condition(BETWEEN, [4, 1000])}
        # reference quirk: <= on the right increments high
        c = one("Range(4 <= bytes <= 1000)")
        assert c.args == {"bytes": Condition(BETWEEN, [4, 1001])}

    def test_neq_null(self):
        c = one("Range(bytes != null)")
        assert c.args == {"bytes": Condition("!=", None)}

    def test_timerange(self):
        c = one("Range(stargazer=1, 2010-01-01T00:00, 2017-03-02T03:00)")
        assert c.args == {
            "stargazer": 1,
            "_start": "2010-01-01T00:00",
            "_end": "2017-03-02T03:00",
        }

    def test_timerange_quoted(self):
        c = one('Range(f=1, "2010-01-01T00:00", "2017-03-02T03:00")')
        assert c.args["_start"] == "2010-01-01T00:00"


class TestAttrs:
    def test_set_row_attrs(self):
        c = one('SetRowAttrs(stargazer, 10, foo="bar", baz=123, active=true, quux=null)')
        assert c.args == {
            "_field": "stargazer",
            "_row": 10,
            "foo": "bar",
            "baz": 123,
            "active": True,
            "quux": None,
        }

    def test_set_column_attrs(self):
        c = one('SetColumnAttrs(10, foo="bar", x=1.5)')
        assert c.args == {"_col": 10, "foo": "bar", "x": 1.5}


class TestValues:
    def test_negative_and_float(self):
        c = one("Range(f > -10)")
        assert c.args == {"f": Condition(">", -10)}
        c = one("F(x=1.25, y=-0.5)")
        assert c.args == {"x": 1.25, "y": -0.5}

    def test_bare_word_value(self):
        c = one("F(x=hello-world)")
        assert c.args == {"x": "hello-world"}

    def test_list_value(self):
        c = one("F(x=[1, 2, 3])")
        assert c.args == {"x": [1, 2, 3]}

    def test_string_escapes(self):
        c = one('F(x="a\\"b")')
        assert c.args == {"x": 'a"b'}


class TestErrors:
    def test_unclosed(self):
        with pytest.raises(ParseError):
            parse("Row(")

    def test_bad_call(self):
        with pytest.raises(ParseError):
            parse("1234()")

    def test_garbage_tail(self):
        with pytest.raises(ParseError):
            parse("Row(f=1) garbage&^%")


class TestStringRoundtrip:
    def test_str(self):
        c = one("Count(Intersect(Row(a=1), Row(b=2)))")
        assert str(c) == "Count(Intersect(Row(a=1), Row(b=2)))"
        c = one("Range(bytes >< [10, 20])")
        assert "10" in str(c) and "20" in str(c)

    def test_every_call_shape_reparses(self):
        """parse(str(parse(q))) == parse(q) for EVERY call form — the
        remote-execution leg re-sends calls as text (reference
        remoteExec, executor.go:1393-1440), so a form that doesn't
        re-parse breaks every cross-node query using it (a TopN with a
        source child did exactly that before this contract existed)."""
        for q in [
            "Count(Intersect(Row(a=1), Row(b=2)))",
            "Union(Row(a=1), Row(b=2), Row(c=3))",
            'F(x="hello", y=[1,2,3], z=null)',
            "Set(33, stargazer=5)",
            "Set(33, stargazer=5, 2017-06-21T09:30)",
            'Set("alice", likes="pizza")',
            "Clear(33, stargazer=5)",
            "TopN(f, n=5)",
            "TopN(f, Row(g=2), n=5)",
            "TopN(f, Union(Row(g=1), Row(g=2)), n=3, threshold=7)",
            'TopN(f, n=2, attrName="cat", attrValues=["a","b"])',
            "TopN(f, Row(g=1), n=4, tanimotoThreshold=70)",
            "TopN(f, ids=[1,2,3])",
            'SetRowAttrs(f, 9, name="x", rank=3)',
            'SetColumnAttrs(7, active=true, score=1.5)',
            "Sum(field=v)",
            "Sum(Row(f=1), field=v)",
            "Min(field=v)",
            "Max(field=v)",
            "Range(v > 10)",
            "Range(v >< [10, 20])",
            "Range(v != null)",
            "Range(f=1, 2010-01-01T00:00, 2010-01-03T00:00)",
            # strings with quote/backslash/newline must re-parse to the
            # same value, never to different PQL (remote-leg injection)
            'SetRowAttrs(f, 9, name="pi\\"zza")',
            'SetRowAttrs(f, 9, name="a\\\\b")',
            'SetColumnAttrs(7, note="x\\", rank=999")',
            # reserved args on non-special calls (the parser's generic
            # fallback accepts them) must survive serialization
            "Row(_col=5)",
            # reserved args a special form's positional grammar doesn't
            # cover must render named, not vanish
            "Set(33, f=9, _row=7)",
            # floats must stay positional notation (no exponent) and
            # stay floats across the wire
            "SetColumnAttrs(7, score=0.0000001)",
            "SetColumnAttrs(7, big=123456789.5)",
        ]:
            c = one(q)
            assert one(str(c)) == c, (q, str(c))
        # exactness: the re-parsed float equals the original bit-for-bit
        c = one("SetColumnAttrs(7, score=0.0000001)")
        assert one(str(c)).args["score"] == 1e-07
        # integral floats must stay floats (1e22 has no '.' in its
        # positional rendering without the explicit suffix)
        from pilosa_tpu.pql.ast import Call, format_value

        assert format_value(1e22) == "10000000000000000000000.0"
        c = Call("SetColumnAttrs", {"_col": 7, "big": 1e22})
        back = one(str(c)).args["big"]
        assert isinstance(back, float) and back == 1e22
