"""HBM-pressure governance + OOM recovery + device fault injection
(ISSUE 14, executor/hbm.py + utils/chaos.py): the process-wide byte
ledger (tenant shares, tiered relief, fused-launch admission), the
double-budget overcommit regression (two caches can no longer jointly
exceed the pinned global budget), the evict → retry once → degrade
policy with health tripped only on repeat failure, device error
classification, and the deterministic DeviceFaultSpec / seeded
ChaosSchedule the soak harness replays from."""

import threading

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import FieldOptions, Holder
from pilosa_tpu.core.field import FIELD_TYPE_INT
from pilosa_tpu.executor import Executor
from pilosa_tpu.executor.devicehealth import DeviceDown
from pilosa_tpu.executor.hbm import (
    DeviceOom,
    HbmGovernor,
    OomRecovery,
    classify_device_error,
)
from pilosa_tpu.plan.cache import DevicePlanCache, PlanCache
from pilosa_tpu.utils import chaos, metrics
from pilosa_tpu.utils.chaos import (
    ChaosSchedule,
    DeviceFaultSpec,
    InjectedDeviceOom,
    InjectedPoisonError,
    install_device_faults,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    """No test leaks an installed device fault schedule."""
    yield
    install_device_faults("")


# -- error classification ----------------------------------------------------


class TestClassify:
    def test_alloc_markers(self):
        assert classify_device_error(RuntimeError("RESOURCE_EXHAUSTED: x")) == "alloc"
        assert classify_device_error(RuntimeError("Out of memory allocating")) == "alloc"
        assert classify_device_error(InjectedDeviceOom("RESOURCE_EXHAUSTED: i")) == "alloc"

    def test_wedge_by_type_name_and_marker(self):
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        assert classify_device_error(XlaRuntimeError("boom")) == "wedge"
        assert classify_device_error(RuntimeError("INTERNAL: stream")) == "wedge"
        assert classify_device_error(RuntimeError("DATA_LOSS on fetch")) == "wedge"

    def test_non_device_errors_stay_loud(self):
        assert classify_device_error(ValueError("bad shape")) is None
        assert classify_device_error(KeyError("f")) is None


# -- the byte ledger ---------------------------------------------------------


class TestGovernor:
    def test_budget_is_sum_of_shares_unless_pinned(self):
        gov = HbmGovernor()
        gov.register("a", share_bytes=100)
        gov.register("b", share_bytes=50)
        assert gov.budget() == 150
        pinned = HbmGovernor(budget_bytes=80)
        pinned.register("a", share_bytes=100)
        pinned.register("b", share_bytes=50)
        assert pinned.budget() == 80  # the double-budget overcommit fix

    def test_reserve_release_and_headroom(self):
        gov = HbmGovernor(budget_bytes=100)
        gov.register("a")
        assert gov.reserve("a", 60) is True
        assert gov.used("a") == 60 and gov.headroom() == 40
        gov.release("a", 25)
        assert gov.used() == 35
        gov.release("a", 10**9)  # floor at zero, never negative
        assert gov.used("a") == 0

    def test_reserve_over_budget_relieves_other_tenants_only(self):
        gov = HbmGovernor(budget_bytes=100)
        evicted = []

        def evict(need):
            evicted.append(need)
            gov.release("cache", min(need, gov.used("cache")))
            return need

        gov.register("cache", share_bytes=100, evict_fn=evict, tier=0)
        me_evicted = []
        gov.register(
            "me", share_bytes=100, evict_fn=lambda n: me_evicted.append(n) or 0,
            tier=1,
        )
        gov.reserve("cache", 90)
        # my reserve pushes the ledger over: the OTHER tenant relieves,
        # my own LRU loop is my job (exclude semantics)
        assert gov.reserve("me", 50) is True
        assert evicted and not me_evicted
        assert gov.over_budget() == 0

    def test_tier_order_device_cache_before_stager(self):
        gov = HbmGovernor(budget_bytes=100)
        order = []

        def tier0(need):
            order.append("device_cache")
            gov.release("device_cache", 40)
            return 40

        def tier1(need):
            order.append("stager")
            gov.release("stager", need)
            return need

        gov.register("device_cache", share_bytes=50, evict_fn=tier0, tier=0)
        gov.register("stager", share_bytes=50, evict_fn=tier1, tier=1)
        gov.reserve("device_cache", 40)
        gov.reserve("stager", 60)
        gov.register("transient")
        gov.reserve("transient", 60)  # 160 total: needs both tiers
        assert order[0] == "device_cache"
        assert gov.over_budget() == 0

    def test_admit_relieves_then_answers(self):
        gov = HbmGovernor(budget_bytes=100)
        gov.register(
            "cache", share_bytes=100, tier=0,
            evict_fn=lambda need: (gov.release("cache", 70), 70)[1],
        )
        gov.reserve("cache", 70)
        assert gov.admit(20) is True  # fits in headroom, no eviction
        assert gov.used("cache") == 70
        assert gov.admit(90) is True  # relieved tier 0 first
        assert gov.used("cache") == 0
        assert gov.admit(10**12) is False  # can never fit

    def test_reset_is_the_epoch_fence(self):
        gov = HbmGovernor(budget_bytes=100)
        gov.register("a")
        gov.register("b")
        gov.reserve("a", 30)
        gov.reserve("b", 40)
        gov.reset("a")
        assert gov.used("a") == 0 and gov.used("b") == 40
        gov.reset()
        assert gov.used() == 0

    def test_stats_shape(self):
        gov = HbmGovernor(budget_bytes=64)
        gov.register("a", share_bytes=64, tier=3)
        gov.reserve("a", 8)
        st = gov.stats()
        assert st["budget_bytes"] == 64 and st["used_bytes"] == 8
        assert st["tenants"]["a"] == {"used": 8, "share": 64, "tier": 3}


class TestDoubleBudgetOvercommit:
    """The PR 12 regression: stager and device plan cache each honored
    their OWN byte budget, so together they could overcommit the chip.
    With the governor pinned below the sum of shares, the joint ledger
    must stay under the GLOBAL budget — each cache evicting for the
    other's pressure."""

    def test_device_cache_respects_global_budget_below_its_share(self):
        gov = HbmGovernor(budget_bytes=1000)
        cache = DevicePlanCache(max_bytes=2000)  # share alone overcommits
        cache.set_governor(gov)
        # a second tenant (the stager's stand-in) holds most of the chip
        gov.register("stager", share_bytes=1000)
        gov.reserve("stager", 700)
        for i in range(10):
            cache.put(("k", i), (1,), object(), nbytes=100)
            assert gov.used() <= gov.budget(), (i, gov.stats())
        # the cache held itself far below its own 2000-byte share
        assert cache.bytes <= 300
        assert gov.used("device_cache") == cache.bytes

    def test_both_caches_jointly_bounded_under_pressure(self):
        gov = HbmGovernor(budget_bytes=500)
        cache = DevicePlanCache(max_bytes=400)
        cache.set_governor(gov)

        stager_held = {"n": 0}

        def stager_evict(need):
            freed = min(need, stager_held["n"])
            stager_held["n"] -= freed
            gov.release("stager", freed)
            return freed

        gov.register("stager", share_bytes=400, evict_fn=stager_evict, tier=1)
        for i in range(20):
            if i % 2:
                stager_held["n"] += 60
                gov.reserve("stager", 60)
                # the stager's own LRU loop: reserve excludes the
                # requester, so its share is its job (mirrors stager.put)
                while gov.over_budget() > 0 and stager_held["n"]:
                    stager_evict(gov.over_budget())
            else:
                cache.put(("k", i), (1,), object(), nbytes=60)
            assert gov.used() <= gov.budget(), (i, gov.stats())
        assert gov.used() == gov.used("device_cache") + gov.used("stager")

    def test_executor_wires_one_ledger_for_all_tenants(self):
        """End to end: a pinned global budget smaller than the shares'
        sum holds across real staged blocks + device plan cache."""
        h = Holder()
        h.open()
        rng = np.random.default_rng(5)
        idx = h.create_index("i")
        f = idx.create_field("f")
        v = idx.create_field(
            "v", FieldOptions(type=FIELD_TYPE_INT, min=-50, max=5000)
        )
        f.import_bits(
            rng.integers(0, 10, size=2000).tolist(),
            rng.integers(0, 2 * SHARD_WIDTH, size=2000).tolist(),
        )
        vcols = rng.choice(2 * SHARD_WIDTH, size=400, replace=False)
        v.import_values(vcols.tolist(), rng.integers(-50, 5000, size=400).tolist())
        gov = HbmGovernor(budget_bytes=32 << 20)
        ex = Executor(
            h, device_policy="always", dispatch_enabled=False,
            plan_cache=PlanCache(), governor=gov,
        )
        try:
            assert ex.governor is gov
            st = gov.stats()["tenants"]
            assert "stager" in st and "device_cache" in st
            q = (
                "Count(Intersect(Row(f=1), Row(f=2)))"
                "TopN(f, Intersect(Row(f=1), Row(f=2)), n=5)"
                'Sum(Row(f=3), field="v")'
            )
            for _ in range(3):
                ex.execute("i", q)
                assert gov.used() <= gov.budget(), gov.stats()
            # the ledger reflects real resident bytes
            assert gov.used("stager") == ex.stager._bytes
        finally:
            ex.close()


# -- OOM recovery policy -----------------------------------------------------


class _FakeHealth:
    def __init__(self):
        self.reasons = []

    def trip(self, reason):
        self.reasons.append(reason)


class TestOomRecovery:
    def test_alloc_failure_evicts_and_retries_once(self):
        gov = HbmGovernor(budget_bytes=100)
        swept = []
        gov.register(
            "cache", share_bytes=100, tier=0,
            evict_fn=lambda need: swept.append(need) or 0,
        )
        rec = OomRecovery(governor=gov)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: alloc failed")
            return 42

        assert rec.run(flaky, kind="kernel") == 42
        assert calls["n"] == 2 and swept  # the sweep ran before the retry
        assert rec.stats()["recovered"] == 1
        assert rec.stats()["degraded"] == 0

    def test_persistent_alloc_failure_degrades_to_cpu(self):
        degraded = []
        health = _FakeHealth()
        rec = OomRecovery(
            health=health, on_degrade=lambda: degraded.append(1), trip_after=2
        )

        def dead():
            raise RuntimeError("RESOURCE_EXHAUSTED: still full")

        with pytest.raises(DeviceOom) as ei:
            rec.run(dead, kind="fused_query")
        assert isinstance(ei.value, DeviceDown)  # rides the CPU fallback
        assert degraded == [1]
        assert health.reasons == []  # ONE failure never gates the device
        assert rec.stats()["degraded"] == 1

    def test_wedge_skips_retry_and_degrades(self):
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        calls = {"n": 0}

        def wedged():
            calls["n"] += 1
            raise XlaRuntimeError("INTERNAL: stream executor died")

        rec = OomRecovery()
        with pytest.raises(DeviceOom):
            rec.run(wedged)
        assert calls["n"] == 1  # retry is pointless for a wedge

    def test_repeat_degrades_trip_health(self):
        health = _FakeHealth()
        rec = OomRecovery(health=health, trip_after=2, window_s=30.0)

        def dead():
            raise RuntimeError("RESOURCE_EXHAUSTED")

        for _ in range(2):
            with pytest.raises(DeviceOom):
                rec.run(dead)
        assert health.reasons  # second unrecovered failure in the window

    def test_non_device_errors_propagate_untouched(self):
        rec = OomRecovery()
        with pytest.raises(ValueError):
            rec.run(lambda: (_ for _ in ()).throw(ValueError("shape bug")))
        assert rec.stats()["ooms"] == 0

    def test_recovery_is_thread_safe_bookkeeping(self):
        rec = OomRecovery()

        def one():
            try:
                rec.run(lambda: (_ for _ in ()).throw(
                    RuntimeError("RESOURCE_EXHAUSTED")
                ))
            except DeviceOom:
                pass

        ts = [threading.Thread(target=one) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        st = rec.stats()
        assert st["ooms"] == 8 and st["degraded"] == 8


# -- deterministic device fault injection ------------------------------------


class TestDeviceFaultSpec:
    def test_parse_roundtrip_and_unknown_knob(self):
        s = DeviceFaultSpec.parse(
            "oom_every=3,stall_every=5,stall_s=0.01,poison_every=2,after=4"
        )
        assert (s.oom_every, s.stall_every, s.poison_every, s.after) == (3, 5, 2, 4)
        assert s.stall_s == 0.01 and bool(s)
        assert not DeviceFaultSpec.parse("")
        with pytest.raises(ValueError):
            # check: disable=fault-spec (deliberately invalid knob — the ValueError is the assertion)
            DeviceFaultSpec.parse("explode_every=1")

    def test_oom_every_nth_kernel_is_deterministic(self):
        s = DeviceFaultSpec.parse("oom_every=2")
        s.on_kernel("k")  # 1: clean
        with pytest.raises(InjectedDeviceOom) as ei:
            s.on_kernel("k")  # 2: injected
        assert classify_device_error(ei.value) == "alloc"
        s.on_kernel("k")  # 3: clean — a retry right after the OOM passes
        with pytest.raises(InjectedDeviceOom):
            s.on_kernel("k")  # 4
        assert s.injected == 2

    def test_after_arms_late(self):
        s = DeviceFaultSpec.parse("oom_every=1,after=2")
        s.on_kernel("k")
        s.on_kernel("k")  # warmup window
        with pytest.raises(InjectedDeviceOom):
            s.on_kernel("k")

    def test_stall_injects_without_failing(self):
        s = DeviceFaultSpec.parse("stall_every=1,stall_s=0.0")
        s.on_kernel("k")
        assert s.injected == 1  # latency, never an error

    def test_poisoned_lowering(self):
        s = DeviceFaultSpec.parse("poison_every=2")
        s.on_lowering()
        with pytest.raises(InjectedPoisonError):
            s.on_lowering()

    def test_install_and_clear_process_schedule(self):
        install_device_faults("oom_every=7")
        assert chaos.FAULTS is not None and chaos.FAULTS.oom_every == 7
        install_device_faults("")
        assert chaos.FAULTS is None

    def test_injection_counts_metric(self):
        base = metrics.snapshot().get("device.faults_injected;fault:oom", 0)
        s = DeviceFaultSpec.parse("oom_every=1")
        with pytest.raises(InjectedDeviceOom):
            s.on_kernel("k")
        assert metrics.snapshot().get("device.faults_injected;fault:oom", 0) > base


class TestChaosSchedule:
    def test_seeded_schedule_is_reproducible(self):
        a = list(ChaosSchedule(seed=14, windows=6, duration_s=1.0))
        b = list(ChaosSchedule(seed=14, windows=6, duration_s=1.0))
        assert a == b
        assert a != list(ChaosSchedule(seed=15, windows=6, duration_s=1.0))

    def test_windows_cover_all_families_with_parsable_specs(self):
        from pilosa_tpu.core.fragment import StorageFaultSpec

        ws = list(ChaosSchedule(seed=3, windows=8))
        assert [w["name"].split("-", 1)[1] for w in ws] == [
            "storage", "device", "mixed", "bitrot",
            "storage", "device", "mixed", "bitrot",
        ]
        for w in ws:
            StorageFaultSpec.parse(w["storage"])  # empty parses clean too
            DeviceFaultSpec.parse(w["device"])
            if "mixed" in w["name"]:
                assert w["storage"] and w["device"]
            if "bitrot" in w["name"]:
                # the bit-rot window rides the storage injector (ISSUE 15)
                assert w["storage"].startswith("bitrot=")
