"""Plan result cache (ISSUE 4, plan/cache.py + plan/planner.py +
executor wiring): whole-call caching with generation-vector validity,
CSE subtree substitution, singleflight, byte-accounted LRU eviction,
epoch resets, the cache=false opt-out, write-path invalidation
completeness, and the randomized read/write interleaving bit-identity
bar (cached vs uncached oracle, 0 mismatches)."""

import json
import threading
import urllib.request

import numpy as np
import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.core import Holder
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.executor import ExecOptions, Executor
from pilosa_tpu.plan.cache import PlanCache
from pilosa_tpu.utils import metrics


@pytest.fixture()
def holder():
    h = Holder()  # in-memory
    h.open()
    return h


def seed(h, index="i", field="f", rows=8, bits=24):
    idx = h.create_index(index)
    fld = idx.create_field(field)
    r_ids, c_ids = [], []
    for r in range(rows):
        for c in range(bits + r):
            r_ids.append(r)
            c_ids.append((c * 131 + r * 17) % (1 << 20))
            r_ids.append(r)
            c_ids.append(SHARD_WIDTH + (c * 151 + r * 19) % (1 << 20))
    fld.import_bits(r_ids, c_ids)
    return fld


def cached_executor(h, **kw):
    pc = PlanCache(**kw)
    return Executor(h, device_policy="never", plan_cache=pc), pc


def norm(r):
    return r.columns().tolist() if hasattr(r, "columns") else r


# -- whole-call caching -----------------------------------------------------


def test_repeat_query_hits_and_stays_bit_identical(holder):
    seed(holder)
    ex, pc = cached_executor(holder)
    oracle = Executor(holder, device_policy="never")
    qs = [
        "Count(Intersect(Row(f=1), Row(f=2)))",
        "TopN(f, Row(f=3), n=4)",
        "Union(Row(f=1), Row(f=4))",
        "Sum(Row(f=2), field=f)",
    ]
    for _ in range(3):
        for q in qs:
            (got,) = ex.execute("i", q)
            (want,) = oracle.execute("i", q)
            assert str(norm(got)) == str(norm(want)), q
    st = pc.stats()
    assert st["misses"] == len(qs)
    assert st["hits"] >= 2 * len(qs)
    assert st["bytes"] > 0


def test_permuted_and_nested_spellings_share_one_entry(holder):
    seed(holder)
    ex, pc = cached_executor(holder)
    ex.execute("i", "Count(Intersect(Row(f=1), Row(f=2)))")
    ex.execute("i", "Count(Intersect(Row(f=2), Row(f=1)))")
    ex.execute("i", "Count(Union(Row(f=1), Union(Row(f=2), Row(f=3))))")
    ex.execute("i", "Count(Union(Row(f=3), Row(f=2), Row(f=1)))")
    st = pc.stats()
    assert st["misses"] == 2 and st["hits"] == 2


def test_same_schema_indexes_never_share_entries(holder):
    """Regression: cache keys carry the index name. Two indexes with
    identical field names and matching generation counts (same-schema
    tenant indexes right after a restart — generations start at 0 per
    process) must never serve each other's results. One bulk import
    each keeps the generation vectors identical while the data differs."""
    holder.create_index("tenant_a").create_field("f").import_bits([1], [10])
    holder.create_index("tenant_b").create_field("f").import_bits([1, 1], [20, 21])
    ex, pc = cached_executor(holder)
    q = "Count(Row(f=1))"
    assert ex.execute("tenant_a", q) == [1]
    assert ex.execute("tenant_b", q) == [2]  # the bug served 1 here
    # and both stay per-index on the hot path
    assert ex.execute("tenant_a", q) == [1]
    assert ex.execute("tenant_b", q) == [2]
    st = pc.stats()
    assert st["hits"] == 2 and st["misses"] == 2 and st["entries"] == 2


def test_failed_build_counts_a_miss(holder):
    pc = PlanCache()

    def build():
        raise ValueError("boom")

    with pytest.raises(ValueError):
        pc.get_or_build(("k",), lambda: ("g",), build)
    st = pc.stats()
    assert st["misses"] == 1 and st["entries"] == 0 and st["building"] == 0


def test_write_invalidates_and_result_reflects_new_state(holder):
    fld = seed(holder)
    ex, pc = cached_executor(holder)
    q = "Count(Row(f=1))"
    (before,) = ex.execute("i", q)
    (hit,) = ex.execute("i", q)
    assert hit == before and pc.stats()["hits"] == 1
    assert fld.set_bit(1, 777_777) is True  # new bit
    (after,) = ex.execute("i", q)
    assert after == before + 1
    assert pc.stats()["invalidations"] == 1
    # the new entry is valid again
    (again,) = ex.execute("i", q)
    assert again == after and pc.stats()["hits"] == 2


def test_cache_false_bypasses_lookup_and_insert(holder):
    seed(holder)
    ex, pc = cached_executor(holder)
    opt = ExecOptions(cache=False)
    ex.execute("i", "Count(Row(f=1))", opt=opt)
    ex.execute("i", "Count(Row(f=1))", opt=opt)
    st = pc.stats()
    assert st["hits"] == 0 and st["misses"] == 0 and st["entries"] == 0


def test_uncacheable_calls_never_insert(holder):
    seed(holder)
    ex, pc = cached_executor(holder)
    # writes never touch the cache
    ex.execute("i", "Set(123, f=1)")
    # attr-filtered TopN depends on attr stores (no generation counter):
    # repeated executions never hit
    ex.execute("i", 'TopN(f, Row(f=1), n=2, attrName="x", attrValues=[1])')
    ex.execute("i", 'TopN(f, Row(f=1), n=2, attrName="x", attrValues=[1])')
    assert pc.stats()["entries"] == 0 and pc.stats()["hits"] == 0


def test_byte_budget_evicts_lru(holder):
    seed(holder, rows=10)
    # size one entry first, then budget for ~2.5 of them
    ex0, pc0 = cached_executor(holder)
    ex0.execute("i", "Union(Row(f=0), Row(f=1))")
    per_entry = pc0.stats()["bytes"]
    assert per_entry > 0
    budget = int(per_entry * 2.5)
    ex, pc = cached_executor(holder, max_bytes=budget)
    for r in range(8):
        ex.execute("i", f"Union(Row(f={r}), Row(f={(r + 1) % 8}))")
    st = pc.stats()
    assert st["evictions"] > 0
    assert st["bytes"] <= budget
    assert st["entries"] < 8


def test_min_cost_filters_cheap_builds(holder):
    seed(holder)
    ex, pc = cached_executor(holder, min_cost=1e9)  # nothing qualifies
    ex.execute("i", "Count(Row(f=1))")
    ex.execute("i", "Count(Row(f=1))")
    st = pc.stats()
    assert st["entries"] == 0 and st["hits"] == 0 and st["misses"] == 2


def test_returned_rows_are_isolated_from_the_cache(holder):
    seed(holder)
    ex, pc = cached_executor(holder)
    (r1,) = ex.execute("i", "Union(Row(f=1), Row(f=2))")
    r1.set_bit(5)  # caller mutates its copy
    r1.keys = ["x"]
    (r2,) = ex.execute("i", "Union(Row(f=1), Row(f=2))")
    assert pc.stats()["hits"] == 1
    assert not r2.includes_column(5) or r2.includes_column(5) == (
        5 in r1.columns().tolist() and False
    )
    oracle = Executor(holder, device_policy="never")
    (want,) = oracle.execute("i", "Union(Row(f=1), Row(f=2))")
    assert r2.columns().tolist() == want.columns().tolist()


def test_singleflight_builds_once_for_concurrent_duplicates(holder):
    seed(holder)
    pc = PlanCache()
    builds = []
    gate = threading.Event()

    def build():
        builds.append(1)
        gate.wait(5)
        return 42

    key = ("h", (0,), (False, False))
    gv = lambda: ("g",)
    out = []
    ts = [
        threading.Thread(target=lambda: out.append(pc.get_or_build(key, gv, build)))
        for _ in range(6)
    ]
    for t in ts:
        t.start()
    gate.set()
    for t in ts:
        t.join()
    assert out == [42] * 6
    assert len(builds) == 1
    assert pc.stats()["hits"] == 5 and pc.stats()["misses"] == 1


def test_epoch_reset_clears_and_fences(holder):
    seed(holder)
    ex, pc = cached_executor(holder)
    ex.execute("i", "Count(Row(f=1))")
    assert pc.stats()["entries"] == 1
    ex._on_device_restore()  # the wedge-recovery hook
    st = pc.stats()
    assert st["entries"] == 0 and st["bytes"] == 0 and st["epoch"] == 1


# -- CSE: intra-query dedupe + cached-subtree feeding -----------------------


def test_repeated_subtree_across_calls_builds_once(holder):
    seed(holder)
    ex, pc = cached_executor(holder)
    q = (
        "Count(Intersect(Row(f=1), Row(f=2))) "
        "TopN(f, Intersect(Row(f=2), Row(f=1)), n=3)"
    )
    oracle = Executor(holder, device_policy="never")
    w = oracle.execute("i", q)  # expectation BEFORE the spy goes in
    shard_evals = []
    orig = Executor._bitmap_call_shard_cpu

    def spy(self, index, c, shard):
        shard_evals.append(c.name)
        return orig(self, index, c, shard)

    Executor._bitmap_call_shard_cpu = spy
    try:
        r = ex.execute("i", q)
    finally:
        Executor._bitmap_call_shard_cpu = orig
    assert r[0] == w[0] and r[1] == w[1]
    # the shared intersection was evaluated by ONE build: its per-shard
    # Intersect evaluations appear exactly once per shard (2 shards),
    # both consumers read the __cached placeholder instead
    assert shard_evals.count("Intersect") == 2
    assert shard_evals.count("__cached") >= 2


def test_cached_subtree_feeds_parent_only_cold_leg_recomputes(holder):
    seed(holder)
    ex, pc = cached_executor(holder)
    # seed the hot leg as a shared subtree (twice in one query)
    ex.execute(
        "i",
        "Count(Intersect(Row(f=1), Row(f=2))) "
        "Count(Union(Intersect(Row(f=1), Row(f=2)), Row(f=7)))",
    )
    hits0 = pc.stats()["hits"]
    # a NEW query shape containing the hot subtree: the probe feeds the
    # cached rows in; only the cold leg (Row(f=6)) evaluates
    (got,) = ex.execute(
        "i", "Count(Union(Intersect(Row(f=2), Row(f=1)), Row(f=6)))"
    )
    oracle = Executor(holder, device_policy="never")
    (want,) = oracle.execute(
        "i", "Count(Union(Intersect(Row(f=2), Row(f=1)), Row(f=6)))"
    )
    assert got == want
    assert pc.stats()["hits"] > hits0


@pytest.mark.parametrize("policy", ["never", "always"])
def test_cse_bit_identical_on_both_paths(holder, policy):
    seed(holder)
    pc = PlanCache()
    ex = Executor(holder, device_policy=policy, plan_cache=pc)
    oracle = Executor(holder, device_policy=policy)
    q = (
        "Count(Intersect(Row(f=1), Row(f=2))) "
        "Count(Intersect(Row(f=2), Row(f=1))) "
        "TopN(f, Intersect(Row(f=1), Row(f=2)), n=3)"
    )
    for _ in range(2):
        got = ex.execute("i", q)
        want = oracle.execute("i", q)
        assert [str(norm(g)) for g in got] == [str(norm(w)) for w in want]


# -- write-path invalidation completeness (ISSUE 4 satellite 3) -------------


def _mut_set_bit(h, fld, frag, api):
    fld.set_bit(1, 999_983)


def _mut_clear_bit(h, fld, frag, api):
    cols = frag.row(1).columns()
    assert frag.clear_bit(1, int(cols[0])) is True


def _mut_bulk_import(h, fld, frag, api):
    frag.bulk_import([1, 2, 3], [11, 22, 33])


def _mut_import_value(h, fld, frag, api):
    frag.import_value([5, 6], [3, 9], bit_depth=8)


def _mut_import_block_pairs(h, fld, frag, api):
    frag.import_block_pairs(
        np.array([1, 2], dtype=np.uint64), np.array([401, 402], dtype=np.uint64)
    )


def _mut_api_restore(h, fld, frag, api):
    blob = api.marshal_fragment("i", "f", VIEW_STANDARD, 0)
    api.unmarshal_fragment("i", "f", VIEW_STANDARD, 0, blob)


@pytest.mark.parametrize(
    "mutate",
    [
        _mut_set_bit,
        _mut_clear_bit,
        _mut_bulk_import,
        _mut_import_value,
        _mut_import_block_pairs,
        _mut_api_restore,
    ],
    ids=[
        "set_bit",
        "clear_bit",
        "bulk_import",
        "import_value",
        "import_block_pairs",
        "api_restore",
    ],
)
def test_every_write_path_bumps_generation_and_invalidates(holder, mutate):
    """The cache's correctness contract: EVERY write path bumps the
    fragment generation, and a planted plan-cache entry therefore
    invalidates on the next lookup."""
    from pilosa_tpu.server.api import API

    fld = seed(holder)
    ex, pc = cached_executor(holder)
    api = API(holder, ex)
    frag = holder.fragment("i", "f", VIEW_STANDARD, 0)
    q = "Count(Row(f=1))"
    ex.execute("i", q)  # plant
    (planted_hit,) = ex.execute("i", q)
    assert pc.stats()["hits"] == 1 and pc.stats()["invalidations"] == 0
    gen0 = frag.generation
    mutate(holder, fld, frag, api)
    assert frag.generation > gen0, "write path did not bump the generation"
    (after,) = ex.execute("i", q)
    assert pc.stats()["invalidations"] == 1, "planted entry survived a write"
    oracle = Executor(holder, device_policy="never")
    (want,) = oracle.execute("i", q)
    assert after == want


# -- the acceptance bar: randomized read/write interleaving -----------------


def test_randomized_read_write_interleaving_bit_identical(holder):
    """Cached executor vs uncached oracle over one holder: a seeded
    random interleaving of reads (Zipf-repeated pool) and writes
    (set/clear on the rows the reads touch) shows 0 result mismatches,
    with real hits AND real invalidations observed."""
    fld = seed(holder, rows=10, bits=40)
    ex, pc = cached_executor(holder)
    oracle = Executor(holder, device_policy="never")
    pool = [
        "Count(Intersect(Row(f=1), Row(f=2)))",
        "Count(Union(Row(f=2), Row(f=3), Row(f=4)))",
        "TopN(f, Row(f=1), n=5)",
        "Union(Row(f=3), Row(f=5))",
        "Sum(Row(f=4), field=f)",
        "Count(Difference(Row(f=5), Row(f=1)))",
    ]
    rng = np.random.default_rng(99)
    mismatches = 0
    for step in range(400):
        if rng.random() < 0.15:
            row = int(rng.integers(0, 6))
            col = int(rng.integers(0, 1 << 20))
            if rng.random() < 0.7:
                fld.set_bit(row, col)
            else:
                frag = holder.fragment("i", "f", VIEW_STANDARD, 0)
                frag.clear_bit(row, col)
        else:
            q = pool[int(rng.zipf(1.5)) % len(pool)]
            (got,) = ex.execute("i", q)
            (want,) = oracle.execute("i", q)
            if str(norm(got)) != str(norm(want)):
                mismatches += 1
    assert mismatches == 0
    st = pc.stats()
    assert st["hits"] > 50
    assert st["invalidations"] > 0


# -- server surface: cache=false, /debug/plancache, recalc epoch ------------


def test_http_cache_option_and_debug_endpoint(tmp_path):
    from pilosa_tpu.server import Config, Server

    cfg = Config(
        data_dir=str(tmp_path / "data"),
        bind="127.0.0.1:0",
        device_policy="never",
        device_timeout=0,
        metric="none",
    )
    s = Server(cfg)
    s.open()
    try:
        def post(path, body):
            r = urllib.request.Request(s.uri + path, data=body, method="POST")
            with urllib.request.urlopen(r, timeout=30) as resp:
                return json.loads(resp.read() or b"{}")

        def get(path):
            with urllib.request.urlopen(s.uri + path, timeout=30) as resp:
                return json.loads(resp.read())

        post("/index/pcx", b"{}")
        post("/index/pcx/field/f", b"{}")
        post("/index/pcx/query", b"Set(3, f=1) Set(4, f=1)")
        a = post("/index/pcx/query", b"Count(Row(f=1))")
        b = post("/index/pcx/query", b"Count(Row(f=1))")
        assert a == b == {"results": [2]}
        snap = get("/debug/plancache")
        assert snap["enabled"] is True
        assert snap["hits"] >= 1 and snap["entries"] >= 1
        # cache=false bypasses (hit count stays put)
        hits0 = get("/debug/plancache")["hits"]
        post("/index/pcx/query?cache=false", b"Count(Row(f=1))")
        assert get("/debug/plancache")["hits"] == hits0
        # recalculate-caches bumps the epoch (rank reorders can change
        # TopN walks without a generation bump)
        epoch0 = get("/debug/plancache")["epoch"]
        post("/recalculate-caches", b"")
        snap = get("/debug/plancache")
        assert snap["epoch"] == epoch0 + 1 and snap["entries"] == 0
    finally:
        s.close()


def test_plancache_metrics_flow_to_registry(holder):
    seed(holder)
    before = metrics.snapshot().get(metrics.PLANCACHE_HITS, 0)
    ex, pc = cached_executor(holder)
    ex.execute("i", "Count(Row(f=2))")
    ex.execute("i", "Count(Row(f=2))")
    snap = metrics.snapshot()
    assert snap.get(metrics.PLANCACHE_HITS, 0) >= before + 1
    assert metrics.PLANCACHE_BYTES in snap
