"""Block-sparse TopN staging: kernel equivalence with the dense matrix
pass and executor-level bit-identity on tall sparse fragments (the
1B-row regime where dense candidate staging is not a memory plan)."""

import numpy as np

from pilosa_tpu import SHARD_WIDTH, ops
from pilosa_tpu.core import Holder
from pilosa_tpu.executor import Executor


def _sparse_fragment(tmp_path, n_rows=300, seed=31):
    h = Holder(str(tmp_path / "data"))
    h.open()
    idx = h.create_index("i")
    fld = idx.create_field("f")
    rng = np.random.default_rng(seed)
    rows, cols = [], []
    for r in range(n_rows):
        k = int(rng.integers(1, 4))
        rows += [r] * k
        cols += rng.integers(0, SHARD_WIDTH, size=k).tolist()
    # a couple of hot rows so TopN has structure + an interesting src
    rows += [7] * 2000 + [11] * 1500
    cols += (np.arange(2000) * 17 % SHARD_WIDTH).tolist()
    cols += (np.arange(1500) * 29 % SHARD_WIDTH).tolist()
    fld.import_bits(rows, cols)
    return h


class TestSparseKernel:
    def test_matches_dense_scores(self, tmp_path):
        h = _sparse_fragment(tmp_path)
        frag = h.fragment("i", "f", "standard", 0)
        ids = frag.row_ids()
        blocks, brow, bslot = frag.sparse_row_blocks(ids)
        assert blocks.shape[0] == frag.sparse_block_count(ids)
        # src = row 7's words
        src64 = frag.row_words(7)
        src = np.ascontiguousarray(src64).view("<u4")
        dense = np.ascontiguousarray(frag.packed_rows(ids)).view("<u4").reshape(
            len(ids), -1
        )
        want = np.asarray(ops.intersection_counts_matrix(src, dense))
        got = np.asarray(
            ops.sparse_intersection_counts(
                src,
                np.ascontiguousarray(blocks).view("<u4"),
                brow,
                bslot,
                len(ids),
            )
        )
        assert np.array_equal(got, want)
        h.close()

    def test_empty_rows_score_zero(self, tmp_path):
        h = _sparse_fragment(tmp_path, n_rows=5)
        frag = h.fragment("i", "f", "standard", 0)
        ids = [0, 1, 9999]  # 9999 has no bits
        blocks, brow, bslot = frag.sparse_row_blocks(ids)
        src = np.ascontiguousarray(frag.row_words(7)).view("<u4")
        got = np.asarray(
            ops.sparse_intersection_counts(
                src, np.ascontiguousarray(blocks).view("<u4"), brow, bslot, 3
            )
        )
        assert got[2] == 0
        h.close()


class TestSparseTopN:
    def test_executor_bit_identity_and_path(self, tmp_path):
        h = _sparse_fragment(tmp_path)
        cpu = Executor(h, device_policy="never")
        dev = Executor(h, device_policy="always")
        q = "TopN(f, Row(f=7), n=10)"
        want = cpu.execute("i", q)
        got = dev.execute("i", q)
        assert want == got
        # the tall sparse candidate set must have taken the sparse path
        kinds = {k[1] for k in dev.stager._cache if len(k) > 1}
        assert "sparse_rows" in kinds
        h.close()

    def test_multishard_stacked_batched(self, tmp_path):
        h = Holder(str(tmp_path / "ms"))
        h.open()
        idx = h.create_index("i")
        fld = idx.create_field("f")
        rng = np.random.default_rng(41)
        rows, cols = [], []
        for shard in range(3):
            base = shard * SHARD_WIDTH
            for r in range(200):
                k = int(rng.integers(1, 4))
                rows += [r + 100] * k
                cols += (base + rng.integers(0, SHARD_WIDTH, size=k)).tolist()
            rows += [7] * 900
            cols += (base + rng.integers(0, SHARD_WIDTH, size=900)).tolist()
        fld.import_bits(rows, cols)
        cpu = Executor(h, device_policy="never")
        dev = Executor(h, device_policy="always")
        for q in ["TopN(f, Row(f=7), n=5)", "TopN(f, n=5)"]:
            assert cpu.execute("i", q) == dev.execute("i", q), q
        kinds = {k[1] for k in dev.stager._cache if len(k) > 1}
        assert "sparse_stack" in kinds
        # fused count tree: one jit per structure
        q = "Count(Intersect(Union(Row(f=101), Row(f=102)), Row(f=7)))"
        assert cpu.execute("i", q) == dev.execute("i", q)
        assert len(dev._tree_jits) == 1
        h.close()

    def test_pass2_reuses_pass1_scores(self, tmp_path, monkeypatch):
        """TopN's exact-count pass must not re-dispatch scoring for ids
        pass 1 already scored — on a tunneled chip that second round
        trip is half the query latency."""
        import pilosa_tpu.ops as ops_mod

        # skewed fixture: a dozen hot rows with distinct high overlap
        # vs a count-1 tail, so the ranked walk's threshold break
        # prunes inside the head chunk (the 1B-bench shape)
        h = Holder(str(tmp_path / "data"))
        h.open()
        fld = h.create_index("i").create_field("f")
        rows, cols = [], []
        for r in range(12):
            k = 200 + r * 50
            rows += [r] * k
            cols += ((np.arange(k) * (r + 3)) % SHARD_WIDTH).tolist()
        for r in range(300):  # singleton tail
            rows.append(100 + r)
            cols.append((r * 7919) % SHARD_WIDTH)
        fld.import_bits(rows, cols)
        cpu = Executor(h, device_policy="never")
        dev = Executor(h, device_policy="always")
        q = "TopN(f, Row(f=0), n=5)"
        want = cpu.execute("i", q)
        dev.execute("i", q)  # warm staging + compile

        calls = []
        for name in (
            "sparse_intersection_counts_stacked",
            "sparse_intersection_counts",
        ):
            orig = getattr(ops_mod, name)

            def spy(*a, _orig=orig, _name=name, **kw):
                calls.append(_name)
                return _orig(*a, **kw)

            monkeypatch.setattr(ops_mod, name, spy)
        got = dev.execute("i", q)
        assert got == want
        # one scoring dispatch for pass 1; pass 2 served from the carry
        assert len(calls) == 1
        h.close()

    def test_concurrent_topn_coalesce_stacked(self, tmp_path):
        """Concurrent TopN queries sharing the staged candidate chunk
        must coalesce into batched stacked-kernel launches (one device
        round-trip serves the batch) and stay bit-identical."""
        from concurrent.futures import ThreadPoolExecutor

        h = Holder(str(tmp_path / "cc"))
        h.open()
        fld = h.create_index("i").create_field("f")
        rng = np.random.default_rng(13)
        rows, cols = [], []
        for shard in range(3):
            base = shard * SHARD_WIDTH
            for r in range(16):
                k = 300 + 20 * r
                rows += [r] * k
                cols += (base + rng.integers(0, SHARD_WIDTH, size=k)).tolist()
            for r in range(150):
                rows.append(100 + r)
                cols.append(base + (r * 7919) % SHARD_WIDTH)
        fld.import_bits(rows, cols)
        cpu = Executor(h, device_policy="never")
        dev = Executor(h, device_policy="always")
        queries = [f"TopN(f, Row(f={r}), n=5)" for r in range(8)]
        want = {q: cpu.execute("i", q) for q in queries}
        dev.execute("i", queries[0])  # warm staging + compile

        with ThreadPoolExecutor(max_workers=8) as pool:
            for _ in range(3):
                futs = {q: pool.submit(dev.execute, "i", q) for q in queries}
                for q, f in futs.items():
                    assert f.result() == want[q], q
        h.close()

    def test_stacked_scorer_batches_deterministically(self, tmp_path):
        """Coalescing itself, without thread-timing luck: hold the
        dispatch lock while peers enqueue, then release — one batched
        launch must serve them all with per-query-correct scores."""
        import threading
        from concurrent.futures import ThreadPoolExecutor

        from pilosa_tpu import ops
        from pilosa_tpu.executor.batcher import BatchedScorer

        h = _sparse_fragment(tmp_path)
        frag = h.fragment("i", "f", "standard", 0)
        ids = tuple(frag.row_ids()[:32])
        blocks, brow, bslot = frag.sparse_row_blocks(list(ids))
        blocks32 = np.ascontiguousarray(blocks).view("<u4")
        bshard = np.zeros(len(brow), dtype=brow.dtype)  # single shard
        staged = (blocks32, brow, bslot, bshard, len(ids))

        scorer = BatchedScorer(
            max_batch=8,
            single_fn=lambda src, st: ops.sparse_intersection_counts_stacked(
                src, *st
            ),
            batch_fn=lambda srcs, st: ops.sparse_intersection_counts_stacked_batch_list(
                srcs, *st
            ),
        )
        key = (id(blocks32), id(brow))
        srcs = [
            np.ascontiguousarray(frag.row_words(r)).view("<u4")[None, :]
            for r in (7, 11, 0, 1)
        ]
        want = [
            np.asarray(ops.sparse_intersection_counts_stacked(s, *staged))
            for s in srcs
        ]

        # mark the dispatcher active so every score() call enqueues as
        # a waiter; run one dispatch round once all four are pending
        with scorer._lock:
            scorer._dispatching = True
        with ThreadPoolExecutor(max_workers=4) as pool:
            futs = [pool.submit(scorer.score, key, staged, s) for s in srcs]
            while sum(len(v[1]) for v in scorer._pending.values()) < 4:
                pass
            scorer._dispatch_loop()
            got = [f.result() for f in futs]
        for g, w in zip(got, want):
            assert np.array_equal(g, w)
        assert scorer.batched_queries == 4
        assert scorer.dispatches == 1
        h.close()

    def test_dense_fragment_keeps_dense_path(self, tmp_path):
        h = Holder(str(tmp_path / "dense"))
        h.open()
        idx = h.create_index("i")
        fld = idx.create_field("f")
        rng = np.random.default_rng(5)
        rows, cols = [], []
        for r in range(8):  # few rows, each spread over many containers
            rows += [r] * 4000
            cols += rng.integers(0, SHARD_WIDTH, size=4000).tolist()
        fld.import_bits(rows, cols)
        cpu = Executor(h, device_policy="never")
        dev = Executor(h, device_policy="always")
        q = "TopN(f, Row(f=1), n=4)"
        assert cpu.execute("i", q) == dev.execute("i", q)
        kinds = {k[1] for k in dev.stager._cache if len(k) > 1}
        assert "sparse_rows" not in kinds
        h.close()
