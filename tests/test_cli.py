"""CLI tests — each subcommand against live servers (mirrors reference
ctl/*_test.go)."""

import json
import os
import urllib.request

import pytest

from pilosa_tpu.cli.main import main
from pilosa_tpu.server import Config, Server


@pytest.fixture()
def server(tmp_path):
    cfg = Config(
        data_dir=str(tmp_path / "data"), bind="127.0.0.1:0", metric="none",
        device_policy="never",
    )
    s = Server(cfg)
    s.open()
    yield s
    s.close()


def test_import_and_export(tmp_path, server, capsys):
    csv_file = tmp_path / "data.csv"
    csv_file.write_text("1,100\n1,200\n2,100\n")
    rc = main(
        [
            "import",
            "--host", server.uri,
            "-i", "i", "-f", "f", "--create",
            str(csv_file),
        ]
    )
    assert rc == 0
    body = json.dumps({}).encode()
    r = urllib.request.Request(
        server.uri + "/index/i/query", data=b"Row(f=1)", method="POST"
    )
    with urllib.request.urlopen(r) as resp:
        out = json.loads(resp.read())
    assert out["results"][0]["columns"] == [100, 200]

    out_file = tmp_path / "out.csv"
    rc = main(
        ["export", "--host", server.uri, "-i", "i", "-f", "f", "-o", str(out_file)]
    )
    assert rc == 0
    assert sorted(out_file.read_text().strip().splitlines()) == [
        "1,100",
        "1,200",
        "2,100",
    ]


def test_import_values(tmp_path, server):
    csv_file = tmp_path / "vals.csv"
    # columnID,value pairs — the reference's value-mode CSV order
    # (ctl/import.go:404-415)
    csv_file.write_text("10,1\n20,2\n30,3\n")
    rc = main(
        [
            "import", "--host", server.uri, "-i", "i", "-f", "v",
            "--create", "--field-type", "int", "--field-min", "0",
            "--field-max", "100", "--values", str(csv_file),
        ]
    )
    assert rc == 0
    r = urllib.request.Request(
        server.uri + "/index/i/query", data=b'Sum(field="v")', method="POST"
    )
    with urllib.request.urlopen(r) as resp:
        out = json.loads(resp.read())
    assert out["results"][0] == {"value": 6, "count": 3}
    # the value landed on the right column
    r = urllib.request.Request(
        server.uri + "/index/i/query", data=b"Range(v == 2)", method="POST"
    )
    with urllib.request.urlopen(r) as resp:
        out = json.loads(resp.read())
    assert out["results"][0]["columns"] == [20]


def test_import_with_timestamp(tmp_path, server):
    csv_file = tmp_path / "t.csv"
    csv_file.write_text("1,100,2018-02-03T00:00\n")
    rc = main(
        [
            "import", "--host", server.uri, "-i", "i", "-f", "t",
            "--create", "--field-type", "time", "--time-quantum", "YMD",
            str(csv_file),
        ]
    )
    assert rc == 0
    r = urllib.request.Request(
        server.uri + "/index/i/query",
        data=b"Range(t=1, 2018-01-01T00:00, 2019-01-01T00:00)",
        method="POST",
    )
    with urllib.request.urlopen(r) as resp:
        out = json.loads(resp.read())
    assert out["results"][0]["columns"] == [100]


def test_check_and_inspect(tmp_path, capsys):
    from pilosa_tpu.core import Fragment

    frag_path = tmp_path / "frag"
    f = Fragment(str(frag_path), "i", "f", "standard", 0)
    f.open()
    f.set_bit(1, 5)
    f.set_bit(1, 6)
    f.close()
    assert main(["check", str(frag_path)]) == 0
    out = capsys.readouterr().out
    assert "ok" in out and "bits=2" in out
    assert main(["inspect", str(frag_path)]) == 0
    out = capsys.readouterr().out
    assert "array" in out

    bad = tmp_path / "bad"
    bad.write_bytes(b"\x00" * 32)
    assert main(["check", str(bad)]) == 1


def test_config_commands(tmp_path, capsys):
    assert main(["generate-config"]) == 0
    out = capsys.readouterr().out
    assert "data-dir" in out and "[cluster]" in out
    cfg_file = tmp_path / "c.toml"
    cfg_file.write_text('bind = "1.2.3.4:5555"\n')
    assert main(["config", "-c", str(cfg_file)]) == 0
    out = capsys.readouterr().out
    assert "1.2.3.4:5555" in out


def test_check_validates_occ_sidecar(tmp_path, capsys):
    """`check` validates the .occ occupancy sidecar: ok when it matches,
    stale when the staleness stamp rejects it, FAILED (exit 1) when a
    stamp-passing sidecar disagrees with the file."""
    import numpy as np

    from pilosa_tpu.cli.main import main
    from pilosa_tpu.roaring import build_fragment_file

    p = str(tmp_path / "frag")
    build_fragment_file(
        p, [np.arange(0, 5 << 16, 7, dtype=np.uint64)]
    )
    assert os.path.exists(p + ".occ")
    assert main(["check", p]) == 0
    out = capsys.readouterr().out
    assert ".occ: ok" in out

    # corrupt one prefix-sum word PAST the header: stamp still matches,
    # data does not -> integrity failure
    with open(p + ".occ", "r+b") as f:
        f.seek(80)
        f.write(b"\xff\xff\xff\xff")
    assert main(["check", p]) == 1

    # a stale stamp (file rewritten) is reported as ignorable, exit 0
    build_fragment_file(
        str(tmp_path / "frag2"), [np.arange(0, 3 << 16, 5, dtype=np.uint64)]
    )
    os.replace(str(tmp_path / "frag2.occ"), p + ".occ")
    assert main(["check", p]) == 0
    assert "stale" in capsys.readouterr().out


def test_metrics_command(tmp_path, server, capsys):
    """`pilosa_tpu metrics` dumps Prometheus text; --traces dumps the
    recent-trace ring as JSON."""
    body = json.dumps({}).encode()
    urllib.request.urlopen(
        urllib.request.Request(server.uri + "/index/m", data=body, method="POST")
    )
    urllib.request.urlopen(
        urllib.request.Request(
            server.uri + "/index/m/field/f", data=body, method="POST"
        )
    )
    urllib.request.urlopen(
        urllib.request.Request(
            server.uri + "/index/m/query?profile=true",
            data=b"Set(1, f=1) Count(Row(f=1))",
            method="POST",
        )
    )
    rc = main(["metrics", "--host", server.uri])
    assert rc == 0
    out = capsys.readouterr().out
    assert "pilosa_executor_calls" in out
    rc = main(["metrics", "--host", server.uri, "--traces"])
    assert rc == 0
    out = capsys.readouterr().out
    traces = json.loads(out)["traces"]
    assert traces and traces[-1]["name"] == "query"
