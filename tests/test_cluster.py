"""Multi-node cluster tests — N full servers in one process (mirrors
reference server/cluster_test.go + cluster_internal_test.go)."""

import json
import socket
import time
import urllib.request

import pytest

from pilosa_tpu import SHARD_WIDTH
from pilosa_tpu.parallel.hashing import fnv64a, jump_hash, partition
from pilosa_tpu.parallel.node import Node, URI
from pilosa_tpu.server import ClusterConfig, Config, Server


def free_ports(n):
    socks = []
    ports = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def req(uri, method, path, body=None, raw=False):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
    r = urllib.request.Request(uri + path, data=data, method=method)
    try:
        with urllib.request.urlopen(r, timeout=30) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload or b"{}")
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, payload if raw else json.loads(payload or b"{}")


def boot_static_cluster(tmp_path, n=3, replicas=1, ports=None, **cluster_kw):
    ports = ports or free_ports(n)
    hosts = [f"127.0.0.1:{p}" for p in ports]
    servers = []
    for i, p in enumerate(ports):
        cfg = Config(
            data_dir=str(tmp_path / f"node{i}"),
            bind=f"127.0.0.1:{p}",
            device_policy="never",
            metric="none",
            cluster=ClusterConfig(
                disabled=False,
                coordinator=(i == 0),
                replicas=replicas,
                hosts=hosts,
                **cluster_kw,
            ),
        )
        s = Server(cfg)
        s.open()
        servers.append(s)
    return servers


class TestHashing:
    def test_fnv64a(self):
        # FNV-1a 64 known vector
        assert fnv64a(b"") == 0xCBF29CE484222325
        assert fnv64a(b"a") == 0xAF63DC4C8601EC8C

    def test_jump_hash_distribution(self):
        counts = [0] * 5
        for k in range(10000):
            b = jump_hash(k, 5)
            assert 0 <= b < 5
            counts[b] += 1
        assert min(counts) > 1500  # roughly uniform

    def test_jump_hash_monotone_stability(self):
        # adding a bucket only moves keys to the NEW bucket
        for k in range(1000):
            b5 = jump_hash(k, 5)
            b6 = jump_hash(k, 6)
            assert b6 == b5 or b6 == 5

    def test_partition_deterministic(self):
        assert partition("i", 0) == partition("i", 0)
        parts = {partition("i", s) for s in range(1000)}
        assert len(parts) > 200  # spreads over the 256 partitions


class TestStaticCluster:
    def test_three_node_query_distribution(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=3)
        try:
            s0 = servers[0]
            st, _ = req(s0.uri, "POST", "/index/i", {})
            assert st == 200
            st, _ = req(s0.uri, "POST", "/index/i/field/f", {})
            assert st == 200
            # schema propagated to all nodes
            for s in servers:
                assert s.holder.field("i", "f") is not None

            # set bits across 6 shards via node 0
            cols = [s * SHARD_WIDTH + 10 for s in range(6)]
            for c in cols:
                st, body = req(s0.uri, "POST", "/index/i/query", f"Set({c}, f=1)".encode())
                assert st == 200 and body["results"] == [True], body

            # every node answers the full query
            for s in servers:
                st, body = req(s.uri, "POST", "/index/i/query", b"Row(f=1)")
                assert st == 200, body
                assert body["results"][0]["columns"] == cols, s.uri
                st, body = req(s.uri, "POST", "/index/i/query", b"Count(Row(f=1))")
                assert body["results"][0] == 6

            # data actually distributed: no node holds every fragment,
            # and the union covers all 6 shards
            held = []
            for s in servers:
                v = s.holder.view("i", "f", "standard")
                held.append(set(v.fragments) if v else set())
            assert set().union(*held) == set(range(6))
            assert max(len(h) for h in held) < 6

            # ownership matches the hash ring on every node
            c0 = servers[0].cluster
            for shard in range(6):
                owner_ids = [n.id for n in c0.shard_nodes("i", shard)]
                for s in servers[1:]:
                    assert [n.id for n in s.cluster.shard_nodes("i", shard)] == owner_ids
        finally:
            for s in servers:
                s.close()

    def test_topn_across_nodes(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=2)
        try:
            s0 = servers[0]
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            # row 1: bits in 4 shards; row 2: bits in 2 shards
            for shard in range(4):
                req(s0.uri, "POST", "/index/i/query", f"Set({shard * SHARD_WIDTH}, f=1)".encode())
            for shard in range(2):
                req(s0.uri, "POST", "/index/i/query", f"Set({shard * SHARD_WIDTH + 1}, f=2)".encode())
            for s in servers:
                req(s.uri, "POST", "/recalculate-caches")
            for s in servers:
                st, body = req(s.uri, "POST", "/index/i/query", b"TopN(f, n=2)")
                assert body["results"][0] == [
                    {"id": 1, "count": 4},
                    {"id": 2, "count": 2},
                ], s.uri
        finally:
            for s in servers:
                s.close()

    def test_replication(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=2, replicas=2)
        try:
            s0 = servers[0]
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 3 for s in range(4)]
            for c in cols:
                req(s0.uri, "POST", "/index/i/query", f"Set({c}, f=9)".encode())
            # with replicas=2 and 2 nodes, both hold every fragment
            for s in servers:
                v = s.holder.view("i", "f", "standard")
                assert set(v.fragments) == set(range(4)), s.uri
                st, body = req(s.uri, "POST", "/index/i/query", b"Row(f=9)")
                assert body["results"][0]["columns"] == cols
        finally:
            for s in servers:
                s.close()

    def test_replicated_ingest_counts_once_and_terminates(self, tmp_path):
        """Durable ingest with replicas=2: the wave applies on BOTH
        replicas, the changed count counts each mutation once (not once
        per replica), and the owner-side leg carries a ``local`` marker
        so the replicas' single-threaded committers never route the
        wave back at each other (a distributed deadlock)."""
        servers = boot_static_cluster(tmp_path, n=2, replicas=2)
        try:
            s0 = servers[0]
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 7 for s in range(4)]
            # HTTP queue path: must ack (not hang on a committer cycle)
            st, body = req(
                s0.uri,
                "POST",
                "/index/i/field/f/ingest",
                {"rowIDs": [1] * 4, "columnIDs": cols},
            )
            assert st == 200 and body["acked"] == 4, body
            # direct wave apply: 4 new bits change 4 bits, not 4×replicas
            cols2 = [s * SHARD_WIDTH + 8 for s in range(4)]
            assert s0.api.apply_write_wave("i", "f", [1] * 4, cols2) == 4
            # and a fully-duplicate wave changes nothing on any replica
            assert s0.api.apply_write_wave("i", "f", [1] * 4, cols2) == 0
            # both replicas hold every bit
            for s in servers:
                v = s.holder.view("i", "f", "standard")
                assert set(v.fragments) == set(range(4)), s.uri
                st, body = req(s.uri, "POST", "/index/i/query", b"Row(f=1)")
                assert body["results"][0]["columns"] == sorted(cols + cols2)
        finally:
            for s in servers:
                s.close()

    def test_failover_to_replica(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=2, replicas=2)
        try:
            s0, s1 = servers
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 3 for s in range(4)]
            for c in cols:
                req(s0.uri, "POST", "/index/i/query", f"Set({c}, f=9)".encode())
            # kill node 1; node 0 must still answer everything from replicas
            s1.close()
            st, body = req(s0.uri, "POST", "/index/i/query", b"Count(Row(f=9))")
            assert st == 200 and body["results"][0] == 4
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass


class TestLiveness:
    """SWIM-analog probing (reference gossip/gossip.go:431-494) and
    NodeStatus exchange (reference server.go:565-630)."""

    def test_probe_marks_dead_node_and_queries_survive(self, tmp_path):
        import time

        servers = boot_static_cluster(
            tmp_path,
            n=3,
            replicas=2,
            probe_interval=0.2,
            probe_timeout=0.5,
            down_after=2,
        )
        try:
            s0, s1, s2 = servers
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 3 for s in range(6)]
            for c in cols:
                req(s0.uri, "POST", "/index/i/query", f"Set({c}, f=9)".encode())
            dead_uri = s2.uri
            s2.close()
            # the probe loop must flip the node to DOWN within a few
            # probe intervals (down_after=2 at 0.2s + broadcast slack)
            deadline = time.monotonic() + 10
            state = None
            while time.monotonic() < deadline:
                state = next(
                    n.state for n in s0.cluster.nodes if n.uri == dead_uri
                )
                if state == "DOWN":
                    break
                time.sleep(0.1)
            assert state == "DOWN", state
            # planner skips the dead node; replicas answer everything
            st, body = req(s0.uri, "POST", "/index/i/query", b"Count(Row(f=9))")
            assert st == 200 and body["results"][0] == 6
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass

    def test_probe_recovers_ready_state(self, tmp_path):
        servers = boot_static_cluster(
            tmp_path, n=2, replicas=1, probe_interval=0, down_after=1
        )
        try:
            s0, s1 = servers
            def node1():
                # state flips broadcast a ClusterStatus, which rebuilds
                # cluster.nodes from dicts — re-fetch, don't hold a ref
                return next(n for n in s0.cluster.nodes if n.uri == s1.uri)

            # direct probes: deterministic, no loop timing
            s0.cluster._note_probe(node1(), False)
            assert node1().state == "DOWN"
            s0.cluster.probe_nodes()
            assert node1().state == "READY"
        finally:
            for s in servers:
                s.close()

    def test_traffic_cannot_resurrect_down_node(self, tmp_path):
        """Passive evidence (a node-status message) must not flip a
        DOWN node back to READY: the message may have been sent while
        the node was still alive and land after the prober declared it
        dead — only a successful probe (the node answers NOW) clears
        DOWN. READY/SUSPECT refresh from traffic is still allowed."""
        servers = boot_static_cluster(
            tmp_path, n=2, replicas=1, probe_interval=0, down_after=1
        )
        try:
            s0, s1 = servers

            def node1():
                return next(n for n in s0.cluster.nodes if n.uri == s1.uri)

            s0.cluster._note_probe(node1(), False)
            assert node1().state == "DOWN"
            # stale traffic arrives after the DOWN verdict: the state
            # must not flip synchronously — only the scheduled
            # verification probe (active evidence) may clear DOWN.
            # Capture instead of running it: s1 is actually alive here,
            # so letting the async probe run would race the asserts.
            scheduled = []
            real_submit = s0.cluster._pool.submit
            s0.cluster._pool.submit = lambda fn, *a: scheduled.append((fn, a))
            try:
                s0.cluster._apply_node_status(
                    {"type": "node-status", "node_id": node1().id}
                )
                assert node1().state == "DOWN"
                assert scheduled and scheduled[0][0] == s0.cluster._verify_down
            finally:
                s0.cluster._pool.submit = real_submit
            # traffic refreshes SUSPECT → READY (non-DOWN states)
            s0.cluster.down_after = 2
            s0.cluster._fail_counts.clear()
            s0.cluster._note_probe(node1(), False)
            assert node1().state == "SUSPECT"
            s0.cluster._apply_node_status(
                {"type": "node-status", "node_id": node1().id}
            )
            assert node1().state == "READY"
            # an actual probe success clears DOWN
            s0.cluster.down_after = 1
            s0.cluster._note_probe(node1(), False)
            assert node1().state == "DOWN"
            s0.cluster.probe_nodes()
            assert node1().state == "READY"
        finally:
            for s in servers:
                s.close()

    def test_node_status_exchange_heals_schema(self, tmp_path):
        servers = boot_static_cluster(
            tmp_path, n=2, replicas=1, probe_interval=0, status_interval=0
        )
        try:
            s0, s1 = servers
            # create schema on node 0 only (holder-level, no broadcast)
            idx = s0.holder.create_index("drifted")
            idx.create_field("f")
            assert s1.holder.index("drifted") is None
            s0.cluster.push_node_status()
            assert s1.holder.index("drifted") is not None
            assert s1.holder.index("drifted").field("f") is not None
        finally:
            for s in servers:
                s.close()


class TestJoinProtocol:
    def test_join_and_resize(self, tmp_path):
        ports = free_ports(2)
        cfg0 = Config(
            data_dir=str(tmp_path / "n0"),
            bind=f"127.0.0.1:{ports[0]}",
            device_policy="never",
            metric="none",
            cluster=ClusterConfig(disabled=False, coordinator=True),
        )
        s0 = Server(cfg0)
        s0.open()
        try:
            # seed data on the single-node cluster
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 7 for s in range(8)]
            for c in cols:
                req(s0.uri, "POST", "/index/i/query", f"Set({c}, f=1)".encode())
            assert set(s0.holder.view("i", "f", "standard").fragments) == set(range(8))

            # second node joins → triggers a resize moving fragments
            cfg1 = Config(
                data_dir=str(tmp_path / "n1"),
                bind=f"127.0.0.1:{ports[1]}",
                device_policy="never",
                metric="none",
                cluster=ClusterConfig(
                    disabled=False,
                    coordinator=False,
                    coordinator_host=s0.uri,
                ),
            )
            s1 = Server(cfg1)
            s1.open()  # blocks until joined (resize complete)
            try:
                assert s1.cluster.state == "NORMAL"
                assert len(s0.cluster.nodes) == 2
                # node 1 received the fragments it now owns
                owned1 = {
                    s
                    for s in range(8)
                    if any(
                        n.id == s1.cluster.node_id
                        for n in s1.cluster.shard_nodes("i", s)
                    )
                }
                v1 = s1.holder.view("i", "f", "standard")
                assert owned1, "expected node 1 to own some shards"
                assert owned1 <= set(v1.fragments)
                # node 0 drops what it no longer owns (holder-clean
                # runs just after the NORMAL broadcast — bounded wait)
                import time as _time

                deadline = _time.time() + 10
                while _time.time() < deadline:
                    v0 = s0.holder.view("i", "f", "standard")
                    if all(
                        s0.cluster.owns_shard("i", sh) for sh in v0.fragments
                    ):
                        break
                    _time.sleep(0.05)
                v0 = s0.holder.view("i", "f", "standard")
                for shard in v0.fragments:
                    assert s0.cluster.owns_shard("i", shard)
                # full query still correct from both nodes
                for s in (s0, s1):
                    st, body = req(s.uri, "POST", "/index/i/query", b"Row(f=1)")
                    assert body["results"][0]["columns"] == cols, s.uri
            finally:
                s1.close()
        finally:
            s0.close()


class TestAntiEntropy:
    def test_sync_converges_replicas(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=2, replicas=2)
        try:
            s0, s1 = servers
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            req(s0.uri, "POST", "/index/i/query", b"Set(1, f=1)Set(2, f=1)")
            # diverge: write directly into node1's holder, bypassing routing
            s1.holder.field("i", "f").set_bit(1, 99)
            rows0 = s0.holder.field("i", "f").row(1).columns().tolist()
            rows1 = s1.holder.field("i", "f").row(1).columns().tolist()
            assert rows0 != rows1  # replicas diverged
            # anti-entropy sweep from node 0 converges both (2 replicas →
            # majority threshold 1 → union semantics, as in the reference)
            s0.cluster.sync_holder()
            assert s0.holder.field("i", "f").row(1).columns().tolist() == [1, 2, 99]
            assert s1.holder.field("i", "f").row(1).columns().tolist() == [1, 2, 99]
            st, b0 = req(s0.uri, "POST", "/index/i/query", b"Row(f=1)")
            assert b0["results"][0]["columns"] == [1, 2, 99]
        finally:
            for s in servers:
                s.close()

    def test_sync_converges_random_divergence(self, tmp_path):
        """Randomized divergence across set/time/int fields written
        DIRECTLY into individual replicas' holders (bypassing the write
        fan-out): one coordinator sweep must converge every node to the
        union/majority state for every view."""
        import numpy as np

        rng = np.random.default_rng(77)
        servers = boot_static_cluster(tmp_path, n=2, replicas=2)
        try:
            s0, s1 = servers
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            req(
                s0.uri, "POST", "/index/i/field/t",
                {"options": {"type": "time", "timeQuantum": "YM"}},
            )
            req(
                s0.uri, "POST", "/index/i/field/v",
                {"options": {"type": "int", "min": 0, "max": 500}},
            )
            # common baseline through the normal path
            for c in range(0, 2 * SHARD_WIDTH, SHARD_WIDTH // 3):
                req(s0.uri, "POST", "/index/i/query", f"Set({c}, f=1)".encode())
            # now diverge each node's holder directly
            from datetime import datetime

            for s in servers:
                for _ in range(60):
                    row = int(rng.integers(0, 8))
                    col = int(rng.integers(0, 2 * SHARD_WIDTH))
                    kind = rng.random()
                    if kind < 0.5:
                        s.holder.field("i", "f").set_bit(row, col)
                    elif kind < 0.8:
                        s.holder.field("i", "t").set_bit(
                            row, col, datetime(2021, int(rng.integers(1, 13)), 5)
                        )
                    else:
                        s.holder.field("i", "v").set_value(
                            col, int(rng.integers(0, 501))
                        )
            # one coordinator sweep
            s0.cluster.sync_holder()
            queries = [
                "Count(Row(f=1))",
                *(f"Count(Row(f={r}))" for r in range(8)),
                *(f"Count(Row(t={r}))" for r in range(8)),
                "Count(Range(t=2, 2021-01-01T00:00, 2022-01-01T00:00))",
                "Sum(field=v)",
                "Count(Range(v > 100))",
            ]
            for q in queries:
                # force LOCAL evaluation on each node over all shards:
                # identical answers prove the holders themselves agree
                vals = []
                for s in servers:
                    st, body = req(
                        s.uri,
                        "POST",
                        "/index/i/query?remote=true&shards=0,1",
                        q.encode(),
                    )
                    assert st == 200, (q, body)
                    vals.append(body["results"][0])
                assert vals[0] == vals[1], (q, vals)
        finally:
            for s in servers:
                s.close()

    def test_sync_converges_time_and_bsi_views_in_one_sweep(self, tmp_path):
        """Time-quantum and bsig_* views converge after ONE coordinator
        sweep: fixes are pushed through the view-aware block endpoint,
        not Set/Clear PQL (which only reaches the standard view —
        reference fragment.go:1874)."""
        servers = boot_static_cluster(tmp_path, n=2, replicas=2)
        try:
            s0, s1 = servers
            req(s0.uri, "POST", "/index/i", {})
            req(
                s0.uri,
                "POST",
                "/index/i/field/t",
                {"options": {"type": "time", "timeQuantum": "YMD"}},
            )
            req(
                s0.uri,
                "POST",
                "/index/i/field/v",
                {"options": {"type": "int", "min": 0, "max": 1000}},
            )
            req(
                s0.uri,
                "POST",
                "/index/i/query",
                b"Set(1, t=1, 2020-03-05T00:00) SetValue(col=1, v=7)",
            )
            # diverge: write directly into node1's holder, bypassing routing
            from datetime import datetime

            s1.holder.field("i", "t").set_bit(1, 42, datetime(2020, 3, 5))
            s1.holder.field("i", "v").set_value(50, 9)
            # one sweep from the coordinator only
            s0.cluster.sync_holder()

            for s in (s0, s1):
                # time views (standard + YMD quantums) all converged
                for view in (
                    "standard",
                    "standard_2020",
                    "standard_202003",
                    "standard_20200305",
                ):
                    frag = s.holder.fragment("i", "t", view, 0)
                    assert frag is not None, (s.uri, view)
                    assert frag.row(1).columns().tolist() == [1, 42], (s.uri, view)
                # BSI view converged: both columns readable on both nodes
                fld = s.holder.field("i", "v")
                bsig = fld.bsi_group("v")
                vfrag = s.holder.fragment("i", "v", "bsig_v", 0)
                assert vfrag.value(1, bsig.bit_depth()) == (7, True), s.uri
                assert vfrag.value(50, bsig.bit_depth()) == (9, True), s.uri
        finally:
            for s in servers:
                s.close()


class TestChaos:
    def test_load_through_node_death_and_rejoin(self, tmp_path):
        """Concurrent writers + readers while a replica dies and comes
        back: reads must keep answering off the surviving replicas, no
        request may hang or 500, and one anti-entropy sweep after the
        restart converges every node to identical counts."""
        import threading
        import time

        servers = boot_static_cluster(
            tmp_path,
            n=3,
            replicas=2,
            probe_interval=0.2,
            probe_timeout=0.5,
            down_after=2,
        )
        stop = threading.Event()  # before try: the finally always sees it
        dead_window = threading.Event()
        write_errors = []  # errors while all nodes alive = real bugs
        read_failures = []
        writes_done = []
        try:
            s0, s1, s2 = servers
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            # seed across 4 shards so every node owns something
            for c in range(0, 4 * SHARD_WIDTH, SHARD_WIDTH // 2):
                req(s0.uri, "POST", "/index/i/query", f"Set({c}, f=1)".encode())

            def attempt(uri, body):
                """One request with a single retry: under full-suite
                machine load a transient connect hiccup must not be
                recorded as a correctness failure."""
                try:
                    return req(uri, "POST", "/index/i/query", body)
                except Exception:
                    time.sleep(0.05)
                    return req(uri, "POST", "/index/i/query", body)

            def writer(base_col, uri):
                i = 0
                while not stop.is_set():
                    col = (base_col + i * 7919) % (4 * SHARD_WIDTH)
                    # snapshot BEFORE issuing: a request in flight
                    # across a window transition must be classified by
                    # the more permissive of its two endpoints
                    window_open = dead_window.is_set()
                    try:
                        st, _ = attempt(uri, f"Set({col}, f=2)".encode())
                        if st == 200:
                            writes_done.append(col)
                        elif not (window_open or dead_window.is_set()):
                            write_errors.append((col, st))
                    except Exception as e:
                        # transport errors are only acceptable while a
                        # replica is down (its fan-out leg fails)
                        if not (window_open or dead_window.is_set()):
                            write_errors.append((col, repr(e)))
                    i += 1
                    time.sleep(0.01)

            def reader(uri):
                while not stop.is_set():
                    try:
                        st, body = attempt(uri, b"Count(Row(f=1))")
                        if st != 200:
                            read_failures.append(st)
                    except Exception as e:
                        read_failures.append(repr(e))
                    time.sleep(0.01)

            threads = [
                threading.Thread(target=writer, args=(1, s0.uri), daemon=True),
                threading.Thread(target=writer, args=(2, s1.uri), daemon=True),
                threading.Thread(target=reader, args=(s0.uri,), daemon=True),
                threading.Thread(target=reader, args=(s1.uri,), daemon=True),
            ]
            for t in threads:
                t.start()
            time.sleep(1.0)  # steady-state load

            # kill node 2 under load
            dead_window.set()
            victim_cfg = s2.config
            s2.close()
            deadline = time.monotonic() + 30
            saw_down = False
            while time.monotonic() < deadline:
                if any(
                    n.state == "DOWN"
                    for n in s0.cluster.nodes
                    if n.uri != s0.uri and n.uri != s1.uri
                ):
                    saw_down = True
                    break
                time.sleep(0.1)
            # the degraded-path claim is only tested if the victim was
            # actually observed DOWN
            assert saw_down, "victim never marked DOWN"
            time.sleep(1.0)  # load against the degraded cluster

            # restart the victim on its old port + data dir
            s2b = Server(victim_cfg)
            s2b.open()
            servers[2] = s2b
            time.sleep(1.0)
            dead_window.clear()
            time.sleep(0.5)
            stop.set()
            for t in threads:
                t.join(timeout=30)
                assert not t.is_alive(), "worker thread hung"

            assert not write_errors, write_errors[:5]
            assert not read_failures, read_failures[:5]
            assert len(writes_done) > 20  # load actually flowed

            # converge: the restarted node missed the dead-window
            # writes. EVERY node sweeps (a node only syncs fragments it
            # owns, so a single coordinator sweep misses shards owned
            # by the other two — in production each node runs its own
            # periodic anti-entropy loop, which this mirrors)
            for s in servers:
                s.cluster.sync_holder()
            want = None
            for s in servers:
                st, body = req(
                    s.uri, "POST", "/index/i/query?shards=0,1,2,3", b"Count(Row(f=2))"
                )
                assert st == 200
                if want is None:
                    want = body["results"][0]
                else:
                    assert body["results"][0] == want, (s.uri, body, want)
            # every acknowledged write must be present; a dead-window
            # write that errored back to the client may still have
            # landed on the surviving replica, so >= not ==
            assert want >= len(set(writes_done))
        finally:
            stop.set()
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass


class TestURI:
    def test_parse(self):
        u = URI.from_address("https://example.com:8080")
        assert (u.scheme, u.host, u.port) == ("https", "example.com", 8080)
        u = URI.from_address("localhost:10101")
        assert (u.scheme, u.host, u.port) == ("http", "localhost", 10101)
        u = URI.from_address("example.com")
        assert (u.scheme, u.host, u.port) == ("http", "example.com", 10101)
        u = URI.from_address(":10101")
        assert (u.scheme, u.host, u.port) == ("http", "localhost", 10101)
        with pytest.raises(ValueError):
            URI.from_address("")

    def test_parse_ipv6_and_validation(self):
        # bracketed IPv6 literal (reference uri.go:29 hostRegexp)
        u = URI.from_address("[fd42:4201::ed80]:9999")
        assert (u.host, u.port) == ("[fd42:4201::ed80]", 9999)
        # scheme-only spelling is valid, everything defaults
        u = URI.from_address("https://")
        assert (u.scheme, u.host, u.port) == ("https", "localhost", 10101)
        for bad in ("foo bar", "host:port", "http://host:99999", "UPPER.example"):
            with pytest.raises(ValueError):
                URI.from_address(bad)
        u = URI()
        with pytest.raises(ValueError):
            u.set_scheme("h ttp")
        with pytest.raises(ValueError):
            u.set_host("bad_host!")

    def test_normalize_and_path(self):
        # a '+'-qualified scheme normalizes to its base for HTTP clients
        u = URI.from_address("https+pb://example.com:8080")
        assert str(u) == "https+pb://example.com:8080"
        assert u.normalize() == "https://example.com:8080"
        assert u.path("/status") == "https://example.com:8080/status"
        assert u.host_port() == "example.com:8080"

    def test_dict_round_trip(self):
        u = URI.from_address("https://example.com:8080")
        assert URI.from_dict(u.to_dict()) == u


class TestAttrSync:
    def test_attr_diff_converges(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=2, replicas=2)
        try:
            s0, s1 = servers
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            # attrs written on node 0 only (bypassing forward) to diverge
            s0.holder.field("i", "f").row_attr_store.set_attrs(5, {"c": "x"})
            s0.holder.index("i").column_attrs.set_attrs(9, {"n": "y"})
            assert s1.holder.field("i", "f").row_attr_store.attrs(5) == {}
            # sweep from node 1 pulls the remote diff
            s1.cluster.sync_holder()
            assert s1.holder.field("i", "f").row_attr_store.attrs(5) == {"c": "x"}
            assert s1.holder.index("i").column_attrs.attrs(9) == {"n": "y"}
        finally:
            for s in servers:
                s.close()


class TestClusterKeyTranslation:
    def test_keyed_writes_on_any_node_share_one_id_space(self, tmp_path):
        """Every node used to mint ids independently, so the same id
        meant DIFFERENT keys per node (Row(likes="pizza") returned a
        different user depending on which node answered). Followers now
        forward minting to the deterministic translate primary and
        stream its WAL, so keyed writes landing on any node converge."""
        import time as _time

        servers = boot_static_cluster(tmp_path, n=3, replicas=1)
        try:
            s0, s1, s2 = servers
            req(s0.uri, "POST", "/index/k", {"options": {"keys": True}})
            req(s0.uri, "POST", "/index/k/field/likes", {"options": {"keys": True}})
            # writes spread over all three nodes
            for i, (who, what) in enumerate(
                [("alice", "pizza"), ("bob", "pizza"), ("carol", "sushi"),
                 ("dave", "pizza"), ("erin", "sushi")]
            ):
                st, body = req(
                    servers[i % 3].uri,
                    "POST",
                    "/index/k/query",
                    f'Set("{who}", likes="{what}")'.encode(),
                )
                assert st == 200 and body["results"] == [True], (who, body)
            # replication tick (1s loop) + settle
            deadline = _time.time() + 10
            want_pizza = ["alice", "bob", "dave"]

            def converged(a):
                # a not-yet-replicated reverse mapping shows up as None
                return a is not None and None not in a and sorted(a) == want_pizza

            while _time.time() < deadline:
                answers = [
                    req(s.uri, "POST", "/index/k/query", b'Row(likes="pizza")')[1][
                        "results"
                    ][0].get("keys")
                    for s in servers
                ]
                if all(converged(a) for a in answers):
                    break
                _time.sleep(0.2)
            assert all(converged(a) for a in answers), answers
            for s in servers:
                st, body = req(
                    s.uri, "POST", "/index/k/query", b'Count(Row(likes="sushi"))'
                )
                assert body["results"][0] == 2, (s.uri, body)
        finally:
            for s in servers:
                s.close()


class TestTranslateReplication:
    def test_replica_pulls_key_log(self, tmp_path):
        from pilosa_tpu.server import ClusterConfig, Config, Server

        ports = free_ports(2)
        s0 = Server(Config(
            data_dir=str(tmp_path / "p"), bind=f"127.0.0.1:{ports[0]}",
            metric="none", device_policy="never",
        ))
        s0.open()
        try:
            req(s0.uri, "POST", "/index/u", {"options": {"keys": True}})
            req(s0.uri, "POST", "/index/u/field/l", {"options": {"keys": True}})
            req(s0.uri, "POST", "/index/u/query", b'Set("alice", l="pizza")')
            s1 = Server(Config(
                data_dir=str(tmp_path / "r"), bind=f"127.0.0.1:{ports[1]}",
                metric="none", device_policy="never",
                translate_primary_url=s0.uri,
            ))
            s1.open()
            try:
                import time as _t

                # the ids are whatever the primary minted (partitioned
                # assignment interleaves residue classes) — the replica
                # must converge on the SAME ids via the pull loop
                cid = s0.translate_store.translate_columns_to_ids(
                    "u", ["alice"], create=False
                )[0]
                rid = s0.translate_store.translate_rows_to_ids(
                    "u", "l", ["pizza"], create=False
                )[0]
                assert cid and rid
                deadline = _t.monotonic() + 15
                while _t.monotonic() < deadline:
                    if (
                        s1.translate_store.translate_column_to_string("u", cid)
                        == "alice"
                    ):
                        break
                    _t.sleep(0.2)
                assert s1.translate_store.translate_column_to_string("u", cid) == "alice"
                assert s1.translate_store.translate_row_to_string("u", "l", rid) == "pizza"
            finally:
                s1.close()
        finally:
            s0.close()


class TestClusterImport:
    def test_import_routes_to_shard_owners(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=3)
        try:
            s0 = servers[0]
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 1 for s in range(6)]
            st, _ = req(
                s0.uri, "POST", "/index/i/field/f/import",
                {"rowIDs": [1] * 6, "columnIDs": cols},
            )
            assert st == 200
            # bits landed on the owning nodes only
            for s in servers:
                v = s.holder.view("i", "f", "standard")
                frags = set(v.fragments) if v else set()
                for shard in frags:
                    assert s.cluster.owns_shard("i", shard), (s.uri, shard)
            st, body = req(s0.uri, "POST", "/index/i/query", b"Row(f=1)")
            assert body["results"][0]["columns"] == cols
            st, body = req(servers[2].uri, "POST", "/index/i/query", b"Count(Row(f=1))")
            assert body["results"][0] == 6
        finally:
            for s in servers:
                s.close()

    def test_import_values_routes(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=2)
        try:
            s0 = servers[0]
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/v",
                {"options": {"type": "int", "min": 0, "max": 100}})
            cols = [s * SHARD_WIDTH for s in range(4)]
            st, _ = req(
                s0.uri, "POST", "/index/i/field/v/import-value",
                {"columnIDs": cols, "values": [10, 20, 30, 40]},
            )
            assert st == 200
            st, body = req(servers[1].uri, "POST", "/index/i/query", b'Sum(field="v")')
            assert body["results"][0] == {"value": 100, "count": 4}
        finally:
            for s in servers:
                s.close()


class TestClusterEquivalenceFuzz:
    def test_cluster_matches_single_node(self, tmp_path):
        """Random queries against a 3-node cluster (asked on every
        node) must match a single-node server holding the same data —
        the HTTP analog of the tri-path executor fuzz: placement,
        fan-out, remote exec, and reduce order all under test."""
        import numpy as np

        rng = np.random.default_rng(99)
        cluster = boot_static_cluster(tmp_path, n=3, replicas=2)
        single = boot_static_cluster(tmp_path / "single", n=1)
        try:
            n_shards, n_rows = 4, 16
            rows = rng.integers(0, n_rows, size=2500)
            cols = rng.integers(0, n_shards * SHARD_WIDTH, size=2500)
            vcols = rng.choice(n_shards * SHARD_WIDTH, size=400, replace=False)
            vvals = rng.integers(-50, 500, size=400)
            for s in (cluster[0], single[0]):
                req(s.uri, "POST", "/index/i", {})
                req(s.uri, "POST", "/index/i/field/f", {})
                req(
                    s.uri,
                    "POST",
                    "/index/i/field/v",
                    {"options": {"type": "int", "min": -50, "max": 500}},
                )
                st, _ = req(
                    s.uri,
                    "POST",
                    "/index/i/field/f/import",
                    {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()},
                )
                assert st == 200
                st, _ = req(
                    s.uri,
                    "POST",
                    "/index/i/field/v/import-value",
                    {"columnIDs": vcols.tolist(), "values": vvals.tolist()},
                )
                assert st == 200
                st, _ = req(s.uri, "POST", "/recalculate-caches", {})
                assert st == 200

            def gen_query():
                kind = rng.choice(
                    ["count", "row", "topn", "topn_plain", "sum", "range", "minmax"]
                )
                a, b = int(rng.integers(0, n_rows)), int(rng.integers(0, n_rows))
                if kind == "count":
                    op = rng.choice(["Intersect", "Union", "Difference", "Xor"])
                    return f"Count({op}(Row(f={a}), Row(f={b})))"
                if kind == "row":
                    return f"Row(f={a})"
                if kind == "topn":
                    return f"TopN(f, Row(f={a}), n={int(rng.integers(1, 6))})"
                if kind == "topn_plain":
                    return f"TopN(f, n={int(rng.integers(1, 8))})"
                if kind == "sum":
                    return f"Sum(Row(f={a}), field=v)"
                if kind == "minmax":
                    return rng.choice(["Min", "Max"]) + "(field=v)"
                pred = int(rng.integers(-60, 510))
                op = rng.choice(["<", "<=", "==", "!=", ">", ">="])
                return f"Count(Range(v {op} {pred}))"

            for i in range(50):
                # multi-call requests exercise the concurrent read pool
                # + batched coalescing through the cluster fan-out
                q = gen_query() if rng.random() < 0.7 else gen_query() + " " + gen_query()
                st, want = req(single[0].uri, "POST", "/index/i/query", q.encode())
                assert st == 200, (q, want)
                for node in cluster:
                    st, got = req(node.uri, "POST", "/index/i/query", q.encode())
                    assert st == 200 and got == want, (q, node.uri, got, want)

            # interleave writes (same write to both deployments, any
            # cluster node) with immediate cross-checks
            for i in range(10):
                row = int(rng.integers(0, n_rows))
                col = int(rng.integers(0, n_shards * SHARD_WIDTH))
                w = f"Set({col}, f={row})"
                st1, r1 = req(
                    cluster[i % 3].uri, "POST", "/index/i/query", w.encode()
                )
                st2, r2 = req(single[0].uri, "POST", "/index/i/query", w.encode())
                assert st1 == 200 and st2 == 200 and r1 == r2, (w, r1, r2)
                q = f"Count(Row(f={row}))"
                _, want = req(single[0].uri, "POST", "/index/i/query", q.encode())
                for node in cluster:
                    _, got = req(node.uri, "POST", "/index/i/query", q.encode())
                    assert got == want, (q, node.uri, got, want)
        finally:
            for s in cluster + single:
                s.close()


class TestPlacementParamAdoption:
    def test_joiner_with_mismatched_replicas_adopts_cluster_value(self, tmp_path):
        """replicas= is cluster-wide semantics: a joiner configured
        with a different value used to compute different ownership than
        everyone else, and its holder-clean deleted fragments the rest
        of the cluster had just transferred to it (observed data loss).
        The coordinator's placement parameters ride every status
        broadcast and the joiner adopts them."""
        import time as _time

        servers = boot_static_cluster(tmp_path, n=3, replicas=2)
        try:
            s0 = servers[0]
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            for sh in range(6):
                req(
                    s0.uri,
                    "POST",
                    "/index/i/query",
                    f"Set({sh * SHARD_WIDTH + 9}, f=2)".encode(),
                )
            # joiner deliberately misconfigured with replicas=1
            ports = free_ports(1)
            cfg = Config(
                data_dir=str(tmp_path / "n3"),
                bind=f"127.0.0.1:{ports[0]}",
                device_policy="never",
                metric="none",
                cluster=ClusterConfig(
                    disabled=False,
                    coordinator=False,
                    coordinator_host=s0.uri,
                    replicas=1,
                ),
            )
            s3 = Server(cfg)
            s3.open()
            servers.append(s3)
            assert s3.cluster.replica_n == 2  # adopted from the cluster
            deadline = _time.time() + 15
            while _time.time() < deadline:
                if all(
                    req(s.uri, "GET", "/status")[1]["state"] == "NORMAL"
                    for s in servers
                ):
                    break
                _time.sleep(0.2)
            _time.sleep(0.5)
            # every shard the joiner owns must actually be present on it
            v = s3.holder.view("i", "f", "standard")
            frags = set(v.fragments) if v else set()
            owned = {
                sh for sh in range(6) if s3.cluster.owns_shard("i", sh)
            }
            assert owned <= frags, (owned, frags)
            for s in servers:
                st, body = req(s.uri, "POST", "/index/i/query", b"Count(Row(f=2))")
                assert st == 200 and body["results"][0] == 6, (s.uri, body)
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass


class TestRemoveDeadNode:
    def test_remove_node_that_died(self, tmp_path):
        """The documented recovery for a dead node is operator removal;
        planning must tolerate the removed node being unreachable and
        answers must survive on the remaining replicas."""
        import time as _time

        servers = boot_static_cluster(tmp_path, n=3, replicas=2)
        try:
            s0, s1, s2 = servers
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            cols = [sh * SHARD_WIDTH + 5 for sh in range(6)]
            for c in cols:
                req(s0.uri, "POST", "/index/i/query", f"Set({c}, f=3)".encode())
            dead_id = s2.cluster.node_id
            s2.close()  # node dies
            st, _ = req(
                s0.uri, "POST", "/cluster/resize/remove-node", {"id": dead_id}
            )
            assert st == 200
            deadline = _time.time() + 20
            ok = False
            while _time.time() < deadline:
                st, body = req(s0.uri, "GET", "/status")
                if body["state"] == "NORMAL" and len(body["nodes"]) == 2:
                    ok = True
                    break
                _time.sleep(0.2)
            assert ok, body
            for s in (s0, s1):
                st, body = req(s.uri, "POST", "/index/i/query", b"Count(Row(f=3))")
                assert st == 200 and body["results"][0] == 6, (s.uri, body)
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass


class TestResizeEquivalence:
    def test_answers_invariant_across_node_join(self, tmp_path):
        """Query answers must be identical before a resize, after the
        fragment moves complete, and from EVERY node — the fuzz form of
        the reference's resize tests (placement changed, data didn't)."""
        import time as _time

        import numpy as np

        rng = np.random.default_rng(41)
        ports = free_ports(3)
        servers = []
        for i in range(2):
            cfg = Config(
                data_dir=str(tmp_path / f"n{i}"),
                bind=f"127.0.0.1:{ports[i]}",
                device_policy="never",
                metric="none",
                cluster=ClusterConfig(
                    disabled=False,
                    coordinator=(i == 0),
                    coordinator_host="" if i == 0 else f"http://127.0.0.1:{ports[0]}",
                ),
            )
            s = Server(cfg)
            s.open()
            servers.append(s)
        try:
            s0 = servers[0]
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            rows = rng.integers(0, 12, size=1500)
            cols = rng.integers(0, 5 * SHARD_WIDTH, size=1500)
            st, _ = req(
                s0.uri,
                "POST",
                "/index/i/field/f/import",
                {"rowIDs": rows.tolist(), "columnIDs": cols.tolist()},
            )
            assert st == 200
            req(s0.uri, "POST", "/recalculate-caches", {})

            queries = []
            for _ in range(15):
                a, b = int(rng.integers(0, 12)), int(rng.integers(0, 12))
                queries += [
                    f"Count(Row(f={a}))",
                    f"Count(Intersect(Row(f={a}), Row(f={b})))",
                    f"TopN(f, Row(f={a}), n=4)",
                ]
            before = {}
            for q in queries:
                st, body = req(s0.uri, "POST", "/index/i/query", q.encode())
                assert st == 200, (q, body)
                before[q] = body

            # join a third node: triggers a resize job + fragment moves
            cfg2 = Config(
                data_dir=str(tmp_path / "n2"),
                bind=f"127.0.0.1:{ports[2]}",
                device_policy="never",
                metric="none",
                cluster=ClusterConfig(
                    disabled=False,
                    coordinator=False,
                    coordinator_host=s0.uri,
                ),
            )
            s2 = Server(cfg2)
            s2.open()  # blocks until the cluster is NORMAL again
            servers.append(s2)
            deadline = _time.time() + 20
            while _time.time() < deadline:
                sts = [req(s.uri, "GET", "/status")[1]["state"] for s in servers]
                if all(s == "NORMAL" for s in sts):
                    break
                _time.sleep(0.2)

            for s in servers:
                for q in queries:
                    st, body = req(s.uri, "POST", "/index/i/query", q.encode())
                    assert st == 200 and body == before[q], (q, s.uri, body, before[q])
        finally:
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass


class TestAsyncResize:
    def test_resize_job_async_and_status(self, tmp_path):
        """The coordinator's join handling must not block: the job runs
        in the background with introspectable state (reference
        resizeJob, cluster.go:1309-1423)."""
        import time as _time

        ports = free_ports(2)
        cfg0 = Config(
            data_dir=str(tmp_path / "n0"),
            bind=f"127.0.0.1:{ports[0]}",
            device_policy="never",
            metric="none",
            cluster=ClusterConfig(disabled=False, coordinator=True),
        )
        s0 = Server(cfg0)
        s0.open()
        try:
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            req(s0.uri, "POST", "/index/i/query", b"Set(7, f=1)")

            cfg1 = Config(
                data_dir=str(tmp_path / "n1"),
                bind=f"127.0.0.1:{ports[1]}",
                device_policy="never",
                metric="none",
                cluster=ClusterConfig(
                    disabled=False, coordinator=False, coordinator_host=s0.uri
                ),
            )
            s1 = Server(cfg1)
            t0 = _time.time()
            s1.open()  # joiner blocks until NORMAL, coordinator does not
            try:
                job = s0.cluster.resize_job_status()
                assert job is not None
                assert job["action"] == "add"
                deadline = _time.time() + 10
                while _time.time() < deadline:
                    if s0.cluster.resize_job_status()["state"] == "DONE":
                        break
                    _time.sleep(0.05)
                assert s0.cluster.resize_job_status()["state"] == "DONE"
                st, body = req(s0.uri, "GET", "/status")
                assert body["resizeJob"]["state"] == "DONE"
            finally:
                s1.close()
        finally:
            s0.close()

    def test_resize_abort_rolls_back(self, tmp_path):
        """An aborted job returns the cluster to NORMAL with state
        ABORTED (reference api.ResizeAbort:795)."""
        servers = boot_static_cluster(tmp_path, n=1, replicas=1)
        s0 = servers[0]
        try:
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            req(s0.uri, "POST", "/index/i/query", b"Set(3, f=1)")
            # start a resize toward an unreachable node: it can never
            # complete, so abort must roll back
            ghost = Node(id="zzzghost", uri="http://127.0.0.1:1", is_coordinator=False)
            s0.cluster._start_resize(add_node=ghost)
            assert s0.cluster.state == "RESIZING"
            job = s0.cluster.resize_job_status()
            assert job["state"] == "RUNNING"
            s0.cluster.resize_abort()
            assert s0.cluster.state == "NORMAL"
            assert s0.cluster.resize_job_status()["state"] == "ABORTED"
        finally:
            s0.close()

    def test_frag_sources_balanced(self, tmp_path):
        """Source replicas are cycled, not always the first owner
        (reference fragSources load spreading, cluster.go:689-773)."""
        servers = boot_static_cluster(tmp_path, n=2, replicas=2)
        try:
            s0 = servers[0]
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            for sh in range(8):
                req(
                    s0.uri,
                    "POST",
                    "/index/i/query",
                    f"Set({sh * SHARD_WIDTH + 1}, f=1)".encode(),
                )
            old_nodes = list(s0.cluster.nodes)
            ghost = Node(id="zzzghost", uri="http://ghost:1", is_coordinator=False)
            new_nodes = sorted(old_nodes + [ghost], key=lambda n: n.id)
            sources = s0.cluster._frag_sources(old_nodes, new_nodes)
            ghost_srcs = sources.get("zzzghost", [])
            assert ghost_srcs, "ghost node should gain fragments"
            # every source now carries the FULL candidate list (404
            # fall-through), rotated for balance: with replicas=2 both
            # old nodes hold every fragment, so each entry lists both
            # and the first choice alternates between them
            firsts = {src["from_uris"][0] for src in ghost_srcs}
            assert len(firsts) == 2, firsts
            assert all(len(src["from_uris"]) == 2 for src in ghost_srcs)
        finally:
            for s in servers:
                s.close()


class TestStatusAuthority:
    """Round-4 advisor fixes: only the coordinator's cluster-status is
    adopted; mints on non-primaries are rejected; resize abort is
    coordinator-only; set-coordinator rides a dedicated message."""

    def test_follower_status_broadcast_is_not_adopted(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=3)
        try:
            s0, s1, s2 = servers
            good_ids = sorted(n.id for n in s0.cluster.nodes)
            # a follower broadcasts a status carrying a STALE node list
            # (missing node 2) — e.g. a node that wedged mid-join
            stale = s1.cluster._status_message()
            assert not stale["fromCoordinator"]
            stale["nodes"] = [n.to_dict() for n in s1.cluster.nodes[:2]]
            stale["replicaN"] = 3  # and a misconfigured placement param
            s0.cluster.receive_message(stale)
            s2.cluster.receive_message(stale)
            assert sorted(n.id for n in s0.cluster.nodes) == good_ids
            assert sorted(n.id for n in s2.cluster.nodes) == good_ids
            assert s0.cluster.replica_n == 1
            # the coordinator's broadcast IS adopted
            fresh = s0.cluster._status_message()
            assert fresh["fromCoordinator"]
            s1.cluster.receive_message(fresh)
            assert sorted(n.id for n in s1.cluster.nodes) == good_ids
        finally:
            for s in servers:
                s.close()

    def test_mint_on_non_owner_is_409(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=2)
        try:
            s0, s1 = servers
            req(s0.uri, "POST", "/index/i", {"options": {"keys": True}})
            # ownership is partitioned (jump hash): find a key each
            # node owns, and one it does not
            def owner_of(key):
                return [
                    s
                    for s in servers
                    if not s.translate_store.misowned("i", "", [key])
                ]

            key = next(f"k{i}" for i in range(64) if owner_of(f"k{i}"))
            owners = owner_of(key)
            assert len(owners) == 1, "exactly one node owns each key"
            owner = owners[0]
            other = s1 if owner is s0 else s0
            # minting on the owner works, and re-minting is idempotent
            st, body = req(
                owner.uri, "POST", "/internal/translate/keys",
                {"index": "i", "keys": [key]},
            )
            assert st == 200 and len(body["ids"]) == 1 and body["ids"][0] >= 1
            st2, body2 = req(
                owner.uri, "POST", "/internal/translate/keys",
                {"index": "i", "keys": [key]},
            )
            assert st2 == 200 and body2["ids"] == body["ids"]
            # posting the same internal mint to a NON-owner must be
            # rejected, not silently minted into a forked id space
            st, body = req(
                other.uri, "POST", "/internal/translate/keys",
                {"index": "i", "keys": [key]},
            )
            assert st == 409, body
            assert "owner" in body.get("error", str(body))
            # and a missing body field is a 400, not a 500
            st, body = req(s0.uri, "POST", "/internal/translate/keys", {})
            assert st == 400, body
        finally:
            for s in servers:
                s.close()

    def test_resize_abort_rejected_on_follower(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=2)
        try:
            s0, s1 = servers
            st, _ = req(s1.uri, "POST", "/cluster/resize/abort", {})
            assert st == 400
            st, _ = req(s0.uri, "POST", "/cluster/resize/abort", {})
            assert st == 200
        finally:
            for s in servers:
                s.close()

    def test_set_coordinator_propagates_from_any_node(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=3)
        try:
            s0, s1, s2 = servers
            new_id = s2.cluster.node_id
            # operator posts to a FOLLOWER naming a new coordinator
            st, _ = req(
                s1.uri, "POST", "/cluster/resize/set-coordinator",
                {"id": new_id},
            )
            assert st == 200
            deadline = time.time() + 5
            while time.time() < deadline:
                if all(s.cluster.is_coordinator == (s is s2) for s in servers):
                    break
                time.sleep(0.05)
            for s in servers:
                assert s.cluster.is_coordinator == (s is s2), s.uri
                coord = [n.id for n in s.cluster.nodes if n.is_coordinator]
                assert coord == [new_id], (s.uri, coord)
        finally:
            for s in servers:
                s.close()


class TestIndirectProbing:
    """SWIM ping-req: a partitioned direct link must not mark a healthy
    node DOWN — a suspect is confirmed through third nodes first
    (reference memberlist IndirectChecks, gossip/gossip.go:431-494)."""

    def test_partitioned_link_does_not_mark_healthy_node_down(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=3, down_after=1)
        try:
            s0, s1, s2 = servers
            target_uri = s2.uri
            real_status = s0.cluster._probe_client.status

            def broken_link(uri):
                if uri == target_uri:
                    raise OSError("simulated partitioned link")
                return real_status(uri)

            s0.cluster._probe_client.status = broken_link
            for _ in range(3):
                s0.cluster.probe_nodes()
            n2 = next(n for n in s0.cluster.nodes if n.uri == target_uri)
            # node1's relay confirmed node2 alive despite the dead link
            assert n2.state == "READY", n2.state
        finally:
            for s in servers:
                s.close()

    def test_actually_dead_node_still_goes_down(self, tmp_path):
        servers = boot_static_cluster(tmp_path, n=3, down_after=1)
        try:
            s0, s1, s2 = servers
            dead_uri = s2.uri
            s2.close()
            s0.cluster.probe_nodes()
            n2 = next(n for n in s0.cluster.nodes if n.uri == dead_uri)
            assert n2.state == "DOWN", n2.state
        finally:
            for s in servers[:2]:
                s.close()


class TestRestartStateSync:
    """A restarted cluster must answer cross-shard queries correctly
    IMMEDIATELY — node-status push/pull runs at startup (memberlist
    join-time state sync), not only on the periodic interval.
    Regression: counts collapsed to one node's local shards right
    after a full restart (caught by the round-4 gauntlet)."""

    def test_full_restart_serves_all_shards_immediately(self, tmp_path):
        ports = free_ports(3)  # SAME ring across the restart
        servers = boot_static_cluster(tmp_path, n=3, ports=ports)
        try:
            s0 = servers[0]
            req(s0.uri, "POST", "/index/i", {})
            req(s0.uri, "POST", "/index/i/field/f", {})
            cols = [s * SHARD_WIDTH + 5 for s in range(6)]
            for c in cols:
                req(s0.uri, "POST", "/index/i/query", f"Set({c}, f=1)".encode())
            st, body = req(s0.uri, "POST", "/index/i/query", b"Count(Row(f=1))")
            assert body["results"][0] == 6
        finally:
            for s in servers:
                s.close()
        # full rolling restart over the same data dirs; query at once
        servers = boot_static_cluster(tmp_path, n=3, ports=ports)
        try:
            for s in servers:
                st, body = req(s.uri, "POST", "/index/i/query", b"Count(Row(f=1))")
                assert st == 200 and body["results"][0] == 6, (s.uri, body)
        finally:
            for s in servers:
                s.close()
