"""Benchmark: TopN queries/sec on the north-star workload.

Synthetic fragment (BASELINE.json config 4 style): R rows × 2^20 columns
per shard at ~2% density; queries are TopN(field, Row(src)) — the
reference's hot path (per-candidate IntersectionCount over the ranked
cache, fragment.go:985) executed as one batched intersection-count
matrix kernel + top_k on the TPU.

Baseline: the same queries through this framework's CPU roaring path
(the reference's algorithm shape — per-candidate container popcount
loops). The reference Go binary itself can't run here (no Go toolchain
in the image); the roaring CPU path is the stand-in and is labeled as
such. vs_baseline = TPU QPS / CPU QPS.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    R, W64 = 4096, 16384  # rows × uint64-words (2^20 columns)
    DENSITY = 0.02
    N_QUERIES = 64
    TOPK = 10

    rng = np.random.default_rng(11)
    # Synthetic packed fragment: each row ~2% density.
    nbits_per_word = (
        rng.random((R, W64)) < 0  # placeholder, filled below
    )
    # Generate sparse rows: choose set words, then random bits in them.
    mat64 = np.zeros((R, W64), dtype=np.uint64)
    for i in range(R):
        nset = int(W64 * 64 * DENSITY)
        cols = rng.choice(W64 * 64, size=nset, replace=False)
        np.bitwise_or.at(
            mat64, (i, cols // 64), np.uint64(1) << np.uint64(cols % 64).astype(np.uint64)
        )
    mat32 = mat64.view("<u4")

    srcs = mat64[rng.integers(0, R, size=N_QUERIES)]  # reuse rows as src filters
    srcs32 = srcs.view("<u4")

    # ---- TPU path: batched intersection-count + top_k ----
    @jax.jit
    def topn_step(src, mat):
        scores = jnp.sum(
            jax.lax.population_count(jnp.bitwise_and(mat, src[None, :])).astype(
                jnp.int32
            ),
            axis=-1,
        )
        counts, ids = jax.lax.top_k(scores, TOPK)
        return ids, counts

    dev_mat = jax.device_put(mat32)
    # warmup / compile
    ids, counts = topn_step(jax.device_put(srcs32[0]), dev_mat)
    ids.block_until_ready()

    lat = []
    t_all = time.perf_counter()
    for q in range(N_QUERIES):
        t0 = time.perf_counter()
        ids, counts = topn_step(jax.device_put(srcs32[q]), dev_mat)
        ids.block_until_ready()
        lat.append(time.perf_counter() - t0)
    tpu_elapsed = time.perf_counter() - t_all
    tpu_qps = N_QUERIES / tpu_elapsed
    p50 = sorted(lat)[len(lat) // 2] * 1000

    # ---- CPU baseline: roaring per-candidate intersection counts ----
    from pilosa_tpu.roaring import Bitmap

    rows_cpu = [Bitmap.from_words_range(mat64[i]) for i in range(R)]
    counts_cpu = [b.count() for b in rows_cpu]
    order = sorted(range(R), key=lambda i: -counts_cpu[i])
    n_cpu = min(4, N_QUERIES)
    t0 = time.perf_counter()
    for q in range(n_cpu):
        src_b = Bitmap.from_words_range(srcs[q])
        scores = []
        for i in order:
            scores.append((src_b.intersection_count(rows_cpu[i]), i))
        scores.sort(reverse=True)
        _ = scores[:TOPK]
    cpu_elapsed = time.perf_counter() - t0
    cpu_qps = n_cpu / cpu_elapsed

    print(
        json.dumps(
            {
                "metric": f"TopN queries/sec ({R} rows x 1M cols, {int(DENSITY*100)}% density, single chip)",
                "value": round(tpu_qps, 2),
                "unit": "queries/s",
                "vs_baseline": round(tpu_qps / cpu_qps, 2),
                "p50_ms": round(p50, 3),
                "baseline_cpu_qps": round(cpu_qps, 3),
                "platform": jax.devices()[0].platform,
            }
        )
    )


if __name__ == "__main__":
    main()
