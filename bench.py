"""Benchmark: TopN queries/sec on the north-star workload.

Synthetic fragment (BASELINE.json config 4 style): R rows × 2^20 columns
per shard at ~2% density; queries are TopN(field, Row(src)) — the
reference's hot path (per-candidate IntersectionCount over the ranked
cache, fragment.go:985) executed as one batched intersection-count
matrix kernel + top_k on the TPU.

The source bitmap of TopN(Row(r)) is a row of the fragment, which the
HBM stager keeps device-resident (executor/stager.py) — so the query
step indexes the staged matrix rather than re-uploading the source from
host each time, exactly as the server's executor does. QPS is measured
with pipelined dispatch and then a forced host-side fetch of every
result (tunneled backends ack block_until_ready before remote
completion, so only a fetch proves the query finished); p50 latency is
a true dispatch+completion+fetch round-trip per query. The batched
path mirrors the executor's continuous micro-batching
(executor/batcher.py): PILOSA_BENCH_BATCH sources per kernel launch.

Baseline: the same queries through this framework's CPU roaring path
(the reference's algorithm shape — per-candidate container popcount
loops). The reference Go binary itself can't run here (no Go toolchain
in the image); the roaring CPU path is the stand-in and is labeled as
such. vs_baseline = TPU QPS / CPU QPS.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import sys
import threading
import time

import numpy as np

# process-start clock for the child's self-enforced deadline: the
# parent's subprocess timeout runs from spawn, so measuring from inside
# main() (after the jax import) would silently eat the guard margin
_T_PROC_START = time.monotonic()

# ---- sub-result checkpointing -------------------------------------------
# Each completed sub-bench (tall full-path, kernel microbench) persists
# to disk the moment it finishes, tagged with the git revision it
# measured. A tunnel wedge mid-run then costs only the unfinished
# parts: the next attempt (same invocation or a retry) reuses fresh
# same-revision parts instead of replaying a whole prior round
# (BENCH_r03's failure mode). Parts from a DIFFERENT revision are never
# reused — stale-replay remains the explicitly-labeled last resort.

_REPO_DIR = os.path.dirname(os.path.abspath(__file__))
PARTS_PATH = os.path.join(_REPO_DIR, ".bench_cache", "bench_parts.json")
PART_MAX_AGE_S = 3 * 3600.0


def _git_rev() -> str:
    import subprocess

    try:
        return subprocess.run(
            ["git", "-C", _REPO_DIR, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def save_part(name: str, obj: dict) -> None:
    try:
        os.makedirs(os.path.dirname(PARTS_PATH), exist_ok=True)
        try:
            with open(PARTS_PATH) as f:
                parts = json.load(f)
        except (OSError, ValueError):
            parts = {}
        parts[name] = {
            "data": obj,
            "ts": time.time(),
            "rev": _git_rev(),
        }
        tmp = PARTS_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(parts, f)
        os.replace(tmp, PARTS_PATH)
    except OSError as e:
        print(f"could not checkpoint part {name}: {e}", file=sys.stderr)


def load_part(name: str):
    """A fresh part measured on THIS code revision, or None."""
    try:
        with open(PARTS_PATH) as f:
            parts = json.load(f)
        p = parts.get(name)
        if not p:
            return None
        if p.get("rev") != _git_rev():
            return None
        age = time.time() - p.get("ts", 0)
        if age > PART_MAX_AGE_S:
            return None
        data = dict(p["data"])
        data["checkpointed_age_s"] = round(age, 1)
        return data
    except (OSError, ValueError):
        return None


def best_closed_loop(d: dict, prefix: str):
    """(key, qps) of the best measured closed-loop number among
    ``prefix``-keyed fields (topn_qps_c8/_c32/...), or (None, None).
    One definition — the live headline, the checkpoint-assembly
    headline, and the core-scaled margin block all use it."""
    best = (None, None)
    for k, v in d.items():
        if k.startswith(prefix) and isinstance(v, (int, float)):
            if best[0] is None or v > best[1]:
                best = (k, v)
    return best


def headline_mode(tall: dict):
    """(mode_label, qps) for the artifact headline: the best measured
    closed-loop serving number, falling back to sequential when no
    concurrency window ran — or when none beat the sequential number
    (a degraded window must not lower the published headline below
    what the run actually achieved)."""
    seq = tall.get("topn_qps") or 0.0
    bk, bv = best_closed_loop(tall, "topn_qps_c")
    if bk is not None and bv > seq:
        return f"{bk.rsplit('c', 1)[1]} closed-loop clients", bv
    return "sequential", seq


def vs_baseline_fields(
    mode: str, headline: float, cpu_qps, cpu_closed_qps=None, seq_qps=None
) -> dict:
    """The vs_baseline fields, identical from the live and the
    checkpoint-assembly paths: ratio + denominator + a note stating
    which convention the ratio uses. A closed-loop headline divides by
    the CPU path's best MEASURED throughput (max of its sequential and
    closed-loop windows — bench_tall measures a short CPU closed loop
    so the denominator is data, not the asserted "sequential is the
    1-core ceiling"); the sequential-vs-sequential ratio always rides
    alongside as vs_baseline_seq when seq_qps is known."""
    if not cpu_qps:
        return {}
    out = {}
    base = cpu_qps
    if mode != "sequential":
        if cpu_closed_qps:
            base = max(cpu_qps, cpu_closed_qps)
            out["baseline_cpu_closed_qps"] = cpu_closed_qps
            note = (
                "headline serving qps vs the CPU full path's best "
                "measured throughput (max of sequential and closed-loop "
                "windows)"
            )
        else:
            note = (
                "headline serving qps vs the CPU full path's sequential "
                "qps (no CPU closed-loop window measured this run)"
            )
    else:
        note = "sequential qps both sides (no concurrency window measured)"
    out.update(
        vs_baseline=round(headline / base, 2),
        baseline_cpu_qps=cpu_qps,
        vs_baseline_note=note,
    )
    if seq_qps and mode != "sequential":
        out["vs_baseline_seq"] = round(seq_qps / cpu_qps, 2)
    return out


# -- bench window self-qualification (VERDICT item 4) -----------------------
# A tunneled-chip window can degrade (slow RTT day, shallow request
# pipelining) without failing outright; a headline measured in such a
# window must not silently overwrite the last-good artifact.

# a run whose RTT is this much worse than the last-good's is degraded
DEGRADED_RTT_FACTOR = 2.5
# a run achieving under this fraction of the last-good pipelining depth
# (concurrent round-trips in flight = qps x RTT) is degraded
DEGRADED_DEPTH_FACTOR = 0.4


def window_quality(tall: dict):
    """Measured quality of the window the headline came from: the
    sustained device RTT (median of the tiny round-trip probe) and the
    achieved pipelining depth (headline qps x RTT = concurrent round
    trips actually in flight). None when the run measured no RTT
    profile — a run that can't prove its window must not displace one
    that could."""
    prof = (tall or {}).get("profile") or {}
    rtt_ms = prof.get("device_rtt_ms")
    if not isinstance(rtt_ms, (int, float)) or rtt_ms <= 0:
        return None
    mode, qps = headline_mode(tall)
    if not qps:
        return None
    out = {
        "sustained_rtt_ms": rtt_ms,
        "pipelining_depth": round(qps * rtt_ms / 1000.0, 2),
        "headline_qps": qps,
        "headline_mode": mode,
    }
    # chain windows ride the same qualification as TopN (VERDICT chain-
    # margin instability): a degraded window must not overwrite the
    # last-good chain numbers either
    seq_chain = tall.get("chain_qps") or 0.0
    ck, cv = best_closed_loop(tall, "chain_qps_c")
    if ck is not None and cv > seq_chain:
        chain_mode, chain_qps = f"{ck.rsplit('c', 1)[1]} closed-loop clients", cv
    else:
        chain_mode, chain_qps = "sequential", seq_chain
    if chain_qps:
        out.update(
            chain_headline_qps=chain_qps,
            chain_headline_mode=chain_mode,
            chain_pipelining_depth=round(chain_qps * rtt_ms / 1000.0, 2),
        )
    # fused-execution window (ISSUE 13): how many device RTTs a warm
    # fused multi-call query costs end to end, and that it really ran
    # as ONE launch. Carried so window_degraded can reject a run where
    # fusion regressed to per-call round trips.
    fr = prof.get("fused_rtt") or {}
    fm = fr.get("rtt_multiple")
    if isinstance(fm, (int, float)) and fm > 0:
        out["fused_rtt_multiple"] = fm
        fl = fr.get("fused_launches_per_query")
        if isinstance(fl, (int, float)):
            out["fused_launches_per_query"] = fl
    return out


def window_degraded(new_wq, old_wq):
    """(degraded, reason) for overwriting an artifact whose window was
    ``old_wq`` with one whose window is ``new_wq``. No old quality
    record (pre-gating artifact) accepts anything — the first qualified
    run seeds the baseline."""
    if not old_wq:
        return False, None
    if not new_wq:
        return True, "no window_quality measured this run (last-good has one)"
    rtt, old_rtt = new_wq["sustained_rtt_ms"], old_wq["sustained_rtt_ms"]
    if old_rtt and rtt > old_rtt * DEGRADED_RTT_FACTOR:
        return True, (
            f"sustained RTT {rtt:.2f} ms > {DEGRADED_RTT_FACTOR}x "
            f"last-good {old_rtt:.2f} ms"
        )
    depth, old_depth = new_wq["pipelining_depth"], old_wq["pipelining_depth"]
    if old_depth and depth < old_depth * DEGRADED_DEPTH_FACTOR:
        return True, (
            f"pipelining depth {depth:.2f} < {DEGRADED_DEPTH_FACTOR}x "
            f"last-good {old_depth:.2f}"
        )
    # symmetric chain-window check: a run whose chain window is shallow
    # (or absent) must not displace qualified chain numbers
    old_cd = old_wq.get("chain_pipelining_depth")
    if old_cd:
        new_cd = new_wq.get("chain_pipelining_depth")
        if not new_cd:
            return True, (
                "no chain window measured this run (last-good has one)"
            )
        if new_cd < old_cd * DEGRADED_DEPTH_FACTOR:
            return True, (
                f"chain pipelining depth {new_cd:.2f} < "
                f"{DEGRADED_DEPTH_FACTOR}x last-good {old_cd:.2f}"
            )
    # symmetric fused-window check (ISSUE 13): once a last-good run has
    # proven one-launch multi-call execution, a run whose fused query
    # costs many more RTTs (fusion off / regressed to per-call round
    # trips) — or that didn't measure it — must not displace it
    old_fm = old_wq.get("fused_rtt_multiple")
    if old_fm:
        new_fm = new_wq.get("fused_rtt_multiple")
        if not new_fm:
            return True, (
                "no fused-query window measured this run (last-good has one)"
            )
        if new_fm > old_fm * DEGRADED_RTT_FACTOR:
            return True, (
                f"fused query costs {new_fm:.2f} RTTs > "
                f"{DEGRADED_RTT_FACTOR}x last-good {old_fm:.2f}"
            )
    return False, None


def _pipeline_serving_probe(budget_s: float) -> dict:
    """Closed-loop HTTP throughput THROUGH the serving pipeline
    (ISSUE 2): boots a real server on :0 with the pipeline enabled over
    a small CPU-path index and drives it with closed-loop HTTP clients.
    Chip-independent — it measures the serving layer (admission, queue,
    coalescing, HTTP glue), the part that bounded round 5 at ~120 qps
    while the kernel sustained thousands. Also runs a short OVERLOAD
    segment (injected per-query delay + shrunken queue so offered load
    exceeds capacity) showing goodput holds near unloaded capacity
    while the excess sheds as 503 + Retry-After."""
    import json as _json
    import shutil as _shutil
    import tempfile
    import urllib.error
    import urllib.request

    from pilosa_tpu.server import Config, Server

    out = {
        "note": (
            "closed-loop HTTP qps through the serving pipeline on a "
            "small CPU-path index (chip-independent: measures the "
            "serving layer, not the kernel)"
        )
    }
    tmp = tempfile.mkdtemp(prefix="pilosa_pipeline_probe_")
    cfg = Config(
        data_dir=tmp,
        bind="127.0.0.1:0",
        device_policy="never",
        device_timeout=0,
        metric="none",
    )
    s = Server(cfg)
    s.open()
    try:
        def post(path, body):
            r = urllib.request.Request(s.uri + path, data=body, method="POST")
            with urllib.request.urlopen(r, timeout=30) as resp:
                return resp.read()

        post("/index/pb", b"{}")
        post("/index/pb/field/f", b"{}")
        rows, cols = [], []
        for r_ in range(8):
            for c in range(256):
                rows.append(r_)
                cols.append((c * 2654435761 + r_ * 97) % (1 << 20))
        post(
            "/index/pb/field/f/import",
            _json.dumps({"rowIDs": rows, "columnIDs": cols}).encode(),
        )
        queries = [f"Count(Row(f={r_}))".encode() for r_ in range(8)]

        def closed_loop(n_clients, seconds):
            stop = time.perf_counter() + seconds
            counts = [0] * n_clients
            shed = [0] * n_clients
            errors = []

            def client(ci):
                i = ci
                try:
                    while time.perf_counter() < stop and not errors:
                        try:
                            post("/index/pb/query", queries[i % len(queries)])
                            counts[ci] += 1
                        except urllib.error.HTTPError as e:
                            if e.code in (429, 503):
                                shed[ci] += 1
                            else:
                                raise
                        except (ConnectionError, urllib.error.URLError):
                            # transport-level drop under overload (RST
                            # before the pipeline could shed politely):
                            # a shed in effect — count it as one
                            shed[ci] += 1
                        i += 1
                except BaseException as e:
                    errors.append(e)

            ts = [
                threading.Thread(target=client, args=(ci,))
                for ci in range(n_clients)
            ]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            if errors:
                raise errors[0]
            dt = time.perf_counter() - t0
            return sum(counts) / dt, sum(shed) / dt

        closed_loop(8, min(2.0, budget_s * 0.15))  # warm
        qps, _ = closed_loop(8, min(4.0, budget_s * 0.3))
        out["closed_loop_qps_c8"] = round(qps, 1)
        if budget_s > 10 and s.pipeline is not None:
            # Overload segment. Reads won't do: singleflight + gang
            # batching legitimately ABSORB a read flood (the c8 window
            # above shows it), so overload is driven with unique writes
            # — never coalesced or combined, each occupies a worker for
            # the injected delay — at 4x more clients than workers. The
            # delay (GIL-released) must dwarf the per-request Python
            # overhead of this 1-core host, or the GIL — not the worker
            # pool — becomes the bottleneck, the queue never fills, and
            # the ratio measures scheduler noise instead of shedding.
            real = s.executor.execute

            def slow(*a, **k):
                time.sleep(0.02)
                return real(*a, **k)

            seq = [0]
            seq_lock = threading.Lock()

            def write_loop(n_clients, seconds):
                stop = time.perf_counter() + seconds
                ok = [0] * n_clients
                shed = [0] * n_clients
                errors = []

                def client(ci):
                    try:
                        while time.perf_counter() < stop and not errors:
                            with seq_lock:
                                seq[0] += 1
                                col = seq[0]
                            try:
                                post(
                                    "/index/pb/query",
                                    f"Set({col % (1 << 20)}, f=30)".encode(),
                                )
                                ok[ci] += 1
                            except urllib.error.HTTPError as e:
                                if e.code in (429, 503):
                                    shed[ci] += 1
                                    # brief backoff (well under the
                                    # advertised Retry-After): a shed
                                    # client that re-fires instantly
                                    # melts the 1-core host with shed
                                    # churn; offered load still far
                                    # exceeds capacity
                                    time.sleep(0.01)
                                else:
                                    raise
                            except (ConnectionError, urllib.error.URLError):
                                shed[ci] += 1
                    except BaseException as e:
                        errors.append(e)

                ts = [
                    threading.Thread(target=client, args=(ci,))
                    for ci in range(n_clients)
                ]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                if errors:
                    raise errors[0]
                dt = time.perf_counter() - t0
                return sum(ok) / dt, sum(shed) / dt

            s.executor.execute = slow
            icq = s.pipeline._classes["interactive"]
            old_limit = icq.limit
            icq.limit = 4
            try:
                # unloaded = clients == workers (saturated, no queueing)
                cap, _ = write_loop(8, min(3.0, budget_s * 0.2))
                good, shed_rate = write_loop(32, min(4.0, budget_s * 0.25))
            finally:
                s.executor.execute = real
                icq.limit = old_limit
            out["overload"] = {
                "unloaded_qps_c8": round(cap, 1),
                "goodput_qps_c32": round(good, 1),
                "shed_per_s": round(shed_rate, 1),
                "goodput_vs_unloaded": round(good / cap, 2) if cap else None,
                "note": (
                    "unique writes (non-coalescable) + 20 ms/query delay "
                    "+ interactive queue shrunk to 4, offered load ~4x "
                    "capacity; goodput should hold near unloaded "
                    "capacity while the excess sheds as 503"
                ),
            }
        with urllib.request.urlopen(s.uri + "/debug/pipeline", timeout=30) as r:
            out["debug_pipeline"] = _json.loads(r.read())
    finally:
        s.close()
        _shutil.rmtree(tmp, ignore_errors=True)
    return out


def _rw_mix_probe(budget_s: float) -> dict:
    """Read/write-mix steady state (ISSUE 3): c8 closed-loop TopN/chain
    reads through the device executor with 1% interleaved single-bit
    writes, in three arms — read-only (denominator), writes absorbed by
    delta staging, and writes with delta staging disabled (every write
    cold-invalidates and the next read re-uploads full blocks). Reports
    steady-state read qps, re-staged bytes, and delta-apply counts per
    arm. Chip-independent (the contrast is staging economics, not
    kernel speed)."""
    import shutil as _shutil
    import tempfile

    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import DeviceStager, Executor
    from pilosa_tpu.utils import metrics as _metrics

    # 256 rows × 4000 bits: big enough that a full chunk re-stage costs
    # ~20 ms host packing (the cost a write used to impose on the next
    # read) while a warm delta apply is ~1-4 ms; at chip scale the gap
    # is upload-bound and orders of magnitude wider
    R, BITS = 256, 4000
    WRITE_FRAC = 0.01
    tmp = tempfile.mkdtemp(prefix="pilosa_rwmix_")
    out = {
        "note": (
            "c8 closed-loop TopN/chain reads on the device executor, 1% "
            "single-bit writes; rw_delta absorbs writes as HBM scatter "
            "deltas, rw_full_restage rebuilds staged blocks per write"
        ),
        "write_frac": WRITE_FRAC,
    }
    h = Holder(tmp)
    h.open()
    try:
        idx = h.create_index("rw")
        fld = idx.create_field("f")
        rng = np.random.default_rng(42)
        rows, cols = [], []
        for r_ in range(R):
            rows += [r_] * BITS
            cols += rng.integers(0, 1 << 20, size=BITS).tolist()
        fld.import_bits(rows, cols)
        queries = [
            "TopN(f, n=10)",
            "TopN(f, Row(f=3), n=8)",
            "Count(Intersect(Row(f=1), Row(f=2)))",
            "Count(Union(Row(f=4), Row(f=5), Row(f=6)))",
        ]

        def arm(write_frac, delta_enabled, seconds, nonce):
            # nonce keys every rng: arms writing the SAME (row, col)
            # sequence as a previous arm would set already-set bits —
            # no-op writes that never bump the generation and fake a
            # write-free steady state
            ex = Executor(
                h,
                device_policy="always",
                stager=DeviceStager(delta_enabled=delta_enabled),
            )
            for q in queries:  # warm: compile + stage
                ex.execute("rw", q)
            if write_frac:
                # absorb the write-path compiles too (delta scatter
                # kernel shapes / restage packing) so the measured
                # window is steady state, not first-write JIT
                wrng = np.random.default_rng(7000 + nonce)
                for w in range(4):
                    fld.set_bit(w % 16, int(wrng.integers(0, 1 << 20)))
                    for q in queries:
                        ex.execute("rw", q)
            snap0 = _metrics.snapshot()
            stop = time.perf_counter() + seconds
            reads = [0] * 8
            writes = [0] * 8
            errors: list = []

            def worker(ci):
                wr = np.random.default_rng(1000 + nonce * 8 + ci)
                i = ci
                try:
                    while time.perf_counter() < stop and not errors:
                        if write_frac and wr.random() < write_frac:
                            # writes land on the rows the read mix keeps
                            # staged (chain sources + TopN candidates) —
                            # the worst case for staging, which is the
                            # point of the probe
                            fld.set_bit(
                                int(wr.integers(0, 16)),
                                int(wr.integers(0, 1 << 20)),
                            )
                            writes[ci] += 1
                        else:
                            ex.execute("rw", queries[i % len(queries)])
                            reads[ci] += 1
                        i += 1
                except BaseException as e:
                    errors.append(e)

            ts = [
                threading.Thread(target=worker, args=(ci,)) for ci in range(8)
            ]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            if errors:
                raise errors[0]
            dt = time.perf_counter() - t0
            snap1 = _metrics.snapshot()

            def delta_of(name):
                tot = 0.0
                for k, v in snap1.items():
                    if isinstance(v, dict) or k.split(";")[0] != name:
                        continue
                    tot += v - (snap0.get(k) or 0)
                return tot

            return {
                "read_qps": round(sum(reads) / dt, 1),
                "writes_per_s": round(sum(writes) / dt, 1),
                "delta_applied": int(delta_of("stager.delta_applied")),
                "delta_fallback": int(delta_of("stager.delta_fallback")),
                "invalidation_misses": int(
                    delta_of("stager.misses_invalidation")
                ),
                "restaged_bytes": int(delta_of("stager.restaged_bytes")),
            }

        seg = max(2.0, min(7.0, budget_s / 4))
        out["read_only"] = arm(0.0, True, seg, nonce=0)
        out["rw_delta"] = arm(WRITE_FRAC, True, seg, nonce=1)
        out["rw_full_restage"] = arm(WRITE_FRAC, False, seg, nonce=2)
        ro = out["read_only"]["read_qps"]
        if ro:
            out["rw_delta_vs_read_only"] = round(
                out["rw_delta"]["read_qps"] / ro, 3
            )
            out["rw_full_vs_read_only"] = round(
                out["rw_full_restage"]["read_qps"] / ro, 3
            )
        full = out["rw_full_restage"]
        nwrites = full["writes_per_s"] * seg
        if nwrites:
            # the per-write re-upload burden delta staging removes; on
            # this CPU rig re-staging only costs host packing, but on a
            # tunneled chip these bytes ride the host→HBM link — divide
            # by link bandwidth for the wall-clock a write mix would
            # add without delta staging
            out["restaged_bytes_per_write_without_delta"] = int(
                full["restaged_bytes"] / nwrites
            )
    finally:
        h.close()
        _shutil.rmtree(tmp, ignore_errors=True)
    return out


def _hist_count_delta(snap0: dict, snap1: dict, name: str) -> int:
    """Observation-count delta of a summary metric between two
    ``metrics.snapshot()`` calls, summed over label sets."""
    tot = 0
    for k, v in snap1.items():
        if not isinstance(v, dict) or k.split(";")[0] != name:
            continue
        prev = snap0.get(k)
        tot += v.get("count", 0) - (prev.get("count", 0) if prev else 0)
    return tot


def _ingest_sustained_probe(budget_s: float) -> dict:
    """Durable streaming ingest steady state (ISSUE 11): c12
    closed-loop TopN/chain reads on the device executor while >=10% of
    operations submit 16-mutation batches through the write-ahead
    IngestQueue — each submit blocks until its wave is group-committed
    + fsynced — interleaved with read-only segments on the same warm
    state (median of adjacent-pair ratios, because this rig's core
    speed drifts 2x within a minute). Reports the read-qps ratio
    (acceptance: >=0.8x at >=10% writes), write-ack p50/p99, wave
    coalescing stats, and the bounded-staleness figure (coalesce window
    + observed ack p99). The post-ingest state is checked bit-for-bit
    against an uncached CPU oracle, and a federated sub-arm drives
    write waves through a replicated-solo leader while a follower
    rejoins mid-stream and must converge. Chip-independent (the
    contrast is queue/commit economics, not kernel speed)."""
    import shutil as _shutil
    import tempfile

    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import DeviceStager, Executor
    from pilosa_tpu.server.api import API
    from pilosa_tpu.server.ingest import IngestQueue
    from pilosa_tpu.utils import metrics as _metrics

    R, BITS = 128, 3000
    WRITE_FRAC = 0.10  # fraction of ops that are batch submits
    BATCH = 16  # mutations per submit
    # a much wider coalesce window than the server default (2 ms):
    # this rig is 1-core, and every wave carries a fixed read-side tax
    # (full-matrix delta scatter on next TopN + fsync + commit, ~5-8 ms
    # total) — at 10% batch submits the wave rate, not the mutation
    # count, decides the read hit, so coalescing harder trades ack
    # latency for most of the read throughput
    WAVE_INTERVAL = 0.050
    # enough closed-loop workers that ack waits (mostly coalesce-window
    # sleep, GIL-free) overlap with reads instead of idling the core;
    # both arms run the same count so the baseline is comparable
    N_WORKERS = 12
    tmp = tempfile.mkdtemp(prefix="pilosa_ingest_probe_")
    out = {
        "note": (
            "c12 closed-loop device reads with 10% of ops submitting "
            "16-mutation batches through the durable IngestQueue (ack "
            "= group commit + fsync), interleaved with read-only "
            "segments on the same warm state; ratio = median of "
            "adjacent pairs; staleness bound = coalesce window + ack "
            "p99"
        ),
        "write_frac": WRITE_FRAC,
        "batch_size": BATCH,
        "wave_interval_s": WAVE_INTERVAL,
    }
    h = Holder(tmp)
    h.open()
    try:
        idx = h.create_index("ing")
        fld = idx.create_field("f")
        rng = np.random.default_rng(53)
        rows, cols = [], []
        for r_ in range(R):
            rows += [r_] * BITS
            cols += rng.integers(0, 1 << 20, size=BITS).tolist()
        fld.import_bits(rows, cols)
        queries = [
            "TopN(f, n=10)",
            "TopN(f, Row(f=3), n=8)",
            "Count(Intersect(Row(f=1), Row(f=2)))",
            "Count(Union(Row(f=4), Row(f=5), Row(f=6)))",
        ]

        def _batch_muts(wrng):
            # streaming-shaped writes: uniform over the whole row space
            # (an event stream lands anywhere, unlike rw_mix's
            # adversarial hot-row writes), mostly sets plus some clears
            # so OP_REMOVE coalescing and replay ride along. The staged
            # read set still pays — the full-matrix TopN entry absorbs
            # every wave, per-row entries only the waves touching them
            rs = wrng.integers(0, R, size=BATCH)
            cs = wrng.integers(0, 1 << 20, size=BATCH)
            ss = wrng.random(BATCH) > 0.2
            return rs.tolist(), cs.tolist(), ss.tolist()

        # one executor + one queue for the WHOLE probe: segments
        # toggle the write mix on warm shared state, so pairing adjacent
        # segments cancels the rig's drift (this shared core's speed
        # moves 2x+ within a minute — a single A/B split mismeasures)
        ex = Executor(
            h,
            device_policy="always",
            stager=DeviceStager(delta_enabled=True),
        )
        for qq in queries:  # warm: compile + stage
            ex.execute("ing", qq)
        iq = IngestQueue(API(h, ex), wave_max=2048, wave_interval=WAVE_INTERVAL)
        wrng = np.random.default_rng(9000)
        for _ in range(40):
            # absorb the write-path compiles (wave apply, delta scatter
            # shapes) AND drive the fragment's ranked cache to its
            # written-to steady state — wave applies maintain the rank
            # cache, which makes the filtered-TopN read ~3x cheaper, so
            # a cold-cache read-only baseline would understate the
            # denominator and flatter the ratio
            rs, cs, ss = _batch_muts(wrng)
            iq.submit("ing", "f", rs, cs, ss)
            for qq in queries:
                ex.execute("ing", qq)

        ack_lat: list = []
        lat_mu = threading.Lock()

        def run_seg(write_frac, seconds, nonce):
            stop = time.perf_counter() + seconds
            reads = [0] * N_WORKERS
            acked = [0] * N_WORKERS
            errors: list = []

            def worker(ci):
                wr = np.random.default_rng(2000 + nonce * N_WORKERS + ci)
                i = ci
                try:
                    while time.perf_counter() < stop and not errors:
                        if write_frac and wr.random() < write_frac:
                            rs, cs, ss = _batch_muts(wr)
                            t1 = time.perf_counter()
                            iq.submit("ing", "f", rs, cs, ss)
                            lat = time.perf_counter() - t1
                            acked[ci] += BATCH
                            with lat_mu:
                                ack_lat.append(lat)
                        else:
                            ex.execute("ing", queries[i % len(queries)])
                            reads[ci] += 1
                        i += 1
                except BaseException as e:
                    errors.append(e)

            ts = [
                threading.Thread(target=worker, args=(ci,))
                for ci in range(N_WORKERS)
            ]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            if errors:
                raise errors[0]
            dt = time.perf_counter() - t0
            return sum(reads) / dt, sum(acked) / dt

        # interleaved pairs: ro seg then ingest seg, repeated; the
        # reported ratio is the MEDIAN of per-pair ratios
        n_pairs = 3
        seg = max(1.5, min(4.0, budget_s / (2 * n_pairs + 1)))
        snap0 = _metrics.snapshot()
        st0_waves = iq.stats()["waves"]
        ro_qps, ing_qps, ing_mut = [], [], []
        for k in range(n_pairs):
            r_qps, _ = run_seg(0.0, seg, nonce=2 * k)
            w_qps, w_mut = run_seg(WRITE_FRAC, seg, nonce=2 * k + 1)
            ro_qps.append(round(r_qps, 1))
            ing_qps.append(round(w_qps, 1))
            ing_mut.append(w_mut)
        snap1 = _metrics.snapshot()
        st = iq.stats()
        iq.close()

        def delta_of(name):
            tot = 0.0
            for k, v in snap1.items():
                if isinstance(v, dict) or k.split(";")[0] != name:
                    continue
                tot += v - (snap0.get(k) or 0)
            return tot

        lats = np.array(ack_lat)
        waves = st["waves"] - st0_waves
        acked_total = seg * sum(ing_mut)
        out["read_only"] = {
            "read_qps": round(float(np.median(ro_qps)), 1),
            "segments": ro_qps,
        }
        out["sustained_ingest"] = {
            "read_qps": round(float(np.median(ing_qps)), 1),
            "segments": ing_qps,
            "acked_mutations_per_s": round(sum(ing_mut) / len(ing_mut), 1),
            "submits": len(ack_lat),
            "write_ack_p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 2),
            "write_ack_p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 2),
            "waves": waves,
            "mean_wave_size": round(acked_total / waves, 1) if waves else None,
            "fsyncs": _hist_count_delta(
                snap0, snap1, "ingest.fsync_seconds.hist"
            ),
            "delta_applied": int(delta_of("stager.delta_applied")),
            "restaged_bytes": int(delta_of("stager.restaged_bytes")),
            # readers lag a submitted mutation by at most the coalesce
            # window + one wave commit — the observed ack p99 bounds
            # the latter
            "staleness_bound_ms": round(
                WAVE_INTERVAL * 1e3 + float(np.percentile(lats, 99)) * 1e3, 2
            ),
        }
        out["ingest_vs_read_only"] = round(
            float(np.median([w / r for r, w in zip(ro_qps, ing_qps) if r])), 3
        )
        # post-ingest oracle: the warm device path (staged deltas from
        # all committed waves) must match a fresh uncached CPU executor
        oracle = Executor(h, device_policy="never")
        checks = queries + [f"Count(Row(f={r_}))" for r_ in range(16)]
        mism = 0
        for qq in checks:
            (got,) = ex.execute("ing", qq)
            (want,) = oracle.execute("ing", qq)
            if str(got) != str(want):
                mism += 1
        out["oracle_checks"] = len(checks)
        out["result_mismatches_vs_uncached_oracle"] = mism
    finally:
        h.close()
        _shutil.rmtree(tmp, ignore_errors=True)

    # federated sub-arm: write waves through a replicated-solo leader
    # (one KIND_WRITE_WAVE descriptor per wave) while a follower
    # rejoins mid-stream; the follower must re-stage the pre-rejoin
    # waves and receive the post-rejoin ones through replication
    if budget_s > 12:
        try:
            out["federated"] = _ingest_federated_subarm()
        except Exception as e:
            out["federated"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _ingest_federated_subarm() -> dict:
    """Boot a replicated-solo federated leader in-process, ingest write
    waves through HTTP, rejoin a follower mid-stream, and verify the
    follower converges to the leader's bit state — the wave-replication
    leg of the durability story (tests/test_federation.py exercises
    the full lifecycle; this records the numbers)."""
    import json as _json
    import shutil as _shutil
    import socket as _socket
    import tempfile
    import urllib.request

    from pilosa_tpu.server import ClusterConfig, Config, Server

    tmp = tempfile.mkdtemp(prefix="pilosa_ingest_fed_")
    out: dict = {}
    servers: list = []
    try:
        # the leader needs the cluster plane wired (federation.wire
        # installs the gang's replicate hook on it) — a 1-node cluster
        # is enough, the follower rides the gang plane only
        with _socket.socket() as _s:
            _s.bind(("127.0.0.1", 0))
            pa = _s.getsockname()[1]
        a = Server(
            Config(
                data_dir=os.path.join(tmp, "lead"),
                bind=f"127.0.0.1:{pa}",
                device_policy="never",
                metric="none",
                federation_leader=True,
                cluster=ClusterConfig(
                    disabled=False,
                    coordinator=True,
                    hosts=[f"127.0.0.1:{pa}"],
                    probe_interval=0,
                ),
            )
        )
        a.open()
        servers.append(a)

        def post(uri, path, body):
            r = urllib.request.Request(uri + path, data=body, method="POST")
            with urllib.request.urlopen(r, timeout=30) as resp:
                return _json.loads(resp.read() or b"{}")

        post(a.uri, "/index/i", b"{}")
        post(a.uri, "/index/i/field/f", b"{}")
        rng = np.random.default_rng(31)

        def ingest_waves(n_batches, batch=32):
            total = 0
            for _ in range(n_batches):
                rs = rng.integers(0, 64, size=batch).tolist()
                cs = rng.integers(0, 1 << 20, size=batch).tolist()
                body = _json.dumps({"rowIDs": rs, "columnIDs": cs}).encode()
                r = post(a.uri, "/index/i/field/f/ingest", body)
                total += r["acked"]
            return total

        out["pre_rejoin_acked"] = ingest_waves(8)
        f = Server(
            Config(
                data_dir=os.path.join(tmp, "fol"),
                bind="127.0.0.1:0",
                device_policy="never",
                metric="none",
                federation_rejoin=a.uri,
            )
        )
        f.open()
        servers.append(f)
        t0 = time.perf_counter()
        t_end = time.monotonic() + 30
        while a.multihost.state != "ACTIVE" and time.monotonic() < t_end:
            time.sleep(0.05)
        out["rejoin_seconds"] = round(time.perf_counter() - t0, 2)
        out["gang_state"] = a.multihost.state
        out["post_rejoin_acked"] = ingest_waves(8)

        def count_on(uri):
            r = post(uri, "/index/i/query", b"Count(Union(Row(f=0), Row(f=1)))")
            return r["results"][0]

        want = count_on(a.uri)
        t0 = time.perf_counter()
        t_end = time.monotonic() + 30
        while count_on(f.uri) != want and time.monotonic() < t_end:
            time.sleep(0.05)
        got = count_on(f.uri)
        out["follower_convergence_seconds"] = round(time.perf_counter() - t0, 2)
        out["follower_converged"] = got == want
        out["leader_count"] = want
        out["follower_count"] = got
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:
                pass
        _shutil.rmtree(tmp, ignore_errors=True)
    return out


def _continuous_batching_probe(budget_s: float) -> dict:
    """Continuous-batching dispatch engine A/B (ISSUE 8): closed-loop
    c8/c32 heterogeneous reads (TopN/Count/Intersect/chain) against two
    bare device executors over the same holder — one routing through
    the async dispatch engine, one blocking per call — recording qps
    per concurrency plus the measured device-idle fraction per arm.
    Chip-independent for the CONTRAST (the engine's wave grouping,
    dedup, and in-flight overlap all exercise on the CPU backend); the
    absolute gap widens on a tunneled chip where each blocking call
    holds a thread for a full RTT."""
    import shutil as _shutil
    import tempfile

    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.utils import metrics as _metrics

    R, BITS = 256, 4000
    tmp = tempfile.mkdtemp(prefix="pilosa_dispatch_probe_")
    out = {
        "note": (
            "closed-loop heterogeneous reads on bare device executors: "
            "dispatch engine (async waves) vs blocking per-call "
            "execution; device_idle_fraction = wall time with no device "
            "work in flight"
        )
    }
    h = Holder(tmp)
    h.open()
    try:
        idx = h.create_index("cb")
        fld = idx.create_field("f")
        rng = np.random.default_rng(77)
        rows, cols = [], []
        for r_ in range(R):
            rows += [r_] * BITS
            cols += rng.integers(0, 1 << 20, size=BITS).tolist()
        fld.import_bits(rows, cols)
        # heterogeneous mix — distinct canonical signatures coexist in
        # one wave; closed-loop round-robin also produces exact
        # duplicates in the backlog, which the engine collapses
        queries = [
            "TopN(f, n=10)",
            "TopN(f, Row(f=3), n=8)",
            "Count(Row(f=1))",
            "Count(Intersect(Row(f=1), Row(f=2)))",
            "Count(Union(Row(f=4), Row(f=5), Row(f=6)))",
            "Count(Difference(Row(f=7), Row(f=8)))",
        ]

        def exec_sum(snap):
            tot = 0.0
            for k, v in snap.items():
                if k.split(";")[0] == "spmd.execute_seconds.hist":
                    tot += (v or {}).get("sum", 0.0)
            return tot

        def arm(engine: bool, n_clients: int, seconds: float):
            ex = Executor(h, device_policy="always", dispatch_enabled=engine)
            try:
                for q in queries:  # warm: compile + stage
                    ex.execute("cb", q)
                counts = [0] * n_clients
                errors: list = []
                stop = time.perf_counter() + seconds

                def client(ci):
                    i = ci
                    try:
                        while time.perf_counter() < stop and not errors:
                            ex.execute("cb", queries[i % len(queries)])
                            counts[ci] += 1
                            i += 1
                    except BaseException as e:
                        errors.append(e)

                snap0 = _metrics.snapshot()
                ts = [
                    threading.Thread(target=client, args=(ci,))
                    for ci in range(n_clients)
                ]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                if errors:
                    raise errors[0]
                dt = time.perf_counter() - t0
                if engine:
                    idle = ex.dispatch_engine.stats()["device_idle_fraction"]
                else:
                    # blocking arm has no engine accounting: idle =
                    # 1 - (device execute seconds / wall). On a
                    # tunneled chip the RTT rides INSIDE the blocking
                    # call, so this flatters the blocking arm if
                    # anything.
                    busy = exec_sum(_metrics.snapshot()) - exec_sum(snap0)
                    idle = max(0.0, min(1.0, 1.0 - busy / dt))
                return sum(counts) / dt, idle
            finally:
                ex.close()

        seg = max(2.0, min(6.0, budget_s / 7))
        for n in (8, 32):
            qps_b, idle_b = arm(False, n, seg)
            qps_e, idle_e = arm(True, n, seg)
            out[f"c{n}_qps"] = round(qps_e, 1)
            out[f"c{n}_qps_blocking"] = round(qps_b, 1)
            out[f"c{n}_speedup"] = round(qps_e / qps_b, 2) if qps_b else None
            out[f"c{n}_device_idle_fraction"] = round(idle_e, 4)
            out[f"c{n}_device_idle_fraction_blocking"] = round(idle_b, 4)
        # hot-set arm: 4 distinct TopN-heavy queries (the dashboard /
        # head-of-Zipf shape the plan cache targets) — wave dedup can
        # collapse c clients toward 4 executions. On a 1-core CPU rig
        # the speedup ceiling at c8 is clients/distinct = 2x; on chip
        # the ceiling is the RTT overlap instead.
        queries[:] = [
            "TopN(f, n=10)",
            "TopN(f, Row(f=3), n=8)",
            "TopN(f, Row(f=5), n=8)",
            "Count(Row(f=1))",
        ]
        for n in (8, 32):
            qps_b, _ = arm(False, n, seg)
            qps_e, _ = arm(True, n, seg)
            out[f"hotset_c{n}_qps"] = round(qps_e, 1)
            out[f"hotset_c{n}_qps_blocking"] = round(qps_b, 1)
            out[f"hotset_c{n}_speedup"] = (
                round(qps_e / qps_b, 2) if qps_b else None
            )
    finally:
        h.close()
        _shutil.rmtree(tmp, ignore_errors=True)
    return out


def _tiering_oversub_probe(budget_s: float) -> dict:
    """Hot-set latency under HBM oversubscription (ISSUE 17): the same
    cyclic hot-set read loop served from a stager whose T0 budget holds
    the whole set (1x arm) vs one-third of it (3x arm — every lap
    re-enters most rows, with the T1 host compressed tier, the
    compressed-upload expansion path, and plan-driven prefetch
    absorbing the cost). Reports per-arm p50/p95, T0 hit rate and
    restaged bytes, T1 hit rate, compressed-upload PCIe savings, and
    prefetch accuracy. Chip-independent (the contrast is residency
    economics, not kernel speed)."""
    import shutil as _shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu import SHARD_WIDTH
    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import DeviceStager, Executor
    from pilosa_tpu.utils import metrics as _metrics

    R, BITS = 18, 1200
    ROW_BYTES = (SHARD_WIDTH // 32) * 4

    def msum(snap, name):
        return sum(
            v
            for k, v in snap.items()
            if not isinstance(v, dict) and k.startswith(name)
        )

    tmp = tempfile.mkdtemp(prefix="pilosa_tiering_")
    out = {
        "note": (
            "Zipf hot-set Count loop, 4 clients with think time (sub-"
            "saturation, so latency measures service + interference, not "
            "closed-loop queueing); the 1x arm's T0 holds the whole "
            "working set (each row stages as both the row and row_stack "
            "forms), the 3x arm a third of it (CPU executor; the "
            "contrast is residency economics, not kernel speed)"
        ),
        "rows": R,
        "row_bytes": ROW_BYTES,
    }
    h = Holder(tmp)
    h.open()
    try:
        idx = h.create_index("tv")
        fld = idx.create_field("f")
        rng = np.random.default_rng(29)
        rows, cols = [], []
        for r_ in range(R):
            rows += [r_] * BITS
            cols += rng.integers(0, SHARD_WIDTH, size=BITS).tolist()
        fld.import_bits(rows, cols)
        queries = [f"Count(Row(f={k}))" for k in range(R)]
        # one fixed Zipf draw sequence shared by both arms: a head-heavy
        # hot set (the dashboard shape), so the hot-set p50 measures the
        # resident head while the tail exercises T1 re-entry
        zdraw = (np.random.default_rng(31).zipf(1.3, size=100_000) - 1) % R
        # the "hot set" for the headline percentile: the Zipf head small
        # enough that both arms can keep it T0-resident (2 staged forms
        # per row x HOT rows < the 3x arm's budget)
        HOT = 4

        def arm(budget_rows, tiered, seconds):
            st = DeviceStager(
                budget_bytes=budget_rows * ROW_BYTES,
                tier1_max_bytes=(128 << 20) if tiered else 0,
                compressed_min_ratio=1.5 if tiered else 0.0,
            )
            # max_wave=1 keeps cold restages out of hot queries' waves
            # (no wave-mate inflation) while arrival bursts still leave
            # a backlog for the plan-driven prefetcher to stage ahead
            ex = Executor(
                h, device_policy="always", stager=st, dispatch_max_wave=1
            )
            try:
                for q in queries[:4]:  # warm the compile caches
                    ex.execute("tv", q)
                snap0 = _metrics.snapshot()
                lats: list = []
                mu = threading.Lock()
                stop = time.perf_counter() + seconds

                def client(cid):
                    mine = []
                    i = cid * 7919  # offset so clients spread over the draw
                    while time.perf_counter() < stop:
                        r_ = int(zdraw[i % len(zdraw)])
                        i += 1
                        t0 = time.perf_counter()
                        ex.execute("tv", queries[r_])
                        mine.append((r_, time.perf_counter() - t0))
                        # think time keeps the arms below saturation so
                        # p50 measures service (+ restage interference),
                        # not closed-loop queue depth
                        time.sleep(0.008)
                    with mu:
                        lats.extend(mine)

                with ThreadPoolExecutor(max_workers=4) as pool:
                    for f in [pool.submit(client, c * 5) for c in range(4)]:
                        f.result()
                arr = np.asarray(lats)
                lat = np.sort(arr[:, 1])
                hot = np.sort(arr[arr[:, 0] < HOT, 1])

                def pct(a, p):
                    return round(
                        float(a[min(len(a) - 1, int(p * len(a)))]) * 1e3, 3
                    )

                snap1 = _metrics.snapshot()
                total = st.hits + st.misses
                res = {
                    "queries": len(lat),
                    "p50_ms": pct(lat, 0.50),
                    "p95_ms": pct(lat, 0.95),
                    "hot_queries": len(hot),
                    "hot_p50_ms": pct(hot, 0.50),
                    "t0_hit_rate": round(st.hits / max(total, 1), 4),
                    "restaged_bytes": int(
                        msum(snap1, _metrics.STAGER_RESTAGED_BYTES)
                        - msum(snap0, _metrics.STAGER_RESTAGED_BYTES)
                    ),
                }
                if tiered and st.tier1 is not None:
                    t1 = st.tier1.stats()
                    res["t1_hit_rate"] = round(
                        t1["hits"] / max(t1["hits"] + t1["misses"], 1), 4
                    )
                    res["compressed_upload_bytes_saved"] = int(
                        msum(snap1, _metrics.TIERING_UPLOAD_BYTES_SAVED)
                        - msum(snap0, _metrics.TIERING_UPLOAD_BYTES_SAVED)
                    )
                    pf = (
                        ex.prefetcher.stats()
                        if ex.prefetcher is not None
                        else {}
                    )
                    res["prefetch_issued"] = pf.get("issued", 0)
                    res["prefetch_accuracy"] = pf.get("accuracy", 0.0)
                return res
            finally:
                ex.close()

        seg = max(2.0, min(8.0, budget_s / 2.5))
        # the hot working set is ~2 rows' bytes per row (row + row_stack
        # forms) — the 1x arm holds all of it plus transient slack, the
        # 3x arm a third
        ws_rows = 2 * R + 4
        out["oversub_1x"] = arm(ws_rows, tiered=True, seconds=seg)
        out["oversub_3x"] = arm(ws_rows // 3, tiered=True, seconds=seg)
        p50_1x = out["oversub_1x"]["p50_ms"]
        p50_3x = out["oversub_3x"]["p50_ms"]
        out["p50_1x_over_3x"] = round(p50_1x / p50_3x, 3) if p50_3x else None
        # the headline: how much of the fully-resident arm's hot-set p50
        # the 3x oversubscribed arm keeps — tiering + prefetch must hold
        # the Zipf head resident while the tail churns through T1
        # (1.0 = no penalty; the tiering acceptance bar is >= 0.9)
        h1 = out["oversub_1x"]["hot_p50_ms"]
        h3 = out["oversub_3x"]["hot_p50_ms"]
        out["hot_p50_1x_over_3x"] = round(h1 / h3, 3) if h3 else None
    finally:
        h.close()
        _shutil.rmtree(tmp, ignore_errors=True)
    return out


def _dashboard_mix_probe(budget_s: float) -> dict:
    """Interactive latency under an analytics panel load (ISSUE 18):
    the same fixed-concurrency TopN/Count interactive loop measured
    alone (analytics-off arm) and with a GroupBy dashboard panel loop
    running alongside (analytics-on arm). The analytic panels execute
    as fused segmented reductions in their own launches, so the
    headline is the interactive p50 ratio between the arms (the
    acceptance bar is < 1.10 — panels must not burn interactive p50)
    plus fused launches per panel (the one-launch-per-panel proof
    under concurrency). Chip-independent (the contrast is isolation,
    not kernel speed)."""
    import shutil as _shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from pilosa_tpu import SHARD_WIDTH
    from pilosa_tpu.core import FieldOptions, Holder
    from pilosa_tpu.core.field import FIELD_TYPE_INT
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.utils import metrics as _metrics

    NSHARDS, BITS = 2, 1500
    SEG_ROWS, DEV_ROWS = 6, 4

    def msum(snap, name):
        return sum(
            v
            for k, v in snap.items()
            if not isinstance(v, dict) and k.startswith(name)
        )

    tmp = tempfile.mkdtemp(prefix="pilosa_dashmix_")
    out = {
        "note": (
            "4 interactive clients (TopN/Count mix, think time, sub-"
            "saturation) measured alone vs with one GroupBy(seg x dev, "
            "Sum) panel loop alongside on the same executor; "
            "interactive_p50_ratio = with-panels / without (< 1.10 = "
            "panels don't burn interactive p50), fused_launches_per_"
            "panel proves each panel stays one segmented-reduction "
            "launch under concurrency"
        ),
        "shards": NSHARDS,
        "panel_groups": SEG_ROWS * DEV_ROWS,
    }
    h = Holder(tmp)
    h.open()
    try:
        idx = h.create_index("dm")
        seg = idx.create_field("seg")
        dev = idx.create_field("dev")
        val = idx.create_field(
            "v", FieldOptions(type=FIELD_TYPE_INT, min=0, max=1000)
        )
        rng = np.random.default_rng(37)
        ncols = NSHARDS * SHARD_WIDTH
        rows, cols = [], []
        for r_ in range(SEG_ROWS):
            rows += [r_] * BITS
            cols += rng.integers(0, ncols, size=BITS).tolist()
        seg.import_bits(rows, cols)
        rows, cols = [], []
        for r_ in range(DEV_ROWS):
            rows += [r_] * BITS
            cols += rng.integers(0, ncols, size=BITS).tolist()
        dev.import_bits(rows, cols)
        vcols = rng.choice(ncols, size=4000, replace=False).tolist()
        val.import_values(vcols, rng.integers(0, 1000, size=4000).tolist())

        interactive = [f"Count(Row(seg={k}))" for k in range(SEG_ROWS)] + [
            "TopN(seg, n=4)",
            "TopN(dev, n=3)",
            f"Count(Intersect(Row(seg=1), Row(dev=2)))",
        ]
        panel = "GroupBy(Rows(seg), Rows(dev), Sum(field=v))"

        ex = Executor(h, device_policy="always", fusion_enabled=True)
        try:
            for q in interactive:  # warm the compile caches
                ex.execute("dm", q)
            ex.execute("dm", panel)

            def arm(with_panels: bool, seconds: float):
                snap0 = _metrics.snapshot()
                lats: list = []
                mu = threading.Lock()
                stop = time.perf_counter() + seconds
                panels = [0]

                def client(cid):
                    mine, i = [], cid * 3
                    while time.perf_counter() < stop:
                        q = interactive[i % len(interactive)]
                        i += 1
                        t0 = time.perf_counter()
                        ex.execute("dm", q)
                        mine.append(time.perf_counter() - t0)
                        # think time keeps the interactive side below
                        # saturation so p50 measures service +
                        # panel interference, not queue depth
                        time.sleep(0.006)
                    with mu:
                        lats.extend(mine)

                def panel_loop():
                    while time.perf_counter() < stop:
                        ex.execute("dm", panel)
                        panels[0] += 1
                        # dashboard refresh cadence (~4 Hz): panels are
                        # periodic redraws, not a saturating loop — the
                        # contrast measured is fused-launch interference
                        # on interactive traffic, not core starvation
                        time.sleep(0.25)

                with ThreadPoolExecutor(max_workers=5) as pool:
                    futs = [pool.submit(client, c) for c in range(4)]
                    if with_panels:
                        futs.append(pool.submit(panel_loop))
                    for f in futs:
                        f.result()
                snap1 = _metrics.snapshot()
                lat = np.sort(np.asarray(lats))

                def pct(a, p):
                    return round(
                        float(a[min(len(a) - 1, int(p * len(a)))]) * 1e3, 3
                    )

                res = {
                    "interactive_queries": len(lat),
                    "interactive_p50_ms": pct(lat, 0.50),
                    "interactive_p95_ms": pct(lat, 0.95),
                    "panels": panels[0],
                }
                if with_panels and panels[0]:
                    launches = msum(
                        snap1, _metrics.FUSION_GROUPBY_LAUNCHES
                    ) - msum(snap0, _metrics.FUSION_GROUPBY_LAUNCHES)
                    res["fused_launches_per_panel"] = round(
                        launches / panels[0], 3
                    )
                return res

            seg_s = max(2.0, min(8.0, budget_s / 2.5))
            arm(True, min(2.0, seg_s))  # throwaway: thread-pool +
            # allocator steady state, so the off arm isn't flattered
            # by a cold first lap
            out["analytics_off"] = arm(False, seg_s)
            out["analytics_on"] = arm(True, seg_s)
            p_off = out["analytics_off"]["interactive_p50_ms"]
            p_on = out["analytics_on"]["interactive_p50_ms"]
            out["interactive_p50_ratio"] = (
                round(p_on / p_off, 3) if p_off else None
            )
        finally:
            ex.close()
    finally:
        h.close()
        _shutil.rmtree(tmp, ignore_errors=True)
    return out


def _plan_cache_probe(budget_s: float) -> dict:
    """Plan result cache under Zipf-repeated traffic (ISSUE 4): a
    TopN/Intersect query mix drawn from a Zipf distribution (the
    dashboard / hot-query traffic shape the serving stack targets) runs
    through an executor with and without the generation-stamped result
    cache. Reports hot vs cold qps, the achieved hit ratio, and bytes
    resident — then a 1%-write arm proving invalidation correctness:
    every read in the write arm is compared bit-for-bit against an
    uncached oracle executor over the same holder, and the arm must
    observe > 0 generation invalidations. Chip-independent (the
    contrast is cache economics, not kernel speed)."""
    import shutil as _shutil
    import tempfile

    from pilosa_tpu.core import Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.plan.cache import PlanCache

    R, BITS = 128, 2000
    N_DISTINCT = 48  # distinct queries in the pool
    ZIPF_A = 1.3  # Zipf exponent: ~85-90% of draws hit the head
    WRITE_FRAC = 0.01
    tmp = tempfile.mkdtemp(prefix="pilosa_plancache_")
    out = {
        "note": (
            "Zipf-repeated TopN/Intersect mix through the plan result "
            "cache (CPU executor; the contrast is cache vs recompute, "
            "not kernel speed); write arm compares every cached read "
            "against an uncached oracle"
        ),
        "zipf_a": ZIPF_A,
        "distinct_queries": N_DISTINCT,
        "write_frac": WRITE_FRAC,
    }
    h = Holder(tmp)
    h.open()
    try:
        idx = h.create_index("zc")
        fld = idx.create_field("f")
        rng = np.random.default_rng(17)
        rows, cols = [], []
        for r_ in range(R):
            rows += [r_] * BITS
            cols += rng.integers(0, 1 << 20, size=BITS).tolist()
        fld.import_bits(rows, cols)
        pool = []
        for i in range(N_DISTINCT):
            a, b, c = i % R, (i * 7 + 1) % R, (i * 13 + 2) % R
            pool.append(
                [
                    f"TopN(f, Row(f={a}), n=10)",
                    f"Count(Intersect(Row(f={a}), Row(f={b})))",
                    f"Count(Union(Row(f={a}), Row(f={b}), Row(f={c})))",
                ][i % 3]
            )
        # one fixed Zipf draw sequence, shared by all arms
        zdraw = (np.random.default_rng(23).zipf(ZIPF_A, size=200_000) - 1) % N_DISTINCT

        def arm(ex, seconds, write_frac=0.0, oracle=None, wnonce=0):
            wrng = np.random.default_rng(5000 + wnonce)
            stop = time.perf_counter() + seconds
            # oracle-checked arms run at the ORACLE's qps, so a pure
            # time budget can finish before 1% of ops were writes —
            # writes fire deterministically every 1/write_frac ops and
            # the arm runs on until a few landed (bounded at 3x budget)
            hard_stop = time.perf_counter() + seconds * 3
            every = int(1 / write_frac) if write_frac else 0
            reads = writes = mismatches = i = 0
            t0 = time.perf_counter()
            while time.perf_counter() < stop or (
                every and writes < 5 and time.perf_counter() < hard_stop
            ):
                if every and i % every == every - 1:
                    # writes land on the rows the hot queries read —
                    # the worst case for the cache, which is the point
                    fld.set_bit(
                        int(wrng.integers(0, 16)),
                        int(wrng.integers(0, 1 << 20)),
                    )
                    writes += 1
                else:
                    q = pool[zdraw[i % len(zdraw)]]
                    (got,) = ex.execute("zc", q)
                    if oracle is not None:
                        (want,) = oracle.execute("zc", q)
                        if str(got) != str(want):
                            mismatches += 1
                    reads += 1
                i += 1
            dt = time.perf_counter() - t0
            return reads / dt, writes / dt, mismatches

        cold_ex = Executor(h, device_policy="never")
        cached_ex = Executor(h, device_policy="never", plan_cache=PlanCache())
        seg = max(1.5, min(6.0, budget_s / 5))
        for q in pool[:6]:  # warm both paths' Python/JIT overheads
            cold_ex.execute("zc", q)
            cached_ex.execute("zc", q)
        cold_qps, _, _ = arm(cold_ex, seg)
        hot_qps, _, _ = arm(cached_ex, seg)
        st = cached_ex.plan_cache.stats()
        out["cold_qps"] = round(cold_qps, 1)
        out["hot_qps"] = round(hot_qps, 1)
        out["speedup"] = round(hot_qps / cold_qps, 2) if cold_qps else None
        out["hit_ratio"] = st["hit_ratio"]
        out["bytes_resident"] = st["bytes"]
        out["entries"] = st["entries"]
        # write arm: cached executor + 1% writes, every read checked
        # bit-for-bit against an uncached oracle on the same holder
        inv0 = cached_ex.plan_cache.stats()["invalidations"]
        w_qps, wps, mism = arm(
            cached_ex, seg, write_frac=WRITE_FRAC, oracle=cold_ex, wnonce=1
        )
        st = cached_ex.plan_cache.stats()
        out["write_arm"] = {
            # oracle double-execution halves qps; correctness arm, not
            # a throughput claim
            "read_qps_with_oracle_check": round(w_qps, 1),
            "writes_per_s": round(wps, 1),
            "invalidations": st["invalidations"] - inv0,
            "result_mismatches_vs_uncached_oracle": mism,
        }
    finally:
        h.close()
        _shutil.rmtree(tmp, ignore_errors=True)
    return out


def _tenant_mix_probe(budget_s: float) -> dict:
    """Multi-tenant isolation under an abusive neighbor (ISSUE 19):
    dozens of index tenants with Zipf-distributed offered load share
    one server, then one extra tenant goes flat-out at >=10x the rate
    its weight entitles it to. The abuser's excess must be refused with
    per-tenant 429s (admitted rate tracks its cap), and the p50 of the
    well-behaved population must move <10% vs a no-abuser baseline
    segment — its burst is invisible to everyone else."""
    import json as _json
    import shutil as _shutil
    import tempfile
    import urllib.error
    import urllib.request

    from pilosa_tpu.server import Config, Server

    n_tenants = int(os.environ.get("PILOSA_BENCH_TENANTS", 24))
    zipf_s = 1.1
    abuser = "noisy"
    abuser_qps = 5.0  # explicit cap == what its weight-1 share buys it

    out = {
        "note": (
            f"{n_tenants} Zipf-traffic tenants + 1 abusive tenant on one "
            "server (chip-independent: measures per-tenant admission and "
            "weighted-fair scheduling, not the kernel)"
        ),
        "tenants": n_tenants,
        "zipf_s": zipf_s,
    }
    tenants = [f"t{i}" for i in range(n_tenants)]
    tmp = tempfile.mkdtemp(prefix="pilosa_tenant_probe_")
    cfg = Config(
        data_dir=tmp,
        bind="127.0.0.1:0",
        device_policy="never",
        device_timeout=0,
        metric="none",
        tenant_weights=f"*=4,{abuser}=1",
        tenant_qps=f"{abuser}={abuser_qps:g}",
        tenant_objectives="*=500@0.99",
    )
    s = Server(cfg)
    s.open()
    try:
        def post(path, body):
            r = urllib.request.Request(s.uri + path, data=body, method="POST")
            with urllib.request.urlopen(r, timeout=30) as resp:
                return resp.read()

        for idx in tenants + [abuser]:
            post(f"/index/{idx}", b"{}")
            post(f"/index/{idx}/field/f", b"{}")
            post(f"/index/{idx}/query", b"Set(1, f=1)")

        # Zipf offered load: tenant i trickles at base/(i+1)^s qps. One
        # thread per tenant — a paced open-ish loop (sleep between
        # queries) so slow tenants don't block fast ones.
        paces = [
            1.0 / max(0.5, 8.0 / ((i + 1) ** zipf_s))
            for i in range(n_tenants)
        ]

        def drive(seconds, with_abuser):
            stop = time.perf_counter() + seconds
            lats: dict[str, list] = {t: [] for t in tenants}
            codes: dict[int, int] = {}
            non200: dict[str, int] = {}
            codes_lock = threading.Lock()
            errors = []

            def well_behaved(ti):
                t = tenants[ti]
                body = b"Count(Row(f=1))"
                try:
                    while time.perf_counter() < stop and not errors:
                        t0 = time.perf_counter()
                        try:
                            post(f"/index/{t}/query", body)
                            lats[t].append(time.perf_counter() - t0)
                        except urllib.error.HTTPError as e:
                            # a well-behaved tenant should never be
                            # shed; record it rather than abort the run
                            with codes_lock:
                                k = f"wb_{e.code}"
                                non200[k] = non200.get(k, 0) + 1
                        time.sleep(paces[ti])
                except BaseException as e:
                    errors.append(e)

            def abuse():
                body = b"Count(Row(f=1))"
                try:
                    while time.perf_counter() < stop and not errors:
                        try:
                            post(f"/index/{abuser}/query", body)
                            with codes_lock:
                                codes[200] = codes.get(200, 0) + 1
                        except urllib.error.HTTPError as e:
                            with codes_lock:
                                codes[e.code] = codes.get(e.code, 0) + 1
                            if e.code not in (429, 503):
                                raise
                            # nudge under the advertised Retry-After so
                            # shed churn doesn't melt the 1-core host
                            time.sleep(0.005)
                except BaseException as e:
                    errors.append(e)

            ts = [
                threading.Thread(target=well_behaved, args=(ti,))
                for ti in range(n_tenants)
            ]
            if with_abuser:
                ts.append(threading.Thread(target=abuse))
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            if errors:
                raise errors[0]
            dt = time.perf_counter() - t0
            return lats, codes, non200, dt

        def p50(xs):
            return sorted(xs)[len(xs) // 2] if xs else 0.0

        seg = max(3.0, min(9.0, (budget_s - 4.0) / 2.0))
        drive(min(2.0, budget_s * 0.1), with_abuser=False)  # warm
        base_lats, _, _, _ = drive(seg, with_abuser=False)
        mix_lats, codes, non200, dt = drive(seg, with_abuser=True)

        pool_base = [v for xs in base_lats.values() for v in xs]
        pool_mix = [v for xs in mix_lats.values() for v in xs]
        b50, m50 = p50(pool_base), p50(pool_mix)
        admitted = codes.get(200, 0)
        throttled = codes.get(429, 0)
        offered_rate = (admitted + throttled) / dt
        admitted_rate = admitted / dt
        out["abuser"] = {
            "weight": 1,
            "qps_cap": abuser_qps,
            "offered_rate": round(offered_rate, 1),
            "admitted_rate": round(admitted_rate, 2),
            "throttled_429": throttled,
            "offered_x_cap": round(offered_rate / abuser_qps, 1),
            "codes": dict(codes),
        }
        out["well_behaved_p50_ms"] = {
            "no_abuser": round(b50 * 1000.0, 3),
            "with_abuser": round(m50 * 1000.0, 3),
            "delta_pct": round((m50 - b50) / b50 * 100.0, 1) if b50 else 0.0,
        }
        out["per_tenant_p50_ms_with_abuser"] = {
            t: round(p50(xs) * 1000.0, 3) for t, xs in mix_lats.items()
        }
        out["well_behaved_non_200s"] = dict(non200)
        snap = _json.loads(
            urllib.request.urlopen(s.uri + "/debug/tenancy", timeout=30).read()
        )
        out["isolated"] = bool(
            throttled > 0
            and not non200
            and offered_rate >= abuser_qps * 10
            and admitted_rate <= abuser_qps * (1.0 + 2.0 / seg) * 1.5
            and snap.get("pipeline", {}).get("weighted_fair")
        )
    finally:
        s.close()
        _shutil.rmtree(tmp, ignore_errors=True)
    return out


def _keyed_mix_probe(budget_s: float) -> dict:
    """Keyed-vs-raw serving cost (ISSUE 20): the same warm TopN traffic
    through a keyed index (string keys resolved pre-canonicalization,
    results reverse-translated through the bounded LRU) and through its
    raw-id twin. Acceptance: keyed warm qps >= 0.9x raw (the translate
    layer must be a lookup, not a tax); the LRU hit ratio is reported —
    warm reverse translation should be ~all hits."""
    import json as _json
    import shutil as _shutil
    import tempfile
    import urllib.request

    from pilosa_tpu.server import Config, Server

    n_cols = int(os.environ.get("PILOSA_BENCH_KEYED_COLS", 2000))
    n_rows = 16

    out = {
        "note": (
            "warm TopN qps through a keyed index vs its raw-id twin "
            "(chip-independent: measures the translate layer, not the "
            "kernel)"
        ),
        "columns": n_cols,
        "rows": n_rows,
    }
    tmp = tempfile.mkdtemp(prefix="pilosa_keyed_probe_")
    cfg = Config(
        data_dir=tmp,
        bind="127.0.0.1:0",
        device_policy="never",
        device_timeout=0,
        metric="none",
    )
    s = Server(cfg)
    s.open()
    try:
        def post(path, body):
            r = urllib.request.Request(s.uri + path, data=body, method="POST")
            with urllib.request.urlopen(r, timeout=60) as resp:
                return resp.read()

        post("/index/k", _json.dumps({"options": {"keys": True}}).encode())
        post(
            "/index/k/field/f",
            _json.dumps({"options": {"keys": True}}).encode(),
        )
        post("/index/r", b"{}")
        post("/index/r/field/f", b"{}")

        # keyed load (mints every key), then the identical bits by
        # pre-translated raw ids into the twin
        batch = 500
        for at in range(0, n_cols, batch):
            cols = [f"user-{j:05d}" for j in range(at, min(at + batch, n_cols))]
            rows = [f"seg-{j % n_rows:02d}" for j in range(at, min(at + batch, n_cols))]
            post(
                "/index/k/field/f/ingest",
                _json.dumps({"rowKeys": rows, "columnKeys": cols}).encode(),
            )
        ts = s.translate_store
        for at in range(0, n_cols, batch):
            cols = [f"user-{j:05d}" for j in range(at, min(at + batch, n_cols))]
            rows = [f"seg-{j % n_rows:02d}" for j in range(at, min(at + batch, n_cols))]
            cids = ts.translate_columns_to_ids("k", cols, create=False)
            rids = ts.translate_rows_to_ids("k", "f", rows, create=False)
            post(
                "/index/r/field/f/ingest",
                _json.dumps({"rowIDs": rids, "columnIDs": cids}).encode(),
            )

        # bulk ingest bypasses the ranked TopN cache — force the
        # recalculation so TopN serves real candidate rows (and the
        # keyed side really pays/amortizes reverse translation)
        post("/recalculate-caches", b"")
        q = b"TopN(f, n=10)"

        def drive(index, seconds):
            # warm first (stager fill + LRU fill), then a timed
            # closed loop; ?cache=false so the plan cache doesn't
            # collapse the measurement into one lookup
            path = f"/index/{index}/query?cache=false"
            for _ in range(5):
                post(path, q)
            n = 0
            t0 = time.perf_counter()
            stop = t0 + seconds
            while time.perf_counter() < stop:
                post(path, q)
                n += 1
            return n / (time.perf_counter() - t0)

        seg = max(2.0, min(8.0, (budget_s - 4.0) / 2.0))
        raw_qps = drive("r", seg)
        keyed_qps = drive("k", seg)
        ratio = keyed_qps / raw_qps if raw_qps else 0.0
        dbg = _json.loads(
            urllib.request.urlopen(s.uri + "/debug/translate", timeout=30).read()
        )
        out["raw_qps"] = round(raw_qps, 1)
        out["keyed_qps"] = round(keyed_qps, 1)
        out["keyed_vs_raw"] = round(ratio, 3)
        out["lru_hit_ratio"] = dbg["cache"].get("hitRatio")
        out["keys"] = dbg["keys"]
        out["acceptance"] = ">=0.9 warm"
        out["pass"] = ratio >= 0.9
    finally:
        s.close()
        _shutil.rmtree(tmp, ignore_errors=True)
    return out


def main():
    import os

    import jax
    import jax.numpy as jnp

    # The image's sitecustomize force-sets jax_platforms to the TPU
    # backend, overriding the JAX_PLATFORMS env var; re-assert it so
    # CPU smoke runs work (the TPU driver leaves it unset/axon).
    # honor JAX_PLATFORMS over the image's sitecustomize pinning, and
    # persist XLA compiles so a cold driver run pays them only once
    from pilosa_tpu.utils.jaxplatform import bootstrap

    bootstrap()

    import os

    # Self-enforced deadline: the parent SIGKILLs this child at its
    # timeout, which would lose everything measured so far. A guard
    # thread prints the progressively-filled result dict (marked
    # partial) and exits just before that happens — cold compiles and
    # first-time HBM staging at the 1B scale are the usual overrunners.
    child_budget = float(os.environ.get("PILOSA_BENCH_CHILD_BUDGET", 400))
    result: dict = {
        "metric": "TopN queries/sec (measurement incomplete)",
        "value": 0.0,
        "unit": "queries/s",
        "vs_baseline": None,
    }
    printed = threading.Event()
    emit_lock = threading.Lock()

    def emit(final: bool) -> None:
        with emit_lock:
            if printed.is_set():
                return
            # dict(result) is one C-level copy (atomic under the GIL);
            # dumping the live dict could race a concurrent update
            snapshot = dict(result)
            # the same metric names the server exports at /metrics
            # (pilosa_tpu/utils/metrics.py) — whatever the in-process
            # executor/batcher/stager instrumentation observed this run
            try:
                from pilosa_tpu.utils import metrics as _metrics

                snapshot["metrics"] = _metrics.snapshot()
            except Exception:
                pass
            # workload heat + placement skew (ISSUE 16): top-K hot
            # shards and imbalance ratio, the baseline curve future
            # tiering/rebalancing PRs compare against
            try:
                from pilosa_tpu.utils import heat as _heat

                hs = _heat.snapshot(dim="reads")
                snapshot["heat"] = {
                    "cells": len(hs["cells"]),
                    "skew": hs["skew"],
                }
            except Exception:
                pass
            # a result without a measured headline must never be
            # persisted over the last COMPLETE measurement
            if not final or snapshot.get("value", 0.0) == 0.0:
                snapshot["partial"] = True
            line = json.dumps(snapshot)
            printed.set()
            # print INSIDE the lock: the guard may os._exit immediately
            # after observing printed — the line must be out by then
            print(line, flush=True)

    def guard():
        remaining = child_budget - (time.monotonic() - _T_PROC_START) - 15
        if remaining > 0 and printed.wait(timeout=remaining):
            return
        emit(final=False)
        os._exit(0)

    threading.Thread(target=guard, daemon=True).start()
    result["platform"] = jax.devices()[0].platform

    # ---- Full-path north-star config FIRST (BASELINE config 4: 1B
    # rows, 64 shards) — it is the headline metric and must not starve
    # behind the kernel microbench when the budget is tight. The data
    # dir builds resumably into .bench_cache/; a kernel-bench reserve is
    # held back so the secondary numbers still get measured.
    tall = None
    if os.environ.get("PILOSA_BENCH_TALL", "1") != "0":
        try:
            import bench_tall

            # resume: a complete same-revision tall part from an attempt
            # wedged later in ITS run (or an earlier attempt of this
            # invocation) is this round's measurement — reuse it instead
            # of burning the budget again
            cached = load_part("tall")
            if cached and cached.get("topn_qps") and cached.get(
                "platform"
            ) == result["platform"]:
                tall = cached
                # top-level marker: the headline below comes from a
                # same-revision checkpoint of an earlier attempt, not
                # a measurement taken by THIS invocation
                result["tall_checkpointed"] = True
                result["tall_checkpoint_age_s"] = cached.get(
                    "checkpointed_age_s"
                )
            else:
                spent = time.monotonic() - _T_PROC_START
                # the full-path number is what matters: it gets the
                # budget minus a small reserve; the kernel microbench
                # below only runs if time is left (its numbers also
                # live in BENCH_r* history)
                tall_deadline = child_budget - spent - 70
                if tall_deadline > 75:
                    tall = bench_tall.run(deadline_s=tall_deadline)
                    if tall.get("topn_qps") and not tall.get("error"):
                        save_part("tall", tall)
            if tall is not None:
                result["tall"] = tall
                if tall.get("topn_qps"):
                    rows = tall["build"]["rows"]
                    # Headline = the best measured closed-loop serving
                    # number: the baseline (reference server / native
                    # proxy x cores) is concurrent server throughput,
                    # so the apples-to-apples headline is ours under
                    # concurrency too. Sequential qps (RTT-bound on a
                    # tunneled chip, rtt_fraction ~0.85) always rides
                    # in seq_qps. A budget-cut run that only measured
                    # sequential reports that, labeled.
                    mode, headline = headline_mode(tall)
                    result["metric"] = (
                        f"TopN queries/sec (full path, {rows:,} rows x "
                        f"{tall['shards']} shards, single chip, {mode})"
                    )
                    result["value"] = headline
                    result["seq_qps"] = tall["topn_qps"]
                    # explicitly SEQUENTIAL p50 (one in-flight query,
                    # RTT-bound on a tunneled chip) — named so the
                    # artifact can't be misread as closed-loop latency
                    result["seq_p50_ms"] = tall["topn_p50_ms"]
                    bk, _ = best_closed_loop(tall, "topn_qps_c")
                    if mode != "sequential" and bk:
                        cp = tall.get(
                            "topn_p50_ms_c" + bk.rsplit("c", 1)[1]
                        )
                        if cp is not None:
                            # per-query latency AT the headline
                            # concurrency (queueing included)
                            result["closed_p50_ms"] = cp
                    result.update(
                        vs_baseline_fields(
                            mode,
                            headline,
                            tall.get("cpu_topn_qps"),
                            cpu_closed_qps=tall.get("cpu_topn_qps_c4"),
                            seq_qps=tall.get("topn_qps"),
                        )
                    )
                    # window self-qualification rides next to the
                    # headline (VERDICT item 4): sustained RTT +
                    # achieved pipelining depth, consumed by the
                    # last-good gating in _guarded_main
                    wq = window_quality(tall)
                    if wq is not None:
                        result["window_quality"] = wq
        except Exception as e:  # keep the JSON line flowing
            print(f"tall bench failed: {type(e).__name__}: {e}", file=sys.stderr)

    # ---- native C++ baseline (the Go-reference proxy, measured offline
    # by native/baseline_topn.cpp): attach before any early return — it
    # costs only a local file read and belongs with the tall headline.
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE_NATIVE.json")) as f:
            _native = json.load(f)["measured"]
        result["native_baseline"] = {
            k: v.get("native_cpu_qps") for k, v in _native.items()
        }
        _tall_rows = result.get("tall", {}).get("build", {}).get("rows", 0)
        # only compare against the native 1B numbers when THIS run was
        # actually at (or near) the 1B scale
        if _tall_rows >= 900_000_000:
            for native_key, tall_key, out_key in (
                ("tall_1Bx64shards", "topn_qps", "vs_native_baseline"),
                ("tall_chains_1Bx64shards", "chain_qps", "chain_vs_native_baseline"),
            ):
                nv = _native.get(native_key, {}).get("native_cpu_qps")
                tv = result.get("tall", {}).get(tall_key)
                if nv and tv:
                    result[out_key] = round(tv / nv, 2)
            # Serving margin vs the CORE-SCALED baseline (BASELINE.md
            # convention: native single-core x8 ~= the reference server
            # parallelizing shards over an 8-core box). The serving
            # number is the best measured concurrency level — on a
            # tunneled chip the sequential qps is RTT-bound and the
            # closed-loop concurrent number is what a deployment sees.
            # prefix matches ONLY the closed-loop concurrency keys
            # (topn_qps_c8/_c32/_c64...) — a budget-cut run that only
            # measured the RTT-bound sequential number must not publish
            # it under a serving label
            for native_key, prefix, out_key in (
                ("tall_1Bx64shards", "topn_qps_c", "topn_vs_native_core8"),
                ("tall_chains_1Bx64shards", "chain_qps_c", "chain_vs_native_core8"),
            ):
                nv = _native.get(native_key, {}).get("native_cpu_qps")
                _, best = best_closed_loop(result.get("tall", {}), prefix)
                if nv and best:
                    result[out_key] = {
                        "serving_qps": best,
                        "native_core8_qps": round(nv * 8, 2),
                        "margin": round(best / (nv * 8), 2),
                    }
            # per-window chain margins (VERDICT chain-margin
            # instability): the margin at EVERY measured chain
            # concurrency window, not just the best — so a single good
            # window can't mask degraded siblings in the artifact
            _cnv = _native.get("tall_chains_1Bx64shards", {}).get(
                "native_cpu_qps"
            )
            if _cnv:
                _cm = {
                    k: round(v / (_cnv * 8), 2)
                    for k, v in result.get("tall", {}).items()
                    if k.startswith("chain_qps_c")
                    and isinstance(v, (int, float))
                }
                if _cm:
                    result["chain_margins_per_window"] = _cm
    except Exception as e:  # any malformed baseline file — keep the JSON flowing
        print(f"native baseline unavailable: {type(e).__name__}: {e}", file=sys.stderr)

    # ---- serving pipeline probe (ISSUE 2): closed-loop HTTP qps
    # through the new admission/batching layer + overload shed
    # behavior. Cheap (~15 s, CPU path) and chip-independent.
    if os.environ.get("PILOSA_BENCH_PIPELINE", "1") != "0":
        rem = child_budget - (time.monotonic() - _T_PROC_START)
        if rem > 60:
            try:
                result["serving_pipeline"] = _pipeline_serving_probe(
                    min(20.0, rem - 35)
                )
            except Exception as e:
                print(
                    f"pipeline probe failed: {type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    # ---- read/write-mix probe (ISSUE 3): steady-state read qps under
    # 1% single-bit writes, delta staging vs forced full re-stage.
    if os.environ.get("PILOSA_BENCH_RWMIX", "1") != "0":
        rem = child_budget - (time.monotonic() - _T_PROC_START)
        if rem > 55:
            try:
                result["rw_mix"] = _rw_mix_probe(min(28.0, rem - 35))
            except Exception as e:
                print(
                    f"rw_mix probe failed: {type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    # ---- plan-cache probe (ISSUE 4): Zipf-repeated TopN/Intersect mix
    # through the generation-stamped result cache — hot vs cold qps,
    # hit ratio, bytes resident, and a 1%-write invalidation-
    # correctness arm checked against an uncached oracle.
    if os.environ.get("PILOSA_BENCH_PLANCACHE", "1") != "0":
        rem = child_budget - (time.monotonic() - _T_PROC_START)
        if rem > 50:
            try:
                result["cached_qps"] = _plan_cache_probe(min(25.0, rem - 30))
            except Exception as e:
                print(
                    f"plan-cache probe failed: {type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    # ---- continuous-batching probe (ISSUE 8): closed-loop c8/c32 qps
    # + device-idle fraction, dispatch engine vs blocking execution.
    if os.environ.get("PILOSA_BENCH_DISPATCH", "1") != "0":
        rem = child_budget - (time.monotonic() - _T_PROC_START)
        if rem > 60:
            try:
                result["continuous_batching"] = _continuous_batching_probe(
                    min(30.0, rem - 30)
                )
            except Exception as e:
                print(
                    f"continuous-batching probe failed: "
                    f"{type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    # ---- durable ingest probe (ISSUE 11): sustained >=10% writes
    # through the write-ahead queue (ack = group commit + fsync) vs a
    # read-only baseline, write-ack p50/p99, bounded staleness, an
    # uncached oracle check, and a federated rejoin-mid-stream sub-arm.
    if os.environ.get("PILOSA_BENCH_INGEST", "1") != "0":
        rem = child_budget - (time.monotonic() - _T_PROC_START)
        if rem > 60:
            try:
                result["ingest_sustained"] = _ingest_sustained_probe(
                    min(30.0, rem - 35)
                )
            except Exception as e:
                print(
                    f"ingest probe failed: {type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    # ---- tiered-staging oversubscription probe (ISSUE 17): hot-set
    # p50 with T0 holding the whole set vs a third of it, T1 host tier
    # + compressed upload + plan-driven prefetch absorbing re-entry.
    if os.environ.get("PILOSA_BENCH_TIERING", "1") != "0":
        rem = child_budget - (time.monotonic() - _T_PROC_START)
        if rem > 55:
            try:
                result["tiering_oversub"] = _tiering_oversub_probe(
                    min(24.0, rem - 30)
                )
                try:
                    with open(
                        os.path.join(_REPO_DIR, "TIERING_r17.json"), "w"
                    ) as f:
                        json.dump(
                            {
                                "ts": time.time(),
                                "platform": result.get("platform"),
                                **result["tiering_oversub"],
                            },
                            f,
                            indent=1,
                        )
                except OSError as e:
                    print(
                        f"could not write TIERING_r17.json: {e}",
                        file=sys.stderr,
                    )
            except Exception as e:
                print(
                    f"tiering probe failed: {type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    # ---- dashboard-mix probe (ISSUE 18): interactive TopN/Count p50
    # with a fused GroupBy panel loop alongside vs analytics off, plus
    # fused launches per panel under concurrency.
    if os.environ.get("PILOSA_BENCH_ANALYTICS", "1") != "0":
        rem = child_budget - (time.monotonic() - _T_PROC_START)
        if rem > 50:
            try:
                result["dashboard_mix"] = _dashboard_mix_probe(
                    min(22.0, rem - 28)
                )
                try:
                    with open(
                        os.path.join(_REPO_DIR, "ANALYTICS_r18.json"), "w"
                    ) as f:
                        json.dump(
                            {
                                "ts": time.time(),
                                "platform": result.get("platform"),
                                **result["dashboard_mix"],
                            },
                            f,
                            indent=1,
                        )
                except OSError as e:
                    print(
                        f"could not write ANALYTICS_r18.json: {e}",
                        file=sys.stderr,
                    )
            except Exception as e:
                print(
                    f"dashboard-mix probe failed: {type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    # ---- tenant-mix probe (ISSUE 19): dozens of Zipf-traffic tenants
    # + one abusive tenant; abuser throttled to its weight's qps while
    # the well-behaved population's p50 holds vs a no-abuser baseline.
    if os.environ.get("PILOSA_BENCH_TENANCY", "1") != "0":
        rem = child_budget - (time.monotonic() - _T_PROC_START)
        if rem > 50:
            try:
                result["tenant_mix"] = _tenant_mix_probe(min(22.0, rem - 28))
                try:
                    with open(
                        os.path.join(_REPO_DIR, "TENANCY_r19.json"), "w"
                    ) as f:
                        json.dump(
                            {
                                "ts": time.time(),
                                "platform": result.get("platform"),
                                **result["tenant_mix"],
                            },
                            f,
                            indent=1,
                        )
                except OSError as e:
                    print(
                        f"could not write TENANCY_r19.json: {e}",
                        file=sys.stderr,
                    )
            except Exception as e:
                print(
                    f"tenant-mix probe failed: {type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    # ---- keyed-mix probe (ISSUE 20): warm TopN through a keyed index
    # vs its raw-id twin; keyed must hold >=0.9x raw qps with the
    # reverse-translation LRU absorbing the id->key cost.
    if os.environ.get("PILOSA_BENCH_KEYED", "1") != "0":
        rem = child_budget - (time.monotonic() - _T_PROC_START)
        if rem > 45:
            try:
                result["keyed_mix"] = _keyed_mix_probe(min(18.0, rem - 25))
            except Exception as e:
                print(
                    f"keyed-mix probe failed: {type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    # a fresh same-revision checkpointed kernel is free — use it even
    # when the remaining budget couldn't afford a fresh measurement
    cached_kernel = load_part("kernel")
    if not (
        cached_kernel and cached_kernel.get("platform") == result["platform"]
    ) and child_budget - (time.monotonic() - _T_PROC_START) < 210:
        # Not enough room for the kernel microbench (measured ~160 s
        # warm: matrix build + compile + three paths) — ship the
        # complete tall headline rather than risk the deadline guard
        # marking the whole line partial over the secondary numbers.
        result["kernel_bench"] = "skipped (budget)"
        emit(final=True)
        return

    if cached_kernel and cached_kernel.get("platform") == result["platform"]:
        result.update(
            {k: v for k, v in cached_kernel.items() if k != "platform"}
        )
        result["kernel_checkpointed"] = True
        if not (tall and tall.get("topn_qps")) and cached_kernel.get("kernel_qps"):
            result.update(
                {
                    "metric": "TopN queries/sec (kernel microbench, single chip)",
                    "value": cached_kernel["kernel_qps"],
                    "vs_baseline": cached_kernel.get("kernel_vs_baseline"),
                    "seq_p50_ms": cached_kernel.get("kernel_p50_ms"),
                    "baseline_cpu_qps": cached_kernel.get("kernel_cpu_qps"),
                }
            )
        emit(final=True)
        return

    R = int(os.environ.get("PILOSA_BENCH_ROWS", 4096))
    W64 = 16384  # uint64 words per row (2^20 columns)
    DENSITY = 0.015625  # 2^-6 via 6-way AND
    N_QUERIES = int(os.environ.get("PILOSA_BENCH_QUERIES", 64))
    TOPK = 10

    rng = np.random.default_rng(11)
    # Synthetic packed fragment at ~2^-6 ≈ 1.6% density: AND of 6
    # uniform word streams (vectorised; per-bit P(set) = 0.5^6).
    mat64 = rng.integers(0, 2**64, size=(R, W64), dtype=np.uint64)
    for _ in range(5):
        mat64 &= rng.integers(0, 2**64, size=(R, W64), dtype=np.uint64)
    mat32 = mat64.view("<u4")

    q_rows = rng.integers(0, R, size=N_QUERIES)  # source row ids per query

    # ---- TPU path: staged-source intersection-count + top_k ----
    # TopN(Row(r))'s source is row r of the staged fragment; index it
    # out of HBM instead of re-uploading from host (stager.row path).
    @jax.jit
    def topn_step(row_id, mat):
        src = mat[row_id]
        scores = jnp.sum(
            jax.lax.population_count(jnp.bitwise_and(mat, src[None, :])).astype(
                jnp.int32
            ),
            axis=-1,
        )
        counts, ids = jax.lax.top_k(scores, TOPK)
        return ids, counts

    def force(out):
        """True completion: fetch one element host-side. On tunneled
        backends block_until_ready acks the dispatch without waiting
        for remote completion, so a tiny fetch is the only honest
        sync — everything below measures COMPLETED queries."""
        return np.asarray(out[0].ravel()[:1])

    dev_mat = jax.device_put(mat32)
    # warmup / compile
    force(topn_step(int(q_rows[0]), dev_mat))

    # Latency: true round-trip (dispatch + completion + fetch) per
    # query; on a tunneled chip this has the tunnel RTT as a floor.
    lat = []
    for q in range(N_QUERIES):
        t0 = time.perf_counter()
        force(topn_step(int(q_rows[q]), dev_mat))
        lat.append(time.perf_counter() - t0)
    p50 = sorted(lat)[len(lat) // 2] * 1000

    # Throughput: pipelined dispatch, then force completion of every
    # query's result.
    t_all = time.perf_counter()
    outs = [topn_step(int(q_rows[q]), dev_mat) for q in range(N_QUERIES)]
    for o in outs:
        force(o)
    tpu_qps = N_QUERIES / (time.perf_counter() - t_all)

    # ---- Pallas-tiled variant (TPU only): keep whichever is faster ----
    from pilosa_tpu.ops.pallas_kernels import (
        intersection_counts_matrix_batch_pallas,
        intersection_counts_matrix_pallas,
        pad_for_pallas,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    # one padded staged copy shared by the pallas and batched paths
    try:
        padded, true_r = pad_for_pallas(mat32)
        dev_pmat = jax.device_put(padded)
        del padded
    except Exception as e:  # e.g. HBM OOM — keep the JSON line flowing
        print(f"pallas staging failed: {type(e).__name__}: {e}", file=sys.stderr)
        dev_pmat = None

    pallas_qps = 0.0
    if on_tpu and dev_pmat is not None:
        try:

            @jax.jit
            def topn_step_pallas(row_id, pmat):
                src = pmat[row_id]
                scores = intersection_counts_matrix_pallas(src, pmat)
                counts, ids = jax.lax.top_k(scores[:true_r], TOPK)
                return ids, counts

            force(topn_step_pallas(int(q_rows[0]), dev_pmat))
            t0 = time.perf_counter()
            pouts = [
                topn_step_pallas(int(q_rows[q]), dev_pmat) for q in range(N_QUERIES)
            ]
            for o in pouts:
                force(o)
            pallas_qps = N_QUERIES / (time.perf_counter() - t0)
        except Exception as e:  # keep the JSON line clean; surface the cause
            print(f"pallas path failed: {type(e).__name__}: {e}", file=sys.stderr)
            pallas_qps = 0.0
    # ---- Batched dispatch (server-style continuous batching): score
    # Q concurrent query sources per kernel launch; the matrix streams
    # from HBM once per batch instead of once per query (executor's
    # BatchedScorer coalesces concurrent requests the same way).
    batched_qps = 0.0
    BATCH = int(os.environ.get("PILOSA_BENCH_BATCH", 512))
    try:
        if dev_pmat is None:
            raise RuntimeError("staged matrix unavailable")
        dev_bmat = dev_pmat

        @jax.jit
        def topn_step_batch(row_ids, pmat):
            srcs = pmat[row_ids]
            if on_tpu:
                scores = intersection_counts_matrix_batch_pallas(srcs, pmat)
            else:
                from pilosa_tpu import ops as _ops

                scores = _ops.intersection_counts_matrix_batch(srcs, pmat)
            counts, ids = jax.lax.top_k(scores[:, :true_r], TOPK)
            return ids, counts

        n_batches = max(N_QUERIES // BATCH, 4)
        batch_ids = [
            jnp.asarray(rng.integers(0, R, size=BATCH)) for _ in range(n_batches)
        ]
        force(topn_step_batch(batch_ids[0], dev_bmat))
        t0 = time.perf_counter()
        bouts = [topn_step_batch(b, dev_bmat) for b in batch_ids]
        for o in bouts:
            force(o)
        batched_qps = n_batches * BATCH / (time.perf_counter() - t0)
    except Exception as e:
        print(f"batched path failed: {type(e).__name__}: {e}", file=sys.stderr)
        batched_qps = 0.0

    best_qps = max(tpu_qps, pallas_qps, batched_qps)

    # ---- CPU baseline: roaring per-candidate intersection counts ----
    # A TopN query walks every candidate row computing
    # src.intersection_count(row) (the reference's fragment.top hot loop).
    # Building all R roaring rows in Python is prohibitive, so measure a
    # SAMPLE of rows and extrapolate the per-query cost linearly in R —
    # the walk is embarrassingly linear in candidate count.
    from pilosa_tpu.roaring import Bitmap

    sample_n = 64
    rows_cpu = [Bitmap.from_words_range(mat64[i]) for i in range(sample_n)]
    src_b = Bitmap.from_words_range(mat64[q_rows[0]])
    t0 = time.perf_counter()
    reps = 2
    for _ in range(reps):
        for b in rows_cpu:
            src_b.intersection_count(b)
    per_row = (time.perf_counter() - t0) / (sample_n * reps)
    cpu_query_s = per_row * R
    cpu_qps = 1.0 / cpu_query_s

    # Roofline: each query's score pass reads the full packed matrix
    # (R x 16384 u64 words) as operands. Effective operand traffic =
    # qps x matrix bytes; compared against v5e HBM peak (~819 GB/s) it
    # shows WHERE the kernel sits — above peak means the staged tiles
    # are reused on-chip across the batch's sources (compute-bound),
    # below means HBM-bound.
    matrix_bytes = R * W64 * 8
    v5e_hbm_peak = 819e9
    kernel_fields = {
        "xla_qps": round(tpu_qps, 2),
        "pallas_qps": round(pallas_qps, 2),
        "batched_qps": round(batched_qps, 2),
        "batch_size": BATCH,
        "kernel_qps": round(best_qps, 2),
        "kernel_cpu_qps": round(cpu_qps, 3),
        "kernel_vs_baseline": round(best_qps / cpu_qps, 2),
        "kernel_p50_ms": round(p50, 3),
        "roofline": {
            "operand_bytes_per_query": matrix_bytes,
            "effective_operand_traffic_GBps": round(
                best_qps * matrix_bytes / 1e9, 1
            ),
            "v5e_hbm_peak_GBps": round(v5e_hbm_peak / 1e9),
            "fraction_of_hbm_peak": round(
                best_qps * matrix_bytes / v5e_hbm_peak, 2
            ),
            "arithmetic": (
                f"{R} rows x {W64} u64 words x 8 B = "
                f"{matrix_bytes / 1e6:.0f} MB operands/query; "
                "traffic = qps x that"
            ),
        },
    }
    result.update(kernel_fields)
    save_part("kernel", {**kernel_fields, "platform": result["platform"]})
    # the kernel microbench is the headline only when the full-path
    # north-star config didn't produce one
    if not (tall and tall.get("topn_qps")):
        result.update(
            {
                "metric": (
                    f"TopN queries/sec ({R} rows x 1M cols, ~2% density, "
                    "single chip)"
                ),
                "value": round(best_qps, 2),
                "vs_baseline": round(best_qps / cpu_qps, 2),
                "seq_p50_ms": round(p50, 3),
                "baseline_cpu_qps": round(cpu_qps, 3),
            }
        )

    try:
        kern_native = (
            result.get("native_baseline", {}).get("kernel_4096x1M")
        )
        if kern_native:
            result["kernel_vs_native_baseline"] = round(best_qps / kern_native, 2)
    except Exception as e:
        print(f"native kernel ratio unavailable: {type(e).__name__}: {e}", file=sys.stderr)

    emit(final=True)


def _cpu_fresh_main():
    """Child mode: measure every chip-independent metric fresh on the
    CPU backend (warm open, staging breakdown, CPU-path QPS). Run when
    the device never answers, so the artifact carries numbers measured
    by THIS code instead of a wholesale stale replay."""
    from pilosa_tpu.utils.jaxplatform import bootstrap

    bootstrap()
    import bench_tall

    budget = float(os.environ.get("PILOSA_BENCH_CHILD_BUDGET", 240))
    out = bench_tall.run_cpu_fresh(deadline_s=budget - 15)
    out["metric"] = "chip-independent fresh measurements (device unreachable)"
    out["measured_at_rev"] = _git_rev()
    print(json.dumps(out), flush=True)


def _probe_main():
    """Tiny device liveness check run in a disposable child: init the
    backend, round-trip one array. Exits 0 iff the device answered."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    d = jax.devices()[0]
    x = jax.device_put(np.arange(8, dtype=np.uint32))
    got = int(np.asarray(jax.numpy.sum(x)))
    assert got == 28, got
    print(f"probe ok: {d.platform}", file=sys.stderr)


LAST_GOOD = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_last_good.json")


def _extract_json_line(text):
    """Last line of stdout that parses as a JSON object with 'metric'."""
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            return obj
    return None


def _guarded_main():
    """Run the measurement in a child process with a watchdog + retries.

    The tunneled TPU backend can wedge at client init (a hung PJRT
    make_c_api_client blocks SIGTERM-less in C code); without a guard
    the whole bench run would hang and emit nothing. Strategy:
      1. Probe the device with a short-timeout child; retry with
         backoff — a wedged tunnel sometimes recovers between attempts.
      2. On a live device, run the real bench child (watchdog'd) and
         persist its JSON line to BENCH_last_good.json.
      3. If the device never answers (or the bench child dies), fall
         back to the last persisted good result marked stale=true —
         a flaky tunnel degrades to stale-but-real instead of 0.0.
    """
    import subprocess
    import time as _time

    def _env_float(name, default):
        try:
            return float(os.environ.get(name, default))
        except ValueError:
            return float(default)

    # Everything — probes, backoff, the bench child, and printing the
    # JSON line — must finish inside this budget, because callers wrap
    # the whole run in an outer `timeout` that would kill us mid-write.
    budget_s = _env_float("PILOSA_BENCH_TIMEOUT", 520)
    deadline = _time.monotonic() + budget_s
    attempts = max(1, int(_env_float("PILOSA_BENCH_ATTEMPTS", 4)))
    me = os.path.abspath(__file__)

    def remaining(margin=10.0):
        return deadline - _time.monotonic() - margin

    def run_child(extra_env, child_timeout):
        env = dict(os.environ, **extra_env)
        try:
            return subprocess.run(
                [sys.executable, me],
                env=env,
                timeout=child_timeout,
                stdout=subprocess.PIPE,
                text=True,
            )
        except subprocess.TimeoutExpired:
            return None

    # Probes are short and ADAPTIVE (20s, 40s, 60s, ...): a healthy
    # backend answers a tiny round-trip in a few seconds even with a
    # cold init, so burning 75s per probe (the round-3 default) just
    # starves the measurement budget when the tunnel is merely slow to
    # come up. Backoff between attempts gives a wedged tunnel a chance
    # to recover without spending the whole budget waiting.
    probe_base = _env_float("PILOSA_BENCH_PROBE_TIMEOUT", 20)
    reason = "device probe never ran"
    alive = False
    for i in range(attempts):
        t = min(probe_base * (i + 1), remaining())
        if t <= 5:
            reason = "budget exhausted before device answered"
            break
        proc = run_child({"PILOSA_BENCH_PROBE": "1"}, t)
        if proc is not None and proc.returncode == 0:
            alive = True
            break
        reason = (
            f"device probe timed out after {t:.0f}s"
            if proc is None
            else f"device probe exited {proc.returncode}"
        )
        print(f"attempt {i + 1}/{attempts}: {reason}", file=sys.stderr)
        if i + 1 < attempts and remaining() > 30:
            _time.sleep(min(5 * (i + 1), 20))

    if alive and remaining() <= 60:
        alive = False
        reason = "device alive but budget too small to run the bench"
    # The bench child gets up to TWO attempts: sub-results checkpoint
    # to .bench_cache/bench_parts.json as they complete, so a child
    # that dies mid-run (tunnel wedge) is resumed by the next attempt
    # reusing every fresh same-revision part instead of starting over.
    child_tries = 0
    while alive and child_tries < 2 and remaining() > 60:
        child_tries += 1
        child_timeout = remaining()
        proc = run_child(
            {
                "PILOSA_BENCH_CHILD": "1",
                "PILOSA_BENCH_CHILD_BUDGET": str(child_timeout),
            },
            child_timeout,
        )
        if proc is None:
            reason = f"bench child timed out after {child_timeout:.0f}s"
            continue
        if proc.returncode != 0:
            reason = f"bench child exited {proc.returncode}"
            continue
        obj = _extract_json_line(proc.stdout)
        if obj is None:
            reason = "bench child produced no JSON line"
            continue
        if obj.get("platform") == "tpu" and not obj.get("partial"):
            # a deadline-cut partial must never shadow the last
            # COMPLETE real-device measurement. Only a real-device
            # result is worth replaying later; a CPU smoke run must
            # not masquerade as the TPU number. Window gating (VERDICT
            # item 4): a run measured in a degraded window (slow RTT,
            # collapsed pipelining depth vs the last-good's recorded
            # window_quality) keeps ITS OWN JSON line but must not
            # displace the last-good artifact. Write-then-rename so a
            # killed writer can't truncate the previous good file.
            old_wq = None
            try:
                with open(LAST_GOOD) as f:
                    old_wq = (json.load(f) or {}).get("window_quality")
            except (OSError, ValueError):
                pass
            degraded, why = window_degraded(obj.get("window_quality"), old_wq)
            if degraded:
                obj["window_degraded"] = why
                print(
                    f"degraded window — keeping prior BENCH_last_good.json: {why}",
                    file=sys.stderr,
                )
            else:
                try:
                    tmp = LAST_GOOD + ".tmp"
                    with open(tmp, "w") as f:
                        json.dump(obj, f)
                        f.write("\n")
                    os.replace(tmp, LAST_GOOD)
                except OSError as e:
                    print(f"could not persist last-good: {e}", file=sys.stderr)
        print(json.dumps(obj))
        return
    print(reason, file=sys.stderr)

    # Before replaying a PRIOR run: assemble from this revision's fresh
    # checkpointed parts — numbers measured by THIS code minutes ago
    # beat a stale replay.
    tall_part = load_part("tall")
    kern_part = load_part("kernel")

    # The device never answered — but most of the system is HOST work
    # that can still be measured NOW: warm open, staging pack, CPU-path
    # QPS. Run them fresh on the CPU backend and partition them from
    # anything replayed below (VERDICT r4: a full-stale replay carried
    # open_warm_s=134.5 while the shipped code opened in ~4 s). Skipped
    # when a fresh same-session tall checkpoint already carries those
    # numbers — re-measuring them would burn the margin that protects
    # the final JSON write from the caller's outer timeout.
    fresh_cpu = None
    if remaining() > 120 and not (tall_part and tall_part.get("topn_qps")):
        proc = run_child(
            {
                "PILOSA_BENCH_CPU_FRESH": "1",
                "JAX_PLATFORMS": "cpu",
                "PILOSA_BENCH_CHILD_BUDGET": str(remaining(margin=20.0)),
            },
            remaining(margin=15.0),
        )
        if proc is not None and proc.returncode == 0:
            fresh_cpu = _extract_json_line(proc.stdout)
            if fresh_cpu:
                fresh_cpu.pop("metric", None)
        if fresh_cpu is None:
            print("cpu-fresh measurement failed", file=sys.stderr)

    def attach_fresh(out: dict) -> dict:
        if fresh_cpu:
            out["fresh_cpu"] = fresh_cpu
            out["note"] = (
                "fresh_cpu fields were measured by THIS run on the CPU "
                "backend and supersede the same-named fields inside any "
                "replayed/checkpointed section"
            )
        return out
    if not (tall_part and tall_part.get("topn_qps")) and kern_part and kern_part.get(
        "kernel_qps"
    ):
        # no tall part, but a fresh same-revision kernel measurement
        # still beats a prior revision's stale replay
        out = {
            "metric": "TopN queries/sec (kernel microbench, single chip)",
            "value": kern_part["kernel_qps"],
            "unit": "queries/s",
            "vs_baseline": kern_part.get("kernel_vs_baseline"),
            "seq_p50_ms": kern_part.get("kernel_p50_ms"),
            "platform": kern_part.get("platform"),
            "assembled_from_checkpoints": True,
            "error": f"final attempt failed ({reason}); kernel part is a "
            "fresh same-revision measurement from this session",
        }
        out.update({k: v for k, v in kern_part.items() if k != "platform"})
        print(json.dumps(attach_fresh(out)))
        return
    if tall_part and tall_part.get("topn_qps"):
        # same headline convention as the live path (one definition:
        # headline_mode): best closed-loop serving number when one was
        # measured and beat sequential, else sequential, labeled either way
        mode, headline = headline_mode(tall_part)
        out = {
            "metric": (
                f"TopN queries/sec (full path, "
                f"{tall_part.get('build', {}).get('rows', 0):,} rows x "
                f"{tall_part.get('shards')} shards, single chip, {mode})"
            ),
            "value": headline,
            "seq_qps": tall_part["topn_qps"],
            "unit": "queries/s",
            **vs_baseline_fields(
                mode,
                headline,
                tall_part.get("cpu_topn_qps"),
                cpu_closed_qps=tall_part.get("cpu_topn_qps_c4"),
                seq_qps=tall_part.get("topn_qps"),
            ),
            "platform": tall_part.get("platform"),
            "tall": tall_part,
            "seq_p50_ms": tall_part.get("topn_p50_ms"),
            "assembled_from_checkpoints": True,
            "error": f"final attempt failed ({reason}); parts are fresh "
            "same-revision measurements from this session",
        }
        wq = window_quality(tall_part)
        if wq is not None:
            out["window_quality"] = wq
        bk, _ = best_closed_loop(tall_part, "topn_qps_c")
        if mode != "sequential" and bk:
            cp = tall_part.get("topn_p50_ms_c" + bk.rsplit("c", 1)[1])
            if cp is not None:
                out["closed_p50_ms"] = cp
        if kern_part:
            out.update({k: v for k, v in kern_part.items() if k != "platform"})
        print(json.dumps(attach_fresh(out)))
        return

    # Fallback: replay the last good DEVICE measurement, marked as the
    # replayed partition — fresh_cpu (above) carries everything this
    # run could honestly re-measure without the chip.
    try:
        with open(LAST_GOOD) as f:
            obj = json.load(f)
        obj["stale"] = True
        obj["stale_device"] = True
        obj["error"] = (
            f"device fields replayed from last good on-chip run; this "
            f"run failed: {reason}"
        )
        print(json.dumps(attach_fresh(obj)))
        return
    except (OSError, ValueError):
        pass
    out = {
        "metric": "TopN queries/sec (backend unavailable)",
        "value": 0.0,
        "unit": "queries/s",
        "vs_baseline": 0.0,
        "error": reason,
    }
    if fresh_cpu and fresh_cpu.get("cpu_topn_qps"):
        # no device and nothing to replay: the CPU full path measured
        # NOW is the only honest headline
        out["metric"] = (
            "TopN queries/sec (CPU full path; device unreachable, no "
            "prior on-chip result to replay)"
        )
        out["value"] = fresh_cpu["cpu_topn_qps"]
        out["vs_baseline"] = 1.0
    print(json.dumps(attach_fresh(out)))


if __name__ == "__main__":
    if os.environ.get("PILOSA_BENCH_PROBE"):
        _probe_main()
    elif os.environ.get("PILOSA_BENCH_CPU_FRESH"):
        _cpu_fresh_main()
    elif os.environ.get("PILOSA_BENCH_CHILD"):
        main()
    else:
        _guarded_main()
