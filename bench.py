"""Benchmark: TopN queries/sec on the north-star workload.

Synthetic fragment (BASELINE.json config 4 style): R rows × 2^20 columns
per shard at ~2% density; queries are TopN(field, Row(src)) — the
reference's hot path (per-candidate IntersectionCount over the ranked
cache, fragment.go:985) executed as one batched intersection-count
matrix kernel + top_k on the TPU.

Baseline: the same queries through this framework's CPU roaring path
(the reference's algorithm shape — per-candidate container popcount
loops). The reference Go binary itself can't run here (no Go toolchain
in the image); the roaring CPU path is the stand-in and is labeled as
such. vs_baseline = TPU QPS / CPU QPS.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import sys
import time

import numpy as np


def main():
    import os

    import jax
    import jax.numpy as jnp

    # The image's sitecustomize force-sets jax_platforms to the TPU
    # backend, overriding the JAX_PLATFORMS env var; re-assert it so
    # CPU smoke runs work (the TPU driver leaves it unset/axon).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import os

    R = int(os.environ.get("PILOSA_BENCH_ROWS", 4096))
    W64 = 16384  # uint64 words per row (2^20 columns)
    DENSITY = 0.015625  # 2^-6 via 6-way AND
    N_QUERIES = int(os.environ.get("PILOSA_BENCH_QUERIES", 64))
    TOPK = 10

    rng = np.random.default_rng(11)
    # Synthetic packed fragment at ~2^-6 ≈ 1.6% density: AND of 6
    # uniform word streams (vectorised; per-bit P(set) = 0.5^6).
    mat64 = rng.integers(0, 2**64, size=(R, W64), dtype=np.uint64)
    for _ in range(5):
        mat64 &= rng.integers(0, 2**64, size=(R, W64), dtype=np.uint64)
    mat32 = mat64.view("<u4")

    srcs = mat64[rng.integers(0, R, size=N_QUERIES)]  # reuse rows as src filters
    srcs32 = srcs.view("<u4")

    # ---- TPU path: batched intersection-count + top_k ----
    @jax.jit
    def topn_step(src, mat):
        scores = jnp.sum(
            jax.lax.population_count(jnp.bitwise_and(mat, src[None, :])).astype(
                jnp.int32
            ),
            axis=-1,
        )
        counts, ids = jax.lax.top_k(scores, TOPK)
        return ids, counts

    dev_mat = jax.device_put(mat32)
    # warmup / compile
    ids, counts = topn_step(jax.device_put(srcs32[0]), dev_mat)
    ids.block_until_ready()

    lat = []
    t_all = time.perf_counter()
    for q in range(N_QUERIES):
        t0 = time.perf_counter()
        ids, counts = topn_step(jax.device_put(srcs32[q]), dev_mat)
        ids.block_until_ready()
        lat.append(time.perf_counter() - t0)
    tpu_elapsed = time.perf_counter() - t_all
    tpu_qps = N_QUERIES / tpu_elapsed
    p50 = sorted(lat)[len(lat) // 2] * 1000

    # ---- Pallas-tiled variant (TPU only): keep whichever is faster ----
    pallas_qps = 0.0
    if jax.devices()[0].platform not in ("cpu",):
        try:
            from pilosa_tpu.ops.pallas_kernels import (
                intersection_counts_matrix_pallas,
                pad_for_pallas,
            )

            padded, true_r = pad_for_pallas(mat32)
            dev_pmat = jax.device_put(padded)
            wpad = padded.shape[1] - srcs32.shape[1]
            psrcs = np.pad(srcs32, ((0, 0), (0, wpad))) if wpad else srcs32

            @jax.jit
            def topn_step_pallas(src, pmat):
                scores = intersection_counts_matrix_pallas(src, pmat)
                counts, ids = jax.lax.top_k(scores[:true_r], TOPK)
                return ids, counts

            ids, _ = topn_step_pallas(jax.device_put(psrcs[0]), dev_pmat)
            ids.block_until_ready()
            t0 = time.perf_counter()
            for q in range(N_QUERIES):
                ids, _ = topn_step_pallas(jax.device_put(psrcs[q]), dev_pmat)
                ids.block_until_ready()
            pallas_qps = N_QUERIES / (time.perf_counter() - t0)
        except Exception as e:  # keep the JSON line clean; surface the cause
            print(f"pallas path failed: {type(e).__name__}: {e}", file=sys.stderr)
            pallas_qps = 0.0
    best_qps = max(tpu_qps, pallas_qps)

    # ---- CPU baseline: roaring per-candidate intersection counts ----
    # A TopN query walks every candidate row computing
    # src.intersection_count(row) (the reference's fragment.top hot loop).
    # Building all R roaring rows in Python is prohibitive, so measure a
    # SAMPLE of rows and extrapolate the per-query cost linearly in R —
    # the walk is embarrassingly linear in candidate count.
    from pilosa_tpu.roaring import Bitmap

    sample_n = 64
    rows_cpu = [Bitmap.from_words_range(mat64[i]) for i in range(sample_n)]
    src_b = Bitmap.from_words_range(srcs[0])
    t0 = time.perf_counter()
    reps = 2
    for _ in range(reps):
        for b in rows_cpu:
            src_b.intersection_count(b)
    per_row = (time.perf_counter() - t0) / (sample_n * reps)
    cpu_query_s = per_row * R
    cpu_qps = 1.0 / cpu_query_s

    print(
        json.dumps(
            {
                "metric": f"TopN queries/sec ({R} rows x 1M cols, ~2% density, single chip)",
                "value": round(best_qps, 2),
                "unit": "queries/s",
                "vs_baseline": round(best_qps / cpu_qps, 2),
                "p50_ms": round(p50, 3),
                "xla_qps": round(tpu_qps, 2),
                "pallas_qps": round(pallas_qps, 2),
                "baseline_cpu_qps": round(cpu_qps, 3),
                "platform": jax.devices()[0].platform,
            }
        )
    )


if __name__ == "__main__":
    main()
