"""Key-translation crash-recovery dryrun (ISSUE 20) — SIGKILL a server
mid KEYED ingest, restart it on the same data dir, and prove the
translate durability contract end to end:

  * every ACKED key→id assignment survives: a keyed ingest batch the
    client saw ack (200 — translate assignments group-committed ahead
    of the write wave's own fsync) resolves to the SAME id after the
    restart,
  * no duplicate ids: the recovered key→id map is injective per space
    (per column partition residue class, per field row space) — a
    replayed log never re-mints an id,
  * unacked tail truncated: a translate frame torn by the kill
    truncates cleanly at reopen (reported via /debug/translate
    ``truncatedBytes``) instead of failing the open,
  * the keyed query surface stays bit-identical to the acked oracle
    across the crash: Row(f="...") serves exactly the acked columns.

    python dryrun_translate_crash.py           # full run + artifact
    python dryrun_translate_crash.py --quick   # smaller load (CI smoke)

Artifact: TRANSLATE_r20.json. Worker mode (spawned server):
PILOSA_TRANSLATE_DRYRUN_MODE set.
"""

from __future__ import annotations

import json
import os
import http.client
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

MODE_ENV = "PILOSA_TRANSLATE_DRYRUN_MODE"
PORT_ENV = "PILOSA_TRANSLATE_DRYRUN_PORT"
DATA_ENV = "PILOSA_TRANSLATE_DRYRUN_DATA"

ARTIFACT = "TRANSLATE_r20.json"


# -- worker (the server process) ---------------------------------------------


def worker() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from pilosa_tpu.server.config import Config
    from pilosa_tpu.server.server import Server

    cfg = Config(
        data_dir=os.environ[DATA_ENV],
        bind=f"127.0.0.1:{os.environ[PORT_ENV]}",
        device_policy="never",
    )
    s = Server(cfg)
    s.open()
    print(f"translate dryrun server up on {cfg.bind}", flush=True)
    while True:  # parent SIGKILLs / SIGTERMs us
        time.sleep(1.0)


# -- parent helpers ----------------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(port: int, method: str, path: str, body: bytes = b"", timeout: float = 60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _wait_ready(port: int, deadline_s: float = 120) -> None:
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        try:
            status, _ = _http(port, "GET", "/status", timeout=2)
            if status == 200:
                return
        except OSError:
            pass
        time.sleep(0.25)
    raise TimeoutError("server HTTP never came up")


def _spawn(port: int, data_dir: str, tmp: str, tag: str):
    env = dict(os.environ)
    env[MODE_ENV] = "server"
    env[PORT_ENV] = str(port)
    env[DATA_ENV] = data_dir
    env["JAX_PLATFORMS"] = "cpu"
    outf = open(os.path.join(tmp, f"server-{tag}.log"), "w+")
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=env,
        stdout=outf,
        stderr=subprocess.STDOUT,
    )
    p._outf = outf  # type: ignore[attr-defined]
    return p


def _resolve(port: int, field: str, keys: list) -> list:
    """key → id through the owner mint endpoint (single node = owner
    of every space; idempotent for existing keys)."""
    st, body = _http(
        port,
        "POST",
        "/internal/translate/keys",
        json.dumps({"index": "i", "field": field, "keys": keys}).encode(),
    )
    assert st == 200, (st, body)
    return json.loads(body)["ids"]


# -- load generation ---------------------------------------------------------


class Writer:
    """One client thread minting a disjoint key namespace via keyed
    ingest. After each ack it resolves the batch's keys to ids and
    records them — the oracle the restarted server must reproduce
    exactly. The batch in flight at the kill is unknown-outcome."""

    def __init__(self, wid: int, port: int, batch: int):
        self.wid = wid
        self.port = port
        self.batch = batch
        # key -> id observed at ack time (never overwritten)
        self.acked_rows: dict = {}
        self.acked_cols: dict = {}
        # row key -> set of column keys acked into it
        self.oracle: dict = {}
        self.unknown_keys: set = set()
        self.acked_batches = 0
        self.retries = 0
        self.stop = threading.Event()
        self.thread = threading.Thread(target=self.run, daemon=True)

    def _batch_keys(self, seq: int):
        rows = [f"w{self.wid}-r{(seq + i) % 8}" for i in range(self.batch)]
        cols = [f"w{self.wid}-c{seq}-{i}" for i in range(self.batch)]
        return rows, cols

    def run(self) -> None:
        seq = 0
        while not self.stop.is_set():
            rows, cols = self._batch_keys(seq)
            body = json.dumps({"rowKeys": rows, "columnKeys": cols}).encode()
            while not self.stop.is_set():
                try:
                    status, _ = _http(
                        self.port, "POST", "/index/i/field/f/ingest", body, timeout=10
                    )
                except (OSError, http.client.HTTPException):
                    # connection died mid-request: the kill — these
                    # keys may or may not have been assigned
                    self.unknown_keys.update(rows)
                    self.unknown_keys.update(cols)
                    self.stop.set()
                    break
                if status == 200:
                    try:
                        rids = _resolve(self.port, "f", rows)
                        cids = _resolve(self.port, "", cols)
                    except (OSError, http.client.HTTPException, AssertionError):
                        # killed between ack and resolve: the ASSIGNMENT
                        # is durable (the 200 proved it) but we never
                        # observed the id — treat as unknown
                        self.unknown_keys.update(rows)
                        self.unknown_keys.update(cols)
                        self.stop.set()
                        break
                    for k, id_ in zip(rows, rids):
                        self.acked_rows.setdefault(k, id_)
                    for k, id_ in zip(cols, cids):
                        self.acked_cols.setdefault(k, id_)
                    for rk, ck in zip(rows, cols):
                        self.oracle.setdefault(rk, set()).add(ck)
                    self.acked_batches += 1
                    break
                self.retries += 1  # 429 shed / 5xx nacked wave: retry
                time.sleep(0.01)
            seq += 1


def main() -> int:
    quick = "--quick" in sys.argv
    n_writers = 4 if quick else 6
    batch = 16
    load_seconds = 2.5 if quick else 6.0

    tmp = tempfile.mkdtemp(prefix="translate-crash-")
    data = os.path.join(tmp, "data")
    port = _free_port()
    result: dict = {"quick": quick, "writers": n_writers}

    print("== phase 1: server up, concurrent KEYED ingest load")
    p = _spawn(port, data, tmp, "a")
    try:
        _wait_ready(port)
        assert (
            _http(port, "POST", "/index/i", json.dumps({"options": {"keys": True}}).encode())[0]
            == 200
        )
        assert (
            _http(
                port,
                "POST",
                "/index/i/field/f",
                json.dumps({"options": {"keys": True}}).encode(),
            )[0]
            == 200
        )

        writers = [Writer(w, port, batch) for w in range(n_writers)]
        for w in writers:
            w.thread.start()
        time.sleep(load_seconds)

        print("== phase 2: SIGKILL mid keyed-ingest")
        p.send_signal(signal.SIGKILL)
        p.wait()
        for w in writers:
            w.stop.set()
        for w in writers:
            w.thread.join(timeout=15)

        acked_keys = sum(len(w.acked_rows) + len(w.acked_cols) for w in writers)
        result["acked_batches"] = sum(w.acked_batches for w in writers)
        result["acked_keys"] = acked_keys
        result["nack_retries"] = sum(w.retries for w in writers)
        result["unknown_keys"] = sum(len(w.unknown_keys) for w in writers)
        print(
            f"   acked-keys={acked_keys} "
            f"batches={result['acked_batches']} "
            f"unknown-at-kill={result['unknown_keys']}"
        )
        if acked_keys == 0:
            print("FAIL: no keyed batch acked before the kill — nothing proven")
            return 1

        print("== phase 3: restart on the same data dir, verify assignments")
        p2 = _spawn(port, data, tmp, "b")
        try:
            _wait_ready(port)
            st, body = _http(port, "GET", "/debug/translate")
            assert st == 200, (st, body)
            dbg = json.loads(body)
            result["recovered_keys"] = dbg["keys"]
            result["truncated_bytes"] = dbg["truncatedBytes"]

            # (1) every acked key resolves to the SAME id
            changed = []
            for w in writers:
                rks = sorted(w.acked_rows)
                for k, id_ in zip(rks, _resolve(port, "f", rks)):
                    if id_ != w.acked_rows[k]:
                        changed.append(("row", k, w.acked_rows[k], id_))
                cks = sorted(w.acked_cols)
                for k, id_ in zip(cks, _resolve(port, "", cks)):
                    if id_ != w.acked_cols[k]:
                        changed.append(("col", k, w.acked_cols[k], id_))
            result["changed_assignments"] = changed[:50]

            # (2) no duplicate ids per space (column ids are globally
            # unique across partitions by the residue-class layout)
            dup = []
            col_ids: dict = {}
            row_ids: dict = {}
            for w in writers:
                for k, id_ in w.acked_cols.items():
                    if col_ids.setdefault(id_, k) != k:
                        dup.append(("col", id_, col_ids[id_], k))
                for k, id_ in w.acked_rows.items():
                    if row_ids.setdefault(id_, k) != k:
                        dup.append(("row", id_, row_ids[id_], k))
            result["duplicate_ids"] = dup[:50]

            # (3) keyed reads bit-identical to the acked oracle
            lost = []
            checked = 0
            for w in writers:
                for rk, want_cols in sorted(w.oracle.items()):
                    st, body = _http(
                        port, "POST", "/index/i/query", f'Row(f="{rk}")'.encode()
                    )
                    assert st == 200, (st, body)
                    got = set(json.loads(body)["results"][0].get("keys") or [])
                    checked += 1
                    for ck in want_cols - got - w.unknown_keys:
                        lost.append((rk, ck, "acked keyed set missing"))
            result["checked_row_keys"] = checked
            result["lost"] = lost[:50]
            ok = not changed and not dup and not lost
            result["ok"] = ok
            print(
                f"   recovered-keys={dbg['keys']} "
                f"truncated-bytes={dbg['truncatedBytes']} "
                f"changed={len(changed)} dup={len(dup)} lost={len(lost)}"
            )

            # the recovered server still mints: fresh keys get fresh,
            # non-colliding ids
            (nid,) = _resolve(port, "f", ["post-recovery-row"])
            assert nid not in row_ids, "recovered mint reused a live id"
            result["post_recovery_mint"] = True
        finally:
            p2.terminate()
            p2.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()

    with open(ARTIFACT, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
    print(f"artifact: {ARTIFACT}")
    if not result.get("ok"):
        print("FAIL: acked assignment changed, id duplicated, or keyed bits lost")
        return 1
    print("PASS: every acked key kept its id; no duplicates; clean recovery")
    return 0


if __name__ == "__main__":
    if os.environ.get(MODE_ENV):
        worker()
    else:
        sys.exit(main())
