"""Multi-host SERVING dryrun — the full Holder → Executor → HTTP path
on a 2-process jax.distributed CPU mesh (VERDICT r5 top next-round
item; the serving-level successor to dryrun_multiprocess.py's
kernel-only collectives).

Two worker processes each own 4 virtual CPU devices; one global
8-device mesh spans them. Rank 0 serves HTTP and gang-dispatches every
state-bearing operation (parallel/multihost.py); rank 1 runs the
follower worker loop and replays each descriptor into its own holder,
entering the identical shard_map collectives in lockstep. The parent:

  1. loads data over real HTTP (Set gangs + an import-value leg, so
     both the query and the import replication paths are exercised),
  2. answers Count / two-pass TopN / BSI Sum / a 3-op chain over HTTP,
  3. checks rank 0's HTTP results AND rank 1's replayed results
     bit-identical to a single-process CPU roaring oracle,
  4. SIGKILLs the follower mid-load and asserts rank 0 answers with a
     bounded clean failure (503 + degrade-to-local-mesh) — never a
     hang — and serves correct results again after the degrade,
  5. records everything in MULTIPROCESS_r6.json.

    python dryrun_multihost.py            # full run + artifact
    python dryrun_multihost.py --quick    # smaller load (CI smoke)

Worker mode (spawned): PILOSA_MH_DRYRUN_RANK set.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

RANK_ENV = "PILOSA_MH_DRYRUN_RANK"
COORD_ENV = "PILOSA_MH_DRYRUN_COORD"
HTTP_ENV = "PILOSA_MH_DRYRUN_HTTP"
DATA_ENV = "PILOSA_MH_DRYRUN_DATA"
TIMEOUT_ENV = "PILOSA_MH_DRYRUN_DISPATCH_TIMEOUT"

N_SHARDS = 6
SETS_PER_SHARD = 120
N_VALUES = 240
N_ROWS = 8

READ_QUERIES = [
    "Count(Row(f=1))",
    "Count(Intersect(Row(f=1), Row(f=2)))",
    # 3-op chain
    "Count(Difference(Union(Row(f=1), Row(f=2)), Intersect(Row(f=3), Row(f=4))))",
    "TopN(f, Row(f=1), n=5)",  # two-pass: pass 2 re-scores the winners
    "TopN(f, n=4)",
    "Sum(field=val)",
    "Sum(Row(f=1), field=val)",
]


def _dataset(quick: bool):
    """The one definition of the load — workers never see it (data
    arrives over HTTP); the parent replays it into the CPU oracle."""
    import numpy as np

    from pilosa_tpu import SHARD_WIDTH

    scale = 4 if quick else 1
    rng = np.random.default_rng(42)
    bits = []
    for shard in range(N_SHARDS):
        base = shard * SHARD_WIDTH
        for _ in range(SETS_PER_SHARD // scale):
            bits.append(
                (int(rng.integers(0, N_ROWS)), base + int(rng.integers(0, SHARD_WIDTH)))
            )
    cols = rng.choice(N_SHARDS * SHARD_WIDTH, size=N_VALUES // scale, replace=False)
    values = [(int(c), int(rng.integers(0, 1000))) for c in cols]
    return bits, values


# -- worker ------------------------------------------------------------------


def worker() -> None:
    rank = int(os.environ[RANK_ENV])

    import jax

    jax.config.update("jax_platforms", "cpu")

    from pilosa_tpu.parallel import multihost
    from pilosa_tpu.server.config import Config
    from pilosa_tpu.server.http_handler import encode_result
    from pilosa_tpu.server.server import Server

    cfg = Config(
        data_dir=os.path.join(os.environ[DATA_ENV], f"rank{rank}"),
        bind=f"127.0.0.1:{os.environ[HTTP_ENV] if rank == 0 else 0}",
        device_policy="always",
        metric="none",
        anti_entropy_interval=0,
        distributed_enabled=True,
        distributed_coordinator=os.environ[COORD_ENV],
        distributed_process_id=rank,
        distributed_num_processes=2,
        distributed_idle_interval=1.0,
        distributed_dispatch_timeout=float(os.environ.get(TIMEOUT_ENV, "20")),
        distributed_leader_timeout=60.0,
    )
    srv = Server(cfg)
    srv.open()

    def jsonable(r):
        return json.loads(json.dumps(encode_result(r)))

    if rank == 0:
        stop = []
        signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
        print(json.dumps({"event": "ready", "rank": 0}), flush=True)
        while not stop:
            time.sleep(0.1)
        stats = srv.multihost.stats()
        srv.close()
        print(json.dumps({"event": "exit", "rank": 0, "stats": stats}), flush=True)
        # linger: this process hosts the jax.distributed coordination
        # service — exiting the instant the poison lands can fatally
        # terminate the follower (coordination poll abort) before it
        # prints its results dump
        time.sleep(3.0)
        return

    # follower: record every replayed query's results so the parent can
    # verify rank 1's serving-level answers against the oracle
    records: list[dict] = []
    orig_apply = srv.multihost.apply_fn

    def recording_apply(kind, payload):
        result = orig_apply(kind, payload)
        if kind == multihost.KIND_QUERY:
            records.append(
                {
                    "query": payload["query"],
                    "plan": payload.get("plan"),
                    "results": [jsonable(r) for r in result],
                }
            )
        return result

    srv.multihost.apply_fn = recording_apply
    reason = srv.serve_follower()
    stats = srv.multihost.stats()
    # dump BEFORE closing: once the leader process exits, the dead
    # coordination service can fatally terminate this process mid-close
    # — the results must already be on stdout by then
    print(
        json.dumps(
            {
                "event": "exit",
                "rank": 1,
                "stop_reason": reason,
                "stats": stats,
                "queries": records,
            }
        ),
        flush=True,
    )
    try:
        srv.close()
    except Exception:
        pass


# -- parent ------------------------------------------------------------------


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(port: int, method: str, path: str, body: bytes = b"", timeout: float = 60):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _wait_ready(port: int, deadline_s: float = 120) -> None:
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        try:
            status, _ = _http(port, "GET", "/status", timeout=2)
            if status == 200:
                return
        except OSError:
            pass
        time.sleep(0.25)
    raise TimeoutError("rank 0 HTTP never came up")


def _spawn(rank: int, env: dict, tmp: str, tag: str = ""):
    """Worker process with stdout/stderr to FILES, never pipes: a
    verbose child (the kill phase logs one re-map line per failed leg)
    would fill an undrained 64 KB pipe and block inside logger writes —
    observed as a total serving wedge that looked like a product bug."""
    import subprocess

    out = open(os.path.join(tmp, f"rank{rank}{tag}.out"), "w+")
    err = open(os.path.join(tmp, f"rank{rank}{tag}.err"), "w+")
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env={**env, RANK_ENV: str(rank)},
        stdout=out,
        stderr=err,
        text=True,
    )
    p._outf, p._errf = out, err  # type: ignore[attr-defined]
    return p


def _finish(p, timeout: float):
    """(stdout, stderr, returncode) after the worker exits (killed on
    timeout); reads the spool files _spawn opened."""
    import subprocess

    try:
        p.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        p.kill()
        p.wait()
    out_text = err_text = ""
    for attr, store in (("_outf", "out"), ("_errf", "err")):
        f = getattr(p, attr, None)
        if f is None:
            continue
        f.flush()
        f.seek(0)
        if store == "out":
            out_text = f.read()
        else:
            err_text = f.read()
        f.close()
    return out_text, err_text, p.returncode


def _worker_env(tmp: str, coord: int, http_port: int, dispatch_timeout: float) -> dict:
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        **{
            COORD_ENV: f"127.0.0.1:{coord}",
            HTTP_ENV: str(http_port),
            DATA_ENV: tmp,
            TIMEOUT_ENV: str(dispatch_timeout),
        },
    )
    return env


def _oracle(bits, values):
    """Single-process CPU roaring oracle over the same dataset."""
    from pilosa_tpu.core import FieldOptions, Holder
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.server.http_handler import encode_result

    h = Holder()
    h.open()
    idx = h.create_index("i")
    f = idx.create_field("f")
    intf = idx.create_field("val", FieldOptions(type="int", min=0, max=1000))
    for row, col in bits:
        f.set_bit(row, col)
    for col, v in values:
        intf.set_value(col, v)
    for fld in idx.fields.values():
        for view in fld.views.values():
            for frag in view.fragments.values():
                frag.cache.recalculate()
    ex = Executor(h, device_policy="never")
    out = {}
    for q in READ_QUERIES:
        out[q] = [
            json.loads(json.dumps(encode_result(r))) for r in ex.execute("i", q)
        ]
    return out


def _load_over_http(port: int, bits, values) -> None:
    status, _ = _http(port, "POST", "/index/i", b"")
    assert status in (200, 409), status
    status, _ = _http(port, "POST", "/index/i/field/f", b"")
    assert status in (200, 409), status
    status, _ = _http(
        port,
        "POST",
        "/index/i/field/val",
        json.dumps({"options": {"type": "int", "min": 0, "max": 1000}}).encode(),
    )
    assert status in (200, 409), status
    sets = [f"Set({col}, f={row})" for row, col in bits]
    for i in range(0, len(sets), 200):
        status, body = _http(
            port, "POST", "/index/i/query", " ".join(sets[i : i + 200]).encode()
        )
        assert status == 200, (status, body[:300])
    # the import-value leg exercises gang import replication
    status, body = _http(
        port,
        "POST",
        "/index/i/field/val/import-value",
        json.dumps(
            {"columnIDs": [c for c, _ in values], "values": [v for _, v in values]}
        ).encode(),
    )
    assert status == 200, (status, body[:300])
    status, _ = _http(port, "POST", "/recalculate-caches", b"")
    assert status == 200, status


def parent(quick: bool) -> int:
    import subprocess
    import tempfile

    bits, values = _dataset(quick)
    oracle = _oracle(bits, values)
    summary: dict = {
        "what": (
            "2-process x 4-device jax.distributed CPU deployment serving "
            "PQL over real HTTP: rank 0 gang-dispatches every operation "
            "(parallel/multihost.py), rank 1 replays it in lockstep, and "
            "the SPMD Count/TopN/Sum collectives span the process "
            "boundary inside one global mesh — the serving-level "
            "successor to MULTIPROCESS_r5.json's kernel-only dryrun"
        ),
        "processes": 2,
        "devices_per_process": 4,
        "quick": quick,
        "queries": READ_QUERIES,
    }
    ok = True

    # -- phase 1: serving bit-identity ------------------------------------
    with tempfile.TemporaryDirectory() as tmp:
        coord, http_port = _free_port(), _free_port()
        env = _worker_env(tmp, coord, http_port, dispatch_timeout=30.0)
        procs = [_spawn(0, env, tmp), _spawn(1, env, tmp)]
        rank0_results = {}
        lat = {}
        mh_stats = None
        phase_error = None
        try:
            _wait_ready(http_port)
            _load_over_http(http_port, bits, values)
            for q in READ_QUERIES:  # warm (compiles), then timed/recorded
                _http(http_port, "POST", "/index/i/query", q.encode(), timeout=180)
            for q in READ_QUERIES:
                t0 = time.monotonic()
                status, body = _http(
                    http_port, "POST", "/index/i/query", q.encode(), timeout=180
                )
                lat[q] = round((time.monotonic() - t0) * 1000, 2)
                assert status == 200, (q, status, body[:300])
                rank0_results[q] = json.loads(body)["results"]
            status, body = _http(http_port, "GET", "/debug/multihost")
            mh_stats = json.loads(body)
        except Exception as e:
            phase_error = f"{type(e).__name__}: {e}"
            ok = False
        finally:
            try:
                procs[0].send_signal(signal.SIGTERM)
            except OSError:
                pass
            outs = [_finish(p, timeout=90) for p in procs]

        follower_dump = None
        for line in outs[1][0].splitlines():
            if line.startswith("{"):
                d = json.loads(line)
                if d.get("event") == "exit":
                    follower_dump = d
        rank0_ok = all(rank0_results.get(q) == oracle[q] for q in READ_QUERIES)
        # follower records key on the gang descriptor's re-serialized
        # PQL (Sum(field="val") for Sum(field=val)) — match by the
        # canonical plan signature instead, which is spelling-invariant
        from pilosa_tpu.plan.canon import query_signature

        by_plan = {}
        if follower_dump:
            for rec in follower_dump.get("queries", []):
                by_plan[rec.get("plan")] = rec["results"]
        follower_results = {q: by_plan.get(query_signature(q)) for q in READ_QUERIES}
        rank1_ok = bool(follower_dump) and all(
            follower_results.get(q) == oracle[q] for q in READ_QUERIES
        )
        ok &= rank0_ok and rank1_ok
        summary["serving"] = {
            "rank0_http_bit_identical": rank0_ok,
            "rank1_replay_bit_identical": rank1_ok,
            "latency_ms": lat,
            "rank0_results": rank0_results,
            "rank1_results": {q: follower_results.get(q) for q in READ_QUERIES},
            "oracle": oracle,
            "multihost_debug": mh_stats,
            "follower_stop_reason": (follower_dump or {}).get("stop_reason"),
            "follower_stats": (follower_dump or {}).get("stats"),
            "worker_rc": [rc for _, _, rc in outs],
            "error": phase_error,
        }
        if not (rank0_ok and rank1_ok):
            for i, (out, err, rc) in enumerate(outs):
                print(f"-- rank {i} rc={rc}\n{err[-4000:]}", file=sys.stderr)

    # -- phase 2: follower kill mid-load → bounded 503 + degrade ----------
    dispatch_timeout = 6.0
    with tempfile.TemporaryDirectory() as tmp:
        coord, http_port = _free_port(), _free_port()
        env = _worker_env(tmp, coord, http_port, dispatch_timeout)
        procs = [_spawn(0, env, tmp), _spawn(1, env, tmp)]
        kill = {}
        try:
            _wait_ready(http_port)
            small = bits[: len(bits) // 4]
            _load_over_http(http_port, small, values[: len(values) // 4])
            _http(http_port, "POST", "/index/i/query", b"Count(Row(f=1))", timeout=120)
            # kill the follower MID-LOAD: a write gang is in flight
            procs[1].kill()
            t0 = time.monotonic()
            status, body = _http(
                http_port,
                "POST",
                "/index/i/query",
                b"Count(Row(f=1))",
                timeout=dispatch_timeout * 3 + 30,
            )
            first_s = time.monotonic() - t0
            # bounded: either the gang already degraded (200, served on
            # the local mesh) or this request ate the dispatch timeout
            # and got the clean 503 — never a hang
            bounded = first_s < dispatch_timeout * 3
            # after the verdict, serving must be correct on the local mesh
            t0 = time.monotonic()
            deg_status, deg_body = _http(
                http_port, "POST", "/index/i/query", b"Count(Row(f=1))", timeout=60
            )
            second_s = time.monotonic() - t0
            status2, dbg = _http(http_port, "GET", "/debug/multihost")
            kill = {
                "dispatch_timeout_s": dispatch_timeout,
                "first_query_status": status,
                "first_query_seconds": round(first_s, 2),
                "first_query_bounded": bounded,
                "post_degrade_status": deg_status,
                "post_degrade_seconds": round(second_s, 2),
                "post_degrade_results": json.loads(deg_body).get("results")
                if deg_status == 200
                else deg_body.decode(errors="replace")[:500],
                "multihost_debug": json.loads(dbg) if status2 == 200 else None,
            }
            degraded = bool((kill["multihost_debug"] or {}).get("degraded"))
            kill["degraded"] = degraded
            kill_ok = (
                bounded
                and status in (200, 503)
                and deg_status == 200
                and degraded
            )
            kill["ok"] = kill_ok
            ok &= kill_ok
        except Exception as e:
            kill["error"] = f"{type(e).__name__}: {e}"
            ok = False
        finally:
            try:
                procs[0].send_signal(signal.SIGTERM)
            except OSError:
                pass
            for i, p in enumerate(procs):
                out, err, rc = _finish(p, timeout=60)
                if not kill.get("ok"):
                    print(
                        f"-- kill-phase rank {i} rc={rc}\n{err[-4000:]}",
                        file=sys.stderr,
                    )
        summary["follower_kill"] = kill

    summary["ok"] = bool(ok)
    print(json.dumps(summary, indent=2))
    if not quick:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "MULTIPROCESS_r6.json"
        )
        with open(path, "w") as f:
            json.dump(summary, f, indent=2)
    return 0 if ok else 1


if __name__ == "__main__":
    if os.environ.get(RANK_ENV) is not None:
        worker()
    else:
        import argparse

        ap = argparse.ArgumentParser()
        ap.add_argument("--quick", action="store_true", help="smaller load (CI smoke)")
        a = ap.parse_args()
        sys.exit(parent(a.quick))
